"""Pull-based metrics export: Prometheus text snapshot + HTTP endpoint.

``prometheus_snapshot`` renders the live counter/gauge tables in the
Prometheus text exposition format (version 0.0.4) under a stable
``lgbtpu_*`` namespace: counters get a ``_total`` suffix, gauge names are
flattened (``/`` and ``.`` become ``_``), and the health watchdog's state
rides along as ``lgbtpu_health_status`` (0=ok, 1=warn, 2=critical) plus
per-rule ``lgbtpu_alert_active`` series.

``MetricsExporter`` serves that snapshot from an opt-in background HTTP
endpoint (``obs_export_port``; a daemon ``ThreadingHTTPServer``, so a
hung scrape never blocks training and the thread dies with the process):

* ``GET /metrics``  — Prometheus text format
* ``GET /healthz``  — the ``Booster.health()`` JSON document
* ``GET /trace``    — the live span ring as Chrome trace-event JSON
  (Perfetto-loadable; see ``obs/trace.py``)

The serving plane (``lightgbm_tpu/serving``) colocates its HTTP/JSON
front end on the same endpoint by passing extra ``routes`` (method/path
handlers, e.g. ``POST /predict``), and registers a serving-snapshot
provider (:func:`set_serving_provider`) so ``health_snapshot`` grows a
``serving`` block and the ``lgbtpu_serve_*`` gauges ride the normal
gauge flattening.

Everything here is host-only code operating on already-recorded telemetry
— no tracer reads, no device syncs (GL003/GL010-clean by construction).
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

from .flight import get_flight
from .health import _SEV_RANK, HealthWatchdog
from .registry import TelemetrySession, _jsonable, get_session
from .trace import get_tracer

METRIC_PREFIX = "lgbtpu_"

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_]")

# Optional provider of the health document's "serving" block, registered
# by the serving plane while a ServingServer is live (obs must not import
# serving — the dependency points the other way).
_serving_provider: Optional[Callable[[], Dict[str, Any]]] = None


def set_serving_provider(
    fn: Optional[Callable[[], Dict[str, Any]]]
) -> Optional[Callable[[], Dict[str, Any]]]:
    """Register (or clear, with ``None``) the serving-snapshot provider.

    Returns the previous provider so a short-lived server (drills, tests)
    can restore it on stop instead of clobbering a longer-lived one."""
    global _serving_provider
    prev = _serving_provider
    _serving_provider = fn
    return prev


def get_serving_provider() -> Optional[Callable[[], Dict[str, Any]]]:
    return _serving_provider


def sanitize_metric_name(name: str) -> str:
    """Flatten a registry counter/gauge name into a Prometheus name."""
    flat = _NAME_BAD.sub("_", name.strip())
    flat = re.sub(r"_+", "_", flat).strip("_")
    if flat and flat[0].isdigit():
        flat = "_" + flat
    return METRIC_PREFIX + (flat or "unnamed")


def _fmt_value(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def prometheus_snapshot(
    ses: Optional[TelemetrySession] = None,
    health: Optional[Dict[str, Any]] = None,
) -> str:
    """Render counters/gauges (+ optional health doc) as Prometheus text."""
    ses = ses or get_session()
    with ses._lock:
        counters = dict(ses.counters)
        gauges = dict(ses.gauges)
    lines: List[str] = []

    def emit(name: str, kind: str, value: float, help_text: str = "") -> None:
        if help_text:
            lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name} {_fmt_value(value)}")

    emit(
        METRIC_PREFIX + "up", "gauge", 1,
        "telemetry endpoint liveness (constant 1 while serving)",
    )
    for raw in sorted(counters):
        name = sanitize_metric_name(raw)
        if not name.endswith("_total"):
            name += "_total"
        emit(name, "counter", counters[raw])
    for raw in sorted(gauges):
        # the serve queue/device attribution gauges are re-rendered below
        # as proper Prometheus summaries — skip the raw gauge lines so
        # strict parsers never see the same sample name with two TYPEs
        if raw.startswith(("serve/queue_ms_", "serve/device_ms_")):
            continue
        emit(sanitize_metric_name(raw), "gauge", gauges[raw])
    # trace-plane health rides every scrape (the recorder is always-on and
    # independent of the telemetry session's enabled flag)
    tracer = get_tracer()
    emit(
        METRIC_PREFIX + "trace_spans_total", "counter", tracer.spans_total,
        "spans recorded by the distributed trace recorder",
    )
    emit(
        METRIC_PREFIX + "trace_dropped_total", "counter",
        tracer.dropped_total, "trace spans evicted from the bounded ring",
    )
    # per-request serving attribution as Prometheus summaries (quantiles
    # from the batcher's window, sum/count from its cumulative totals)
    for dim in ("queue", "device"):
        p50 = gauges.get(f"serve/{dim}_ms_p50")
        p99 = gauges.get(f"serve/{dim}_ms_p99")
        if p50 is None or p99 is None:
            continue
        name = f"{METRIC_PREFIX}serve_{dim}_ms"
        lines.append(f"# TYPE {name} summary")
        lines.append(f'{name}{{quantile="0.5"}} {_fmt_value(p50)}')
        lines.append(f'{name}{{quantile="0.99"}} {_fmt_value(p99)}')
        lines.append(
            f"{name}_sum {_fmt_value(gauges.get(f'serve/{dim}_ms_sum', 0.0))}"
        )
        lines.append(
            f"{name}_count "
            f"{_fmt_value(counters.get('serve/requests_total', 0))}"
        )
    if health is not None:
        status = str(health.get("status", "ok"))
        emit(
            METRIC_PREFIX + "health_status", "gauge",
            {"ok": 0, "warn": 1, "critical": 2}.get(status, 1),
            "watchdog status: 0=ok 1=warn 2=critical",
        )
        alerts = health.get("alerts") or []
        lines.append(f"# TYPE {METRIC_PREFIX}alert_active gauge")
        for alert in alerts:
            rule = _NAME_BAD.sub("_", str(alert.get("rule", "unknown")))
            sev = _NAME_BAD.sub("_", str(alert.get("severity", "warn")))
            lines.append(
                f'{METRIC_PREFIX}alert_active{{rule="{rule}",'
                f'severity="{sev}"}} 1'
            )
    return "\n".join(lines) + "\n"


def health_snapshot(
    watchdog: Optional[HealthWatchdog] = None,
    ses: Optional[TelemetrySession] = None,
) -> Dict[str, Any]:
    """The ``Booster.health()`` / ``GET /healthz`` document."""
    ses = ses or get_session()
    flight = get_flight()
    with ses._lock:
        counters = dict(ses.counters)
        gauges = dict(ses.gauges)
    alerts = watchdog.active_alerts() if watchdog is not None else []
    status = watchdog.status() if watchdog is not None else "ok"
    serving: Optional[Dict[str, Any]] = None
    if _serving_provider is not None:
        try:
            serving = _serving_provider()
        except Exception:
            serving = None
    doc = _jsonable(
        {
            "schema": "lgbtpu.health.v1",
            "status": status,
            "status_rank": _SEV_RANK.get(status, 0),
            "iter": int(counters.get("iterations", 0)),
            "alerts": alerts,
            "alerts_emitted": (
                watchdog.alerts_emitted if watchdog is not None else 0
            ),
            "counters": counters,
            "gauges": gauges,
            "flight": {
                "capacity": flight.capacity,
                "n_events": len(flight.events()),
                "last_dump": flight.last_dump_path,
                "last_trace_dump": flight.last_trace_path,
                "last_checkpoint": flight.last_checkpoint,
            },
            "trace": get_tracer().stats(),
        }
    )
    if serving is not None:
        doc["serving"] = _jsonable(serving)
    return doc


class _Handler(BaseHTTPRequestHandler):
    exporter: "MetricsExporter"

    def _respond(
        self,
        status: int,
        ctype: str,
        body: bytes,
        headers: Optional[Dict[str, str]] = None,
    ) -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for key, value in (headers or {}).items():
            self.send_header(key, value)
        self.end_headers()
        self.wfile.write(body)

    def _dispatch_route(self, method: str, path: str, body: bytes) -> bool:
        route = self.exporter._routes.get((method, path))
        if route is None:
            return False
        extra: Dict[str, str] = {}
        try:
            # handlers marked ``wants_headers`` (a function attribute) get
            # the request headers — how the serving front end reads a
            # caller's ``traceparent`` — and may return a 4th element of
            # response headers to echo it back
            if getattr(route, "wants_headers", False):
                hdrs = {k.lower(): v for k, v in self.headers.items()}
                result = route(body, hdrs)
            else:
                result = route(body)
            if len(result) == 4:
                status, ctype, out, extra = result
            else:
                status, ctype, out = result
        except Exception as e:
            status, ctype = 500, "application/json"
            out = json.dumps({"error": str(e)}).encode("utf-8")
        self._respond(status, ctype, out, extra)
        return True

    def do_GET(self):  # noqa: N802 - http.server API
        path = self.path.split("?", 1)[0]
        if path in ("/metrics", "/"):
            body = prometheus_snapshot(
                health=self.exporter._health()
            ).encode("utf-8")
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif path == "/healthz":
            body = json.dumps(self.exporter._health() or {}).encode("utf-8")
            ctype = "application/json"
        elif path == "/trace":
            # the live span ring as Chrome trace-event JSON — save the
            # response body and load it in Perfetto / chrome://tracing
            body = get_tracer().chrome_trace_json().encode("utf-8")
            ctype = "application/json"
        elif self._dispatch_route("GET", path, b""):
            return
        else:
            self.send_response(404)
            self.end_headers()
            return
        self._respond(200, ctype, body)

    def do_POST(self):  # noqa: N802 - http.server API
        path = self.path.split("?", 1)[0]
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else b""
        if not self._dispatch_route("POST", path, body):
            self.send_response(404)
            self.end_headers()

    def log_message(self, fmt, *args):  # silence per-request stderr spam
        pass


class MetricsExporter:
    """Background HTTP endpoint serving /metrics and /healthz.

    ``port=0`` binds an ephemeral port (useful in tests); the bound port
    is available as ``.port`` after :meth:`start`.
    """

    def __init__(
        self,
        port: int,
        host: str = "127.0.0.1",
        health_provider: Optional[Callable[[], Dict[str, Any]]] = None,
        routes: Optional[
            Dict[Any, Callable[[bytes], Any]]
        ] = None,
    ) -> None:
        self._requested_port = int(port)
        self._host = host
        self._health_provider = health_provider
        # extra (method, path) -> fn(body) -> (status, ctype, bytes)
        # handlers, consulted after the built-in /metrics and /healthz —
        # how the serving plane colocates POST /predict on this endpoint
        self._routes: Dict[Any, Callable[[bytes], Any]] = dict(routes or {})
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def add_route(
        self, method: str, path: str, fn: Callable[[bytes], Any]
    ) -> None:
        self._routes[(method, path)] = fn

    def _health(self) -> Optional[Dict[str, Any]]:
        if self._health_provider is None:
            return health_snapshot()
        try:
            return self._health_provider()
        except Exception:
            return None

    @property
    def port(self) -> int:
        return self._server.server_address[1] if self._server else 0

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}" if self._server else ""

    def start(self) -> int:
        if self._server is not None:
            return self.port
        handler = type("_BoundHandler", (_Handler,), {"exporter": self})
        self._server = ThreadingHTTPServer(
            (self._host, self._requested_port), handler
        )
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="lgbtpu-metrics-exporter",
            daemon=True,
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        server, self._server = self._server, None
        thread, self._thread = self._thread, None
        if server is not None:
            server.shutdown()
            server.server_close()
        if thread is not None:
            thread.join(timeout=5.0)
