"""Unified training/inference telemetry.

One process-global :class:`TelemetrySession` that every hot path reports
into:

* per-iteration event records (phase walls, commit counts, bagging counts,
  eval metrics) with an optional JSONL sink — ``registry``;
* compile accounting — ``instrumented_jit`` counts actual retraces at every
  ``jax.jit`` call site, ``compile_count()`` is the global no-recompile
  invariant — and (``obs_device_accounting=True``) executable accounting:
  ``cost_analysis()``/``memory_analysis()`` of each compiled artifact as
  ``cost/*`` / ``memory/*`` gauges — ``jit``;
* collective accounting — the data-parallel grower's psum bytes, modeled
  analytically (``parallel.psum_bytes_per_iteration``) and MEASURED by
  timed psum/pmax wrappers (``collective_measured/*``) — ``collectives``;
* live HBM watermarks via ``device.memory_stats()`` at phase boundaries
  (graceful no-op on backends without allocator stats) — ``device``;
* per-host aggregation — GlobalSyncUp-style counter/gauge merge plus
  straggler gauges for multi-host runs — ``aggregate``;
* ``jax.profiler`` trace capture over an iteration window — ``profiler``;
* the LIVE ops plane — ``flight`` (always-on bounded ring buffer with
  atomic dump-on-fault: NumericsError, degradation latch, SIGTERM),
  ``health`` (per-iteration host-side watchdog emitting severity-tagged
  alerts), ``export`` (Prometheus text-format snapshot + opt-in HTTP
  endpoint via ``obs_export_port`` and the ``Booster.health()`` API);
* distributed tracing — ``trace`` (always-on span recorder with
  ``trace_id``/``span_id``/parent links and per-category sampling,
  exported as Perfetto-loadable Chrome trace JSON via
  ``Booster.dump_trace``, ``GET /trace``, and automatically next to every
  flight dump).  See README "Distributed tracing".

Enable with ``telemetry=True`` (params/Config), stream to a file with
``telemetry_out=<path.jsonl>``, make phase walls measure device time with
``obs_sync_timing=True``, capture executable cost/memory with
``obs_device_accounting=True``.  See README "Observability".
"""

from .aggregate import (  # noqa: F401
    global_rollup,
    host_snapshot,
    merge_snapshots,
)
from .collectives import (  # noqa: F401
    collectives_snapshot,
    measured_summary,
    timed_pmax,
    timed_pmin,
    timed_psum,
)
from .device import (  # noqa: F401
    device_memory_supported,
    sample_device_memory,
)
from .export import (  # noqa: F401
    MetricsExporter,
    health_snapshot,
    prometheus_snapshot,
    sanitize_metric_name,
    set_serving_provider,
)
from .flight import (  # noqa: F401
    FlightRecorder,
    get_flight,
    install_sigterm_handler,
    list_flight_dumps,
    uninstall_sigterm_handler,
)
from .health import HealthWatchdog  # noqa: F401
from .jit import (  # noqa: F401
    compile_count,
    compile_counts_by_label,
    instrumented_jit,
    note_compile,
    note_executable,
    record_executable,
)
from .profiler import TraceWindow  # noqa: F401
from .registry import (  # noqa: F401
    TelemetrySession,
    get_session,
    session_disabled,
)
from .trace import (  # noqa: F401
    TraceRecorder,
    format_traceparent,
    get_tracer,
    parse_traceparent,
)

__all__ = [
    "TelemetrySession",
    "get_session",
    "session_disabled",
    "FlightRecorder",
    "get_flight",
    "list_flight_dumps",
    "install_sigterm_handler",
    "uninstall_sigterm_handler",
    "HealthWatchdog",
    "MetricsExporter",
    "health_snapshot",
    "prometheus_snapshot",
    "sanitize_metric_name",
    "set_serving_provider",
    "instrumented_jit",
    "note_compile",
    "note_executable",
    "record_executable",
    "compile_count",
    "compile_counts_by_label",
    "collectives_snapshot",
    "measured_summary",
    "timed_psum",
    "timed_pmax",
    "timed_pmin",
    "sample_device_memory",
    "device_memory_supported",
    "global_rollup",
    "host_snapshot",
    "merge_snapshots",
    "TraceWindow",
    "TraceRecorder",
    "get_tracer",
    "parse_traceparent",
    "format_traceparent",
]
