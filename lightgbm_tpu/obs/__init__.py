"""Unified training/inference telemetry.

One process-global :class:`TelemetrySession` that every hot path reports
into:

* per-iteration event records (phase walls, commit counts, bagging counts,
  eval metrics) with an optional JSONL sink — ``registry``;
* compile accounting — ``instrumented_jit`` counts actual retraces at every
  ``jax.jit`` call site, ``compile_count()`` is the global no-recompile
  invariant — ``jit``;
* collective accounting — the data-parallel grower's psum bytes, modeled
  analytically (``parallel.psum_bytes_per_iteration``) and recorded as
  gauges;
* ``jax.profiler`` trace capture over an iteration window — ``profiler``.

Enable with ``telemetry=True`` (params/Config), stream to a file with
``telemetry_out=<path.jsonl>``, make phase walls measure device time with
``obs_sync_timing=True``.  See README "Observability".
"""

from .jit import (  # noqa: F401
    compile_count,
    compile_counts_by_label,
    instrumented_jit,
    note_compile,
)
from .profiler import TraceWindow  # noqa: F401
from .registry import (  # noqa: F401
    TelemetrySession,
    get_session,
    session_disabled,
)

__all__ = [
    "TelemetrySession",
    "get_session",
    "session_disabled",
    "instrumented_jit",
    "note_compile",
    "compile_count",
    "compile_counts_by_label",
    "TraceWindow",
]
