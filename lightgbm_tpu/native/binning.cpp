// Native (OpenMP) host-side binning — the hot loop of Dataset.construct.
//
// Reference analog: the multi-threaded DatasetLoader/Bin construction
// (src/io/dataset_loader.cpp CostructFromSampleData + DenseBin::Push under
// OpenMP).  The device-side training path is JAX/XLA; ingestion is host work
// exactly as it is in the reference, so it gets the same native treatment.
//
// Compiled on demand by native/build.py (g++ -O3 -fopenmp), loaded via
// ctypes; lightgbm_tpu/binning.py falls back to NumPy when unavailable.

#include <algorithm>
#include <cmath>
#include <cstdint>

extern "C" {

// MissingType values mirror lightgbm_tpu/binning.py
enum { MISSING_NONE = 0, MISSING_ZERO = 1, MISSING_NAN = 2 };

// bin one numeric column: out[i] = lower_bound(ub, value) with the
// missing-direction rules of BinMapper.values_to_bins
void bin_numeric_f64(const double* values, long long n, const double* ub,
                     int nb, int missing_type, int nan_bin,
                     double zero_threshold, int32_t* out) {
#pragma omp parallel for schedule(static)
  for (long long i = 0; i < n; ++i) {
    double v = values[i];
    bool is_nan = std::isnan(v);
    double safe = is_nan ? 0.0 : v;
    int b = static_cast<int>(std::lower_bound(ub, ub + nb, safe) - ub);
    if (missing_type == MISSING_ZERO) {
      if (is_nan || std::fabs(v) <= zero_threshold) b = nan_bin;
    } else if (missing_type == MISSING_NAN && nan_bin >= 0) {
      if (is_nan) b = nan_bin;
    }
    out[i] = b;
  }
}

}  // extern "C"
