// Native (OpenMP) host-side binning — the hot loop of Dataset.construct.
//
// Reference analog: the multi-threaded DatasetLoader/Bin construction
// (src/io/dataset_loader.cpp CostructFromSampleData + DenseBin::Push under
// OpenMP).  The device-side training path is JAX/XLA; ingestion is host work
// exactly as it is in the reference, so it gets the same native treatment.
//
// Compiled on demand by native/build.py (g++ -O3 -fopenmp), loaded via
// ctypes; lightgbm_tpu/binning.py falls back to NumPy when unavailable.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <vector>

extern "C" {

// MissingType values mirror lightgbm_tpu/binning.py
enum { MISSING_NONE = 0, MISSING_ZERO = 1, MISSING_NAN = 2 };

// bin one numeric column: out[i] = lower_bound(ub, value) with the
// missing-direction rules of BinMapper.values_to_bins
void bin_numeric_f64(const double* values, long long n, const double* ub,
                     int nb, int missing_type, int nan_bin,
                     double zero_threshold, int32_t* out) {
#pragma omp parallel for schedule(static)
  for (long long i = 0; i < n; ++i) {
    double v = values[i];
    bool is_nan = std::isnan(v);
    double safe = is_nan ? 0.0 : v;
    int b = static_cast<int>(std::lower_bound(ub, ub + nb, safe) - ub);
    if (missing_type == MISSING_ZERO) {
      if (is_nan || std::fabs(v) <= zero_threshold) b = nan_bin;
    } else if (missing_type == MISSING_NAN && nan_bin >= 0) {
      if (is_nan) b = nan_bin;
    }
    out[i] = b;
  }
}

// Equal-count greedy binning over sorted distinct values — the O(n_distinct)
// inner loop of bin-boundary construction (reference GreedyFindBin,
// src/io/bin.cpp).  Matches lightgbm_tpu/binning.py _greedy_find_bin
// operation-for-operation (same float expressions, same branch order) so the
// boundaries are bit-identical to the Python fallback.  big_suffix[i] =
// #heavy distinct values at indices >= i, precomputed so the rebudgeting
// branch (which reads big_suffix[i + 1]) is O(1) instead of an O(n) scan.
// Returns the number of bounds written (<= max_bin); the +inf terminator is
// appended by the caller.
int greedy_find_bin(const double* distinct_values, const double* counts,
                    long long n, int max_bin, double total_sample_cnt,
                    double min_data_in_bin, double* bounds_out) {
  int nb = 0;
  if (n == 0) return 0;
  if (n <= max_bin) {
    double cur_cnt = 0.0;
    for (long long i = 0; i + 1 < n; ++i) {
      cur_cnt += counts[i];
      if (cur_cnt >= min_data_in_bin || max_bin >= n) {
        bounds_out[nb++] = (distinct_values[i] + distinct_values[i + 1]) / 2.0;
        cur_cnt = 0.0;
      }
    }
    return nb;
  }
  if (max_bin < 1) max_bin = 1;
  double mean_bin_size = total_sample_cnt / max_bin;
  // is_big + suffix counts in one backward pass
  double big_cnt = 0.0;
  std::vector<long long> big_suffix(n + 1);
  big_suffix[n] = 0;
  for (long long i = n - 1; i >= 0; --i) {
    bool big = counts[i] >= mean_bin_size;
    big_suffix[i] = big_suffix[i + 1] + (big ? 1 : 0);
    if (big) big_cnt += counts[i];
  }
  double rest_cnt = total_sample_cnt - big_cnt;
  long long rest_bins = max_bin - big_suffix[0];
  if (rest_bins > 0) mean_bin_size = rest_cnt / rest_bins;
  double orig_mean = total_sample_cnt / max_bin;  // is_big uses the ORIGINAL
  double cur_cnt = 0.0;
  long long remaining_bins = max_bin;
  for (long long i = 0; i + 1 < n; ++i) {
    bool big_i = counts[i] >= orig_mean;
    bool big_next = counts[i + 1] >= orig_mean;
    if (!big_i) rest_cnt -= counts[i];
    cur_cnt += counts[i];
    if (big_i || cur_cnt >= mean_bin_size ||
        (big_next && cur_cnt >= std::max(1.0, mean_bin_size * 0.5))) {
      bounds_out[nb++] = (distinct_values[i] + distinct_values[i + 1]) / 2.0;
      cur_cnt = 0.0;
      --remaining_bins;
      if (remaining_bins <= 1) break;
      if (!big_i && rest_bins > 0) {
        long long rest_bins_left = remaining_bins - big_suffix[i + 1];
        if (rest_bins_left > 0)
          mean_bin_size = std::max(1.0, rest_cnt / rest_bins_left);
      }
    }
  }
  return nb;
}

}  // extern "C"
