"""Native host-runtime components (C++/OpenMP via ctypes).

The TPU compute path is JAX/XLA; host-side ingestion (binning, parsing) is
native here just as the reference's DatasetLoader is C++/OpenMP. Builds on
demand with g++; every caller falls back to the NumPy path when the
toolchain or the compiled library is unavailable.
"""

from .build import load_native  # noqa: F401
