"""On-demand g++ build + ctypes loader for the native helpers."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

_lock = threading.Lock()
_lib = None
_tried = False


def _build(src: Path, out: Path) -> bool:
    # no -march=native: the cached .so may be shared across hosts (NFS,
    # container images) and a binary search gains little from wide SIMD.
    # compile to a temp file and os.replace: concurrent builders (the
    # multi-process launcher) must never let a reader map a half-written ELF
    tmp = out.with_name(f"{out.name}.{os.getpid()}.tmp")
    cmd = [
        "g++", "-O3", "-fopenmp", "-shared", "-fPIC",
        str(src), "-o", str(tmp),
    ]
    try:
        r = subprocess.run(cmd, capture_output=True, timeout=120)
        if r.returncode != 0 or not tmp.exists():
            return False
        os.replace(tmp, out)
        return True
    except (OSError, subprocess.TimeoutExpired):
        return False
    finally:
        if tmp.exists():
            try:
                tmp.unlink()
            except OSError:
                pass


def load_native() -> Optional[ctypes.CDLL]:
    """The compiled helper library, or None (NumPy fallback)."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if os.environ.get("LGBM_TPU_NO_NATIVE"):
            return None
        here = Path(__file__).parent
        src = here / "binning.cpp"
        out = here / "_binning.so"

        def _load():
            lib = ctypes.CDLL(str(out))
            lib.bin_numeric_f64.argtypes = [
                ctypes.c_void_p, ctypes.c_longlong, ctypes.c_void_p,
                ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_double,
                ctypes.c_void_p,
            ]
            lib.greedy_find_bin.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_longlong,
                ctypes.c_int, ctypes.c_double, ctypes.c_double,
                ctypes.c_void_p,
            ]
            lib.greedy_find_bin.restype = ctypes.c_int
            return lib

        try:
            if not out.exists() or out.stat().st_mtime < src.stat().st_mtime:
                if not _build(src, out):
                    return None
            try:
                _lib = _load()
            except AttributeError:
                # stale cached .so predating a newly added symbol
                # (mtime-preserving copies skip the rebuild): rebuild once
                out.unlink(missing_ok=True)
                _lib = _load() if _build(src, out) else None
        except (OSError, AttributeError):
            _lib = None
        return _lib
