"""Out-of-core streaming ingest: chunked two-pass Dataset construction.

The reference's ``DatasetLoader`` is sample-then-bin by design —
``GreedyFindBin`` fits bin boundaries from a ``bin_construct_sample_cnt``
row *sample* (src/io/bin.cpp), yet the loader still materializes the whole
raw matrix first.  This package removes that last O(num_rows x features)
host allocation:

* **pass 1** draws the one-shot path's exact seeded sample
  (``rng.choice`` over the known row count — byte-identical sample rows)
  from a chunked source and fits bin mappers + the EFB bundle layout on
  the sample only;
* **pass 2** streams chunks through ``BinMapper.values_to_bins`` straight
  into the preallocated packed bin planes (optionally ``np.memmap``-backed
  via ``ingest_mmap_dir``), a thread pool binning chunks in parallel.

Peak host memory is O(max(chunk_rows, sample_cnt) x features) + the packed
uint8/uint16 planes; the raw float64 matrix never exists.  The acceptance
oracle is byte parity: a chunk-streamed Dataset produces bit-identical bin
planes, bundle layout, and trained model dump versus the one-shot path on
the same data and seed (tests/test_ingest.py).

Sources (``sources.py``): chunked text/CSV, memory-mapped ``.npy``, Arrow
record-batch slices, pandas frames, ``Sequence`` batches, plain ndarrays,
and a user-facing ``Dataset(data=[chunk0, chunk1, ...])`` /
``Dataset(data=callable)`` chunk-iterable path.  Sharded per-host ingest
(``sharded.py``): under ``pre_partition`` each host reads only its row
shard and the per-host sample blocks are allgathered (bit-exact f64 over
the uint8 varlen channel, JSON summaries riding alongside as in
``obs/aggregate.py``) so every host fits identical global bin mappers.
"""

from .pipeline import stream_pack
from .sources import (
    ChunkSource,
    StreamingUnsupported,
    is_chunk_iterable,
    make_chunk_source,
    materialize_chunks,
)

__all__ = [
    "ChunkSource",
    "StreamingUnsupported",
    "is_chunk_iterable",
    "make_chunk_source",
    "materialize_chunks",
    "stream_pack",
]
