"""Sharded per-host streaming ingest: one GLOBAL sample, identical mappers.

Under ``pre_partition`` (``tree_learner=data``) each host reads only its
row shard, so per-host samples would fit disagreeing bin mappers.  The
one-shot path solves this with a per-feature mapper allgather
(``_sync_mappers_across_processes``); the streamed path instead assembles
the GLOBAL seeded sample on every host, after which mapper fitting — and,
unlike the one-shot path, EFB bundling — is an identical local
computation everywhere:

* per-host shard summaries (row count + the sampling knobs every host
  must agree on) ride as JSON-over-uint8 on
  ``parallel.allgather_host_varlen`` — the same channel
  ``obs/aggregate.py`` uses for telemetry snapshots;
* the global sample rows are drawn from the summed row count with the
  one-shot rng (`default_rng(data_random_seed).choice`) — byte-identical
  to a single-host draw over the concatenated matrix;
* each host gathers its owned sampled rows and the float64 blocks are
  allgathered bit-exactly (8-byte payloads ride ``allgather_host_exact``
  as uint32 pairs).  Rank shards own ascending global row ranges, so the
  rank-order concatenation IS the row-sorted global sample.
"""

from __future__ import annotations

import json
from typing import Tuple

import numpy as np


def exchange_global_sample(source, config) -> Tuple[int, int, np.ndarray]:
    """Returns ``(global_n, row_offset, sample)``: the shard's global row
    offset and the [sample_cnt, F] sample matrix, identical on every host
    and byte-identical to the one-shot single-host draw."""
    import jax

    from ..parallel import allgather_host_varlen

    rank = jax.process_index()
    summary = {
        "process": int(rank),
        "rows": int(source.n_rows),
        "cols": int(source.n_cols),
        "seed": int(config.data_random_seed),
        "sample_cnt": int(config.bin_construct_sample_cnt),
    }
    payload = np.frombuffer(
        json.dumps(summary, sort_keys=True).encode("utf-8"), dtype=np.uint8
    )
    gathered, counts = allgather_host_varlen(payload, return_counts=True)
    summaries = []
    off = 0
    for c in counts:
        c = int(c)
        summaries.append(
            json.loads(bytes(gathered[off : off + c]).decode("utf-8"))
        )
        off += c
    for s in summaries:
        for key in ("seed", "sample_cnt"):
            if s[key] != summary[key]:
                raise ValueError(
                    f"sharded ingest {key} disagrees across hosts: "
                    f"process {s['process']} has {s[key]}, "
                    f"process {rank} has {summary[key]}"
                )
        if s["cols"] != summary["cols"]:
            raise ValueError(
                "sharded ingest shards disagree on feature count: "
                f"process {s['process']} has {s['cols']} columns, "
                f"process {rank} has {summary['cols']}"
            )
    rows_per_host = [int(s["rows"]) for s in summaries]  # process order
    global_n = int(sum(rows_per_host))
    offset = int(sum(rows_per_host[:rank]))

    sample_cnt = min(global_n, int(config.bin_construct_sample_cnt))
    if sample_cnt < global_n:
        rng = np.random.default_rng(config.data_random_seed)
        rows = np.sort(rng.choice(global_n, size=sample_cnt, replace=False))
    else:
        rows = np.arange(global_n, dtype=np.int64)
    lo = np.searchsorted(rows, offset)
    hi = np.searchsorted(rows, offset + source.n_rows)
    local_block = source.sample_rows(np.asarray(rows[lo:hi]) - offset)
    sample = allgather_host_varlen(np.ascontiguousarray(local_block))
    return global_n, offset, sample
