"""Pass 2 of streaming construction: parallel chunk binning into
preallocated packed planes.

``stream_pack`` walks a :class:`~.sources.ChunkSource` once and writes each
chunk's packed bins into its row slice of one preallocated [N, P]
uint8/uint16 matrix (optionally ``np.memmap``-backed).  Packing is per-row
— ``pack_columns`` on a chunk equals the corresponding row slice of
``pack_columns`` on the full matrix — so the result is byte-identical to
the one-shot path.  Chunks bin on a thread pool (``num_threads``; binning
is numpy, which releases the GIL) writing disjoint row slices; a bounded
in-flight window keeps at most a few raw chunks alive at once.
"""

from __future__ import annotations

import os
import tempfile
import time
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from typing import List, Optional

import numpy as np

from ..obs.registry import get_session

# raw chunks admitted beyond the worker count before the producer blocks;
# bounds peak memory at ~(num_threads + _BACKLOG) chunks
_BACKLOG = 2


def peak_rss_bytes() -> int:
    """This process's lifetime peak RSS (``ru_maxrss`` is KB on Linux)."""
    try:
        import resource

        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:  # pragma: no cover - non-POSIX
        return 0


def _alloc_bins(n: int, n_cols: int, dtype, mmap_dir: str) -> np.ndarray:
    if not mmap_dir or n * max(1, n_cols) == 0:
        return np.zeros((n, n_cols), dtype=dtype)
    os.makedirs(mmap_dir, exist_ok=True)
    fd, path = tempfile.mkstemp(
        prefix="lgbtpu_bins_", suffix=".mmap", dir=mmap_dir
    )
    os.close(fd)
    out = np.memmap(path, dtype=dtype, mode="w+", shape=(n, n_cols))
    # unlink-after-map: the mapping stays valid and the blocks are
    # reclaimed when the last reference drops, with nothing left behind
    # even on a crash (ndarrays take no weakrefs, so no finalizer)
    os.unlink(path)
    return out


def stream_pack(
    source,
    bin_mappers: List,
    used_features: List[int],
    layout,
    dtype,
    config,
) -> np.ndarray:
    """Bin + pack every chunk of ``source`` into one [n_rows, planes]
    matrix; byte-identical to one-shot packing of the full matrix."""
    n = source.n_rows
    n_cols = layout.num_planes if layout is not None else len(used_features)
    out = _alloc_bins(n, n_cols, dtype, config.ingest_mmap_dir)

    def pack_chunk(start: int, chunk: np.ndarray) -> int:
        m = chunk.shape[0]
        if layout is not None:
            block = layout.pack_columns(
                m, lambda j: bin_mappers[j].values_to_bins(chunk[:, j])
            )
        elif used_features:
            block = np.stack(
                [
                    bin_mappers[j].values_to_bins(chunk[:, j])
                    for j in used_features
                ],
                axis=1,
            )
        else:
            block = np.zeros((m, 0), dtype=np.int32)
        out[start : start + m] = block
        return m

    threads = max(1, int(config.num_threads) or (os.cpu_count() or 1))
    t0 = time.perf_counter()
    chunks_total = 0
    if threads == 1:
        for s, c in source.chunks():
            pack_chunk(s, c)
            chunks_total += 1
    else:
        inflight = set()
        with ThreadPoolExecutor(
            max_workers=threads, thread_name_prefix="lgbtpu-ingest"
        ) as ex:
            for s, c in source.chunks():
                inflight.add(ex.submit(pack_chunk, s, c))
                chunks_total += 1
                if len(inflight) > threads + _BACKLOG:
                    done, inflight = wait(
                        inflight, return_when=FIRST_COMPLETED
                    )
                    for f in done:
                        f.result()
            for f in inflight:
                f.result()
    elapsed = time.perf_counter() - t0
    sess = get_session()
    if sess.enabled:
        sess.update_gauges(
            {
                "ingest/chunks_total": float(chunks_total),
                "ingest/rows_per_sec": (n / elapsed) if elapsed > 0 else 0.0,
                "ingest/peak_rss_bytes": float(peak_rss_bytes()),
            }
        )
    return out
