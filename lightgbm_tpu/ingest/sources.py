"""Chunked row sources for streaming Dataset construction.

Every source yields float64 FEATURE chunks ``(row_start, [m, n_cols])`` in
row order and must be re-iterable: the two-pass pipeline reads the sample
in pass 1 (``sample_rows`` — sources with random access gather directly;
the rest replay their chunks) and streams every row in pass 2
(``chunks()``).  Conversion to float64 happens per chunk, which is the
whole point — it is elementwise, so the binned output is byte-identical
to converting the full matrix at once, without ever holding that matrix.

The text source additionally collects the one-shot text loader's per-row
fields (label / weight_column / group_column plus the ``.query``/
``.weight``/``.init``/``.position`` sidecars) while pass 2 streams by, and
serves them from :meth:`row_fields` afterwards.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

# chunk granularity when a source must stream but the knob is unset
# (chunk-iterable data with ingest_chunk_rows=0)
DEFAULT_CHUNK_ROWS = 65536


class StreamingUnsupported(Exception):
    """Raised when a source cannot stream (LibSVM text, parser plugins,
    sparse matrices); the caller falls back to the one-shot path."""


class ChunkSource:
    """Re-iterable chunked view over a row-major data source."""

    n_rows: int = 0
    n_cols: int = 0
    # features forced trivial (weight/group/ignore columns); text sources
    # resolve these up front so pass-1 mapper fitting can honor them
    ignore_features: Tuple[int, ...] = ()

    def chunks(self) -> Iterator[Tuple[int, np.ndarray]]:
        raise NotImplementedError

    def sample_rows(self, rows: np.ndarray) -> np.ndarray:
        """Gather ``rows`` (sorted global row indices) as an [k, n_cols]
        float64 matrix.  Default: replay chunks and gather; random-access
        sources override with a direct fancy index."""
        rows = np.asarray(rows, dtype=np.int64)
        out = np.empty((len(rows), self.n_cols), np.float64)
        for start, chunk in self.chunks():
            lo = np.searchsorted(rows, start)
            hi = np.searchsorted(rows, start + len(chunk))
            if lo < hi:
                out[lo:hi] = chunk[rows[lo:hi] - start]
        return out

    def row_fields(self) -> Dict[str, Any]:
        """Per-row fields discovered while streaming (text sources: label,
        weight, group, sidecars).  Valid only after ``chunks()`` has been
        fully consumed at least once."""
        return {}


class ArrayChunkSource(ChunkSource):
    """ndarray / np.memmap source: slices convert to float64 per chunk."""

    def __init__(self, arr, chunk_rows: int) -> None:
        if getattr(arr, "ndim", None) != 2:
            raise ValueError(f"data must be 2-D, got shape {getattr(arr, 'shape', None)}")
        self._arr = arr
        self._chunk_rows = max(1, int(chunk_rows))
        self.n_rows, self.n_cols = arr.shape

    def chunks(self):
        for s in range(0, self.n_rows, self._chunk_rows):
            e = min(self.n_rows, s + self._chunk_rows)
            yield s, np.asarray(self._arr[s:e], dtype=np.float64)

    def sample_rows(self, rows):
        rows = np.asarray(rows, dtype=np.int64)
        return np.asarray(self._arr[rows], dtype=np.float64)


class ChunkListSource(ChunkSource):
    """User-provided chunk iterable: a list/tuple of 2-D row blocks.

    The blocks define the chunk granularity; a ragged last block is fine.
    """

    def __init__(self, parts) -> None:
        parts = list(parts)
        if not parts:
            raise ValueError("empty chunk list")
        shapes = []
        for p in parts:
            if getattr(p, "ndim", None) != 2:
                raise ValueError(
                    "chunked data must be a sequence of 2-D row blocks; got "
                    f"a block of shape {getattr(p, 'shape', None)}"
                )
            shapes.append(p.shape)
        widths = {s[1] for s in shapes}
        if len(widths) != 1:
            raise ValueError(f"chunk column counts disagree: {sorted(widths)}")
        self._parts = parts
        self.n_rows = sum(s[0] for s in shapes)
        self.n_cols = widths.pop()

    def chunks(self):
        start = 0
        for p in self._parts:
            yield start, np.asarray(p, dtype=np.float64)
            start += p.shape[0]


class CallableChunkSource(ChunkSource):
    """``data=callable``: each call must return a FRESH iterator of 2-D row
    blocks (the two-pass build iterates more than once).  One extra probe
    iteration establishes the row count the seeded sample draw needs."""

    def __init__(self, fn) -> None:
        self._fn = fn
        n = 0
        cols: Optional[int] = None
        for p in self._iter_blocks():
            n += p.shape[0]
            if cols is None:
                cols = p.shape[1]
            elif cols != p.shape[1]:
                raise ValueError(
                    f"chunk column counts disagree: {cols} vs {p.shape[1]}"
                )
        if cols is None:
            raise ValueError("chunk callable yielded no chunks")
        self.n_rows, self.n_cols = n, cols

    def _iter_blocks(self):
        it = self._fn()
        if it is None or not hasattr(it, "__iter__"):
            raise ValueError(
                "chunk callable must return an iterable of 2-D row blocks"
            )
        for p in it:
            p = np.asarray(p)
            if p.ndim != 2:
                raise ValueError(
                    f"chunk callable yielded a block of shape {p.shape}; "
                    "2-D row blocks expected"
                )
            yield p

    def chunks(self):
        start = 0
        for p in self._iter_blocks():
            yield start, np.asarray(p, dtype=np.float64)
            start += p.shape[0]
        if start != self.n_rows:
            raise ValueError(
                f"chunk callable is not re-iterable: {start} rows on replay "
                f"vs {self.n_rows} on the first pass (generators exhaust; "
                "return a fresh iterator per call)"
            )


class SequenceChunkSource(ChunkSource):
    """lightgbm Sequence sources, streamed batch-by-batch instead of
    materialized (the batches are the same slices
    ``_materialize_sequences`` takes, so values match elementwise)."""

    def __init__(self, seqs) -> None:
        self._seqs = list(seqs)
        self.n_rows = sum(len(s) for s in self._seqs)
        first = np.asarray(self._seqs[0][slice(0, 1)])
        if first.ndim != 2:
            raise ValueError(
                f"Sequence rows must be 2-D slices, got shape {first.shape}"
            )
        self.n_cols = first.shape[1]

    def chunks(self):
        start = 0
        for seq in self._seqs:
            n = len(seq)
            bs = getattr(seq, "batch_size", None) or 4096
            for s in range(0, n, bs):
                part = np.asarray(seq[slice(s, min(s + bs, n))])
                yield start, np.asarray(part, dtype=np.float64)
                start += part.shape[0]


class ArrowChunkSource(ChunkSource):
    """pyarrow Table/RecordBatch, converted slice-by-slice.

    The table is combined once up front so every slice shares ONE unified
    dictionary per categorical column — slice conversions then reuse the
    recorded category order verbatim and the float codes are identical to
    a full-table ``_arrow_to_numpy``.
    """

    def __init__(self, data, chunk_rows: int, ref_maps=None) -> None:
        import pyarrow as pa

        if isinstance(data, pa.RecordBatch):
            data = pa.Table.from_batches([data])
        self._table = data.combine_chunks()
        self._chunk_rows = max(1, int(chunk_rows))
        self.n_rows = self._table.num_rows
        self.n_cols = self._table.num_columns
        self.names = [str(c) for c in self._table.schema.names]
        self.cats = [
            self.names[i]
            for i, f in enumerate(self._table.schema)
            if pa.types.is_dictionary(f.type)
        ]
        if ref_maps is not None:
            self.category_maps = ref_maps
        else:
            # record the unified dictionaries only — no row data touched
            self.category_maps = {}
            for i, f in enumerate(self._table.schema):
                if pa.types.is_dictionary(f.type):
                    cc = self._table.column(i).combine_chunks()
                    self.category_maps[self.names[i]] = [
                        v.as_py() for v in cc.dictionary
                    ]

    def _convert(self, tbl) -> np.ndarray:
        from ..dataset import _arrow_to_numpy

        mat, _names, _cats, _maps = _arrow_to_numpy(tbl, self.category_maps)
        return mat

    def chunks(self):
        for s in range(0, self.n_rows, self._chunk_rows):
            m = min(self.n_rows, s + self._chunk_rows) - s
            yield s, self._convert(self._table.slice(s, m))

    def sample_rows(self, rows):
        rows = np.asarray(rows, dtype=np.int64)
        return self._convert(self._table.take(rows))


class PandasChunkSource(ChunkSource):
    """pandas DataFrame, converted ``iloc`` slice-by-slice through the
    full-column category record (float codes match a full-frame
    ``_pandas_to_numpy`` by value)."""

    def __init__(self, df, chunk_rows: int, ref_maps=None) -> None:
        from ..dataset import _is_cat_dtype

        self._df = df
        self._chunk_rows = max(1, int(chunk_rows))
        self.n_rows, self.n_cols = len(df), len(df.columns)
        self.names = [str(c) for c in df.columns]
        self.cats = [
            str(c) for c in df.columns if _is_cat_dtype(df[c].dtype)
        ]
        if ref_maps is not None:
            self.category_maps = ref_maps
        else:
            self.category_maps = {}
            for name in self.cats:
                cc = df[name].astype("category")
                self.category_maps[name] = [
                    v.item() if hasattr(v, "item") else v
                    for v in cc.cat.categories
                ]

    def _convert(self, frame) -> np.ndarray:
        from ..dataset import _pandas_to_numpy

        mat, _cats, _maps = _pandas_to_numpy(frame, self.category_maps)
        return mat

    def chunks(self):
        for s in range(0, self.n_rows, self._chunk_rows):
            e = min(self.n_rows, s + self._chunk_rows)
            yield s, self._convert(self._df.iloc[s:e])

    def sample_rows(self, rows):
        rows = np.asarray(rows, dtype=np.int64)
        return self._convert(self._df.iloc[rows])


class TextChunkSource(ChunkSource):
    """Chunked CSV/TSV reader with one-shot ``_load_text_file`` parity.

    Three streaming passes over the file, none holding more than a chunk:
    a line-count probe (the seeded sample draw needs the row count up
    front), a pass-1 gather that parses ONLY the sampled lines, and the
    pass-2 full parse that also collects label / weight_column /
    group_column.  Values go through the same ``np.loadtxt`` parser as the
    one-shot path, fed batches of lines instead of the whole file.
    """

    def __init__(self, path: str, config, chunk_rows: int) -> None:
        from ..dataset import (
            _is_libsvm_row,
            _label_column_index,
            _resolve_data_columns,
        )

        self._path = str(path)
        self._config = config
        self._chunk_rows = max(1, int(chunk_rows))
        if config.parser_config_file:
            raise StreamingUnsupported("parser plugins load one-shot")
        self._skip = 1 if config.header else 0
        with open(self._path, "r") as fh:
            first = fh.readline().rstrip("\n").rstrip("\r")
            probe: List[str] = []
            fh.seek(0)
            for i, ln in enumerate(fh):
                if i < self._skip:
                    continue
                if ln.strip():
                    probe.append(ln)
                if len(probe) >= 20:
                    break
        header_line = first if (config.header and first) else None
        if probe and any(_is_libsvm_row(ln) for ln in probe):
            raise StreamingUnsupported("LibSVM loads through the sparse path")
        self._delim = "\t" if "\t" in first else ("," if "," in first else None)
        self._label_col = _label_column_index(config, header_line)
        self._wcols = _resolve_data_columns(
            config.weight_column, header_line, self._label_col, "weight_column"
        )
        self._gcols = _resolve_data_columns(
            config.group_column, header_line, self._label_col, "group_column"
        )
        self._icols = _resolve_data_columns(
            config.ignore_column, header_line, self._label_col, "ignore_column"
        )
        ignore_raw = self._wcols[:1] + self._gcols[:1] + self._icols
        lc = self._label_col
        self.ignore_features = tuple(
            sorted({c - (1 if c > lc else 0) for c in ignore_raw if c != lc})
        )
        # count pass: rows np.loadtxt would parse (blank/comment lines drop)
        n = 0
        for _ in self._data_lines():
            n += 1
        self.n_rows = n
        probe_arr = self._parse(probe[:1]) if probe else np.zeros((0, 1))
        self.n_cols = probe_arr.shape[1] - 1  # label column removed
        self._fields: Optional[Dict[str, Any]] = None

    def _data_lines(self) -> Iterator[str]:
        """The file's parseable data lines, comment-stripped — exactly the
        rows ``np.loadtxt`` (comments='#') yields for the whole file."""
        with open(self._path, "r") as fh:
            for i, ln in enumerate(fh):
                if i < self._skip:
                    continue
                ln = ln.split("#", 1)[0].strip()
                if ln:
                    yield ln

    def _parse(self, lines: List[str]) -> np.ndarray:
        return np.loadtxt(
            lines, delimiter=self._delim, dtype=np.float64, ndmin=2
        )

    def sample_rows(self, rows):
        rows = np.asarray(rows, dtype=np.int64)
        wanted = set(rows.tolist())
        picked = [
            ln for i, ln in enumerate(self._data_lines()) if i in wanted
        ]
        arr = self._parse(picked)
        return np.delete(arr, self._label_col, axis=1)

    def chunks(self):
        collect = self._fields is None
        labels: List[np.ndarray] = []
        weights: List[np.ndarray] = []
        qids: List[np.ndarray] = []
        start = 0
        batch: List[str] = []
        for ln in self._data_lines():
            batch.append(ln)
            if len(batch) >= self._chunk_rows:
                start = yield from self._emit(
                    batch, start, collect, labels, weights, qids
                )
                batch = []
        if batch:
            start = yield from self._emit(
                batch, start, collect, labels, weights, qids
            )
        if collect:
            self._fields = self._assemble_fields(labels, weights, qids)

    def _emit(self, batch, start, collect, labels, weights, qids):
        arr = self._parse(batch)
        if collect:
            # copy: a column view would pin the whole parsed chunk alive
            # until pass-2 ends, rebuilding the matrix we're streaming out
            labels.append(arr[:, self._label_col].copy())
            if self._wcols:
                weights.append(arr[:, self._wcols[0]].astype(np.float64))
            if self._gcols:
                qids.append(arr[:, self._gcols[0]].astype(np.int64))
        yield start, np.delete(arr, self._label_col, axis=1)
        return start + arr.shape[0]

    def _assemble_fields(self, labels, weights, qids) -> Dict[str, Any]:
        from ..dataset import _attach_sidecars

        out: Dict[str, Any] = {
            "label": (
                np.concatenate(labels) if labels else np.zeros(0, np.float64)
            )
        }
        if self._wcols:
            out["weight"] = np.concatenate(weights)
        if self._gcols:
            # consecutive query-id runs -> sizes (Metadata::SetQueryId)
            q = np.concatenate(qids)
            change = np.nonzero(np.diff(q))[0] + 1
            bounds = np.concatenate([[0], change, [len(q)]])
            out["group"] = np.diff(bounds)
        if self.ignore_features:
            out["ignore"] = list(self.ignore_features)
        return _attach_sidecars(out, self._path)

    def row_fields(self) -> Dict[str, Any]:
        if self._fields is None:
            raise RuntimeError("row_fields() before pass-2 iteration")
        return self._fields


def is_chunk_iterable(data) -> bool:
    """True for the explicit chunked-data API: a list/tuple of 2-D row
    blocks, or a callable returning a fresh iterator of them."""
    if callable(data) and not isinstance(data, type) and not hasattr(
        data, "__array__"
    ):
        return True
    return isinstance(data, (list, tuple)) and bool(data) and all(
        isinstance(p, np.ndarray) and p.ndim == 2 for p in data
    )


def materialize_chunks(data):
    """Chunk-iterable -> one dense float64 matrix: the one-shot fallback
    when streaming is declined (linear_tree / free_raw_data=false need the
    raw matrix anyway).  Non-chunk-iterable data passes through."""
    if not is_chunk_iterable(data):
        return data
    src = (
        CallableChunkSource(data) if callable(data) else ChunkListSource(data)
    )
    return np.concatenate([c for _, c in src.chunks()], axis=0)


def _all_sequences(data) -> bool:
    from ..dataset import Sequence

    return isinstance(data, list) and bool(data) and all(
        isinstance(d, Sequence) for d in data
    )


def make_chunk_source(data, config, ref_maps=None) -> Optional[ChunkSource]:
    """A ChunkSource for ``data``, or None for the one-shot path.

    Chunk-iterable inputs (list/tuple of row blocks, or a callable
    returning a fresh block iterator) ALWAYS stream — they are the
    explicit out-of-core API.  Everything else streams only when
    ``ingest_chunk_rows > 0``: text/CSV files, ``.npy`` (memory-mapped),
    ndarrays, Sequences, Arrow tables and pandas frames.  Sparse matrices
    bin through the CSC path, which never densifies anyway.
    """
    from ..dataset import Sequence, _is_arrow

    chunk_rows = int(config.ingest_chunk_rows) or DEFAULT_CHUNK_ROWS
    if callable(data) and not isinstance(data, type) and not hasattr(
        data, "__array__"
    ):
        return CallableChunkSource(data)
    if isinstance(data, (list, tuple)) and data and all(
        isinstance(p, np.ndarray) and getattr(p, "ndim", 0) == 2
        for p in data
    ):
        return ChunkListSource(data)
    if config.ingest_chunk_rows <= 0:
        return None
    if isinstance(data, (str, Path)):
        p = str(data)
        if p.endswith(".npy"):
            return ArrayChunkSource(
                np.load(p, mmap_mode="r"), chunk_rows
            )
        try:
            return TextChunkSource(p, config, chunk_rows)
        except StreamingUnsupported:
            return None
    if isinstance(data, Sequence):
        return SequenceChunkSource([data])
    if _all_sequences(data):
        return SequenceChunkSource(data)
    if _is_arrow(data):
        return ArrowChunkSource(data, chunk_rows, ref_maps)
    try:
        import pandas as pd  # noqa: F401

        if isinstance(data, pd.DataFrame):
            return PandasChunkSource(data, chunk_rows, ref_maps)
    except ImportError:
        pass
    if isinstance(data, np.ndarray) and data.ndim == 2:
        return ArrayChunkSource(data, chunk_rows)
    return None
