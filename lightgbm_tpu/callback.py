"""Training callbacks (reference: python-package/lightgbm/callback.py).

The reference's callback protocol is reproduced exactly: callables receive a
``CallbackEnv`` namedtuple; ``before_iteration`` attributes order them before
the boosting update; ``EarlyStopException`` unwinds the training loop.
"""

from __future__ import annotations

import collections
from .utils.log import log_info
from typing import Any, Callable, Dict, List, Optional


class EarlyStopException(Exception):
    """Raised to stop training (reference callback.py EarlyStopException)."""

    def __init__(self, best_iteration: int, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration", "evaluation_result_list"],
)


def log_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    """Print eval results every ``period`` iterations (reference
    callback.py log_evaluation)."""

    def _callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list and (env.iteration + 1) % period == 0:
            parts = []
            for item in env.evaluation_result_list:
                if len(item) == 4:
                    data_name, eval_name, result, _ = item
                    parts.append(f"{data_name}'s {eval_name}: {result:g}")
                else:
                    data_name, eval_name, result, _, stdv = item
                    if show_stdv:
                        parts.append(f"{data_name}'s {eval_name}: {result:g} + {stdv:g}")
                    else:
                        parts.append(f"{data_name}'s {eval_name}: {result:g}")
            log_info(f"[{env.iteration + 1}]\t" + "\t".join(parts))

    _callback.order = 10
    return _callback


print_evaluation = log_evaluation  # legacy alias


def record_evaluation(eval_result: Dict[str, Dict[str, List[float]]]) -> Callable:
    """Record eval results into a nested dict (reference record_evaluation)."""
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result should be a dictionary")

    def _init(env: CallbackEnv) -> None:
        eval_result.clear()
        for item in env.evaluation_result_list or []:
            data_name, eval_name = item[0], item[1]
            eval_result.setdefault(data_name, collections.OrderedDict()).setdefault(
                eval_name, []
            )

    def _callback(env: CallbackEnv) -> None:
        if not eval_result:
            _init(env)
        for item in env.evaluation_result_list or []:
            data_name, eval_name, result = item[0], item[1], item[2]
            eval_result.setdefault(data_name, collections.OrderedDict()).setdefault(
                eval_name, []
            ).append(result)

    _callback.order = 20
    return _callback


def reset_parameter(**kwargs: Any) -> Callable:
    """Reset parameters per iteration: value list or callable(iter) -> value
    (reference reset_parameter; used for learning-rate schedules)."""

    def _callback(env: CallbackEnv) -> None:
        new_parameters = {}
        for key, value in kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError(
                        f"Length of list {key!r} has to equal to 'num_boost_round'."
                    )
                new_param = value[env.iteration - env.begin_iteration]
            elif callable(value):
                new_param = value(env.iteration - env.begin_iteration)
            else:
                raise ValueError(f"invalid value for {key!r}")
            new_parameters[key] = new_param
        if new_parameters:
            env.model.reset_parameter(new_parameters)

    _callback.before_iteration = True
    _callback.order = 10
    return _callback


def early_stopping(
    stopping_rounds: int,
    first_metric_only: bool = False,
    verbose: bool = True,
    min_delta: float = 0.0,
) -> Callable:
    """Early stopping on validation metrics (reference callback.py
    early_stopping / _EarlyStoppingCallback)."""
    best_score: List[float] = []
    best_iter: List[int] = []
    best_score_list: List[Any] = []
    cmp_op: List[Callable] = []
    enabled = [True]
    first_metric = [""]

    def _init(env: CallbackEnv) -> None:
        enabled[0] = bool(env.evaluation_result_list)
        if not enabled[0]:
            return
        best_score.clear()
        best_iter.clear()
        best_score_list.clear()
        cmp_op.clear()
        first_metric[0] = env.evaluation_result_list[0][1].split(" ")[-1]
        deltas = (
            min_delta
            if isinstance(min_delta, list)
            else [min_delta] * len(env.evaluation_result_list)
        )
        for item, delta in zip(env.evaluation_result_list, deltas):
            best_iter.append(0)
            best_score_list.append(None)
            higher_better = item[3]
            if higher_better:
                best_score.append(float("-inf"))
                cmp_op.append(lambda curr, best, d=delta: curr > best + d)
            else:
                best_score.append(float("inf"))
                cmp_op.append(lambda curr, best, d=delta: curr < best - d)

    def _callback(env: CallbackEnv) -> None:
        if not best_score:
            _init(env)
        if not enabled[0]:
            return
        for i, item in enumerate(env.evaluation_result_list):
            data_name, eval_name, score = item[0], item[1], item[2]
            if best_score_list[i] is None or cmp_op[i](score, best_score[i]):
                best_score[i] = score
                best_iter[i] = env.iteration
                best_score_list[i] = env.evaluation_result_list
            if first_metric_only and first_metric[0] != eval_name.split(" ")[-1]:
                continue
            if data_name == "training":
                continue
            if env.iteration - best_iter[i] >= stopping_rounds:
                env.model.best_iteration = best_iter[i] + 1
                if verbose:
                    log_info(
                        f"Early stopping, best iteration is:\n[{best_iter[i] + 1}]\t"
                        + "\t".join(
                            f"{it[0]}'s {it[1]}: {it[2]:g}" for it in best_score_list[i]
                        )
                    )
                raise EarlyStopException(best_iter[i], best_score_list[i])
            if env.iteration == env.end_iteration - 1:
                env.model.best_iteration = best_iter[i] + 1
                if verbose:
                    log_info(
                        "Did not meet early stopping. Best iteration is:\n"
                        f"[{best_iter[i] + 1}]\t"
                        + "\t".join(
                            f"{it[0]}'s {it[1]}: {it[2]:g}" for it in best_score_list[i]
                        )
                    )
                raise EarlyStopException(best_iter[i], best_score_list[i])

    _callback.order = 30
    return _callback

def checkpoint_callback(checkpoint_dir: str, period: int = 1, keep_last: Optional[int] = None) -> Callable:
    """Write a full resilience checkpoint every ``period`` iterations.

    Callback-driven alternative to the ``checkpoint_dir``/
    ``checkpoint_interval`` params (engine.py writes those in the train
    loop) for callers who manage callbacks explicitly; resume either way
    with ``lgb.train(..., resume_from=checkpoint_dir)``."""

    def _callback(env: CallbackEnv) -> None:
        if period > 0 and (env.iteration + 1) % period == 0:
            from .resilience.checkpoint import save_checkpoint as _save

            _save(env.model, checkpoint_dir, keep_last=keep_last)

    _callback.order = 40
    return _callback


class TelemetryCallback:
    """Collect each iteration's telemetry event (phases, compile counts,
    eval results) into ``self.history`` — requires ``telemetry=True`` in the
    training params so the obs session records events."""

    order = 25
    before_iteration = False

    def __init__(self) -> None:
        self.history: List[Dict[str, Any]] = []

    def __call__(self, env: CallbackEnv) -> None:
        from .obs.registry import get_session

        ses = get_session()
        if not ses.enabled:
            return
        for ev in ses.events:
            if ev.get("event") == "iteration" and ev.get("iter") == env.iteration:
                entry = dict(ev)
                if env.evaluation_result_list:
                    entry["eval"] = {
                        f"{item[0]}/{item[1]}": item[2]
                        for item in env.evaluation_result_list
                    }
                self.history.append(entry)
                break
