"""Jitted batch prediction: level-synchronous tree walks on device.

Reference analogs: the fork's batch path ``GBDT::PredictRawBatch``
(src/boosting/gbdt_prediction.cpp:60) -> ``PredictTreeBatchAVX512``
(include/LightGBM/tree_avx512.hpp:41) — 8-row level-synchronous walks; and the
scalar ``Tree::Predict`` (include/LightGBM/tree.h:596).

TPU-native formulation: ALL rows x ALL trees advance one level per step of a
``lax.while_loop`` — the AVX512 kernel's ``nodes[8]`` array becomes a
``[rows, trees]`` node-index matrix, every step is a pair of gathers plus a
compare (vectorized over the full batch), and the loop exits when every walk
has reached a leaf.  Two variants:

  * bin space (exact, used when BinMappers are available): decisions are
    ``bin <= split_bin`` with the NaN-bin default-direction rule — bit-for-bit
    the same decisions the trainer made;
  * real-value space (used for models loaded from text without mappers):
    ``NumericalDecision`` semantics (tree.h:346) in f32.
"""

from __future__ import annotations

import functools
from typing import List, NamedTuple, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .tree import (
    K_CATEGORICAL_MASK,
    K_DEFAULT_LEFT_MASK,
    K_ZERO_THRESHOLD,
    MISSING_NAN,
    MISSING_ZERO,
    Tree,
)


class BinTreeBatch(NamedTuple):
    """Stacked bin-space trees [T, ...]; bin-space mirrors the trainer."""

    split_feature: jnp.ndarray  # [T, M] used-feature column index
    split_bin: jnp.ndarray  # [T, M] int32
    default_left: jnp.ndarray  # [T, M] bool
    left_child: jnp.ndarray  # [T, M] int32 (neg = ~leaf)
    right_child: jnp.ndarray  # [T, M] int32
    leaf_value: jnp.ndarray  # [T, L] f32
    split_is_cat: jnp.ndarray  # [T, M] bool
    cat_mask: jnp.ndarray  # [T, M, Bm] bool — bin goes left (Bm=1 if no cat)


class RealTreeBatch(NamedTuple):
    """Stacked real-value trees (categoricals as per-node value bitsets)."""

    split_feature: jnp.ndarray  # [T, M] original feature index
    threshold: jnp.ndarray  # [T, M] f32
    decision_type: jnp.ndarray  # [T, M] int32
    left_child: jnp.ndarray  # [T, M] int32
    right_child: jnp.ndarray  # [T, M] int32
    leaf_value: jnp.ndarray  # [T, L] f32
    cat_words: jnp.ndarray  # [T, M, W] uint32 bitset over category VALUES
    cat_nwords: jnp.ndarray  # [T, M] int32 valid word count per node


def stack_bin_trees(records: List[dict], num_leaves_cap: int) -> BinTreeBatch:
    """Pad per-tree bin-space arrays (host dicts) into one [T, ...] batch."""
    t = len(records)
    m = max(1, max(len(r["split_feature"]) for r in records))
    # merged init-model trees may exceed the current config's num_leaves
    L = max(1, num_leaves_cap, max(len(r["leaf_value"]) for r in records))

    def padded(key, fill, dtype):
        out = np.full((t, m), fill, dtype=dtype)
        for i, r in enumerate(records):
            arr = np.asarray(r[key])
            out[i, : len(arr)] = arr
        return out

    leaf = np.zeros((t, L), dtype=np.float32)
    for i, r in enumerate(records):
        lv = np.asarray(r["leaf_value"], dtype=np.float32)
        leaf[i, : len(lv)] = lv
    left = padded("left_child", -1, np.int32)
    # single-leaf trees: route node 0 to leaf 0
    for i, r in enumerate(records):
        if len(r["split_feature"]) == 0:
            left[i, 0] = -1
    # categorical masks: width = max over trees (1 when no tree has any)
    bm = max(
        [1]
        + [
            np.asarray(r["cat_mask"]).shape[1]
            for r in records
            if r.get("cat_mask") is not None and np.size(r.get("cat_mask"))
        ]
    )
    is_cat = np.zeros((t, m), dtype=bool)
    cmask = np.zeros((t, m, bm), dtype=bool)
    for i, r in enumerate(records):
        sic = r.get("split_is_cat")
        cm = r.get("cat_mask")
        if sic is not None and len(sic):
            is_cat[i, : len(sic)] = sic
        if cm is not None and np.size(cm):
            cm = np.asarray(cm)
            cmask[i, : cm.shape[0], : cm.shape[1]] = cm
    return BinTreeBatch(
        split_feature=jnp.asarray(padded("split_feature", 0, np.int32)),
        split_bin=jnp.asarray(padded("split_bin", 0, np.int32)),
        default_left=jnp.asarray(padded("default_left", False, bool)),
        left_child=jnp.asarray(left),
        right_child=jnp.asarray(padded("right_child", -1, np.int32)),
        leaf_value=jnp.asarray(leaf),
        split_is_cat=jnp.asarray(is_cat),
        cat_mask=jnp.asarray(cmask),
    )


def stack_real_trees(trees: List[Tree]) -> RealTreeBatch:
    t = len(trees)
    m = max(1, max(tr.num_leaves - 1 for tr in trees))
    L = max(1, max(tr.num_leaves for tr in trees))
    sf = np.zeros((t, m), dtype=np.int32)
    th = np.zeros((t, m), dtype=np.float32)
    dt = np.zeros((t, m), dtype=np.int32)
    lc = np.full((t, m), -1, dtype=np.int32)
    rc = np.full((t, m), -1, dtype=np.int32)
    lv = np.zeros((t, L), dtype=np.float32)
    # per-node category-value bitsets (reference cat_threshold_ words,
    # tree.h:283): W = widest bitset across all cat nodes, 1 if none
    w = 1
    for tr in trees:
        if tr.cat_boundaries is not None:
            for ci in range(len(tr.cat_boundaries) - 1):
                w = max(w, int(tr.cat_boundaries[ci + 1] - tr.cat_boundaries[ci]))
    cw = np.zeros((t, m, w), dtype=np.uint32)
    cn = np.zeros((t, m), dtype=np.int32)
    for i, tr in enumerate(trees):
        nn = tr.num_leaves - 1
        sf[i, :nn] = tr.split_feature
        th[i, :nn] = tr.threshold
        dt[i, :nn] = tr.decision_type
        lc[i, :nn] = tr.left_child
        rc[i, :nn] = tr.right_child
        lv[i, : tr.num_leaves] = tr.leaf_value
        if tr.cat_boundaries is not None:
            for node in range(nn):
                if tr.decision_type[node] & 1:
                    ci = int(tr.threshold[node])
                    b0 = int(tr.cat_boundaries[ci])
                    b1 = int(tr.cat_boundaries[ci + 1])
                    cw[i, node, : b1 - b0] = tr.cat_threshold[b0:b1]
                    cn[i, node] = b1 - b0
    return RealTreeBatch(
        split_feature=jnp.asarray(sf),
        threshold=jnp.asarray(th),
        decision_type=jnp.asarray(dt),
        left_child=jnp.asarray(lc),
        right_child=jnp.asarray(rc),
        leaf_value=jnp.asarray(lv),
        cat_words=jnp.asarray(cw),
        cat_nwords=jnp.asarray(cn),
    )


def _walk(gather_decide, left, right, n_rows: int, n_trees: int):
    """Shared level-synchronous loop: advance [rows, trees] node indices."""
    tree_ids = jnp.arange(n_trees, dtype=jnp.int32)[None, :]

    def cond(nodes):
        return jnp.any(nodes >= 0)

    def body(nodes):
        cur = jnp.maximum(nodes, 0)
        go_left = gather_decide(cur, tree_ids)
        nxt = jnp.where(
            go_left, left[tree_ids, cur], right[tree_ids, cur]
        )
        return jnp.where(nodes >= 0, nxt, nodes)

    nodes0 = jnp.zeros((n_rows, n_trees), dtype=jnp.int32)
    return lax.while_loop(cond, body, nodes0)


@jax.jit
def predict_bins_leaves(batch: BinTreeBatch, bins: jnp.ndarray, nan_bins: jnp.ndarray) -> jnp.ndarray:
    """Leaf index per (row, tree). bins: [N, F_used] int32; nan_bins: [F_used]."""
    n = bins.shape[0]
    t = batch.split_feature.shape[0]

    def decide(cur, tree_ids):
        feat = batch.split_feature[tree_ids, cur]  # [N, T]
        tbin = batch.split_bin[tree_ids, cur]
        dl = batch.default_left[tree_ids, cur]
        fval = jnp.take_along_axis(bins, feat, axis=1)
        nb = nan_bins[feat]
        gl = (fval <= tbin) | (dl & (nb >= 0) & (fval == nb))
        bm = batch.cat_mask.shape[-1]
        if bm > 1:
            # one joint gather to [N, T] — a two-step index would materialize
            # an [N, T, Bm] intermediate inside every walk iteration
            gl_cat = batch.cat_mask[tree_ids, cur, jnp.minimum(fval, bm - 1)]
            # out-of-range bins (unseen-category sentinel) are never in the
            # left subset (reference CategoricalDecision, tree.h:382)
            gl_cat = gl_cat & (fval < bm)
            gl = jnp.where(batch.split_is_cat[tree_ids, cur], gl_cat, gl)
        return gl

    nodes = _walk(decide, batch.left_child, batch.right_child, n, t)
    return ~nodes  # [N, T] leaf indices


@jax.jit
def predict_bins_raw(batch: BinTreeBatch, bins: jnp.ndarray, nan_bins: jnp.ndarray) -> jnp.ndarray:
    """Sum of per-tree outputs [N, T] (caller groups by class and sums)."""
    leaves = predict_bins_leaves(batch, bins, nan_bins)
    t = batch.split_feature.shape[0]
    tree_ids = jnp.arange(t, dtype=jnp.int32)[None, :]
    return batch.leaf_value[tree_ids, leaves]  # [N, T]


@jax.jit
def predict_real_leaves(batch: RealTreeBatch, X: jnp.ndarray) -> jnp.ndarray:
    """Leaf index per (row, tree) with NumericalDecision semantics (f32)."""
    n = X.shape[0]
    t = batch.split_feature.shape[0]

    def decide(cur, tree_ids):
        feat = batch.split_feature[tree_ids, cur]
        thr = batch.threshold[tree_ids, cur]
        dt = batch.decision_type[tree_ids, cur]
        fval = jnp.take_along_axis(X, feat, axis=1)
        missing = (dt >> 2) & 3
        is_nan = jnp.isnan(fval)
        fv = jnp.where(is_nan & (missing != MISSING_NAN), 0.0, fval)
        is_missing = ((missing == MISSING_ZERO) & (jnp.abs(fv) <= K_ZERO_THRESHOLD)) | (
            (missing == MISSING_NAN) & jnp.isnan(fv)
        )
        dl = (dt & K_DEFAULT_LEFT_MASK) != 0
        gl = jnp.where(is_missing, dl, fv <= thr)
        # categorical: bit test in the node's value bitset; NaN/negative/
        # out-of-range values go right (CategoricalDecision, tree.h:346)
        wmax = batch.cat_words.shape[-1]
        is_cat = (dt & 1) != 0
        iv = jnp.where(is_nan | (fval < 0), -1, fval).astype(jnp.int32)
        word_idx = jnp.clip(iv // 32, 0, wmax - 1)
        words = batch.cat_words[tree_ids, cur]  # [N, T, W]
        word = jnp.take_along_axis(words, word_idx[..., None], axis=2)[..., 0]
        in_range = (iv >= 0) & ((iv // 32) < batch.cat_nwords[tree_ids, cur])
        bit = (word >> (iv % 32).astype(jnp.uint32)) & 1
        return jnp.where(is_cat, in_range & (bit == 1), gl)

    nodes = _walk(decide, batch.left_child, batch.right_child, n, t)
    return ~nodes


@jax.jit
def predict_real_raw(batch: RealTreeBatch, X: jnp.ndarray) -> jnp.ndarray:
    leaves = predict_real_leaves(batch, X)
    t = batch.split_feature.shape[0]
    tree_ids = jnp.arange(t, dtype=jnp.int32)[None, :]
    return batch.leaf_value[tree_ids, leaves]


@functools.partial(jax.jit, donate_argnums=(0,))
def add_tree_to_score(
    score_k: jnp.ndarray,  # [N] f32 (donated)
    bins: jnp.ndarray,  # [N, F_used]
    nan_bins: jnp.ndarray,  # [F_used]
    split_feature: jnp.ndarray,  # [L-1]
    split_bin: jnp.ndarray,
    default_left: jnp.ndarray,
    left_child: jnp.ndarray,
    right_child: jnp.ndarray,
    leaf_value: jnp.ndarray,  # [L] ALREADY shrunk
    split_is_cat: Optional[jnp.ndarray] = None,  # [L-1] bool
    cat_mask: Optional[jnp.ndarray] = None,  # [L-1, Bm] bool
) -> jnp.ndarray:
    """Walk one bin-space tree over a dataset and add leaf outputs to score —
    the valid-set ScoreUpdater::AddScore (src/boosting/score_updater.hpp:54)."""
    n = bins.shape[0]
    use_cat = cat_mask is not None and cat_mask.shape[-1] > 1

    def cond(nodes):
        return jnp.any(nodes >= 0)

    def body(nodes):
        cur = jnp.maximum(nodes, 0)
        feat = split_feature[cur]
        tbin = split_bin[cur]
        dl = default_left[cur]
        fval = jnp.take_along_axis(bins, feat[:, None], axis=1)[:, 0]
        nb = nan_bins[feat]
        go_left = (fval <= tbin) | (dl & (nb >= 0) & (fval == nb))
        if use_cat:
            bm = cat_mask.shape[-1]
            gl_cat = cat_mask[cur, jnp.minimum(fval, bm - 1)] & (fval < bm)
            go_left = jnp.where(split_is_cat[cur], gl_cat, go_left)
        nxt = jnp.where(go_left, left_child[cur], right_child[cur])
        return jnp.where(nodes >= 0, nxt, nodes)

    nodes = lax.while_loop(cond, body, jnp.zeros((n,), jnp.int32))
    return score_k + leaf_value[~nodes]
