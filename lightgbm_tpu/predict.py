"""Jitted batch prediction: level-synchronous tree walks on device.

Reference analogs: the fork's batch path ``GBDT::PredictRawBatch``
(src/boosting/gbdt_prediction.cpp:60) -> ``PredictTreeBatchAVX512``
(include/LightGBM/tree_avx512.hpp:41) — 8-row level-synchronous walks; and the
scalar ``Tree::Predict`` (include/LightGBM/tree.h:596).

TPU-native formulation: ALL rows x ALL trees advance one level per step of a
``lax.while_loop`` — the AVX512 kernel's ``nodes[8]`` array becomes a
``[rows, trees]`` node-index matrix, every step is a pair of gathers plus a
compare (vectorized over the full batch), and the loop exits when every walk
has reached a leaf.  Two variants:

  * bin space (exact, used when BinMappers are available): decisions are
    ``bin <= split_bin`` with the NaN-bin default-direction rule — bit-for-bit
    the same decisions the trainer made;
  * real-value space (used for models loaded from text without mappers):
    ``NumericalDecision`` semantics (tree.h:346) in f32.
"""

from __future__ import annotations

import functools
import time
from collections import deque
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .obs.device import sample_device_memory
from .obs.jit import instrumented_jit, note_executable
from .obs.registry import get_session
from .ops.tensor_forest import (
    _tensor_bins_leaves_impl,
    _tensor_bins_pertree_impl,
    build_tensor_forest,
    parity_probe_reason,
    tensor_reject_reason,
)
from .tree import (
    K_CATEGORICAL_MASK,
    K_DEFAULT_LEFT_MASK,
    K_ZERO_THRESHOLD,
    MISSING_NAN,
    MISSING_ZERO,
    Tree,
)


class BinTreeBatch(NamedTuple):
    """Stacked bin-space trees [T, ...]; bin-space mirrors the trainer."""

    split_feature: jnp.ndarray  # [T, M] used-feature column index
    split_bin: jnp.ndarray  # [T, M] int32
    default_left: jnp.ndarray  # [T, M] bool
    left_child: jnp.ndarray  # [T, M] int32 (neg = ~leaf)
    right_child: jnp.ndarray  # [T, M] int32
    leaf_value: jnp.ndarray  # [T, L] f32
    split_is_cat: jnp.ndarray  # [T, M] bool
    cat_mask: jnp.ndarray  # [T, M, Bm] bool — bin goes left (Bm=1 if no cat)


class RealTreeBatch(NamedTuple):
    """Stacked real-value trees (categoricals as per-node value bitsets)."""

    split_feature: jnp.ndarray  # [T, M] original feature index
    threshold: jnp.ndarray  # [T, M] f32
    decision_type: jnp.ndarray  # [T, M] int32
    left_child: jnp.ndarray  # [T, M] int32
    right_child: jnp.ndarray  # [T, M] int32
    leaf_value: jnp.ndarray  # [T, L] f32
    cat_words: jnp.ndarray  # [T, M, W] uint32 bitset over category VALUES
    cat_nwords: jnp.ndarray  # [T, M] int32 valid word count per node


def stack_bin_trees(records: List[dict], num_leaves_cap: int) -> BinTreeBatch:
    """Pad per-tree bin-space arrays (host dicts) into one [T, ...] batch."""
    t = len(records)
    m = max(1, max(len(r["split_feature"]) for r in records))
    # merged init-model trees may exceed the current config's num_leaves
    L = max(1, num_leaves_cap, max(len(r["leaf_value"]) for r in records))

    def padded(key, fill, dtype):
        out = np.full((t, m), fill, dtype=dtype)
        for i, r in enumerate(records):
            arr = np.asarray(r[key])
            out[i, : len(arr)] = arr
        return out

    leaf = np.zeros((t, L), dtype=np.float32)
    for i, r in enumerate(records):
        lv = np.asarray(r["leaf_value"], dtype=np.float32)
        leaf[i, : len(lv)] = lv
    left = padded("left_child", -1, np.int32)
    # single-leaf trees: route node 0 to leaf 0
    for i, r in enumerate(records):
        if len(r["split_feature"]) == 0:
            left[i, 0] = -1
    # categorical masks: width = max over trees (1 when no tree has any)
    bm = max(
        [1]
        + [
            np.asarray(r["cat_mask"]).shape[1]
            for r in records
            if r.get("cat_mask") is not None and np.size(r.get("cat_mask"))
        ]
    )
    is_cat = np.zeros((t, m), dtype=bool)
    cmask = np.zeros((t, m, bm), dtype=bool)
    for i, r in enumerate(records):
        sic = r.get("split_is_cat")
        cm = r.get("cat_mask")
        if sic is not None and len(sic):
            is_cat[i, : len(sic)] = sic
        if cm is not None and np.size(cm):
            cm = np.asarray(cm)
            cmask[i, : cm.shape[0], : cm.shape[1]] = cm
    return BinTreeBatch(
        split_feature=jnp.asarray(padded("split_feature", 0, np.int32)),
        split_bin=jnp.asarray(padded("split_bin", 0, np.int32)),
        default_left=jnp.asarray(padded("default_left", False, bool)),
        left_child=jnp.asarray(left),
        right_child=jnp.asarray(padded("right_child", -1, np.int32)),
        leaf_value=jnp.asarray(leaf),
        split_is_cat=jnp.asarray(is_cat),
        cat_mask=jnp.asarray(cmask),
    )


def stack_real_trees(trees: List[Tree]) -> RealTreeBatch:
    t = len(trees)
    m = max(1, max(tr.num_leaves - 1 for tr in trees))
    L = max(1, max(tr.num_leaves for tr in trees))
    sf = np.zeros((t, m), dtype=np.int32)
    th = np.zeros((t, m), dtype=np.float32)
    dt = np.zeros((t, m), dtype=np.int32)
    lc = np.full((t, m), -1, dtype=np.int32)
    rc = np.full((t, m), -1, dtype=np.int32)
    lv = np.zeros((t, L), dtype=np.float32)
    # per-node category-value bitsets (reference cat_threshold_ words,
    # tree.h:283): W = widest bitset across all cat nodes, 1 if none
    w = 1
    for tr in trees:
        if tr.cat_boundaries is not None:
            for ci in range(len(tr.cat_boundaries) - 1):
                w = max(w, int(tr.cat_boundaries[ci + 1] - tr.cat_boundaries[ci]))
    cw = np.zeros((t, m, w), dtype=np.uint32)
    cn = np.zeros((t, m), dtype=np.int32)
    for i, tr in enumerate(trees):
        nn = tr.num_leaves - 1
        sf[i, :nn] = tr.split_feature
        th[i, :nn] = tr.threshold
        dt[i, :nn] = tr.decision_type
        lc[i, :nn] = tr.left_child
        rc[i, :nn] = tr.right_child
        lv[i, : tr.num_leaves] = tr.leaf_value
        if tr.cat_boundaries is not None:
            for node in range(nn):
                if tr.decision_type[node] & 1:
                    ci = int(tr.threshold[node])
                    b0 = int(tr.cat_boundaries[ci])
                    b1 = int(tr.cat_boundaries[ci + 1])
                    cw[i, node, : b1 - b0] = tr.cat_threshold[b0:b1]
                    cn[i, node] = b1 - b0
    return RealTreeBatch(
        split_feature=jnp.asarray(sf),
        threshold=jnp.asarray(th),
        decision_type=jnp.asarray(dt),
        left_child=jnp.asarray(lc),
        right_child=jnp.asarray(rc),
        leaf_value=jnp.asarray(lv),
        cat_words=jnp.asarray(cw),
        cat_nwords=jnp.asarray(cn),
    )


def _walk(gather_decide, left, right, n_rows: int, n_trees: int):
    """Shared level-synchronous loop: advance [rows, trees] node indices."""
    tree_ids = jnp.arange(n_trees, dtype=jnp.int32)[None, :]

    def cond(nodes):
        return jnp.any(nodes >= 0)

    def body(nodes):
        cur = jnp.maximum(nodes, 0)
        go_left = gather_decide(cur, tree_ids)
        nxt = jnp.where(
            go_left, left[tree_ids, cur], right[tree_ids, cur]
        )
        return jnp.where(nodes >= 0, nxt, nodes)

    nodes0 = jnp.zeros((n_rows, n_trees), dtype=jnp.int32)
    return lax.while_loop(cond, body, nodes0)


def _predict_bins_leaves_impl(batch: BinTreeBatch, bins: jnp.ndarray, nan_bins: jnp.ndarray) -> jnp.ndarray:
    """Leaf index per (row, tree). bins: [N, F_used] int32; nan_bins: [F_used]."""
    n = bins.shape[0]
    t = batch.split_feature.shape[0]

    def decide(cur, tree_ids):
        feat = batch.split_feature[tree_ids, cur]  # [N, T]
        tbin = batch.split_bin[tree_ids, cur]
        dl = batch.default_left[tree_ids, cur]
        fval = jnp.take_along_axis(bins, feat, axis=1)
        nb = nan_bins[feat]
        gl = (fval <= tbin) | (dl & (nb >= 0) & (fval == nb))
        bm = batch.cat_mask.shape[-1]
        if bm > 1:
            # one joint gather to [N, T] — a two-step index would materialize
            # an [N, T, Bm] intermediate inside every walk iteration
            gl_cat = batch.cat_mask[tree_ids, cur, jnp.minimum(fval, bm - 1)]
            # out-of-range bins (unseen-category sentinel) are never in the
            # left subset (reference CategoricalDecision, tree.h:382)
            gl_cat = gl_cat & (fval < bm)
            gl = jnp.where(batch.split_is_cat[tree_ids, cur], gl_cat, gl)
        return gl

    nodes = _walk(decide, batch.left_child, batch.right_child, n, t)
    return ~nodes  # [N, T] leaf indices


predict_bins_leaves = instrumented_jit(_predict_bins_leaves_impl, label="predict/bins_leaves")


def _predict_bins_raw_impl(batch: BinTreeBatch, bins: jnp.ndarray, nan_bins: jnp.ndarray) -> jnp.ndarray:
    """Sum of per-tree outputs [N, T] (caller groups by class and sums)."""
    leaves = _predict_bins_leaves_impl(batch, bins, nan_bins)
    t = batch.split_feature.shape[0]
    tree_ids = jnp.arange(t, dtype=jnp.int32)[None, :]
    return batch.leaf_value[tree_ids, leaves]  # [N, T]


predict_bins_raw = instrumented_jit(_predict_bins_raw_impl, label="predict/bins_raw")


def _predict_real_leaves_impl(batch: RealTreeBatch, X: jnp.ndarray) -> jnp.ndarray:
    """Leaf index per (row, tree) with NumericalDecision semantics (f32)."""
    n = X.shape[0]
    t = batch.split_feature.shape[0]

    def decide(cur, tree_ids):
        feat = batch.split_feature[tree_ids, cur]
        thr = batch.threshold[tree_ids, cur]
        dt = batch.decision_type[tree_ids, cur]
        fval = jnp.take_along_axis(X, feat, axis=1)
        missing = (dt >> 2) & 3
        is_nan = jnp.isnan(fval)
        fv = jnp.where(is_nan & (missing != MISSING_NAN), 0.0, fval)
        is_missing = ((missing == MISSING_ZERO) & (jnp.abs(fv) <= K_ZERO_THRESHOLD)) | (
            (missing == MISSING_NAN) & jnp.isnan(fv)
        )
        dl = (dt & K_DEFAULT_LEFT_MASK) != 0
        gl = jnp.where(is_missing, dl, fv <= thr)
        # categorical: bit test in the node's value bitset; NaN/negative/
        # out-of-range values go right (CategoricalDecision, tree.h:346)
        wmax = batch.cat_words.shape[-1]
        is_cat = (dt & 1) != 0
        iv = jnp.where(is_nan | (fval < 0), -1, fval).astype(jnp.int32)
        word_idx = jnp.clip(iv // 32, 0, wmax - 1)
        words = batch.cat_words[tree_ids, cur]  # [N, T, W]
        word = jnp.take_along_axis(words, word_idx[..., None], axis=2)[..., 0]
        in_range = (iv >= 0) & ((iv // 32) < batch.cat_nwords[tree_ids, cur])
        bit = (word >> (iv % 32).astype(jnp.uint32)) & 1
        return jnp.where(is_cat, in_range & (bit == 1), gl)

    nodes = _walk(decide, batch.left_child, batch.right_child, n, t)
    return ~nodes


predict_real_leaves = instrumented_jit(_predict_real_leaves_impl, label="predict/real_leaves")


def _predict_real_raw_impl(batch: RealTreeBatch, X: jnp.ndarray) -> jnp.ndarray:
    leaves = _predict_real_leaves_impl(batch, X)
    t = batch.split_feature.shape[0]
    tree_ids = jnp.arange(t, dtype=jnp.int32)[None, :]
    return batch.leaf_value[tree_ids, leaves]


predict_real_raw = instrumented_jit(_predict_real_raw_impl, label="predict/real_raw")


def _stacked_bins_value_impl(batch: BinTreeBatch, nan_bins: jnp.ndarray, bins: jnp.ndarray):
    """Engine-facing order: tables first, data chunk LAST (the streaming
    executables all take the chunk as their final argument)."""
    return _predict_bins_raw_impl(batch, bins, nan_bins)


def _stacked_bins_leaves_impl(batch: BinTreeBatch, nan_bins: jnp.ndarray, bins: jnp.ndarray):
    return _predict_bins_leaves_impl(batch, bins, nan_bins)


def _add_tree_to_score_impl(
    score_k: jnp.ndarray,  # [N] f32 (donated in the jitted wrappers)
    bins: jnp.ndarray,  # [N, F_used]
    nan_bins: jnp.ndarray,  # [F_used]
    split_feature: jnp.ndarray,  # [L-1]
    split_bin: jnp.ndarray,
    default_left: jnp.ndarray,
    left_child: jnp.ndarray,
    right_child: jnp.ndarray,
    leaf_value: jnp.ndarray,  # [L] ALREADY shrunk
    split_is_cat: Optional[jnp.ndarray] = None,  # [L-1] bool
    cat_mask: Optional[jnp.ndarray] = None,  # [L-1, Bm] bool
) -> jnp.ndarray:
    """Walk one bin-space tree over a dataset and add leaf outputs to score —
    the valid-set ScoreUpdater::AddScore (src/boosting/score_updater.hpp:54)."""
    n = bins.shape[0]
    use_cat = cat_mask is not None and cat_mask.shape[-1] > 1

    def cond(nodes):
        return jnp.any(nodes >= 0)

    def body(nodes):
        cur = jnp.maximum(nodes, 0)
        feat = split_feature[cur]
        tbin = split_bin[cur]
        dl = default_left[cur]
        fval = jnp.take_along_axis(bins, feat[:, None], axis=1)[:, 0]
        nb = nan_bins[feat]
        go_left = (fval <= tbin) | (dl & (nb >= 0) & (fval == nb))
        if use_cat:
            bm = cat_mask.shape[-1]
            gl_cat = cat_mask[cur, jnp.minimum(fval, bm - 1)] & (fval < bm)
            go_left = jnp.where(split_is_cat[cur], gl_cat, go_left)
        nxt = jnp.where(go_left, left_child[cur], right_child[cur])
        return jnp.where(nodes >= 0, nxt, nodes)

    nodes = lax.while_loop(cond, body, jnp.zeros((n,), jnp.int32))
    return score_k + leaf_value[~nodes]


# standalone entry (valid-score updates call it once per tree with a dead
# score row: the old buffer is donated back to the allocator)
add_tree_to_score = instrumented_jit(
    _add_tree_to_score_impl, label="add_tree_to_score", donate_argnums=(0,)
)


# ---------------------------------------------------------------------------
# Streaming batch-prediction engine (the fork's PredictRawBatch pipeline,
# original.md / SURVEY §2.9): fixed-size chunks padded to a power-of-two
# bucket ladder so every chunk hits a cached compiled executable, with
# double-buffered host prep (binning chunk k+1 while chunk k walks the
# forest) and optional row-sharding over a local device mesh.
# ---------------------------------------------------------------------------

LADDER_MIN = 256  # smallest bucket: tiny requests pad here, not per-size


def bucket_rows(rows: int, chunk: int) -> int:
    """Smallest ladder bucket >= rows: powers of two from LADDER_MIN up,
    capped at the full chunk size (chunk itself need not be a power of two).
    Full chunks always map to `chunk`, so a stream of any length touches at
    most ceil(log2(chunk / LADDER_MIN)) + 1 executables per model."""
    if rows >= chunk:
        return chunk
    b = LADDER_MIN
    while b < rows:
        b <<= 1
    return min(b, chunk)


def ladder_buckets(chunk: int) -> List[int]:
    """Every bucket `bucket_rows` can produce for this chunk size."""
    out = []
    b = LADDER_MIN
    while b < chunk:
        out.append(b)
        b <<= 1
    out.append(chunk)
    return out


class PackedBinForest(NamedTuple):
    """Bin-space forest with all per-node scalars bit-packed into ONE i32
    table (the forest-walk kernel's pk1/pk2 layout, XLA-shaped): a walk
    level costs one node gather + one bin gather + one child gather instead
    of the five separate table gathers of the BinTreeBatch walker."""

    pk1: jnp.ndarray  # [T, M] i32: thr(9) | feat(9)<<9 | dl<<18 | (nanb+1)(10)<<19
    pk2: jnp.ndarray  # [T, M] i32: (left+base)(16) | (right+base)<<16 (neg = ~leaf)
    leaf: jnp.ndarray  # [T, L] f32 leaf values


_PACK_THR = 512  # split/NaN bins must fit 9/10-bit fields
_PACK_F = 512  # feature index field is 9 bits
_PACK_BASE = 32768  # children are offset by base in 16-bit halves


def packed_reject_reason(records, nan_bins: np.ndarray, num_features: int):
    """None when the packed walker covers this model exactly, else why not
    (categorical splits, wide bins, or wide trees keep the general walker)."""
    if num_features > _PACK_F:
        return f"{num_features} bin columns > {_PACK_F}"
    if len(nan_bins) and int(np.max(nan_bins)) >= _PACK_THR:
        return f"a NaN bin >= {_PACK_THR}"
    base = 1
    for r in records:
        sf = r.get("split_feature")
        if sf is None:
            return "a tree has no bin-space record"
        sic = r.get("split_is_cat")
        if sic is not None and np.any(np.asarray(sic)):
            return "categorical splits"
        if len(sf) and int(np.max(np.asarray(r["split_bin"]))) >= _PACK_THR:
            return f"a split threshold bin >= {_PACK_THR}"
        base = max(base, len(sf) + 1, len(r["leaf_value"]))
    if base >= _PACK_BASE:
        return f"{base} leaves >= {_PACK_BASE}"
    return None


def build_packed_bin_tables(records, nan_bins: np.ndarray) -> Tuple[PackedBinForest, int]:
    """Stack bin-space records into packed tables; caller checked
    `packed_reject_reason`.  Returns (tables, base) — base is the child
    offset (max of node/leaf counts) the walker subtracts back out."""
    t = len(records)
    m = max(1, max(len(r["split_feature"]) for r in records))
    L = max(1, max(len(r["leaf_value"]) for r in records))
    base = max(m, L)
    pk1 = np.zeros((t, m), np.int32)
    pk2 = np.zeros((t, m), np.int32)
    leaf = np.zeros((t, L), np.float32)
    nan_bins = np.asarray(nan_bins, np.int64)
    for i, r in enumerate(records):
        sf = np.asarray(r["split_feature"], np.int64)
        nn = len(sf)
        lv = np.asarray(r["leaf_value"], np.float32)
        leaf[i, : len(lv)] = lv
        if nn == 0:
            # single-leaf tree: node 0 routes every row to leaf 0
            pk2[i, 0] = (~0 + base) | ((~0 + base) << 16)
            continue
        thr = np.asarray(r["split_bin"], np.int64)
        dl = np.asarray(r["default_left"], np.int64)
        lc = np.asarray(r["left_child"], np.int64)
        rc = np.asarray(r["right_child"], np.int64)
        nb = nan_bins[sf] + 1  # 0 = no NaN bin
        pk1[i, :nn] = (thr | (sf << 9) | (dl << 18) | (nb << 19)).astype(np.int32)
        pk2[i, :nn] = ((lc + base) | ((rc + base) << 16)).astype(np.int32)
    return (
        PackedBinForest(
            pk1=jnp.asarray(pk1), pk2=jnp.asarray(pk2), leaf=jnp.asarray(leaf)
        ),
        base,
    )


def _packed_walk_nodes(forest: PackedBinForest, bins: jnp.ndarray, base: int):
    """Level-synchronous walk over packed tables -> final [N, T] node state
    (negative = ~leaf).  Decision rule identical to the BinTreeBatch walker:
    go left iff fval <= thr, or the feature's NaN bin matches under
    default_left."""
    n = bins.shape[0]
    t = forest.pk1.shape[0]
    tree_ids = jnp.arange(t, dtype=jnp.int32)[None, :]

    def cond(nodes):
        return jnp.any(nodes >= 0)

    def body(nodes):
        cur = jnp.maximum(nodes, 0)
        p1 = forest.pk1[tree_ids, cur]
        thr = p1 & 0x1FF
        feat = (p1 >> 9) & 0x1FF
        dl = (p1 >> 18) & 1
        nb = ((p1 >> 19) & 0x3FF) - 1
        fval = jnp.take_along_axis(bins, feat, axis=1)
        gl = (fval <= thr) | ((dl != 0) & (nb >= 0) & (fval == nb))
        p2 = forest.pk2[tree_ids, cur]
        child = jnp.where(gl, p2 & 0xFFFF, (p2 >> 16) & 0xFFFF) - base
        return jnp.where(nodes >= 0, child, nodes)

    return lax.while_loop(cond, body, jnp.zeros((n, t), jnp.int32))


def _packed_bins_pertree_impl(forest: PackedBinForest, bins: jnp.ndarray, *, base: int):
    """Per-tree leaf outputs [N, T] f32 via the packed walker."""
    nodes = _packed_walk_nodes(forest, bins, base)
    t = forest.pk1.shape[0]
    tree_ids = jnp.arange(t, dtype=jnp.int32)[None, :]
    return forest.leaf[tree_ids, ~nodes]


def _packed_bins_leaves_impl(forest: PackedBinForest, bins: jnp.ndarray, *, base: int):
    """Leaf index per (row, tree) [N, T] i32 via the packed walker."""
    return ~_packed_walk_nodes(forest, bins, base)


# executables are shared ACROSS boosters (like jit's global cache): the key
# is shapes + statics only, tables arrive as call arguments.  A scoped
# engine (serving registry) prepends its scope string so two co-resident
# models never collide on a key even at identical table shapes, and so a
# retired model's executables can be evicted without touching its
# neighbours' (`evict_exec_scope`).
_EXEC_CACHE: Dict[Any, Any] = {}
_COMPILE_COUNT = 0


def streaming_compile_count() -> int:
    """Total bucket executables compiled this process (test hook: asserting
    this stays flat across varying batch sizes proves zero recompiles)."""
    return _COMPILE_COUNT


def evict_exec_scope(scope: str) -> int:
    """Drop every cached executable compiled under `scope` (serving registry
    retirement after drain).  Returns how many entries were evicted.  The
    unscoped (scope=None) shared cache is never touched."""
    if not scope:
        return 0
    dead = [k for k in _EXEC_CACHE if k[0] == scope]
    for k in dead:
        del _EXEC_CACHE[k]
    return len(dead)


# streaming-engine executable bodies by (variant, kind) — the lint IR
# matrix traces the tensor entries straight out of this table so the
# audited callable IS the one the engine AOT-compiles
_STREAM_IMPLS = {
    ("packed", "value"): _packed_bins_pertree_impl,
    ("packed", "leaf"): _packed_bins_leaves_impl,
    ("stacked", "value"): _stacked_bins_value_impl,
    ("stacked", "leaf"): _stacked_bins_leaves_impl,
    ("real", "value"): _predict_real_raw_impl,
    ("real", "leaf"): _predict_real_leaves_impl,
    ("tensor", "value"): _tensor_bins_pertree_impl,
    ("tensor", "leaf"): _tensor_bins_leaves_impl,
}


def _shape_key(tree):
    return tuple(
        (a.shape, str(a.dtype)) for a in jax.tree_util.tree_leaves(tree)
    )


def _clamp_pow2(x: int) -> int:
    p = 1
    while p * 2 <= x:
        p *= 2
    return p


class StreamingPredictor:
    """Chunked, bucket-padded, double-buffered prediction engine.

    The scheduler splits the input into `pred_chunk_rows`-sized chunks, pads
    each to a `bucket_rows` ladder bucket, and feeds an AOT-compiled
    executable per (model shape x bucket x output kind) — so varying batch
    sizes never recompile.  While chunk k walks the forest on device, chunk
    k+1 is binned on host (native `_binning.so` fast path via the
    BinMapper) — jax's async dispatch overlaps the two; `pred_num_buffers`
    bounds how many device outputs may be in flight.  With
    `pred_shard_devices` > 1 each chunk's rows are sharded over a local
    device mesh (pjit data axis), tables replicated.
    """

    def __init__(self, booster, scope: Optional[str] = None):
        self._b = booster
        # scope=None (default) keeps the process-global shared cache and the
        # frozen `predict/stream/{variant}` labels; a registry-owned engine
        # passes its model identity so cache keys and retrace labels become
        # per-model (`predict/stream/{scope}/{variant}`)
        self._scope = scope
        self.last_stats: Dict[str, Any] = {}

    # ------------------------------------------------------------- tables
    def _tables(self, space: str, t0: int, t1: int, engine: str = "walk"):
        """(variant, table_pytree, static_kwargs) for this tree range,
        cached in the booster's _stack_cache (same invalidation discipline
        as the other stacks: any models_ mutation bumps _model_version).
        ``engine`` is the RESOLVED engine ("walk"/"matmul"): the caller
        already ran `resolve_engine`, so "matmul" implies eligibility."""
        b = self._b
        if space == "real":
            return "real", (b._stacked_real(t0, t1),), {}
        recs = b._bin_records[t0:t1]
        nanb = np.asarray(b._nan_bins)
        width = b._bin_matrix_width()
        if engine == "matmul":
            key = ("tf", t0, t1, b._model_version)
            if key not in b._stack_cache:
                b._stack_cache = {
                    kk: v
                    for kk, v in b._stack_cache.items()
                    if kk[0] != "tf"
                }
                b._stack_cache[key] = build_tensor_forest(recs, nanb, width)
            return "tensor", (b._stack_cache[key],), {}
        if packed_reject_reason(recs, nanb, width) is None:
            key = ("pkbin", t0, t1, b._model_version)
            if key not in b._stack_cache:
                b._stack_cache = {
                    kk: v
                    for kk, v in b._stack_cache.items()
                    if kk[0] != "pkbin"
                }
                b._stack_cache[key] = build_packed_bin_tables(recs, nanb)
            forest, base = b._stack_cache[key]
            return "packed", (forest,), {"base": base}
        return "stacked", (b._stacked_bins(t0, t1), b._nan_bins), {}

    # ------------------------------------------------------------- engine
    def resolve_engine(self, engine: str, space: str, t0: int, t1: int):
        """Resolve a ``pred_engine`` request to the engine that will run.

        Returns ``(resolved, reject_reason)`` with resolved in
        {"walk", "matmul"}.  "matmul"/"auto" requests check tensor-forest
        eligibility (cached per model version); "auto" additionally runs
        the host-side byte-parity probe vs the walker.  A fallback emits
        ONE telemetry event + the `pred/engine_selected` gauge per model
        version so the silent walker downgrade is visible in obs_top and
        /metrics."""
        if engine in (None, "", "walk"):
            return "walk", None
        b = self._b
        key = ("tfrej", t0, t1, b._model_version, engine)
        if key not in b._stack_cache:
            b._stack_cache = {
                kk: v for kk, v in b._stack_cache.items() if kk[0] != "tfrej"
            }
            b._stack_cache[key] = self._tensor_reject(engine, space, t0, t1)
        reason = b._stack_cache[key]
        ses = get_session()
        if reason is None:
            if ses.enabled:
                ses.set_gauge("pred/engine_selected", 1.0)
            return "matmul", None
        warn_key = ("tfwarn", t0, t1, b._model_version, engine)
        if warn_key not in b._stack_cache:
            b._stack_cache[warn_key] = True
            if ses.enabled:
                ses.set_gauge("pred/engine_selected", 0.0)
                ses.inc("pred/engine_fallback_total")
                ses.record(
                    {
                        "event": "pred_engine_fallback",
                        "requested": engine,
                        "reason": reason,
                        "trees": t1 - t0,
                    }
                )
        return "walk", reason

    def _tensor_reject(self, engine, space, t0, t1):
        """Eligibility (+ auto's parity probe) — None or the reject reason."""
        b = self._b
        if space != "bin":
            return "real-space model (no bin mappers)"
        recs = b._bin_records[t0:t1]
        nanb = np.asarray(b._nan_bins)
        width = b._bin_matrix_width()
        max_bin = getattr(b, "_max_bin_padded", None)
        reason = tensor_reject_reason(recs, nanb, width, max_bin=max_bin)
        if reason is not None or engine != "auto":
            return reason
        # auto: compile-time byte-parity probe against a reference walk
        # (host numpy on both sides — no device executables, so warmed
        # ladders stay flat)
        _, (forest,), _ = self._tables(space, t0, t1, engine="matmul")
        return parity_probe_reason(
            recs, nanb, forest, width, max_bin or _PACK_THR
        )

    # -------------------------------------------------------- executables
    def _get_exec(self, variant, kind, tables, statics, bucket, width, dtype, ndev):
        global _COMPILE_COUNT
        key = (
            self._scope,
            variant,
            kind,
            bucket,
            width,
            dtype,
            ndev,
            tuple(sorted(statics.items())),
            _shape_key(tables),
        )
        label = (
            f"predict/stream/{self._scope}/{variant}"
            if self._scope
            else f"predict/stream/{variant}"
        )
        hit = _EXEC_CACHE.get(key)
        if hit is not None:
            # device_accounting may have turned on after the miss that
            # compiled this bucket; note_executable dedups per object
            note_executable(label, hit)
            return hit
        impl = _STREAM_IMPLS[(variant, kind)]
        if statics:
            # bind statics up front: pjit rejects kwargs when in_shardings
            # is set, and the cache key already carries their values
            impl = functools.partial(impl, **statics)
        jit_kwargs: Dict[str, Any] = {}
        if ndev > 1:
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

            mesh = Mesh(np.array(jax.local_devices()[:ndev]), ("data",))
            repl = NamedSharding(mesh, P())
            rows = NamedSharding(mesh, P("data"))
            in_sh = tuple(
                jax.tree_util.tree_map(lambda _: repl, t) for t in tables
            ) + (rows,)
            jit_kwargs["in_shardings"] = in_sh
            jit_kwargs["out_shardings"] = NamedSharding(mesh, P("data", None))
        elif jax.default_backend() == "tpu":
            # donate the chunk buffer: the walk never reuses it, and
            # donation lets XLA recycle the H2D staging allocation
            jit_kwargs["donate_argnums"] = (len(tables),)
        # labeled per table variant so suspect re-walk ("real") compiles are
        # separable in compile_counts_by_label(); the lower().compile() below
        # traces exactly once, which instrumented_jit counts at trace time
        fn = instrumented_jit(impl, label=label, **jit_kwargs)
        avals = tuple(
            jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), t
            )
            for t in tables
        ) + (jax.ShapeDtypeStruct((bucket, width), dtype),)
        compiled = fn.lower(*avals).compile()
        _EXEC_CACHE[key] = compiled
        _COMPILE_COUNT += 1
        note_executable(label, compiled)
        return compiled

    def warmup(
        self,
        t0: int,
        t1: int,
        *,
        space: str,
        chunk: int,
        shard_devices: int = 1,
        width: Optional[int] = None,
        kinds=("value",),
        engine: str = "walk",
    ) -> int:
        """AOT-lower and cache every ladder bucket executable for this model
        so the first request pays no compile.  Returns how many executables
        this call actually compiled (0 = everything was already cached).

        ``engine`` is the pred_engine request: it is resolved first, so an
        ineligible forest never AOT-compiles the matmul ladder (warm time
        and HBM would double for executables the model can't use).  When
        matmul DOES resolve, the walker ladder is warmed alongside it —
        the runtime fallback path stays compile-free through serving."""
        resolved, _ = self.resolve_engine(engine, space, t0, t1)
        if width is None:
            width = (
                self._b.max_feature_idx + 1
                if space == "real"
                else self._b._bin_matrix_width()
            )
        dtype = np.float32 if space == "real" else np.int32
        ndev = self._shard_count(shard_devices)
        before = _COMPILE_COUNT
        engines = ("matmul", "walk") if resolved == "matmul" else ("walk",)
        for eng in engines:
            variant, tables, statics = self._tables(space, t0, t1, engine=eng)
            for bucket in ladder_buckets(chunk):
                for kind in kinds:
                    self._get_exec(
                        variant, kind, tables, statics, bucket, width,
                        dtype, ndev,
                    )
        return _COMPILE_COUNT - before

    @staticmethod
    def _shard_count(shard_devices: int) -> int:
        """Usable mesh size: clamped to a power of two (buckets are powers
        of two, so the row axis always divides) and the local device count;
        -1 means all local devices."""
        avail = jax.local_device_count()
        if shard_devices in (0, 1):
            return 1
        if shard_devices < 0:
            shard_devices = avail
        return _clamp_pow2(min(shard_devices, avail))

    # ---------------------------------------------------------- scheduler
    def run(
        self,
        X,
        t0: int,
        t1: int,
        *,
        space: str,
        kind: str = "value",
        chunk: int,
        num_buffers: int = 2,
        shard_devices: int = 1,
        reduce_fn: Optional[Callable[[np.ndarray, int], np.ndarray]] = None,
        engine: str = "walk",
    ) -> np.ndarray:
        """Stream X through the engine.  kind="value" yields per-tree leaf
        outputs as float64 [rows, T] blocks (bit-identical to the legacy
        single-shot walk + float64 cast), kind="leaf" int32 leaf indices;
        `reduce_fn(block, rows)` maps each chunk's block before
        concatenation (e.g. the per-class sum), running on host while the
        next chunk computes on device."""
        b = self._b
        ses = get_session()
        n = int(X.shape[0])
        t_count = t1 - t0
        chunk = max(LADDER_MIN, int(chunk))
        num_buffers = max(1, int(num_buffers))
        ndev = self._shard_count(shard_devices)
        resolved, _ = self.resolve_engine(engine, space, t0, t1)
        stats = {
            "path": "stream_" + space,
            "engine": resolved,
            "rows": n,
            "chunks": 0,
            "buckets": [],
            "shard_devices": ndev,
            "bin_ms": 0.0,
            "transfer_ms": 0.0,
            "walk_ms": 0.0,
            "host_ms": 0.0,
            "compiles": 0,
        }
        variant, tables, statics = self._tables(space, t0, t1, engine=resolved)
        suspects = kind == "value" and space == "real"
        if n == 0:
            # empty-input edge: no device work, correctly shaped output
            empty = np.zeros(
                (0, t_count), np.int32 if kind == "leaf" else np.float64
            )
            out = reduce_fn(empty, 0) if reduce_fn is not None else empty
            self.last_stats = stats
            return out

        if space == "real":
            width = int(X.shape[1])
            dtype = np.float32

            def host_rows(lo: int, rows: int):
                xo = X[lo : lo + rows]
                return np.ascontiguousarray(xo, dtype=np.float32), xo

        else:
            width = b._bin_matrix_width()
            dtype = np.int32
            sparse = hasattr(X, "tocsc") and hasattr(X, "nnz")
            if sparse:
                # scipy input: bin once from CSC (column-sliced), then
                # stream the int32 matrix — row-slicing sparse per chunk
                # would re-walk indptr per feature per chunk
                t_b = time.perf_counter()
                full_bins = b._bin_input_host(X)
                stats["bin_ms"] += (time.perf_counter() - t_b) * 1e3
            else:
                full_bins = None
            # dense host binning runs in blocks of >= _HOST_BIN_BLOCK rows:
            # per-chunk mapper calls at small chunks would pay the
            # per-feature dispatch overhead ~n_chunks times
            block_rows = max(chunk, _HOST_BIN_BLOCK)
            block_cache = {"lo": -1, "mat": None}

            def host_rows(lo: int, rows: int):
                if full_bins is not None:
                    return full_bins[lo : lo + rows], None
                blo = (lo // block_rows) * block_rows
                if block_cache["lo"] != blo:
                    block_cache["lo"] = blo
                    block_cache["mat"] = b._bin_input_host(
                        X[blo : blo + block_rows]
                    )
                mat = block_cache["mat"]
                return mat[lo - blo : lo - blo + rows], None

        compiles_before = _COMPILE_COUNT
        blocks: List[np.ndarray] = []
        inflight: deque = deque()

        def drain_one():
            dev, rows, patch = inflight.popleft()
            t_w = time.perf_counter()
            with jax.profiler.TraceAnnotation("predict/walk"):
                host = np.asarray(dev)
            stats["walk_ms"] += (time.perf_counter() - t_w) * 1e3
            t_h = time.perf_counter()
            blk = host[:rows]
            if kind == "value":
                blk = blk.astype(np.float64)
            if patch is not None:
                sidx, pvals = patch
                blk[sidx] = pvals
            if reduce_fn is not None:
                blk = reduce_fn(blk, rows)
            blocks.append(blk)
            stats["host_ms"] += (time.perf_counter() - t_h) * 1e3

        for lo in range(0, n, chunk):
            rows = min(chunk, n - lo)
            bucket = bucket_rows(rows, chunk)
            t_b = time.perf_counter()
            with jax.profiler.TraceAnnotation("predict/bin"):
                mat, x_orig = host_rows(lo, rows)
            if bucket > rows:
                padded = np.zeros((bucket, width), dtype)
                padded[:rows] = mat
            else:
                padded = np.ascontiguousarray(mat, dtype=dtype)
            patch = None
            if suspects:
                # f64 suspect re-walk (rows within f32 rounding of a
                # threshold) is per-row, so per-chunk patching is
                # bit-identical to the legacy full-batch patch — and runs
                # on host while earlier chunks walk on device
                sidx = b._real_walk_suspects(
                    np.asarray(x_orig, np.float64), t0, t1
                )
                if sidx.size:
                    patch = (
                        sidx,
                        np.stack(
                            [
                                tr.predict(x_orig[sidx])
                                for tr in b.models_[t0:t1]
                            ],
                            axis=1,
                        ),
                    )
            stats["bin_ms"] += (time.perf_counter() - t_b) * 1e3
            compiled = self._get_exec(
                variant, kind, tables, statics, bucket, width, dtype, ndev
            )
            t_t = time.perf_counter()
            with jax.profiler.TraceAnnotation("predict/transfer"):
                dev = compiled(*tables, padded)
            stats["transfer_ms"] += (time.perf_counter() - t_t) * 1e3
            inflight.append((dev, rows, patch))
            stats["chunks"] += 1
            if ses.enabled:
                ses.record({
                    "event": "predict_chunk",
                    "chunk": stats["chunks"] - 1,
                    "rows": rows,
                    "bucket": bucket,
                })
            if bucket not in stats["buckets"]:
                stats["buckets"].append(bucket)
            while len(inflight) >= num_buffers:
                drain_one()
        while inflight:
            drain_one()
        t_h = time.perf_counter()
        out = blocks[0] if len(blocks) == 1 else np.concatenate(blocks, axis=0)
        stats["host_ms"] += (time.perf_counter() - t_h) * 1e3
        stats["compiles"] = _COMPILE_COUNT - compiles_before
        self.last_stats = stats
        sample_device_memory("predict")
        if ses.enabled:
            ses.inc("predict_chunks", stats["chunks"])
            ses.set_gauge(
                "pred/engine", 1.0 if resolved == "matmul" else 0.0
            )
            ses.record({
                "event": "predict",
                "path": stats["path"],
                "engine": resolved,
                "rows": n,
                "chunks": stats["chunks"],
                "shard_devices": ndev,
                "phases": {
                    "bin_ms": stats["bin_ms"],
                    "transfer_ms": stats["transfer_ms"],
                    "walk_ms": stats["walk_ms"],
                    "host_ms": stats["host_ms"],
                },
                "compiles": stats["compiles"],
            })
        return out


_HOST_BIN_BLOCK = 65536  # dense host-binning block size (rows)
