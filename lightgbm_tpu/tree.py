"""Host-side tree model: real-valued thresholds, serialization, prediction.

Reference analogs: ``Tree`` (include/LightGBM/tree.h:497 SoA arrays,
NumericalDecision :346, CategoricalDecision :382), text round-trip
``Tree::ToString`` (src/io/tree.cpp:343) / ``Tree(const char*, size_t*)``.

The device-side grower (ops/grower.py) emits bin-space TreeArrays; this module
materializes them into the reference's representation — original feature
indices, real-valued thresholds, decision_type bitfield — so the text model
format matches LightGBM's and models interoperate both ways.

Categorical splits are stored the reference way: ``threshold`` holds an index
into ``cat_boundaries_``/``cat_threshold_`` bitsets of category values that go
left (tree.h:87 SplitCategorical).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np

# decision_type bit layout (reference include/LightGBM/tree.h:21-22, :283)
K_CATEGORICAL_MASK = 1
K_DEFAULT_LEFT_MASK = 2

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2

K_ZERO_THRESHOLD = 1e-35


def _missing_type_of(decision_type: int) -> int:
    return (decision_type >> 2) & 3


def _make_decision_type(categorical: bool, default_left: bool, missing_type: int) -> int:
    dt = 0
    if categorical:
        dt |= K_CATEGORICAL_MASK
    if default_left:
        dt |= K_DEFAULT_LEFT_MASK
    dt |= (missing_type & 3) << 2
    return dt


def _fmt(x: float) -> str:
    """High-precision float formatting like the reference's ArrayToString<true>."""
    return repr(float(x)) if np.isfinite(x) else ("inf" if x > 0 else "-inf")


def _arr_str(arr, high_precision: bool = False) -> str:
    if high_precision:
        return " ".join(_fmt(v) for v in arr)
    out = []
    for v in arr:
        if isinstance(v, (bool, np.bool_)):
            out.append(str(int(v)))
        elif float(v).is_integer() and not isinstance(v, (float, np.floating)):
            out.append(str(int(v)))
        elif isinstance(v, (int, np.integer)):
            out.append(str(int(v)))
        else:
            out.append(f"{float(v):g}")
    return " ".join(out)


@dataclasses.dataclass
class Tree:
    """One decision tree in reference representation (SoA over nodes/leaves)."""

    num_leaves: int
    split_feature: np.ndarray  # [n-1] int32, ORIGINAL feature index
    split_gain: np.ndarray  # [n-1] f32
    threshold: np.ndarray  # [n-1] f64 (real value; cat: index into cat_boundaries)
    decision_type: np.ndarray  # [n-1] int8 bitfield
    left_child: np.ndarray  # [n-1] int32 (neg = ~leaf)
    right_child: np.ndarray  # [n-1] int32
    leaf_value: np.ndarray  # [n] f64
    leaf_weight: np.ndarray  # [n] f64
    leaf_count: np.ndarray  # [n] int64
    internal_value: np.ndarray  # [n-1] f64
    internal_weight: np.ndarray  # [n-1] f64
    internal_count: np.ndarray  # [n-1] int64
    shrinkage: float = 1.0
    # categorical split storage (reference tree.h cat_boundaries_/cat_threshold_)
    num_cat: int = 0
    cat_boundaries: Optional[np.ndarray] = None  # [num_cat+1] int32 (word offsets)
    cat_threshold: Optional[np.ndarray] = None  # uint32 bitset words
    # per-leaf linear models (linear_tree)
    is_linear: bool = False
    leaf_const: Optional[np.ndarray] = None  # [n] f64
    leaf_features: Optional[List[np.ndarray]] = None  # per-leaf orig feature idx
    leaf_coeff: Optional[List[np.ndarray]] = None  # per-leaf f64 coefficients

    # ------------------------------------------------------------------ build
    @classmethod
    def from_device_arrays(
        cls,
        ta,  # ops.grower.TreeArrays pulled to host (numpy-compatible)
        bin_mappers,  # List[BinMapper] for ALL original features
        used_features: Sequence[int],  # used-col -> original feature index
        bundle_layout=None,  # bundling.BundleLayout: columns are EFB planes
    ) -> "Tree":
        """Materialize bin-space device TreeArrays into a real-valued Tree.

        With ``bundle_layout`` the device column axis is EFB planes: a
        bundle-plane split (recorded as a plane-bin membership mask on
        device) decodes back to a NUMERIC threshold on the owning original
        feature, so serialized models and prediction are expressed in
        original-feature space exactly like unbundled training."""
        n = int(ta.num_leaves)
        nn = max(n - 1, 0)
        split_feature_used = np.asarray(ta.split_feature)[:nn]
        split_bin = np.asarray(ta.split_bin)[:nn]
        default_left = np.asarray(ta.default_left)[:nn]
        split_is_cat = np.asarray(ta.split_is_cat)[:nn]
        node_cat_mask = np.asarray(ta.cat_mask)[:nn]

        split_feature = np.zeros(nn, dtype=np.int32)
        threshold = np.zeros(nn, dtype=np.float64)
        decision_type = np.zeros(nn, dtype=np.int8)
        cat_boundaries = [0]
        cat_threshold: List[int] = []
        num_cat = 0
        for t in range(nn):
            plane = int(split_feature_used[t])
            if bundle_layout is not None:
                feats_p = bundle_layout.planes[plane]
                if len(feats_p) > 1:
                    # EFB bundle plane: candidate bin tb means "member-local
                    # bin <= tb - start goes left" (ops/split.py bundle_end)
                    orig, tl = bundle_layout.decode(plane, int(split_bin[t]))
                    split_feature[t] = orig
                    mapper = bin_mappers[orig]
                    threshold[t] = mapper.bin_to_threshold(tl)
                    # eligibility guarantees missing_type NONE and the
                    # value-0 bin below every threshold: NaN (treated as 0
                    # at predict) and zeros go left, matching the training
                    # partition's shared default bin
                    decision_type[t] = _make_decision_type(
                        False, False, mapper.missing_type
                    )
                    continue
                orig = feats_p[0]
            else:
                orig = used_features[plane]
            split_feature[t] = orig
            mapper = bin_mappers[orig]
            if mapper.is_categorical:
                # left = category values of the bins the split search chose
                # (SplitCandidate.cat_mask -> reference cat_threshold_ bitset;
                # the NaN bin is never in the mask, matching prediction's
                # NaN-goes-right rule, tree.h:346)
                if split_is_cat[t]:
                    bins_left = np.nonzero(node_cat_mask[t])[0]
                else:  # freq-rank prefix fallback (legacy records)
                    bins_left = np.arange(int(split_bin[t]) + 1)
                bins_left = bins_left[bins_left < len(mapper.bin_to_cat)]
                cats = mapper.bin_to_cat[bins_left]
                max_cat = int(cats.max()) if len(cats) else 0
                words = [0] * (max_cat // 32 + 1)
                for c in cats:
                    words[int(c) // 32] |= 1 << (int(c) % 32)
                threshold[t] = num_cat
                cat_threshold.extend(words)
                cat_boundaries.append(len(cat_threshold))
                num_cat += 1
                decision_type[t] = _make_decision_type(True, False, mapper.missing_type)
            else:
                threshold[t] = mapper.bin_to_threshold(int(split_bin[t]))
                decision_type[t] = _make_decision_type(
                    False, bool(default_left[t]), mapper.missing_type
                )

        return cls(
            num_leaves=n,
            split_feature=split_feature,
            split_gain=np.asarray(ta.split_gain, dtype=np.float64)[:nn],
            threshold=threshold,
            decision_type=decision_type,
            left_child=np.asarray(ta.left_child, dtype=np.int32)[:nn],
            right_child=np.asarray(ta.right_child, dtype=np.int32)[:nn],
            leaf_value=np.asarray(ta.leaf_value, dtype=np.float64)[:n],
            leaf_weight=np.asarray(ta.leaf_weight, dtype=np.float64)[:n],
            leaf_count=np.asarray(ta.leaf_count, dtype=np.int64)[:n],
            internal_value=np.asarray(ta.internal_value, dtype=np.float64)[:nn],
            internal_weight=np.asarray(ta.internal_weight, dtype=np.float64)[:nn],
            internal_count=np.asarray(ta.internal_count, dtype=np.int64)[:nn],
            shrinkage=1.0,
            num_cat=num_cat,
            cat_boundaries=np.asarray(cat_boundaries, dtype=np.int64) if num_cat else None,
            cat_threshold=np.asarray(cat_threshold, dtype=np.uint32) if num_cat else None,
        )

    # ------------------------------------------------------------- validate
    def validate(self) -> None:
        """Structural invariants (reference CHECK paths, e.g.
        Tree::Split CHECKs under DEBUG, src/io/tree.cpp / the learner's
        CheckSplit). Raises AssertionError on corruption; run by the Booster
        at verbosity >= 2."""
        n = self.num_leaves
        nn = n - 1
        if n <= 1:
            return
        assert len(self.left_child) >= nn and len(self.right_child) >= nn
        seen_leaves = set()
        seen_nodes = set()
        stack = [0]
        while stack:
            node = stack.pop()
            assert 0 <= node < nn, f"node {node} out of range [0, {nn})"
            assert node not in seen_nodes, f"node {node} visited twice (cycle)"
            seen_nodes.add(node)
            for child in (int(self.left_child[node]), int(self.right_child[node])):
                if child < 0:
                    leaf = ~child
                    assert 0 <= leaf < n, f"leaf {leaf} out of range [0, {n})"
                    assert leaf not in seen_leaves, f"leaf {leaf} reached twice"
                    seen_leaves.add(leaf)
                else:
                    stack.append(child)
        assert len(seen_leaves) == n, (
            f"tree reaches {len(seen_leaves)} leaves, expected {n}"
        )
        assert len(seen_nodes) == nn, (
            f"tree reaches {len(seen_nodes)} internal nodes, expected {nn}"
        )
        assert np.isfinite(self.leaf_value[:n]).all(), "non-finite leaf value"
        assert np.isfinite(self.threshold[:nn]).all(), "non-finite threshold"
        assert (np.asarray(self.split_feature[:nn]) >= 0).all()

    # ---------------------------------------------------------------- mutate
    def apply_shrinkage(self, rate: float) -> None:
        """Tree::Shrinkage (include/LightGBM/tree.h:197).

        The rate is rounded to float32 before the multiply: the device score
        update computes ``leaf_value(f32) * rate(f32)`` in f32, and leaf
        values coming off the accelerator are f32-representable, so the f64
        product here is exact and casting it back to f32 reproduces the
        device addend bit-for-bit. That makes host-side score replay
        (init_model continuation, checkpoint-free resume) byte-identical to
        an uninterrupted run; with an unrounded f64 rate the two roundings
        disagree by 1 ulp on a few percent of leaves.
        """
        r = float(np.float32(rate))
        self.leaf_value = self.leaf_value * r
        self.internal_value = self.internal_value * r
        if self.is_linear and self.leaf_const is not None:
            self.leaf_const = self.leaf_const * r
            self.leaf_coeff = [c * r for c in self.leaf_coeff]
        self.shrinkage *= rate

    def set_leaf_values(self, values: np.ndarray) -> None:
        self.leaf_value = np.asarray(values, dtype=np.float64)[: self.num_leaves]

    def add_bias(self, val: float) -> None:
        """Tree::AddBias — used by boost_from_average fold-in."""
        self.leaf_value = self.leaf_value + val
        self.internal_value = self.internal_value + val

    @classmethod
    def constant_tree(cls, val: float = 0.0) -> "Tree":
        """Tree::AsConstantTree — single-leaf tree."""
        z = np.zeros(0)
        zi = np.zeros(0, dtype=np.int32)
        return cls(
            num_leaves=1,
            split_feature=zi,
            split_gain=z,
            threshold=z,
            decision_type=np.zeros(0, dtype=np.int8),
            left_child=zi,
            right_child=zi,
            leaf_value=np.array([val]),
            leaf_weight=np.zeros(1),
            leaf_count=np.zeros(1, dtype=np.int64),
            internal_value=z,
            internal_weight=z,
            internal_count=np.zeros(0, dtype=np.int64),
        )

    # --------------------------------------------------------------- predict
    def _decide(self, fval: float, node: int) -> int:
        dt = int(self.decision_type[node])
        if dt & K_CATEGORICAL_MASK:
            if np.isnan(fval) or fval < 0:
                return int(self.right_child[node])
            int_fval = int(fval)
            cat_idx = int(self.threshold[node])
            b0, b1 = self.cat_boundaries[cat_idx], self.cat_boundaries[cat_idx + 1]
            w = int_fval // 32
            if b0 + w < b1 and (int(self.cat_threshold[b0 + w]) >> (int_fval % 32)) & 1:
                return int(self.left_child[node])
            return int(self.right_child[node])
        missing = _missing_type_of(dt)
        if np.isnan(fval) and missing != MISSING_NAN:
            fval = 0.0
        if (missing == MISSING_ZERO and abs(fval) <= K_ZERO_THRESHOLD) or (
            missing == MISSING_NAN and np.isnan(fval)
        ):
            return int(self.left_child[node]) if dt & K_DEFAULT_LEFT_MASK else int(self.right_child[node])
        return int(self.left_child[node]) if fval <= self.threshold[node] else int(self.right_child[node])

    def predict_leaf(self, row: np.ndarray) -> int:
        """Per-row leaf index (reference Tree::PredictLeafIndex)."""
        if self.num_leaves <= 1:
            return 0
        node = 0
        while node >= 0:
            node = self._decide(float(row[self.split_feature[node]]), node)
        return ~node

    def predict_row(self, row: np.ndarray) -> float:
        leaf = self.predict_leaf(row)
        out = float(self.leaf_value[leaf])
        if self.is_linear and self.leaf_coeff is not None:
            feats = self.leaf_features[leaf]
            if len(feats):
                vals = row[feats]
                if np.isnan(vals).any():
                    return out
                out = float(self.leaf_const[leaf] + (self.leaf_coeff[leaf] * vals).sum())
        return out

    def predict(self, X: np.ndarray) -> np.ndarray:
        """Vectorized level-synchronous batch walk (the fork's
        tree_avx512.hpp:41 idea, full-width instead of 8 rows)."""
        n = X.shape[0]
        if self.num_leaves <= 1:
            return np.full(n, float(self.leaf_value[0]))
        nodes = np.zeros(n, dtype=np.int64)
        while True:
            active = nodes >= 0
            if not active.any():
                break
            idx = np.nonzero(active)[0]
            cur = nodes[idx]
            feat = self.split_feature[cur]
            fval = X[idx, feat].astype(np.float64)
            dt = self.decision_type[cur].astype(np.int64)
            is_cat = (dt & K_CATEGORICAL_MASK) != 0
            left = self.left_child[cur]
            right = self.right_child[cur]
            go_left = np.zeros(len(idx), dtype=bool)

            num = ~is_cat
            if num.any():
                missing = (dt >> 2) & 3
                v = fval.copy()
                nanv = np.isnan(v)
                v[nanv & (missing != MISSING_NAN)] = 0.0
                is_missing = ((missing == MISSING_ZERO) & (np.abs(v) <= K_ZERO_THRESHOLD)) | (
                    (missing == MISSING_NAN) & np.isnan(v)
                )
                dl = (dt & K_DEFAULT_LEFT_MASK) != 0
                gl = np.where(is_missing, dl, v <= self.threshold[cur])
                go_left[num] = gl[num]
            if is_cat.any():
                ci = np.nonzero(is_cat)[0]
                for k in ci:
                    fv = fval[k]
                    if np.isnan(fv) or fv < 0:
                        go_left[k] = False
                        continue
                    int_fval = int(fv)
                    cat_idx = int(self.threshold[cur[k]])
                    b0 = self.cat_boundaries[cat_idx]
                    b1 = self.cat_boundaries[cat_idx + 1]
                    w = int_fval // 32
                    go_left[k] = bool(
                        b0 + w < b1
                        and (int(self.cat_threshold[b0 + w]) >> (int_fval % 32)) & 1
                    )
            nodes[idx] = np.where(go_left, left, right)
        leaves = ~nodes
        out = self.leaf_value[leaves]
        if self.is_linear and self.leaf_coeff is not None:
            for i in range(n):
                leaf = leaves[i]
                feats = self.leaf_features[leaf]
                if len(feats):
                    vals = X[i, feats]
                    if not np.isnan(vals).any():
                        out[i] = self.leaf_const[leaf] + (self.leaf_coeff[leaf] * vals).sum()
        return out

    # ----------------------------------------------------------- serialization
    def to_string(self, tree_index: int) -> str:
        """LightGBM text format (reference Tree::ToString, src/io/tree.cpp:343)."""
        n = self.num_leaves
        lines = [f"Tree={tree_index}"]
        lines.append(f"num_leaves={n}")
        lines.append(f"num_cat={self.num_cat}")
        lines.append("split_feature=" + _arr_str(self.split_feature))
        lines.append("split_gain=" + _arr_str(self.split_gain))
        lines.append("threshold=" + _arr_str(self.threshold, high_precision=True))
        lines.append("decision_type=" + _arr_str(self.decision_type))
        lines.append("left_child=" + _arr_str(self.left_child))
        lines.append("right_child=" + _arr_str(self.right_child))
        lines.append("leaf_value=" + _arr_str(self.leaf_value, high_precision=True))
        lines.append("leaf_weight=" + _arr_str(self.leaf_weight, high_precision=True))
        lines.append("leaf_count=" + _arr_str(self.leaf_count))
        lines.append("internal_value=" + _arr_str(self.internal_value))
        lines.append("internal_weight=" + _arr_str(self.internal_weight))
        lines.append("internal_count=" + _arr_str(self.internal_count))
        if self.num_cat > 0:
            lines.append("cat_boundaries=" + _arr_str(self.cat_boundaries))
            lines.append("cat_threshold=" + _arr_str(self.cat_threshold))
        lines.append(f"is_linear={int(self.is_linear)}")
        if self.is_linear:
            lines.append("leaf_const=" + _arr_str(self.leaf_const, high_precision=True))
            num_feat = [len(f) for f in self.leaf_features]
            lines.append("num_features=" + _arr_str(num_feat))
            lf = []
            for f in self.leaf_features:
                if len(f):
                    lf.append(_arr_str(f) + " ")
                lf.append(" ")
            lines.append("leaf_features=" + "".join(lf).rstrip())
            lc = []
            for c in self.leaf_coeff:
                if len(c):
                    lc.append(_arr_str(c, high_precision=True) + " ")
                lc.append(" ")
            lines.append("leaf_coeff=" + "".join(lc).rstrip())
        lines.append(f"shrinkage={self.shrinkage:g}")
        lines.append("")
        lines.append("")
        return "\n".join(lines)

    @classmethod
    def from_string(cls, block: str) -> "Tree":
        """Parse one Tree= block of a model file (reference Tree ctor from
        string, src/io/tree.cpp:714)."""
        kv = {}
        for line in block.splitlines():
            line = line.strip()
            if not line or line.startswith("Tree="):
                continue
            if "=" in line:
                k, v = line.split("=", 1)
                kv[k] = v

        def ints(key, default=None):
            if key not in kv:
                return default
            s = kv[key].split()
            return np.asarray([int(float(x)) for x in s], dtype=np.int64)

        def floats(key, default=None):
            if key not in kv:
                return default
            return np.asarray([float(x) for x in kv[key].split()], dtype=np.float64)

        n = int(kv["num_leaves"])
        num_cat = int(kv.get("num_cat", 0))
        nn = max(n - 1, 0)
        tree = cls(
            num_leaves=n,
            split_feature=(ints("split_feature", np.zeros(nn))).astype(np.int32),
            split_gain=floats("split_gain", np.zeros(nn)),
            threshold=floats("threshold", np.zeros(nn)),
            decision_type=(ints("decision_type", np.zeros(nn))).astype(np.int8),
            left_child=(ints("left_child", np.zeros(nn))).astype(np.int32),
            right_child=(ints("right_child", np.zeros(nn))).astype(np.int32),
            leaf_value=floats("leaf_value", np.zeros(n)),
            leaf_weight=floats("leaf_weight", np.zeros(n)),
            leaf_count=ints("leaf_count", np.zeros(n, dtype=np.int64)),
            internal_value=floats("internal_value", np.zeros(nn)),
            internal_weight=floats("internal_weight", np.zeros(nn)),
            internal_count=ints("internal_count", np.zeros(nn, dtype=np.int64)),
            shrinkage=float(kv.get("shrinkage", 1.0)),
            num_cat=num_cat,
        )
        if num_cat > 0:
            tree.cat_boundaries = ints("cat_boundaries")
            tree.cat_threshold = ints("cat_threshold").astype(np.uint32)
        if int(kv.get("is_linear", 0)):
            tree.is_linear = True
            tree.leaf_const = floats("leaf_const", np.zeros(n))
            num_feat = ints("num_features", np.zeros(n, dtype=np.int64))
            feats_flat = kv.get("leaf_features", "").split()
            coefs_flat = kv.get("leaf_coeff", "").split()
            tree.leaf_features = []
            tree.leaf_coeff = []
            fpos = cpos = 0
            for i in range(n):
                k = int(num_feat[i])
                tree.leaf_features.append(
                    np.asarray([int(x) for x in feats_flat[fpos : fpos + k]], dtype=np.int32)
                )
                tree.leaf_coeff.append(
                    np.asarray([float(x) for x in coefs_flat[cpos : cpos + k]])
                )
                fpos += k
                cpos += k
        return tree

    def to_json(self) -> dict:
        """Structured dump (reference Tree::ToJSON, src/io/tree.cpp:418)."""

        def node(i: int) -> dict:
            if i < 0:
                leaf = ~i
                return {
                    "leaf_index": int(leaf),
                    "leaf_value": float(self.leaf_value[leaf]),
                    "leaf_weight": float(self.leaf_weight[leaf]),
                    "leaf_count": int(self.leaf_count[leaf]),
                }
            dt = int(self.decision_type[i])
            is_cat = bool(dt & K_CATEGORICAL_MASK)
            missing = _missing_type_of(dt)
            d = {
                "split_index": int(i),
                "split_feature": int(self.split_feature[i]),
                "split_gain": float(self.split_gain[i]),
                "threshold": float(self.threshold[i]),
                "decision_type": "==" if is_cat else "<=",
                "default_left": bool(dt & K_DEFAULT_LEFT_MASK),
                "missing_type": ["None", "Zero", "NaN"][missing],
                "internal_value": float(self.internal_value[i]),
                "internal_weight": float(self.internal_weight[i]),
                "internal_count": int(self.internal_count[i]),
                "left_child": node(int(self.left_child[i])),
                "right_child": node(int(self.right_child[i])),
            }
            return d

        return {
            "num_leaves": int(self.num_leaves),
            "num_cat": int(self.num_cat),
            "shrinkage": float(self.shrinkage),
            "tree_structure": node(0 if self.num_leaves > 1 else ~0),
        }

    # ------------------------------------------------------------ importance
    def split_counts(self, num_features: int) -> np.ndarray:
        out = np.zeros(num_features)
        for f in self.split_feature:
            out[int(f)] += 1
        return out

    def gain_sums(self, num_features: int) -> np.ndarray:
        out = np.zeros(num_features)
        for f, g in zip(self.split_feature, self.split_gain):
            out[int(f)] += float(g)
        return out
