"""Exclusive Feature Bundling (EFB): pack mutually-exclusive sparse columns
into shared histogram bin planes.

Reference analogs: ``DatasetLoader``'s bundling pipeline in
``src/io/dataset.cpp`` — ``FindGroups`` (greedy conflict-count assignment of
features to groups) and ``FastFeatureBundling`` — following Algorithm 3/4 of
the LightGBM paper (Ke et al., NeurIPS 2017): features that are (almost)
never simultaneously nonzero share one histogram plane, so histogram cost
scales with #bundles instead of #columns.

TPU-native layout: the bundle IS a bin plane of the dense ``[N, P]`` bin
matrix (dataset.py).  Plane bin 0 is the shared all-default bin; member
feature ``k`` owns the contiguous sub-range ``[start_k, start_k + w_k)``
holding its non-default bins (its local bin ``b`` maps to plane bin
``start_k + b - 1``).  Eligibility keeps the decode trivially exact: only
numeric features with ``default_bin == 0`` (no negative values), no NaN bin
and no ``zero_as_missing`` are bundled, so "feature at its default" always
means "raw value 0" and every plane-threshold candidate decodes back to a
single original-feature threshold (see ops/split.py ``bundle_end`` and
``Tree.from_device_arrays``).

The greedy scan is vectorized NumPy over a row sample: bundle occupancy is a
``[G, S]`` bool matrix, a feature's conflict count against EVERY open bundle
is one fancy-index + sum, and first-fit picks the lowest-index bundle whose
accumulated conflicts stay under ``max_conflict_rate * S`` (reference
``FindGroups``' max_error budget).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

# plane bin budget: bins stay byte-sized so bundled datasets keep the uint8
# layout and the seg fast path (reference caps EFB groups at 256 bins too)
MAX_PLANE_BINS = 256
# bundles past this count stop being probed (bounds the [G, S] occupancy
# matrix when nothing is exclusive); later features become singletons
MAX_SEARCH_GROUPS = 512
# columns denser than this can't be usefully exclusive with anything and
# probing them would only burn time (dense data must stay byte-identical)
MAX_BUNDLE_DENSITY = 0.5


@dataclasses.dataclass
class BundleLayout:
    """Plane layout of a bundled dataset.

    ``planes[p]`` lists the ORIGINAL feature ids sharing plane ``p``
    (ascending; singleton planes keep the identity mapping).  ``starts[p]``
    gives each member's sub-range start in plane-bin space (singletons:
    ``[0]``).  ``widths[p]`` gives each member's sub-range width
    (``num_bins - 1`` for bundled members; full ``num_bins`` for
    singletons).
    """

    planes: List[List[int]]
    starts: List[List[int]]
    widths: List[List[int]]
    plane_bins: List[int]  # total bins per plane (incl. shared bin 0)

    # ------------------------------------------------------------- derived
    def __post_init__(self) -> None:
        self._pos: Dict[int, Tuple[int, int]] = {}
        for p, feats in enumerate(self.planes):
            for k, j in enumerate(feats):
                self._pos[int(j)] = (p, k)

    @property
    def num_planes(self) -> int:
        return len(self.planes)

    @property
    def has_bundles(self) -> bool:
        return any(len(p) > 1 for p in self.planes)

    def is_bundle(self, plane: int) -> bool:
        return len(self.planes[plane]) > 1

    def feature_position(self, orig: int) -> Tuple[int, int]:
        """(plane, member index) of an original used feature."""
        return self._pos[int(orig)]

    def decode(self, plane: int, plane_bin: int) -> Tuple[int, int]:
        """(original feature, feature-local bin) owning ``plane_bin``.

        For a bundle-plane split candidate at plane bin ``t`` (see
        ops/split.py: left child = everything except plane bins
        ``[t, end]``), the local threshold is ``t - start`` — "feature-local
        bin <= t - start goes left", with the shared default bin 0 always
        left.  Singleton planes are the identity.
        """
        feats = self.planes[plane]
        if len(feats) == 1:
            return feats[0], int(plane_bin)
        starts = self.starts[plane]
        widths = self.widths[plane]
        for j, s, w in zip(feats, starts, widths):
            if s <= plane_bin < s + w:
                return j, int(plane_bin) - s
        raise ValueError(
            f"plane bin {plane_bin} is outside every sub-range of plane "
            f"{plane} (starts={starts}, widths={widths})"
        )

    def bundle_end_array(self, num_bins_padded: int) -> np.ndarray:
        """[P, B] int32: for bundle-plane bins inside a member sub-range,
        the sub-range's LAST bin (the split-scan operand, ops/split.py);
        -1 everywhere else (singleton planes, shared bin 0, padding)."""
        out = np.full((self.num_planes, num_bins_padded), -1, np.int32)
        for p, feats in enumerate(self.planes):
            if len(feats) < 2:
                continue
            for s, w in zip(self.starts[p], self.widths[p]):
                out[p, s : s + w] = s + w - 1
        return out

    # ------------------------------------------------------------- packing
    def pack_columns(self, n: int, local_bins_of, dtype=np.int32) -> np.ndarray:
        """Build the [N, P] plane matrix from per-feature local bin columns.

        ``local_bins_of(orig) -> [n] int array`` returns a feature's own
        (mapper) bin column.  Bundle members write their non-default bins at
        ``start + local - 1``; members are visited in ascending feature id,
        so conflict rows (two members nonzero — allowed up to
        max_conflict_rate) deterministically keep the highest feature's
        value, and every packer (train, valid, predict) agrees.
        """
        out = np.zeros((n, self.num_planes), dtype=dtype)
        for p, feats in enumerate(self.planes):
            if len(feats) == 1:
                out[:, p] = local_bins_of(feats[0])
                continue
            for j, s in zip(feats, self.starts[p]):
                local = np.asarray(local_bins_of(j))
                nz = local > 0
                if nz.any():
                    out[nz, p] = (s - 1) + local[nz]
        return out

    def pack_sparse_members(
        self, out: np.ndarray, plane: int, member: int,
        rows: np.ndarray, local_bins: np.ndarray,
    ) -> None:
        """Scatter one bundle member's nonzero-row local bins into ``out``
        (the sparse-CSC packer's inner step; same conflict convention as
        pack_columns provided members are visited in ascending id)."""
        s = self.starts[plane][member]
        nz = local_bins > 0
        if nz.any():
            out[rows[nz], plane] = (s - 1) + local_bins[nz]


def _eligible(mapper, budget: int) -> bool:
    """Bundling eligibility of one feature's BinMapper (module docstring:
    the restrictions that make the bundle decode exact)."""
    from .binning import MissingType

    return (
        not mapper.is_categorical
        and mapper.missing_type == MissingType.NONE
        and mapper.nan_bin < 0
        and mapper.default_bin == 0
        and 2 <= mapper.num_bins
        and mapper.num_bins - 1 <= budget - 1
    )


def greedy_find_bundles(
    nz_lists: List[np.ndarray],
    widths: np.ndarray,
    sample_n: int,
    max_conflict_rate: float,
    budget: int = MAX_PLANE_BINS,
    max_search: int = MAX_SEARCH_GROUPS,
) -> List[List[int]]:
    """Greedy conflict-count bundling (reference FindGroups,
    src/io/dataset.cpp; paper Algorithm 3 with the sort-by-count note).

    ``nz_lists[i]``: sorted sample-row indices where candidate ``i`` is
    nonzero; ``widths[i]``: plane bins the candidate needs.  Returns groups
    of candidate indices (singletons included).  Features are visited in
    ORIGINAL column order (like the reference's FindGroups): each tries the
    first open bundle whose accumulated conflict count stays within
    ``max_conflict_rate * sample_n`` and whose bin budget still fits, else
    opens a new bundle.  Original order is deliberate — one-hot blocks are
    consecutive columns in practice, and once a block has filled its bundle
    the bundle's occupancy covers (nearly) every row, so the next block's
    first column conflicts immediately and opens a fresh bundle; the
    paper's sort-by-count variant scatters blocks across bundles and
    measured ~1.9x more planes on 50k-column block one-hot data.
    """
    nf = len(nz_lists)
    order = range(nf)
    max_err = max_conflict_rate * max(sample_n, 1)

    occupancy = np.zeros((0, sample_n), bool)
    conflicts: List[float] = []
    used_bins: List[int] = []
    groups: List[List[int]] = []
    extra_singletons: List[List[int]] = []
    for fi in order:
        fi = int(fi)
        nz = nz_lists[fi]
        w = int(widths[fi])
        gsel = -1
        if occupancy.shape[0]:
            if len(nz):
                cnt = occupancy[:, nz].sum(axis=1)
            else:
                cnt = np.zeros(occupancy.shape[0], np.int64)
            ok = (
                (np.asarray(conflicts) + cnt <= max_err)
                & (np.asarray(used_bins) + w <= budget - 1)
            )
            hits = np.flatnonzero(ok)
            if len(hits):
                gsel = int(hits[0])
        if gsel >= 0:
            groups[gsel].append(fi)
            conflicts[gsel] += float(cnt[gsel])
            used_bins[gsel] += w
            if len(nz):
                occupancy[gsel, nz] = True
        elif occupancy.shape[0] >= max_search:
            extra_singletons.append([fi])
        else:
            groups.append([fi])
            conflicts.append(0.0)
            used_bins.append(w)
            row = np.zeros((1, sample_n), bool)
            if len(nz):
                row[0, nz] = True
            occupancy = np.concatenate([occupancy, row], axis=0)
    return groups + extra_singletons


def build_layout(
    used_features: List[int],
    bin_mappers,
    nonzeros_of,
    n_rows: int,
    *,
    sample_rows: Optional[np.ndarray] = None,
    max_conflict_rate: float = 0.0,
    budget: int = MAX_PLANE_BINS,
) -> Optional[BundleLayout]:
    """Bundle-aware plane layout for a dataset, or None when nothing bundles
    (identity layout — the bin matrix stays byte-identical to the unbundled
    build, so dense datasets and their goldens are untouched).

    ``nonzeros_of(orig) -> sorted row indices`` with a nonzero raw value
    (full rows; sampled down here).  Candidate features must be eligible
    (_eligible) and sparse enough (MAX_BUNDLE_DENSITY) to possibly pay off.
    """
    if len(used_features) < 2:
        return None
    if sample_rows is not None:
        sample_n = len(sample_rows)
        pos = np.full(n_rows, -1, np.int64)
        pos[np.asarray(sample_rows)] = np.arange(sample_n)
    else:
        sample_n = n_rows
        pos = None
    cand: List[int] = []
    nz_lists: List[np.ndarray] = []
    widths: List[int] = []
    for j in used_features:
        m = bin_mappers[j]
        if not _eligible(m, budget):
            continue
        nz = np.asarray(nonzeros_of(j))
        if pos is not None:
            nz = pos[nz]
            nz = nz[nz >= 0]
        if len(nz) > MAX_BUNDLE_DENSITY * sample_n:
            continue
        cand.append(j)
        nz_lists.append(nz)
        widths.append(m.num_bins - 1)
    if len(cand) < 2:
        return None
    groups = greedy_find_bundles(
        nz_lists, np.asarray(widths), sample_n, max_conflict_rate, budget
    )
    if not any(len(g) > 1 for g in groups):
        return None

    # plane order: each plane sits at the position of its LOWEST original
    # feature in used-feature order, so unbundled features keep their
    # relative column order and singleton layouts match the identity build
    bundled_of: Dict[int, List[int]] = {}
    for g in groups:
        if len(g) > 1:
            feats = sorted(cand[i] for i in g)
            for j in feats:
                bundled_of[j] = feats
    planes: List[List[int]] = []
    starts: List[List[int]] = []
    widths_out: List[List[int]] = []
    plane_bins: List[int] = []
    seen = set()
    for j in used_features:
        if j in seen:
            continue
        feats = bundled_of.get(j)
        if feats is None:
            planes.append([j])
            starts.append([0])
            widths_out.append([bin_mappers[j].num_bins])
            plane_bins.append(bin_mappers[j].num_bins)
            continue
        seen.update(feats)
        ss, ww = [], []
        s = 1  # plane bin 0 = shared all-default bin
        for f in feats:
            w = bin_mappers[f].num_bins - 1
            ss.append(s)
            ww.append(w)
            s += w
        planes.append(list(feats))
        starts.append(ss)
        widths_out.append(ww)
        plane_bins.append(s)
    return BundleLayout(
        planes=planes, starts=starts, widths=widths_out, plane_bins=plane_bins
    )
