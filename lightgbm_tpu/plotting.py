"""Plotting API (reference: python-package/lightgbm/plotting.py).

matplotlib/graphviz are optional exactly as in the reference: functions
import them lazily and raise ImportError with the same guidance when absent.
"""

from __future__ import annotations

from typing import Any, Optional


def _check_matplotlib():
    try:
        import matplotlib.pyplot as plt  # type: ignore

        return plt
    except ImportError as e:  # pragma: no cover - env-dependent
        raise ImportError(
            "You must install matplotlib and restart your session to plot."
        ) from e


def plot_importance(
    booster,
    ax=None,
    height: float = 0.2,
    xlim=None,
    ylim=None,
    title: Optional[str] = "Feature importance",
    xlabel: Optional[str] = "Feature importance",
    ylabel: Optional[str] = "Features",
    importance_type: str = "auto",
    max_num_features: Optional[int] = None,
    ignore_zero: bool = True,
    figsize=None,
    dpi=None,
    grid: bool = True,
    precision: Optional[int] = 3,
    **kwargs: Any,
):
    """Horizontal bar chart of feature importances (plotting.py:38)."""
    plt = _check_matplotlib()
    if importance_type == "auto":
        importance_type = "split"
    imp = booster.feature_importance(importance_type)
    names = booster.feature_name()
    pairs = sorted(zip(imp, names), key=lambda t: t[0])
    if ignore_zero:
        pairs = [p for p in pairs if p[0] > 0]
    if max_num_features is not None and max_num_features > 0:
        pairs = pairs[-max_num_features:]
    values = [p[0] for p in pairs]
    labels = [p[1] for p in pairs]
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ylocs = range(len(values))
    ax.barh(list(ylocs), values, height=height, **kwargs)
    for y, v in zip(ylocs, values):
        ax.text(
            v + 1,
            y,
            f"{v:.{precision}f}" if precision is not None else str(v),
            va="center",
        )
    ax.set_yticks(list(ylocs))
    ax.set_yticklabels(labels)
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    if title:
        ax.set_title(title)
    if xlabel:
        ax.set_xlabel(xlabel)
    if ylabel:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(
    booster,
    metric: Optional[str] = None,
    dataset_names=None,
    ax=None,
    xlim=None,
    ylim=None,
    title: Optional[str] = "Metric during training",
    xlabel: Optional[str] = "Iterations",
    ylabel: Optional[str] = "@metric@",
    figsize=None,
    dpi=None,
    grid: bool = True,
):
    """Plot an eval history recorded by record_evaluation (plotting.py:167)."""
    plt = _check_matplotlib()
    if isinstance(booster, dict):
        eval_results = booster
    else:
        eval_results = getattr(booster, "evals_result_", None)
        if not eval_results:
            raise ValueError(
                "eval results not found; pass the dict from record_evaluation"
            )
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    names = dataset_names or list(eval_results.keys())
    for name in names:
        metrics = eval_results[name]
        m = metric or next(iter(metrics))
        vals = metrics[m]
        ax.plot(range(len(vals)), vals, label=name)
    m = metric or next(iter(next(iter(eval_results.values()))))
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    if title:
        ax.set_title(title)
    if xlabel:
        ax.set_xlabel(xlabel)
    if ylabel:
        ax.set_ylabel(ylabel.replace("@metric@", m))
    ax.legend()
    ax.grid(grid)
    return ax


def plot_split_value_histogram(
    booster,
    feature,
    bins=None,
    ax=None,
    width_coef: float = 0.8,
    xlim=None,
    ylim=None,
    title: Optional[str] = "Split value histogram for feature with @index/name@ @feature@",
    xlabel: Optional[str] = "Feature split value",
    ylabel: Optional[str] = "Count",
    figsize=None,
    dpi=None,
    grid: bool = True,
    **kwargs: Any,
):
    """Histogram of a feature's split thresholds (plotting.py:268)."""
    plt = _check_matplotlib()
    hist, edges = booster.get_split_value_histogram(feature, bins=bins)
    if hist.sum() == 0:
        raise ValueError(
            f"Cannot plot split value histogram, because feature {feature} "
            "was not used in splitting"
        )
    centred = (edges[:-1] + edges[1:]) / 2
    width = width_coef * (edges[1] - edges[0])
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ax.bar(centred, hist, width=width, **kwargs)
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    if title:
        which = "name" if isinstance(feature, str) else "index"
        ax.set_title(
            title.replace("@index/name@", which).replace("@feature@", str(feature))
        )
    if xlabel:
        ax.set_xlabel(xlabel)
    if ylabel:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def create_tree_digraph(booster, tree_index: int = 0, **kwargs: Any):
    """Graphviz digraph of one tree (plotting.py:414)."""
    try:
        import graphviz  # type: ignore
    except ImportError as e:  # pragma: no cover - env-dependent
        raise ImportError(
            "You must install graphviz and restart your session to plot a tree."
        ) from e
    tree = booster.models_[tree_index]
    names = booster.feature_name()
    g = graphviz.Digraph(**kwargs)

    def rec(node):
        if node < 0:
            leaf = ~node
            nid = f"leaf{leaf}"
            g.node(nid, f"leaf {leaf}: {float(tree.leaf_value[leaf]):.6g}")
            return nid
        nid = f"split{node}"
        f = int(tree.split_feature[node])
        fname = names[f] if f < len(names) else str(f)
        op = "==" if tree.decision_type[node] & 1 else "<="
        g.node(nid, f"{fname} {op} {float(tree.threshold[node]):.6g}")
        g.edge(nid, rec(int(tree.left_child[node])), label="yes")
        g.edge(nid, rec(int(tree.right_child[node])), label="no")
        return nid

    rec(0 if tree.num_leaves > 1 else ~0)
    return g


def plot_tree(booster, tree_index: int = 0, ax=None, figsize=None, dpi=None,
              **kwargs: Any):
    """Render one tree via graphviz (plotting.py:560)."""
    plt = _check_matplotlib()
    g = create_tree_digraph(booster, tree_index, **kwargs)
    import io

    try:
        import matplotlib.image as mpimg  # type: ignore
    except ImportError as e:  # pragma: no cover
        raise ImportError("matplotlib required") from e
    s = io.BytesIO(g.pipe(format="png"))
    img = mpimg.imread(s)
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ax.imshow(img)
    ax.axis("off")
    return ax
