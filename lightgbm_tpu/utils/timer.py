"""Per-phase wall-clock accumulation (reference: FunctionTimer/global_timer,
include/LightGBM/utils/common.h:979-1055 — scoped timers summed per label,
summary printed at shutdown when verbosity allows).

On an async accelerator runtime, phase walls measure HOST time: dispatch cost
for jitted phases, full device time for phases that synchronize (eval pulls
scores to host).  ``jax.named_scope`` annotations inside the grower mark the
same phases for ``jax.profiler`` traces.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import defaultdict
from typing import Dict, Iterator


class GlobalTimer:
    def __init__(self) -> None:
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)
        # defaultdict += is read-modify-write: concurrent phases (dask
        # workers, threaded predict) would drop increments without a lock
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def timed(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self.totals[name] += dt
                self.counts[name] += 1

    def reset(self) -> None:
        with self._lock:
            self.totals.clear()
            self.counts.clear()

    def summary(self) -> str:
        with self._lock:
            totals = dict(self.totals)
            counts = dict(self.counts)
        if not totals:
            return "LightGBM::timer: (no phases recorded)"
        width = max(len(k) for k in totals)
        lines = ["LightGBM::timer (host wall per phase)"]
        for name, total in sorted(totals.items(), key=lambda kv: -kv[1]):
            lines.append(
                f"  {name.ljust(width)}  {total:9.3f}s  x{counts[name]}"
            )
        return "\n".join(lines)


global_timer = GlobalTimer()
