"""Logging bridge (reference: include/LightGBM/utils/log.h — Log::Info/
Warning/Debug with a redirectable callback, LGBM_RegisterLogCallback) and the
python-package ``register_logger`` (basic.py:160).

Default output is print-to-stdout like the reference CLI; ``register_logger``
redirects every message through a user logger object.
"""

from __future__ import annotations

from typing import Any, Optional


class _LogBridge:
    def __init__(self) -> None:
        self._logger: Optional[Any] = None
        self._info_name = "info"
        self._warning_name = "warning"

    def register(self, logger: Any, info_method_name: str = "info",
                 warning_method_name: str = "warning") -> None:
        for name in (info_method_name, warning_method_name):
            if not callable(getattr(logger, name, None)):
                raise TypeError(
                    f"logger must provide a callable {name!r} method"
                )
        self._logger = logger
        self._info_name = info_method_name
        self._warning_name = warning_method_name

    def info(self, msg: str) -> None:
        if self._logger is not None:
            getattr(self._logger, self._info_name)(msg)
        else:
            print(msg)

    def warning(self, msg: str) -> None:
        if self._logger is not None:
            getattr(self._logger, self._warning_name)(msg)
        else:
            print(f"[LightGBM] [Warning] {msg}")


_bridge = _LogBridge()


def register_logger(logger: Any, info_method_name: str = "info",
                    warning_method_name: str = "warning") -> None:
    """Redirect library output to ``logger`` (python-package basic.py:160)."""
    _bridge.register(logger, info_method_name, warning_method_name)


def unregister_logger() -> None:
    """Restore the default print-to-stdout logging (undoes
    :func:`register_logger`)."""
    _bridge._logger = None
    _bridge._info_name = "info"
    _bridge._warning_name = "warning"


def log_info(msg: str) -> None:
    _bridge.info(msg)


def log_warning(msg: str) -> None:
    _bridge.warning(msg)
