"""scikit-learn estimator API (reference: python-package/lightgbm/sklearn.py).

LGBMModel/LGBMRegressor/LGBMClassifier/LGBMRanker with the same constructor
parameters, fit/predict contracts, and fitted attributes (``booster_``,
``best_iteration_``, ``best_score_``, ``feature_importances_``, ``classes_``).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from .callback import early_stopping as early_stopping_cb
from .dataset import Dataset
from .engine import train as engine_train


class LGBMModel:
    def __init__(
        self,
        boosting_type: str = "gbdt",
        num_leaves: int = 31,
        max_depth: int = -1,
        learning_rate: float = 0.1,
        n_estimators: int = 100,
        subsample_for_bin: int = 200000,
        objective: Optional[str] = None,
        class_weight=None,
        min_split_gain: float = 0.0,
        min_child_weight: float = 1e-3,
        min_child_samples: int = 20,
        subsample: float = 1.0,
        subsample_freq: int = 0,
        colsample_bytree: float = 1.0,
        reg_alpha: float = 0.0,
        reg_lambda: float = 0.0,
        random_state: Optional[int] = None,
        n_jobs: int = -1,
        importance_type: str = "split",
        **kwargs: Any,
    ):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.importance_type = importance_type
        self._other_params = dict(kwargs)
        self._Booster = None
        self._evals_result: Dict = {}
        self._best_iteration = -1
        self._classes = None
        self._n_classes = -1

    # ------------------------------------------------------------- sklearn API
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = {
            "boosting_type": self.boosting_type,
            "num_leaves": self.num_leaves,
            "max_depth": self.max_depth,
            "learning_rate": self.learning_rate,
            "n_estimators": self.n_estimators,
            "subsample_for_bin": self.subsample_for_bin,
            "objective": self.objective,
            "class_weight": self.class_weight,
            "min_split_gain": self.min_split_gain,
            "min_child_weight": self.min_child_weight,
            "min_child_samples": self.min_child_samples,
            "subsample": self.subsample,
            "subsample_freq": self.subsample_freq,
            "colsample_bytree": self.colsample_bytree,
            "reg_alpha": self.reg_alpha,
            "reg_lambda": self.reg_lambda,
            "random_state": self.random_state,
            "n_jobs": self.n_jobs,
            "importance_type": self.importance_type,
        }
        params.update(self._other_params)
        return params

    def set_params(self, **params: Any) -> "LGBMModel":
        for key, value in params.items():
            if hasattr(self, key):
                setattr(self, key, value)
            else:
                self._other_params[key] = value
        return self

    def _default_objective(self) -> str:
        return "regression"

    def _lgb_params(self) -> Dict[str, Any]:
        params = {
            "boosting": self.boosting_type,
            "num_leaves": self.num_leaves,
            "max_depth": self.max_depth,
            "learning_rate": self.learning_rate,
            "bin_construct_sample_cnt": self.subsample_for_bin,
            "objective": self.objective or self._default_objective(),
            "min_gain_to_split": self.min_split_gain,
            "min_sum_hessian_in_leaf": self.min_child_weight,
            "min_data_in_leaf": self.min_child_samples,
            "bagging_fraction": self.subsample,
            "bagging_freq": self.subsample_freq,
            "feature_fraction": self.colsample_bytree,
            "lambda_l1": self.reg_alpha,
            "lambda_l2": self.reg_lambda,
            "verbosity": -1,
        }
        if self.random_state is not None:
            params["seed"] = self.random_state
        params.update(self._other_params)
        return params

    def _sample_weight_with_class_weight(self, y, sample_weight):
        if self.class_weight is None:
            return sample_weight
        classes, counts = np.unique(y, return_counts=True)
        if self.class_weight == "balanced":
            cw = {c: len(y) / (len(classes) * cnt) for c, cnt in zip(classes, counts)}
        else:
            cw = dict(self.class_weight)
        w = np.asarray([cw.get(v, 1.0) for v in y], dtype=np.float64)
        if sample_weight is not None:
            w = w * np.asarray(sample_weight, dtype=np.float64)
        return w

    def fit(
        self,
        X,
        y,
        sample_weight=None,
        init_score=None,
        group=None,
        eval_set=None,
        eval_names=None,
        eval_sample_weight=None,
        eval_init_score=None,
        eval_group=None,
        eval_metric=None,
        early_stopping_rounds: Optional[int] = None,
        feature_name: Union[str, List[str]] = "auto",
        categorical_feature: Union[str, List] = "auto",
        callbacks: Optional[List[Callable]] = None,
        init_model=None,
    ) -> "LGBMModel":
        params = self._lgb_params()
        if eval_metric is not None:
            params["metric"] = eval_metric
        sample_weight = self._sample_weight_with_class_weight(y, sample_weight)
        train_set = Dataset(
            np.asarray(X, dtype=np.float64),
            np.asarray(y, dtype=np.float64),
            weight=sample_weight,
            group=group,
            init_score=init_score,
            feature_name=feature_name,
            categorical_feature=categorical_feature,
            params=params,
        )
        valid_sets = []
        valid_names = []
        for i, pair in enumerate(eval_set or []):
            vx, vy = pair
            vw = eval_sample_weight[i] if eval_sample_weight else None
            vg = eval_group[i] if eval_group else None
            vi = eval_init_score[i] if eval_init_score else None
            valid_sets.append(
                train_set.create_valid(
                    np.asarray(vx, dtype=np.float64),
                    np.asarray(vy, dtype=np.float64),
                    weight=vw,
                    group=vg,
                    init_score=vi,
                )
            )
            valid_names.append(eval_names[i] if eval_names else f"valid_{i}")
        callbacks = list(callbacks or [])
        if early_stopping_rounds is not None and early_stopping_rounds > 0:
            callbacks.append(early_stopping_cb(early_stopping_rounds))
        from .callback import record_evaluation

        self._evals_result = {}
        callbacks.append(record_evaluation(self._evals_result))
        self._Booster = engine_train(
            params,
            train_set,
            num_boost_round=self.n_estimators,
            valid_sets=valid_sets,
            valid_names=valid_names,
            callbacks=callbacks,
            init_model=init_model,
        )
        self._best_iteration = self._Booster.best_iteration
        return self

    def predict(
        self,
        X,
        raw_score: bool = False,
        start_iteration: int = 0,
        num_iteration: Optional[int] = None,
        pred_leaf: bool = False,
        pred_contrib: bool = False,
        **kwargs,
    ):
        if self._Booster is None:
            raise ValueError("Estimator not fitted, call fit first")
        if num_iteration is None and self._best_iteration > 0:
            num_iteration = self._best_iteration
        # keep scipy inputs sparse: the streaming engine bins CSC directly
        # (densifying here would also break on wide sparse matrices)
        if not hasattr(X, "tocsc"):
            X = np.asarray(X, dtype=np.float64)
        return self._Booster.predict(
            X,
            raw_score=raw_score,
            start_iteration=start_iteration,
            num_iteration=num_iteration,
            pred_leaf=pred_leaf,
            pred_contrib=pred_contrib,
            **kwargs,
        )

    # --------------------------------------------------------------- fitted
    @property
    def booster_(self):
        if self._Booster is None:
            raise ValueError("Estimator not fitted")
        return self._Booster

    @property
    def best_iteration_(self) -> int:
        return self._best_iteration

    @property
    def best_score_(self):
        return self._Booster.best_score if self._Booster else {}

    @property
    def evals_result_(self):
        return self._evals_result

    @property
    def feature_importances_(self) -> np.ndarray:
        return self.booster_.feature_importance(self.importance_type)

    @property
    def n_features_(self) -> int:
        return self.booster_.num_feature()

    @property
    def feature_name_(self) -> List[str]:
        return self.booster_.feature_name()


class LGBMRegressor(LGBMModel):
    def _default_objective(self) -> str:
        return "regression"


class LGBMClassifier(LGBMModel):
    def _default_objective(self) -> str:
        return "binary" if (self._n_classes or 2) <= 2 else "multiclass"

    def fit(self, X, y, **kwargs):
        y = np.asarray(y)
        self._classes = np.unique(y)
        self._n_classes = len(self._classes)
        y_enc = np.searchsorted(self._classes, y).astype(np.float64)
        if self.objective is None:
            if self._n_classes > 2:
                self._other_params.setdefault("num_class", self._n_classes)
        super().fit(X, y_enc, **kwargs)
        return self

    @property
    def classes_(self):
        return self._classes

    @property
    def n_classes_(self) -> int:
        return self._n_classes

    def predict_proba(self, X, **kwargs):
        prob = super().predict(X, **kwargs)
        if self._n_classes <= 2 and prob.ndim == 1:
            return np.stack([1.0 - prob, prob], axis=1)
        return prob

    def predict(self, X, raw_score=False, pred_leaf=False, pred_contrib=False, **kwargs):
        if raw_score or pred_leaf or pred_contrib:
            return super().predict(
                X, raw_score=raw_score, pred_leaf=pred_leaf, pred_contrib=pred_contrib, **kwargs
            )
        prob = self.predict_proba(X, **kwargs)
        return self._classes[np.argmax(prob, axis=1)]


class LGBMRanker(LGBMModel):
    def _default_objective(self) -> str:
        return "lambdarank"

    def fit(self, X, y, group=None, **kwargs):
        if group is None:
            raise ValueError("LGBMRanker requires the group parameter")
        return super().fit(X, y, group=group, **kwargs)
