"""Parameter/config system.

Reference analog: ``include/LightGBM/config.h`` (struct Config) and the
generated alias table in ``src/io/config_auto.cpp``.  The reference declares
~200 typed fields and code-generates a string->struct parser; here a plain
dataclass plus an explicit alias map gives the same user-facing contract
(param dicts with aliases, first-value-wins precedence, post-parse
consistency fixes) without codegen.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional


# Alias -> canonical name. Mirrors the documented LightGBM parameter aliases
# (reference: src/io/config_auto.cpp alias table).
_PARAM_ALIASES: Dict[str, str] = {
    # core
    "config_file": "config",
    "task_type": "task",
    "objective_type": "objective",
    "app": "objective",
    "application": "objective",
    "loss": "objective",
    "boosting_type": "boosting",
    "boost": "boosting",
    "train": "data",
    "train_data": "data",
    "train_data_file": "data",
    "data_filename": "data",
    "test": "valid",
    "valid_data": "valid",
    "valid_data_file": "valid",
    "test_data": "valid",
    "test_data_file": "valid",
    "valid_filenames": "valid",
    "num_iteration": "num_iterations",
    "n_iter": "num_iterations",
    "num_tree": "num_iterations",
    "num_trees": "num_iterations",
    "num_round": "num_iterations",
    "num_rounds": "num_iterations",
    "nrounds": "num_iterations",
    "num_boost_round": "num_iterations",
    "n_estimators": "num_iterations",
    "max_iter": "num_iterations",
    "shrinkage_rate": "learning_rate",
    "eta": "learning_rate",
    "num_leaf": "num_leaves",
    "max_leaves": "num_leaves",
    "max_leaf": "num_leaves",
    "max_leaf_nodes": "num_leaves",
    "tree": "tree_learner",
    "tree_type": "tree_learner",
    "tree_learner_type": "tree_learner",
    "num_thread": "num_threads",
    "nthread": "num_threads",
    "nthreads": "num_threads",
    "n_jobs": "num_threads",
    "device": "device_type",
    "random_seed": "seed",
    "random_state": "seed",
    # learning control
    "min_data_per_leaf": "min_data_in_leaf",
    "min_data": "min_data_in_leaf",
    "min_child_samples": "min_data_in_leaf",
    "min_samples_leaf": "min_data_in_leaf",
    "min_sum_hessian_per_leaf": "min_sum_hessian_in_leaf",
    "min_sum_hessian": "min_sum_hessian_in_leaf",
    "min_hessian": "min_sum_hessian_in_leaf",
    "min_child_weight": "min_sum_hessian_in_leaf",
    "sub_row": "bagging_fraction",
    "subsample": "bagging_fraction",
    "bagging": "bagging_fraction",
    "pos_sub_row": "pos_bagging_fraction",
    "pos_subsample": "pos_bagging_fraction",
    "pos_bagging": "pos_bagging_fraction",
    "neg_sub_row": "neg_bagging_fraction",
    "neg_subsample": "neg_bagging_fraction",
    "neg_bagging": "neg_bagging_fraction",
    "subsample_freq": "bagging_freq",
    "bagging_fraction_seed": "bagging_seed",
    "sub_feature": "feature_fraction",
    "colsample_bytree": "feature_fraction",
    "sub_feature_bynode": "feature_fraction_bynode",
    "colsample_bynode": "feature_fraction_bynode",
    "early_stopping_rounds": "early_stopping_round",
    "early_stopping": "early_stopping_round",
    "n_iter_no_change": "early_stopping_round",
    "max_tree_output": "max_delta_step",
    "max_leaf_output": "max_delta_step",
    "reg_alpha": "lambda_l1",
    "l1_regularization": "lambda_l1",
    "reg_lambda": "lambda_l2",
    "lambda": "lambda_l2",
    "l2_regularization": "lambda_l2",
    "min_split_gain": "min_gain_to_split",
    "rate_drop": "drop_rate",
    "topk": "top_k",
    "mc": "monotone_constraints",
    "monotone_constraint": "monotone_constraints",
    "monotone_constraining_method": "monotone_constraints_method",
    "mc_method": "monotone_constraints_method",
    "monotone_splits_penalty": "monotone_penalty",
    "ms_penalty": "monotone_penalty",
    "mc_penalty": "monotone_penalty",
    "feature_contrib": "feature_contri",
    "fc": "feature_contri",
    "fp": "feature_contri",
    "feature_penalty": "feature_contri",
    "fs": "forcedsplits_filename",
    "forced_splits_filename": "forcedsplits_filename",
    "forced_splits_file": "forcedsplits_filename",
    "forced_splits": "forcedsplits_filename",
    "verbose": "verbosity",
    # dataset
    "linear_trees": "linear_tree",
    "max_bins": "max_bin",
    "subsample_for_bin": "bin_construct_sample_cnt",
    "data_seed": "data_random_seed",
    "is_sparse": "is_enable_sparse",
    "enable_sparse": "is_enable_sparse",
    "sparse": "is_enable_sparse",
    "is_enable_bundle": "enable_bundle",
    "bundle": "enable_bundle",
    "is_pre_partition": "pre_partition",
    "two_round_loading": "two_round",
    "use_two_round_loading": "two_round",
    "has_header": "header",
    "label": "label_column",
    "weight": "weight_column",
    "group": "group_column",
    "group_id": "group_column",
    "query_column": "group_column",
    "query": "group_column",
    "query_id": "group_column",
    "ignore_feature": "ignore_column",
    "blacklist": "ignore_column",
    "cat_feature": "categorical_feature",
    "categorical_column": "categorical_feature",
    "cat_column": "categorical_feature",
    "categorical_features": "categorical_feature",
    # predict
    "is_predict_raw_score": "predict_raw_score",
    "predict_rawscore": "predict_raw_score",
    "raw_score": "predict_raw_score",
    "is_predict_leaf_index": "predict_leaf_index",
    "leaf_index": "predict_leaf_index",
    "is_predict_contrib": "predict_contrib",
    "contrib": "predict_contrib",
    # objective
    "num_classes": "num_class",
    "unbalance": "is_unbalance",
    "unbalanced_sets": "is_unbalance",
    "num_position_buckets": "lambdarank_position_bias_regularization",
    # metric
    "metrics": "metric",
    "metric_types": "metric",
    "output_freq": "metric_freq",
    "training_metric": "is_provide_training_metric",
    "is_training_metric": "is_provide_training_metric",
    "train_metric": "is_provide_training_metric",
    "ndcg_eval_at": "eval_at",
    "ndcg_at": "eval_at",
    "map_eval_at": "eval_at",
    "map_at": "eval_at",
    # observability
    "telemetry_output": "telemetry_out",
    "telemetry_file": "telemetry_out",
    "trace_dir": "profile_trace_dir",
    "trace_enabled": "trace_spans",
    "trace_sample_rate": "trace_sample",
    # resilience
    "checkpoint_path": "checkpoint_dir",
    "checkpoint_freq": "checkpoint_interval",
    "checkpoint_keep_last": "checkpoint_keep",
    "restore_from": "resume_from",
    "check_numeric": "check_numerics",
    # network
    "num_machine": "num_machines",
    "local_port": "local_listen_port",
    "port": "local_listen_port",
    "machine_list_file": "machine_list_filename",
    "machine_list": "machine_list_filename",
    "mlist": "machine_list_filename",
    "workers": "machines",
    "nodes": "machines",
}

_OBJECTIVE_ALIASES: Dict[str, str] = {
    "regression": "regression",
    "regression_l2": "regression",
    "l2": "regression",
    "mean_squared_error": "regression",
    "mse": "regression",
    "l2_root": "regression",
    "root_mean_squared_error": "regression",
    "rmse": "regression",
    "regression_l1": "regression_l1",
    "l1": "regression_l1",
    "mean_absolute_error": "regression_l1",
    "mae": "regression_l1",
    "mean_absolute_percentage_error": "mape",
    "mape": "mape",
    "huber": "huber",
    "fair": "fair",
    "poisson": "poisson",
    "quantile": "quantile",
    "gamma": "gamma",
    "tweedie": "tweedie",
    "binary": "binary",
    "multiclass": "multiclass",
    "softmax": "multiclass",
    "multiclassova": "multiclassova",
    "multiclass_ova": "multiclassova",
    "ova": "multiclassova",
    "ovr": "multiclassova",
    "cross_entropy": "cross_entropy",
    "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda",
    "xentlambda": "cross_entropy_lambda",
    "lambdarank": "lambdarank",
    "rank_xendcg": "rank_xendcg",
    "xendcg": "rank_xendcg",
    "xe_ndcg": "rank_xendcg",
    "xe_ndcg_mart": "rank_xendcg",
    "xendcg_mart": "rank_xendcg",
    "none": "none",
    "null": "none",
    "custom": "none",
    "na": "none",
}


def _to_bool(v: Any) -> bool:
    if isinstance(v, bool):
        return v
    if isinstance(v, (int, float)):
        return bool(v)
    s = str(v).strip().lower()
    if s in ("true", "1", "yes", "+"):
        return True
    if s in ("false", "0", "no", "-"):
        return False
    raise ValueError(f"cannot parse boolean from {v!r}")


def _to_int_list(v: Any) -> List[int]:
    if v is None or v == "":
        return []
    if isinstance(v, (list, tuple)):
        return [int(x) for x in v]
    # "(1,0,-1)" / "[1, 0, -1]" forms round-trip from the model file's
    # parameters block (python repr of a list param)
    sv = str(v).strip().strip("[]()")
    return [int(x) for x in sv.split(",") if x.strip() != ""]


def _to_float_list(v: Any) -> List[float]:
    if v is None or v == "":
        return []
    if isinstance(v, (list, tuple)):
        return [float(x) for x in v]
    sv = str(v).strip().strip("[]()")
    return [float(x) for x in sv.split(",") if x.strip() != ""]


def _to_str_list(v: Any) -> List[str]:
    if v is None or v == "":
        return []
    if isinstance(v, (list, tuple)):
        return [str(x) for x in v]
    return [s for s in str(v).split(",") if s != ""]


@dataclasses.dataclass
class Config:
    """Typed view of a LightGBM-style parameter dict.

    Field names and defaults follow the reference's documented parameters
    (include/LightGBM/config.h); only fields the TPU build consumes (or will
    consume) are materialized.
    """

    # Core
    task: str = "train"
    objective: str = "regression"
    boosting: str = "gbdt"
    data: str = ""
    valid: List[str] = dataclasses.field(default_factory=list)
    num_iterations: int = 100
    learning_rate: float = 0.1
    num_leaves: int = 31
    tree_learner: str = "serial"
    num_threads: int = 0
    device_type: str = "tpu"
    seed: Optional[int] = None
    deterministic: bool = False

    # Learning control
    force_col_wise: bool = False
    force_row_wise: bool = False
    histogram_pool_size: float = -1.0
    max_depth: int = -1
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    bagging_fraction: float = 1.0
    pos_bagging_fraction: float = 1.0
    neg_bagging_fraction: float = 1.0
    bagging_freq: int = 0
    bagging_seed: int = 3
    bagging_by_query: bool = False
    feature_fraction: float = 1.0
    feature_fraction_bynode: float = 1.0
    feature_fraction_seed: int = 2
    extra_trees: bool = False
    extra_seed: int = 6
    # TPU extension: fuse the best-split scan into the Pallas kernel on the
    # basic numeric path (targets the per-split fixed cost; default off
    # pending on-chip measurement — see ops/pallas/split_scan.py)
    fused_split_scan: bool = False
    # TPU extension: frontier-batched growth — split up to this many leaves
    # per compiled loop step (amortizes the per-split fixed program cost;
    # exact via the prefix-commit rule, see ops/grower.py).  1 = serial,
    # byte-identical to the unbatched grower.
    leaf_batch: int = 1
    # TPU extension: adaptively clamp the effective leaf_batch by the
    # remaining-leaf budget and the observed commit rate (splits committed /
    # slots offered, from TreeArrays.grow_steps).  Near the num_leaves cap a
    # large K mostly speculates — round-8 measured K=8 at 3.4% SLOWER than
    # serial there — so when the EMA commit rate drops below
    # leaf_batch_min_commit_rate the booster halves K (sticky: it never
    # grows back within a training run; every K has a warm compiled loop).
    leaf_batch_adaptive: bool = True
    leaf_batch_min_commit_rate: float = 0.625
    # TPU extension: model-fleet training (engine.train_fleet /
    # boosting/fleet.py) — when train_fleet receives ONE params dict it is
    # expanded to this many members whose seeds are offset by the member
    # index, all trained in lockstep through a single vmapped grow
    # executable.  Explicit params_list entries override this count.
    num_fleet: int = 1
    # TPU extension: fused Pallas grow step — partition + smaller-child
    # election + histogram for the whole frontier batch in ONE kernel launch
    # (ops/pallas/grow_step.py), collapsing the fixed dispatch/fusion-
    # boundary cost between the separately-launched grower phases.
    # 'auto' = on whenever the seg fast path is active (hist_mode='seg',
    # no feature-parallel, no data-parallel axis); 'on' / 'off' force it.
    # Off TPU the fused dispatcher lowers to the same XLA composition as the
    # two-launch path, so tree structures are byte-identical either way.
    grow_fused: str = "auto"
    # TPU extension: histogram accumulator (histogram engine v2).  'auto'
    # engages 2-digit int8 MXU accumulation by default on the single-host
    # seg TPU path — true f32 gradients are scaled onto the int8 grid once
    # per iteration and near-tie split decisions are re-accumulated in f32
    # before the structure commit (hist_near_tie_tol); 'bf16' keeps the
    # 3-term bf16 split accumulator everywhere; 'int8' forces the int8 path
    # where eligible (same gating as 'auto' today).  Off TPU both resolve
    # to the exact f32 reference — golden parity is unaffected.
    hist_acc: str = "auto"
    # relative gain gap below which the int8 winner counts as a near tie
    # and its histogram is redone with direct f32 accumulation
    hist_near_tie_tol: float = 1e-3
    # TPU extension: named-mesh layout (parallel/mesh.py).  'auto' derives
    # the layout from tree_learner ('data'/'voting' -> all devices on the
    # data axis, 'feature' -> all on the feature axis); 'data'/'feature'
    # force a 1-D layout; 'hybrid' factors the devices into a
    # (data, feature) 2-D mesh — rows sharded AND features sliced, the
    # layout a multi-chip pod wants.  All layouts run the SAME jitted
    # grow path; this knob only changes the mesh shape.
    mesh_layout: str = "auto"
    # TPU extension: double-buffered histogram collectives — split the
    # frontier-batched histogram psum into two half-stack psums issued
    # between the half builds, so the all-reduce of buffer 0 overlaps the
    # histogram build of buffer 1 (byte-identical; see ops/grower.py).
    # 'auto' = on whenever there is a data-axis histogram psum and
    # leaf_batch > 1 (the serial loop has nothing to overlap with);
    # 'on' / 'off' force it.
    overlap_collectives: str = "auto"
    # TPU extension: device-resident boosting (boosting/launch.py) — fuse N
    # consecutive boosting iterations (gradients, tree grow, score update,
    # in-scan bagging/GOSS mask derivation) into ONE compiled lax.scan
    # program so the host loop advances N trees per dispatch.  Host-boundary
    # work (eval, early stopping, callbacks, checkpointing, flight events)
    # buckets to launch boundaries, so the validator clamps N to divide the
    # active eval period / checkpoint_interval / snapshot_freq and warns
    # once.  'auto' = 8 on TPU backends, 1 elsewhere; model dumps are
    # byte-identical to the N=1 serial loop for every eligible config.
    train_steps_per_launch: Any = "auto"
    early_stopping_round: int = 0
    early_stopping_min_delta: float = 0.0
    first_metric_only: bool = False
    saved_feature_importance_type: int = 0  # 0=split counts, 1=gain sums
    max_delta_step: float = 0.0
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    linear_lambda: float = 0.0
    min_gain_to_split: float = 0.0
    drop_rate: float = 0.1
    max_drop: int = 50
    skip_drop: float = 0.5
    xgboost_dart_mode: bool = False
    uniform_drop: bool = False
    drop_seed: int = 4
    top_rate: float = 0.2
    other_rate: float = 0.1
    min_data_per_group: int = 100
    max_cat_threshold: int = 32
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    max_cat_to_onehot: int = 4
    top_k: int = 20
    monotone_constraints: List[int] = dataclasses.field(default_factory=list)
    monotone_constraints_method: str = "basic"
    monotone_penalty: float = 0.0
    feature_contri: List[float] = dataclasses.field(default_factory=list)
    forcedsplits_filename: str = ""
    refit_decay_rate: float = 0.9
    # IO (reference config.h:611/:623)
    output_model: str = "LightGBM_model.txt"
    snapshot_freq: int = -1
    cegb_tradeoff: float = 1.0
    cegb_penalty_split: float = 0.0
    cegb_penalty_feature_lazy: List[float] = dataclasses.field(default_factory=list)
    cegb_penalty_feature_coupled: List[float] = dataclasses.field(default_factory=list)
    path_smooth: float = 0.0
    interaction_constraints: Any = ""
    verbosity: int = 1
    # Observability (lightgbm_tpu/obs/): structured per-iteration telemetry,
    # optional JSONL sink, per-phase block_until_ready timing, and a
    # jax.profiler trace window over iterations [profile_iter_start,
    # profile_iter_end] (end < 0 = until training ends)
    telemetry: bool = False
    telemetry_out: str = ""
    obs_sync_timing: bool = False
    # deep device observability (needs telemetry=True):
    # obs_device_accounting captures executable cost/memory analysis
    # (cost/* / memory/* gauges; one extra lower per retraced jit label) and
    # live HBM watermarks (no-op on backends without memory_stats);
    # obs_collectives swaps the data-parallel grower's psums for timed
    # byte-counted wrappers (collective_measured/* — cross-checked against
    # the analytic parallel.psum_bytes_per_iteration model)
    obs_device_accounting: bool = False
    obs_collectives: bool = True
    # live ops plane (obs/flight, obs/health, obs/export): the flight
    # recorder ring is always on (capacity below, floor 32); the health
    # watchdog evaluates per-iteration alert rules host-side from recorded
    # telemetry; obs_export_port > 0 serves /metrics (Prometheus text) and
    # /healthz from a background HTTP endpoint for the run's duration
    obs_export_port: int = 0
    health_watchdog: bool = True
    flight_capacity: int = 256
    # distributed tracing (obs/trace): always-on span recorder exporting
    # Chrome trace-event JSON (Booster.dump_trace / GET /trace / paired
    # with every flight dump); trace_sample is the default per-span accept
    # rate (deterministic, per category — 1.0 records everything)
    trace_spans: bool = True
    trace_capacity: int = 4096
    trace_sample: float = 1.0
    profile_trace_dir: str = ""
    profile_iter_start: int = 0
    profile_iter_end: int = -1
    # Resilience (lightgbm_tpu/resilience/): iteration-granular atomic
    # checkpoints of FULL trainer state (model + score cache + RNG stream +
    # bagging mask + adaptive leaf_batch EMA + telemetry counters) so a run
    # killed mid-train resumes byte-identical; resume_from names a
    # checkpoint file or directory (latest wins).  check_numerics adds
    # opt-in finiteness guards on gradients/hessians and split gains.
    checkpoint_dir: str = ""
    checkpoint_interval: int = 0
    checkpoint_keep: int = 3
    resume_from: str = ""
    check_numerics: bool = False
    use_quantized_grad: bool = False
    num_grad_quant_bins: int = 4
    quant_train_renew_leaf: bool = False
    stochastic_rounding: bool = True

    # Dataset
    linear_tree: bool = False
    max_bin: int = 255
    max_bin_by_feature: List[int] = dataclasses.field(default_factory=list)
    min_data_in_bin: int = 3
    bin_construct_sample_cnt: int = 200000
    data_random_seed: int = 1
    is_enable_sparse: bool = True
    enable_bundle: bool = True
    # EFB conflict budget: fraction of rows of a bundle allowed to carry two
    # nonzero members (reference config.h max_conflict_rate; 0.0 = exact)
    max_conflict_rate: float = 0.0
    use_missing: bool = True
    zero_as_missing: bool = False
    feature_pre_filter: bool = True
    # Out-of-core streaming ingest (lightgbm_tpu/ingest): chunk row count
    # for two-pass Dataset construction (0 = one-shot in-core path; chunk
    # iterables always stream), and an optional directory for np.memmap
    # backing of the packed bin planes so even [N, P] bins stay off-heap
    ingest_chunk_rows: int = 0
    ingest_mmap_dir: str = ""
    pre_partition: bool = False
    two_round: bool = False
    header: bool = False
    label_column: str = ""
    weight_column: str = ""
    group_column: str = ""
    ignore_column: str = ""
    categorical_feature: Any = ""
    forcedbins_filename: str = ""
    save_binary: bool = False
    precise_float_parser: bool = False
    parser_config_file: str = ""

    # Predict
    start_iteration_predict: int = 0
    num_iteration_predict: int = -1
    predict_raw_score: bool = False
    predict_leaf_index: bool = False
    predict_contrib: bool = False
    predict_disable_shape_check: bool = False
    pred_early_stop: bool = False
    pred_early_stop_freq: int = 10
    pred_early_stop_margin: float = 10.0
    # streaming batch-prediction engine (predict.StreamingPredictor): chunk
    # size fed per compiled walk, pipeline depth (chunks in flight), local
    # devices to row-shard each chunk over (-1 = all), and whether Booster
    # load AOT-compiles the bucket-ladder executables up front
    pred_chunk_rows: int = 4096
    pred_num_buffers: int = 2
    pred_shard_devices: int = 1
    pred_aot_compile: bool = False
    # prediction engine: 'walk' = level-synchronous gather walker;
    # 'matmul' = tensor-forest contractions (ops/tensor_forest.py) for
    # forests in the serving sweet spot (<= 64 leaves, depth <= 8, numeric
    # splits inside the packed-bin envelope), falling back to the walker
    # with a telemetry event when ineligible; 'auto' = matmul only when
    # eligible AND the compile-time parity probe matches the walker
    # byte-for-byte
    pred_engine: str = "walk"

    # Serving (lightgbm_tpu/serving/): lgb.serve() micro-batcher + registry.
    # serve_deadline_ms bounds how long a request may wait for coalescing
    # before its batch flushes; serve_max_batch caps coalesced rows per
    # dispatch (and is the registry's warmed ladder chunk, so every flush
    # hits an AOT bucket); serve_memory_budget_mb bounds the registry's
    # estimated device-table residency (0 = unlimited, LRU-evicts beyond);
    # serve_port binds the HTTP front end (/predict + /metrics + /healthz;
    # 0 disables, -1 binds an ephemeral port and reports it).
    serve_deadline_ms: float = 5.0
    serve_max_batch: int = 4096
    serve_memory_budget_mb: float = 0.0
    serve_port: int = 0

    # Objective
    objective_seed: int = 5
    num_class: int = 1
    is_unbalance: bool = False
    scale_pos_weight: float = 1.0
    sigmoid: float = 1.0
    boost_from_average: bool = True
    reg_sqrt: bool = False
    alpha: float = 0.9
    fair_c: float = 1.0
    poisson_max_delta_step: float = 0.7
    tweedie_variance_power: float = 1.5
    lambdarank_truncation_level: int = 30
    lambdarank_norm: bool = True
    label_gain: List[float] = dataclasses.field(default_factory=list)
    lambdarank_position_bias_regularization: float = 0.0

    # Metric
    metric: List[str] = dataclasses.field(default_factory=list)
    metric_freq: int = 1
    is_provide_training_metric: bool = False
    eval_at: List[int] = dataclasses.field(default_factory=lambda: [1, 2, 3, 4, 5])
    multi_error_top_k: int = 1
    auc_mu_weights: List[float] = dataclasses.field(default_factory=list)

    # Network
    num_machines: int = 1
    local_listen_port: int = 12400
    time_out: int = 120
    machine_list_filename: str = ""
    machines: str = ""

    # Raw (post-alias) params as given by the user.
    raw: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @classmethod
    def from_params(cls, params: Optional[Dict[str, Any]]) -> "Config":
        params = dict(params or {})
        cfg = cls()
        resolved: Dict[str, Any] = {}
        # first-value-wins among aliases, canonical name wins over aliases
        # (matches reference Config::KeepFirstValues semantics closely enough:
        # the reference warns and keeps the first-seen; canonical-first is the
        # common convention in the python package).
        for key, value in params.items():
            canon = _PARAM_ALIASES.get(key, key)
            if canon in resolved and canon != key:
                continue
            resolved[canon] = value
        cfg.raw = dict(resolved)
        for f in dataclasses.fields(cls):
            if f.name == "raw" or f.name not in resolved:
                continue
            v = resolved[f.name]
            try:
                if f.type in ("bool", bool):
                    setattr(cfg, f.name, _to_bool(v))
                elif f.type in ("int", int):
                    setattr(cfg, f.name, int(float(v)))
                elif f.type in ("float", float):
                    setattr(cfg, f.name, float(v))
                elif f.name in ("metric", "valid"):
                    setattr(cfg, f.name, _to_str_list(v))
                elif f.name in ("monotone_constraints", "eval_at", "max_bin_by_feature"):
                    setattr(cfg, f.name, _to_int_list(v))
                elif f.name in (
                    "label_gain",
                    "feature_contri",
                    "cegb_penalty_feature_lazy",
                    "cegb_penalty_feature_coupled",
                    "auc_mu_weights",
                ):
                    setattr(cfg, f.name, _to_float_list(v))
                elif f.name == "seed":
                    setattr(cfg, f.name, int(float(v)))
                else:
                    setattr(cfg, f.name, v)
            except (TypeError, ValueError) as exc:
                raise ValueError(f"bad value for parameter {f.name!r}: {v!r}") from exc
        cfg.objective = _OBJECTIVE_ALIASES.get(str(cfg.objective), str(cfg.objective))
        if str(params.get("objective", "")).lower() in ("l2_root", "root_mean_squared_error", "rmse"):
            cfg.reg_sqrt = True
        cfg._apply_seed()
        cfg._check_conflicts()
        return cfg

    def _apply_seed(self) -> None:
        # reference: Config seed re-derives sub-seeds deterministically
        if self.seed is not None:
            base = int(self.seed)
            if "bagging_seed" not in self.raw:
                self.bagging_seed = base + 3
            if "feature_fraction_seed" not in self.raw:
                self.feature_fraction_seed = base + 2
            if "drop_seed" not in self.raw:
                self.drop_seed = base + 4
            if "data_random_seed" not in self.raw:
                self.data_random_seed = base + 1
            if "extra_seed" not in self.raw:
                self.extra_seed = base + 6
            if "objective_seed" not in self.raw:
                self.objective_seed = base + 5

    def _check_conflicts(self) -> None:
        # reference: Config::CheckParamConflict (src/io/config.cpp:346)
        if self.num_machines <= 1 and self.tree_learner in ("feature", "data", "voting"):
            # single machine: parallel learners degrade to sharded-on-one-mesh;
            # keep the learner (on TPU "data" means mesh-sharded, still valid
            # with a 1..N device mesh), so no forced downgrade here.
            pass
        if self.is_unbalance and self.scale_pos_weight != 1.0:
            raise ValueError("cannot set both is_unbalance and scale_pos_weight")
        if self.objective in ("multiclass", "multiclassova") and self.num_class < 2:
            raise ValueError(f"objective {self.objective} requires num_class >= 2")
        if self.num_leaves < 2:
            raise ValueError("num_leaves must be >= 2")
        if self.max_bin < 2:
            raise ValueError("max_bin must be >= 2")
        if self.leaf_batch < 1:
            raise ValueError("leaf_batch must be >= 1")
        if self.grow_fused not in ("auto", "on", "off"):
            raise ValueError("grow_fused must be one of 'auto', 'on', 'off'")
        if self.pred_engine not in ("walk", "matmul", "auto"):
            raise ValueError(
                "pred_engine must be one of 'walk', 'matmul', 'auto'"
            )
        if self.hist_acc not in ("auto", "int8", "bf16"):
            raise ValueError("hist_acc must be one of 'auto', 'int8', 'bf16'")
        if self.mesh_layout not in ("auto", "data", "feature", "hybrid"):
            raise ValueError(
                "mesh_layout must be one of 'auto', 'data', 'feature', "
                "'hybrid'"
            )
        if self.overlap_collectives not in ("auto", "on", "off"):
            raise ValueError(
                "overlap_collectives must be one of 'auto', 'on', 'off'"
            )
        if self.hist_near_tie_tol < 0.0:
            raise ValueError("hist_near_tie_tol must be >= 0")
        if self.train_steps_per_launch != "auto":
            try:
                n = int(self.train_steps_per_launch)
            except (TypeError, ValueError):
                raise ValueError(
                    "train_steps_per_launch must be 'auto' or an integer >= 1"
                )
            if n < 1:
                raise ValueError(
                    "train_steps_per_launch must be 'auto' or an integer >= 1"
                )
            self.train_steps_per_launch = n
        if not (0.0 <= self.leaf_batch_min_commit_rate <= 1.0):
            raise ValueError("leaf_batch_min_commit_rate must be in [0, 1]")
        if self.checkpoint_interval < 0:
            raise ValueError("checkpoint_interval must be >= 0 (0 disables)")
        if self.checkpoint_interval > 0 and not self.checkpoint_dir:
            raise ValueError(
                "checkpoint_interval > 0 requires checkpoint_dir to be set"
            )
        if self.checkpoint_keep < 0:
            raise ValueError("checkpoint_keep must be >= 0 (0 keeps all)")
        if self.ingest_chunk_rows < 0:
            raise ValueError(
                "ingest_chunk_rows must be >= 0 (0 = one-shot construction)"
            )
        if not (0 <= self.obs_export_port <= 65535):
            raise ValueError(
                "obs_export_port must be in [0, 65535] (0 disables)"
            )
        if self.serve_deadline_ms <= 0:
            raise ValueError("serve_deadline_ms must be > 0")
        if self.serve_max_batch < 1:
            raise ValueError("serve_max_batch must be >= 1")
        if self.serve_memory_budget_mb < 0:
            raise ValueError(
                "serve_memory_budget_mb must be >= 0 (0 = unlimited)"
            )
        if not (-1 <= self.serve_port <= 65535):
            raise ValueError(
                "serve_port must be in [-1, 65535] (0 disables, -1 ephemeral)"
            )
        if self.flight_capacity < 32:
            raise ValueError(
                "flight_capacity must be >= 32 (the dump-on-fault contract "
                "promises the last 32 iteration events)"
            )
        if self.trace_capacity < 64:
            raise ValueError(
                "trace_capacity must be >= 64 (one training iteration or "
                "serving flush records several spans)"
            )
        if not (0.0 <= self.trace_sample <= 1.0):
            raise ValueError("trace_sample must be in [0, 1]")
        if self.bagging_freq > 0 and (self.pos_bagging_fraction < 1.0 or self.neg_bagging_fraction < 1.0):
            if self.objective != "binary":
                raise ValueError("pos/neg bagging fractions require binary objective")
        if (
            self.monotone_constraints_method == "advanced"
            and self.monotone_constraints
            # all-zero constraints never build adv planes (train-time gate)
            and any(v != 0 for v in self.monotone_constraints)
            and self.max_bin > 256
        ):
            # adv_planes materializes [refresh_batch, num_leaves, F, B]
            # slice masks; B > 256 puts that in the tens of GB
            raise ValueError(
                "monotone_constraints_method='advanced' supports max_bin <= "
                "256 (the per-threshold bound planes scale with num_leaves x "
                "num_features x max_bin); use method='intermediate' or lower "
                "max_bin"
            )

    @property
    def num_tree_per_iteration(self) -> int:
        if self.objective in ("multiclass", "multiclassova"):
            return self.num_class
        return 1

    def default_metric(self) -> List[str]:
        obj = self.objective
        table = {
            "regression": ["l2"],
            "regression_l1": ["l1"],
            "huber": ["huber"],
            "fair": ["fair"],
            "poisson": ["poisson"],
            "quantile": ["quantile"],
            "mape": ["mape"],
            "gamma": ["gamma"],
            "tweedie": ["tweedie"],
            "binary": ["binary_logloss"],
            "multiclass": ["multi_logloss"],
            "multiclassova": ["multi_logloss"],
            "cross_entropy": ["cross_entropy"],
            "cross_entropy_lambda": ["cross_entropy_lambda"],
            "lambdarank": ["ndcg"],
            "rank_xendcg": ["ndcg"],
        }
        return table.get(obj, [])


def canonical_objective(name: str) -> str:
    return _OBJECTIVE_ALIASES.get(name, name)
