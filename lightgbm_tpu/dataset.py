"""Dataset: binned training data + metadata, resident on device.

Reference analogs: ``Dataset``/``Metadata`` (include/LightGBM/dataset.h:487,
:48, src/io/dataset.cpp), ``DatasetLoader`` (src/io/dataset_loader.cpp).

TPU-first design: instead of per-feature Bin column objects with col-wise /
row-wise layout heuristics (reference dataset.cpp:619 GetShareStates), the
whole dataset is ONE dense ``[num_rows, num_planes]`` uint8/uint16 device
array of bin indices.  Binning happens host-side in NumPy at construction
from a row sample (reference bin_construct_sample_cnt), then the binned
matrix is pushed to HBM once.

Exclusive Feature Bundling (EFB, reference dataset.cpp FindGroups /
FastFeatureBundling): with ``enable_bundle`` (default true), mutually
exclusive sparse columns share one bin plane — plane bin 0 is the shared
all-default bin and each member owns a contiguous sub-range (bundling.py).
Wide one-hot data then trains with #bundles planes instead of #columns,
which is both the histogram-volume win and what keeps the dense [N, P]
layout viable at 50k+ columns.  Dense data never bundles (eligibility in
bundling.py), so its bin matrix stays byte-identical to the unbundled form.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Any, Dict, List, Optional, Union
from typing import Sequence as TypingSequence

import numpy as np

from .binning import BinMapper
from .config import Config

try:  # pandas is optional
    import pandas as pd  # type: ignore
except Exception:  # pragma: no cover
    pd = None


def _is_1d(a: np.ndarray) -> np.ndarray:
    a = np.asarray(a)
    if a.ndim == 2 and a.shape[1] == 1:
        a = a.ravel()
    if a.ndim != 1:
        raise ValueError(f"expected 1-D array, got shape {a.shape}")
    return a


def _check_label_finite(label: np.ndarray) -> None:
    """Eager NaN/inf label validation (resilience guard rail): a poisoned
    label would otherwise surface many iterations later as NaN gradients
    (or, worse, silently as a degenerate model).  Fail at construction
    with the offending row."""
    bad = ~np.isfinite(label)
    if bad.any():
        first = int(np.argmax(bad))
        raise ValueError(
            f"label contains {int(bad.sum())} non-finite value(s) "
            f"(NaN/inf); first at row {first} "
            f"(value={label[first]!r})"
        )


@dataclasses.dataclass
class Metadata:
    """Per-row metadata (reference: include/LightGBM/dataset.h:48)."""

    label: np.ndarray
    weight: Optional[np.ndarray] = None
    init_score: Optional[np.ndarray] = None
    query_boundaries: Optional[np.ndarray] = None  # [num_queries+1] int32
    position: Optional[np.ndarray] = None

    @property
    def num_data(self) -> int:
        return len(self.label)

    def set_query(self, group_sizes: np.ndarray) -> None:
        group_sizes = _is_1d(group_sizes).astype(np.int64)
        boundaries = np.zeros(len(group_sizes) + 1, dtype=np.int32)
        np.cumsum(group_sizes, out=boundaries[1:])
        if boundaries[-1] != self.num_data:
            raise ValueError(
                f"sum of query sizes ({boundaries[-1]}) != num_data ({self.num_data})"
            )
        self.query_boundaries = boundaries


class Sequence:
    """Generic row-batched data source (reference: basic.py Sequence, the
    out-of-core ingestion ABC). Subclasses implement __getitem__ (row or
    slice -> numpy rows) and __len__; Dataset materializes in
    ``batch_size`` chunks at construction."""

    batch_size = 4096

    def __getitem__(self, idx):  # pragma: no cover - abstract
        raise NotImplementedError("Sub-classes of Sequence must implement __getitem__")

    def __len__(self) -> int:  # pragma: no cover - abstract
        raise NotImplementedError("Sub-classes of Sequence must implement __len__")


def _materialize_sequences(seqs) -> np.ndarray:
    parts = []
    for seq in seqs:
        n = len(seq)
        bs = getattr(seq, "batch_size", None) or 4096
        for start in range(0, n, bs):
            parts.append(np.asarray(seq[slice(start, min(start + bs, n))]))
    return np.concatenate(parts, axis=0)


def _parse_libsvm(lines, path: str) -> Dict[str, Any]:
    """LibSVM text parser (reference: LibSVMParser, src/io/parser.hpp:136):
    ``label [qid:q] idx:val idx:val ...`` -> CSR matrix, never densified."""
    import scipy.sparse as sp

    labels: List[float] = []
    rows: List[int] = []
    cols: List[int] = []
    vals: List[float] = []
    qids: List[int] = []
    r = 0
    for ln in lines:
        parts = ln.split()
        if not parts:
            continue
        labels.append(float(parts[0]))
        for tok in parts[1:]:
            k, v = tok.split(":", 1)
            if k == "qid":
                qids.append(int(v))
                continue
            rows.append(r)
            cols.append(int(k))
            vals.append(float(v))
        r += 1
    ncol = max(cols) + 1 if cols else 1
    csr = sp.csr_matrix(
        (np.asarray(vals), (np.asarray(rows), np.asarray(cols))),
        shape=(r, ncol),
    )
    out: Dict[str, Any] = {"data": csr, "label": np.asarray(labels)}
    if len(qids) == r and r > 0:
        # consecutive qid runs -> group sizes (reference parses qid the same
        # way its query file does)
        q = np.asarray(qids)
        change = np.nonzero(np.diff(q))[0] + 1
        bounds = np.concatenate([[0], change, [r]])
        out["group"] = np.diff(bounds)
    qpath = Path(str(path) + ".query")
    if qpath.exists():
        out["group"] = np.loadtxt(qpath, dtype=np.int64, ndmin=1)
    wpath = Path(str(path) + ".weight")
    if wpath.exists():
        out["weight"] = np.loadtxt(wpath, dtype=np.float64, ndmin=1)
    return out


def _is_arrow(data) -> bool:
    """True for pyarrow Table/RecordBatch (duck-typed so pyarrow stays an
    optional dependency, like the reference's header-only arrow ingestion,
    include/LightGBM/arrow.h)."""
    t = type(data).__module__
    return t.startswith("pyarrow") and hasattr(data, "schema") and hasattr(
        data, "column"
    )


def _arrow_to_numpy(data, category_maps=None):
    """pyarrow Table/RecordBatch -> (float64 matrix with nulls as NaN,
    feature names, categorical column names, category_maps).

    Reference analog: the Arrow C-data ingestion
    (include/LightGBM/arrow.h + c_api LGBM_DatasetCreateFromArrow) — numeric
    and boolean columns bin as floats, dictionary-encoded columns become
    categorical features via integer codes.  Codes are made STABLE across
    tables the way the reference's ``pandas_categorical`` remap is: the
    training call records each column's dictionary values in
    ``category_maps``; later tables (predict) remap their codes through the
    recorded value order, and unseen categories become NaN (routed like
    missing, matching the reference's unseen-category handling)."""
    import pyarrow as pa  # deferred; _is_arrow guaranteed pyarrow is loaded

    if isinstance(data, pa.RecordBatch):
        data = pa.Table.from_batches([data])
    data = data.combine_chunks()
    names = [str(c) for c in data.schema.names]
    record = category_maps is None
    if record:
        category_maps = {}
    cats = []
    cols = []
    for i, field in enumerate(data.schema):
        col = data.column(i)
        name = names[i]
        if pa.types.is_dictionary(field.type):
            cats.append(name)
            cc = col.combine_chunks()
            values = [v.as_py() for v in cc.dictionary]
            codes = cc.indices.to_numpy(zero_copy_only=False).astype(np.float64)
            mask = col.is_null().to_numpy(zero_copy_only=False)
            if record:
                category_maps[name] = values
            else:
                train_vals = category_maps.get(name)
                if train_vals is not None and train_vals != values:
                    # remap this table's codes onto the TRAIN dictionary
                    # order; unseen categories -> NaN (missing)
                    lut = {v: float(j) for j, v in enumerate(train_vals)}
                    remap = np.array(
                        [lut.get(v, np.nan) for v in values] or [np.nan]
                    )
                    # null slots surface as NaN indices: substitute 0 before
                    # indexing (the null mask overwrites them below anyway)
                    safe_idx = np.clip(
                        np.nan_to_num(codes, nan=0.0), 0, len(values) - 1
                    ).astype(np.int64)
                    codes = remap[safe_idx]
            arr = np.where(mask, np.nan, codes)
        elif pa.types.is_boolean(field.type) or pa.types.is_floating(
            field.type
        ) or pa.types.is_integer(field.type):
            arr = col.to_numpy(zero_copy_only=False).astype(np.float64)
        else:
            raise ValueError(
                f"Arrow column {name!r} has unsupported type "
                f"{field.type} (numeric, boolean, or dictionary expected)"
            )
        cols.append(arr)
    mat = (
        np.stack(cols, axis=1)
        if cols
        else np.zeros((data.num_rows, 0), np.float64)
    )
    return mat, names, cats, category_maps


def _is_cat_dtype(dtype) -> bool:
    """Column dtypes that carry non-numeric category values: classic
    object/category plus pandas 2.x (arrow-backed) string dtypes."""
    s = str(dtype)
    return s in ("category", "object", "str") or s.startswith(
        ("string", "large_string")
    )


def _pandas_to_numpy(df, category_maps=None):
    """DataFrame -> (float64 matrix with NaN missing, categorical column
    names, category_maps).

    category/object columns become float codes through a recorded category
    order, exactly like the reference's ``pandas_categorical`` machinery
    (python-package/lightgbm/basic.py ``_data_from_pandas``): the training
    call records each column's category values; later frames (valid sets,
    predict) remap their values through the recorded order and unseen
    categories become NaN (routed like missing)."""
    import pandas as pd  # caller guaranteed pandas is importable

    record = category_maps is None
    if record:
        category_maps = {}
    cats: List[str] = []
    cols = []
    for name in df.columns:
        col = df[name]
        sname = str(name)
        if _is_cat_dtype(col.dtype):
            cats.append(sname)
            cc = col.astype("category")
            if not record and category_maps and sname not in category_maps:
                # this column was NOT categorical at train time — its codes
                # would be this frame's own arbitrary order (reference:
                # "train and valid dataset categorical_feature do not match")
                raise ValueError(
                    f"column {sname!r} is categorical but the train-time "
                    "category record has no entry for it (categorical "
                    "features must match between train and later frames)"
                )
            if record and sname not in category_maps:
                # native python values (np.int64 -> int, …) so the maps
                # survive a JSON model-file round trip without stringifying
                category_maps[sname] = [
                    v.item() if hasattr(v, "item") else v
                    for v in cc.cat.categories
                ]
            train_vals = category_maps.get(sname)
            if train_vals is not None and list(cc.cat.categories) != list(
                train_vals
            ):
                cc = cc.cat.set_categories(train_vals)
            codes = cc.cat.codes.to_numpy().astype(np.float64)
            codes[codes < 0] = np.nan  # pandas NaN / unseen category -> -1
            cols.append(codes)
        else:
            cols.append(col.to_numpy(dtype=np.float64, na_value=np.nan))
    mat = (
        np.column_stack(cols)
        if cols
        else np.zeros((len(df), 0), np.float64)
    )
    return mat, cats, category_maps


def _arrow_column_to_numpy(arr):
    """A pyarrow Array/ChunkedArray — or single-column Table/RecordBatch —
    as a 1-D numpy array (labels/weights)."""
    import pyarrow as pa

    if isinstance(arr, (pa.Table, pa.RecordBatch)):
        if arr.num_columns != 1:
            raise ValueError(
                f"expected a single-column Arrow table for a label/weight, "
                f"got {arr.num_columns} columns"
            )
        arr = arr.column(0)
    return arr.to_numpy(zero_copy_only=False)


def _is_binary_dataset_file(path: str) -> bool:
    """True when ``path`` is a lightgbm_tpu binary dataset (pickle with our
    format marker in the first bytes) — the reference's binary-magic check
    (dataset_loader.cpp LoadFromBinFile)."""
    try:
        with open(path, "rb") as fh:
            head = fh.read(64)
    except OSError:
        return False
    return head[:1] == b"\x80" and b"lightgbm_tpu.dataset.v1" in head


def _label_column_index(config: Config, header_line: Optional[str]) -> int:
    """Resolve label_column to a 0-based index: plain int, ``column=N``,
    or the reference's ``name:<colname>`` form (needs the header line)."""
    if config.label_column in ("", None):
        return 0
    lc = str(config.label_column)
    if lc.startswith("name:"):
        name = lc[len("name:"):]
        if not header_line:
            raise ValueError(
                "label_column='name:...' requires header=true so the column "
                "name can be resolved"
            )
        delim = "\t" if "\t" in header_line else ","
        names = [t.strip() for t in header_line.split(delim)]
        if name not in names:
            raise ValueError(
                f"label_column names {name!r} but the header has {names}"
            )
        return names.index(name)
    return int(lc.split("=")[-1]) if "=" in lc else int(lc)


def _resolve_data_columns(
    spec, header_line: Optional[str], label_col: int, what: str
) -> List[int]:
    """Resolve a weight/group/ignore column spec to RAW file-column indices
    (reference DatasetLoader::SetHeader, src/io/dataset_loader.cpp:111-160):
    integer indices do NOT count the label column; ``name:a,b`` forms need
    ``header=true`` and resolve against the header names."""
    if spec in ("", None):
        return []
    s = str(spec)
    if s.startswith("name:"):
        if not header_line:
            raise ValueError(
                f"{what}='name:...' requires header=true so column names "
                "can be resolved"
            )
        delim = "\t" if "\t" in header_line else ","
        names = [t.strip() for t in header_line.split(delim)]
        out = []
        for nm in s[len("name:"):].split(","):
            nm = nm.strip()
            if nm == "":
                continue
            if nm not in names:
                raise ValueError(f"{what} names {nm!r} but the header has {names}")
            out.append(names.index(nm))
        return out
    out = []
    for tok in s.split(","):
        tok = tok.strip()
        if tok == "":
            continue
        idx = int(tok)
        # "doesn't count the label column": data column i is raw column
        # i when i < label_col, else i + 1
        out.append(idx if idx < label_col else idx + 1)
    return out


def _extract_column_fields(
    arr: np.ndarray, config: Config, header_line: Optional[str], label_col: int
) -> Dict[str, Any]:
    """weight_column / group_column / ignore_column extraction for the dense
    text path (reference dataset_loader.cpp:111-160).  Extracted columns
    REMAIN in the feature matrix but are marked ignored (trivial mappers),
    preserving the reference's original feature numbering in models."""
    out: Dict[str, Any] = {}
    ignore_raw: List[int] = []
    wcols = _resolve_data_columns(
        config.weight_column, header_line, label_col, "weight_column"
    )
    if wcols:
        out["weight"] = arr[:, wcols[0]].astype(np.float64)
        ignore_raw += wcols[:1]
    gcols = _resolve_data_columns(
        config.group_column, header_line, label_col, "group_column"
    )
    if gcols:
        # the group column holds per-row query ids; consecutive runs become
        # query sizes (reference Metadata::SetQueryId)
        q = arr[:, gcols[0]].astype(np.int64)
        change = np.nonzero(np.diff(q))[0] + 1
        bounds = np.concatenate([[0], change, [len(q)]])
        out["group"] = np.diff(bounds)
        ignore_raw += gcols[:1]
    ignore_raw += _resolve_data_columns(
        config.ignore_column, header_line, label_col, "ignore_column"
    )
    if ignore_raw:
        # raw file column -> feature index after the label column is removed
        out["ignore"] = sorted(
            {c - (1 if c > label_col else 0) for c in ignore_raw
             if c != label_col}
        )
    return out


def _attach_sidecars(out: Dict[str, Any], path: str) -> Dict[str, Any]:
    """Load the reference's sidecar files (train.txt.query/.weight/.init)
    next to any text data file (reference Metadata::LoadQueryBoundaries)."""
    qpath = Path(str(path) + ".query")
    if qpath.exists() and "group" not in out:
        # an explicit group_column wins over the sidecar
        out["group"] = np.loadtxt(qpath, dtype=np.int64, ndmin=1)
    wpath = Path(str(path) + ".weight")
    if wpath.exists() and "weight" not in out:
        out["weight"] = np.loadtxt(wpath, dtype=np.float64, ndmin=1)
    ipath = Path(str(path) + ".init")
    if ipath.exists():
        out["init_score"] = np.loadtxt(ipath, dtype=np.float64, ndmin=1)
    ppath = Path(str(path) + ".position")
    if ppath.exists():
        # result positions for unbiased lambdarank (reference
        # Metadata::LoadPositions, src/io/metadata.cpp:663); string
        # position ids map to dense codes like the reference's
        # position_ids_
        raw = [
            ln.strip() for ln in ppath.read_text().splitlines() if ln.strip()
        ]
        try:
            out["position"] = np.asarray([int(v) for v in raw], np.int32)
        except (ValueError, OverflowError):
            ids = sorted(set(raw))
            code = {v: i for i, v in enumerate(ids)}
            out["position"] = np.asarray([code[v] for v in raw], np.int32)
    return out


def _is_libsvm_row(ln: str) -> bool:
    toks = ln.replace(",", " ").split()
    return len(toks) > 1 and ":" in toks[1]


def _load_text_file(path: str, config: Config) -> Dict[str, Any]:
    """Parse a CSV/TSV/LibSVM training file (reference src/io/parser.cpp);
    LibSVM rows load into a CSR matrix (sparse path), dense CSV/TSV into a
    float matrix. Label column defaults to 0 as in the reference CLI."""
    p = Path(path)
    text = p.read_text()
    lines = text.splitlines()
    skip = 1 if config.header else 0
    header_line = lines[0] if (config.header and lines) else None
    if config.parser_config_file:
        # custom parser plugin (Parser::CreateParser's add-on dispatch,
        # src/io/parser.cpp:288): className routes lines through a
        # registered Python parser; the config str persists with the
        # dataset like the reference's parser_config_str_
        from .parser import create_parser, generate_parser_config_str

        pcs = generate_parser_config_str(
            config.parser_config_file, config.header,
            _label_column_index(config, header_line),
        )
        parse_line = create_parser(pcs)
        if parse_line is not None:
            labels, rows = [], []
            max_col = -1
            for ln in lines[skip:]:
                if not ln.strip():
                    continue
                feats, lab = parse_line(ln)
                labels.append(float(lab))
                rows.append(list(feats))
            # decide sparse from ANY row, not the first (a legal label-only
            # row parses to []); mixed outputs normalize to pairs
            sparse = any(r and isinstance(r[0], tuple) for r in rows)
            if sparse:
                rows = [
                    r if (not r or isinstance(r[0], tuple))
                    else list(enumerate(r))
                    for r in rows
                ]
                for r in rows:
                    for ci, _ in r:
                        max_col = max(max_col, int(ci))
            else:
                for r in rows:
                    max_col = max(max_col, len(r) - 1)
            n, f = len(rows), max_col + 1
            if sparse:
                try:
                    import scipy.sparse as sp
                except Exception as exc:  # pragma: no cover
                    raise ValueError(
                        "custom parser returned sparse rows but scipy is "
                        "unavailable"
                    ) from exc
                data_v, indices, indptr = [], [], [0]
                for feats in rows:
                    for ci, v in feats:
                        indices.append(int(ci))
                        data_v.append(float(v))
                    indptr.append(len(indices))
                mat = sp.csr_matrix(
                    (data_v, indices, indptr), shape=(n, f)
                )
                out = {"data": mat, "label": np.asarray(labels)}
            else:
                dense = np.zeros((n, f), np.float64)
                for i, feats in enumerate(rows):
                    dense[i, : len(feats)] = feats
                out = {"data": dense, "label": np.asarray(labels)}
            out["parser_config_str"] = pcs
            return _attach_sidecars(out, path)
    # scan a few rows: a leading label-only line is legal LibSVM (all-zero
    # sample), so one line is not enough to decide the format
    probe = [ln for ln in lines[skip:] if ln.strip()][:20]
    if probe and any(_is_libsvm_row(ln) for ln in probe):
        return _parse_libsvm(lines[skip:], path)
    first = lines[0] if lines else ""
    delim = "\t" if "\t" in first else ("," if "," in first else None)
    arr = np.loadtxt(path, delimiter=delim, skiprows=skip, dtype=np.float64, ndmin=2)
    label_col = _label_column_index(config, header_line)
    label = arr[:, label_col]
    feats = np.delete(arr, label_col, axis=1)
    out: Dict[str, Any] = {"data": feats, "label": label}
    out.update(_extract_column_fields(arr, config, header_line, label_col))
    return _attach_sidecars(out, path)


class Dataset:
    """Binned dataset (reference: Dataset, include/LightGBM/dataset.h:487).

    Lazily constructed like the python-package Dataset (basic.py:1744): raw
    data is held until ``construct()`` bins it (or bins are inherited from a
    reference dataset for validation sets).
    """

    def __init__(
        self,
        data: Union[np.ndarray, str, "pd.DataFrame", None],
        label: Optional[np.ndarray] = None,
        *,
        reference: Optional["Dataset"] = None,
        weight: Optional[np.ndarray] = None,
        group: Optional[np.ndarray] = None,
        init_score: Optional[np.ndarray] = None,
        feature_name: Union[str, TypingSequence[str]] = "auto",
        categorical_feature: Union[str, TypingSequence] = "auto",
        params: Optional[Dict[str, Any]] = None,
        free_raw_data: bool = True,
        position: Optional[np.ndarray] = None,
    ) -> None:
        self.params: Dict[str, Any] = dict(params or {})
        self.config = Config.from_params(self.params)
        self._raw_data = data
        self._label = label
        self._weight = weight
        self._group = group
        self._init_score = init_score
        self._position = position
        self._feature_name = feature_name
        self._categorical_feature = categorical_feature
        self.reference = reference
        self.free_raw_data = free_raw_data

        # filled by construct()
        self._constructed = False
        self.bin_mappers: List[BinMapper] = []
        self.used_features: List[int] = []  # original feature idx per used column
        # EFB plane layout (bundling.py), or None for the identity layout
        # (bins column ci <=> used_features[ci])
        self.bundle_layout = None
        self._ignore_set: set = set()  # ignore_column / weight_column / group_column
        self.bins: Optional[np.ndarray] = None  # [N, num_planes] uint8/uint16
        self.raw: Optional[np.ndarray] = None  # raw values (for linear trees / predict checks)
        self.metadata: Optional[Metadata] = None
        self.feature_names: List[str] = []
        self.num_total_features: int = 0
        self.arrow_categories: Optional[Dict[str, list]] = None
        self.pandas_categorical: Optional[Dict[str, list]] = None
        self._device_cache: Dict[str, Any] = {}

    # ----------------------------------------------------------- properties
    @property
    def num_data(self) -> int:
        self.construct()
        return int(self.bins.shape[0])

    @property
    def num_feature(self) -> int:
        """Number of original (pre-pruning) features, like the reference."""
        self.construct()
        return self.num_total_features

    @property
    def num_used_feature(self) -> int:
        self.construct()
        return int(self.bins.shape[1])

    def num_bins_per_feature(self) -> np.ndarray:
        self.construct()
        return np.array([self.bin_mappers[i].num_bins for i in self.used_features], dtype=np.int32)

    # -------------------------------------------------- plane-space accessors
    # The trainer consumes bins COLUMN-wise; with EFB a column is a bundle
    # plane, without it a used feature (identity).  These return per-column
    # arrays either way (boosting/gbdt.py builds its device operands here).
    @property
    def num_planes(self) -> int:
        self.construct()
        return int(self.bins.shape[1])

    def plane_num_bins(self) -> np.ndarray:
        self.construct()
        if self.bundle_layout is not None:
            return np.asarray(self.bundle_layout.plane_bins, dtype=np.int32)
        return self.num_bins_per_feature()

    def plane_nan_bins(self) -> np.ndarray:
        self.construct()
        if self.bundle_layout is None:
            return np.array(
                [self.bin_mappers[j].nan_bin for j in self.used_features],
                dtype=np.int32,
            )
        # bundle planes never carry a NaN bin (bundling eligibility)
        return np.array(
            [
                self.bin_mappers[feats[0]].nan_bin if len(feats) == 1 else -1
                for feats in self.bundle_layout.planes
            ],
            dtype=np.int32,
        )

    def plane_is_cat(self) -> np.ndarray:
        self.construct()
        if self.bundle_layout is None:
            return np.array(
                [self.bin_mappers[j].is_categorical for j in self.used_features],
                dtype=bool,
            )
        return np.array(
            [
                len(feats) == 1 and self.bin_mappers[feats[0]].is_categorical
                for feats in self.bundle_layout.planes
            ],
            dtype=bool,
        )

    # ------------------------------------------------------------ construct
    def construct(self) -> "Dataset":
        if self._constructed:
            return self
        from .utils.timer import global_timer

        with global_timer.timed("dataset/construct"):
            return self._construct_inner()

    def _construct_inner(self) -> "Dataset":
        from .utils.timer import global_timer

        data = self._raw_data
        label = self._label
        if isinstance(data, (str, Path)) and _is_binary_dataset_file(str(data)):
            # binary dataset auto-detection (reference: DatasetLoader checks
            # the binary magic before falling back to the text parsers,
            # src/io/dataset_loader.cpp LoadFromBinFile)
            if self.reference is not None:
                raise ValueError(
                    "a binary dataset carries its own bin mappers and "
                    "cannot be re-binned against a reference dataset; "
                    "construct the validation set from the raw data file, "
                    "or save the binary from a Dataset built with "
                    "reference= so its bins already match"
                )
            # explicitly passed per-row fields override the pickled ones
            keep = {
                "label": self._label,
                "weight": self._weight,
                "group": self._group,
                "init_score": self._init_score,
                "position": self._position,
            }
            loaded_ds = Dataset.load_binary(str(data), params=self.params)
            self.__dict__.update(loaded_ds.__dict__)
            self._constructed = True
            for name, val in keep.items():
                if val is not None:
                    self.set_field(name, val)
            return self
        # ---- out-of-core streaming ingest (lightgbm_tpu/ingest): two-pass
        # chunked construction whenever the data is chunk-iterable (the
        # explicit out-of-core API) or ingest_chunk_rows is set.  Bins,
        # bundle layout and the downstream model are byte-identical to the
        # one-shot path; the raw float64 matrix never materializes.
        streamed = self._maybe_construct_streamed(data, label)
        if streamed is not None:
            return streamed
        if data is not None and not isinstance(data, (str, Path)):
            from .ingest.sources import materialize_chunks

            data = materialize_chunks(data)
        if isinstance(data, (str, Path)):
            loaded = _load_text_file(str(data), self.config)
            data = loaded["data"]
            self.parser_config_str = loaded.get("parser_config_str", "")
            self._ignore_set = set(loaded.get("ignore", []))
            if label is None:
                label = loaded.get("label")
            if self._group is None:
                self._group = loaded.get("group")
            if self._weight is None:
                self._weight = loaded.get("weight")
            if self._init_score is None:
                self._init_score = loaded.get("init_score")
            if self._position is None:
                self._position = loaded.get("position")
        if isinstance(data, Sequence):
            data = _materialize_sequences([data])
        elif isinstance(data, list) and data and all(
            isinstance(d, Sequence) for d in data
        ):
            data = _materialize_sequences(data)
        if _is_arrow(data):
            # reuse a reference dataset's dictionaries so valid sets bin
            # categories consistently with the train set
            ref_maps = getattr(
                self.reference, "arrow_categories", None
            ) or getattr(self.reference, "pandas_categorical", None)
            data, names, cats, self.arrow_categories = _arrow_to_numpy(
                data, ref_maps
            )
            if self._feature_name == "auto" and names is not None:
                self._feature_name = names
            if self._categorical_feature == "auto":
                self._categorical_feature = cats
        if label is not None and type(label).__module__.startswith("pyarrow"):
            label = _arrow_column_to_numpy(label)
        if pd is not None and isinstance(data, pd.DataFrame):
            if self._feature_name == "auto":
                self._feature_name = [str(c) for c in data.columns]
            # category/object columns -> stable float codes; valid sets reuse
            # the train set's recorded category order (reference:
            # pandas_categorical in basic.py _data_from_pandas)
            ref_maps = getattr(
                self.reference, "pandas_categorical", None
            ) or getattr(self.reference, "arrow_categories", None)
            data, cats, self.pandas_categorical = _pandas_to_numpy(
                data, ref_maps
            )
            if self._categorical_feature == "auto":
                self._categorical_feature = cats
        if data is None:
            raise ValueError("Dataset has no data")
        sparse_csc = None
        if hasattr(data, "tocsc") and hasattr(data, "nnz"):
            # scipy CSR/CSC (reference: Dataset::CreateFromCSR, c_api.cpp +
            # SparseBin construction, src/io/sparse_bin.hpp): bin directly
            # from the sparse columns — the dense FLOAT matrix is never
            # materialized; only the uint8/16 bin matrix is (zeros fill each
            # feature's zero bin, nonzeros scatter their bins)
            sparse_csc = data.tocsc()
            n, num_features = sparse_csc.shape
        else:
            data = np.asarray(data, dtype=np.float64)
            if data.ndim != 2:
                raise ValueError(f"data must be 2-D, got shape {data.shape}")
            n, num_features = data.shape
        self.num_total_features = num_features

        if label is None:
            raise ValueError("label is required to construct a Dataset")
        label = _is_1d(np.asarray(label, dtype=np.float64))
        if len(label) != n:
            raise ValueError(f"label length {len(label)} != num rows {n}")
        _check_label_finite(label)

        if isinstance(self._feature_name, str):
            self.feature_names = [f"Column_{i}" for i in range(num_features)]
        else:
            self.feature_names = [str(s) for s in self._feature_name]

        cat_idx = self._resolve_categorical(num_features)

        if self.reference is not None:
            ref = self.reference.construct()
            self.bin_mappers = ref.bin_mappers
            self.used_features = ref.used_features
            self.bundle_layout = getattr(ref, "bundle_layout", None)
            self.feature_names = ref.feature_names
            self.num_total_features = ref.num_total_features
            if sparse_csc is not None and sparse_csc.shape[1] < self.num_total_features:
                # a sparse file may simply lack the highest-index features
                # (LibSVM row widths vary); missing columns are all-zero.
                # copy first: tocsc() on a csc_matrix aliases the caller's
                # object and resize() would mutate it
                sparse_csc = sparse_csc.copy()
                sparse_csc.resize(n, self.num_total_features)
        elif sparse_csc is not None:
            with global_timer.timed("dataset/bin_fit"):
                self._build_bin_mappers_sparse(sparse_csc, cat_idx)
        else:
            with global_timer.timed("dataset/bin_fit"):
                self._build_bin_mappers(data, cat_idx)
        self._sync_mappers_across_processes()

        # ---- EFB (reference dataset.cpp FindGroups): bundle mutually
        # exclusive sparse columns into shared planes BEFORE the footprint
        # check — bundling is exactly what makes sparse-wide data fit the
        # dense plane layout.  Validation sets inherit the reference layout
        # above so planes bin identically.
        if self.reference is None and self.config.enable_bundle \
                and self._bundling_allowed():
            with global_timer.timed("dataset/bundle"):
                self.bundle_layout = self._find_bundle_layout(
                    data, sparse_csc, n
                )
        layout = self.bundle_layout
        if layout is not None:
            max_bins = max(layout.plane_bins)
            n_cols = layout.num_planes
        else:
            max_bins = max((m.num_bins for m in self.bin_mappers), default=1)
            n_cols = len(self.used_features)
        dtype = np.uint8 if max_bins <= 256 else np.uint16
        self._check_binned_footprint(n, n_cols, np.dtype(dtype).itemsize)
        if sparse_csc is not None:
            binned = np.zeros((n, n_cols), dtype=dtype)
            for ci, j in enumerate(self.used_features):
                mapper = self.bin_mappers[j]
                sl = slice(sparse_csc.indptr[j], sparse_csc.indptr[j + 1])
                if layout is None:
                    p, bundled = ci, False
                else:
                    p, k = layout.feature_position(j)
                    bundled = layout.is_bundle(p)
                if not bundled:
                    zb = mapper.values_to_bins(np.zeros(1))[0]
                    if zb:
                        binned[:, p] = zb
                    binned[sparse_csc.indices[sl], p] = mapper.values_to_bins(
                        sparse_csc.data[sl]
                    ).astype(dtype)
                else:
                    # bundle member: non-default bins land at start + b - 1;
                    # zeros stay in the shared plane bin 0 (default_bin == 0
                    # is a bundling-eligibility invariant)
                    local = mapper.values_to_bins(sparse_csc.data[sl])
                    layout.pack_sparse_members(
                        binned, p, k, sparse_csc.indices[sl], local
                    )
            self.bins = binned
            if self.config.linear_tree:
                raise ValueError("linear_tree is not supported for sparse input")
            # free_raw_data=False keeps the (row-sliceable) sparse matrix so
            # cv()'s fold slicing works; the dense float is still never built
            self.raw = None if self.free_raw_data else sparse_csc.tocsr()
        else:
            with global_timer.timed("dataset/pack"):
                if layout is not None:
                    binned = layout.pack_columns(
                        n,
                        lambda j: self.bin_mappers[j].values_to_bins(
                            data[:, j]
                        ),
                    )
                    self.bins = binned.astype(dtype)
                else:
                    cols = []
                    for j in self.used_features:
                        cols.append(
                            self.bin_mappers[j].values_to_bins(data[:, j])
                        )
                    if cols:
                        binned = np.stack(cols, axis=1)
                    else:
                        binned = np.zeros((n, 0), dtype=np.int32)
                    self.bins = binned.astype(dtype)
            self.raw = (
                data
                if (self.config.linear_tree or not self.free_raw_data)
                else None
            )

        weight = self._weight
        if weight is not None:
            weight = _is_1d(np.asarray(weight, dtype=np.float64))
        init_score = self._init_score
        if init_score is not None:
            init_score = np.asarray(init_score, dtype=np.float64)
        self.metadata = Metadata(label=label, weight=weight, init_score=init_score)
        if self._group is not None:
            self.metadata.set_query(np.asarray(self._group))
        if self._position is not None:
            # per-row result position for unbiased lambdarank
            # (reference Metadata::SetPosition, src/io/metadata.cpp:360)
            pos = np.asarray(self._position)
            if len(pos) != len(label):
                raise ValueError(
                    f"position length {len(pos)} != num_data {len(label)}"
                )
            self.metadata.position = pos

        self._constructed = True
        if self.free_raw_data and not self.config.linear_tree:
            self._raw_data = None
        return self

    def _maybe_construct_streamed(self, data, label) -> Optional["Dataset"]:
        """Route construction through the streaming ingest pipeline, or
        return None for the one-shot path (knob unset, unstreamable
        format, or a mode that needs the raw matrix anyway)."""
        from .ingest.sources import (
            StreamingUnsupported,
            is_chunk_iterable,
            make_chunk_source,
        )

        cfg = self.config
        chunky = is_chunk_iterable(data)
        if data is None or (not chunky and cfg.ingest_chunk_rows <= 0):
            return None
        if hasattr(data, "tocsc") and hasattr(data, "nnz"):
            # sparse input bins column-wise from CSC without ever
            # densifying — already out-of-core in the way that matters
            return None
        if cfg.linear_tree or not self.free_raw_data:
            from .utils.log import log_warning

            log_warning(
                "streaming ingest frees the raw matrix after binning; "
                "linear_tree / free_raw_data=false fall back to one-shot "
                "construction"
            )
            return None
        ref_maps = getattr(
            self.reference, "arrow_categories", None
        ) or getattr(self.reference, "pandas_categorical", None)
        try:
            source = make_chunk_source(data, cfg, ref_maps)
        except StreamingUnsupported:
            return None
        if source is None:
            return None
        return self._construct_streamed(source, label)

    def _construct_streamed(self, source, label) -> "Dataset":
        """Two-pass out-of-core construction (lightgbm_tpu/ingest): pass 1
        draws the one-shot path's exact seeded sample from chunks and fits
        bin mappers + the EFB layout on it; pass 2 streams chunks through
        binning into preallocated packed planes.  Under multi-process
        ``pre_partition`` the sample is assembled GLOBALLY
        (ingest/sharded.py), so every host fits identical mappers from its
        row shard alone."""
        from .ingest.pipeline import stream_pack
        from .ingest.sources import ArrowChunkSource, PandasChunkSource
        from .utils.timer import global_timer

        cfg = self.config
        n = source.n_rows
        num_features = source.n_cols
        self.num_total_features = num_features
        self.parser_config_str = ""
        self._ignore_set = set(source.ignore_features)
        if isinstance(source, ArrowChunkSource):
            self.arrow_categories = source.category_maps
        elif isinstance(source, PandasChunkSource):
            self.pandas_categorical = source.category_maps
        if self._feature_name == "auto" and getattr(source, "names", None):
            self._feature_name = source.names
        if self._categorical_feature == "auto" and hasattr(source, "cats"):
            self._categorical_feature = source.cats
        if isinstance(self._feature_name, str):
            self.feature_names = [f"Column_{i}" for i in range(num_features)]
        else:
            self.feature_names = [str(s) for s in self._feature_name]
        cat_idx = self._resolve_categorical(num_features)

        sharded = False
        if cfg.pre_partition:
            try:
                import jax

                sharded = jax.process_count() > 1
            except Exception:  # pragma: no cover
                sharded = False

        if self.reference is not None:
            ref = self.reference.construct()
            self.bin_mappers = ref.bin_mappers
            self.used_features = ref.used_features
            self.bundle_layout = getattr(ref, "bundle_layout", None)
            self.feature_names = ref.feature_names
            self.num_total_features = ref.num_total_features
        else:
            with global_timer.timed("dataset/ingest/sample"):
                if sharded:
                    from .ingest.sharded import exchange_global_sample

                    # mappers fit from the GLOBAL sample on every host:
                    # no per-rank feature slicing, no mapper allgather,
                    # and EFB layouts agree by construction
                    self._ingest_global_mappers = True
                    _gn, _off, sample = exchange_global_sample(source, cfg)
                else:
                    sample_cnt = min(n, cfg.bin_construct_sample_cnt)
                    if sample_cnt < n:
                        rng = np.random.default_rng(cfg.data_random_seed)
                        rows = np.sort(
                            rng.choice(n, size=sample_cnt, replace=False)
                        )
                    else:
                        rows = np.arange(n, dtype=np.int64)
                    sample = source.sample_rows(rows)
            with global_timer.timed("dataset/ingest/bin_fit"):
                self.bin_mappers = []
                self.used_features = []
                for j in range(num_features):
                    self._add_mapper(j, sample[:, j], cat_idx)
            if cfg.enable_bundle and self._bundling_allowed():
                with global_timer.timed("dataset/ingest/bundle"):
                    from .bundling import build_layout

                    # nonzero scan over the SAMPLE matrix with the sample
                    # count as the row universe — bit-identical to the
                    # one-shot scan over full data mapped through
                    # sample_rows (bundling.py maps nz to sample positions
                    # and normalizes by the sample count either way)
                    self.bundle_layout = build_layout(
                        self.used_features,
                        self.bin_mappers,
                        lambda j: np.flatnonzero(sample[:, j]),
                        sample.shape[0],
                        sample_rows=None,
                        max_conflict_rate=cfg.max_conflict_rate,
                    )
            del sample

        layout = self.bundle_layout
        if layout is not None:
            max_bins = max(layout.plane_bins)
            n_cols = layout.num_planes
        else:
            max_bins = max(
                (m.num_bins for m in self.bin_mappers), default=1
            )
            n_cols = len(self.used_features)
        dtype = np.uint8 if max_bins <= 256 else np.uint16
        self._check_binned_footprint(n, n_cols, np.dtype(dtype).itemsize)
        with global_timer.timed("dataset/ingest/pack"):
            self.bins = stream_pack(
                source, self.bin_mappers, self.used_features, layout,
                dtype, cfg,
            )
        self.raw = None

        fields = source.row_fields()
        if label is None:
            label = fields.get("label")
        if self._group is None:
            self._group = fields.get("group")
        if self._weight is None:
            self._weight = fields.get("weight")
        if self._init_score is None:
            self._init_score = fields.get("init_score")
        if self._position is None:
            self._position = fields.get("position")
        if label is None:
            raise ValueError("label is required to construct a Dataset")
        label = _is_1d(np.asarray(label, dtype=np.float64))
        if len(label) != n:
            raise ValueError(f"label length {len(label)} != num rows {n}")
        _check_label_finite(label)
        weight = self._weight
        if weight is not None:
            weight = _is_1d(np.asarray(weight, dtype=np.float64))
        init_score = self._init_score
        if init_score is not None:
            init_score = np.asarray(init_score, dtype=np.float64)
        self.metadata = Metadata(
            label=label, weight=weight, init_score=init_score
        )
        if self._group is not None:
            self.metadata.set_query(np.asarray(self._group))
        if self._position is not None:
            pos = np.asarray(self._position)
            if len(pos) != len(label):
                raise ValueError(
                    f"position length {len(pos)} != num_data {len(label)}"
                )
            self.metadata.position = pos
        self._constructed = True
        self._raw_data = None
        return self

    def _resolve_categorical(self, num_features: int) -> List[int]:
        cf = self._categorical_feature
        if cf == "auto" or cf is None or cf == "":
            cfg_cf = self.config.categorical_feature
            cf = cfg_cf if cfg_cf not in ("", "auto", None) else []
        if isinstance(cf, str):
            cf = [c for c in cf.split(",") if c != ""]
        out: List[int] = []
        for c in cf:
            if isinstance(c, (int, np.integer)):
                out.append(int(c))
            elif str(c) in self.feature_names:
                out.append(self.feature_names.index(str(c)))
            else:
                out.append(int(str(c).replace("name:", "")) if str(c).isdigit() else -1)
        return [c for c in out if 0 <= c < num_features]

    def _sync_mappers_across_processes(self) -> None:
        """Distributed binning (reference:
        DatasetLoader::ConstructBinMappersFromTextData,
        src/io/dataset_loader.cpp:1079): under ``pre_partition`` each process
        holds only its local rows, so per-process quantile mappers would
        disagree.  Like the reference, each rank keeps the mappers for its
        CONTIGUOUS feature slice (built from local rows) and the slices are
        allgathered so every process ends with identical mappers; binning
        then proceeds locally."""
        if not self.config.pre_partition:
            return
        try:
            import jax

            nproc = jax.process_count()
        except Exception:  # pragma: no cover
            return
        if nproc <= 1:
            return
        from .parallel import allgather_host_exact

        f = len(self.bin_mappers)
        rank = jax.process_index()
        mb_max = max(
            [int(self.config.max_bin), 2]
            + [int(m) for m in self.config.max_bin_by_feature]
        )
        width = 16 + 2 * mb_max
        local = np.zeros((f, width), np.float64)
        per = (f + nproc - 1) // nproc
        lo, hi = rank * per, min(f, (rank + 1) * per)
        for j in range(lo, hi):
            local[j] = self.bin_mappers[j].to_vector(width)
        # bit-exact gather: boundaries are float64 and a lossy f32 roundtrip
        # would bin train rows differently per... identically-wrong on every
        # process, but differently from single-process binning of the same
        # sample (observed: 1e-35 -> 1.00000002e-35)
        gathered = allgather_host_exact(local)  # [nproc, F, W]
        mappers: List[BinMapper] = []
        for j in range(f):
            owner = min(j // per, nproc - 1)
            mappers.append(BinMapper.from_vector(gathered[owner, j]))
        self.bin_mappers = mappers
        self.used_features = [
            j for j in range(f) if not mappers[j].is_trivial
        ]

    def _owned_feature_range(self, f: int):
        """Under pre_partition + multi-process, the contiguous feature slice
        this rank bins (others arrive via the mapper allgather); None when
        every feature is local."""
        if getattr(self, "_ingest_global_mappers", False):
            # streamed sharded ingest fits every mapper from the GLOBAL
            # sample (ingest/sharded.py): no per-rank feature slicing
            return None
        if not self.config.pre_partition:
            return None
        try:
            import jax

            nproc = jax.process_count()
        except Exception:  # pragma: no cover
            return None
        if nproc <= 1:
            return None
        per = (f + nproc - 1) // nproc
        rank = jax.process_index()
        return rank * per, min(f, (rank + 1) * per)

    def _add_mapper(self, j: int, values: np.ndarray, cat_idx: List[int],
                    total_cnt: Optional[int] = None) -> None:
        """Shared per-feature mapper construction for the dense and sparse
        builders (max_bin_by_feature lookup + trivial-feature pruning)."""
        cfg = self.config
        if j in self._ignore_set:
            # ignore_column / weight_column / group_column features stay in
            # the column count (reference keeps original feature numbering)
            # but never train: a trivial mapper drops them from used_features
            self.bin_mappers.append(
                BinMapper(bin_upper_bound=np.array([np.inf]), num_bins=1)
            )
            return
        owned = self._owned_feature_range(self.num_total_features)
        if owned is not None and not (owned[0] <= j < owned[1]):
            # another rank bins this feature; a placeholder keeps indices
            # aligned until _sync_mappers_across_processes replaces it
            self.bin_mappers.append(
                BinMapper(bin_upper_bound=np.array([np.inf]), num_bins=1)
            )
            return
        mb = (
            cfg.max_bin_by_feature[j]
            if j < len(cfg.max_bin_by_feature)
            else cfg.max_bin
        )
        mapper = BinMapper.from_sample(
            values,
            mb,
            is_categorical=j in cat_idx,
            min_data_in_bin=cfg.min_data_in_bin,
            use_missing=cfg.use_missing,
            zero_as_missing=cfg.zero_as_missing,
            total_cnt=total_cnt,
            forced_bounds=self._forced_bin_bounds(j, cat_idx),
        )
        self.bin_mappers.append(mapper)
        if not mapper.is_trivial:
            self.used_features.append(j)

    def _check_binned_footprint(self, n: int, n_cols: int, itemsize: int):
        """Enforce the dense-layout memory ceiling with an actionable error.

        The TPU build stores bins as ONE dense [N, P] matrix (module
        docstring); the check runs AFTER the EFB bundling decision, so the
        column count already reflects the bundled plane count.  A dataset
        still over the ceiling (bundling off, or columns that are not
        mutually exclusive) would materialize hundreds of GB and OOM deep
        inside allocation — fail early and say what to do: exclusive
        one-hot blocks bundle away with enable_bundle=true (or carry the
        same information as ONE integer-coded categorical column,
        categorical_feature= + sorted-subset splits)."""
        import os

        est = n * max(1, n_cols) * itemsize
        ceiling = int(
            os.environ.get("LGBM_TPU_MAX_BINNED_BYTES", 16 << 30)
        )
        if est > ceiling:
            bundled = (
                f" after bundling into {n_cols} planes"
                if self.bundle_layout is not None
                else ""
            )
            raise ValueError(
                f"binned dataset would need {est / (1 << 30):.1f} GiB "
                f"({n} rows x {n_cols} columns{bundled}, dense layout) — "
                f"over the {ceiling / (1 << 30):.1f} GiB ceiling. Enable "
                "EFB feature bundling (enable_bundle=true, on by default) "
                "for mutually-exclusive sparse columns, encode exclusive "
                "one-hot column blocks as a single integer-coded "
                "categorical feature (categorical_feature=...), drop "
                "empty/constant columns, or raise LGBM_TPU_MAX_BINNED_BYTES "
                "if the footprint is intended."
            )

    def _bundling_allowed(self) -> bool:
        """EFB is skipped under multi-process pre_partition feeding: the
        conflict scan sees only local rows, so per-process layouts would
        disagree (the mapper allgather has no layout channel yet)."""
        if getattr(self, "_ingest_global_mappers", False):
            # streamed sharded ingest scans conflicts on the allgathered
            # GLOBAL sample — identical layout on every process
            return True
        if not self.config.pre_partition:
            return True
        try:
            import jax

            return jax.process_count() <= 1
        except Exception:  # pragma: no cover
            return True

    def _find_bundle_layout(self, data, sparse_csc, n: int):
        """Greedy conflict-count bundling over a row sample (reference
        DatasetLoader FindGroups; bundling.py has the algorithm)."""
        from .bundling import build_layout

        cfg = self.config
        if sparse_csc is not None:
            indptr = sparse_csc.indptr
            indices = sparse_csc.indices
            vals = sparse_csc.data

            def nonzeros_of(j):
                sl = slice(indptr[j], indptr[j + 1])
                idx = indices[sl]
                return np.sort(idx[vals[sl] != 0])
        else:

            def nonzeros_of(j):
                return np.flatnonzero(data[:, j])

        sample_cnt = min(n, cfg.bin_construct_sample_cnt)
        sample_rows = None
        if sample_cnt < n:
            rng = np.random.default_rng(cfg.data_random_seed)
            sample_rows = np.sort(
                rng.choice(n, size=sample_cnt, replace=False)
            )
        return build_layout(
            self.used_features,
            self.bin_mappers,
            nonzeros_of,
            n,
            sample_rows=sample_rows,
            max_conflict_rate=cfg.max_conflict_rate,
        )

    def _forced_bin_bounds(self, j: int, cat_idx: List[int]):
        """User-forced bin upper bounds for feature j, or None.

        ``forcedbins_filename`` points at a JSON array of
        ``{"feature": i, "bin_upper_bound": [...]}`` records (reference:
        DatasetLoader::GetForcedBins, src/io/dataset_loader.cpp:1431);
        categorical features ignore their record with a warning, duplicate
        bounds are dropped."""
        path = getattr(self.config, "forcedbins_filename", "")
        if not path:
            return None
        if getattr(self, "_forced_bins_cache", None) is None:
            import json

            from .utils.log import log_warning

            table = {}
            try:
                with open(path) as fh:
                    records = json.load(fh)
                for rec in records:
                    fi = int(rec["feature"])
                    bounds = [float(v) for v in rec.get("bin_upper_bound", [])]
                    # remove consecutive duplicates (reference std::unique)
                    dedup: List[float] = []
                    for b in bounds:
                        if not dedup or b != dedup[-1]:
                            dedup.append(b)
                    table[fi] = dedup
            except (OSError, ValueError, TypeError, KeyError, AttributeError):
                # unreadable OR malformed (bad JSON, wrong shape, missing
                # keys): warn and ignore, as the reference's GetForcedBins
                # does — never crash construct()
                log_warning(f"Could not parse {path}. Will ignore.")
                table = {}
            self._forced_bins_cache = table
        if j not in self._forced_bins_cache:
            return None
        if j in cat_idx:
            from .utils.log import log_warning

            log_warning(
                f"Feature {j} is categorical. Will ignore forced bins for "
                "this feature."
            )
            return None
        return self._forced_bins_cache[j]

    def _build_bin_mappers(self, data: np.ndarray, cat_idx: List[int]) -> None:
        cfg = self.config
        n = data.shape[0]
        sample_cnt = min(n, cfg.bin_construct_sample_cnt)
        if sample_cnt < n:
            rng = np.random.default_rng(cfg.data_random_seed)
            sample_rows = rng.choice(n, size=sample_cnt, replace=False)
            sample = data[np.sort(sample_rows)]
        else:
            sample = data
        self.bin_mappers = []
        self.used_features = []
        for j in range(data.shape[1]):
            self._add_mapper(j, sample[:, j], cat_idx)

    def _build_bin_mappers_sparse(self, csc, cat_idx: List[int]) -> None:
        """Per-column binning from CSC nonzeros; zeros enter as an implied
        count (reference: BinMapper::FindBin's zero_cnt handling,
        src/io/bin.cpp — the sparse loader never expands columns)."""
        cfg = self.config
        n = csc.shape[0]
        self.bin_mappers = []
        self.used_features = []
        # sampling: cap the per-column nonzeros considered, like
        # bin_construct_sample_cnt caps rows for the dense path
        sample_cnt = min(n, cfg.bin_construct_sample_cnt)
        frac = sample_cnt / n
        rng = np.random.default_rng(cfg.data_random_seed)
        for j in range(csc.shape[1]):
            sl = slice(csc.indptr[j], csc.indptr[j + 1])
            vals = np.asarray(csc.data[sl], dtype=np.float64)
            total = n
            if frac < 1.0 and len(vals) > 0:
                keep = rng.random(len(vals)) < frac
                vals = vals[keep]
                # the binomial draw can keep more than sample_cnt * density
                # nonzeros; never let the implied zero count go negative
                total = max(sample_cnt, len(vals))
            if j in cat_idx and total > len(vals):
                # categorical zeros are a real category, not an implied bin
                vals = np.concatenate([vals, np.zeros(total - len(vals))])
            self._add_mapper(j, vals, cat_idx, total_cnt=total)

    # ----------------------------------------------------------- field API
    def set_label(self, label: np.ndarray) -> "Dataset":
        if self._constructed:
            arr = _is_1d(np.asarray(label, dtype=np.float64))
            _check_label_finite(arr)
            self.metadata.label = arr
            self._device_cache.clear()
        else:
            self._label = label
        return self

    def set_weight(self, weight: Optional[np.ndarray]) -> "Dataset":
        if self._constructed:
            self.metadata.weight = (
                None if weight is None else _is_1d(np.asarray(weight, dtype=np.float64))
            )
            self._device_cache.clear()
        else:
            self._weight = weight
        return self

    def set_group(self, group: Optional[np.ndarray]) -> "Dataset":
        if self._constructed:
            if group is not None:
                self.metadata.set_query(np.asarray(group))
        else:
            self._group = group
        return self

    def set_position(self, position: Optional[np.ndarray]) -> "Dataset":
        if position is not None and self._constructed:
            position = np.asarray(position)
            if len(position) != self.num_data:
                raise ValueError(
                    f"position length {len(position)} != num_data {self.num_data}"
                )
        if self._constructed:
            self.metadata.position = position
        else:
            self._position = position
        return self

    def get_data(self):
        """Raw data if retained (reference basic.py get_data; requires
        free_raw_data=False)."""
        self.construct()
        if self.raw is None:
            raise ValueError(
                "raw data was freed; construct the Dataset with "
                "free_raw_data=False to keep it"
            )
        return self.raw

    def get_feature_name(self) -> List[str]:
        self.construct()
        return list(self.feature_names)

    def set_feature_name(self, feature_name) -> "Dataset":
        if feature_name is None or (
            isinstance(feature_name, str) and feature_name == "auto"
        ):
            return self
        names = [str(s) for s in feature_name]
        if self._constructed:
            if len(names) != self.num_total_features:
                raise ValueError(
                    f"{len(names)} feature names for "
                    f"{self.num_total_features} features"
                )
            self.feature_names = names
        else:
            self._feature_name = names
        return self

    def set_categorical_feature(self, categorical_feature) -> "Dataset":
        if self._constructed:
            raise ValueError(
                "cannot change categorical_feature after construction; "
                "create a new Dataset"
            )
        self._categorical_feature = categorical_feature
        return self

    def set_reference(self, reference: "Dataset") -> "Dataset":
        if self._constructed:
            raise ValueError(
                "cannot change reference after construction; create a new Dataset"
            )
        self.reference = reference
        return self

    def get_ref_chain(self, ref_limit: int = 100):
        """Set of datasets reachable via reference links (basic.py)."""
        head = self
        chain = set()
        while head is not None and len(chain) < ref_limit:
            if head in chain:
                break
            chain.add(head)
            head = head.reference
        return chain

    def feature_num_bin(self, feature) -> int:
        """Number of bins for a feature (reference LGBM_DatasetGetFeatureNumBin)."""
        self.construct()
        if isinstance(feature, str):
            feature = self.feature_names.index(feature)
        return int(self.bin_mappers[feature].num_bins)

    def get_position(self):
        self.construct()
        return self.metadata.position

    def add_features_from(self, other: "Dataset") -> "Dataset":
        """Column-concatenate another dataset's features (reference
        LGBM_DatasetAddFeaturesFrom). Both must be constructed and have the
        same row count."""
        self.construct()
        other.construct()
        if self.num_data != other.num_data:
            raise ValueError("datasets must have the same number of rows")
        if self.bundle_layout is not None or other.bundle_layout is not None:
            raise ValueError(
                "add_features_from is not supported on EFB-bundled datasets "
                "(plane columns are not per-feature); construct with "
                "enable_bundle=false to merge"
            )
        base_f = self.num_total_features
        self.bin_mappers = list(self.bin_mappers) + list(other.bin_mappers)
        self.used_features = list(self.used_features) + [
            base_f + j for j in other.used_features
        ]
        self.bins = np.concatenate(
            [
                self.bins.astype(np.uint16),
                other.bins.astype(np.uint16),
            ],
            axis=1,
        )
        if self.bins.max(initial=0) < 256:
            self.bins = self.bins.astype(np.uint8)
        self.feature_names = list(self.feature_names) + list(other.feature_names)
        self.num_total_features = base_f + other.num_total_features
        if self.raw is not None and other.raw is not None:
            if hasattr(self.raw, "toarray") or hasattr(other.raw, "toarray"):
                import scipy.sparse as sp

                self.raw = sp.hstack(
                    [sp.csr_matrix(self.raw), sp.csr_matrix(other.raw)]
                ).tocsr()
            else:
                self.raw = np.concatenate([self.raw, other.raw], axis=1)
        elif self.raw is not None:
            from .utils.log import log_warning

            log_warning(
                "cannot merge raw data: the other dataset freed its raw "
                "data; the merged dataset keeps none (reference warns too)"
            )
            self.raw = None
        self._device_cache.clear()
        return self

    def set_init_score(self, init_score: Optional[np.ndarray]) -> "Dataset":
        if self._constructed:
            self.metadata.init_score = (
                None if init_score is None else np.asarray(init_score, dtype=np.float64)
            )
        else:
            self._init_score = init_score
        return self

    def get_label(self) -> np.ndarray:
        self.construct()
        return self.metadata.label

    def get_weight(self) -> Optional[np.ndarray]:
        self.construct()
        return self.metadata.weight

    def get_group(self) -> Optional[np.ndarray]:
        self.construct()
        qb = self.metadata.query_boundaries
        return None if qb is None else np.diff(qb)

    def get_init_score(self) -> Optional[np.ndarray]:
        self.construct()
        return self.metadata.init_score

    def get_field(self, name: str):
        getters = {
            "label": self.get_label,
            "weight": self.get_weight,
            "group": self.get_group,
            "init_score": self.get_init_score,
            "position": self.get_position,
        }
        if name not in getters:
            raise KeyError(name)
        return getters[name]()

    def set_field(self, name: str, value) -> "Dataset":
        setters = {
            "label": self.set_label,
            "weight": self.set_weight,
            "group": self.set_group,
            "init_score": self.set_init_score,
            "position": self.set_position,
        }
        if name not in setters:
            raise KeyError(name)
        return setters[name](value)

    def create_valid(
        self,
        data,
        label=None,
        weight=None,
        group=None,
        init_score=None,
        params=None,
    ) -> "Dataset":
        return Dataset(
            data,
            label,
            reference=self,
            weight=weight,
            group=group,
            init_score=init_score,
            params=params if params is not None else self.params,
        )

    # ------------------------------------------------------------- binary IO
    def save_binary(self, filename: str) -> "Dataset":
        """Serialize the constructed (binned) dataset (reference:
        Dataset::SaveBinaryFile via save_binary, src/io/dataset_loader.cpp:424).
        Format: npz with bins, metadata and per-feature mapper tables."""
        self.construct()
        import pickle

        with open(filename, "wb") as fh:
            pickle.dump(
                {
                    "format": "lightgbm_tpu.dataset.v1",
                    "bins": self.bins,
                    "used_features": self.used_features,
                    "bundle_layout": self.bundle_layout,
                    "bin_mappers": self.bin_mappers,
                    "feature_names": self.feature_names,
                    "num_total_features": self.num_total_features,
                    "label": self.metadata.label,
                    "weight": self.metadata.weight,
                    "init_score": self.metadata.init_score,
                    "query_boundaries": self.metadata.query_boundaries,
                    "position": getattr(self.metadata, "position", None),
                    "arrow_categories": self.arrow_categories,
                    "pandas_categorical": self.pandas_categorical,
                    # parser_config_str_ persists with the binary dataset
                    # (reference dataset.cpp SaveBinaryFile / :875 load)
                    "parser_config_str": getattr(
                        self, "parser_config_str", ""
                    ),
                    "raw": self.raw,
                },
                fh,
            )
        return self

    @classmethod
    def load_binary(cls, filename: str, params=None) -> "Dataset":
        import pickle

        with open(filename, "rb") as fh:
            blob = pickle.load(fh)
        if blob.get("format") != "lightgbm_tpu.dataset.v1":
            raise ValueError(f"{filename} is not a lightgbm_tpu binary dataset")
        ds = cls.__new__(cls)
        ds.params = dict(params or {})
        ds.config = Config.from_params(ds.params)
        ds._raw_data = None
        ds._label = None
        ds._weight = None
        ds._group = None
        ds._init_score = None
        ds._feature_name = "auto"
        ds._categorical_feature = "auto"
        ds.reference = None
        ds.free_raw_data = True
        ds._constructed = True
        ds.arrow_categories = blob.get("arrow_categories")
        ds.pandas_categorical = blob.get("pandas_categorical")
        ds.parser_config_str = blob.get("parser_config_str", "")
        ds.bin_mappers = blob["bin_mappers"]
        ds.used_features = blob["used_features"]
        ds.bundle_layout = blob.get("bundle_layout")
        ds._ignore_set = set()
        ds.bins = blob["bins"]
        ds.raw = blob.get("raw")
        ds.feature_names = blob["feature_names"]
        ds.num_total_features = blob["num_total_features"]
        ds._position = None
        ds.metadata = Metadata(
            label=blob["label"],
            weight=blob["weight"],
            init_score=blob["init_score"],
            query_boundaries=blob["query_boundaries"],
        )
        ds.metadata.position = blob.get("position")
        ds._device_cache = {}
        return ds

    def subset(self, used_indices, params=None) -> "Dataset":
        """Row subset sharing the bin mappers (reference: Dataset::CopySubrow,
        python basic.py Dataset.subset)."""
        self.construct()
        idx = np.asarray(used_indices, dtype=np.int64)
        ds = Dataset.__new__(Dataset)
        ds.params = dict(params or self.params)
        ds.config = Config.from_params(ds.params)
        ds._raw_data = None
        ds._label = None
        ds._weight = None
        ds._group = None
        ds._init_score = None
        ds._feature_name = "auto"
        ds._categorical_feature = "auto"
        ds.reference = self
        ds.free_raw_data = self.free_raw_data
        ds._constructed = True
        ds.arrow_categories = self.arrow_categories
        ds.pandas_categorical = self.pandas_categorical
        ds.parser_config_str = getattr(self, "parser_config_str", "")
        ds.bin_mappers = self.bin_mappers
        ds.used_features = self.used_features
        ds.bundle_layout = self.bundle_layout
        ds._ignore_set = set()
        ds.bins = self.bins[idx]
        ds.raw = None if self.raw is None else self.raw[idx]
        ds.feature_names = self.feature_names
        ds.num_total_features = self.num_total_features
        md = self.metadata
        ds.metadata = Metadata(
            label=md.label[idx],
            weight=None if md.weight is None else md.weight[idx],
            init_score=None if md.init_score is None else md.init_score[idx],
        )
        ds._device_cache = {}
        return ds

    # -------------------------------------------------------------- device
    def device_bins(self):
        """The binned matrix as a device array (cached)."""
        import jax.numpy as jnp

        self.construct()
        if "bins" not in self._device_cache:
            # keep the narrow host dtype (uint8/uint16): 4x less HBM traffic
            # for every gather in the grower and 4x smaller kernel tiles; the
            # Pallas kernel widens per-tile in VMEM
            self._device_cache["bins"] = jnp.asarray(self.bins)
        return self._device_cache["bins"]

    def device_label(self):
        import jax.numpy as jnp

        self.construct()
        if "label" not in self._device_cache:
            self._device_cache["label"] = jnp.asarray(self.metadata.label, dtype=jnp.float32)
        return self._device_cache["label"]

    def device_weight(self):
        import jax.numpy as jnp

        self.construct()
        if "weight" not in self._device_cache:
            w = self.metadata.weight
            self._device_cache["weight"] = (
                None if w is None else jnp.asarray(w, dtype=jnp.float32)
            )
        return self._device_cache["weight"]
