"""graftlint core: project model, findings, suppressions, baseline.

The linter is pure ``ast`` — it never imports the modules it scans, so a
full-tree run costs parse time only (well under the 10 s budget) and cannot
be perturbed by import-time side effects (jax platform probing, config
globals).  Each rule gets a :class:`Project`: every module pre-parsed with
its import map and module-level integer/float constant table, which is what
lets rules resolve ``pl.pallas_call`` / ``jnp.asarray`` spellings and
constant block-shape dims (``LANES = 128``) without executing anything.

Suppression contract (per line, reviewed in-diff like the baseline):

    something_flagged()  # graftlint: disable=GL001
    other_flagged()      # graftlint: disable=GL002,GL005
    anything_flagged()   # graftlint: disable

Baseline contract: ``lint_baseline.json`` holds the explicit, justified
exceptions.  A finding matches an entry on ``(rule, path, ident)`` — the
ident is a per-rule stable key (function/field/spec slot), NOT a line
number, so baselines survive unrelated edits.  Entries that no longer fire
are STALE and fail the run: a baseline may only shrink through review, the
same discipline test_config_consumers.py applies to its allowlist.  The
end-state goal is an empty baseline.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

# rule code -> (one-line summary, autofix hint)
RULES: Dict[str, Tuple[str, str]] = {
    "GL001": (
        "bare jax.jit/jax.pmap outside obs/jit.py",
        "route through lightgbm_tpu.obs.jit.instrumented_jit(label=...) so "
        "compile_count() stays exact",
    ),
    "GL002": (
        "Pallas kernel reads a ref that is the input side of "
        "input_output_aliases",
        "read through the output-aliased ref instead (see "
        "ops/pallas/partition.read_aliased_tile) — input-ref reads miss "
        "earlier writes in interpret mode and on re-read boundary tiles",
    ),
    "GL003": (
        "host-sync call on a tracer-flowing value inside a jit/pallas-"
        "reachable function",
        "keep the value on device (jnp ops) or hoist the host conversion "
        "out of the traced function",
    ),
    "GL004": (
        "weak-typed Python scalar constant closed over by a jitted function",
        "wrap at the use site as jnp.asarray(CONST, dtype=...) (or pass it "
        "as a typed argument) to pin the dtype across retraces",
    ),
    "GL005": (
        "pallas_call contract violation (block tiling / index_map arity / "
        "out_shape vs out_specs)",
        "align VMEM block shapes to (sublane, 128) for the dtype (f32: 8, "
        "bf16/i16: 16, i8: 32; a 1-row block is allowed), and keep "
        "grid/index_map/out_shape/out_specs consistent",
    ),
    "GL006": (
        "Config field declared in config.py but never read anywhere",
        "wire a consumer or add a baseline entry documenting why the TPU "
        "build deliberately ignores it",
    ),
    "GL007": (
        "collective not congruent across replicas (raw jax.lax collective, "
        "or a psum/pmax/pmin/all_gather reached on only one branch)",
        "route raw collectives through obs.collectives.timed_* (the every-"
        "site-is-measured invariant), and make every lax.cond / divergent "
        "if branch execute the SAME collective sequence — a replica that "
        "skips a collective deadlocks the ones that entered it",
    ),
    "GL008": (
        "axis_name inconsistency: mixed axis-name sources in one jitted "
        "region, or a collective reachable where the axis name can be None",
        "use ONE axis-name source per jitted region (the GrowerParams."
        "axis_name plumbing, not ad-hoc literals) and dominate every "
        "collective with an `axis_name is not None` guard",
    ),
    "GL009": (
        "retrace hazard: non-static Python scalar/tuple flowing into a jit "
        "entry, or an io_callback/pure_callback without ordered=True",
        "declare Python scalars in static_argnames (or pin them with "
        "jnp.asarray) so they stop retracing per value, and pass "
        "ordered=True to callbacks unless ordering is enforced by an "
        "explicit data dependency",
    ),
    "GL010": (
        "host-divergent value (process_index / time / os.environ / "
        "unseeded RNG) gates a branch containing a collective",
        "hoist the collective out of the divergent branch, or derive the "
        "gate from replicated data (psummed stats, static config) so every "
        "process takes the same path",
    ),
    # ---- IR-grade rules (lint.ir traces the real entries to jaxprs;
    # rules_ir.py audits the traced facts; run with --ir)
    "GL011": (
        "traced collective incongruent with the sanctioned wrappers, the "
        "entry's mesh axes, the analytic payload model, or the GL007 AST "
        "site model (or the entry failed to trace at all)",
        "route the collective through obs.collectives.timed_* on a "
        "declared mesh axis, and keep mesh_psum_bytes_per_iteration in "
        "sync with what the jaxpr actually moves",
    ),
    "GL012": (
        "64-bit aval traced in a hot entry (directly, or the moment "
        "enable_x64 flips on)",
        "pin the dtype at the producing op (dtype=jnp.float32 / "
        "jnp.int32 on arange, random.uniform, asarray) so the entry is "
        "invariant to the x64 flag",
    ),
    "GL013": (
        "per-iteration carried state rebound without donate_argnums",
        "declare donate_argnums on the instrumented_jit entry for every "
        "dead-after-call carried buffer so XLA reuses (or at least "
        "frees) the input allocation instead of doubling the HBM "
        "footprint",
    ),
    "GL014": (
        "pallas kernel's static VMEM working set (2x operand blocks + "
        "scratch) exceeds the per-core budget",
        "shrink the block shapes / grid so the double-buffered working "
        "set plus scratch fits the 16 MiB v5e per-core VMEM arena",
    ),
    "GL015": (
        "host callback compiled into a hot (per-iteration) entry outside "
        "the sanctioned obs.collectives wrappers",
        "drop the callback from the compiled hot path (aggregate on "
        "device, fetch after the loop) or route it through the timed "
        "obs.collectives wrappers so the transfer is measured and "
        "gated",
    ),
}

# rules produced by the IR pass (rules_ir.py): their baseline entries are
# only checked for staleness when the FULL entry matrix was traced
IR_RULE_CODES = frozenset(
    {"GL011", "GL012", "GL013", "GL014", "GL015"}
)

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable(?:=(?P<codes>[A-Z0-9,\s]+))?"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # posix path relative to the scan base (repo root)
    line: int
    ident: str  # per-rule stable baseline key (no line numbers)
    message: str

    @property
    def hint(self) -> str:
        return RULES[self.rule][1]

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.ident)

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


class Module:
    """One parsed source file plus the lookup tables rules share."""

    def __init__(self, path: Path, rel: str, source: str):
        self.path = path
        self.rel = rel  # posix, relative to scan base
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=str(path))
        # local name -> ("ext", dotted_module) | ("extobj", module, obj)
        #            | ("mod", rel_path)      | ("obj", rel_path, obj)
        self.imports: Dict[str, Tuple] = {}
        # module-level NAME = <int/float literal>
        self.consts: Dict[str, float] = {}
        # module-level NAME = "<str literal>" (axis-name source resolution)
        self.str_consts: Dict[str, str] = {}
        # module-level function defs by name
        self.functions: Dict[str, ast.FunctionDef] = {}
        for node in self.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Name) and isinstance(
                    node.value, ast.Constant
                ):
                    if isinstance(node.value.value, (int, float)) and not (
                        isinstance(node.value.value, bool)
                    ):
                        self.consts[t.id] = node.value.value
                    elif isinstance(node.value.value, str):
                        self.str_consts[t.id] = node.value.value

    def suppressed(self, line: int, rule: str) -> bool:
        if not (1 <= line <= len(self.lines)):
            return False
        m = _SUPPRESS_RE.search(self.lines[line - 1])
        if not m:
            return False
        codes = m.group("codes")
        if codes is None:
            return True  # bare disable: all rules
        return rule in {c.strip() for c in codes.split(",") if c.strip()}


class Project:
    """All modules under one package root, with import resolution."""

    def __init__(self, root: Path):
        self.root = Path(root).resolve()
        self.base = self.root.parent  # findings are relative to this
        self.pkg = self.root.name
        self.modules: Dict[str, Module] = {}  # rel-to-root posix -> Module
        for path in sorted(self.root.rglob("*.py")):
            rel_root = path.relative_to(self.root).as_posix()
            rel_base = path.relative_to(self.base).as_posix()
            try:
                mod = Module(path, rel_base, path.read_text())
            except SyntaxError as exc:  # pragma: no cover - tree is parseable
                raise SystemExit(f"graftlint: cannot parse {rel_base}: {exc}")
            self.modules[rel_root] = mod
            self._index_imports(rel_root, mod)

    # ----------------------------------------------------------- imports
    def _module_file(self, dotted: str) -> Optional[str]:
        """Resolve an in-package dotted module to a rel-to-root file path."""
        parts = dotted.split(".") if dotted else []
        for cand in (
            "/".join(parts) + ".py" if parts else None,
            "/".join(parts + ["__init__"]) + ".py",
        ):
            if cand and cand in self.modules:
                return cand
        return None

    def _index_imports(self, rel_root: str, mod: Module) -> None:
        pkg_parts = rel_root.split("/")[:-1]  # containing package dirs
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    name = alias.asname or alias.name.split(".")[0]
                    target = alias.name if alias.asname else alias.name.split(".")[0]
                    mod.imports[name] = ("ext", target)
            elif isinstance(node, ast.ImportFrom):
                src = node.module or ""
                if node.level:
                    base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                    dotted = ".".join(base + ([src] if src else []))
                    internal = True
                elif src == self.pkg or src.startswith(self.pkg + "."):
                    dotted = src[len(self.pkg) :].lstrip(".")
                    internal = True
                else:
                    dotted, internal = src, False
                for alias in node.names:
                    name = alias.asname or alias.name
                    if internal:
                        target = self._module_file(
                            (dotted + "." if dotted else "") + alias.name
                        )
                        if target is not None:  # `from . import mod`
                            mod.imports[name] = ("mod", target)
                            continue
                        owner = self._module_file(dotted)
                        if owner is not None:
                            mod.imports[name] = ("obj", owner, alias.name)
                    else:
                        mod.imports[name] = ("extobj", dotted, alias.name)

    # --------------------------------------------------------- resolution
    def dotted_callee(self, mod: Module, func: ast.AST) -> Optional[str]:
        """Canonical dotted name for an EXTERNAL callee expression, e.g.
        ``jnp.asarray`` -> ``jax.numpy.asarray``; None if not external."""
        parts: List[str] = []
        node = func
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        entry = mod.imports.get(node.id)
        if entry is None:
            return None
        if entry[0] == "ext":
            return ".".join([entry[1]] + list(reversed(parts)))
        if entry[0] == "extobj":
            return ".".join([entry[1], entry[2]] + list(reversed(parts)))
        return None

    def internal_callee(
        self, mod: Module, mod_rel: str, func: ast.AST
    ) -> Optional[Tuple[str, str]]:
        """Resolve a callee expression to an in-package (module_rel,
        function_name), or None."""
        if isinstance(func, ast.Name):
            entry = mod.imports.get(func.id)
            if entry is not None and entry[0] == "obj":
                return (entry[1], entry[2])
            if func.id in mod.functions:
                return (mod_rel, func.id)
            return None
        if isinstance(func, ast.Attribute) and isinstance(
            func.value, ast.Name
        ):
            entry = mod.imports.get(func.value.id)
            if entry is not None and entry[0] == "mod":
                owner = self.modules.get(entry[1])
                if owner is not None and func.attr in owner.functions:
                    return (entry[1], func.attr)
        return None

    def function(self, mod_rel: str, name: str) -> Optional[ast.FunctionDef]:
        owner = self.modules.get(mod_rel)
        return owner.functions.get(name) if owner else None


# ------------------------------------------------------------------ utils
def call_kwargs(call: ast.Call) -> Dict[str, ast.AST]:
    return {kw.arg: kw.value for kw in call.keywords if kw.arg}


def const_names(seq: ast.AST) -> Optional[List[str]]:
    """String elements of a literal tuple/list, else None."""
    if isinstance(seq, (ast.Tuple, ast.List)):
        out = []
        for elt in seq.elts:
            if not (
                isinstance(elt, ast.Constant) and isinstance(elt.value, str)
            ):
                return None
            out.append(elt.value)
        return out
    if isinstance(seq, ast.Constant) and isinstance(seq.value, str):
        return [seq.value]
    return None


def literal_dims(
    shape: ast.AST, consts: Dict[str, float]
) -> Optional[List[Optional[int]]]:
    """Per-dim ints for a literal tuple block shape; None entries for dims
    the linter cannot resolve statically (names that are not module-level
    int constants, arithmetic on dynamic values)."""
    if not isinstance(shape, (ast.Tuple, ast.List)):
        return None
    dims: List[Optional[int]] = []
    for elt in shape.elts:
        if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
            dims.append(elt.value)
        elif isinstance(elt, ast.Name) and isinstance(
            consts.get(elt.id), int
        ):
            dims.append(int(consts[elt.id]))
        else:
            dims.append(None)
    return dims


def names_in(node: ast.AST) -> List[str]:
    return [
        n.id
        for n in ast.walk(node)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
    ]


# --------------------------------------------------------------- baseline
def load_baseline(path: Optional[Path]) -> List[Dict]:
    if path is None or not Path(path).exists():
        return []
    data = json.loads(Path(path).read_text())
    entries = data.get("entries", data if isinstance(data, list) else [])
    for e in entries:
        for field in ("rule", "path", "ident", "justification"):
            if field not in e:
                raise SystemExit(
                    f"graftlint: baseline entry missing '{field}': {e}"
                )
    return entries


def write_baseline(path: Path, findings: Sequence[Finding]) -> None:
    entries = [
        {
            "rule": f.rule,
            "path": f.path,
            "ident": f.ident,
            "justification": "TODO: one line on why this exception is "
            "intentional",
        }
        for f in sorted(findings, key=lambda f: (f.rule, f.path, f.ident))
    ]
    Path(path).write_text(
        json.dumps({"version": 1, "entries": entries}, indent=2) + "\n"
    )


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]  # everything that fired (unsuppressed)
    new: List[Finding]  # not covered by the baseline
    stale: List[Dict]  # baseline entries that no longer fire
    timings: Dict[str, float] = dataclasses.field(default_factory=dict)
    # per-rule wall seconds, keyed by rule code (GL001..), for --json

    @property
    def ok(self) -> bool:
        return not self.new and not self.stale


def run_lint(
    root: Path,
    baseline: Optional[Path] = None,
    only_paths: Sequence[str] = (),
    ir: bool = False,
    ir_entry_filter: Optional[Sequence[str]] = None,
    ir_changed_modules: Optional[Sequence[str]] = None,
) -> LintResult:
    """Scan the package at ``root`` and diff against ``baseline``.

    ``only_paths``: optional path-prefix filters (relative to the repo
    root, e.g. ``lightgbm_tpu/ops``) applied to REPORTING only — the whole
    package is always analyzed so the GL003 call graph stays complete.
    Baseline STALE detection is restricted to the same prefixes, so a
    filtered run (``--changed-only``, explicit paths) never misreads
    untouched entries as stale.

    ``ir=True`` additionally traces the lint.ir entry matrix and runs
    the GL011-GL015 jaxpr audits (this IMPORTS the package — see the
    ir.py docstring).  ``ir_entry_filter`` (name prefixes) and
    ``ir_changed_modules`` (package-relative paths) scope which entries
    are traced; when either scopes the matrix down, IR-rule baseline
    entries are exempt from stale detection (an untraced entry cannot
    re-fire its baselined findings).
    """
    import time

    from . import rules_config, rules_jit, rules_pallas, rules_spmd

    project = Project(root)
    findings: List[Finding] = []
    timings: Dict[str, float] = {}
    for rule_mod in (rules_jit, rules_pallas, rules_config, rules_spmd):
        for code, check in rule_mod.RULE_CHECKS.items():
            t0 = time.monotonic()
            findings.extend(check(project))
            timings[code] = timings.get(code, 0.0) + (
                time.monotonic() - t0
            )
    ir_ran_full = False
    if ir:
        from . import rules_ir

        ir_findings, ir_timings, trace_s = rules_ir.run_ir_rules(
            project,
            entry_filter=ir_entry_filter,
            changed_modules=ir_changed_modules,
        )
        findings.extend(ir_findings)
        for code, t in ir_timings.items():
            timings[code] = timings.get(code, 0.0) + t
        timings["ir_trace"] = trace_s
        ir_ran_full = (
            not ir_entry_filter and ir_changed_modules is None
        )
    # suppressions, dedup, stable order
    seen = set()
    kept: List[Finding] = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule, f.ident)):
        mod = next(
            (m for m in project.modules.values() if m.rel == f.path), None
        )
        if mod is not None and mod.suppressed(f.line, f.rule):
            continue
        if f.key() in seen:
            continue
        seen.add(f.key())
        kept.append(f)

    def in_scope(path: str) -> bool:
        return not only_paths or any(
            path.startswith(p.rstrip("/")) for p in only_paths
        )

    if only_paths:
        kept = [f for f in kept if in_scope(f.path)]
    entries = load_baseline(baseline)
    covered = {(e["rule"], e["path"], e["ident"]) for e in entries}
    fired = {f.key() for f in kept}
    new = [f for f in kept if f.key() not in covered]
    stale = [
        e
        for e in entries
        if in_scope(e["path"])
        and (e["rule"], e["path"], e["ident"]) not in fired
        # IR-rule entries can only be judged stale by a FULL matrix run:
        # with the IR pass off (or scoped down) an entry simply was not
        # given the chance to fire
        and (ir_ran_full or e["rule"] not in IR_RULE_CODES)
    ]
    return LintResult(findings=kept, new=new, stale=stale, timings=timings)
