"""Tracer-safety rules around jit call sites.

GL001 — bare ``jax.jit``/``jax.pmap`` outside ``obs/jit.py``.  Every jit
site must route through ``instrumented_jit`` so ``compile_count()`` counts
actual retraces exactly (the PR-5 telemetry contract); a bare site is a
hole in the no-recompile invariant the telemetry tests assert on.

GL003 — host-sync calls (``float``/``int``/``bool``, ``.item()``/
``.tolist()``, ``np.asarray``/``np.array``, ``jax.device_get``) on
tracer-flowing values inside functions reachable from a jit or Pallas
entry point.  Reachability and taint come from callgraph.TaintWalker; jit
``static_argnames`` are excluded from taint, so ``float(l1)`` on a static
hyper-parameter does not fire.

GL004 — module-level Python FLOAT constants closed over by jitted
functions without an explicit ``jnp.asarray(..., dtype=...)`` (or
``jnp.float32(...)``-style) wrap at the use site.  Weak-typed closures
promote by value and drift the traced dtype (retrace hazard).  Integer
constants are deliberately out of scope: they are overwhelmingly shapes,
strides and loop bounds, which are static by design.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from .callgraph import TaintWalker, jit_entries, pallas_call_sites, positional_params
from .core import Finding, Module, Project, names_in

_NUMPY_SYNC = {
    "asarray", "array", "float32", "float64", "int32", "int64", "ascontiguousarray",
}
_ASARRAY_WRAPPERS = {
    "asarray", "array", "float32", "float64", "int32", "int16", "int8",
    "bfloat16", "float16",
}


# ------------------------------------------------------------------ GL001
def _check_gl001(project: Project) -> List[Finding]:
    findings = []
    for rel, mod in project.modules.items():
        if rel == "obs/jit.py":
            continue  # the one sanctioned wrapper site
        stack: List[str] = []

        def visit(node: ast.AST) -> None:
            is_fn = isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            if is_fn:
                stack.append(node.name)
            # flag REFERENCES, not just call expressions, so decorator form
            # (@jax.jit) and functools.partial(jax.jit, ...) are caught too
            if isinstance(node, (ast.Attribute, ast.Name)):
                dotted = project.dotted_callee(mod, node)
                if dotted in ("jax.jit", "jax.pmap"):
                    where = ".".join(stack) or "<module>"
                    findings.append(
                        Finding(
                            rule="GL001",
                            path=mod.rel,
                            line=node.lineno,
                            ident=where,
                            message=f"bare {dotted} in {where}; route "
                            "through instrumented_jit(label=...) so "
                            "compile_count()/compile_counts_by_label() see "
                            "its retraces",
                        )
                    )
            for child in ast.iter_child_nodes(node):
                visit(child)
            if is_fn:
                stack.pop()

        visit(mod.tree)
    return findings


# ------------------------------------------------------------------ GL003
def _check_gl003(project: Project) -> List[Finding]:
    findings: List[Finding] = []

    def make_visitor(entry_label: str):
        def visit(mod_rel: str, fn: ast.FunctionDef, tainted: Set[str],
                  node: ast.AST) -> None:
            if not isinstance(node, ast.Call):
                return
            mod = project.modules[mod_rel]
            dotted = project.dotted_callee(mod, node.func)
            hit = None  # (callable spelling, offending names)
            if dotted == "jax.device_get":
                hit = ("jax.device_get", set())
            elif isinstance(node.func, ast.Name) and node.func.id in (
                "float", "int", "bool"
            ) and node.func.id not in mod.imports:
                names = set()
                for arg in node.args:
                    names |= set(names_in(arg)) & tainted
                if names:
                    hit = (node.func.id, names)
            elif isinstance(node.func, ast.Attribute) and node.func.attr in (
                "item", "tolist"
            ):
                names = set(names_in(node.func.value)) & tainted
                if names:
                    hit = ("." + node.func.attr, names)
            elif dotted is not None and dotted.startswith("numpy.") and \
                    dotted.split(".")[-1] in _NUMPY_SYNC and node.args:
                names = set(names_in(node.args[0])) & tainted
                if names:
                    hit = (dotted, names)
            if hit is None:
                return
            spelling, names = hit
            via = f" via {', '.join(sorted(names))}" if names else ""
            findings.append(
                Finding(
                    rule="GL003",
                    path=mod.rel,
                    line=node.lineno,
                    ident=f"{fn.name}:{spelling}:{','.join(sorted(names))}",
                    message=f"host-sync {spelling}(){via} in {fn.name}(), "
                    f"reachable from traced entry {entry_label} — this "
                    "blocks (or fails) under tracing",
                )
            )

        return visit

    for rel, mod, fn, statics in jit_entries(project):
        tainted = frozenset(set(positional_params(fn)) - set(statics))
        walker = TaintWalker(project, make_visitor(f"{fn.name} (jit)"))
        walker.walk(rel, fn, tainted)
    for rel, mod, call, kernel, _encl in pallas_call_sites(project):
        if kernel is None:
            continue
        krel, kfn = kernel
        walker = TaintWalker(
            project, make_visitor(f"{kfn.name} (pallas kernel)")
        )
        walker.walk(krel, kfn, frozenset(positional_params(kfn)))
    return findings


# ------------------------------------------------------------------ GL004
def _bound_names(fn: ast.FunctionDef) -> Set[str]:
    bound: Set[str] = {a.arg for a in (
        fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs
    )}
    if fn.args.vararg:
        bound.add(fn.args.vararg.arg)
    if fn.args.kwarg:
        bound.add(fn.args.kwarg.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            bound.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            if node is not fn:
                bound.add(node.name)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                bound.add(alias.asname or alias.name.split(".")[0])
    return bound


def _check_gl004(project: Project) -> List[Finding]:
    findings = []
    for rel, mod, fn, _statics in jit_entries(project):
        float_consts = {
            k for k, v in mod.consts.items() if isinstance(v, float)
        }
        if not float_consts:
            continue
        bound = _bound_names(fn)
        parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(fn):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        for node in ast.walk(fn):
            if not (
                isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)
                and node.id in float_consts
                and node.id not in bound
            ):
                continue
            # exempt uses already wrapped in an explicit dtype pin
            wrapped = False
            cur = parents.get(node)
            while cur is not None:
                if isinstance(cur, ast.Call):
                    d = project.dotted_callee(mod, cur.func)
                    if d is not None and d.split(".")[-1] in _ASARRAY_WRAPPERS:
                        wrapped = True
                        break
                cur = parents.get(cur)
            if wrapped:
                continue
            findings.append(
                Finding(
                    rule="GL004",
                    path=mod.rel,
                    line=node.lineno,
                    ident=f"{fn.name}:{node.id}",
                    message=f"jitted {fn.name}() closes over weak-typed "
                    f"float constant {node.id}; pin it with "
                    f"jnp.asarray({node.id}, dtype=...) to avoid dtype "
                    "drift across retraces",
                )
            )
    return findings


# rule code -> per-rule check callable (run_lint times each one)
RULE_CHECKS = {
    "GL001": _check_gl001,
    "GL003": _check_gl003,
    "GL004": _check_gl004,
}


def check(project: Project) -> List[Finding]:
    return _check_gl001(project) + _check_gl003(project) + _check_gl004(project)
