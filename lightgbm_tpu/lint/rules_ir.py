"""graftlint IR rules GL011-GL015: audits over traced jaxprs.

The AST pass (rules_spmd et al.) models what the source SAYS; this pass
checks what jax actually TRACES for the real entry points (lint.ir's
config matrix).  Each check consumes ``(project, entries)`` — the AST
project is still needed because GL011 cross-checks every traced
collective against the GL007 static site model (a collective the AST
cannot see is a blind spot worth failing on), and because findings flow
through the same suppression/baseline machinery as the AST rules.

Finding idents are stable per-rule keys (core.py baseline contract —
no line numbers): collective findings key on (arm, kind, enclosing
function) so one bad call site dedups across the entries that trace it;
per-entry findings (dtype widening, donation) key on the entry name.

One finding per traced collective eqn, first failed arm wins, in order:

(a) provenance — the innermost in-package frame must be the
    ``obs/collectives`` timed wrapper (the every-site-is-measured
    invariant GL007 enforces statically);
(b) axis containment — the eqn's axis names must be within the entry's
    declared mesh axes;
(c) payload congruence — psum/pmax/pmin payload bytes must be in the
    per-axis allowed set derived from the same formula pieces as
    ``mesh_psum_bytes_per_iteration`` (a payload the analytic model
    does not predict means model and code have drifted);
(d) AST congruence — the outermost user frame must land inside a GL007
    ``CollectiveSite`` span of that module (else the static SPMD rules
    are blind to a real collective).
"""

from __future__ import annotations

import time
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

from .callgraph import spmd_index
from .core import Finding, Project
from . import ir as ir_mod
from .ir import (
    CollectiveFact,
    SrcFrame,
    TracedEntry,
    VMEM_LIMIT_BYTES,
    VMEM_TARGET,
    WideDtypeFact,
)

_SANCTIONED = ir_mod.PKG_NAME + "/obs/collectives.py"
# observability infrastructure (the timed wrappers, instrumented_jit):
# never "the client site" a finding should point at
_INFRA_PREFIX = ir_mod.PKG_NAME + "/obs/"

# traced primitive name -> the AST-side CollectiveSite kind (GL007 model)
_AST_KIND = {
    "psum": "psum",
    "psum2": "psum",
    "pmax": "pmax",
    "pmin": "pmin",
    "all_gather": "all_gather",
}
# kinds the analytic payload model covers (all_gather payloads scale with
# the axis size the jaxpr does not name statically — skipped)
_MODELED_KINDS = {"psum", "psum2", "pmax", "pmin"}


def _user_site(frames: Tuple[SrcFrame, ...]) -> Optional[SrcFrame]:
    """Innermost in-package frame OUTSIDE the obs/ infrastructure —
    the client call site a finding should point at."""
    for fr in frames:
        if not fr.path.startswith(_INFRA_PREFIX):
            return fr
    return None


def _ast_site_spans(
    project: Project,
) -> Dict[str, List[Tuple[str, int, int]]]:
    """(kind, lineno, end_lineno) spans of every GL007 CollectiveSite,
    keyed by base-relative module path (the SrcFrame.path format)."""
    spans: Dict[str, List[Tuple[str, int, int]]] = {}
    for scope in spmd_index(project).scopes:
        base = ir_mod.PKG_NAME + "/" + scope.rel
        for site in scope.sites:
            node = site.node
            end = getattr(node, "end_lineno", None) or node.lineno
            spans.setdefault(base, []).append(
                (site.kind, node.lineno, end)
            )
    return spans


def _where(
    fr: Optional[SrcFrame], spec
) -> Tuple[str, int]:
    if fr is not None:
        return fr.path, fr.line
    return spec.anchor


# ------------------------------------------------------------------ GL011
def check_collective_congruence(
    project: Project, entries: Sequence[TracedEntry]
) -> List[Finding]:
    spans = _ast_site_spans(project)
    out: List[Finding] = []
    for te in entries:
        spec = te.spec
        if te.error:
            out.append(
                Finding(
                    "GL011",
                    spec.anchor[0],
                    spec.anchor[1],
                    f"{spec.name}:trace-error",
                    f"entry '{spec.name}' failed to trace: {te.error}",
                )
            )
            continue
        model = spec.psum_model() if spec.psum_model is not None else {}
        for c in te.facts.collectives:
            inner = c.frames[0] if c.frames else None
            site = _user_site(c.frames)
            loc = site or inner
            path, line = _where(loc, spec)
            func = loc.func if loc is not None else "?"
            # (a) provenance: must come out of the timed wrappers
            if inner is None or inner.path != _SANCTIONED:
                at = (
                    f"{inner.path}:{inner.line}" if inner else "unknown"
                )
                out.append(
                    Finding(
                        "GL011",
                        path,
                        line,
                        f"unsanctioned:{c.kind}:{func}",
                        f"raw '{c.kind}' in entry '{spec.name}' does not "
                        f"route through obs.collectives timed_* "
                        f"(innermost frame {at})",
                    )
                )
                continue
            # (b) axis containment
            bad = [a for a in c.axes if a not in spec.axes]
            if bad:
                declared = sorted(spec.axes) if spec.axes else "none"
                out.append(
                    Finding(
                        "GL011",
                        path,
                        line,
                        f"axis:{c.kind}:{','.join(bad)}:{func}",
                        f"'{c.kind}' in entry '{spec.name}' reduces over "
                        f"axis {bad} outside the entry's declared mesh "
                        f"axes ({declared})",
                    )
                )
                continue
            # (c) payload congruence vs the analytic model
            if model and c.kind in _MODELED_KINDS and c.axes:
                allowed: FrozenSet[int] = frozenset().union(
                    *(model.get(a, frozenset()) for a in c.axes)
                )
                if allowed and c.payload_bytes not in allowed:
                    out.append(
                        Finding(
                            "GL011",
                            path,
                            line,
                            f"payload:{c.kind}:{','.join(c.axes)}:"
                            f"{c.payload_bytes}:{func}",
                            f"'{c.kind}' over {list(c.axes)} in entry "
                            f"'{spec.name}' moves {c.payload_bytes} B, "
                            f"which the analytic payload model "
                            f"(mesh_psum_bytes_per_iteration terms: "
                            f"{sorted(allowed)}) does not predict — "
                            f"model and code have drifted",
                        )
                    )
                    continue
            # (d) AST congruence: the GL007 model must see this site
            if site is not None:
                kind = _AST_KIND.get(c.kind)
                if kind is not None and not any(
                    k == kind and lo <= site.line <= hi
                    for k, lo, hi in spans.get(site.path, ())
                ):
                    out.append(
                        Finding(
                            "GL011",
                            site.path,
                            site.line,
                            f"ast-blind:{c.kind}:{func}",
                            f"'{c.kind}' traced in entry '{spec.name}' "
                            f"at {site.path}:{site.line} has no matching "
                            f"GL007 AST collective site — the static "
                            f"SPMD congruence rules are blind to it",
                        )
                    )
    return out


# ------------------------------------------------------------------ GL012
def _wide_sites(
    facts: Sequence[WideDtypeFact],
) -> List[Tuple[WideDtypeFact, Optional[SrcFrame]]]:
    seen = set()
    client, infra = [], []
    for w in facts:
        site = _user_site(w.frames)
        fr = site or (w.frames[0] if w.frames else None)
        key = (w.dtype, fr.path if fr else "?", fr.line if fr else 0)
        if key in seen:
            continue
        seen.add(key)
        # facts with a real client frame lead: the finding anchors on
        # the first listed site, and an obs/-internal frame (the outer
        # pjit eqn through instrumented_jit) is never the root cause
        (client if site is not None else infra).append((w, fr))
    return client + infra


def check_dtype_promotion(
    project: Project, entries: Sequence[TracedEntry]
) -> List[Finding]:
    out: List[Finding] = []
    for te in entries:
        if te.error:
            continue
        spec = te.spec
        for arm, facts, why in (
            (
                "wide",
                te.facts.wide,
                "computes in 64-bit on the hot path",
            ),
            (
                "x64",
                te.x64_wide,
                "widens to 64-bit the moment enable_x64 flips on "
                "(unpinned default dtype)",
            ),
        ):
            sites = _wide_sites(facts)
            if not sites:
                continue
            path, line = _where(sites[0][1], spec)
            detail = "; ".join(
                f"{w.dtype} ({w.prim}) at {fr.path}:{fr.line}"
                if fr
                else f"{w.dtype} ({w.prim})"
                for w, fr in sites[:3]
            )
            extra = (
                f" (+{len(sites) - 3} more)" if len(sites) > 3 else ""
            )
            out.append(
                Finding(
                    "GL012",
                    path,
                    line,
                    f"{spec.name}:{arm}",
                    f"entry '{spec.name}' {why}: {detail}{extra}",
                )
            )
    return out


# ------------------------------------------------------------------ GL013
def check_donation(
    project: Project, entries: Sequence[TracedEntry]
) -> List[Finding]:
    out: List[Finding] = []
    for te in entries:
        if te.error:
            continue
        spec = te.spec
        donated = set(te.donate_argnums)
        for argnum, argname in spec.carried:
            if argnum in donated:
                continue
            nbytes = (
                te.arg_bytes[argnum]
                if argnum < len(te.arg_bytes)
                else 0
            )
            out.append(
                Finding(
                    "GL013",
                    spec.anchor[0],
                    spec.anchor[1],
                    f"{spec.name}:{argname}",
                    f"entry '{spec.name}' rebinds carried state "
                    f"'{argname}' (arg {argnum}, {nbytes} B) every "
                    f"iteration without donate_argnums — the dead input "
                    f"buffer stays live across the update, wasting "
                    f"{nbytes} B of HBM per live instance",
                )
            )
    return out


# ------------------------------------------------------------------ GL014
def check_vmem_budget(
    project: Project, entries: Sequence[TracedEntry]
) -> List[Finding]:
    limit = VMEM_LIMIT_BYTES[VMEM_TARGET]
    out: List[Finding] = []
    for te in entries:
        if te.error:
            continue
        for p in te.facts.pallas:
            est = p.vmem_estimate()
            if est <= limit:
                continue
            fr = p.frames[0] if p.frames else None
            path, line = _where(fr, te.spec)
            out.append(
                Finding(
                    "GL014",
                    path,
                    line,
                    f"vmem:{p.kernel}",
                    f"pallas kernel '{p.kernel}' (entry "
                    f"'{te.spec.name}') wants ~{est} B of VMEM "
                    f"(2x operand blocks {sum(p.block_bytes)} B + "
                    f"scratch {p.scratch_bytes} B, grid {p.grid}) > "
                    f"the {VMEM_TARGET} per-core limit of {limit} B",
                )
            )
    return out


# ------------------------------------------------------------------ GL015
def check_host_transfers(
    project: Project, entries: Sequence[TracedEntry]
) -> List[Finding]:
    out: List[Finding] = []
    for te in entries:
        if te.error or not te.spec.hot:
            continue
        for cb in te.facts.callbacks:
            inner = cb.frames[0] if cb.frames else None
            if inner is not None and inner.path == _SANCTIONED:
                continue
            path, line = _where(inner, te.spec)
            func = inner.func if inner else "?"
            out.append(
                Finding(
                    "GL015",
                    path,
                    line,
                    f"callback:{cb.kind}:{func}",
                    f"'{cb.kind}' compiled into hot entry "
                    f"'{te.spec.name}' forces a device->host round trip "
                    f"every iteration; only the obs.collectives timed "
                    f"wrappers are sanctioned callback sources",
                )
            )
    return out


RULE_CHECKS = {
    "GL011": check_collective_congruence,
    "GL012": check_dtype_promotion,
    "GL013": check_donation,
    "GL014": check_vmem_budget,
    "GL015": check_host_transfers,
}


def run_ir_rules(
    project: Project,
    entry_filter: Optional[Sequence[str]] = None,
    changed_modules: Optional[Sequence[str]] = None,
) -> Tuple[List[Finding], Dict[str, float], float]:
    """Trace the entry matrix and run GL011-GL015.

    ``entry_filter``: optional entry-name prefixes.  ``changed_modules``:
    optional package-relative .py paths (the --changed-only set) — an
    entry is traced only when its transitive AST module closure
    intersects them.  Returns (findings, per-rule wall seconds, trace
    seconds).
    """
    ir_mod.ensure_virtual_devices()
    t0 = time.monotonic()
    specs = ir_mod.build_entry_specs()
    if entry_filter:
        specs = [
            s
            for s in specs
            if any(s.name.startswith(p) for p in entry_filter)
        ]
    if changed_modules is not None:
        changed = set(changed_modules)
        specs = [
            s
            for s in specs
            if ir_mod.transitive_modules(project, s.root_modules)
            & changed
        ]
    entries = [ir_mod.trace_entry(s) for s in specs]
    trace_s = time.monotonic() - t0
    findings: List[Finding] = []
    timings: Dict[str, float] = {}
    for code, check in RULE_CHECKS.items():
        t1 = time.monotonic()
        findings.extend(check(project, entries))
        timings[code] = time.monotonic() - t1
    return findings, timings, trace_s
