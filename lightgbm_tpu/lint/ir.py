"""graftlint IR pass: trace the real jit/shard_map entries to jaxprs and
collect the facts the GL011-GL015 rules audit.

Unlike the AST pass (core.py docstring: never imports the scanned
modules), the IR pass deliberately IMPORTS the library and traces its
actual entry points under an abstract-input config matrix derived from
the perf-gate scenarios (tools/perf_gate.py: N=512, F=10, num_leaves=7,
max_bin=63->padded 64; serial / 8-way data / hybrid (4,2) / quantized).
Tracing uses ``jax.make_jaxpr`` with ``jax.ShapeDtypeStruct`` inputs
only — shapes and dtypes, no device buffers, no execution — so a full
matrix run is pure CPU trace time and fits the <30 s gate budget.

What the walker extracts per entry (recursively through every inner
jaxpr: pjit, scan, while, cond branches, shard_map, pallas_call):

* collective eqns (``psum``/``psum2``/``pmax``/``pmin``/``all_gather``
  ...) with axis names, payload bytes and the in-package source frames
  jax recorded at trace time — GL011 checks them against the sanctioned
  ``obs/collectives`` wrappers, the entry's declared mesh axes, the
  AST-level GL007 site model and the ``mesh_psum_bytes_per_iteration``
  analytic payload model;
* callback eqns (``io_callback``/``pure_callback``/...) with frames —
  GL015's per-iteration host-transfer audit (the timed-collective
  wrappers are the one sanctioned source);
* ``pallas_call`` eqns with block shapes, grid and scratch avals —
  GL014's static VMEM budget arithmetic;
* every aval's dtype/weak_type plus an optional second trace under
  ``enable_x64`` for entries declared ``x64_strict`` — GL012's
  promotion audit (an unpinned ``arange``/``random.uniform`` goes i64/
  f64 the moment someone flips x64 on);
* the entry's ``donate_argnums`` (read off the ``instrumented_jit``
  wrapper) and per-argument byte sizes — GL013's donation audit of the
  per-iteration carried buffers declared in each spec.

The entry registry is explicit: every spec names its expected collective
axes, its donation-required (carried) arguments and its root modules, so
``--changed-only`` can scope tracing to entries whose transitive module
set intersects the edited files.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import time
from pathlib import Path
from typing import Callable, Dict, FrozenSet, List, Optional, Sequence, Tuple

PKG_NAME = "lightgbm_tpu"

# perf-gate scenario geometry (tools/perf_gate.py collect()):
# X = rand(512, 10), num_leaves=7, max_bin=63 (padded bin axis 64)
N_ROWS = 512
N_FEATURES = 10
NUM_LEAVES = 7
MAX_BIN_PADDED = 64
N_TREES = 8  # predict-entry tree batch

# per-core VMEM budget table for GL014 (bytes).  ~16 MiB/core on every
# shipped TPU generation the repo targets (see /opt/skills guides); the
# rule's estimate is 2x the block working set (double buffering) plus
# scratch, so the limit is the full physical arena.
VMEM_LIMIT_BYTES = {
    "v5e": 16 * 1024 * 1024,
}
VMEM_TARGET = "v5e"


def ensure_virtual_devices(n: int = 8) -> None:
    """Set the CPU-mesh env for the mesh entries (8 virtual devices).

    XLA reads these at BACKEND INITIALIZATION (the first ``jax.devices()``
    call), not at ``import jax`` — so this works even after the package
    import chain has pulled jax in, as long as nothing touched a device
    yet.  If a backend is already live with fewer devices, the mesh
    entries degrade to per-entry trace errors rather than breaking the
    rest of the matrix."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()


# ------------------------------------------------------------------ facts
@dataclasses.dataclass(frozen=True)
class SrcFrame:
    path: str  # posix, relative to the repo root (lightgbm_tpu/...)
    line: int
    func: str


@dataclasses.dataclass
class CollectiveFact:
    kind: str  # psum | psum2 | pmax | pmin | all_gather | ...
    axes: Tuple[str, ...]
    payload_bytes: int
    frames: Tuple[SrcFrame, ...]  # in-package frames, innermost first


@dataclasses.dataclass
class CallbackFact:
    kind: str  # io_callback | pure_callback | debug_callback
    frames: Tuple[SrcFrame, ...]


@dataclasses.dataclass
class PallasFact:
    kernel: str
    grid: Tuple[int, ...]
    block_bytes: Tuple[int, ...]  # per in/out operand block
    scratch_bytes: int
    frames: Tuple[SrcFrame, ...]

    def vmem_estimate(self) -> int:
        # double-buffered operand blocks + scratch (resident for the
        # whole launch) — the standard Mosaic working-set model
        return 2 * sum(self.block_bytes) + self.scratch_bytes


@dataclasses.dataclass
class WideDtypeFact:
    dtype: str
    prim: str
    frames: Tuple[SrcFrame, ...]


@dataclasses.dataclass
class TraceFacts:
    collectives: List[CollectiveFact] = dataclasses.field(default_factory=list)
    callbacks: List[CallbackFact] = dataclasses.field(default_factory=list)
    pallas: List[PallasFact] = dataclasses.field(default_factory=list)
    wide: List[WideDtypeFact] = dataclasses.field(default_factory=list)
    weak_outputs: List[int] = dataclasses.field(default_factory=list)


# ------------------------------------------------------------------ specs
@dataclasses.dataclass
class EntrySpec:
    """One traced entry of the config matrix.

    ``build()`` returns ``(fn, args, kwargs)`` with abstract
    ShapeDtypeStruct leaves; ``axes`` is the complete set of mesh axis
    names collectives may legally reduce over; ``carried`` marks the
    positional arguments that are per-iteration dead state the caller
    always rebinds — GL013 requires each to be donated; ``x64_strict``
    entries are traced a second time under enable_x64 and must stay
    free of 64-bit avals (the dtype-pin contract); ``psum_model`` maps
    each axis to the byte payloads the analytic model allows."""

    name: str
    build: Callable[[], Tuple[Callable, tuple, dict]]
    anchor: Tuple[str, int]  # (repo-relative path, line) findings point at
    axes: FrozenSet[str] = frozenset()
    carried: Tuple[Tuple[int, str], ...] = ()  # (argnum, argname)
    x64_strict: bool = False
    psum_model: Optional[Callable[[], Dict[str, FrozenSet[int]]]] = None
    hot: bool = True  # reachable every training/predict iteration (GL015)
    root_modules: Tuple[str, ...] = ()  # package-relative .py paths


@dataclasses.dataclass
class TracedEntry:
    spec: EntrySpec
    facts: TraceFacts
    x64_wide: List[WideDtypeFact]
    donate_argnums: Tuple[int, ...]
    arg_bytes: Tuple[int, ...]  # per positional arg (pytree-leaf sum)
    elapsed_s: float
    error: Optional[str] = None  # trace failure (reported as a finding)


# ----------------------------------------------------------------- walker
_COLLECTIVE_PRIMS = {
    "psum",
    "psum2",
    "pmax",
    "pmin",
    "all_gather",
    "all_to_all",
    "reduce_scatter",
    "ppermute",
}
_CALLBACK_PRIMS = {"io_callback", "pure_callback", "debug_callback"}
_WIDE_DTYPES = {"float64", "int64", "uint64", "complex128"}


def _pkg_frames(eqn) -> Tuple[SrcFrame, ...]:
    """In-package source frames for an eqn, innermost first, lint/
    excluded (the tracer itself must never be 'the source')."""
    try:
        from jax._src import source_info_util as siu

        frames = []
        marker = os.sep + PKG_NAME + os.sep
        for fr in siu.user_frames(eqn.source_info):
            fname = fr.file_name or ""
            if marker not in fname:
                continue
            rel = PKG_NAME + "/" + fname.split(marker, 1)[1].replace(os.sep, "/")
            if rel.startswith(PKG_NAME + "/lint/"):
                continue
            frames.append(
                SrcFrame(path=rel, line=int(fr.start_line), func=fr.function_name)
            )
        return tuple(frames)
    except Exception:
        return ()


def _aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None or not hasattr(dtype, "itemsize"):
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n * int(dtype.itemsize)


def _dtype_name(aval) -> Optional[str]:
    dtype = getattr(aval, "dtype", None)
    if dtype is None:
        return None
    try:
        # extended dtypes (prng keys) have no numpy name that matters here
        return str(dtype.name) if hasattr(dtype, "name") else str(dtype)
    except Exception:
        return None


def _subjaxprs(params: dict):
    for v in params.values():
        items = v if isinstance(v, (list, tuple)) else (v,)
        for item in items:
            if hasattr(item, "eqns"):
                yield item
            elif hasattr(item, "jaxpr") and hasattr(item.jaxpr, "eqns"):
                yield item.jaxpr


def _pallas_fact(eqn) -> Optional[PallasFact]:
    try:
        gm = eqn.params.get("grid_mapping")
        nsi = eqn.params.get("name_and_src_info")
        kernel = getattr(nsi, "name", None) or "pallas_call"
        grid = tuple(int(g) for g in getattr(gm, "grid", ()) if isinstance(g, int))
        blocks = []
        for bm in getattr(gm, "block_mappings", ()):
            # only VMEM-resident operand blocks count toward the budget:
            # SMEM scalars are tiny and ANY operands stay in HBM (the
            # kernel DMAs windows into its own scratch, already counted)
            space = str(
                getattr(getattr(bm, "block_aval", None), "memory_space", "")
            ).lower()
            if "smem" in space or "any" in space:
                continue
            shape = [
                int(d) if isinstance(d, int) else 1
                for d in getattr(bm, "block_shape", ())
            ]
            asd = getattr(bm, "array_shape_dtype", None)
            itemsize = (
                int(asd.dtype.itemsize)
                if asd is not None and hasattr(asd.dtype, "itemsize")
                else 4
            )
            n = 1
            for d in shape:
                n *= d
            blocks.append(n * itemsize)
        scratch = 0
        inner = eqn.params.get("jaxpr")
        n_scratch = int(getattr(gm, "num_scratch_operands", 0) or 0)
        if inner is not None and n_scratch:
            for v in list(inner.invars)[-n_scratch:]:
                aval = getattr(v, "aval", None)
                base = getattr(aval, "inner_aval", aval)
                scratch += _aval_bytes(base)
        return PallasFact(
            kernel=str(kernel),
            grid=grid,
            block_bytes=tuple(blocks),
            scratch_bytes=scratch,
            frames=_pkg_frames(eqn),
        )
    except Exception:
        return None


def walk_jaxpr(jaxpr, facts: TraceFacts) -> None:
    """Recursively collect facts from a (Closed)Jaxpr."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in inner.eqns:
        name = eqn.primitive.name
        if name in _COLLECTIVE_PRIMS:
            params = eqn.params
            axes = params.get("axes", params.get("axis_name", ()))
            if not isinstance(axes, (tuple, list)):
                axes = (axes,)
            axes = tuple(str(a) for a in axes)
            payload = sum(
                _aval_bytes(getattr(v, "aval", None)) for v in eqn.invars
            )
            facts.collectives.append(
                CollectiveFact(
                    kind=name,
                    axes=axes,
                    payload_bytes=payload,
                    frames=_pkg_frames(eqn),
                )
            )
        elif name in _CALLBACK_PRIMS:
            facts.callbacks.append(
                CallbackFact(kind=name, frames=_pkg_frames(eqn))
            )
        elif name == "pallas_call":
            pf = _pallas_fact(eqn)
            if pf is not None:
                facts.pallas.append(pf)
        for v in eqn.outvars:
            dn = _dtype_name(getattr(v, "aval", None))
            if dn in _WIDE_DTYPES:
                facts.wide.append(
                    WideDtypeFact(dtype=dn, prim=name, frames=_pkg_frames(eqn))
                )
        for sub in _subjaxprs(eqn.params):
            walk_jaxpr(sub, facts)
    for i, v in enumerate(getattr(inner, "outvars", ())):
        aval = getattr(v, "aval", None)
        if getattr(aval, "weak_type", False):
            facts.weak_outputs.append(i)


# --------------------------------------------------------------- registry
def _sds(shape, dtype):
    import jax

    return jax.ShapeDtypeStruct(shape, dtype)


def _grower_params(**over):
    from ..ops.grower import GrowerParams

    base = dict(
        num_leaves=NUM_LEAVES,
        max_bin=MAX_BIN_PADDED,
        min_data_in_leaf=5,
        hist_mode="ordered",
    )
    base.update(over)
    return GrowerParams(**base)


def _grow_operands(n_local: int, f: int):
    """The 17 positional operands of the parallel/sharded_grow entry, in
    gbdt._grow_one_inner order, as abstract leaves (dummies statically
    gated off inside grow_tree, mirroring _setup_sharded_grower)."""
    import jax.numpy as jnp

    f32, i32 = jnp.float32, jnp.int32
    return (
        _sds((n_local, f), jnp.uint8),  # bins
        _sds((n_local,), f32),  # grad
        _sds((n_local,), f32),  # hess
        _sds((n_local,), f32),  # count_mask
        _sds((f,), i32),  # num_bins
        _sds((f,), i32),  # nan_bins
        _sds((f,), jnp.bool_),  # feature_mask
        _sds((f,), jnp.int8),  # monotone (dummy)
        _sds((1, f), jnp.bool_),  # interaction_sets (dummy)
        _sds((2,), jnp.uint32),  # rng
        _sds((f,), jnp.bool_),  # is_cat (dummy)
        None,  # forced
        _sds((f,), f32),  # cegb_penalty (dummy)
        _sds((f,), jnp.bool_),  # cegb_used (dummy)
        (_sds((), f32), _sds((), f32)),  # quant_scales (dummy)
        _sds((1, 1), i32),  # bundle_end (dummy)
        _sds((f,), f32),  # feature_contri (dummy)
    )


def _grow_psum_model(spec, leaf_batch: int) -> Dict[str, FrozenSet[int]]:
    """Per-axis allowed collective payload bytes, derived from the same
    formula pieces as ``mesh_psum_bytes_per_iteration`` — GL011's
    congruence contract.  The analytic model counts per-iteration
    TOTALS; statically a jaxpr shows each loop-body site once, so the
    allowed set holds the per-site payloads the model is built from:

    * 'data': the [K, F_loc, B, 3] frontier histogram psum (or its two
      db0/db1 halves under overlap), the [F_loc, B, 3] root histogram,
      and the small per-step count payloads (2 x i32/f32 per member,
      plus the serial root [2]);
    * 'feature': the 11-value winner-election broadcast and the [3]
      root-totals psum.
    """
    f_loc = (
        N_FEATURES // spec.feature if spec.feature > 1 else N_FEATURES
    )
    hist = f_loc * MAX_BIN_PADDED * 3 * 4
    k = max(1, leaf_batch)
    allowed: Dict[str, FrozenSet[int]] = {}
    if spec.data > 1:
        allowed["data"] = frozenset(
            {
                hist,  # root / per-step smaller-child histogram
                k * hist,  # batched frontier histogram [K, F_loc, B, 3]
                k * hist // 2,  # overlap db0/db1 half-batch planes
                4,  # scalar count / stat psum (f32 or i32)
                8,  # [2] count pair
                k * 4,  # per-member scalar ([K])
                k * 2 * 4,  # per-member count pair [K, 2]
            }
        )
    if spec.feature > 1:
        allowed["feature"] = frozenset(
            {
                11 * 4,  # winner-election broadcast (11 packed values)
                k * 11 * 4,  # batched election [K, 11]
                3 * 4,  # root-totals (g, h, count)
                4,
                8,
                k * 4,
            }
        )
    return allowed


def _entry_mesh(layout: str, data: int, feature: int):
    from ..parallel.mesh import MeshSpec, build_mesh

    spec = MeshSpec(layout, data=data, feature=feature)
    return spec, build_mesh(spec)


def _anchor(module, obj_name: str) -> Tuple[str, int]:
    """(repo-relative path, def line) for a module-level callable, via
    the AST — stable even for decorated/wrapped objects."""
    import ast

    path = Path(module.__file__)
    marker = PKG_NAME
    parts = path.as_posix().split("/" + marker + "/")
    rel = marker + "/" + parts[-1] if len(parts) > 1 else path.name
    try:
        tree = ast.parse(path.read_text())
        for node in ast.walk(tree):
            if (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name == obj_name
            ):
                return rel, node.lineno
            if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == obj_name
                for t in node.targets
            ):
                return rel, node.lineno
    except Exception:
        pass
    return rel, 1


def build_entry_specs() -> List[EntrySpec]:
    """The config matrix: every spec mirrors a perf-gate scenario (or a
    kernel wrapper the scenarios lower through on TPU)."""
    import jax.numpy as jnp

    from ..ops import grower as grower_mod
    from ..ops import quantize as quantize_mod
    from ..ops import tensor_forest as tf_mod
    from ..ops.pallas import histogram as ph_mod
    from ..ops.pallas import seg as seg_mod
    from .. import predict as predict_mod
    from ..boosting import gbdt as gbdt_mod

    f32, i32 = jnp.float32, jnp.int32
    N, F, L, T = N_ROWS, N_FEATURES, NUM_LEAVES, N_TREES
    specs: List[EntrySpec] = []

    # ---- grower entries (serial / data / batched+overlap / hybrid)
    def grow_entry(name, layout, data, feature, leaf_batch=1, overlap=False,
                   measure=False, hist_mode="ordered"):
        def build():
            from ..parallel.mesh import MeshSpec, make_mesh_grow

            if data * feature > 1:
                spec, mesh = _entry_mesh(layout, data, feature)
            else:
                spec, mesh = MeshSpec("data", data=1), None
            params = _grower_params(
                leaf_batch=leaf_batch,
                overlap_collectives=overlap,
                measure_collectives=measure,
                hist_mode=hist_mode,
                grow_fused=hist_mode == "seg",
            )
            fn = make_mesh_grow(mesh, params, spec)
            n_local = N  # shard_map operands are GLOBAL shapes
            return fn, _grow_operands(n_local, F), {}

        from ..parallel.mesh import MeshSpec

        spec = MeshSpec(layout if data * feature > 1 else "data",
                        data=data, feature=feature)
        axes = set()
        if data > 1:
            axes.add("data")
        if feature > 1:
            axes.add("feature")
        return EntrySpec(
            name=name,
            build=build,
            anchor=_anchor(grower_mod, "grow_tree"),
            axes=frozenset(axes),
            psum_model=lambda s=spec, k=leaf_batch: _grow_psum_model(s, k),
            root_modules=(
                "ops/grower.py",
                "parallel/mesh.py",
                "obs/collectives.py",
                "ops/histogram.py",
                "ops/split.py",
            ),
        )

    specs.append(grow_entry("grow/serial", "data", 1, 1))
    specs.append(
        grow_entry("grow/data8", "data", 8, 1, measure=True)
    )
    specs.append(
        grow_entry(
            "grow/data8_k4", "data", 8, 1, leaf_batch=4, overlap=True,
            measure=True,
        )
    )
    specs.append(
        grow_entry(
            "grow/hybrid42", "hybrid", 4, 2, measure=True,
            hist_mode="gather",
        )
    )
    # fused grow step (hist_mode="seg" implies grow_fused): the TPU
    # production path — traces the seg/partition pallas kernels for GL014
    specs.append(grow_entry("grow/seg_fused", "data", 1, 1, hist_mode="seg"))

    # ---- fleet grow (perf-gate fleet scenario): the M=4 vmapped grow
    # step on the data mesh.  Every collective payload inside the member
    # vmap carries a leading [M] axis, so the sanctioned per-site bytes
    # are exactly M x the solo model (the same scaling
    # fleet_psum_bytes_per_iteration pins analytically).
    FLEET_M = 4

    def build_fleet_grow():
        from ..parallel.mesh import make_fleet_grow

        spec, mesh = _entry_mesh("data", 8, 1)
        params = _grower_params(measure_collectives=True)
        fn = make_fleet_grow(mesh, params, spec)
        ops = list(_grow_operands(N, F))
        for idx in (1, 2, 3, 6, 9):  # grad, hess, mask, feature_mask, rng
            o = ops[idx]
            ops[idx] = _sds((FLEET_M,) + o.shape, o.dtype)
        return fn, tuple(ops), {}

    def _fleet_psum_model():
        from ..parallel.mesh import MeshSpec

        solo = _grow_psum_model(MeshSpec("data", data=8), leaf_batch=1)
        model = {
            axis: frozenset(FLEET_M * v for v in vals)
            for axis, vals in solo.items()
        }
        # capacity-ladder pmax over the vmapped member axis: a scalar i32
        # bucket size per member (the only cross-member collective).  The
        # vmap batching rule rewrites the named-axis pmax into a
        # positional reduction, so the jaxpr records axis '0' with the
        # batched [M] operand
        for ax in ("fleet", "0"):
            model[ax] = frozenset({4, FLEET_M * 4})
        return model

    specs.append(
        EntrySpec(
            name="grow/fleet_m4_data8",
            build=build_fleet_grow,
            anchor=_anchor(grower_mod, "grow_tree"),
            axes=frozenset({"data", "fleet", "0"}),
            psum_model=_fleet_psum_model,
            root_modules=(
                "ops/grower.py",
                "parallel/mesh.py",
                "obs/collectives.py",
                "ops/histogram.py",
                "ops/split.py",
            ),
        )
    )

    # ---- device-resident launch scan (perf-gate launch scenario): the
    # REAL production N=4 launch body over the data-8 mesh, built from a
    # live Booster so the traced jaxpr is exactly what training runs.
    # GL011 walks into the lax.scan body (walk_jaxpr recurses through
    # sub-jaxprs) and must find each psum site once with the SAME
    # payloads as the solo grow/data8 model — the scan multiplies trip
    # count, never payload shape.  GL013 requires the scanned carry
    # (the donated score cache, arg 0) to hand its buffer back.
    def build_launch_scan():
        import numpy as np

        from ..boosting import create_booster
        from ..boosting.launch import LaunchRunner
        from ..dataset import Dataset

        rng = np.random.RandomState(3)
        Xl = rng.rand(N, F).astype(np.float32)
        yl = (Xl[:, 0] + 0.25 * Xl[:, 1]).astype(np.float32)
        b = create_booster(
            {
                "objective": "regression",
                "num_leaves": NUM_LEAVES,
                "max_bin": MAX_BIN_PADDED - 1,  # pads back to MAX_BIN_PADDED
                "min_data_in_leaf": 5,
                "verbosity": -1,
                "tree_learner": "data",
                "num_machines": 8,
            },
            Dataset(Xl, label=yl),
        )
        runner = LaunchRunner(b, 4)
        args = (
            _sds(tuple(b._score.shape), b._score.dtype),  # score (carried)
            _sds((2,), jnp.uint32),  # rng key
            _sds((1,), f32),  # bagging-mask carry (dummy: no sampling)
            _sds((4,), i32),  # iteration numbers
            _sds((4, b._bins.shape[1]), jnp.bool_),  # feature masks
            _sds(tuple(b._bins.shape), b._bins.dtype),  # bins
            _sds((b._bins.shape[0],), f32),  # ones_mask
            _sds((1,), f32),  # fixed-row mask (dummy)
        )
        return runner._fn, args, {}

    def _scan_psum_model():
        from ..parallel.mesh import MeshSpec

        return _grow_psum_model(MeshSpec("data", data=8), leaf_batch=1)

    from ..boosting import launch as launch_mod

    specs.append(
        EntrySpec(
            name="grow/scan4_data8",
            build=build_launch_scan,
            anchor=_anchor(launch_mod, "LaunchRunner"),
            axes=frozenset({"data"}),
            carried=((0, "score"),),
            psum_model=_scan_psum_model,
            root_modules=(
                "boosting/launch.py",
                "boosting/gbdt.py",
                "ops/grower.py",
                "parallel/mesh.py",
                "obs/collectives.py",
                "ops/histogram.py",
                "ops/split.py",
            ),
        )
    )

    # ---- quantized training entries (perf-gate quantized scenario)
    def build_quantize():
        fn = quantize_mod.quantize_gradients
        args = (_sds((N,), f32), _sds((N,), f32), _sds((2,), jnp.uint32))
        return (
            lambda g, h, r: fn(g, h, r, num_bins=4, stochastic=True),
            args,
            {},
        )

    specs.append(
        EntrySpec(
            name="quant/quantize_gradients",
            build=build_quantize,
            anchor=_anchor(quantize_mod, "quantize_gradients"),
            x64_strict=True,
            root_modules=("ops/quantize.py",),
        )
    )

    def build_renew():
        fn = quantize_mod.renew_leaf_values
        args = (
            _sds((N,), i32),
            _sds((N,), f32),
            _sds((N,), f32),
            _sds((N,), f32),
            _sds((), i32),
        )
        return (
            lambda lid, g, h, m, nl: fn(
                lid, g, h, m, nl, NUM_LEAVES, 0.0, 0.0, 0.0
            ),
            args,
            {},
        )

    specs.append(
        EntrySpec(
            name="quant/renew_leaf_values",
            build=build_renew,
            anchor=_anchor(quantize_mod, "renew_leaf_values"),
            x64_strict=True,
            root_modules=("ops/quantize.py", "ops/split.py"),
        )
    )

    # ---- boosting score updates (per-iteration carried state: GL013)
    def build_score_update():
        fn = gbdt_mod._apply_tree_score
        args = (
            _sds((1, N), f32),
            _sds((L,), f32),
            _sds((N,), i32),
            _sds((), i32),
        )
        return fn, args, {}

    specs.append(
        EntrySpec(
            name="boost/score_update",
            build=build_score_update,
            anchor=_anchor(gbdt_mod, "_apply_tree_score"),
            carried=((0, "score"),),
            x64_strict=True,
            root_modules=("boosting/gbdt.py",),
        )
    )

    def build_valid_score_update():
        fn = gbdt_mod._apply_tree_valid_score
        args = (
            _sds((1, N), f32),  # score (carried)
            _sds((N, F), jnp.uint8),  # bins
            _sds((F,), i32),  # nan_bins
            _sds((L - 1,), i32),  # split_feature
            _sds((L - 1,), i32),  # split_bin
            _sds((L - 1,), jnp.bool_),  # default_left
            _sds((L - 1,), i32),  # left_child
            _sds((L - 1,), i32),  # right_child
            _sds((L,), f32),  # leaf_value
            _sds((L - 1,), jnp.bool_),  # split_is_cat
            _sds((L - 1, 1), jnp.bool_),  # cat_mask
            _sds((), i32),  # kk
        )
        return fn, args, {}

    specs.append(
        EntrySpec(
            name="boost/valid_score_update",
            build=build_valid_score_update,
            anchor=_anchor(gbdt_mod, "_apply_tree_valid_score"),
            carried=((0, "score"),),
            x64_strict=True,
            root_modules=("boosting/gbdt.py", "predict.py"),
        )
    )

    # ---- tree-state handoff (pipelined path donates its dead TreeArrays)
    def build_pack():
        from ..ops.grower import TreeArrays

        fn = grower_mod.pack_tree_arrays_donated
        nn = L - 1
        ta = grower_mod.TreeArrays(
            split_feature=_sds((nn,), i32),
            split_bin=_sds((nn,), i32),
            split_gain=_sds((nn,), f32),
            default_left=_sds((nn,), jnp.bool_),
            left_child=_sds((nn,), i32),
            right_child=_sds((nn,), i32),
            internal_value=_sds((nn,), f32),
            internal_weight=_sds((nn,), f32),
            internal_count=_sds((nn,), f32),
            leaf_value=_sds((L,), f32),
            leaf_weight=_sds((L,), f32),
            leaf_count=_sds((L,), f32),
            leaf_depth=_sds((L,), i32),
            num_leaves=_sds((), i32),
            grow_steps=_sds((), i32),
            refine_count=_sds((), i32),
            split_is_cat=_sds((nn,), jnp.bool_),
            cat_mask=_sds((nn, 1), jnp.bool_),
        )
        return fn, (ta,), {}

    specs.append(
        EntrySpec(
            name="grower/pack_tree_arrays",
            build=build_pack,
            anchor=_anchor(grower_mod, "pack_tree_arrays_donated"),
            carried=((0, "ta"),),
            x64_strict=True,
            root_modules=("ops/grower.py",),
        )
    )

    # ---- streaming predict entries + the donated score walk
    def build_predict(variant):
        def build():
            from ..predict import BinTreeBatch

            batch = BinTreeBatch(
                split_feature=_sds((T, L - 1), i32),
                split_bin=_sds((T, L - 1), i32),
                default_left=_sds((T, L - 1), jnp.bool_),
                left_child=_sds((T, L - 1), i32),
                right_child=_sds((T, L - 1), i32),
                leaf_value=_sds((T, L), f32),
                split_is_cat=_sds((T, L - 1), jnp.bool_),
                cat_mask=_sds((T, L - 1, 1), jnp.bool_),
            )
            fn = getattr(predict_mod, f"_predict_bins_{variant}_impl")
            args = (batch, _sds((N, F), jnp.uint8), _sds((F,), i32))
            return fn, args, {}

        return build

    for variant in ("raw", "leaves"):
        specs.append(
            EntrySpec(
                name=f"predict/bins_{variant}",
                build=build_predict(variant),
                anchor=_anchor(predict_mod, f"_predict_bins_{variant}_impl"),
                x64_strict=True,
                root_modules=("predict.py",),
            )
        )

    # ---- tensor-forest (pred_engine=matmul) contraction entries: the
    # direct compiler impls plus the streaming variant pulled out of the
    # engine's own dispatch table (_STREAM_IMPLS), so the audited callable
    # is exactly what the bucket ladder AOT-compiles.  Geometry mirrors the
    # eligibility sweet spot at gate scale: depth 3, 8 trees.
    TF_DEPTH = 3
    TF_PTREE = (1 << TF_DEPTH) - 1
    TF_LP = 1 << TF_DEPTH

    def build_tensor(fn_getter):
        def build():
            forest = tf_mod.TensorForest(
                sel=_sds((F, T * TF_PTREE), jnp.int8),
                thr=_sds((T * TF_PTREE,), i32),
                nanb=_sds((T * TF_PTREE,), i32),
                dleft=_sds((T * TF_PTREE,), jnp.bool_),
                routes=_sds((TF_PTREE, TF_LP), jnp.int8),
                leaf_val=_sds((T, TF_LP), f32),
                leaf_idx=_sds((T, TF_LP), i32),
            )
            args = (forest, _sds((N, F), i32))
            return fn_getter(), args, {}

        return build

    for kind in ("pertree", "leaves"):
        specs.append(
            EntrySpec(
                name=f"predict/tensor_{kind}",
                build=build_tensor(
                    lambda k=kind: getattr(tf_mod, f"_tensor_bins_{k}_impl")
                ),
                anchor=_anchor(tf_mod, f"_tensor_bins_{kind}_impl"),
                x64_strict=True,
                root_modules=("ops/tensor_forest.py",),
            )
        )
    specs.append(
        EntrySpec(
            name="predict/tensor_stream",
            build=build_tensor(
                lambda: predict_mod._STREAM_IMPLS[("tensor", "value")]
            ),
            anchor=_anchor(predict_mod, "_STREAM_IMPLS"),
            x64_strict=True,
            root_modules=("predict.py", "ops/tensor_forest.py"),
        )
    )

    def build_add_tree():
        fn = predict_mod.add_tree_to_score
        args = (
            _sds((N,), f32),  # score_k (donated)
            _sds((N, F), jnp.uint8),
            _sds((F,), i32),
            _sds((L - 1,), i32),
            _sds((L - 1,), i32),
            _sds((L - 1,), jnp.bool_),
            _sds((L - 1,), i32),
            _sds((L - 1,), i32),
            _sds((L,), f32),
        )
        return fn, args, {}

    specs.append(
        EntrySpec(
            name="predict/add_tree_to_score",
            build=build_add_tree,
            anchor=_anchor(predict_mod, "add_tree_to_score"),
            carried=((0, "score_k"),),
            x64_strict=True,
            root_modules=("predict.py",),
        )
    )

    # ---- Pallas kernel wrappers (GL014 VMEM arithmetic material).
    # Traced with interpret=False: make_jaxpr only records the pallas_call
    # eqn — Mosaic never runs, so this works on the CPU gate.
    def build_hist_pallas():
        fn = ph_mod.histogram_pallas

        def call(bins, grad, hess, mask):
            return fn(bins, grad, hess, mask, num_bins=MAX_BIN_PADDED)

        args = (
            _sds((N, F), i32),
            _sds((N,), f32),
            _sds((N,), f32),
            _sds((N,), f32),
        )
        return call, args, {}

    specs.append(
        EntrySpec(
            name="pallas/histogram",
            build=build_hist_pallas,
            anchor=_anchor(ph_mod, "histogram_pallas"),
            root_modules=("ops/pallas/histogram.py",),
        )
    )

    def build_seg_batch():
        fn = seg_mod.seg_hist_pallas_batch
        k = 4
        n_pad = seg_mod.padded_rows(N)
        lanes = seg_mod.storage_lanes(F)

        def call(seg, scal):
            return fn(seg, scal, f=F, num_bins=MAX_BIN_PADDED, n_pad=n_pad)

        args = (
            _sds((lanes, n_pad), jnp.int16),  # pack_rows plane-major layout
            _sds((k, 2), i32),  # (start, cnt) per batch member
        )
        return call, args, {}

    specs.append(
        EntrySpec(
            name="pallas/seg_hist_batch",
            build=build_seg_batch,
            anchor=_anchor(seg_mod, "seg_hist_pallas_batch"),
            root_modules=("ops/pallas/seg.py",),
        )
    )

    return specs


# ----------------------------------------------------------------- tracer
def _flat_arg_bytes(args) -> Tuple[int, ...]:
    import jax

    out = []
    for a in args:
        leaves = jax.tree_util.tree_leaves(a)
        out.append(sum(_aval_bytes(l) for l in leaves))
    return tuple(out)


def _donate_argnums(fn) -> Tuple[int, ...]:
    kw = getattr(fn, "jit_kwargs", None)
    if not isinstance(kw, dict):
        return ()
    dn = kw.get("donate_argnums", ())
    if isinstance(dn, int):
        dn = (dn,)
    return tuple(int(i) for i in dn)


def trace_entry(spec: EntrySpec) -> TracedEntry:
    import jax

    t0 = time.monotonic()
    try:
        fn, args, kwargs = spec.build()
        jaxpr = jax.make_jaxpr(fn)(*args, **kwargs)
        facts = TraceFacts()
        walk_jaxpr(jaxpr, facts)
        x64_wide: List[WideDtypeFact] = []
        if spec.x64_strict:
            from jax.experimental import enable_x64

            with enable_x64():
                jaxpr64 = jax.make_jaxpr(fn)(*args, **kwargs)
            f64 = TraceFacts()
            walk_jaxpr(jaxpr64, f64)
            x64_wide = f64.wide
        # donation is declared on the underlying instrumented_jit entry;
        # builders that wrap it in an adapter lambda tag the wrapper via
        # __wrapped_entry__ so the declaration stays readable
        donate = _donate_argnums(getattr(fn, "__wrapped_entry__", fn))
        return TracedEntry(
            spec=spec,
            facts=facts,
            x64_wide=x64_wide,
            donate_argnums=donate,
            arg_bytes=_flat_arg_bytes(args),
            elapsed_s=time.monotonic() - t0,
        )
    except Exception as exc:  # trace failure IS a finding (GL011 reports it)
        return TracedEntry(
            spec=spec,
            facts=TraceFacts(),
            x64_wide=[],
            donate_argnums=(),
            arg_bytes=(),
            elapsed_s=time.monotonic() - t0,
            error=f"{type(exc).__name__}: {exc}",
        )


def transitive_modules(
    project, roots: Sequence[str]
) -> FrozenSet[str]:
    """Package-relative module closure reachable from ``roots`` through
    the AST import graph (lint.core.Project.imports)."""
    seen = set()
    stack = [r for r in roots if r in project.modules]
    while stack:
        rel = stack.pop()
        if rel in seen:
            continue
        seen.add(rel)
        mod = project.modules[rel]
        for entry in mod.imports.values():
            target = None
            if entry[0] == "mod":
                target = entry[1]
            elif entry[0] == "obj":
                target = entry[1]
            if target is not None and target not in seen:
                stack.append(target)
    return frozenset(seen)


def trace_entries(
    names: Optional[Sequence[str]] = None,
) -> List[TracedEntry]:
    """Trace the matrix (or the name-prefix-filtered subset)."""
    specs = build_entry_specs()
    if names:
        specs = [
            s for s in specs if any(s.name.startswith(p) for p in names)
        ]
    return [trace_entry(s) for s in specs]


# ------------------------------------------------------------- debug dump
def _dump(entries: List[TracedEntry]) -> None:
    for te in entries:
        print(f"== {te.spec.name}  [{te.elapsed_s:.2f}s]")
        if te.error:
            print(f"   TRACE ERROR: {te.error}")
            continue
        print(f"   donate={te.donate_argnums} arg_bytes={te.arg_bytes}")
        for c in te.facts.collectives:
            src = c.frames[0] if c.frames else None
            print(
                f"   {c.kind} axes={c.axes} payload={c.payload_bytes}B "
                f"@ {src.path}:{src.line} ({src.func})" if src else
                f"   {c.kind} axes={c.axes} payload={c.payload_bytes}B @ ?"
            )
        for cb in te.facts.callbacks:
            src = cb.frames[0] if cb.frames else None
            where = f"{src.path}:{src.line} ({src.func})" if src else "?"
            print(f"   callback {cb.kind} @ {where}")
        for p in te.facts.pallas:
            print(
                f"   pallas {p.kernel} grid={p.grid} blocks={p.block_bytes} "
                f"scratch={p.scratch_bytes} est={p.vmem_estimate()}"
            )
        for w in te.facts.wide:
            src = w.frames[0] if w.frames else None
            where = f"{src.path}:{src.line}" if src else "?"
            print(f"   WIDE {w.dtype} in {w.prim} @ {where}")
        if te.facts.weak_outputs:
            print(f"   WEAK outputs: {te.facts.weak_outputs}")
        for w in te.x64_wide:
            src = w.frames[0] if w.frames else None
            where = f"{src.path}:{src.line}" if src else "?"
            print(f"   X64-WIDE {w.dtype} in {w.prim} @ {where}")


if __name__ == "__main__":
    ensure_virtual_devices()
    t0 = time.monotonic()
    entries = trace_entries(sys.argv[1:] or None)
    _dump(entries)
    print(f"total: {time.monotonic() - t0:.2f}s for {len(entries)} entries")
