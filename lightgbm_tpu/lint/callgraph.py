"""Conservative intra-package call graph + tracer-taint propagation.

Entry points are the places a value becomes a tracer:

* jit-wrapped functions — ``@instrumented_jit``, ``@jax.jit``,
  ``@functools.partial(instrumented_jit, static_argnames=...)``, and the
  assignment forms ``g = instrumented_jit(f, ...)`` / ``jax.jit(f)``;
* Pallas kernel bodies — the first argument of ``pl.pallas_call`` (resolved
  through a local ``functools.partial(kernel_fn, ...)`` binding).

Taint model (deliberately simple, biased against false positives):

* at a jit entry every parameter is tainted EXCEPT names listed in
  ``static_argnames``; in a pallas kernel every parameter (ref) is tainted;
* an assignment whose right-hand side mentions a tainted name taints its
  targets; a call result is tainted iff any argument is tainted;
* taint flows into in-package callees positionally/by keyword, computed to
  a fixpoint over (function, tainted-param-set) pairs — the "conservative
  intra-package call graph" of GL003;
* ``*args``/``**kwargs`` forwarding is modeled coarsely: a tainted splat
  taints every remaining positional slot (plus the callee's ``*args``), a
  tainted ``**mapping`` taints every keyword-bindable parameter (plus the
  callee's ``**kwargs``) — over-approximate at the forwarding site, which
  is the right bias for GL003/GL010 taint.  Aliasing through containers is
  still NOT modeled: an un-modeled flow can only lose taint, i.e. miss a
  finding, never invent one.

The SPMD layer (:class:`SpmdIndex`, rules_spmd.py) adds a path-sensitive
abstract walk under "all replicas execute this together" semantics: every
function scope is analyzed with the stack of guards dominating each
``psum``/``pmax``/``pmin``/``all_gather`` site (including guards inherited
from a nested function's definition site and ``if not guard: return``
early-return dominators), an *axis-derived* name family that marks guards
as trace-static, and depth-bounded collective summaries of branches and
callees for congruence checks.
"""

from __future__ import annotations

import ast
import dataclasses
from collections import Counter
from typing import Callable, Dict, FrozenSet, List, Optional, Set, Tuple

from .core import Module, Project, call_kwargs, const_names, names_in

_JIT_NAMES = {"instrumented_jit"}
_JIT_DOTTED = {"jax.jit", "jax.pmap", "jax.obs.jit.instrumented_jit"}


def _jit_wrapper_call(
    project: Project, mod: Module, node: ast.AST
) -> Optional[ast.Call]:
    """Return the jit-wrapper Call if ``node`` is one (possibly through
    ``functools.partial(<jit>, ...)``), else None."""
    if not isinstance(node, ast.Call):
        return None
    dotted = project.dotted_callee(mod, node.func)
    name = node.func.id if isinstance(node.func, ast.Name) else (
        node.func.attr if isinstance(node.func, ast.Attribute) else None
    )
    if dotted in _JIT_DOTTED or name in _JIT_NAMES:
        return node
    if dotted == "functools.partial" and node.args:
        inner = node.args[0]
        idotted = project.dotted_callee(mod, inner)
        iname = inner.id if isinstance(inner, ast.Name) else None
        if idotted in _JIT_DOTTED or iname in _JIT_NAMES:
            return node
    return None


def jit_entries(
    project: Project,
) -> List[Tuple[str, Module, ast.FunctionDef, FrozenSet[str]]]:
    """All jit entry points: (module_rel, module, func, static_argnames).

    Memoized per Project: five rules call this and the full-tree ast.walk
    dominates lint CPU; callers only iterate the result.
    """
    cached = project.__dict__.get("_jit_entries_cache")
    if cached is not None:
        return cached
    out = []
    for rel, mod in project.modules.items():
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    call = _jit_wrapper_call(project, mod, deco)
                    is_bare = not isinstance(deco, ast.Call) and (
                        project.dotted_callee(mod, deco) in _JIT_DOTTED
                        or (
                            isinstance(deco, ast.Name)
                            and deco.id in _JIT_NAMES
                        )
                    )
                    if call is None and not is_bare:
                        continue
                    statics: Set[str] = set()
                    if call is not None:
                        names = const_names(
                            call_kwargs(call).get("static_argnames", ast.Tuple(elts=[]))
                        )
                        statics = set(names or ())
                    out.append((rel, mod, node, frozenset(statics)))
                    break
            elif isinstance(node, ast.Call):
                # assignment / expression form: instrumented_jit(fn, ...)
                call = _jit_wrapper_call(project, mod, node)
                if call is None or call is not node or not node.args:
                    continue
                target = project.internal_callee(mod, rel, node.args[0])
                if target is None:
                    continue
                fn = project.function(*target)
                if fn is None or fn.name in _JIT_NAMES:
                    # the instrumented_jit wrapper forwards itself through
                    # functools.partial — the wrapper is not an entry
                    continue
                names = const_names(
                    call_kwargs(node).get("static_argnames", ast.Tuple(elts=[]))
                )
                out.append(
                    (target[0], project.modules[target[0]], fn,
                     frozenset(names or ()))
                )
    project.__dict__["_jit_entries_cache"] = out
    return out


def pallas_call_sites(
    project: Project,
) -> List[Tuple[str, Module, ast.Call, Optional[Tuple[str, ast.FunctionDef]], str]]:
    """All ``pl.pallas_call(...)`` sites with their resolved kernel body:
    (module_rel, module, call_node, (module_rel, kernel_def) | None,
    enclosing_function_name).

    The kernel argument is resolved through one level of local binding:
    a bare function name, or ``k = functools.partial(kernel_fn, ...)``
    assigned in the enclosing function before the call.  Sites inside
    nested functions resolve against their INNERMOST enclosing scope
    (``ast.walk`` yields outer scopes first, so the last write wins).

    Memoized per Project, like :func:`jit_entries` — three rules re-walk
    otherwise and callers only iterate.
    """
    cached = project.__dict__.get("_pallas_sites_cache")
    if cached is not None:
        return cached
    sites: Dict[int, Tuple] = {}
    for rel, mod in project.modules.items():
        for encl in ast.walk(mod.tree):
            if not isinstance(encl, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            # enclosing-scope partial bindings: name -> wrapped func expr
            local_partials: Dict[str, ast.AST] = {}
            for node in ast.walk(encl):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and isinstance(node.value, ast.Call):
                    dotted = project.dotted_callee(mod, node.value.func)
                    if dotted == "functools.partial" and node.value.args:
                        local_partials[node.targets[0].id] = node.value.args[0]
            for node in ast.walk(encl):
                if not isinstance(node, ast.Call):
                    continue
                dotted = project.dotted_callee(mod, node.func)
                if dotted is None or not dotted.endswith(".pallas_call"):
                    continue
                kernel = None
                if node.args:
                    kexpr = node.args[0]
                    if isinstance(kexpr, ast.Name) and kexpr.id in local_partials:
                        kexpr = local_partials[kexpr.id]
                    target = project.internal_callee(mod, rel, kexpr)
                    if target is not None:
                        fn = project.function(*target)
                        if fn is not None:
                            kernel = (target[0], fn)
                sites[id(node)] = (rel, mod, node, kernel, encl.name)
    out = list(sites.values())
    project.__dict__["_pallas_sites_cache"] = out
    return out


def positional_params(fn: ast.FunctionDef) -> List[str]:
    return [a.arg for a in fn.args.posonlyargs + fn.args.args]


class TaintWalker:
    """Propagate tracer taint from entry functions through the in-package
    call graph, invoking ``visit(module_rel, func, tainted_names, node)``
    on every statement-level AST node of every reached function."""

    def __init__(
        self,
        project: Project,
        visit: Callable[[str, ast.FunctionDef, Set[str], ast.AST], None],
        max_depth: int = 12,
        taint_attr_bases: bool = True,
    ):
        self.project = project
        self.visit = visit
        self.max_depth = max_depth
        # ``obj.field = tainted`` taints ``obj`` itself when True — the
        # right bias for GL003 (a tracer stored on self stays a tracer).
        # GL010 turns it off: host-setup code stores dozens of unrelated
        # attributes on self/config, and one divergent store must not mark
        # every later ``self.x`` gate as divergent.
        self.taint_attr_bases = taint_attr_bases
        self._seen: Set[Tuple[int, FrozenSet[str]]] = set()

    def walk(
        self,
        mod_rel: str,
        fn: ast.FunctionDef,
        tainted_params: FrozenSet[str],
        depth: int = 0,
    ) -> None:
        key = (id(fn), tainted_params)
        if key in self._seen or depth > self.max_depth:
            return
        self._seen.add(key)
        mod = self.project.modules[mod_rel]
        tainted: Set[str] = set(tainted_params)
        # fixpoint over simple assignments (loops can forward-reference)
        for _ in range(2):
            before = len(tainted)
            for node in ast.walk(fn):
                targets: List[ast.AST] = []
                value: Optional[ast.AST] = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                    value = node.value
                elif isinstance(node, ast.For):
                    targets, value = [node.target], node.iter
                if value is None:
                    continue
                if set(names_in(value)) & tainted:
                    for t in targets:
                        if not self.taint_attr_bases and not isinstance(
                            t, (ast.Name, ast.Tuple, ast.List, ast.Starred)
                        ):
                            continue
                        for n in ast.walk(t):
                            if isinstance(n, ast.Name):
                                tainted.add(n.id)
            if len(tainted) == before:
                break
        for node in ast.walk(fn):
            self.visit(mod_rel, fn, tainted, node)
            if isinstance(node, ast.Call):
                self._propagate(mod_rel, mod, node, tainted, depth)

    def _propagate(
        self,
        mod_rel: str,
        mod: Module,
        call: ast.Call,
        tainted: Set[str],
        depth: int,
    ) -> None:
        target = self.project.internal_callee(mod, mod_rel, call.func)
        if target is None:
            return
        fn = self.project.function(*target)
        if fn is None:
            return
        params = positional_params(fn)
        kwonly = {a.arg for a in fn.args.kwonlyargs}
        flowing: Set[str] = set()
        pos = 0
        for arg in call.args:
            if isinstance(arg, ast.Starred):
                # *seq forwarding: the splat's length is unknown, so a
                # tainted splat may land in ANY remaining positional slot
                # (and the callee's own *args); either way positional
                # matching cannot continue past it
                if set(names_in(arg.value)) & tainted:
                    flowing.update(params[pos:])
                    if fn.args.vararg:
                        flowing.add(fn.args.vararg.arg)
                pos = len(params)
                continue
            if set(names_in(arg)) & tainted:
                if pos < len(params):
                    flowing.add(params[pos])
                elif fn.args.vararg:
                    flowing.add(fn.args.vararg.arg)  # positional overflow
            pos += 1
        for kw in call.keywords:
            if kw.arg is None:
                # **mapping forwarding: a tainted mapping may bind any
                # keyword-addressable parameter (and the callee's **kwargs)
                if set(names_in(kw.value)) & tainted:
                    flowing.update(params)
                    flowing.update(kwonly)
                    if fn.args.kwarg:
                        flowing.add(fn.args.kwarg.arg)
                continue
            if set(names_in(kw.value)) & tainted:
                if kw.arg in params or kw.arg in kwonly:
                    flowing.add(kw.arg)
                elif fn.args.kwarg:
                    flowing.add(fn.args.kwarg.arg)
        if flowing:
            self.walk(target[0], fn, frozenset(flowing), depth + 1)


# ----------------------------------------------------------------- SPMD model
# Collectives the SPMD rules reason about: the raw jax.lax spellings plus
# the obs/collectives timed wrappers (the sanctioned sites).  Host-level
# gathers only participate in GL010 divergence checks (include_host=True).
_COLLECTIVE_KINDS = {"psum", "pmax", "pmin", "all_gather"}
_TIMED_TO_KIND = {
    "timed_psum": "psum",
    "timed_pmax": "pmax",
    "timed_pmin": "pmin",
}
_HOST_GATHERS = {
    "process_allgather",
    "allgather_host_varlen",
    "allgather_host_exact",
}


@dataclasses.dataclass(frozen=True)
class GuardInfo:
    """One conditional dominating a site.  ``axis=True`` means the test
    mentions the axis-name family — such tests are trace-static (the axis
    name rides in static jit args), so every replica agrees on them."""

    test_src: str
    axis: bool


@dataclasses.dataclass
class CollectiveSite:
    kind: str  # psum | pmax | pmin | all_gather
    raw: bool  # spelled jax.lax.*, not an obs/collectives timed wrapper
    node: ast.Call
    axis_expr: Optional[ast.AST]
    axis_key: Tuple  # ("param", name) | ("literal", v) | ("none",) | ("unknown",)
    guards: Tuple[GuardInfo, ...]  # outermost-first, incl. def-site inherited

    @property
    def axis_guarded(self) -> bool:
        return any(g.axis for g in self.guards)


@dataclasses.dataclass
class CondSite:
    """A ``lax.cond``/``lax.switch`` call — runtime branching on a traced
    predicate, where one-sided collectives deadlock for real."""

    node: ast.Call
    is_switch: bool
    guards: Tuple[GuardInfo, ...]


@dataclasses.dataclass
class CallbackSite:
    node: ast.Call
    name: str  # io_callback | pure_callback
    ordered: bool


@dataclasses.dataclass
class IfSite:
    """A Python-level ``if`` recorded for congruence checking.  When the
    body return-terminates with no ``orelse``, ``sibling`` holds the
    continuation statements (the code dominated by ``not test``)."""

    node: ast.If
    guards: Tuple[GuardInfo, ...]
    sibling: Optional[List[ast.stmt]]


@dataclasses.dataclass
class SpmdScope:
    """One function (or module) body analyzed as an SPMD scope."""

    rel: str  # module path relative to the package root
    mod: Module
    node: Optional[ast.AST]  # FunctionDef | AsyncFunctionDef | None (module)
    qualname: str
    parent: Optional["SpmdScope"]
    guards_at_def: Tuple[GuardInfo, ...] = ()
    axis_derived: Set[str] = dataclasses.field(default_factory=set)
    # names derived from a jit entry's static_argnames (replica-uniform by
    # the static-argument contract) — guards over them are trace-static
    static_derived: Set[str] = dataclasses.field(default_factory=set)
    children: Dict[str, "SpmdScope"] = dataclasses.field(default_factory=dict)
    sites: List[CollectiveSite] = dataclasses.field(default_factory=list)
    conds: List[CondSite] = dataclasses.field(default_factory=list)
    callbacks: List[CallbackSite] = dataclasses.field(default_factory=list)
    ifs: List[IfSite] = dataclasses.field(default_factory=list)


def _walk_no_defs(node: ast.AST):
    """ast.walk that does not descend into nested function/class bodies
    (lambdas ARE descended — their body executes in the enclosing trace)."""
    stack = [node]
    while stack:
        cur = stack.pop()
        yield cur
        for child in ast.iter_child_nodes(cur):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue
            stack.append(child)


def _test_src(test: ast.AST, limit: int = 60) -> str:
    """Stable textual key for a guard/if test (no line numbers)."""
    try:
        src = ast.unparse(test)
    except Exception:  # pragma: no cover - unparse handles all exprs
        src = type(test).__name__
    src = " ".join(src.split())
    return src[:limit]


class SpmdIndex:
    """Path-sensitive SPMD model of every function scope in the package.

    Built once per :class:`Project` and shared by the GL007–GL010 rules:

    * every collective site with its dominating guard stack (Python ``if``
      guards, ``while`` guards, ``if not X: return`` early-return
      dominators, and guards inherited from a nested def's definition
      site) and a normalized axis-name source key;
    * the *axis-derived* name family per scope: names whose value is
      computed from the axis name (``use_featpar = ... p.axis_name ...``,
      ``hist_axis = None if ... else p.axis_name``, ``voting_active(p, f)``
      whose body reads axis_name).  Guards over this family are
      trace-static, hence replica-uniform;
    * ``lax.cond``/``lax.switch`` sites and ``io_callback``/
      ``pure_callback`` sites;
    * depth-bounded collective summaries of statement blocks and callees
      (multisets of ``(kind, axis_key)``), with axis-argument
      specialization so ``leaf_histogram(..., axis_name=None)`` correctly
      contributes no collectives.
    """

    def __init__(self, project: Project):
        self.project = project
        self.scopes: List[SpmdScope] = []
        self.by_func: Dict[int, SpmdScope] = {}
        self.site_by_node: Dict[int, CollectiveSite] = {}
        self._fn_axis_cache: Dict[int, bool] = {}
        self._summary_cache: Dict[Tuple, Counter] = {}
        self._static_params: Dict[int, FrozenSet[str]] = {}
        for _rel, _mod, fn, statics in jit_entries(project):
            self._static_params[id(fn)] = statics
        for rel, mod in project.modules.items():
            root = SpmdScope(
                rel=rel, mod=mod, node=None, qualname="<module>", parent=None
            )
            self.scopes.append(root)
            self._build(root, mod.tree.body)

    # ------------------------------------------------------------- building
    def _build(self, scope: SpmdScope, body: List[ast.stmt]) -> None:
        self._compute_axis_derived(scope, body)
        if scope.node is not None:
            self.by_func[id(scope.node)] = scope
        self._walk_block(scope, body, ())

    def _compute_axis_derived(
        self, scope: SpmdScope, body: List[ast.stmt]
    ) -> None:
        derived = set(scope.parent.axis_derived) if scope.parent else set()
        fn = scope.node
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for a in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs:
                if a.arg.endswith("axis_name"):
                    derived.add(a.arg)
        scope.axis_derived = derived
        statics = set(
            scope.parent.static_derived if scope.parent else set()
        )
        if fn is not None:
            statics |= set(self._static_params.get(id(fn), ()))
        scope.static_derived = statics
        for _ in range(2):  # two passes: assignments can forward-reference
            before = len(derived) + len(statics)
            for st in body:
                for node in _walk_no_defs(st):
                    value = None
                    targets: List[ast.AST] = []
                    if isinstance(node, ast.Assign):
                        targets, value = node.targets, node.value
                    elif isinstance(node, ast.AnnAssign):
                        targets, value = [node.target], node.value
                    if value is None:
                        continue
                    axis_hit = self._mentions_axis(scope, value)
                    static_hit = statics and (
                        set(names_in(value)) & statics
                    )
                    if not axis_hit and not static_hit:
                        continue
                    for t in targets:
                        for n in ast.walk(t):
                            if isinstance(n, ast.Name):
                                if axis_hit:
                                    derived.add(n.id)
                                if static_hit:
                                    statics.add(n.id)
            if len(derived) + len(statics) == before:
                break

    def _fn_mentions_axis(self, fn: ast.FunctionDef) -> bool:
        cached = self._fn_axis_cache.get(id(fn))
        if cached is not None:
            return cached
        hit = False
        for n in ast.walk(fn):
            if isinstance(n, ast.Attribute) and n.attr.endswith("axis_name"):
                hit = True
                break
            if isinstance(n, ast.Name) and n.id.endswith("axis_name"):
                hit = True
                break
            if isinstance(n, ast.arg) and n.arg.endswith("axis_name"):
                hit = True
                break
        self._fn_axis_cache[id(fn)] = hit
        return hit

    def _mentions_axis(self, scope: SpmdScope, expr: ast.AST) -> bool:
        """Does this expression depend on the axis-name family?  Direct
        ``.axis_name`` access, an axis-derived name, or a call into an
        in-package function whose body reads the axis name."""
        for n in ast.walk(expr):
            if isinstance(n, ast.Attribute) and n.attr.endswith("axis_name"):
                return True
            if isinstance(n, ast.Name) and (
                n.id.endswith("axis_name") or n.id in scope.axis_derived
            ):
                return True
            if isinstance(n, ast.Call):
                target = self.project.internal_callee(
                    scope.mod, scope.rel, n.func
                )
                if target is not None:
                    fn = self.project.function(*target)
                    if fn is not None and self._fn_mentions_axis(fn):
                        return True
        return False

    def trace_static_test(self, scope: SpmdScope, test: ast.AST) -> bool:
        """Is this test replica-uniform by construction?  True when it
        depends on the axis-name family or on names derived from a jit
        entry's static_argnames — both ride in static jit arguments, so
        every replica traces the same side of the branch."""
        if self._mentions_axis(scope, test):
            return True
        return bool(set(names_in(test)) & scope.static_derived)

    def _walk_block(
        self,
        scope: SpmdScope,
        stmts: List[ast.stmt],
        guards: Tuple[GuardInfo, ...],
    ) -> None:
        extra: Tuple[GuardInfo, ...] = ()
        for idx, st in enumerate(stmts):
            g = guards + extra
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                base = "" if scope.node is None else scope.qualname + "."
                child = SpmdScope(
                    rel=scope.rel,
                    mod=scope.mod,
                    node=st,
                    qualname=base + st.name,
                    parent=scope,
                    guards_at_def=scope.guards_at_def + g,
                )
                scope.children[st.name] = child
                self.scopes.append(child)
                for deco in st.decorator_list:  # evaluate in enclosing scope
                    self._scan_expr(scope, deco, g)
                self._build(child, st.body)
                continue
            if isinstance(st, ast.ClassDef):
                self._walk_block(scope, st.body, g)
                continue
            if isinstance(st, ast.If):
                self._scan_expr(scope, st.test, g)
                gi = GuardInfo(
                    _test_src(st.test), self._mentions_axis(scope, st.test)
                )
                self._walk_block(scope, st.body, g + (gi,))
                sibling: Optional[List[ast.stmt]] = None
                if st.orelse:
                    self._walk_block(scope, st.orelse, g + (gi,))
                elif st.body and isinstance(st.body[-1], ast.Return):
                    # early-return guard: the rest of this block runs only
                    # when the test is false (same trace-staticness)
                    extra = extra + (gi,)
                    sibling = stmts[idx + 1 :]
                scope.ifs.append(IfSite(node=st, guards=g, sibling=sibling))
                continue
            if isinstance(st, ast.While):
                self._scan_expr(scope, st.test, g)
                gi = GuardInfo(
                    _test_src(st.test), self._mentions_axis(scope, st.test)
                )
                self._walk_block(scope, st.body, g + (gi,))
                self._walk_block(scope, st.orelse, g)
                continue
            if isinstance(st, (ast.For, ast.AsyncFor)):
                self._scan_expr(scope, st.iter, g)
                self._walk_block(scope, st.body, g)
                self._walk_block(scope, st.orelse, g)
                continue
            if isinstance(st, ast.Try):
                self._walk_block(scope, st.body, g)
                for h in st.handlers:
                    self._walk_block(scope, h.body, g)
                self._walk_block(scope, st.orelse, g)
                self._walk_block(scope, st.finalbody, g)
                continue
            if isinstance(st, (ast.With, ast.AsyncWith)):
                for item in st.items:
                    self._scan_expr(scope, item.context_expr, g)
                self._walk_block(scope, st.body, g)
                continue
            self._scan_expr(scope, st, g)

    def _scan_expr(
        self, scope: SpmdScope, node: ast.AST, guards: Tuple[GuardInfo, ...]
    ) -> None:
        for n in _walk_no_defs(node):
            if isinstance(n, ast.Call):
                self._classify_call(scope, n, guards)

    def _callee_name(self, func: ast.AST) -> Optional[str]:
        if isinstance(func, ast.Name):
            return func.id
        if isinstance(func, ast.Attribute):
            return func.attr
        return None

    def _classify_call(
        self, scope: SpmdScope, node: ast.Call, guards: Tuple[GuardInfo, ...]
    ) -> None:
        dotted = self.project.dotted_callee(scope.mod, node.func)
        name = self._callee_name(node.func)
        kind: Optional[str] = None
        raw = False
        if dotted is not None and dotted.startswith("jax.lax."):
            last = dotted.split(".")[-1]
            if last in _COLLECTIVE_KINDS:
                kind, raw = last, True
            elif last in ("cond", "switch"):
                scope.conds.append(
                    CondSite(
                        node=node, is_switch=(last == "switch"), guards=guards
                    )
                )
        if kind is None and name in _TIMED_TO_KIND:
            kind, raw = _TIMED_TO_KIND[name], False
        if kind is not None:
            axis_expr: Optional[ast.AST]
            if len(node.args) > 1:
                axis_expr = node.args[1]
            else:
                axis_expr = call_kwargs(node).get("axis_name")
            site = CollectiveSite(
                kind=kind,
                raw=raw,
                node=node,
                axis_expr=axis_expr,
                axis_key=self.axis_key(scope, axis_expr),
                guards=scope.guards_at_def + guards,
            )
            scope.sites.append(site)
            self.site_by_node[id(node)] = site
            return
        if name in ("io_callback", "pure_callback") or (
            dotted is not None
            and dotted.endswith((".io_callback", ".pure_callback"))
        ):
            kw = call_kwargs(node).get("ordered")
            ordered = isinstance(kw, ast.Constant) and kw.value is True
            cname = "io_callback"
            if (name or "").endswith("pure_callback") or (
                dotted or ""
            ).endswith("pure_callback"):
                cname = "pure_callback"
            scope.callbacks.append(
                CallbackSite(node=node, name=cname, ordered=ordered)
            )

    # --------------------------------------------------------- axis sources
    def axis_key(self, scope: SpmdScope, expr: Optional[ast.AST]) -> Tuple:
        """Normalize an axis-name argument to its SOURCE:  the parameter
        plumbing (``("param", "axis_name")`` — GrowerParams.axis_name, an
        axis_name parameter, or a name derived from them), a string
        literal (module-level constants resolve), literal None, or
        unknown."""
        if expr is None:
            return ("unknown",)
        if isinstance(expr, ast.Constant):
            if expr.value is None:
                return ("none",)
            if isinstance(expr.value, str):
                return ("literal", expr.value)
            return ("unknown",)
        if isinstance(expr, ast.Attribute) and expr.attr == "axis_name":
            return ("param", "axis_name")
        if isinstance(expr, ast.Name):
            if expr.id == "axis_name" or expr.id in scope.axis_derived:
                return ("param", "axis_name")
            lit = scope.mod.str_consts.get(expr.id)
            if lit is not None:
                return ("literal", lit)
        return ("unknown",)

    def axis_possibly_none(
        self, scope: SpmdScope, expr: Optional[ast.AST]
    ) -> bool:
        """Can this axis-name source be None on some call?  Attribute
        access (GrowerParams.axis_name is Optional by design) and
        axis-derived locals (``hist_axis = None if ... else p.axis_name``)
        count as possibly-None; a parameter only when its annotation is
        Optional or its default is None.  Unresolvable sources are NOT
        guessed (the linter is biased to miss)."""
        if isinstance(expr, ast.Attribute) and expr.attr == "axis_name":
            return True
        if not isinstance(expr, ast.Name):
            return False
        # a parameter of an enclosing function scope?
        cur: Optional[SpmdScope] = scope
        while cur is not None:
            fn = cur.node
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info = _param_info(fn, expr.id)
                if info is not None:
                    ann, default = info
                    if default is not None and isinstance(
                        default, ast.Constant
                    ) and default.value is None:
                        return True
                    return _is_optional_annotation(ann)
            cur = cur.parent
        # a derived local (hist_axis-style) may carry None by construction
        return expr.id in scope.axis_derived

    # ------------------------------------------------------------ summaries
    def _resolve_call_scope(
        self, scope: SpmdScope, node: ast.Call
    ) -> Optional[SpmdScope]:
        """The SpmdScope a call lands in: an in-package module function, or
        a nested def visible up the lexical scope chain."""
        target = self.project.internal_callee(scope.mod, scope.rel, node.func)
        if target is not None:
            fn = self.project.function(*target)
            if fn is not None:
                return self.by_func.get(id(fn))
        if isinstance(node.func, ast.Name):
            cur: Optional[SpmdScope] = scope
            while cur is not None:
                child = cur.children.get(node.func.id)
                if child is not None:
                    return child
                cur = cur.parent
        return None

    def _call_axis_key(
        self, scope: SpmdScope, node: ast.Call, callee: SpmdScope
    ) -> Optional[Tuple]:
        """The axis-name key the CALLER passes into ``callee`` for its
        ``axis_name`` parameter; None when the callee has no such
        parameter (no specialization)."""
        fn = callee.node
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return None
        params = positional_params(fn)
        kwonly = [a.arg for a in fn.args.kwonlyargs]
        if "axis_name" not in params and "axis_name" not in kwonly:
            return None
        for kw in node.keywords:
            if kw.arg == "axis_name":
                return self.axis_key(scope, kw.value)
        if "axis_name" in params:
            i = params.index("axis_name")
            if i < len(node.args) and not any(
                isinstance(a, ast.Starred) for a in node.args[: i + 1]
            ):
                return self.axis_key(scope, node.args[i])
        info = _param_info(fn, "axis_name")
        if info is not None and isinstance(info[1], ast.Constant) and (
            info[1].value is None
        ):
            return ("none",)
        return None

    def block_summary(
        self,
        scope: SpmdScope,
        stmts,
        depth: int = 3,
        include_host: bool = False,
        _stack: Tuple[int, ...] = (),
    ) -> Counter:
        """Multiset of ``(kind, axis_key)`` collectives a statement block
        (or expression list) executes, inlining in-package callees to
        ``depth`` with axis-argument specialization."""
        c: Counter = Counter()
        for st in stmts:
            for node in _walk_no_defs(st):
                if not isinstance(node, ast.Call):
                    continue
                site = self.site_by_node.get(id(node))
                if site is not None:
                    c[(site.kind, site.axis_key)] += 1
                    continue
                if include_host:
                    name = self._callee_name(node.func)
                    dotted = self.project.dotted_callee(scope.mod, node.func)
                    if name in _HOST_GATHERS or (
                        dotted is not None
                        and dotted.endswith(".process_allgather")
                    ):
                        c[("host_gather", ("host",))] += 1
                        continue
                if depth <= 0:
                    continue
                callee = self._resolve_call_scope(scope, node)
                if callee is None or id(callee) in _stack:
                    continue
                c += self.scope_summary(
                    callee,
                    depth - 1,
                    include_host,
                    axis_arg_key=self._call_axis_key(scope, node, callee),
                    _stack=_stack + (id(callee),),
                )
        return c

    def scope_summary(
        self,
        scope: SpmdScope,
        depth: int = 2,
        include_host: bool = False,
        axis_arg_key: Optional[Tuple] = None,
        _stack: Tuple[int, ...] = (),
    ) -> Counter:
        """Collective summary of a whole function scope, specialized on the
        axis argument the caller passes: a site whose axis source is the
        callee's parameter family takes the caller's key, and an
        axis-guarded site vanishes when the caller passes axis_name=None
        (the guard is statically false on that call)."""
        key = (id(scope), depth, include_host, axis_arg_key)
        cached = self._summary_cache.get(key)
        if cached is not None:
            return cached
        c: Counter = Counter()
        for site in scope.sites:
            k = site.axis_key
            if axis_arg_key is not None and k == ("param", "axis_name"):
                if axis_arg_key == ("none",):
                    if site.axis_guarded:
                        continue
                    k = ("none",)
                else:
                    k = axis_arg_key
            c[(site.kind, k)] += 1
        body = scope.node.body if scope.node is not None else []
        for st in body:
            for node in _walk_no_defs(st):
                if not isinstance(node, ast.Call):
                    continue
                if id(node) in self.site_by_node:
                    continue  # counted above via scope.sites
                if include_host:
                    name = self._callee_name(node.func)
                    dotted = self.project.dotted_callee(scope.mod, node.func)
                    if name in _HOST_GATHERS or (
                        dotted is not None
                        and dotted.endswith(".process_allgather")
                    ):
                        c[("host_gather", ("host",))] += 1
                        continue
                if depth <= 0:
                    continue
                callee = self._resolve_call_scope(scope, node)
                if callee is None or id(callee) in _stack:
                    continue
                c += self.scope_summary(
                    callee,
                    depth - 1,
                    include_host,
                    axis_arg_key=self._call_axis_key(scope, node, callee),
                    _stack=_stack + (id(callee),),
                )
        self._summary_cache[key] = c
        return c

    def expr_summary(
        self,
        scope: SpmdScope,
        expr: ast.AST,
        depth: int = 3,
        include_host: bool = False,
    ) -> Optional[Counter]:
        """Collective summary of a branch callable expression (lax.cond /
        lax.switch branch): a lambda, a resolvable function name, or a
        functools.partial over one.  None when unresolvable — congruence
        checks then SKIP the site rather than guess."""
        if isinstance(expr, ast.Lambda):
            return self.block_summary(
                scope, [ast.Expr(value=expr.body)], depth, include_host
            )
        if isinstance(expr, ast.Call):
            dotted = self.project.dotted_callee(scope.mod, expr.func)
            if dotted == "functools.partial" and expr.args:
                return self.expr_summary(
                    scope, expr.args[0], depth, include_host
                )
            return None
        callee = self._resolve_call_scope(
            scope, ast.Call(func=expr, args=[], keywords=[])
        )
        if callee is not None:
            return self.scope_summary(callee, depth, include_host)
        return None


def _param_info(
    fn: ast.FunctionDef, name: str
) -> Optional[Tuple[Optional[ast.AST], Optional[ast.AST]]]:
    """(annotation, default) for a named parameter, or None if absent."""
    pos = fn.args.posonlyargs + fn.args.args
    defaults = [None] * (len(pos) - len(fn.args.defaults)) + list(
        fn.args.defaults
    )
    for a, d in zip(pos, defaults):
        if a.arg == name:
            return (a.annotation, d)
    for a, d in zip(fn.args.kwonlyargs, fn.args.kw_defaults):
        if a.arg == name:
            return (a.annotation, d)
    return None


def _is_optional_annotation(ann: Optional[ast.AST]) -> bool:
    if ann is None:
        return False
    if isinstance(ann, ast.Subscript) and isinstance(ann.value, ast.Name):
        if ann.value.id == "Optional":
            return True
    if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
        # PEP 604 `str | None`
        for side in (ann.left, ann.right):
            if isinstance(side, ast.Constant) and side.value is None:
                return True
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return "Optional" in ann.value or "None" in ann.value
    return False


def spmd_index(project: Project) -> SpmdIndex:
    """Build (or reuse) the SPMD index for a project — rules share one."""
    idx = getattr(project, "_spmd_index", None)
    if idx is None or idx.project is not project:
        idx = SpmdIndex(project)
        project._spmd_index = idx
    return idx
