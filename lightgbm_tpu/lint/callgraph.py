"""Conservative intra-package call graph + tracer-taint propagation.

Entry points are the places a value becomes a tracer:

* jit-wrapped functions — ``@instrumented_jit``, ``@jax.jit``,
  ``@functools.partial(instrumented_jit, static_argnames=...)``, and the
  assignment forms ``g = instrumented_jit(f, ...)`` / ``jax.jit(f)``;
* Pallas kernel bodies — the first argument of ``pl.pallas_call`` (resolved
  through a local ``functools.partial(kernel_fn, ...)`` binding).

Taint model (deliberately simple, biased against false positives):

* at a jit entry every parameter is tainted EXCEPT names listed in
  ``static_argnames``; in a pallas kernel every parameter (ref) is tainted;
* an assignment whose right-hand side mentions a tainted name taints its
  targets; a call result is tainted iff any argument is tainted;
* taint flows into in-package callees positionally/by keyword, computed to
  a fixpoint over (function, tainted-param-set) pairs — the "conservative
  intra-package call graph" of GL003.  ``*args``/``**kwargs`` forwarding
  and aliasing through containers are NOT modeled: an un-modeled flow can
  only lose taint, i.e. miss a finding, never invent one.
"""

from __future__ import annotations

import ast
from typing import Callable, Dict, FrozenSet, List, Optional, Set, Tuple

from .core import Module, Project, call_kwargs, const_names, names_in

_JIT_NAMES = {"instrumented_jit"}
_JIT_DOTTED = {"jax.jit", "jax.pmap", "jax.obs.jit.instrumented_jit"}


def _jit_wrapper_call(
    project: Project, mod: Module, node: ast.AST
) -> Optional[ast.Call]:
    """Return the jit-wrapper Call if ``node`` is one (possibly through
    ``functools.partial(<jit>, ...)``), else None."""
    if not isinstance(node, ast.Call):
        return None
    dotted = project.dotted_callee(mod, node.func)
    name = node.func.id if isinstance(node.func, ast.Name) else (
        node.func.attr if isinstance(node.func, ast.Attribute) else None
    )
    if dotted in _JIT_DOTTED or name in _JIT_NAMES:
        return node
    if dotted == "functools.partial" and node.args:
        inner = node.args[0]
        idotted = project.dotted_callee(mod, inner)
        iname = inner.id if isinstance(inner, ast.Name) else None
        if idotted in _JIT_DOTTED or iname in _JIT_NAMES:
            return node
    return None


def jit_entries(
    project: Project,
) -> List[Tuple[str, Module, ast.FunctionDef, FrozenSet[str]]]:
    """All jit entry points: (module_rel, module, func, static_argnames)."""
    out = []
    for rel, mod in project.modules.items():
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    call = _jit_wrapper_call(project, mod, deco)
                    is_bare = not isinstance(deco, ast.Call) and (
                        project.dotted_callee(mod, deco) in _JIT_DOTTED
                        or (
                            isinstance(deco, ast.Name)
                            and deco.id in _JIT_NAMES
                        )
                    )
                    if call is None and not is_bare:
                        continue
                    statics: Set[str] = set()
                    if call is not None:
                        names = const_names(
                            call_kwargs(call).get("static_argnames", ast.Tuple(elts=[]))
                        )
                        statics = set(names or ())
                    out.append((rel, mod, node, frozenset(statics)))
                    break
            elif isinstance(node, ast.Call):
                # assignment / expression form: instrumented_jit(fn, ...)
                call = _jit_wrapper_call(project, mod, node)
                if call is None or call is not node or not node.args:
                    continue
                target = project.internal_callee(mod, rel, node.args[0])
                if target is None:
                    continue
                fn = project.function(*target)
                if fn is None:
                    continue
                names = const_names(
                    call_kwargs(node).get("static_argnames", ast.Tuple(elts=[]))
                )
                out.append(
                    (target[0], project.modules[target[0]], fn,
                     frozenset(names or ()))
                )
    return out


def pallas_call_sites(
    project: Project,
) -> List[Tuple[str, Module, ast.Call, Optional[Tuple[str, ast.FunctionDef]], str]]:
    """All ``pl.pallas_call(...)`` sites with their resolved kernel body:
    (module_rel, module, call_node, (module_rel, kernel_def) | None,
    enclosing_function_name).

    The kernel argument is resolved through one level of local binding:
    a bare function name, or ``k = functools.partial(kernel_fn, ...)``
    assigned in the enclosing function before the call.  Sites inside
    nested functions resolve against their INNERMOST enclosing scope
    (``ast.walk`` yields outer scopes first, so the last write wins).
    """
    sites: Dict[int, Tuple] = {}
    for rel, mod in project.modules.items():
        for encl in ast.walk(mod.tree):
            if not isinstance(encl, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            # enclosing-scope partial bindings: name -> wrapped func expr
            local_partials: Dict[str, ast.AST] = {}
            for node in ast.walk(encl):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and isinstance(node.value, ast.Call):
                    dotted = project.dotted_callee(mod, node.value.func)
                    if dotted == "functools.partial" and node.value.args:
                        local_partials[node.targets[0].id] = node.value.args[0]
            for node in ast.walk(encl):
                if not isinstance(node, ast.Call):
                    continue
                dotted = project.dotted_callee(mod, node.func)
                if dotted is None or not dotted.endswith(".pallas_call"):
                    continue
                kernel = None
                if node.args:
                    kexpr = node.args[0]
                    if isinstance(kexpr, ast.Name) and kexpr.id in local_partials:
                        kexpr = local_partials[kexpr.id]
                    target = project.internal_callee(mod, rel, kexpr)
                    if target is not None:
                        fn = project.function(*target)
                        if fn is not None:
                            kernel = (target[0], fn)
                sites[id(node)] = (rel, mod, node, kernel, encl.name)
    return list(sites.values())


def positional_params(fn: ast.FunctionDef) -> List[str]:
    return [a.arg for a in fn.args.posonlyargs + fn.args.args]


class TaintWalker:
    """Propagate tracer taint from entry functions through the in-package
    call graph, invoking ``visit(module_rel, func, tainted_names, node)``
    on every statement-level AST node of every reached function."""

    def __init__(
        self,
        project: Project,
        visit: Callable[[str, ast.FunctionDef, Set[str], ast.AST], None],
        max_depth: int = 12,
    ):
        self.project = project
        self.visit = visit
        self.max_depth = max_depth
        self._seen: Set[Tuple[int, FrozenSet[str]]] = set()

    def walk(
        self,
        mod_rel: str,
        fn: ast.FunctionDef,
        tainted_params: FrozenSet[str],
        depth: int = 0,
    ) -> None:
        key = (id(fn), tainted_params)
        if key in self._seen or depth > self.max_depth:
            return
        self._seen.add(key)
        mod = self.project.modules[mod_rel]
        tainted: Set[str] = set(tainted_params)
        # fixpoint over simple assignments (loops can forward-reference)
        for _ in range(2):
            before = len(tainted)
            for node in ast.walk(fn):
                targets: List[ast.AST] = []
                value: Optional[ast.AST] = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                    value = node.value
                elif isinstance(node, ast.For):
                    targets, value = [node.target], node.iter
                if value is None:
                    continue
                if set(names_in(value)) & tainted:
                    for t in targets:
                        for n in ast.walk(t):
                            if isinstance(n, ast.Name):
                                tainted.add(n.id)
            if len(tainted) == before:
                break
        for node in ast.walk(fn):
            self.visit(mod_rel, fn, tainted, node)
            if isinstance(node, ast.Call):
                self._propagate(mod_rel, mod, node, tainted, depth)

    def _propagate(
        self,
        mod_rel: str,
        mod: Module,
        call: ast.Call,
        tainted: Set[str],
        depth: int,
    ) -> None:
        target = self.project.internal_callee(mod, mod_rel, call.func)
        if target is None:
            return
        fn = self.project.function(*target)
        if fn is None:
            return
        params = positional_params(fn)
        flowing: Set[str] = set()
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                break
            if i < len(params) and set(names_in(arg)) & tainted:
                flowing.add(params[i])
        for kw in call.keywords:
            if kw.arg and set(names_in(kw.value)) & tainted:
                flowing.add(kw.arg)
        if flowing:
            self.walk(target[0], fn, frozenset(flowing), depth + 1)
