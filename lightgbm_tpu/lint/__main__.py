"""CLI: ``python -m lightgbm_tpu.lint``.

Exit status 0 when the tree is clean against the baseline (no new
findings, no stale baseline entries); 1 otherwise.  ``--write-baseline``
regenerates the baseline from the current findings with TODO
justifications for review.

``--ir`` additionally traces the real jit/shard_map entries to jaxprs
(lint.ir config matrix, CPU-only abstract tracing) and runs the
GL011-GL015 IR audits; with ``--changed-only`` the IR matrix is scoped
to entries whose transitive module closure intersects the changed
files (CI runs the full matrix).  ``--format=github`` emits
``::error file=...,line=...::`` annotations for both passes.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

from .core import RULES, run_lint, write_baseline

PKG_ROOT = Path(__file__).resolve().parents[1]  # the lightgbm_tpu package
REPO_ROOT = PKG_ROOT.parent


def _git_changed_files():
    """Repo-root-relative paths git sees as modified (vs HEAD) or
    untracked; None when git is unavailable or this is not a checkout."""
    import subprocess

    out = []
    for cmd in (
        ["git", "diff", "--name-only", "HEAD"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            proc = subprocess.run(
                cmd,
                cwd=REPO_ROOT,
                capture_output=True,
                text=True,
                timeout=10,
            )
        except (OSError, subprocess.TimeoutExpired):
            return None
        if proc.returncode != 0:
            return None
        out.extend(l.strip() for l in proc.stdout.splitlines() if l.strip())
    return sorted(set(out))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.lint",
        description="graftlint: tracer-safety & Pallas-contract static "
        "analysis for the lightgbm_tpu tree",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help="optional path prefixes (relative to the repo root, e.g. "
        "lightgbm_tpu/ops) to filter REPORTED findings; the whole package "
        "is always analyzed so the call graph stays complete",
    )
    parser.add_argument(
        "--baseline",
        type=Path,
        default=None,
        help="baseline JSON of reviewed exceptions (default: "
        "lint_baseline.json next to the package, when present)",
    )
    parser.add_argument(
        "--write-baseline",
        type=Path,
        metavar="PATH",
        default=None,
        help="write the current findings as a fresh baseline and exit 0",
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    parser.add_argument(
        "--format",
        choices=("text", "github"),
        default="text",
        help="finding output format: 'github' prints ::error "
        "file=...,line=... workflow annotations",
    )
    parser.add_argument(
        "--ir",
        action="store_true",
        help="also trace the jit/shard_map entry matrix to jaxprs and "
        "run the GL011-GL015 IR audits (imports the package; still "
        "CPU-only abstract tracing, no device execution)",
    )
    parser.add_argument(
        "--ir-entries",
        nargs="+",
        metavar="PREFIX",
        default=None,
        help="with --ir: trace only entries whose name starts with one "
        "of these prefixes (e.g. grow/ pallas/histogram)",
    )
    parser.add_argument(
        "--changed-only",
        action="store_true",
        help="dev-loop fast mode: report only findings in files git sees "
        "as changed (staged, unstaged, or untracked); the whole package "
        "is still analyzed so the call graph stays complete, and stale "
        "detection is restricted to the same files — CI keeps the "
        "full-tree gate",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule table"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for code, (title, hint) in sorted(RULES.items()):
            print(f"{code}  {title}\n       fix: {hint}")
        return 0

    baseline = args.baseline
    if baseline is None and args.write_baseline is None:
        cand = REPO_ROOT / "lint_baseline.json"
        baseline = cand if cand.exists() else None

    only_paths = list(args.paths)
    ir_changed_modules = None
    if args.changed_only:
        changed = _git_changed_files()
        if changed is None:
            print(
                "graftlint: --changed-only needs a git checkout; "
                "falling back to the full tree",
                file=sys.stderr,
            )
        else:
            pkg_prefix = PKG_ROOT.name + "/"
            changed = [
                c for c in changed
                if c.endswith(".py") and c.startswith(pkg_prefix)
            ]
            if not changed:
                print(
                    "graftlint: no changed python files under "
                    f"{pkg_prefix} — nothing to report"
                )
                return 0
            only_paths.extend(changed)
            if args.ir:
                # entries are scoped to the package-relative closure
                ir_changed_modules = [
                    c[len(pkg_prefix):] for c in changed
                ]

    t0 = time.monotonic()
    c0 = time.process_time()
    result = run_lint(
        PKG_ROOT,
        baseline=baseline,
        only_paths=only_paths,
        ir=args.ir,
        ir_entry_filter=args.ir_entries,
        ir_changed_modules=ir_changed_modules,
    )
    elapsed = time.monotonic() - t0
    cpu = time.process_time() - c0

    if args.write_baseline is not None:
        write_baseline(args.write_baseline, result.findings)
        print(
            f"graftlint: wrote {len(result.findings)} entries to "
            f"{args.write_baseline} — fill in the TODO justifications"
        )
        return 0

    if args.json:
        print(
            json.dumps(
                {
                    "new": [vars(f) for f in result.new],
                    "baselined": len(result.findings) - len(result.new),
                    "stale": result.stale,
                    "elapsed_s": round(elapsed, 3),
                    "cpu_s": round(cpu, 3),
                    "rule_timings_s": {
                        code: round(t, 4)
                        for code, t in sorted(result.timings.items())
                    },
                },
                indent=2,
            )
        )
        return 0 if result.ok else 1

    if args.format == "github":
        for f in result.new:
            print(
                f"::error file={f.path},line={f.line}::"
                f"{f.rule} {f.message}"
            )
        for e in result.stale:
            print(
                f"::error file={e['path']}::stale baseline entry "
                f"(no longer fires — remove it): {e['rule']} "
                f"ident={e['ident']}"
            )
    else:
        for f in result.new:
            print(f.render())
            print(f"    fix: {f.hint}")
        for e in result.stale:
            print(
                f"stale baseline entry (no longer fires — remove it): "
                f"{e['rule']} {e['path']} ident={e['ident']!r}"
            )
    n_base = len(result.findings) - len(result.new)
    print(
        f"graftlint: {len(result.findings)} finding(s) "
        f"({n_base} baselined, {len(result.new)} new), "
        f"{len(result.stale)} stale baseline entr(y/ies) "
        f"[{elapsed:.2f}s]"
    )
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
