"""CLI: ``python -m lightgbm_tpu.lint``.

Exit status 0 when the tree is clean against the baseline (no new
findings, no stale baseline entries); 1 otherwise.  ``--write-baseline``
regenerates the baseline from the current findings with TODO
justifications for review.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

from .core import RULES, run_lint, write_baseline

PKG_ROOT = Path(__file__).resolve().parents[1]  # the lightgbm_tpu package
REPO_ROOT = PKG_ROOT.parent


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.lint",
        description="graftlint: tracer-safety & Pallas-contract static "
        "analysis for the lightgbm_tpu tree",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help="optional path prefixes (relative to the repo root, e.g. "
        "lightgbm_tpu/ops) to filter REPORTED findings; the whole package "
        "is always analyzed so the call graph stays complete",
    )
    parser.add_argument(
        "--baseline",
        type=Path,
        default=None,
        help="baseline JSON of reviewed exceptions (default: "
        "lint_baseline.json next to the package, when present)",
    )
    parser.add_argument(
        "--write-baseline",
        type=Path,
        metavar="PATH",
        default=None,
        help="write the current findings as a fresh baseline and exit 0",
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule table"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for code, (title, hint) in sorted(RULES.items()):
            print(f"{code}  {title}\n       fix: {hint}")
        return 0

    baseline = args.baseline
    if baseline is None and args.write_baseline is None:
        cand = REPO_ROOT / "lint_baseline.json"
        baseline = cand if cand.exists() else None

    t0 = time.monotonic()
    result = run_lint(PKG_ROOT, baseline=baseline, only_paths=args.paths)
    elapsed = time.monotonic() - t0

    if args.write_baseline is not None:
        write_baseline(args.write_baseline, result.findings)
        print(
            f"graftlint: wrote {len(result.findings)} entries to "
            f"{args.write_baseline} — fill in the TODO justifications"
        )
        return 0

    if args.json:
        print(
            json.dumps(
                {
                    "new": [vars(f) for f in result.new],
                    "baselined": len(result.findings) - len(result.new),
                    "stale": result.stale,
                    "elapsed_s": round(elapsed, 3),
                },
                indent=2,
            )
        )
        return 0 if result.ok else 1

    for f in result.new:
        print(f.render())
        print(f"    fix: {f.hint}")
    for e in result.stale:
        print(
            f"stale baseline entry (no longer fires — remove it): "
            f"{e['rule']} {e['path']} ident={e['ident']!r}"
        )
    n_base = len(result.findings) - len(result.new)
    print(
        f"graftlint: {len(result.findings)} finding(s) "
        f"({n_base} baselined, {len(result.new)} new), "
        f"{len(result.stale)} stale baseline entr(y/ies) "
        f"[{elapsed:.2f}s]"
    )
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
