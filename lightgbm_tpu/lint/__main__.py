"""CLI: ``python -m lightgbm_tpu.lint``.

Exit status 0 when the tree is clean against the baseline (no new
findings, no stale baseline entries); 1 otherwise.  ``--write-baseline``
regenerates the baseline from the current findings with TODO
justifications for review.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

from .core import RULES, run_lint, write_baseline

PKG_ROOT = Path(__file__).resolve().parents[1]  # the lightgbm_tpu package
REPO_ROOT = PKG_ROOT.parent


def _git_changed_files():
    """Repo-root-relative paths git sees as modified (vs HEAD) or
    untracked; None when git is unavailable or this is not a checkout."""
    import subprocess

    out = []
    for cmd in (
        ["git", "diff", "--name-only", "HEAD"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            proc = subprocess.run(
                cmd,
                cwd=REPO_ROOT,
                capture_output=True,
                text=True,
                timeout=10,
            )
        except (OSError, subprocess.TimeoutExpired):
            return None
        if proc.returncode != 0:
            return None
        out.extend(l.strip() for l in proc.stdout.splitlines() if l.strip())
    return sorted(set(out))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.lint",
        description="graftlint: tracer-safety & Pallas-contract static "
        "analysis for the lightgbm_tpu tree",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help="optional path prefixes (relative to the repo root, e.g. "
        "lightgbm_tpu/ops) to filter REPORTED findings; the whole package "
        "is always analyzed so the call graph stays complete",
    )
    parser.add_argument(
        "--baseline",
        type=Path,
        default=None,
        help="baseline JSON of reviewed exceptions (default: "
        "lint_baseline.json next to the package, when present)",
    )
    parser.add_argument(
        "--write-baseline",
        type=Path,
        metavar="PATH",
        default=None,
        help="write the current findings as a fresh baseline and exit 0",
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    parser.add_argument(
        "--changed-only",
        action="store_true",
        help="dev-loop fast mode: report only findings in files git sees "
        "as changed (staged, unstaged, or untracked); the whole package "
        "is still analyzed so the call graph stays complete, and stale "
        "detection is restricted to the same files — CI keeps the "
        "full-tree gate",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule table"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for code, (title, hint) in sorted(RULES.items()):
            print(f"{code}  {title}\n       fix: {hint}")
        return 0

    baseline = args.baseline
    if baseline is None and args.write_baseline is None:
        cand = REPO_ROOT / "lint_baseline.json"
        baseline = cand if cand.exists() else None

    only_paths = list(args.paths)
    if args.changed_only:
        changed = _git_changed_files()
        if changed is None:
            print(
                "graftlint: --changed-only needs a git checkout; "
                "falling back to the full tree",
                file=sys.stderr,
            )
        else:
            pkg_prefix = PKG_ROOT.name + "/"
            changed = [
                c for c in changed
                if c.endswith(".py") and c.startswith(pkg_prefix)
            ]
            if not changed:
                print(
                    "graftlint: no changed python files under "
                    f"{pkg_prefix} — nothing to report"
                )
                return 0
            only_paths.extend(changed)

    t0 = time.monotonic()
    c0 = time.process_time()
    result = run_lint(PKG_ROOT, baseline=baseline, only_paths=only_paths)
    elapsed = time.monotonic() - t0
    cpu = time.process_time() - c0

    if args.write_baseline is not None:
        write_baseline(args.write_baseline, result.findings)
        print(
            f"graftlint: wrote {len(result.findings)} entries to "
            f"{args.write_baseline} — fill in the TODO justifications"
        )
        return 0

    if args.json:
        print(
            json.dumps(
                {
                    "new": [vars(f) for f in result.new],
                    "baselined": len(result.findings) - len(result.new),
                    "stale": result.stale,
                    "elapsed_s": round(elapsed, 3),
                    "cpu_s": round(cpu, 3),
                    "rule_timings_s": {
                        code: round(t, 4)
                        for code, t in sorted(result.timings.items())
                    },
                },
                indent=2,
            )
        )
        return 0 if result.ok else 1

    for f in result.new:
        print(f.render())
        print(f"    fix: {f.hint}")
    for e in result.stale:
        print(
            f"stale baseline entry (no longer fires — remove it): "
            f"{e['rule']} {e['path']} ident={e['ident']!r}"
        )
    n_base = len(result.findings) - len(result.new)
    print(
        f"graftlint: {len(result.findings)} finding(s) "
        f"({n_base} baselined, {len(result.new)} new), "
        f"{len(result.stale)} stale baseline entr(y/ies) "
        f"[{elapsed:.2f}s]"
    )
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
