"""Pallas-contract rules.

GL002 — the PR-6 bug class: a kernel body that READS a ref listed as the
input side of ``input_output_aliases`` in its enclosing ``pallas_call``.
On hardware input and output alias the same HBM buffer, but interpret mode
materializes them separately, so writes through the output ref are
invisible to later input-ref reads (and batched grids re-read boundary
tiles an earlier program already rewrote).  The analysis tracks REF
ALIASING only — ``x = ref``, ``x = ref if c else other_ref``, and passing
the ref itself to an in-package helper — not derived values: reading data
that CAME from the ref is fine, re-reading the REF is the bug.  A read is
a ``ref[...]`` subscript load or a ``ref.at[...]`` slice (the DMA-source
idiom).

GL005 — statically checkable ``pallas_call`` contract breaches:

* VMEM block shapes whose lane (last) dim is not a multiple of 128, or
  whose sublane (second-minor) dim is neither 1 nor a multiple of the
  dtype tile height (f32/i32: 8, bf16/i16: 16, i8: 32 — the "(8, 128) ×
  dtype" rule; out_specs use the out_shape dtype, in_specs conservatively
  use 8).  Dims that are not integer literals or module-level int
  constants are skipped, not guessed.
* ``index_map`` lambda arity != grid rank, and index-map result length !=
  block rank.
* ``out_specs``/``out_shape`` list-length mismatch and per-slot block rank
  vs ``ShapeDtypeStruct`` rank mismatch.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from .callgraph import pallas_call_sites, positional_params
from .core import Finding, Module, Project, call_kwargs, literal_dims

_SUBLANE = {
    "float32": 8, "int32": 8, "uint32": 8,
    "bfloat16": 16, "float16": 16, "int16": 16, "uint16": 16,
    "int8": 32, "uint8": 32, "float8_e4m3fn": 32, "float8_e5m2": 32,
}


# ------------------------------------------------------------------ GL002
class _AliasReadWalker:
    """Find reads of aliased refs in a kernel, following ref aliasing
    through simple assignments and in-package helper calls."""

    def __init__(self, project: Project, kernel_name: str):
        self.project = project
        self.kernel_name = kernel_name
        self.findings: List[Finding] = []
        self._seen: Set[Tuple[int, FrozenSet[str]]] = set()

    def walk(self, mod_rel: str, fn: ast.FunctionDef,
             aliased: FrozenSet[str], depth: int = 0) -> None:
        key = (id(fn), aliased)
        if key in self._seen or depth > 10:
            return
        self._seen.add(key)
        mod = self.project.modules[mod_rel]
        refs: Set[str] = set(aliased)
        for _ in range(2):  # aliases may be formed before first use in loops
            before = len(refs)
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    continue
                v = node.value
                is_alias = (isinstance(v, ast.Name) and v.id in refs) or (
                    isinstance(v, ast.IfExp)
                    and any(
                        isinstance(b, ast.Name) and b.id in refs
                        for b in (v.body, v.orelse)
                    )
                )
                if is_alias:
                    refs.add(node.targets[0].id)
            if len(refs) == before:
                break

        def ref_name(expr: ast.AST) -> Optional[str]:
            if isinstance(expr, ast.Name) and expr.id in refs:
                return expr.id
            if isinstance(expr, ast.Attribute) and isinstance(
                expr.value, ast.Name
            ) and expr.value.id in refs:
                return expr.value.id
            return None

        for node in ast.walk(fn):
            if isinstance(node, ast.Subscript) and isinstance(
                node.ctx, ast.Load
            ):
                name = ref_name(node.value)
                if name is not None:
                    self.findings.append(
                        Finding(
                            rule="GL002",
                            path=mod.rel,
                            line=node.lineno,
                            ident=f"{self.kernel_name}:{fn.name}:{name}",
                            message=f"kernel {self.kernel_name} reads "
                            f"input-aliased ref '{name}' in {fn.name}(); "
                            "reads must go through the output-aliased ref "
                            "or they miss earlier writes (interpret mode, "
                            "re-read boundary tiles)",
                        )
                    )
            elif isinstance(node, ast.Call):
                target = self.project.internal_callee(mod, mod_rel, node.func)
                if target is None:
                    continue
                callee = self.project.function(*target)
                if callee is None:
                    continue
                params = positional_params(callee)
                flowing: Set[str] = set()
                for i, arg in enumerate(node.args):
                    if isinstance(arg, ast.Starred):
                        break
                    if i < len(params) and isinstance(arg, ast.Name) \
                            and arg.id in refs:
                        flowing.add(params[i])
                for kw in node.keywords:
                    if kw.arg and isinstance(kw.value, ast.Name) \
                            and kw.value.id in refs:
                        flowing.add(kw.arg)
                if flowing:
                    self.walk(target[0], callee, frozenset(flowing), depth + 1)


def _check_gl002(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for rel, mod, call, kernel, _encl in pallas_call_sites(project):
        aliases = call_kwargs(call).get("input_output_aliases")
        if kernel is None or not isinstance(aliases, ast.Dict):
            continue
        in_indices = [
            k.value
            for k in aliases.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, int)
        ]
        krel, kfn = kernel
        params = positional_params(kfn)
        aliased = frozenset(
            params[i] for i in in_indices if i < len(params)
        )
        if not aliased:
            continue
        walker = _AliasReadWalker(project, kfn.name)
        walker.walk(krel, kfn, aliased)
        findings.extend(walker.findings)
    return findings


# ------------------------------------------------------------------ GL005
def _spec_calls(project: Project, mod: Module, expr: ast.AST) -> List[Optional[ast.Call]]:
    """BlockSpec Call nodes from an in_specs/out_specs expression: a
    literal list/tuple or a single spec.  Unresolvable elements are None.
    Returns [] when the whole expression is not statically a spec list."""
    elts = expr.elts if isinstance(expr, (ast.List, ast.Tuple)) else [expr]
    out: List[Optional[ast.Call]] = []
    for e in elts:
        if isinstance(e, ast.Call):
            d = project.dotted_callee(mod, e.func)
            name = e.func.id if isinstance(e.func, ast.Name) else None
            if (d is not None and d.endswith(".BlockSpec")) or name == "BlockSpec":
                out.append(e)
                continue
        out.append(None)
    return out


def _memory_space(spec: ast.Call) -> Optional[str]:
    ms = call_kwargs(spec).get("memory_space")
    if ms is None:
        return None
    if isinstance(ms, ast.Attribute):
        return ms.attr
    if isinstance(ms, ast.Name):
        return ms.id
    return None


def _dtype_name(project: Project, mod: Module, expr: ast.AST) -> Optional[str]:
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        entry = mod.imports.get(expr.id)
        if entry is not None and entry[0] == "extobj":
            return entry[2]
        return expr.id
    return None


def _out_shape_calls(project: Project, mod: Module, expr: ast.AST) -> List[Optional[ast.Call]]:
    elts = expr.elts if isinstance(expr, (ast.List, ast.Tuple)) else [expr]
    out: List[Optional[ast.Call]] = []
    for e in elts:
        if isinstance(e, ast.Call):
            d = project.dotted_callee(mod, e.func)
            if d is not None and d.endswith(".ShapeDtypeStruct"):
                out.append(e)
                continue
        out.append(None)
    return out


def _check_block_spec(
    project: Project,
    mod: Module,
    spec: ast.Call,
    slot: str,
    encl: str,
    grid_rank: Optional[int],
    sublane_req: int,
    shape_struct: Optional[ast.Call],
    findings: List[Finding],
) -> None:
    def add(line: int, what: str, message: str) -> None:
        findings.append(
            Finding(
                rule="GL005",
                path=mod.rel,
                line=line,
                ident=f"{encl}:{slot}:{what}",
                message=message,
            )
        )

    block_shape = spec.args[0] if spec.args else None
    index_map = spec.args[1] if len(spec.args) > 1 else call_kwargs(spec).get(
        "index_map"
    )
    if _memory_space(spec) in ("SMEM", "ANY", "SEMAPHORE"):
        return  # tiling constraints apply to VMEM blocks only
    dims = literal_dims(block_shape, mod.consts) if block_shape is not None else None
    if dims is not None:
        if len(dims) >= 1 and dims[-1] is not None and dims[-1] % 128 != 0:
            add(
                block_shape.lineno, "lane",
                f"{slot} block lane dim {dims[-1]} is not a multiple of "
                "128 (VMEM tiling)",
            )
        if len(dims) >= 2 and dims[-2] is not None and dims[-2] != 1 \
                and dims[-2] % sublane_req != 0:
            add(
                block_shape.lineno, "sublane",
                f"{slot} block sublane dim {dims[-2]} is neither 1 nor a "
                f"multiple of {sublane_req} (dtype tile height)",
            )
    if isinstance(index_map, ast.Lambda):
        arity = len(index_map.args.args)
        if grid_rank is not None and arity != grid_rank:
            add(
                index_map.lineno, "arity",
                f"{slot} index_map takes {arity} args but the grid has "
                f"{grid_rank} dims",
            )
        ret = index_map.body
        if isinstance(ret, ast.Tuple) and dims is not None and \
                len(ret.elts) != len(dims):
            add(
                index_map.lineno, "rank",
                f"{slot} index_map returns {len(ret.elts)} coordinates for "
                f"a rank-{len(dims)} block shape",
            )
    if shape_struct is not None and dims is not None:
        sshape = shape_struct.args[0] if shape_struct.args else None
        if isinstance(sshape, (ast.Tuple, ast.List)) and \
                len(sshape.elts) != len(dims):
            add(
                block_shape.lineno, "out_rank",
                f"{slot} block shape is rank {len(dims)} but its out_shape "
                f"ShapeDtypeStruct is rank {len(sshape.elts)}",
            )


def _check_gl005(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for rel, mod, call, _kernel, encl in pallas_call_sites(project):
        kwargs = call_kwargs(call)
        grid = kwargs.get("grid")
        grid_rank: Optional[int] = None
        if isinstance(grid, (ast.Tuple, ast.List)):
            grid_rank = len(grid.elts)
        elif isinstance(grid, ast.Constant) and isinstance(grid.value, int):
            grid_rank = 1
        out_specs = kwargs.get("out_specs")
        out_shape = kwargs.get("out_shape")
        shape_calls: List[Optional[ast.Call]] = []
        if out_shape is not None:
            shape_calls = _out_shape_calls(project, mod, out_shape)
        if out_specs is not None and out_shape is not None and \
                isinstance(out_specs, (ast.List, ast.Tuple)) and \
                isinstance(out_shape, (ast.List, ast.Tuple)) and \
                len(out_specs.elts) != len(out_shape.elts):
            findings.append(
                Finding(
                    rule="GL005",
                    path=mod.rel,
                    line=out_specs.lineno,
                    ident=f"{encl}:out_specs:count",
                    message=f"pallas_call in {encl}() declares "
                    f"{len(out_specs.elts)} out_specs but "
                    f"{len(out_shape.elts)} out_shape entries",
                )
            )
        if out_specs is not None:
            for i, spec in enumerate(_spec_calls(project, mod, out_specs)):
                if spec is None:
                    continue
                struct = shape_calls[i] if i < len(shape_calls) else None
                sublane = 8
                if struct is not None and len(struct.args) > 1:
                    dname = _dtype_name(project, mod, struct.args[1])
                    sublane = _SUBLANE.get(dname or "", 8)
                _check_block_spec(
                    project, mod, spec, f"out_specs[{i}]", encl, grid_rank,
                    sublane, struct, findings,
                )
        in_specs = kwargs.get("in_specs")
        if in_specs is not None:
            for i, spec in enumerate(_spec_calls(project, mod, in_specs)):
                if spec is None:
                    continue
                _check_block_spec(
                    project, mod, spec, f"in_specs[{i}]", encl, grid_rank,
                    8, None, findings,
                )
    return findings


# rule code -> per-rule check callable (run_lint times each one)
RULE_CHECKS = {
    "GL002": _check_gl002,
    "GL005": _check_gl005,
}


def check(project: Project) -> List[Finding]:
    return _check_gl002(project) + _check_gl005(project)
