"""GL006 — Config fields declared but never read.

Generalizes tests/test_config_consumers.py: every field of the ``Config``
dataclass in the package's top-level ``config.py`` must be READ somewhere
outside config.py — as an attribute (``cfg.field``) or through
``getattr(obj, "field", ...)``.  Mentions in strings/comments do not
count.  Accept-and-ignore parameters (the VERDICT round-5 class) therefore
fail the lint gate unless they carry a baseline entry whose justification
documents WHY the TPU build deliberately has no consumer — the linter's
baseline is the single reviewed allowlist.
"""

from __future__ import annotations

import ast
from typing import List, Set

from .core import Finding, Project


def _config_fields(project: Project):
    mod = project.modules.get("config.py")
    if mod is None:
        return None, []
    for node in mod.tree.body:
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            fields = [
                (stmt.target.id, stmt.lineno)
                for stmt in node.body
                if isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
                and stmt.target.id != "raw"
            ]
            return mod, fields
    return mod, []


def _consumed_names(project: Project) -> Set[str]:
    names: Set[str] = set()
    for rel, mod in project.modules.items():
        if rel == "config.py":
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Attribute):
                names.add(node.attr)
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "getattr"
                and len(node.args) >= 2
                and isinstance(node.args[1], ast.Constant)
            ):
                names.add(str(node.args[1].value))
    return names


def check(project: Project) -> List[Finding]:
    mod, fields = _config_fields(project)
    if mod is None or not fields:
        return []
    consumed = _consumed_names(project)
    return [
        Finding(
            rule="GL006",
            path=mod.rel,
            line=line,
            ident=name,
            message=f"Config.{name} is declared but never read outside "
            "config.py — an accept-and-ignore parameter",
        )
        for name, line in fields
        if name not in consumed
    ]


# rule code -> per-rule check callable (run_lint times each one)
RULE_CHECKS = {"GL006": check}
