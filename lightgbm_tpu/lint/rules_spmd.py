"""GL007–GL010: SPMD collective-congruence, axis-name, retrace-hazard and
host-divergence rules.

All four rules share one :class:`~.callgraph.SpmdIndex` build (cached on the
project), evaluating every function scope under "all replicas execute this
together" semantics:

* **GL007** — a ``psum``/``pmax``/``pmin``/``all_gather`` must be spelled as
  an ``obs/collectives`` timed wrapper (the every-site-is-measured
  invariant) and must be congruent across branches: a Python ``if`` whose
  test is NOT derived from the axis-name family may not make one side
  execute collectives the other side skips, and every resolvable
  ``lax.cond``/``lax.switch`` branch must execute the same collective
  multiset.  Axis-derived guards are exempt: the axis name rides in static
  jit arguments, so every replica traces the same side.
* **GL008** — one axis-name source per jitted region (literal vs the
  ``GrowerParams.axis_name`` plumbing), and no collective whose axis source
  can be ``None`` without an ``axis_name is not None``-style dominator.
* **GL009** — scalar-annotated jit-entry parameters must be declared in
  ``static_argnames`` (or pinned with an ``asarray``-family wrapper), and
  ``io_callback``/``pure_callback`` sites must pass ``ordered=True`` unless
  ordering is enforced by an explicit data dependency (baseline-justified).
* **GL010** — a value derived from ``process_index``, ``time.*``,
  ``os.environ``, or unseeded RNG may not gate a branch that executes a
  collective (including host gathers): hosts that disagree on the gate
  deadlock the ones that entered.

The bias mirrors the rest of graftlint: unresolvable constructs (variable
``lax.switch`` branch lists, out-of-package callees) are skipped, never
guessed — a miss is recoverable, a noisy gate is not.
"""

from __future__ import annotations

import ast
from collections import Counter
from typing import List, Optional, Set

from .callgraph import (
    SpmdScope,
    TaintWalker,
    _test_src,
    jit_entries,
    spmd_index,
)
from .core import Finding, Module, Project, names_in
from .rules_jit import _ASARRAY_WRAPPERS

# the one module allowed to spell raw jax.lax collectives: the timed
# wrappers themselves (and their axis-name handling is the sanctioned one)
_OBS_COLLECTIVES = "obs/collectives.py"


def _sanctioned(scope: SpmdScope) -> bool:
    return scope.rel.replace("\\", "/").endswith(_OBS_COLLECTIVES)


def _summary_str(c: Counter) -> str:
    if not c:
        return "no collectives"
    parts = []
    for (kind, key), n in sorted(c.items(), key=lambda kv: str(kv[0])):
        parts.append(f"{n}x {kind}[{_axis_key_str(key)}]")
    return ", ".join(parts)


def _axis_key_str(key) -> str:
    if key == ("param", "axis_name"):
        return "params.axis_name"
    if key and key[0] == "literal":
        return f'literal "{key[1]}"'
    if key and key[0] == "mesh":
        return f"mesh axes ({key[1]})"
    if key == ("none",):
        return "None"
    if key == ("host",):
        return "host"
    return "?"


def _mesh_axis_names(project: Project) -> frozenset:
    """Axis-name literals declared by a module-level ``MESH_AXIS_NAMES``
    tuple (parallel/mesh.py) — the named-mesh table.

    GL008(a) treats literals drawn from this table as ONE consistent
    source per jitted region: a 2-D ``('data', 'feature')`` grow path
    legitimately psums histograms over one mesh axis while electing the
    winner over the other, and both spellings come from the same table.
    Literals NOT in the table (a typo'd axis, an ad-hoc string) still
    count as separate sources and keep firing."""
    names: Set[str] = set()
    for mod in project.modules.values():
        for node in mod.tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "MESH_AXIS_NAMES"
                and isinstance(node.value, (ast.Tuple, ast.List))
            ):
                for e in node.value.elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, str):
                        names.add(e.value)
    return frozenset(names)


def _collapse_mesh_literals(keys: Set, mesh_names: frozenset) -> Set:
    """Merge literal axis keys that all come from the mesh-axis table into
    one ``('mesh', ...)`` pseudo-key; every other key passes through."""
    mesh_lits = {
        k for k in keys if k[0] == "literal" and k[1] in mesh_names
    }
    if len(mesh_lits) < 2:
        return keys
    merged = ("mesh", ", ".join(sorted(k[1] for k in mesh_lits)))
    return (keys - mesh_lits) | {merged}


# ------------------------------------------------------------------ GL007
def _check_gl007(project: Project) -> List[Finding]:
    idx = spmd_index(project)
    findings: List[Finding] = []
    for scope in idx.scopes:
        if _sanctioned(scope):
            continue
        # (a) raw jax.lax collectives outside obs/collectives.py
        raw_seen: Counter = Counter()
        for site in scope.sites:
            if not site.raw:
                continue
            raw_seen[site.kind] += 1
            findings.append(
                Finding(
                    rule="GL007",
                    path=scope.mod.rel,
                    line=site.node.lineno,
                    ident=(
                        f"{scope.qualname}:raw-{site.kind}:"
                        f"{raw_seen[site.kind]}"
                    ),
                    message=(
                        f"raw jax.lax.{site.kind} in {scope.qualname}; "
                        "route it through obs.collectives.timed_"
                        f"{site.kind if site.kind != 'all_gather' else 'psum'}"
                        "(..., site=...) so measured-collective accounting "
                        "and the perf contract cover this site"
                    ),
                )
            )
        # (b) one-sided collectives across a non-axis-derived Python if
        for ifsite in scope.ifs:
            test = ifsite.node.test
            if idx.trace_static_test(scope, test):
                continue
            body = idx.block_summary(scope, ifsite.node.body)
            other_stmts = (
                ifsite.node.orelse
                if ifsite.node.orelse
                else (ifsite.sibling or [])
            )
            other = idx.block_summary(scope, other_stmts)
            if bool(body) == bool(other):
                continue
            entered, skipped = ("taken", "fall-through")
            summary = body if body else other
            findings.append(
                Finding(
                    rule="GL007",
                    path=scope.mod.rel,
                    line=ifsite.node.lineno,
                    ident=f"{scope.qualname}:if:{_test_src(test)}",
                    message=(
                        f"one-sided collective in {scope.qualname}: the "
                        f"{entered if body else skipped} branch of "
                        f"`if {_test_src(test)}` executes "
                        f"{_summary_str(summary)} the other side skips, "
                        "and the test is not derived from the axis-name "
                        "family — replicas that disagree deadlock"
                    ),
                )
            )
        # (c) lax.cond / lax.switch branch congruence
        ncond = 0
        for cond in scope.conds:
            branches: Optional[List[ast.AST]]
            if cond.is_switch:
                seq = (
                    cond.node.args[1] if len(cond.node.args) > 1 else None
                )
                if isinstance(seq, (ast.List, ast.Tuple)):
                    branches = list(seq.elts)
                else:
                    branches = None  # variable branch list: skip, don't guess
            else:
                branches = (
                    [cond.node.args[1], cond.node.args[2]]
                    if len(cond.node.args) >= 3
                    else None
                )
            if not branches:
                continue
            summaries = [idx.expr_summary(scope, b) for b in branches]
            if any(s is None for s in summaries):
                continue
            ncond += 1
            if all(s == summaries[0] for s in summaries[1:]):
                continue
            op = "lax.switch" if cond.is_switch else "lax.cond"
            detail = " vs ".join(_summary_str(s) for s in summaries)
            findings.append(
                Finding(
                    rule="GL007",
                    path=scope.mod.rel,
                    line=cond.node.lineno,
                    ident=f"{scope.qualname}:cond:{ncond}",
                    message=(
                        f"{op} in {scope.qualname} has incongruent "
                        f"collective branches ({detail}); the predicate is "
                        "traced, so one replica can enter a branch whose "
                        "collective the others never post"
                    ),
                )
            )
    return findings


# ------------------------------------------------------------------ GL008
def _check_gl008(project: Project) -> List[Finding]:
    idx = spmd_index(project)
    findings: List[Finding] = []
    mesh_names = _mesh_axis_names(project)
    # (a) mixed axis-name sources inside one jitted region.  Literals from
    # the MESH_AXIS_NAMES table collapse to one source first: the named-mesh
    # grow path runs per-axis collectives over both 'data' and 'feature'.
    seen_entries: Set[int] = set()
    for rel, mod, fn, _statics in jit_entries(project):
        if id(fn) in seen_entries:
            continue
        seen_entries.add(id(fn))
        scope = idx.by_func.get(id(fn))
        if scope is None or _sanctioned(scope):
            continue
        summary = idx.scope_summary(scope, depth=8)
        keys = {k for (_kind, k) in summary if k[0] in ("literal", "param")}
        keys = _collapse_mesh_literals(keys, mesh_names)
        if len(keys) <= 1:
            continue
        findings.append(
            Finding(
                rule="GL008",
                path=mod.rel,
                line=fn.lineno,
                ident=f"{fn.name}:axis-sources",
                message=(
                    f"jitted {fn.name}() reaches collectives with MIXED "
                    "axis-name sources ("
                    + ", ".join(sorted(_axis_key_str(k) for k in keys))
                    + "); paired reduction sites with different axis names "
                    "sum over different meshes — wrong numbers, no crash"
                ),
            )
        )
    # (b) collective reachable where the axis name can be None
    for scope in idx.scopes:
        if _sanctioned(scope):
            continue
        nsite = 0
        for site in scope.sites:
            if site.axis_key != ("param", "axis_name"):
                continue
            if site.axis_guarded:
                continue
            if not idx.axis_possibly_none(scope, site.axis_expr):
                continue
            nsite += 1
            findings.append(
                Finding(
                    rule="GL008",
                    path=scope.mod.rel,
                    line=site.node.lineno,
                    ident=f"{scope.qualname}:none-{site.kind}:{nsite}",
                    message=(
                        f"{site.kind} in {scope.qualname} is reachable "
                        "with axis_name=None (Optional source, no "
                        "`axis_name is not None` dominator on this path); "
                        "dominate the site with an axis guard"
                    ),
                )
            )
    return findings


# ------------------------------------------------------------------ GL009
_SCALARS = {"int", "float", "bool", "str"}


def _scalar_annotation(ann: Optional[ast.AST]) -> bool:
    """Python-scalar annotations that mark a retrace-per-value hazard when
    the parameter is not static.  Bare ``Tuple``/``tuple`` is NOT scalar —
    an unparameterized tuple can (and in this tree does) hold arrays."""
    if ann is None:
        return False
    if isinstance(ann, ast.Name):
        return ann.id in _SCALARS
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value.strip() in _SCALARS
    if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
        return _scalar_annotation(ann.left) or _scalar_annotation(ann.right)
    if isinstance(ann, ast.Subscript):
        base = ann.value
        bname = (
            base.id
            if isinstance(base, ast.Name)
            else base.attr if isinstance(base, ast.Attribute) else None
        )
        if bname == "Optional":
            return _scalar_annotation(ann.slice)
        if bname in ("Tuple", "tuple"):
            sl = ann.slice
            if isinstance(sl, ast.Tuple):
                return any(_scalar_annotation(e) for e in sl.elts)
            return _scalar_annotation(sl)
    return False


def _check_gl009(project: Project) -> List[Finding]:
    idx = spmd_index(project)
    findings: List[Finding] = []
    # (a) scalar-annotated jit-entry params outside static_argnames
    seen_entries: Set[int] = set()
    for rel, mod, fn, statics in jit_entries(project):
        if id(fn) in seen_entries:
            continue
        seen_entries.add(id(fn))
        pinned: Set[str] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            dotted = project.dotted_callee(mod, node.func)
            if dotted is None or dotted.split(".")[-1] not in (
                _ASARRAY_WRAPPERS
            ):
                continue
            for arg in node.args:
                pinned.update(names_in(arg))
        args = fn.args
        for a in args.posonlyargs + args.args + args.kwonlyargs:
            if a.arg in statics or a.arg in pinned:
                continue
            if not _scalar_annotation(a.annotation):
                continue
            findings.append(
                Finding(
                    rule="GL009",
                    path=mod.rel,
                    line=a.lineno,
                    ident=f"{fn.name}:{a.arg}",
                    message=(
                        f"jit entry {fn.name}() takes Python scalar "
                        f"`{a.arg}` ({ast.unparse(a.annotation)}) without "
                        "declaring it in static_argnames or pinning it "
                        "with jnp.asarray — every new value retraces"
                    ),
                )
            )
    # (b) io_callback / pure_callback without ordered=True
    for scope in idx.scopes:
        ncb = 0
        for cb in scope.callbacks:
            if cb.ordered:
                continue
            ncb += 1
            findings.append(
                Finding(
                    rule="GL009",
                    path=scope.mod.rel,
                    line=cb.node.lineno,
                    ident=f"{scope.qualname}:{cb.name}:{ncb}",
                    message=(
                        f"{cb.name} in {scope.qualname} without "
                        "ordered=True; XLA may reorder it across the "
                        "region it is meant to bracket — pass "
                        "ordered=True, or enforce ordering with an "
                        "explicit data dependency and baseline this site"
                    ),
                )
            )
    return findings


# ------------------------------------------------------------------ GL010
def _is_source_call(project: Project, mod: Module, node: ast.Call) -> bool:
    dotted = project.dotted_callee(mod, node.func)
    if dotted is None:
        return False
    if dotted.endswith(".process_index") or dotted == "process_index":
        return True
    if dotted == "os.getenv" or dotted.startswith("os.environ"):
        return True
    if dotted.startswith("time."):
        return True
    if dotted.startswith("random."):
        return True
    if dotted.startswith("numpy.random."):
        last = dotted.split(".")[-1]
        if last in ("default_rng", "RandomState") and (
            node.args or node.keywords
        ):
            return False  # explicitly seeded: replica-uniform
        return True
    return False


def _expr_has_source(project: Project, mod: Module, expr: ast.AST) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Call) and _is_source_call(project, mod, n):
            return True
        if (
            isinstance(n, ast.Attribute)
            and n.attr == "environ"
            and isinstance(n.value, ast.Name)
            and n.value.id == "os"
        ):
            return True
    return False


def _fn_has_source(project: Project, mod: Module, fn: ast.AST) -> bool:
    for n in ast.walk(fn):
        if isinstance(n, ast.Call) and _is_source_call(project, mod, n):
            return True
        if (
            isinstance(n, ast.Attribute)
            and n.attr == "environ"
            and isinstance(n.value, ast.Name)
            and n.value.id == "os"
        ):
            return True
    return False


def _divergent_seeds(
    project: Project, mod: Module, fn: ast.FunctionDef
) -> Set[str]:
    """Names assigned (anywhere in ``fn``) from a host-divergent source
    expression.  The TaintWalker's own assignment fixpoint takes it from
    here — these are just the roots."""
    seeds: Set[str] = set()
    for node in ast.walk(fn):
        value = None
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign):
            targets, value = [node.target], node.value
        if value is None or not _expr_has_source(project, mod, value):
            continue
        for t in targets:
            # plain-name targets only: `self.x = time.time()` must not
            # mark every later `self.y` gate divergent
            if not isinstance(t, (ast.Name, ast.Tuple, ast.List)):
                continue
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    seeds.add(n.id)
    return seeds


def _check_gl010(project: Project) -> List[Finding]:
    idx = spmd_index(project)
    findings: List[Finding] = []

    def visit(
        mod_rel: str, fn: ast.FunctionDef, tainted: Set[str], node: ast.AST
    ) -> None:
        mod = project.modules[mod_rel]
        scope = idx.by_func.get(id(fn))
        if scope is None:
            return
        if isinstance(node, (ast.If, ast.While)):
            test = node.test
            if not (
                set(names_in(test)) & tainted
                or _expr_has_source(project, mod, test)
            ):
                return
            branch = list(node.body) + list(node.orelse)
            if not idx.block_summary(scope, branch, include_host=True):
                return
            findings.append(
                Finding(
                    rule="GL010",
                    path=mod.rel,
                    line=node.lineno,
                    ident=f"{fn.name}:{_test_src(test)}",
                    message=(
                        f"host-divergent gate `{_test_src(test)}` in "
                        f"{fn.name}() guards a branch that executes a "
                        "collective; hosts that disagree on the gate "
                        "deadlock the ones that entered — hoist the "
                        "collective or derive the gate from replicated "
                        "data"
                    ),
                )
            )
            return
        if isinstance(node, ast.Call) and node.args:
            dotted = project.dotted_callee(mod, node.func)
            if dotted not in ("jax.lax.cond", "jax.lax.switch"):
                return
            pred = node.args[0]
            if not (
                set(names_in(pred)) & tainted
                or _expr_has_source(project, mod, pred)
            ):
                return
            has_collective = False
            for b in node.args[1:3]:
                s = idx.expr_summary(scope, b, include_host=True)
                if s:
                    has_collective = True
            if not has_collective:
                return
            findings.append(
                Finding(
                    rule="GL010",
                    path=mod.rel,
                    line=node.lineno,
                    ident=f"{fn.name}:{_test_src(pred)}",
                    message=(
                        f"host-divergent predicate `{_test_src(pred)}` "
                        f"feeds a {dotted} whose branches execute "
                        f"collectives in {fn.name}() — replicas that "
                        "disagree deadlock"
                    ),
                )
            )

    for rel, mod in project.modules.items():
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not _fn_has_source(project, mod, node):
                continue
            seeds = _divergent_seeds(project, mod, node)
            walker = TaintWalker(project, visit, taint_attr_bases=False)
            walker.walk(rel, node, frozenset(seeds))
    return findings


RULE_CHECKS = {
    "GL007": _check_gl007,
    "GL008": _check_gl008,
    "GL009": _check_gl009,
    "GL010": _check_gl010,
}


def check(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for fn in RULE_CHECKS.values():
        out.extend(fn(project))
    return out
