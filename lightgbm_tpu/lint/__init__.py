"""graftlint — tracer-safety & Pallas-contract static analysis.

Purpose-built for this JAX/Pallas codebase: the rule set encodes the bug
classes previous PRs paid for at runtime (interpret-mode aliased-ref
reads, bare-jit retrace-accounting holes, accept-and-ignore config
params) so they become build-time errors instead.  Run it as

    python -m lightgbm_tpu.lint [--baseline lint_baseline.json] [paths...]

or through the pytest gate (tests/test_lint.py) and the hard CI gate at
the top of tools/run_tests.sh.  Rules:

=====  ==============================================================
GL001  bare ``jax.jit``/``jax.pmap`` outside obs/jit.py
GL002  Pallas kernel reads the input side of ``input_output_aliases``
GL003  host-sync call on a tracer-flowing value in jit-reachable code
GL004  weak-typed float constant closed over by a jitted function
GL005  ``pallas_call`` contract: block tiling, index_map arity,
       out_shape/out_specs consistency
GL006  Config field declared in config.py but never read
=====  ==============================================================

Per-line suppression: ``# graftlint: disable=GL001`` (comma-separated
codes, or bare ``disable`` for all).  Intentional exceptions live in
``lint_baseline.json`` with a one-line justification each; stale entries
fail the run.  See README "Static analysis".
"""

from .core import (  # noqa: F401
    Finding,
    LintResult,
    Project,
    RULES,
    load_baseline,
    run_lint,
    write_baseline,
)

__all__ = [
    "Finding",
    "LintResult",
    "Project",
    "RULES",
    "load_baseline",
    "run_lint",
    "write_baseline",
]
