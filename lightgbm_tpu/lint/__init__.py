"""graftlint — tracer-safety, Pallas-contract & SPMD static analysis.

Purpose-built for this JAX/Pallas codebase: the rule set encodes the bug
classes previous PRs paid for at runtime (interpret-mode aliased-ref
reads, bare-jit retrace-accounting holes, accept-and-ignore config
params) plus the silent multi-host SPMD classes the mesh refactor risks
(one-sided collectives, mismatched axis names, host-divergent gates) so
they become build-time errors instead.  Run it as

    python -m lightgbm_tpu.lint [--baseline lint_baseline.json] [paths...]
    python -m lightgbm_tpu.lint --changed-only   # dev-loop fast mode
    python -m lightgbm_tpu.lint --json           # incl. per-rule timings
    python -m lightgbm_tpu.lint --ir             # + GL011-GL015 jaxpr audit
    python -m lightgbm_tpu.lint --format=github  # ::error annotations

or through the pytest gate (tests/test_lint.py) and the hard CI gate at
the top of tools/run_tests.sh.  Rules:

=====  ==============================================================
GL001  bare ``jax.jit``/``jax.pmap`` outside obs/jit.py
GL002  Pallas kernel reads the input side of ``input_output_aliases``
GL003  host-sync call on a tracer-flowing value in jit-reachable code
GL004  weak-typed float constant closed over by a jitted function
GL005  ``pallas_call`` contract: block tiling, index_map arity,
       out_shape/out_specs consistency
GL006  Config field declared in config.py but never read
GL007  collective congruence: raw ``jax.lax`` collective outside
       obs/collectives.py, or a psum/pmax/pmin/all_gather reached on
       only one branch of a non-trace-static ``if`` / ``lax.cond``
GL008  axis-name consistency: mixed axis-name sources in one jitted
       region, or a collective reachable with ``axis_name=None``
GL009  retrace hazards: scalar-annotated jit params outside
       ``static_argnames``, callbacks without ``ordered=True``
GL010  host-divergent value (process_index / time / os.environ /
       unseeded RNG) gating a branch that executes a collective
-----  --------------------------------------------------------------
       IR-grade rules (``--ir``): ``lint.ir`` traces the real
       jit/shard_map entries to jaxprs under an abstract-input config
       matrix (``jax.make_jaxpr`` only — no device execution) and
       ``rules_ir`` audits the traced facts
GL011  traced collective incongruent with the sanctioned timed
       wrappers, the entry's declared mesh axes, the analytic
       ``mesh_psum_bytes_per_iteration`` payload model, or the GL007
       AST site model (incl. entries that fail to trace)
GL012  64-bit aval in a hot entry — directly, or the moment
       ``enable_x64`` flips on (the dtype-pin invariance contract)
GL013  per-iteration carried state rebound without ``donate_argnums``
       (wasted-HBM bytes reported per argument)
GL014  pallas kernel's static VMEM working set (2x operand blocks +
       scratch) exceeds the 16 MiB v5e per-core arena
GL015  host callback compiled into a hot entry outside the sanctioned
       obs.collectives wrappers (per-iteration device->host round trip)
=====  ==============================================================

GL007–GL010 share one SPMD index (``callgraph.SpmdIndex``): a
path-sensitive walk of every function scope under "all replicas execute
this together" semantics, with guards derived from the axis-name family
or a jit entry's ``static_argnames`` treated as trace-static (replica-
uniform by the static-argument contract).

Per-line suppression: ``# graftlint: disable=GL001`` (comma-separated
codes, or bare ``disable`` for all).  Intentional exceptions live in
``lint_baseline.json`` with a one-line justification each; stale entries
fail the run.  See README "Static analysis".
"""

from .core import (  # noqa: F401
    Finding,
    LintResult,
    Project,
    RULES,
    load_baseline,
    run_lint,
    write_baseline,
)

__all__ = [
    "Finding",
    "LintResult",
    "Project",
    "RULES",
    "load_baseline",
    "run_lint",
    "write_baseline",
]
