"""Objective functions: (score, label, weight) -> (grad, hess), vmapped JAX.

Reference analogs: include/LightGBM/objective_function.h (GetGradients
contract), src/objective/*.hpp (per-loss math), factory
src/objective/objective_function.cpp:22.

TPU-native design: every objective exposes ``get_gradients(score, rng)`` as a
pure JAX function over a ``[num_class, N]`` score array — the reference's
per-row OpenMP loops become whole-array vectorized expressions that XLA fuses
into the boosting step.  Ranking objectives pre-pack queries into padded
``[num_queries, Q]`` segments so the per-query OpenMP loop
(rank_objective.hpp:73) becomes a vmap; the CUDA per-query bitonic sort
(cuda_rank_objective.cu) becomes ``jnp.argsort`` inside the vmap.

Host-side (setup-time) work — label validation, class priors, max-DCG
normalizers — stays NumPy, exactly as it is setup-time C++ in the reference.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..config import Config

_EPS = 1e-15


def _weighted_percentile(values: np.ndarray, weights: Optional[np.ndarray], alpha: float) -> float:
    """Percentile used by l1/quantile/mape boost-from-score and leaf renewal.

    Follows the reference's PercentileFun / WeightedPercentileFun
    (src/objective/regression_objective.hpp:18-88): linear interpolation
    between the two order statistics around the alpha position.
    """
    values = np.asarray(values, dtype=np.float64)
    cnt = len(values)
    if cnt == 0:
        return 0.0
    if cnt == 1:
        return float(values[0])
    if weights is None:
        sorted_v = np.sort(values)
        float_pos = (cnt - 1) * alpha  # position from the low end
        pos = int(float_pos)
        bias = float_pos - pos
        if pos + 1 < cnt:
            return float(sorted_v[pos] * (1 - bias) + sorted_v[pos + 1] * bias)
        return float(sorted_v[pos])
    order = np.argsort(values, kind="stable")
    sv = values[order]
    sw = np.asarray(weights, dtype=np.float64)[order]
    cdf = np.cumsum(sw)
    threshold = cdf[-1] * alpha
    pos = int(np.searchsorted(cdf, threshold, side="right"))
    pos = min(pos, cnt - 1)
    if pos == 0 or pos == cnt - 1:
        return float(sv[pos])
    v1, v2 = sv[pos - 1], sv[pos]
    if pos + 1 < cnt and cdf[pos + 1] - cdf[pos] >= 1.0:
        return float((threshold - cdf[pos]) / (cdf[pos + 1] - cdf[pos]) * (v2 - v1) + v1)
    return float(v2)


class ObjectiveFunction:
    """Base objective (reference: include/LightGBM/objective_function.h:37)."""

    name: str = "custom"
    is_constant_hessian: bool = False
    is_renew_tree_output: bool = False
    need_query: bool = False

    def __init__(self, config: Config):
        self.config = config
        self.num_class = 1
        self.label: Optional[jnp.ndarray] = None
        self.weight: Optional[jnp.ndarray] = None
        self._label_np: Optional[np.ndarray] = None
        self._weight_np: Optional[np.ndarray] = None
        self.num_data = 0

    # ------------------------------------------------------------------ init
    def init(self, label: np.ndarray, weight: Optional[np.ndarray], query_boundaries=None, position=None) -> None:
        self._label_np = np.asarray(label, dtype=np.float64)
        self._weight_np = None if weight is None else np.asarray(weight, dtype=np.float64)
        self.num_data = len(self._label_np)
        self.label = jnp.asarray(self._label_np, dtype=jnp.float32)
        self.weight = None if weight is None else jnp.asarray(self._weight_np, dtype=jnp.float32)

    def per_row_device_arrays(self):
        """Per-row DEVICE arrays consumed by ``get_gradients``, as
        (holder, attr_name, row_axis) triples.

        The distributed Booster pads these with zero rows and re-places them
        sharded over the data mesh; host-side statistics (``_label_np`` /
        ``_weight_np``, class priors, percentiles) stay UNPADDED so
        boost_from_score / renew_tree_output remain exact.  Padded rows carry
        zero weight, which zeroes their gradients in every objective."""
        return [(self, "label", 0), (self, "weight", 0)]

    # ------------------------------------------------------------- gradients
    def get_gradients(self, score: jnp.ndarray, rng: Optional[jax.Array] = None) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """score: [num_class, N] raw scores -> (grad, hess) of the same shape."""
        raise NotImplementedError

    def _apply_weight(self, grad, hess):
        if self.weight is None:
            return grad, hess
        return grad * self.weight, hess * self.weight

    # ----------------------------------------------------------------- misc
    def boost_from_score(self, class_id: int = 0) -> float:
        """Init score (reference BoostFromScore); 0.0 when not applicable."""
        return 0.0

    def convert_output(self, raw: jnp.ndarray) -> jnp.ndarray:
        """Raw score -> output space (sigmoid/softmax/exp); identity default."""
        return raw

    def class_need_train(self, class_id: int) -> bool:
        return True

    def renew_tree_output(
        self,
        score: np.ndarray,  # [N] current score (before adding this tree)
        leaf_id: np.ndarray,  # [N] leaf index per row
        leaf_values: np.ndarray,  # [L] current leaf outputs (no shrinkage yet)
        mask: Optional[np.ndarray],  # in-bag mask or None
    ) -> np.ndarray:
        """Per-leaf output renewal for order-statistic losses (host-side)."""
        return leaf_values

    def to_string(self) -> str:
        return self.name

    @property
    def num_tree_per_iteration(self) -> int:
        return self.num_class


# =========================================================== regression family
class RegressionL2(ObjectiveFunction):
    """L2 loss (reference: RegressionL2loss, regression_objective.hpp:95)."""

    name = "regression"

    def __init__(self, config: Config):
        super().__init__(config)
        self.sqrt = bool(config.reg_sqrt)
        self.is_constant_hessian = True

    def init(self, label, weight, query_boundaries=None, position=None):
        super().init(label, weight)
        if self.sqrt:
            t = np.sign(self._label_np) * np.sqrt(np.abs(self._label_np))
            self._label_np = t
            self.label = jnp.asarray(t, dtype=jnp.float32)
        self.is_constant_hessian = weight is None

    def get_gradients(self, score, rng=None):
        grad = score[0] - self.label
        hess = jnp.ones_like(grad)
        g, h = self._apply_weight(grad, hess)
        return g[None], h[None]

    def boost_from_score(self, class_id: int = 0) -> float:
        if self._weight_np is None:
            return float(np.mean(self._label_np))
        return float(np.average(self._label_np, weights=self._weight_np))

    def convert_output(self, raw):
        if self.sqrt:
            return jnp.sign(raw) * raw * raw
        return raw

    def to_string(self):
        return f"{self.name} sqrt" if self.sqrt else self.name


class RegressionL1(RegressionL2):
    """L1 loss (reference: RegressionL1loss, regression_objective.hpp:205)."""

    name = "regression_l1"
    is_renew_tree_output = True

    def __init__(self, config: Config):
        super().__init__(config)
        self.sqrt = False
        self._renew_alpha = 0.5

    def get_gradients(self, score, rng=None):
        diff = score[0] - self.label
        grad = jnp.sign(diff)
        hess = jnp.ones_like(grad)
        g, h = self._apply_weight(grad, hess)
        return g[None], h[None]

    def boost_from_score(self, class_id: int = 0) -> float:
        return _weighted_percentile(self._label_np, self._weight_np, 0.5)

    def _renew_weights(self) -> Optional[np.ndarray]:
        return self._weight_np

    def renew_tree_output(self, score, leaf_id, leaf_values, mask):
        """Weighted median of residual per leaf (regression_objective.hpp:252)."""
        out = np.array(leaf_values, dtype=np.float64)
        residual = self._label_np - score
        w = self._renew_weights()
        sel_all = np.ones(len(residual), bool) if mask is None else mask > 0
        for leaf in range(len(out)):
            sel = (leaf_id == leaf) & sel_all
            if sel.any():
                out[leaf] = _weighted_percentile(
                    residual[sel], None if w is None else w[sel], self._renew_alpha
                )
        return out

    def convert_output(self, raw):
        return raw

    def to_string(self):
        return self.name


class RegressionHuber(RegressionL2):
    """Huber loss (reference: RegressionHuberLoss, regression_objective.hpp:292)."""

    name = "huber"

    def __init__(self, config: Config):
        super().__init__(config)
        self.sqrt = False
        self.alpha = float(config.alpha)

    def get_gradients(self, score, rng=None):
        diff = score[0] - self.label
        grad = jnp.clip(diff, -self.alpha, self.alpha)
        hess = jnp.ones_like(grad)
        g, h = self._apply_weight(grad, hess)
        return g[None], h[None]

    def convert_output(self, raw):
        return raw

    def to_string(self):
        return self.name


class RegressionFair(RegressionL2):
    """Fair loss (reference: RegressionFairLoss, regression_objective.hpp:351)."""

    name = "fair"

    def __init__(self, config: Config):
        super().__init__(config)
        self.sqrt = False
        self.c = float(config.fair_c)

    def init(self, label, weight, query_boundaries=None, position=None):
        super().init(label, weight)
        self.is_constant_hessian = False

    def get_gradients(self, score, rng=None):
        x = score[0] - self.label
        denom = jnp.abs(x) + self.c
        grad = self.c * x / denom
        hess = self.c * self.c / (denom * denom)
        g, h = self._apply_weight(grad, hess)
        return g[None], h[None]

    def convert_output(self, raw):
        return raw

    def to_string(self):
        return self.name


class RegressionPoisson(RegressionL2):
    """Poisson loss (reference: RegressionPoissonLoss, regression_objective.hpp:398)."""

    name = "poisson"

    def __init__(self, config: Config):
        super().__init__(config)
        self.sqrt = False
        self.max_delta_step = float(config.poisson_max_delta_step)

    def init(self, label, weight, query_boundaries=None, position=None):
        super().init(label, weight)
        self.is_constant_hessian = False
        if np.min(self._label_np) < 0:
            raise ValueError(f"[{self.name}]: at least one target label is negative")
        if np.sum(self._label_np) == 0:
            raise ValueError(f"[{self.name}]: sum of labels is zero")

    def get_gradients(self, score, rng=None):
        exp_score = jnp.exp(score[0])
        grad = exp_score - self.label
        hess = exp_score * math.exp(self.max_delta_step)
        g, h = self._apply_weight(grad, hess)
        return g[None], h[None]

    def boost_from_score(self, class_id: int = 0) -> float:
        mean = RegressionL2.boost_from_score(self)
        return math.log(max(mean, 1e-300))

    def convert_output(self, raw):
        return jnp.exp(raw)

    def to_string(self):
        return self.name


class RegressionQuantile(RegressionL2):
    """Quantile loss (reference: RegressionQuantileloss, regression_objective.hpp:478)."""

    name = "quantile"
    is_renew_tree_output = True

    def __init__(self, config: Config):
        super().__init__(config)
        self.sqrt = False
        self.alpha = float(config.alpha)
        if not (0.0 < self.alpha < 1.0):
            raise ValueError("alpha must be in (0, 1) for quantile objective")

    def get_gradients(self, score, rng=None):
        delta = score[0] - self.label
        grad = jnp.where(delta >= 0, 1.0 - self.alpha, -self.alpha)
        hess = jnp.ones_like(grad)
        g, h = self._apply_weight(grad, hess)
        return g[None], h[None]

    def boost_from_score(self, class_id: int = 0) -> float:
        return _weighted_percentile(self._label_np, self._weight_np, self.alpha)

    def renew_tree_output(self, score, leaf_id, leaf_values, mask):
        out = np.array(leaf_values, dtype=np.float64)
        residual = self._label_np - score
        w = self._weight_np
        sel_all = np.ones(len(residual), bool) if mask is None else mask > 0
        for leaf in range(len(out)):
            sel = (leaf_id == leaf) & sel_all
            if sel.any():
                out[leaf] = _weighted_percentile(
                    residual[sel], None if w is None else w[sel], self.alpha
                )
        return out

    def convert_output(self, raw):
        return raw

    def to_string(self):
        return f"{self.name} alpha:{self.alpha:g}"


class RegressionMAPE(RegressionL1):
    """MAPE loss (reference: RegressionMAPELOSS, regression_objective.hpp:578)."""

    name = "mape"

    def init(self, label, weight, query_boundaries=None, position=None):
        super().init(label, weight)
        lw = 1.0 / np.maximum(1.0, np.abs(self._label_np))
        if self._weight_np is not None:
            lw = lw * self._weight_np
        self._label_weight_np = lw
        self._label_weight = jnp.asarray(lw, dtype=jnp.float32)
        self.is_constant_hessian = True

    def per_row_device_arrays(self):
        return super().per_row_device_arrays() + [(self, "_label_weight", 0)]

    def get_gradients(self, score, rng=None):
        diff = score[0] - self.label
        grad = jnp.sign(diff) * self._label_weight
        hess = jnp.ones_like(grad) if self.weight is None else self.weight
        return grad[None], hess[None]

    def boost_from_score(self, class_id: int = 0) -> float:
        return _weighted_percentile(self._label_np, self._label_weight_np, 0.5)

    def _renew_weights(self) -> Optional[np.ndarray]:
        return self._label_weight_np


class RegressionGamma(RegressionPoisson):
    """Gamma loss (reference: RegressionGammaLoss, regression_objective.hpp:682)."""

    name = "gamma"

    def get_gradients(self, score, rng=None):
        exp_neg = jnp.exp(-score[0])
        grad = 1.0 - self.label * exp_neg
        hess = self.label * exp_neg
        g, h = self._apply_weight(grad, hess)
        return g[None], h[None]


class RegressionTweedie(RegressionPoisson):
    """Tweedie loss (reference: RegressionTweedieLoss, regression_objective.hpp:718)."""

    name = "tweedie"

    def __init__(self, config: Config):
        super().__init__(config)
        self.rho = float(config.tweedie_variance_power)

    def get_gradients(self, score, rng=None):
        s = score[0]
        exp1 = jnp.exp((1.0 - self.rho) * s)
        exp2 = jnp.exp((2.0 - self.rho) * s)
        grad = -self.label * exp1 + exp2
        hess = -self.label * (1.0 - self.rho) * exp1 + (2.0 - self.rho) * exp2
        g, h = self._apply_weight(grad, hess)
        return g[None], h[None]


# =============================================================== binary family
class BinaryLogloss(ObjectiveFunction):
    """Binary log-loss (reference: BinaryLogloss, binary_objective.hpp:20)."""

    name = "binary"

    def __init__(self, config: Config, is_pos=None):
        super().__init__(config)
        self.sigmoid = float(config.sigmoid)
        if self.sigmoid <= 0:
            raise ValueError("sigmoid parameter must be > 0")
        self.is_unbalance = bool(config.is_unbalance)
        self.scale_pos_weight = float(config.scale_pos_weight)
        self._is_pos = is_pos if is_pos is not None else (lambda y: y > 0)
        self.need_train = True

    def init(self, label, weight, query_boundaries=None, position=None):
        super().init(label, weight)
        pos = self._is_pos(self._label_np)
        cnt_pos = int(pos.sum())
        cnt_neg = self.num_data - cnt_pos
        self.num_pos_data = cnt_pos
        self.need_train = cnt_pos > 0 and cnt_neg > 0
        label_weights = [1.0, 1.0]
        if self.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                label_weights[0] = cnt_pos / cnt_neg
            else:
                label_weights[1] = cnt_neg / cnt_pos
        label_weights[1] *= self.scale_pos_weight
        self._label_weights = label_weights
        self._pos_np = pos
        pos_dev = jnp.asarray(pos)
        self._y = jnp.where(pos_dev, 1.0, -1.0)  # label in {-1, +1}
        self._lw = jnp.where(pos_dev, label_weights[1], label_weights[0])

    def per_row_device_arrays(self):
        return super().per_row_device_arrays() + [
            (self, "_y", 0),
            (self, "_lw", 0),
        ]

    def get_gradients(self, score, rng=None):
        if not self.need_train:
            z = jnp.zeros_like(score)
            return z, z
        s = score[0]
        sig = self.sigmoid
        response = -self._y * sig / (1.0 + jnp.exp(self._y * sig * s))
        abs_resp = jnp.abs(response)
        grad = response * self._lw
        hess = abs_resp * (sig - abs_resp) * self._lw
        g, h = self._apply_weight(grad, hess)
        return g[None], h[None]

    def boost_from_score(self, class_id: int = 0) -> float:
        if self._weight_np is None:
            pavg = float(self._pos_np.mean())
        else:
            pavg = float(np.average(self._pos_np.astype(np.float64), weights=self._weight_np))
        pavg = min(max(pavg, _EPS), 1.0 - _EPS)
        return math.log(pavg / (1.0 - pavg)) / self.sigmoid

    def class_need_train(self, class_id: int) -> bool:
        return self.need_train

    def convert_output(self, raw):
        return 1.0 / (1.0 + jnp.exp(-self.sigmoid * raw))

    def to_string(self):
        return f"{self.name} sigmoid:{self.sigmoid:g}"


# =========================================================== multiclass family
class MulticlassSoftmax(ObjectiveFunction):
    """Softmax multiclass (reference: MulticlassSoftmax, multiclass_objective.hpp:24)."""

    name = "multiclass"

    def __init__(self, config: Config):
        super().__init__(config)
        self.num_class = int(config.num_class)
        if self.num_class < 2:
            raise ValueError("multiclass objective requires num_class >= 2")
        # rescales the redundant K-output parameterization (Friedman GBDT paper)
        self.factor = self.num_class / (self.num_class - 1.0)

    def init(self, label, weight, query_boundaries=None, position=None):
        super().init(label, weight)
        li = self._label_np.astype(np.int64)
        if li.min() < 0 or li.max() >= self.num_class:
            raise ValueError(f"label must be in [0, {self.num_class})")
        if self._weight_np is None:
            probs = np.bincount(li, minlength=self.num_class).astype(np.float64)
            probs /= self.num_data
        else:
            probs = np.zeros(self.num_class)
            np.add.at(probs, li, self._weight_np)
            probs /= self._weight_np.sum()
        self.class_init_probs = probs
        label_int = jnp.asarray(li, dtype=jnp.int32)
        self._onehot = jax.nn.one_hot(label_int, self.num_class, dtype=jnp.float32).T  # [K, N]

    def per_row_device_arrays(self):
        return super().per_row_device_arrays() + [(self, "_onehot", 1)]

    def get_gradients(self, score, rng=None):
        p = jax.nn.softmax(score, axis=0)  # [K, N]
        grad = p - self._onehot
        hess = self.factor * p * (1.0 - p)
        if self.weight is not None:
            grad = grad * self.weight[None]
            hess = hess * self.weight[None]
        return grad, hess

    def boost_from_score(self, class_id: int = 0) -> float:
        return math.log(max(_EPS, self.class_init_probs[class_id]))

    def class_need_train(self, class_id: int) -> bool:
        p = self.class_init_probs[class_id]
        return _EPS < abs(p) < 1.0 - _EPS

    def convert_output(self, raw):
        """raw: [..., K] -> softmax over the last axis."""
        return jax.nn.softmax(raw, axis=-1)

    def to_string(self):
        return f"{self.name} num_class:{self.num_class}"


class MulticlassOVA(ObjectiveFunction):
    """One-vs-all multiclass (reference: MulticlassOVA, multiclass_objective.hpp:178)."""

    name = "multiclassova"

    def __init__(self, config: Config):
        super().__init__(config)
        self.num_class = int(config.num_class)
        self.sigmoid = float(config.sigmoid)
        self._binary = [BinaryLogloss(config) for _ in range(self.num_class)]

    def init(self, label, weight, query_boundaries=None, position=None):
        super().init(label, weight)
        for k, b in enumerate(self._binary):
            b._is_pos = (lambda kk: (lambda y: y == kk))(k)
            b.init(label, weight)

    def per_row_device_arrays(self):
        out = super().per_row_device_arrays()
        for b in self._binary:
            out.extend(b.per_row_device_arrays())
        return out

    def get_gradients(self, score, rng=None):
        gs, hs = [], []
        for k, b in enumerate(self._binary):
            g, h = b.get_gradients(score[k][None])
            gs.append(g[0])
            hs.append(h[0])
        return jnp.stack(gs), jnp.stack(hs)

    def boost_from_score(self, class_id: int = 0) -> float:
        return self._binary[class_id].boost_from_score(0)

    def class_need_train(self, class_id: int) -> bool:
        return self._binary[class_id].need_train

    def convert_output(self, raw):
        return 1.0 / (1.0 + jnp.exp(-self.sigmoid * raw))

    def to_string(self):
        return f"{self.name} num_class:{self.num_class} sigmoid:{self.sigmoid:g}"


# ============================================================ xentropy family
class CrossEntropy(ObjectiveFunction):
    """Cross-entropy with labels in [0,1] (reference: xentropy_objective.hpp:38)."""

    name = "cross_entropy"

    def init(self, label, weight, query_boundaries=None, position=None):
        super().init(label, weight)
        if self._label_np.min() < 0 or self._label_np.max() > 1:
            raise ValueError(f"[{self.name}]: labels must be in [0, 1]")
        if self._weight_np is not None:
            if self._weight_np.min() < 0:
                raise ValueError(f"[{self.name}]: at least one weight is negative")
            if self._weight_np.sum() == 0:
                raise ValueError(f"[{self.name}]: sum of weights is zero")

    def get_gradients(self, score, rng=None):
        s = score[0]
        z = jax.nn.sigmoid(s)
        grad = z - self.label
        hess = z * (1.0 - z)
        g, h = self._apply_weight(grad, hess)
        return g[None], h[None]

    def boost_from_score(self, class_id: int = 0) -> float:
        if self._weight_np is None:
            pavg = float(self._label_np.mean())
        else:
            pavg = float(np.average(self._label_np, weights=self._weight_np))
        pavg = min(max(pavg, _EPS), 1.0 - _EPS)
        return math.log(pavg / (1.0 - pavg))

    def convert_output(self, raw):
        return jax.nn.sigmoid(raw)


class CrossEntropyLambda(ObjectiveFunction):
    """Weighted cross-entropy, alternative parameterization
    (reference: CrossEntropyLambda, xentropy_objective.hpp:180)."""

    name = "cross_entropy_lambda"

    def init(self, label, weight, query_boundaries=None, position=None):
        super().init(label, weight)
        if self._label_np.min() < 0 or self._label_np.max() > 1:
            raise ValueError(f"[{self.name}]: labels must be in [0, 1]")
        if self._weight_np is not None and self._weight_np.min() <= 0:
            raise ValueError(f"[{self.name}]: at least one weight is non-positive")

    def get_gradients(self, score, rng=None):
        s = score[0]
        if self.weight is None:
            z = jax.nn.sigmoid(s)
            grad = z - self.label
            hess = z * (1.0 - z)
            return grad[None], hess[None]
        w = self.weight
        y = self.label
        epf = jnp.exp(s)
        hhat = jnp.log1p(epf)
        z = 1.0 - jnp.exp(-w * hhat)
        enf = jnp.exp(-s)
        grad = (1.0 - y / z) * w / (1.0 + enf)
        c = 1.0 / (1.0 - z)
        d = 1.0 + epf
        a = w * epf / (d * d)
        d2 = c - 1.0
        b = (c / (d2 * d2)) * (1.0 + w * epf - c)
        hess = a * (1.0 + y * b)
        return grad[None], hess[None]

    def boost_from_score(self, class_id: int = 0) -> float:
        if self._weight_np is None:
            pavg = float(self._label_np.mean())
        else:
            pavg = float(np.average(self._label_np, weights=self._weight_np))
        pavg = min(max(pavg, _EPS), 1.0 - _EPS)
        return math.log(pavg / (1.0 - pavg))

    def convert_output(self, raw):
        # output is the normalized exponential parameter, not a probability
        return jnp.log1p(jnp.exp(raw))


# ============================================================= ranking family
def _default_label_gain(max_label: int = 31) -> np.ndarray:
    return (2.0 ** np.arange(max_label + 1)) - 1.0


def _pad_queries(query_boundaries: np.ndarray) -> Tuple[np.ndarray, int]:
    """Query sizes -> (per-query row index matrix [num_q, Q], Q) with -1 pad."""
    sizes = np.diff(query_boundaries)
    q = int(sizes.max()) if len(sizes) else 1
    # round up to a power of two to limit recompiles across datasets
    q = max(8, 1 << (q - 1).bit_length())
    idx = np.full((len(sizes), q), -1, dtype=np.int32)
    for i, (b, e) in enumerate(zip(query_boundaries[:-1], query_boundaries[1:])):
        idx[i, : e - b] = np.arange(b, e, dtype=np.int32)
    return idx, q


class RankingObjective(ObjectiveFunction):
    """Base for per-query ranking objectives (reference: rank_objective.hpp:30)."""

    need_query = True

    def init(self, label, weight, query_boundaries=None, position=None):
        super().init(label, weight)
        if query_boundaries is None:
            raise ValueError(f"[{self.name}]: query data (group) is required")
        self.query_boundaries = np.asarray(query_boundaries, dtype=np.int64)
        self.num_queries = len(self.query_boundaries) - 1
        idx, self.q_pad = _pad_queries(self.query_boundaries)
        self._qidx = jnp.asarray(idx)  # [num_q, Q] row ids, -1 = pad
        self._qvalid = jnp.asarray(idx >= 0)
        lab = np.zeros(idx.shape, dtype=np.float32)
        lab[idx >= 0] = self._label_np[idx[idx >= 0]]
        self._qlabel = jnp.asarray(lab)

    def _scatter_back(self, per_query: jnp.ndarray) -> jnp.ndarray:
        """[num_q, Q] padded per-row values -> [N] row vector."""
        idx = self._qidx.reshape(-1)
        vals = per_query.reshape(-1)
        safe = jnp.where(idx >= 0, idx, 0)
        return jnp.zeros((self.num_data,), jnp.float32).at[safe].add(
            jnp.where(idx >= 0, vals, 0.0)
        )

    def _gather_scores(self, score: jnp.ndarray) -> jnp.ndarray:
        safe = jnp.where(self._qidx >= 0, self._qidx, 0)
        s = score[0][safe]
        return jnp.where(self._qvalid, s, -jnp.inf)


class LambdarankNDCG(RankingObjective):
    """Pairwise LambdaRank with NDCG (reference: LambdarankNDCG,
    rank_objective.hpp:137; per-query math :180-272).

    The per-query OpenMP loop + stable sort becomes a vmapped function over
    padded [num_q, Q] segments; the O(Q^2) pair loop becomes dense [Q, Q]
    masked matrices (chunked over queries to bound memory).  The sigmoid
    lookup table (rank_objective.hpp:287) is replaced by direct computation —
    on TPU the exp is cheaper than the gather.
    """

    name = "lambdarank"

    def __init__(self, config: Config):
        super().__init__(config)
        self.sigmoid = float(config.sigmoid)
        if self.sigmoid <= 0:
            raise ValueError("sigmoid parameter must be > 0")
        self.norm = bool(config.lambdarank_norm)
        self.truncation_level = int(config.lambdarank_truncation_level)
        lg = config.label_gain
        self.label_gain = np.asarray(lg, dtype=np.float64) if lg else _default_label_gain()

    def init(self, label, weight, query_boundaries=None, position=None):
        super().init(label, weight, query_boundaries)
        if self._label_np.max() >= len(self.label_gain):
            raise ValueError("label exceeds label_gain size")
        # position debias (reference: positions_/pos_biases_,
        # rank_objective.hpp:44-56; Newton update :302-341): scores are
        # adjusted by a learned per-position bias before the lambda
        # computation, and the biases update each iteration from the
        # accumulated lambdas/hessians per position.
        self._pos_inv = None
        if position is not None:
            pos = np.asarray(position)
            uniq, inv = np.unique(pos, return_inverse=True)
            self.position_ids = uniq
            self.num_position_ids = len(uniq)
            self._pos_inv = jnp.asarray(inv.astype(np.int32))
            self._pos_counts = jnp.asarray(
                np.bincount(inv, minlength=len(uniq)).astype(np.float32)
            )
            self.pos_biases = jnp.zeros((len(uniq),), jnp.float32)
            self._pos_reg = float(
                self.config.lambdarank_position_bias_regularization
            )
            self._pos_lr = float(self.config.learning_rate)
        # per-query inverse max DCG at truncation level (host, setup-time)
        inv = np.zeros(self.num_queries)
        disc = 1.0 / np.log2(np.arange(2, self.q_pad + 2))
        for i in range(self.num_queries):
            b, e = self.query_boundaries[i], self.query_boundaries[i + 1]
            ls = np.sort(self._label_np[b:e])[::-1][: self.truncation_level]
            m = (self.label_gain[ls.astype(np.int64)] * disc[: len(ls)]).sum()
            inv[i] = 1.0 / m if m > 0 else 0.0
        self._inv_max_dcg = jnp.asarray(inv, dtype=jnp.float32)
        self._gain_table = jnp.asarray(self.label_gain, dtype=jnp.float32)
        self._discount = jnp.asarray(disc, dtype=jnp.float32)

    def _update_position_bias(self, grad_row, hess_row) -> None:
        """Newton-Raphson step on the per-position bias factors
        (UpdatePositionBiasFactors, rank_objective.hpp:302)."""
        p = self.num_position_ids
        fd = -jax.ops.segment_sum(grad_row, self._pos_inv, num_segments=p)
        sd = -jax.ops.segment_sum(hess_row, self._pos_inv, num_segments=p)
        fd = fd - self.pos_biases * self._pos_reg * self._pos_counts
        sd = sd - self._pos_reg * self._pos_counts
        self.pos_biases = self.pos_biases + self._pos_lr * fd / (
            jnp.abs(sd) + 0.001
        )

    def _one_query(self, s, lab, valid, inv_max_dcg):
        """Lambdas/hessians for one padded query. s/lab/valid: [Q]."""
        q = s.shape[0]
        order = jnp.argsort(-jnp.where(valid, s, -jnp.inf), stable=True)
        ss = s[order]
        ll = lab[order]
        vv = valid[order]
        gain = self._gain_table[jnp.clip(ll.astype(jnp.int32), 0, len(self.label_gain) - 1)]
        disc = self._discount[:q] * vv
        best = jnp.max(jnp.where(vv, ss, -jnp.inf))
        worst = jnp.min(jnp.where(vv, ss, jnp.inf))

        i_idx = jnp.arange(q)
        pair_valid = (
            vv[:, None]
            & vv[None, :]
            & (i_idx[:, None] < i_idx[None, :])
            & (i_idx[:, None] < self.truncation_level)
            & (ll[:, None] != ll[None, :])
        )
        hi_is_i = ll[:, None] > ll[None, :]
        dcg_gap = jnp.abs(gain[:, None] - gain[None, :])
        paired_disc = jnp.abs(disc[:, None] - disc[None, :])
        delta_ndcg = dcg_gap * paired_disc * inv_max_dcg
        s_hi = jnp.where(hi_is_i, ss[:, None], ss[None, :])
        s_lo = jnp.where(hi_is_i, ss[None, :], ss[:, None])
        delta_score = s_hi - s_lo
        if self.norm:
            delta_ndcg = jnp.where(
                best != worst, delta_ndcg / (0.01 + jnp.abs(delta_score)), delta_ndcg
            )
        sig = self.sigmoid
        p_sig = 1.0 / (1.0 + jnp.exp(sig * delta_score))
        p_hess = p_sig * (1.0 - p_sig) * sig * sig * delta_ndcg
        p_lambda = -sig * delta_ndcg * p_sig  # contribution with the 'high' sign
        p_lambda = jnp.where(pair_valid, p_lambda, 0.0)
        p_hess = jnp.where(pair_valid, p_hess, 0.0)

        # lambdas[high] += p_lambda; lambdas[low] -= p_lambda
        contrib_i = jnp.where(hi_is_i, p_lambda, -p_lambda)
        lam_sorted = contrib_i.sum(axis=1) - contrib_i.sum(axis=0)
        hess_sorted = p_hess.sum(axis=1) + p_hess.sum(axis=0)
        sum_lambdas = -2.0 * p_lambda.sum()
        if self.norm:
            norm_factor = jnp.where(
                sum_lambdas > 0,
                jnp.log2(1.0 + sum_lambdas) / jnp.maximum(sum_lambdas, _EPS),
                1.0,
            )
            lam_sorted = lam_sorted * norm_factor
            hess_sorted = hess_sorted * norm_factor
        inv_order = jnp.argsort(order)
        return lam_sorted[inv_order], hess_sorted[inv_order]

    def get_gradients(self, score, rng=None):
        if self._pos_inv is not None:
            # bias-adjusted scores feed the lambda computation
            # (rank_objective.hpp:68-73)
            score = (score[0] + self.pos_biases[self._pos_inv])[None]
        qs = self._gather_scores(score)  # [num_q, Q]
        qq = self.q_pad
        # chunk queries so the [chunk, Q, Q] intermediate stays ~16M elements
        chunk = max(1, min(self.num_queries, (1 << 24) // max(1, qq * qq)))
        nq = qs.shape[0]
        pad_q = (-nq) % chunk

        def padq(a, fill):
            return jnp.pad(a, ((0, pad_q),) + ((0, 0),) * (a.ndim - 1), constant_values=fill)

        qs_c = padq(qs, -jnp.inf).reshape(-1, chunk, qq)
        lab_c = padq(self._qlabel, 0.0).reshape(-1, chunk, qq)
        val_c = padq(self._qvalid, False).reshape(-1, chunk, qq)
        inv_c = padq(self._inv_max_dcg, 0.0).reshape(-1, chunk)

        f = jax.vmap(self._one_query)

        def body(_, xs):
            s, l, v, im = xs
            return None, f(s, l, v, im)

        _, (lam, hes) = jax.lax.scan(body, None, (qs_c, lab_c, val_c, inv_c))
        lam = lam.reshape(-1, qq)[:nq]
        hes = hes.reshape(-1, qq)[:nq]
        grad = self._scatter_back(lam)
        hess = self._scatter_back(hes)
        if self.weight is not None:
            grad = grad * self.weight
            hess = hess * self.weight
        if self._pos_inv is not None:
            self._update_position_bias(grad, hess)
        return grad[None], hess[None]

    def to_string(self):
        return self.name


class RankXENDCG(RankingObjective):
    """Listwise XE-NDCG (reference: RankXENDCG, rank_objective.hpp:386;
    arxiv.org/abs/1911.09798)."""

    name = "rank_xendcg"

    def __init__(self, config: Config):
        super().__init__(config)
        self.seed = int(config.objective_seed)

    def _one_query(self, s, lab, valid, gamma):
        rho = jax.nn.softmax(jnp.where(valid, s, -jnp.inf))
        rho = jnp.where(valid, rho, 0.0)
        params = jnp.where(valid, 2.0 ** jnp.floor(lab) - gamma, 0.0)
        inv_denominator = 1.0 / jnp.maximum(_EPS, params.sum())
        # first-order terms
        term1 = jnp.where(valid, -params * inv_denominator + rho, 0.0)
        lambdas = term1
        params1 = jnp.where(valid, term1 / jnp.maximum(1.0 - rho, _EPS), 0.0)
        sum_l1 = params1.sum()
        # second-order terms
        term2 = jnp.where(valid, rho * (sum_l1 - params1), 0.0)
        lambdas = lambdas + term2
        params2 = jnp.where(valid, term2 / jnp.maximum(1.0 - rho, _EPS), 0.0)
        sum_l2 = params2.sum()
        lambdas = lambdas + jnp.where(valid, rho * (sum_l2 - params2), 0.0)
        hessians = jnp.where(valid, rho * (1.0 - rho), 0.0)
        keep = valid.sum() > 1  # skip groups with a single item
        return jnp.where(keep & valid, lambdas, 0.0), jnp.where(keep & valid, hessians, 0.0)

    def get_gradients(self, score, rng=None):
        if rng is None:
            rng = jax.random.PRNGKey(self.seed)
        qs = self._gather_scores(score)
        gamma = jax.random.uniform(rng, (self.num_queries, self.q_pad))
        lam, hes = jax.vmap(self._one_query)(qs, self._qlabel, self._qvalid, gamma)
        grad = self._scatter_back(lam)
        hess = self._scatter_back(hes)
        if self.weight is not None:
            grad = grad * self.weight
            hess = hess * self.weight
        return grad[None], hess[None]

    def to_string(self):
        return self.name


# ================================================================== factory
_OBJECTIVES = {
    "regression": RegressionL2,
    "regression_l1": RegressionL1,
    "huber": RegressionHuber,
    "fair": RegressionFair,
    "poisson": RegressionPoisson,
    "quantile": RegressionQuantile,
    "mape": RegressionMAPE,
    "gamma": RegressionGamma,
    "tweedie": RegressionTweedie,
    "binary": BinaryLogloss,
    "multiclass": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
    "cross_entropy": CrossEntropy,
    "cross_entropy_lambda": CrossEntropyLambda,
    "lambdarank": LambdarankNDCG,
    "rank_xendcg": RankXENDCG,
}


def create_objective(config: Config) -> Optional[ObjectiveFunction]:
    """Factory (reference: ObjectiveFunction::CreateObjectiveFunction,
    src/objective/objective_function.cpp:22)."""
    name = config.objective
    if name in ("none", "null", "custom", "na", ""):
        return None
    if name not in _OBJECTIVES:
        raise ValueError(f"unknown objective: {name!r}")
    return _OBJECTIVES[name](config)
