"""Collective-cost model for the data-parallel grower (VERDICT r2 #6).

Measures step time vs mesh size (1/2/4/8 virtual CPU devices) at
Higgs-shaped histograms and computes the psum BYTES each split exchanges,
then projects v5e-16 behavior from published ICI numbers.  Run:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/collective_model.py [rows]

Writes a markdown table to stdout (paste into BENCH_NOTES.md).
"""

import os
import sys
import time

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")
import numpy as np


def main():
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    f, B, L = 28, 256, 255
    rng = np.random.default_rng(0)
    X = rng.normal(size=(rows, f)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float64)

    import lightgbm_tpu as lgb

    # psum volume per split (the analytical part of the model):
    # data-parallel exchanges the smaller child's full [F, B, 3] f32
    # histogram; with Higgs shapes that is F*B*3*4 bytes.
    hist_bytes = f * B * 3 * 4
    print(f"per-split psum payload: [F={f}, B={B}, 3] f32 = {hist_bytes/2**20:.2f} MiB")
    print(f"per-tree ({L - 1} splits): {(L - 1) * hist_bytes / 2**20:.1f} MiB\n")
    print("| mesh | iters/s | step ms | vs 1-dev |")
    print("|---|---|---|---|")

    base = None
    for ndev in (1, 2, 4, 8):
        os.environ["LGBM_TPU_FORCE_NDEV"] = str(ndev)
        params = {
            "objective": "binary",
            "num_leaves": L,
            "max_bin": 255,
            "min_data_in_leaf": 100,
            "verbosity": -1,
            "metric": "none",
            "tree_learner": "data" if ndev > 1 else "serial",
        }
        d = lgb.Dataset(X, y, params=params)
        b = lgb.Booster(params, d)
        if ndev > 1 and b._mesh is not None:
            assert len(b._mesh.devices.ravel()) >= 1
        b.update()  # compile + warmup
        jax.block_until_ready(b._score)
        t0 = time.perf_counter()
        iters = 3
        for _ in range(iters):
            b.update()
        jax.block_until_ready(b._score)
        dt = (time.perf_counter() - t0) / iters
        if base is None:
            base = dt
        print(
            f"| {ndev} | {1/dt:.3f} | {dt*1e3:.0f} | {base/dt:.2f}x |",
            flush=True,
        )

    print(
        "\nProjection: on v5e ICI (~100 GB/s/link bidirectional ring), the "
        f"{hist_bytes/2**20:.2f} MiB all-reduce costs ~"
        f"{2 * hist_bytes / 100e9 * 1e6:.0f} us/split -> "
        f"{(L-1) * 2 * hist_bytes / 100e9 * 1e3:.1f} ms/tree at any mesh "
        "size (ring all-reduce is bandwidth-bound per chip); "
        "DCN (multi-host, ~25 GB/s) multiplies that by ~4."
    )


if __name__ == "__main__":
    main()
