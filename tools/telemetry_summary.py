"""Roll up a telemetry JSONL stream (telemetry_out=...) into one summary.

Usage:
    python tools/telemetry_summary.py events.jsonl [more.jsonl ...]
    python -m lightgbm_tpu ... telemetry=true telemetry_out=events.jsonl

Prints one human block per file: iteration count, wall/phase means with
p50/p99 percentiles, compile deltas, collective-byte totals (analytic and
measured), cost/memory gauge columns from the train_summary event, plus
predict-event rollups when present.  Exits non-zero on empty or unparseable
input so CI smoke checks can gate on it (tools/run_tests.sh runs a
3-iteration train through this).
"""

from __future__ import annotations

import json
import sys
from collections import defaultdict
from typing import Any, Dict, List


def _percentile(vals: List[float], q: float) -> float:
    """Nearest-rank percentile (no numpy dependency for offline use)."""
    if not vals:
        return 0.0
    s = sorted(vals)
    idx = min(len(s) - 1, max(0, int(round(q / 100.0 * (len(s) - 1)))))
    return s[idx]


def load_events(path: str) -> List[Dict[str, Any]]:
    events = []
    with open(path) as fp:
        for lineno, line in enumerate(fp, 1):
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError as e:
                raise SystemExit(f"{path}:{lineno}: bad JSONL line: {e}")
    return events


def summarize(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    iters = [e for e in events if e.get("event") == "iteration"]
    preds = [e for e in events if e.get("event") == "predict"]
    chunks = [e for e in events if e.get("event") == "predict_chunk"]
    out: Dict[str, Any] = {"events": len(events)}
    if iters:
        phase_tot: Dict[str, float] = defaultdict(float)
        phase_vals: Dict[str, List[float]] = defaultdict(list)
        for e in iters:
            for k, v in (e.get("phases") or {}).items():
                phase_tot[k] += float(v)
                phase_vals[k].append(float(v))
        n = len(iters)
        out["iterations"] = n
        walls = [float(e.get("wall_ms", 0.0)) for e in iters]
        out["wall_ms_mean"] = round(sum(walls) / n, 2)
        out["wall_ms_p50"] = round(_percentile(walls, 50), 2)
        out["wall_ms_p99"] = round(_percentile(walls, 99), 2)
        out["phases_ms_mean"] = {
            k: round(v / n, 2) for k, v in sorted(phase_tot.items())
        }
        out["phases_ms_p50"] = {
            k: round(_percentile(v, 50), 2)
            for k, v in sorted(phase_vals.items())
        }
        out["phases_ms_p99"] = {
            k: round(_percentile(v, 99), 2)
            for k, v in sorted(phase_vals.items())
        }
        out["compiles_total"] = sum(
            int(e.get("compiles_delta", 0)) for e in iters
        )
        out["recompiles_after_first"] = sum(
            int(e.get("compiles_delta", 0)) for e in iters[1:]
        )
        out["splits_total"] = sum(int(e.get("splits", 0)) for e in iters)
        colls = [e["collective"] for e in iters if "collective" in e]
        if colls:
            out["collective_bytes_total"] = {
                k: round(sum(float(c[k]) for c in colls))
                for k in ("hist_bytes", "count_bytes", "ring_bytes_per_device")
            }
        meas = [
            e["collective_measured"]
            for e in iters
            if "collective_measured" in e
        ]
        if meas:
            out["collective_measured_total"] = {
                k: round(sum(float(m.get(k, 0.0)) for m in meas), 2)
                for k in ("bytes", "psum_bytes", "calls", "wall_ms")
            }
        evals = [e["eval"] for e in iters if "eval" in e]
        if evals:
            out["final_eval"] = evals[-1]
    summaries = [e for e in events if e.get("event") == "train_summary"]
    if summaries:
        gauges = summaries[-1].get("gauges") or {}
        cost = {
            k: v
            for k, v in sorted(gauges.items())
            if k.startswith(("cost/", "memory/"))
        }
        if cost:
            out["cost_memory_gauges"] = cost
        straggler = {
            k: round(float(v), 3)
            for k, v in sorted(gauges.items())
            if k.startswith("straggler/")
        }
        if straggler:
            out["straggler"] = straggler
    rollups = [e for e in events if e.get("event") == "host_rollup"]
    if rollups:
        out["hosts"] = rollups[-1].get("hosts")
    if preds:
        out["predict_runs"] = len(preds)
        out["predict_rows"] = sum(int(e.get("rows", 0)) for e in preds)
        out["predict_chunks"] = len(chunks) or sum(
            int(e.get("chunks", 0)) for e in preds
        )
        out["predict_compiles"] = sum(int(e.get("compiles", 0)) for e in preds)
    return out


def main(argv: List[str]) -> int:
    if not argv:
        print(__doc__)
        return 2
    rc = 0
    for path in argv:
        events = load_events(path)
        if not events:
            print(f"{path}: no events", file=sys.stderr)
            rc = 1
            continue
        print(f"== {path}")
        for k, v in summarize(events).items():
            print(f"  {k}: {json.dumps(v)}")
    return rc


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
