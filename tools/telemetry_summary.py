"""Roll up a telemetry JSONL stream (telemetry_out=...) into one summary.

Usage:
    python tools/telemetry_summary.py events.jsonl [more.jsonl ...]
    python tools/telemetry_summary.py --flight flight_*.json
    python -m lightgbm_tpu ... telemetry=true telemetry_out=events.jsonl

Prints one human block per file: iteration count, wall/phase means with
p50/p99 percentiles, compile deltas, collective-byte totals (analytic and
measured), cost/memory gauge columns from the train_summary event,
watchdog alert rollups, plus predict-event rollups (with per-phase
p50/p99) when present.  ``--flight`` switches to pretty-printing flight
recorder fault dumps instead.  Exits non-zero on empty or unparseable
input so CI smoke checks can gate on it (tools/run_tests.sh runs a
3-iteration train through this).
"""

from __future__ import annotations

import json
import sys
from collections import defaultdict
from typing import Any, Dict, List


def _percentile(vals: List[float], q: float) -> float:
    """Nearest-rank percentile (no numpy dependency for offline use)."""
    if not vals:
        return 0.0
    s = sorted(vals)
    idx = min(len(s) - 1, max(0, int(round(q / 100.0 * (len(s) - 1)))))
    return s[idx]


def load_events(path: str) -> List[Dict[str, Any]]:
    events = []
    with open(path) as fp:
        for lineno, line in enumerate(fp, 1):
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError as e:
                raise SystemExit(f"{path}:{lineno}: bad JSONL line: {e}")
    return events


def summarize(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    iters = [e for e in events if e.get("event") == "iteration"]
    preds = [e for e in events if e.get("event") == "predict"]
    chunks = [e for e in events if e.get("event") == "predict_chunk"]
    out: Dict[str, Any] = {"events": len(events)}
    if iters:
        phase_tot: Dict[str, float] = defaultdict(float)
        phase_vals: Dict[str, List[float]] = defaultdict(list)
        for e in iters:
            for k, v in (e.get("phases") or {}).items():
                phase_tot[k] += float(v)
                phase_vals[k].append(float(v))
        n = len(iters)
        out["iterations"] = n
        walls = [float(e.get("wall_ms", 0.0)) for e in iters]
        out["wall_ms_mean"] = round(sum(walls) / n, 2)
        out["wall_ms_p50"] = round(_percentile(walls, 50), 2)
        out["wall_ms_p99"] = round(_percentile(walls, 99), 2)
        out["phases_ms_mean"] = {
            k: round(v / n, 2) for k, v in sorted(phase_tot.items())
        }
        out["phases_ms_p50"] = {
            k: round(_percentile(v, 50), 2)
            for k, v in sorted(phase_vals.items())
        }
        out["phases_ms_p99"] = {
            k: round(_percentile(v, 99), 2)
            for k, v in sorted(phase_vals.items())
        }
        out["compiles_total"] = sum(
            int(e.get("compiles_delta", 0)) for e in iters
        )
        out["recompiles_after_first"] = sum(
            int(e.get("compiles_delta", 0)) for e in iters[1:]
        )
        out["splits_total"] = sum(int(e.get("splits", 0)) for e in iters)
        # device-resident launches replay one synthetic iteration event
        # per consumed step (from_launch=true), so the shape above holds
        # for both serial and launched runs; surface the split explicitly
        from_launch = sum(1 for e in iters if e.get("from_launch"))
        if from_launch:
            out["iterations_from_launch"] = from_launch
    launches = [e for e in events if e.get("event") == "launch"]
    if launches:
        out["launches"] = len(launches)
        out["steps_per_launch"] = launches[-1].get("steps_per_launch")
        colls = [e["collective"] for e in iters if "collective" in e]
        if colls:
            out["collective_bytes_total"] = {
                k: round(sum(float(c[k]) for c in colls))
                for k in ("hist_bytes", "count_bytes", "ring_bytes_per_device")
            }
        meas = [
            e["collective_measured"]
            for e in iters
            if "collective_measured" in e
        ]
        if meas:
            out["collective_measured_total"] = {
                k: round(sum(float(m.get(k, 0.0)) for m in meas), 2)
                for k in ("bytes", "psum_bytes", "calls", "wall_ms")
            }
        evals = [e["eval"] for e in iters if "eval" in e]
        if evals:
            out["final_eval"] = evals[-1]
    summaries = [e for e in events if e.get("event") == "train_summary"]
    if summaries:
        gauges = summaries[-1].get("gauges") or {}
        cost = {
            k: v
            for k, v in sorted(gauges.items())
            if k.startswith(("cost/", "memory/"))
        }
        if cost:
            out["cost_memory_gauges"] = cost
        straggler = {
            k: round(float(v), 3)
            for k, v in sorted(gauges.items())
            if k.startswith("straggler/")
        }
        if straggler:
            out["straggler"] = straggler
    rollups = [e for e in events if e.get("event") == "host_rollup"]
    if rollups:
        out["hosts"] = rollups[-1].get("hosts")
    alerts = [e for e in events if e.get("event") == "alert"]
    if alerts:
        by_rule: Dict[str, int] = defaultdict(int)
        worst = "warn"
        for a in alerts:
            by_rule[str(a.get("rule", "unknown"))] += 1
            if a.get("severity") == "critical":
                worst = "critical"
        out["alerts_total"] = len(alerts)
        out["alerts_by_rule"] = dict(sorted(by_rule.items()))
        out["alerts_worst_severity"] = worst
        last = alerts[-1]
        out["last_alert"] = {
            k: last.get(k) for k in ("iter", "rule", "severity", "message")
        }
    if preds:
        out["predict_runs"] = len(preds)
        out["predict_rows"] = sum(int(e.get("rows", 0)) for e in preds)
        out["predict_chunks"] = len(chunks) or sum(
            int(e.get("chunks", 0)) for e in preds
        )
        out["predict_compiles"] = sum(int(e.get("compiles", 0)) for e in preds)
        pvals: Dict[str, List[float]] = defaultdict(list)
        for e in preds:
            for k, v in (e.get("phases") or {}).items():
                pvals[k].append(float(v))
        if pvals:
            out["predict_phases_ms_p50"] = {
                k: round(_percentile(v, 50), 2)
                for k, v in sorted(pvals.items())
            }
            out["predict_phases_ms_p99"] = {
                k: round(_percentile(v, 99), 2)
                for k, v in sorted(pvals.items())
            }
    return out


def print_flight(path: str) -> int:
    """Pretty-print a flight recorder fault dump (flight_*.json)."""
    with open(path) as fp:
        try:
            doc = json.load(fp)
        except json.JSONDecodeError as e:
            raise SystemExit(f"{path}: bad flight dump JSON: {e}")
    print(f"== flight dump {path}")
    print(f"  schema: {doc.get('schema')}")
    print(f"  reason: {doc.get('reason')}")
    print(
        f"  dumped_at_unix: {doc.get('dumped_at_unix')}  "
        f"pid: {doc.get('pid')}"
    )
    if doc.get("run_info"):
        print(f"  run_info: {json.dumps(doc['run_info'])}")
    if doc.get("last_checkpoint"):
        print(f"  last_checkpoint: {doc['last_checkpoint']}")
    events = doc.get("events") or []
    by_kind: Dict[str, int] = defaultdict(int)
    for e in events:
        by_kind[str(e.get("event", "?"))] += 1
    print(
        f"  ring: {len(events)}/{doc.get('ring_capacity')} events "
        f"{json.dumps(dict(sorted(by_kind.items())))}"
    )
    iters = [e for e in events if e.get("event") == "iteration"]
    if iters:
        lo, hi = iters[0].get("iter"), iters[-1].get("iter")
        walls = [float(e.get("wall_ms", 0.0)) for e in iters]
        print(
            f"  iterations: {lo}..{hi}  wall_ms "
            f"p50 {_percentile(walls, 50):.2f} "
            f"p99 {_percentile(walls, 99):.2f}"
        )
    alerts = doc.get("alerts") or []
    print(f"  alerts: {len(alerts)}")
    for a in alerts[-10:]:
        print(
            f"    [{a.get('severity', '?')}] it{a.get('iter', '?')} "
            f"{a.get('rule', '?')}: {a.get('message', '')}"
        )
    tail = events[-5:]
    if tail:
        print("  last events:")
        for e in tail:
            print(f"    {json.dumps(e)[:160]}")
    return 0


def main(argv: List[str]) -> int:
    if not argv:
        print(__doc__)
        return 2
    if argv[0] == "--flight":
        if len(argv) < 2:
            print("--flight needs at least one flight_*.json", file=sys.stderr)
            return 2
        rc = 0
        for path in argv[1:]:
            rc = max(rc, print_flight(path))
        return rc
    rc = 0
    for path in argv:
        events = load_events(path)
        if not events:
            print(f"{path}: no events", file=sys.stderr)
            rc = 1
            continue
        print(f"== {path}")
        for k, v in summarize(events).items():
            print(f"  {k}: {json.dumps(v)}")
    return rc


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
