"""Round-4 TPU measurement program — run THE MOMENT the tunnel is up.

    python tools/perf_r4.py all   # everything, crash-tolerant, results to
                                  # tools/PERF_R4_RESULTS.md as it goes

Individual modes: parity (native partition + int8 + forest-walk bit/close
checks), part (partition perf), train [rows] [iters], train_int8 [rows]
(quantized A/B), overhead (ms/split fixed-cost row sweep), profile [rows],
predict, all.

Every timing uses the marginal-rep method (axon result caching + dispatch
variance make naive timings lie — see BENCH_NOTES).  `all` orders steps by
priority so a mid-run tunnel death still leaves the headline numbers:
train@10.5M -> train@1M -> train_int8@10.5M -> predict -> parity -> part
-> overhead -> profile.
"""

import io
import sys
import time
import traceback
from contextlib import redirect_stdout
from pathlib import Path

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from perf_r3 import (  # noqa: E402
    bench_partition,
    bench_predict,
    bench_profile,
    bench_train,
    marginal,
)


def parity_native():
    """Native (non-interpret) TPU runs of the escrowed kernels vs their
    oracles — the r3 ADVICE medium item."""
    from lightgbm_tpu.ops.pallas.partition import seg_partition_pallas
    from lightgbm_tpu.ops.pallas.seg import (
        pack_rows,
        padded_rows,
        seg_hist_pallas,
        unpack_stats,
    )
    from lightgbm_tpu.ops.segpart import sort_partition_xla
    from lightgbm_tpu.ops.histogram import leaf_histogram_segment

    rng = np.random.default_rng(7)
    f, n = 11, 200_000
    n_pad = padded_rows(n)
    bins = rng.integers(0, 256, size=(n, f)).astype(np.int32)
    g = rng.normal(size=n).astype(np.float32)
    h = rng.random(n).astype(np.float32) + 0.5
    m = (rng.random(n) < 0.8).astype(np.float32)
    seg = jax.device_put(
        pack_rows(jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h),
                  jnp.asarray(m), n_pad)
    )
    catm_narrow = (rng.random(256) < 0.5).astype(np.float32)
    catm = jnp.zeros((1, 256), jnp.float32).at[0].set(jnp.asarray(catm_narrow))

    # -- partition kernel: native vs XLA sort, bit-identical
    for (sb, cnt, feat, tbin, dl, nanb, iscat) in (
        (0, n, 3, 120, 0, -1, 0),
        (137, 60_000, 5, 80, 1, 200, 0),
        (513, 1029, 7, 30, 0, -1, 1),
    ):
        scal = jnp.asarray([sb, cnt, feat, tbin, dl, nanb, iscat, 0], jnp.int32)
        got, nl_k = seg_partition_pallas(
            seg, scal, catm, f=f, n_pad=n_pad, use_cat=bool(iscat)
        )
        want, nl_s, _ = sort_partition_xla(
            seg, jnp.int32(sb), jnp.int32(cnt), jnp.int32(feat),
            jnp.int32(tbin), jnp.int32(dl), jnp.int32(nanb),
            jnp.int32(iscat), jnp.asarray(catm_narrow), f=f, n_pad=n_pad,
        )
        assert int(nl_k) == int(nl_s), (int(nl_k), int(nl_s))
        assert np.array_equal(np.asarray(got), np.asarray(want)), (
            f"partition kernel mismatch at window ({sb},{cnt})"
        )
    print("partition kernel NATIVE parity: bit-identical to sort path")

    # bits-fed variant (feature-parallel seg)
    colv = np.zeros(n_pad, np.int64)
    colv[:n] = bins[:, 3]
    glv = jnp.asarray((colv <= 120).astype(np.float32))
    scal = jnp.asarray([0, n, 3, 120, 0, -1, 0, 0], jnp.int32)
    got, nl_k = seg_partition_pallas(
        seg, scal, catm, glv, f=f, n_pad=n_pad, use_cat=False
    )
    want, nl_s, _ = sort_partition_xla(
        seg, jnp.int32(0), jnp.int32(n), jnp.int32(3), jnp.int32(120),
        jnp.int32(0), jnp.int32(-1), jnp.int32(0),
        jnp.asarray(catm_narrow), f=f, n_pad=n_pad,
    )
    assert int(nl_k) == int(nl_s)
    assert np.array_equal(np.asarray(got), np.asarray(want))
    print("bits-fed partition kernel NATIVE parity: bit-identical")

    # -- seg histogram (bf16 three-term) native tolerance
    hs = seg_hist_pallas(
        seg, jnp.asarray([137, 60_000], jnp.int32), f=f, num_bins=256,
        n_pad=n_pad,
    )
    bo, go, ho, mo, _ = unpack_stats(seg[:, 137:137 + 60_000], f)
    ref = leaf_histogram_segment(bo, go, ho, mo, 256)
    rel = float(
        np.abs(np.asarray(hs) - np.asarray(ref)).max()
        / max(1e-9, np.abs(np.asarray(ref)).max())
    )
    assert rel < 5e-6, rel
    print(f"seg_hist NATIVE parity: rel err {rel:.2e} (< 5e-6)")

    # -- int8 grid variant native exactness (quantized training)
    gs, hsc = np.float32(0.037), np.float32(0.0021)
    kq = rng.integers(-63, 64, size=n).astype(np.float32)
    hq = rng.integers(0, 64, size=n).astype(np.float32)
    seg_q = jax.device_put(
        pack_rows(jnp.asarray(bins), jnp.asarray(kq * gs),
                  jnp.asarray(hq * hsc), jnp.asarray(m), n_pad)
    )
    out_q = seg_hist_pallas(
        seg_q, jnp.asarray([137, 60_000], jnp.int32),
        jnp.asarray([gs, hsc], jnp.float32), f=f, num_bins=256, n_pad=n_pad,
        quantized=True,
    )
    bo, go, ho, mo, _ = unpack_stats(seg_q[:, 137:137 + 60_000], f)
    ref_q = leaf_histogram_segment(bo, go, ho, mo, 256)
    assert np.array_equal(
        np.asarray(out_q)[:, :, 2], np.asarray(ref_q)[:, :, 2]
    )
    assert np.allclose(np.asarray(out_q), np.asarray(ref_q), rtol=1e-6, atol=1e-6)
    print("int8 seg_hist NATIVE parity: counts exact, g/h at 1e-6")

    # -- forest-walk kernel native vs XLA walker (via a trained model)
    import lightgbm_tpu as lgb

    X = rng.normal(size=(20_000, 7))
    X[::5, 2] = np.nan
    y = np.where(np.isnan(X[:, 2]), 1.0, X[:, 0])
    b = lgb.train(
        {"objective": "regression", "num_leaves": 31, "verbosity": -1},
        lgb.Dataset(X, y), 12,
    )
    raw_fw = b._forest_walk_raw(X[:5000], 0, 12, 1)
    assert raw_fw is not None, "forest-walk ineligible on TPU?!"
    from lightgbm_tpu.predict import predict_bins_raw

    bins_h = jnp.asarray(b._bin_input_host(X[:5000]))
    batch = b._stacked_bins(0, 12)
    exp = np.asarray(predict_bins_raw(batch, bins_h, b._nan_bins)).reshape(
        5000, -1
    ).sum(axis=1)
    assert np.allclose(raw_fw[:, 0], exp, atol=1e-5), "forest walk mismatch"
    print("forest-walk kernel NATIVE parity: matches XLA walker at 1e-5")


def bench_overhead():
    """ms/split fixed-cost extraction: serial training at halving row
    counts; the row->0 intercept is the per-split fixed overhead (VERDICT
    r3 #4 asks for <= 0.2 ms/split)."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(0)
    X0 = rng.normal(size=(1_000_000, 28)).astype(np.float32)
    y0 = (X0[:, 0] + X0[:, 1] > 0).astype(np.float64)
    print("| rows | ms/tree | ms/split |")
    print("|---|---|---|")
    pts = []
    for rows in (1_000_000, 500_000, 250_000, 125_000, 62_500):
        params = {
            "objective": "binary", "num_leaves": 255, "max_bin": 255,
            "min_data_in_leaf": 100, "verbosity": -1, "metric": "none",
        }
        d = lgb.Dataset(X0[:rows], y0[:rows], params=params)
        b = lgb.Booster(params, d)

        def step(i):
            b.update()
            return b._score

        dt = marginal(step, 2, 5)
        pts.append((rows, dt))
        print(f"| {rows} | {dt*1e3:.0f} | {dt*1e3/254:.3f} |", flush=True)
    # linear fit: ms/split = a * rows + c
    rs = np.array([p[0] for p in pts], np.float64)
    ts = np.array([p[1] * 1e3 / 254 for p in pts], np.float64)
    a, c = np.polyfit(rs, ts, 1)
    print(
        f"\nfit ms/split = {a:.3e} * rows + {c:.3f}  ->  fixed overhead "
        f"~{c:.3f} ms/split (target <= 0.2)"
    )


def bench_train_fused(rows, iters=8):
    """fused_split_scan A/B against bench_train's default scan — the
    per-split fixed-cost bet (ops/pallas/split_scan.py; VERDICT r4 #4).
    Identical data/shape/warmup; the only delta is the fused kernel."""
    import perf_r3

    orig = perf_r3._make_booster

    def _mk(rows_):
        return orig(rows_, extra_params={"fused_split_scan": True})

    perf_r3._make_booster = _mk
    try:
        print("fused ", end="")
        bench_train(rows, iters)
    finally:
        perf_r3._make_booster = orig


def parity_native_fused():
    """Native run of the fused split-scan kernel vs the XLA best_split on a
    real trained tree: structure equality end-to-end."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(3)
    X = rng.normal(size=(200_000, 28))
    X[::9, 5] = np.nan
    y = X[:, 0] + np.sin(X[:, 1]) + 0.3 * np.isnan(X[:, 5])
    base = {"objective": "regression", "verbosity": -1, "num_leaves": 255,
            "min_data_in_leaf": 100}
    b0 = lgb.train(base, lgb.Dataset(X, y, params=base), 4)
    pf = {**base, "fused_split_scan": True}
    b1 = lgb.train(pf, lgb.Dataset(X, y, params=pf), 4)

    def _structure(bst):
        return [
            line for line in bst.model_to_string().splitlines()
            if line.startswith(("split_feature=", "threshold="))
        ]

    assert _structure(b0) == _structure(b1), "fused split-scan tree diverges"
    print("fused split-scan NATIVE parity: tree structure identical")


def bench_train_int8(rows, iters=8):
    """Quantized training with the int8 seg-hist grid kernel — the measured
    A/B against bench_train's bf16 path (expected ~2x histogram
    throughput).  Identical data/shape/warmup to bench_train: the only
    delta is the quantized-gradient int8 kernel, so the two numbers are
    directly comparable."""
    import perf_r3

    orig = perf_r3._make_booster

    def _mk(rows_):
        return orig(
            rows_,
            extra_params={
                "use_quantized_grad": True,
                "hist_method": "pallas_int8",
            },
        )

    perf_r3._make_booster = _mk
    try:
        print("int8 ", end="")
        bench_train(rows, iters)
    finally:
        perf_r3._make_booster = orig


_STEPS = [
    ("train_10p5M", lambda: bench_train(10_500_000, 8)),
    ("train_1M", lambda: bench_train(1_000_000, 8)),
    ("train_10p5M_int8", lambda: bench_train_int8(10_500_000, 8)),
    ("predict", lambda: bench_predict()),
    ("parity_native", parity_native),
    ("parity_native_fused", parity_native_fused),
    ("train_10p5M_fused", lambda: bench_train_fused(10_500_000, 8)),
    ("partition_perf", bench_partition),
    ("overhead", bench_overhead),
    ("profile_10p5M", lambda: bench_profile(10_500_000)),
]


def run_all():
    out_path = Path(__file__).parent / "PERF_R4_RESULTS.md"
    with open(out_path, "a") as fp:
        fp.write(f"\n# perf_r4 run {time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())}\n")
        fp.write(f"backend: {jax.default_backend()}, devices: {jax.devices()}\n\n")
        for name, fn in _STEPS:
            fp.write(f"## {name}\n\n")
            buf = io.StringIO()
            t0 = time.perf_counter()
            try:
                with redirect_stdout(buf):
                    fn()
                status = "ok"
            except Exception:
                buf.write("\n" + traceback.format_exc())
                status = "FAILED"
            fp.write(buf.getvalue())
            fp.write(
                f"\n[{name}: {status} in {time.perf_counter()-t0:.0f}s]\n\n"
            )
            fp.flush()
            print(f"{name}: {status}", flush=True)
    print(f"results appended to {out_path}")


if __name__ == "__main__":
    mode = sys.argv[1] if len(sys.argv) > 1 else "all"
    if mode == "all":
        run_all()
    elif mode == "parity":
        parity_native()
    elif mode == "part":
        bench_partition()
    elif mode == "train":
        bench_train(int(sys.argv[2]) if len(sys.argv) > 2 else 10_500_000,
                    int(sys.argv[3]) if len(sys.argv) > 3 else 8)
    elif mode == "train_int8":
        bench_train_int8(
            int(sys.argv[2]) if len(sys.argv) > 2 else 10_500_000,
            int(sys.argv[3]) if len(sys.argv) > 3 else 8,
        )
    elif mode == "overhead":
        bench_overhead()
    elif mode == "profile":
        bench_profile(int(sys.argv[2]) if len(sys.argv) > 2 else 10_500_000)
    elif mode == "predict":
        bench_predict()
