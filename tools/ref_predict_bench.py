"""Same-machine predict A/B: reference CLI vs our Booster, SAME model file.

    python tools/ref_predict_bench.py /path/to/lightgbm-cli

The fork's 84k preds/s target (original.md) was measured on its own AVX
machine; this gives the denominator on THIS machine.  The reference
trains a 376-tree binary model (the fork benchmark's tree count) on
bench.py-shaped data, then both engines predict the same 500k rows from
the same model.txt — cross-engine model compatibility makes the
comparison exact.
"""

import subprocess
import sys
import tempfile
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

TRAIN = """task = train
objective = binary
data = train.csv
label_column = 0
num_leaves = 31
learning_rate = 0.1
min_data_in_leaf = 100
num_trees = 376
metric = none
num_threads = 1
verbosity = -1
output_model = model.txt
"""

PRED = """task = predict
data = pred.csv
input_model = model.txt
output_result = preds.txt
num_threads = 1
header = false
"""


def main(cli):
    cli = str(Path(cli).resolve())
    import jax

    jax.config.update("jax_platforms", "cpu")
    import lightgbm_tpu as lgb
    from bench import _make_data

    X, y = _make_data(500_000, 28)
    with tempfile.TemporaryDirectory() as td:
        work = Path(td)
        np.savetxt(
            work / "train.csv",
            np.column_stack([y[:300_000], X[:300_000].astype(np.float64)]),
            delimiter=",", fmt="%.7g",
        )
        (work / "train.conf").write_text(TRAIN)
        t0 = time.perf_counter()
        p = subprocess.run([cli, "config=train.conf"], cwd=work,
                           capture_output=True, text=True)
        if p.returncode != 0:
            raise RuntimeError(p.stdout + p.stderr)
        print(f"reference trained 376 trees in {time.perf_counter()-t0:.0f}s")
        np.savetxt(work / "pred.csv", X.astype(np.float64), delimiter=",",
                   fmt="%.10g")  # f32 needs 9 sig digits to round-trip
        (work / "pred.conf").write_text(PRED)
        # reference predict: time includes CSV parse (its real pipeline);
        # run twice, second run quotes the steady state
        for tag in ("cold", "warm"):
            t0 = time.perf_counter()
            p = subprocess.run([cli, "config=pred.conf"], cwd=work,
                               capture_output=True, text=True)
            dt = time.perf_counter() - t0
            if p.returncode != 0:
                raise RuntimeError(p.stdout + p.stderr)
            print(f"reference predict 500k ({tag}): {dt:.1f}s = "
                  f"{500_000/dt:,.0f} preds/s (incl. CSV parse)")
        ref_preds = np.loadtxt(work / "preds.txt", ndmin=1)

        b = lgb.Booster(model_file=str(work / "model.txt"))
        ours = b.predict(X)  # warmup + correctness
        np.testing.assert_allclose(ours, ref_preds, rtol=1e-5, atol=1e-6)
        t0 = time.perf_counter()
        ours = b.predict(X)
        dt = time.perf_counter() - t0
        print(f"ours predict 500k (warm, ndarray in memory): {dt:.1f}s = "
              f"{500_000/dt:,.0f} preds/s")


if __name__ == "__main__":
    main(sys.argv[1])
