"""Round-3 TPU perf harness: partition kernel vs XLA sort, seg_hist, and
end-to-end training at configurable rows.

Usage (real TPU):
    python tools/perf_r3.py part      # partition kernel vs sort, by window
    python tools/perf_r3.py train [rows] [iters]   # e2e iters/s
    python tools/perf_r3.py profile [rows]         # per-phase decomposition

All timings use the marginal-rep method (two loop lengths) per the round-2
measurement notes: axon result caching + 30-300 ms dispatch variance make
naive single-call timings lie.
"""

import sys
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


def marginal(fn, r1=3, r2=9):
    """Marginal per-rep cost of fn(i) via two loop lengths."""
    def run(reps):
        t0 = time.perf_counter()
        out = None
        for i in range(reps):
            out = fn(i)
        jax.block_until_ready(out)
        return time.perf_counter() - t0

    run(1)  # compile/warm
    t1 = run(r1)
    t2 = run(r2)
    return (t2 - t1) / (r2 - r1)


def bench_partition():
    from lightgbm_tpu.ops.pallas.partition import seg_partition_pallas
    from lightgbm_tpu.ops.pallas.seg import pack_rows, padded_rows
    from lightgbm_tpu.ops.segpart import sort_partition_xla

    f, n = 28, 10_500_000
    n_pad = padded_rows(n)
    rng = np.random.default_rng(0)
    bins = rng.integers(0, 256, size=(n, f)).astype(np.int32)
    g = rng.normal(size=n).astype(np.float32)
    h = np.ones(n, np.float32)
    m = np.ones(n, np.float32)
    seg = pack_rows(jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h), jnp.asarray(m), n_pad)
    seg = jax.device_put(seg)
    catm = jnp.zeros((1, 256), jnp.float32)
    print("| window | kernel ms | ns/row | sort ms | ns/row | speedup |")
    print("|---|---|---|---|---|---|")
    for cnt in (8192, 65536, 262144, 1 << 20, 1 << 22, n):
        sb = 12345

        def k_call(i, cnt=cnt, sb=sb):
            scal = jnp.asarray([sb, cnt, i % f, 120, 0, -1, 0, 0], jnp.int32)
            s2, nl = seg_partition_pallas(
                seg, scal, catm, f=f, n_pad=n_pad, use_cat=False
            )
            return nl

        def s_call(i, cnt=cnt, sb=sb):
            s2, nl, nr = sort_partition_xla(
                seg, jnp.int32(sb), jnp.int32(cnt), jnp.int32(i % f),
                jnp.int32(120), jnp.int32(0), jnp.int32(-1), jnp.int32(0),
                jnp.zeros((1,), jnp.float32), f=f, n_pad=n_pad,
            )
            return nl

        tk = marginal(k_call)
        ts = marginal(s_call)
        print(
            f"| {cnt} | {tk*1e3:.2f} | {tk/cnt*1e9:.2f} | "
            f"{ts*1e3:.2f} | {ts/cnt*1e9:.2f} | {ts/tk:.1f}x |",
            flush=True,
        )


def _make_booster(rows, extra_params=None):
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(42)
    X = rng.normal(size=(rows, 28)).astype(np.float32)
    w = rng.normal(size=28)
    y = ((X @ w * 0.5 + rng.normal(scale=1.0, size=rows)) > 0).astype(np.float64)
    params = {
        "objective": "binary",
        "num_leaves": 255,
        "max_bin": 255,
        "learning_rate": 0.1,
        "min_data_in_leaf": 100,
        "verbosity": -1,
        "metric": "none",
    }
    if extra_params:
        params.update(extra_params)
    d = lgb.Dataset(X, y, params=params)
    return lgb.Booster(params, d)


def bench_train(rows, iters=8):
    b = _make_booster(rows)
    for _ in range(2):
        b.update()
    jax.block_until_ready(b._score)
    t0 = time.perf_counter()
    for _ in range(iters):
        b.update()
    jax.block_until_ready(b._score)
    dt = (time.perf_counter() - t0) / iters
    print(f"rows={rows}: {1/dt:.3f} iters/s ({dt*1e3:.0f} ms/tree)")


def bench_profile(rows):
    """Decompose one tree: grow vs score-update vs host bookkeeping."""
    b = _make_booster(rows)
    b.update(); b.update()
    jax.block_until_ready(b._score)
    grad, hess = b.objective.get_gradients(b._score, b._next_rng())
    mask, grad, hess = b._sample(grad, hess)
    fm = b._feature_mask_for_iter()

    def grow_only(i):
        ta, leaf_id = b._grow_one(grad[0] + i * 1e-12, hess[0], mask, fm, None)
        return leaf_id

    tg = marginal(grow_only, 2, 5)
    print(f"grow_tree alone: {tg*1e3:.0f} ms/tree")

    def full(i):
        b.update()
        return b._score

    tf = marginal(full, 2, 5)
    print(f"full update:     {tf*1e3:.0f} ms/iter (pipeline overhead {100*(tf-tg)/tf:.0f}%)")

    from lightgbm_tpu.ops.pallas.seg import padded_rows, seg_hist

    n_pad = padded_rows(b._bins.shape[0])
    seg = b._grow_one  # noqa: placeholder to keep flake quiet


def bench_predict(rows=500_000):
    b = _make_booster(max(rows, 1_000_000))
    for _ in range(6):
        b.update()
    # replicate to 376 trees like bench.py
    orig_models = list(b.models_)
    orig_recs = list(b._bin_records)
    while len(b.models_) < 376:
        b.models_.extend(orig_models)
        b._bin_records.extend(orig_recs)
    del b.models_[376:]
    del b._bin_records[376:]
    b._bump_model_version()
    rng = np.random.default_rng(1)
    X = rng.normal(size=(rows, 28)).astype(np.float32)
    b.predict(X[:1000])  # compile
    for tag, xs in (("cold", X), ("warm", X)):
        t0 = time.perf_counter()
        b.predict(xs)
        dt = time.perf_counter() - t0
        print(f"predict {tag}: {rows/dt:,.0f} preds/s ({dt*1e3:.0f} ms)")


if __name__ == "__main__":
    mode = sys.argv[1] if len(sys.argv) > 1 else "part"
    if mode == "part":
        bench_partition()
    elif mode == "train":
        bench_train(int(sys.argv[2]) if len(sys.argv) > 2 else 1_000_000,
                    int(sys.argv[3]) if len(sys.argv) > 3 else 8)
    elif mode == "profile":
        bench_profile(int(sys.argv[2]) if len(sys.argv) > 2 else 10_500_000)
    elif mode == "predict":
        bench_predict()
