#!/bin/bash
# Full test suite in CHUNKED pytest processes.
#
# One process compiling the whole suite's ~1000+ XLA programs can segfault
# XLA:CPU's LLVM JIT near the end of the run (jax 0.9.0, single-core VM;
# crash stack inside backend_compile_and_load).  Running the suite as a few
# separate processes keeps each under the threshold; the persistent
# compilation cache (tests/conftest.py) removes most recompiles between
# chunks.  Usage:  bash tools/run_tests.sh [extra pytest args]
set -u
cd "$(dirname "$0")/.." || exit 1
export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}"

rc=0

# graftlint gate: pure-ast static analysis (tracer safety, Pallas
# contracts, SPMD collective congruence GL007-GL010) diffed against the
# reviewed baseline.  Runs FIRST over the FULL tree and is a hard gate —
# a new finding or a stale baseline entry fails the suite before any
# pytest chunk spends time compiling.  (--changed-only is for the dev
# loop only; CI always takes the full-tree run.)
echo "=== graftlint (python -m lightgbm_tpu.lint --baseline lint_baseline.json) ==="
python -m lightgbm_tpu.lint --baseline lint_baseline.json || rc=$?

# graftlint IR gate: trace the real jit/shard_map entry matrix to jaxprs
# (abstract CPU tracing, no execution) and audit collectives, dtype
# promotion, donation and Pallas VMEM budgets (GL011-GL015).  Also a
# hard gate, full matrix in CI (--changed-only scopes it in the dev
# loop); budgeted <30 s on top of the AST pass.
echo "=== graftlint IR (python -m lightgbm_tpu.lint --ir --baseline lint_baseline.json) ==="
python -m lightgbm_tpu.lint --ir --baseline lint_baseline.json || rc=$?

chunks=(
  "tests/test_a* tests/test_b* tests/test_c*"
  "tests/test_d* tests/test_e* tests/test_f* tests/test_g* tests/test_h* tests/test_i* tests/test_l*"
  "tests/test_m* tests/test_n* tests/test_o* tests/test_p*"
  "tests/test_q* tests/test_r* tests/test_s* tests/test_v*"
)
for chunk in "${chunks[@]}"; do
  echo "=== pytest $chunk $* ==="
  # shellcheck disable=SC2086
  python -m pytest $chunk -q "$@" || rc=$?
done

# telemetry smoke: a 3-iteration instrumented train must produce a JSONL
# stream the rollup tool can parse (one event per iteration, no recompiles
# hiding in steady state)
echo "=== telemetry smoke (3-iteration train -> tools/telemetry_summary.py) ==="
tel_out=$(mktemp /tmp/telemetry_smoke.XXXXXX.jsonl)
python - "$tel_out" <<'PYEOF' && python tools/telemetry_summary.py "$tel_out" || rc=$?
import sys
import numpy as np
import lightgbm_tpu as lgb

rng = np.random.default_rng(0)
X = rng.normal(size=(400, 6))
y = X[:, 0] + 0.1 * rng.normal(size=400)
lgb.train(
    {"objective": "regression", "num_leaves": 7, "verbosity": -1,
     "metric": "l2", "telemetry": True, "telemetry_out": sys.argv[1]},
    lgb.Dataset(X, y), 3,
    valid_sets=[lgb.Dataset(X, y)], valid_names=["t"],
)
PYEOF
rm -f "$tel_out"

# live-obs smoke: a 3-iteration train must serve parseable Prometheus
# text from the opt-in exporter WHILE training (scraped from an iteration
# callback), the chaos drills must each leave a valid flight dump behind,
# and the offline tools must digest both artifacts.
echo "=== live-obs smoke (exporter scrape + chaos flight dumps + obs_top) ==="
python - <<'PYEOF' || rc=$?
import json
import socket
import subprocess
import sys
import tempfile
import urllib.request

import numpy as np
import lightgbm_tpu as lgb
from lightgbm_tpu.resilience import chaos

with socket.socket() as s:
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]

scraped = {}

def scrape(env):
    if env.iteration == 1 and not scraped:
        url = f"http://127.0.0.1:{port}"
        scraped["metrics"] = urllib.request.urlopen(
            url + "/metrics", timeout=5).read().decode()
        scraped["health"] = json.loads(
            urllib.request.urlopen(url + "/healthz", timeout=5).read())

rng = np.random.default_rng(0)
X = rng.normal(size=(400, 6))
y = X[:, 0] + 0.1 * rng.normal(size=400)
tel = tempfile.mktemp(suffix=".jsonl")
booster = lgb.train(
    {"objective": "regression", "num_leaves": 7, "verbosity": -1,
     "telemetry": True, "telemetry_out": tel, "obs_export_port": port},
    lgb.Dataset(X, y), 3, callbacks=[scrape],
)
assert scraped, "exporter scrape callback never ran"
for line in scraped["metrics"].splitlines():  # parseable exposition text
    assert line.startswith("#") or len(line.split(" ")) == 2, line
assert "lgbtpu_iterations_total" in scraped["metrics"]
assert scraped["health"]["status"] == "ok"
assert booster.health()["iter"] == 3
print("live-obs smoke: exporter served parseable metrics during training")

dumps = []
for drill in (chaos.flight_dump_drill_numerics,
              chaos.flight_dump_drill_degradation):
    wd = tempfile.mkdtemp(prefix="lgbm_tpu_flight_smoke_")
    dumps.append(drill(wd))
    print(f"live-obs smoke: {drill.__name__} -> {dumps[-1]}")

for tool_args in ([ "tools/telemetry_summary.py", "--flight"] + dumps,
                  ["tools/obs_top.py", "--tail", tel, "--once",
                   "--no-color"]):
    r = subprocess.run([sys.executable] + tool_args, capture_output=True)
    assert r.returncode == 0, (tool_args, r.stderr.decode())
print("live-obs smoke: flight dumps + offline tools OK")
PYEOF

# serving smoke: lgb.serve() over a 3-tree model must coalesce concurrent
# mixed-size requests bit-identically to Booster.predict, publish
# lgbtpu_serve_* on /metrics and the serving block on /healthz, survive
# one hot-swap with full parity on the new version, and tear down clean.
echo "=== serving smoke (lgb.serve: mixed-size parity + /metrics + hot-swap) ==="
python - <<'PYEOF' || rc=$?
import json
import urllib.request

import numpy as np
import lightgbm_tpu as lgb

rng = np.random.default_rng(0)
X = rng.normal(size=(500, 6))
params = {"objective": "regression", "num_leaves": 7, "verbosity": -1}
b1 = lgb.train(params, lgb.Dataset(X, X[:, 0] + 0.1 * X[:, 1]), 3)
b2 = lgb.train(params, lgb.Dataset(X, X[:, 1] - 0.3 * X[:, 2]), 3)
queries = {n: rng.normal(size=(n, 6)) for n in (1, 7, 64, 300, 700)}
r1 = {n: b1.predict(q) for n, q in queries.items()}
r2 = {n: b2.predict(q) for n, q in queries.items()}

server = lgb.serve(b1, deadline_ms=3.0, max_batch=512, port=-1)
try:
    futs = [(n, server.predict_async(q)) for n, q in list(queries.items()) * 3]
    for n, f in futs:
        assert np.array_equal(f.result(timeout=30.0).values, r1[n]), n
    text = urllib.request.urlopen(server.url + "/metrics", timeout=5).read().decode()
    serve_lines = [l for l in text.splitlines() if l.startswith("lgbtpu_serve_")]
    assert serve_lines, "no lgbtpu_serve_* series on /metrics"
    hz = json.loads(urllib.request.urlopen(server.url + "/healthz", timeout=5).read())
    assert hz["serving"]["models"][0]["model_id"] == "default"
    info = server.swap("default", b2)
    assert info["version"] == 2
    for n, q in queries.items():
        assert np.array_equal(server.predict(q, timeout=30.0), r2[n]), n
    print("serving smoke: parity + metrics + hot-swap OK "
          f"({len(serve_lines)} serve series)")
finally:
    server.stop()
PYEOF

# tensor-forest smoke: the matmul prediction engine must be byte-identical
# to the walker on a 3-iteration eligible model (values + leaf indices),
# resolve via pred_engine=auto (the compile-time parity probe), and warm
# its own retrace label next to the walker's.
echo "=== tensor-forest smoke (pred_engine=matmul byte parity vs walker) ==="
python - <<'PYEOF' || rc=$?
import numpy as np
import lightgbm_tpu as lgb

rng = np.random.default_rng(0)
X = rng.normal(size=(800, 8))
X[rng.random(X.shape) < 0.05] = np.nan
y = np.nan_to_num(X[:, 0]) + 0.3 * np.nan_to_num(X[:, 1])
params = {"objective": "regression", "num_leaves": 15, "verbosity": -1}
b = lgb.train(params, lgb.Dataset(X, y, params=params), 3)
Xq = rng.normal(size=(700, 8))
Xq[rng.random(Xq.shape) < 0.05] = np.nan
walk = b.predict(Xq, pred_engine="walk")
mm = b.predict(Xq, pred_engine="matmul")
assert walk.tobytes() == mm.tobytes(), "matmul values diverged from walker"
assert b.last_predict_stats.get("engine") == "matmul"
auto = b.predict(Xq, pred_engine="auto")
assert auto.tobytes() == walk.tobytes(), "auto engine diverged from walker"
lw = b.predict(Xq, pred_leaf=True, pred_engine="walk")
lm = b.predict(Xq, pred_leaf=True, pred_engine="matmul")
assert np.array_equal(lw, lm), "matmul leaf indices diverged from walker"
labels = lgb.compile_counts_by_label()
assert any("tensor" in k for k in labels), sorted(labels)
print("tensor-forest smoke: walker/matmul byte parity OK")
PYEOF

# streaming-ingest smoke: a 3-iteration train whose Dataset was built by
# the chunked two-pass ingest (pass 1 samples + fits mappers, pass 2
# streams chunks through binning; the full raw f64 matrix never
# materializes) must dump byte-identically to the one-shot build of the
# same data/seed, including through a memmap-backed bin-plane spill.
echo "=== streaming-ingest smoke (chunked two-pass train parity vs one-shot) ==="
python - <<'PYEOF' || rc=$?
import tempfile

import numpy as np
import lightgbm_tpu as lgb

rng = np.random.default_rng(0)
X = rng.normal(size=(3000, 12))
X[:, 4] = (rng.random(3000) < 0.06) * rng.normal(size=3000)  # sparse col
y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
          "bin_construct_sample_cnt": 700, "data_random_seed": 3,
          "min_data_in_leaf": 10}

def dump(extra):
    p = dict(params, **extra)
    b = lgb.train(p, lgb.Dataset(X.copy(), y, params=p), 3)
    return "\n".join(ln for ln in b.model_to_string().splitlines()
                     if not ln.startswith("[ingest_"))

ref = dump({})
assert dump({"ingest_chunk_rows": 611}) == ref, (
    "chunked-ingest dump diverged from one-shot")
with tempfile.TemporaryDirectory() as td:
    assert dump({"ingest_chunk_rows": 611, "ingest_mmap_dir": td}) == ref, (
        "memmap-spill chunked dump diverged from one-shot")
print("streaming-ingest smoke: chunked/memmap train parity OK")
PYEOF

# perf-contract gate: collect the deterministic telemetry slice (retraces
# by label, analytic+measured collective bytes, executable FLOPs/temp HBM)
# and diff it against the committed contract.  HARD gate — any drift in a
# hard metric fails the suite; wall times only warn.  Accepted changes are
# committed via  python tools/perf_gate.py --update --justify "<why>".
echo "=== perf-contract gate (tools/perf_gate.py vs tools/perf_contract.json) ==="
python tools/perf_gate.py || rc=$?

# fused grow-step smoke: run the Pallas kernel itself (interpret mode,
# JAX_PLATFORMS=cpu) through a 3-iteration train and require structural
# parity with the XLA oracle.  A fresh process matters: grow_step._INTERPRET
# is read at trace time, so flipping it next to an already-traced config
# would silently reuse the oracle trace.
echo "=== fused grow-step smoke (3-iteration interpret-mode train vs oracle) ==="
python - <<'PYEOF' || rc=$?
import numpy as np
import lightgbm_tpu as lgb
from lightgbm_tpu.ops.pallas import grow_step

rng = np.random.default_rng(0)
X = rng.normal(size=(1200, 10)).astype(np.float32)
y = (X[:, 0] + 0.6 * X[:, 1] + 0.1 * rng.normal(size=1200) > 0.2).astype(
    np.float32)
KEEP = ("split_feature=", "threshold=", "decision_type=", "left_child=",
        "right_child=", "num_leaves=")

def structure(**over):
    p = dict(objective="binary", num_leaves=15, learning_rate=0.2,
             hist_mode="seg", min_data_in_leaf=20, verbosity=-1,
             deterministic=True, seed=7)
    p.update(over)
    b = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=3)
    s = b.model_to_string()
    return [l for l in s[s.index("Tree=0"):s.index("end of trees")].splitlines()
            if l.startswith(KEEP)]

ref = structure(grow_fused="off")
grow_step._INTERPRET = True
got = structure(grow_fused="on")
assert got == ref, "fused interpret-mode structure diverged from oracle"
print("fused grow-step interpret smoke: structure parity OK")
PYEOF

# int8 histogram smoke: run the histogram engine's int8-by-default path
# (seg kernels in interpret mode, which also engages the int8 accumulator
# off-TPU) through a 3-iteration train, serial AND leaf_batch=2 fused, and
# require structural parity with the f32 XLA oracle.  Fresh process for
# the same trace-time-flag reason as the fused smoke; the oracle refs are
# computed BEFORE the flags flip.  Exact parity holds on this workload
# because no decisive split sits inside a sub-1e-4 relative-gain tie —
# the engine's contract (zero flips at >=1e-4 gap, near-tie f32 refine
# below) is property-tested in tests/test_split_scan.py; data with a
# decisive deeper tie would exercise the benign-flip regime instead.
echo "=== int8 fused-histogram smoke (3-iteration interpret-mode train vs oracle) ==="
python - <<'PYEOF' || rc=$?
import numpy as np
import lightgbm_tpu as lgb
from lightgbm_tpu.ops.pallas import grow_step, seg

rng = np.random.default_rng(0)
X = rng.normal(size=(1200, 10)).astype(np.float32)
y = (X[:, 0] + 0.6 * X[:, 1] + 0.1 * rng.normal(size=1200) > 0.2).astype(
    np.float32)
KEEP = ("split_feature=", "threshold=", "decision_type=", "left_child=",
        "right_child=", "num_leaves=")

def structure(**over):
    p = dict(objective="binary", num_leaves=15, learning_rate=0.2,
             hist_mode="seg", min_data_in_leaf=20, verbosity=-1,
             deterministic=True, seed=7)
    p.update(over)
    b = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=3)
    s = b.model_to_string()
    return [l for l in s[s.index("Tree=0"):s.index("end of trees")].splitlines()
            if l.startswith(KEEP)]

ref = structure(grow_fused="off")
ref_b2 = structure(grow_fused="off", leaf_batch=2)
seg._INTERPRET = True       # seg kernels interpret + int8-default engages
grow_step._INTERPRET = True
got = structure(grow_fused="on")
assert got == ref, "int8 histogram structure diverged from f32 oracle"
got_b2 = structure(grow_fused="on", leaf_batch=2)
assert got_b2 == ref_b2, (
    "int8 batched (K=2) structure diverged from f32 oracle")
print("int8 fused-histogram interpret smoke: structure parity OK")
PYEOF

# kill-and-resume smoke: SIGKILL a checkpointing train mid-run (via the
# chaos harness, the closest stand-in for a TPU-pod preemption), resume
# from the latest checkpoint, and require a byte-identical model dump vs
# the uninterrupted run.  Needs real process death, so it lives here and
# not in pytest.
echo "=== kill-and-resume smoke (SIGKILL at iteration 15, resume to 30) ==="
python - <<'PYEOF' || rc=$?
import subprocess
import sys
import tempfile

ckdir = tempfile.mkdtemp(prefix="lgbm_tpu_ckpt_smoke_")

COMMON = f"""
import numpy as np
import lightgbm_tpu as lgb
rng = np.random.default_rng(0)
X = rng.normal(size=(400, 6))
y = X[:, 0] * 2 + np.sin(X[:, 1]) + 0.1 * rng.normal(size=400)
params = dict(objective="regression", num_leaves=15, learning_rate=0.1,
              min_data_in_leaf=20, verbosity=-1, deterministic=True, seed=7,
              bagging_fraction=0.7, bagging_freq=2, bagging_seed=11,
              checkpoint_dir={ckdir!r}, checkpoint_interval=5)
"""

child = COMMON + """
from lightgbm_tpu.resilience import chaos
chaos.kill_at_iteration(15)
lgb.train(params, lgb.Dataset(X, y, params=params), num_boost_round=30)
raise SystemExit("unreachable: SIGKILL did not fire")
"""
proc = subprocess.run([sys.executable, "-c", child])
assert proc.returncode == -9, f"expected SIGKILL (-9), got {proc.returncode}"

exec(COMMON)
resumed = lgb.train(
    params, lgb.Dataset(X, y, params=params), num_boost_round=30,
    resume_from=ckdir,
)
baseline = lgb.train(
    params, lgb.Dataset(X, y, params=params), num_boost_round=30
)
assert resumed.current_iteration() == 30
assert resumed.model_to_string() == baseline.model_to_string(), (
    "resumed dump diverged from uninterrupted run")
print("kill-and-resume smoke: byte-identical dump after SIGKILL+resume OK")
PYEOF

# launch-scan smoke: device-resident boosting must be invisible in the
# model bytes.  3 launches of N=2 scanned iterations (one compiled
# lax.scan dispatch each) vs 6 serial iterations: byte-identical dump
# (modulo the requested-N config echo) and exactly ONE compile of the
# scan executable across all 3 launches.
echo "=== launch-scan smoke (3 launches x N=2 vs 6 serial iterations) ==="
python - <<'PYEOF' || rc=$?
import re

import numpy as np
import lightgbm_tpu as lgb

rng = np.random.default_rng(0)
X = rng.normal(size=(400, 8))
y = X[:, 0] * 2 + np.sin(X[:, 1]) + 0.1 * rng.normal(size=400)
params = dict(objective="regression", num_leaves=15, learning_rate=0.1,
              min_data_in_leaf=20, verbosity=-1, seed=7,
              bagging_fraction=0.7, bagging_freq=1)

def dump(n):
    p = dict(params, train_steps_per_launch=n)
    b = lgb.train(p, lgb.Dataset(X, y), num_boost_round=6)
    return re.sub(r"\[train_steps_per_launch: [^\]]*\]\n?", "",
                  b.model_to_string())

ref = dump(1)
before = dict(lgb.compile_counts_by_label())
assert dump(2) == ref, "launch-scan dump diverged from serial loop"
after = lgb.compile_counts_by_label()
scan_compiles = after.get("grow/scan2", 0) - before.get("grow/scan2", 0)
assert scan_compiles == 1, (
    f"expected 1 scan compile across 3 launches, saw {scan_compiles}")
print("launch-scan smoke: byte parity + single scan compile OK")
PYEOF

# trace smoke: a 3-iteration train plus one served request (with a caller
# traceparent) must yield a Perfetto-loadable Chrome trace via
# Booster.dump_trace containing the train span tree AND the serve request
# decomposition, with the request joined to the caller's trace id.
echo "=== trace smoke (dump_trace: train + serve spans, traceparent join) ==="
python - <<'PYEOF' || rc=$?
import json
import tempfile

import numpy as np
import lightgbm_tpu as lgb

rng = np.random.default_rng(0)
X = rng.normal(size=(400, 6))
y = X[:, 0] + 0.1 * rng.normal(size=400)
b = lgb.train({"objective": "regression", "num_leaves": 7, "verbosity": -1},
              lgb.Dataset(X, y), 3)
caller = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
server = lgb.serve(b, deadline_ms=2.0, port=-1)
try:
    resp = server.predict_async(X[:5], traceparent=caller).result(timeout=30.0)
    echoed = resp.info.get("traceparent", "")
    assert echoed.split("-")[1] == "ab" * 16, echoed
finally:
    server.stop()
path = tempfile.mktemp(suffix=".json")
b.dump_trace(path)
with open(path) as fp:
    doc = json.load(fp)
names = {e.get("name") for e in doc["traceEvents"]}
for want in ("train/run", "train/iteration", "serve/request",
             "serve/queue_wait", "serve/batch"):
    assert want in names, (want, sorted(names))
req = [e for e in doc["traceEvents"]
       if e.get("name") == "serve/request" and e.get("ph") == "X"]
assert req and req[0]["args"]["trace_id"] == "ab" * 16, req
print(f"trace smoke: {len(doc['traceEvents'])} events, "
      "train+serve spans + traceparent join OK")
PYEOF
exit $rc
