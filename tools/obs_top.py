"""Live terminal dashboard for a running lightgbm_tpu training job.

Two data sources, pick one:

    python tools/obs_top.py --endpoint http://127.0.0.1:9184
    python tools/obs_top.py --tail events.jsonl

``--endpoint`` polls the opt-in metrics exporter (``obs_export_port``),
scraping ``/metrics`` (Prometheus text) and ``/healthz`` (JSON) each
refresh.  ``--tail`` follows a telemetry JSONL file (``telemetry_out``)
from its current end, consuming iteration/alert/predict events as the
trainer appends them.  Either way the frame shows: health status,
iterations + iters/s, wall and phase p50/p99 over a sliding window,
collective-byte gauges, key histogram/int8 gauges, and the most recent
alerts.

Dependency-free by design: plain ANSI escapes (no curses), stdlib HTTP
client, nearest-rank percentiles.  ``--once`` renders a single frame and
exits (used by the test suite and handy for cron snapshots).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

CLEAR = "\x1b[2J\x1b[H"
BOLD = "\x1b[1m"
DIM = "\x1b[2m"
RED = "\x1b[31m"
YELLOW = "\x1b[33m"
GREEN = "\x1b[32m"
RESET = "\x1b[0m"

_STATUS_COLOR = {"ok": GREEN, "warn": YELLOW, "critical": RED}


def _percentile(vals: List[float], q: float) -> float:
    """Nearest-rank percentile (matches tools/telemetry_summary.py)."""
    if not vals:
        return 0.0
    s = sorted(vals)
    idx = min(len(s) - 1, max(0, int(round(q / 100.0 * (len(s) - 1)))))
    return s[idx]


def parse_prometheus(text: str) -> Dict[str, float]:
    """Parse Prometheus text exposition into {name_or_series: value}.

    Labeled series keep their label block as part of the key, so
    ``lgbtpu_alert_active{rule="hbm",severity="warn"}`` stays distinct.
    """
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        # value is the last whitespace-separated token; the name (with an
        # optional {label} block that may itself contain spaces) is the rest
        name, _, value = line.rpartition(" ")
        try:
            out[name.strip()] = float(value)
        except ValueError:
            continue
    return out


class TopState:
    """Sliding-window aggregation shared by both data sources."""

    def __init__(self, window: int = 120) -> None:
        self.window = int(window)
        self.iter_marks: Deque[Tuple[float, float]] = deque(maxlen=self.window)
        self.walls: Deque[float] = deque(maxlen=self.window)
        self.phases: Dict[str, Deque[float]] = {}
        self.predict_phases: Dict[str, Deque[float]] = {}
        self.alerts: Deque[Dict[str, Any]] = deque(maxlen=8)
        self.metrics: Dict[str, float] = {}
        self.health: Dict[str, Any] = {}
        self.iterations = 0.0
        self.source = ""
        self.error = ""

    # ---------------------------------------------------------- ingestion
    def update_from_metrics(
        self,
        metrics: Dict[str, float],
        health: Optional[Dict[str, Any]],
        now: Optional[float] = None,
    ) -> None:
        now = time.time() if now is None else now
        self.metrics = metrics
        self.health = health or {}
        self.error = ""
        iters = metrics.get("lgbtpu_iterations_total", 0.0)
        if not self.iter_marks or iters != self.iter_marks[-1][1]:
            self.iter_marks.append((now, iters))
        self.iterations = iters
        for alert in self.health.get("alerts") or []:
            if not any(
                a.get("rule") == alert.get("rule")
                and a.get("iter") == alert.get("iter")
                for a in self.alerts
            ):
                self.alerts.append(alert)

    def update_from_events(
        self, events: List[Dict[str, Any]], now: Optional[float] = None
    ) -> None:
        now = time.time() if now is None else now
        for e in events:
            kind = e.get("event")
            if kind == "iteration":
                self.iterations = float(e.get("iter", self.iterations)) + 1
                self.iter_marks.append((now, self.iterations))
                if "wall_ms" in e:
                    self.walls.append(float(e["wall_ms"]))
                for k, v in (e.get("phases") or {}).items():
                    self.phases.setdefault(
                        k, deque(maxlen=self.window)
                    ).append(float(v))
            elif kind == "alert":
                self.alerts.append(e)
            elif kind == "predict":
                for k, v in (e.get("phases") or {}).items():
                    self.predict_phases.setdefault(
                        k, deque(maxlen=self.window)
                    ).append(float(v))
            elif kind == "train_summary":
                for k, v in (e.get("gauges") or {}).items():
                    if isinstance(v, (int, float)):
                        self.metrics["gauge:" + k] = float(v)

    # --------------------------------------------------------- derivation
    def iters_per_sec(self) -> float:
        if len(self.iter_marks) < 2:
            return 0.0
        (t0, i0), (t1, i1) = self.iter_marks[0], self.iter_marks[-1]
        dt = t1 - t0
        return (i1 - i0) / dt if dt > 0 else 0.0

    def status(self) -> str:
        if self.health:
            return str(self.health.get("status", "ok"))
        rank = self.metrics.get("lgbtpu_health_status")
        if rank is not None:
            return {0: "ok", 1: "warn", 2: "critical"}.get(int(rank), "warn")
        worst = ""
        for a in self.alerts:
            if a.get("severity") == "critical":
                return "critical"
            worst = "warn"
        return worst or "ok"

    def gauge(self, name: str) -> Optional[float]:
        """Look a gauge up under either source's naming."""
        for key in (
            "gauge:" + name,
            "lgbtpu_" + name.replace("/", "_").replace(".", "_"),
        ):
            if key in self.metrics:
                return self.metrics[key]
        g = (self.health.get("gauges") or {}).get(name)
        return float(g) if g is not None else None


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}TiB"


def render_frame(state: TopState, width: int = 78, color: bool = True) -> str:
    """Pure frame renderer — returns the dashboard text for one refresh."""

    def c(code: str, s: str) -> str:
        return f"{code}{s}{RESET}" if color else s

    status = state.status()
    lines: List[str] = []
    lines.append(
        c(BOLD, "lgbtpu obs_top")
        + f"  {state.source}"
        + "  health: "
        + c(_STATUS_COLOR.get(status, YELLOW), status.upper())
    )
    if state.error:
        lines.append(c(RED, f"  source error: {state.error}"))
    lines.append(
        f"  iter {int(state.iterations)}"
        f"   {state.iters_per_sec():.2f} it/s"
        + (
            f"   wall p50 {_percentile(list(state.walls), 50):.1f} ms"
            f"  p99 {_percentile(list(state.walls), 99):.1f} ms"
            if state.walls
            else ""
        )
    )
    if state.phases:
        lines.append(c(DIM, "  train phases (ms)      p50      p99"))
        for k in sorted(state.phases):
            vals = list(state.phases[k])
            lines.append(
                f"    {k:<18}{_percentile(vals, 50):>9.2f}"
                f"{_percentile(vals, 99):>9.2f}"
            )
    if state.predict_phases:
        lines.append(c(DIM, "  predict phases (ms)    p50      p99"))
        for k in sorted(state.predict_phases):
            vals = list(state.predict_phases[k])
            lines.append(
                f"    {k:<18}{_percentile(vals, 50):>9.2f}"
                f"{_percentile(vals, 99):>9.2f}"
            )
    gauge_rows: List[str] = []
    for label, name, fmt in (
        ("int8 engaged", "hist/int8_engaged", "{:.0f}"),
        ("near-tie refine rate", "hist/near_tie_refine_rate", "{:.3f}"),
        ("live-plane skip", "hist/live_plane_skip_ratio", "{:.3f}"),
        ("commit rate", "grower.commit_rate", "{:.3f}"),
        ("straggler skew", "straggler/skew", "{:.2f}"),
    ):
        v = state.gauge(name)
        if v is not None:
            gauge_rows.append(f"    {label:<22}{fmt.format(v):>10}")
    for label, name in (
        ("hbm in use", "memory/hbm_bytes_in_use"),
        ("collective hist", "collective_hist_bytes"),
        ("collective ring/dev", "collective_ring_bytes_per_device"),
    ):
        v = state.gauge(name)
        if v is not None:
            gauge_rows.append(f"    {label:<22}{_fmt_bytes(v):>10}")
    if gauge_rows:
        lines.append(c(DIM, "  gauges"))
        lines.extend(gauge_rows)
    serving = (state.health or {}).get("serving")
    if serving:
        gen = serving.get("generation", 0)
        lines.append(
            c(DIM, "  serving")
            + f"  gen {gen}"
            + f"  models {len(serving.get('models') or [])}"
            + f"  resident {_fmt_bytes(float(serving.get('resident_bytes', 0)))}"
        )
        batchers = serving.get("batchers") or {}
        if batchers:
            # q99/d99: latency attribution — where the p99 wall went
            # (queue_wait vs device_dispatch), from the trace decomposition
            lines.append(
                c(
                    DIM,
                    "    model             p50ms    p99ms    q99ms    d99ms"
                    "   fill  miss%     reqs",
                )
            )
            for mid in sorted(batchers):
                b = batchers[mid]
                lines.append(
                    f"    {mid:<16}{b.get('p50_ms', 0.0):>8.2f}"
                    f"{b.get('p99_ms', 0.0):>9.2f}"
                    f"{b.get('queue_ms_p99', 0.0):>9.2f}"
                    f"{b.get('device_ms_p99', 0.0):>9.2f}"
                    f"{b.get('batch_fill', 0.0):>7.2f}"
                    f"{100.0 * b.get('deadline_miss_rate', 0.0):>6.1f}"
                    f"{int(b.get('requests', 0)):>9}"
                )
    elif state.gauge("serve/p50_ms") is not None:
        # metrics-only source: flat serve gauges, no per-model breakdown
        lines.append(
            c(DIM, "  serving")
            + f"  p50 {state.gauge('serve/p50_ms') or 0.0:.2f}ms"
            + f"  p99 {state.gauge('serve/p99_ms') or 0.0:.2f}ms"
            + f"  fill {state.gauge('serve/batch_fill') or 0.0:.2f}"
            + f"  miss {100.0 * (state.gauge('serve/deadline_miss_rate') or 0.0):.1f}%"
        )
    # trace recorder health: span/drop counts from either source (the
    # health doc's "trace" block, or the lgbtpu_trace_* counters)
    trace_doc = (state.health or {}).get("trace") or {}
    spans_total = trace_doc.get(
        "spans_total", state.metrics.get("lgbtpu_trace_spans_total")
    )
    if spans_total is not None:
        dropped = trace_doc.get(
            "dropped_total",
            state.metrics.get("lgbtpu_trace_dropped_total", 0.0),
        )
        ring = trace_doc.get("ring")
        cap = trace_doc.get("capacity")
        lines.append(
            c(DIM, "  trace")
            + f"  spans {int(spans_total)}"
            + (f"  ring {int(ring)}/{int(cap)}" if ring is not None else "")
            + f"  dropped {int(dropped or 0)}"
        )
    lines.append(
        c(DIM, f"  alerts (last {len(state.alerts)})")
        if state.alerts
        else c(DIM, "  alerts: none")
    )
    for a in list(state.alerts)[-8:]:
        sev = str(a.get("severity", "warn"))
        lines.append(
            "    "
            + c(_STATUS_COLOR.get(sev, YELLOW), f"[{sev}]")
            + f" it{a.get('iter', '?')} {a.get('rule', '?')}: "
            + str(a.get("message", ""))[: max(10, width - 30)]
        )
    return "\n".join(line[: width + 24] for line in lines) + "\n"


# ------------------------------------------------------------- data sources


def _fetch(url: str, timeout: float) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


def poll_endpoint(state: TopState, base: str, timeout: float = 2.0) -> None:
    base = base.rstrip("/")
    try:
        metrics = parse_prometheus(
            _fetch(base + "/metrics", timeout).decode("utf-8")
        )
        try:
            health = json.loads(_fetch(base + "/healthz", timeout))
        except Exception:
            health = None
        state.update_from_metrics(metrics, health)
    except Exception as e:  # endpoint gone == run finished; keep last frame
        state.error = str(e)


class JsonlTail:
    """Incremental reader for an append-only telemetry JSONL file."""

    def __init__(self, path: str, from_start: bool = False) -> None:
        self.path = path
        self._pos = 0
        if not from_start:
            try:
                import os

                self._pos = os.path.getsize(path)
            except OSError:
                self._pos = 0

    def read_new(self) -> List[Dict[str, Any]]:
        events: List[Dict[str, Any]] = []
        try:
            with open(self.path) as fp:
                fp.seek(self._pos)
                for line in fp:
                    if not line.endswith("\n"):
                        break  # partial trailing write; re-read next poll
                    self._pos += len(line)
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        events.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue
        except OSError:
            pass
        return events


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="live dashboard for lightgbm_tpu training telemetry"
    )
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument(
        "--endpoint", help="metrics exporter base URL (obs_export_port)"
    )
    src.add_argument("--tail", help="telemetry JSONL file to follow")
    ap.add_argument(
        "--interval", type=float, default=1.0, help="refresh seconds"
    )
    ap.add_argument(
        "--from-start",
        action="store_true",
        help="with --tail, consume the whole file instead of only new lines",
    )
    ap.add_argument(
        "--once", action="store_true", help="render one frame and exit"
    )
    ap.add_argument("--no-color", action="store_true")
    ap.add_argument("--width", type=int, default=78)
    args = ap.parse_args(argv)

    state = TopState()
    tail: Optional[JsonlTail] = None
    if args.tail:
        state.source = f"tail:{args.tail}"
        # --once over a file only makes sense from the start
        tail = JsonlTail(args.tail, from_start=args.from_start or args.once)
    else:
        state.source = f"endpoint:{args.endpoint}"

    color = not args.no_color and sys.stdout.isatty()
    try:
        while True:
            if tail is not None:
                state.update_from_events(tail.read_new())
            else:
                poll_endpoint(state, args.endpoint)
            frame = render_frame(state, width=args.width, color=color)
            if args.once:
                sys.stdout.write(frame)
                return 0
            sys.stdout.write(CLEAR + frame)
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        sys.stdout.write("\n")
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
