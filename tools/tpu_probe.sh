#!/bin/bash
# Probe the axon TPU tunnel every 3 minutes; touch /tmp/tpu_up when alive.
# The FIRST time the tunnel comes up, immediately run the round-4
# measurement program (tools/perf_r4.py all — crash-tolerant, appends to
# tools/PERF_R4_RESULTS.md) so a brief tunnel window still captures the
# headline numbers. Logs to /tmp/tpu_probe.log.
cd /root/repo || exit 1
while true; do
  if timeout 90 python -c "import jax; d=jax.devices(); assert d[0].platform=='tpu'" 2>/dev/null; then
    date -u +"%FT%TZ up" >> /tmp/tpu_probe.log
    touch /tmp/tpu_up
    if [ ! -f /tmp/perf_r4_done ]; then
      date -u +"%FT%TZ launching perf_r4" >> /tmp/tpu_probe.log
      PYTHONPATH=/root/repo timeout 5400 python tools/perf_r4.py all \
        >> /tmp/perf_r4.log 2>&1
      rc=$?
      date -u +"%FT%TZ perf_r4 done rc=$rc" >> /tmp/tpu_probe.log
      # mark done only on success: a tunnel flap mid-run retries next time
      # it comes up (individual steps are idempotent and append results)
      if [ "$rc" -eq 0 ]; then
        touch /tmp/perf_r4_done
      fi
    fi
  else
    date -u +"%FT%TZ down" >> /tmp/tpu_probe.log
    rm -f /tmp/tpu_up
  fi
  sleep 180
done
