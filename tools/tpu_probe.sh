#!/bin/bash
# Probe the axon TPU tunnel every 3 minutes; touch /tmp/tpu_up when alive.
# Runs until killed. Logs to /tmp/tpu_probe.log.
while true; do
  if timeout 90 python -c "import jax; d=jax.devices(); assert d[0].platform=='tpu'" 2>/dev/null; then
    date -u +"%FT%TZ up" >> /tmp/tpu_probe.log
    touch /tmp/tpu_up
  else
    date -u +"%FT%TZ down" >> /tmp/tpu_probe.log
    rm -f /tmp/tpu_up
  fi
  sleep 180
done
