#!/bin/bash
# Probe the axon TPU tunnel every 3 minutes; touch /tmp/tpu_up when alive.
# The FIRST time the tunnel comes up, immediately run the measurement
# program (tools/perf_r4.py all — crash-tolerant, appends to
# tools/PERF_R4_RESULTS.md), then bench.py (the driver artifact's number)
# and the native_tpu pytest tier, so a brief tunnel window still captures
# the headline numbers.  Logs to /tmp/tpu_probe.log.
cd /root/repo || exit 1
while true; do
  if timeout 90 python -c "import jax; d=jax.devices(); assert d[0].platform=='tpu'" 2>/dev/null; then
    date -u +"%FT%TZ up" >> /tmp/tpu_probe.log
    touch /tmp/tpu_up
    if [ ! -f /tmp/perf_r5_done ]; then
      date -u +"%FT%TZ launching perf_r4" >> /tmp/tpu_probe.log
      PYTHONPATH=/root/repo timeout 7200 python tools/perf_r4.py all \
        >> /tmp/perf_r4.log 2>&1
      rc=$?
      date -u +"%FT%TZ perf_r4 done rc=$rc" >> /tmp/tpu_probe.log
      date -u +"%FT%TZ launching bench.py" >> /tmp/tpu_probe.log
      timeout 3600 python bench.py > /tmp/bench_tpu.json 2>/tmp/bench_tpu.err
      brc=$?
      date -u +"%FT%TZ bench done rc=$brc ($(tail -c 200 /tmp/bench_tpu.json))" >> /tmp/tpu_probe.log
      date -u +"%FT%TZ launching native_tpu tier" >> /tmp/tpu_probe.log
      LGBM_TPU_NATIVE=1 timeout 3600 python -m pytest tests -m native_tpu -q \
        > /tmp/native_tier.log 2>&1
      nrc=$?
      date -u +"%FT%TZ native tier done rc=$nrc ($(tail -n 1 /tmp/native_tier.log))" >> /tmp/tpu_probe.log
      # mark done only when ALL THREE stages succeeded: a tunnel flap
      # mid-run retries the whole block next time it comes up (steps are
      # idempotent and append results)
      if [ "$rc" -eq 0 ] && [ "$brc" -eq 0 ] && [ "$nrc" -eq 0 ]; then
        touch /tmp/perf_r5_done
      fi
    fi
  else
    date -u +"%FT%TZ down" >> /tmp/tpu_probe.log
    rm -f /tmp/tpu_up
  fi
  sleep 180
done
