"""Same-machine reference-CLI benchmark on bench.py's exact workload.

    python tools/ref_bench.py /path/to/lightgbm-cli [rows]

BASELINE.md's 3.8 iters/s was measured on a 16-core Xeon; this sandbox
has ONE core, so cross-machine comparison is meaningless.  This script
runs the REFERENCE on the identical synthetic workload bench.py uses
(same rng seed, shapes, params), on THIS machine, so the driver's
cpu-fallback number finally has a denominator measured under the same
conditions.  Marginal-rep: wall(num_trees=N2) - wall(num_trees=N1)
over N2-N1 iterations cancels data loading/binning.
"""

import subprocess
import sys
import tempfile
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

CONF = """task = train
objective = binary
data = train.csv
label_column = 0
num_leaves = 255
max_bin = 255
learning_rate = 0.1
min_data_in_leaf = 100
metric = none
num_threads = {threads}
num_trees = {trees}
verbosity = -1
output_model = model.txt
"""


def run(cli, work, trees, threads):
    (work / "train.conf").write_text(CONF.format(trees=trees, threads=threads))
    t0 = time.perf_counter()
    p = subprocess.run(
        [cli, "config=train.conf"], cwd=work, capture_output=True, text=True
    )
    dt = time.perf_counter() - t0
    if p.returncode != 0:
        raise RuntimeError(p.stdout + p.stderr)
    return dt


def main(cli, rows=1_000_000):
    cli = str(Path(cli).resolve())
    from bench import _make_data  # identical data: same seed and shapes

    X, y = _make_data(rows, 28)
    with tempfile.TemporaryDirectory() as td:
        work = Path(td)
        arr = np.column_stack([y, X.astype(np.float64)])
        np.savetxt(work / "train.csv", arr, delimiter=",", fmt="%.7g")
        n1, n2, threads = 2, 12, 1
        t_small = run(cli, work, n1, threads)
        t_big = run(cli, work, n2, threads)
        per = (t_big - t_small) / (n2 - n1)
        print(
            f"reference CLI @{rows} rows, num_threads={threads}: "
            f"{1.0 / per:.4f} iters/s ({per * 1e3:.0f} ms/iter; "
            f"{n1}-tree run {t_small:.1f}s incl. load+bin)"
        )


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]) if len(sys.argv) > 2 else 1_000_000)
