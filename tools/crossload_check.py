"""Two-way model-file compatibility check against a built reference CLI.

    python tools/crossload_check.py /path/to/lightgbm-cli

For several model classes (numeric+NaN regression, binary, multiclass,
integer categorical, gain importances), trains OUR booster, saves the
model file, has the REFERENCE CLI predict with it on the same data, and
compares against our predictions.  This is the direction the in-repo
golden tests cannot cover (they cross-load reference files into us);
round-4 ADVICE found a real bug in this direction (the
pandas_categorical trailer shape), so every release-shaped change to
model_to_string should re-run this when a reference binary is around.

Results print per case; exit 0 = all match.
"""

import subprocess
import sys
import tempfile
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def ref_predict(cli, model_text, X, workdir):
    work = Path(workdir)
    (work / "model.txt").write_text(model_text)
    np.savetxt(work / "data.csv", X, delimiter=",", fmt="%.10g")
    (work / "pred.conf").write_text(
        "task = predict\ndata = data.csv\ninput_model = model.txt\n"
        "output_result = preds.txt\npredict_disable_shape_check = true\n"
        "header = false\n"
    )
    p = subprocess.run(
        [cli, "config=pred.conf"], cwd=work, capture_output=True, text=True
    )
    if p.returncode != 0:
        raise RuntimeError(p.stdout + p.stderr)
    return np.loadtxt(work / "preds.txt", ndmin=1)


def main(cli):
    cli = str(Path(cli).resolve())  # subprocess cwd changes; pin the binary
    import jax

    jax.config.update("jax_platforms", "cpu")
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(0)
    failures = []

    def check(name, booster, X, ours, atol=1e-6, rtol=1e-5):
        # a crash (CLI rejecting the file, shape mismatch) IS the bug
        # class this tool hunts — record it as FAIL, keep going
        try:
            with tempfile.TemporaryDirectory() as td:
                got = ref_predict(cli, booster.model_to_string(), X, td)
            if got.size != ours.size:
                raise ValueError(
                    f"shape mismatch: ref {got.shape} vs ours {ours.shape}"
                )
            got = got.reshape(ours.shape)
            ok = np.allclose(got, ours, atol=atol, rtol=rtol)
            detail = f"max diff {np.abs(got - ours).max():.2e}"
        except Exception as e:
            ok, detail = False, f"{type(e).__name__}: {e}"
        print(f"{'OK  ' if ok else 'FAIL'} {name}: {detail}")
        if not ok:
            failures.append(name)

    # 1. regression with NaNs (missing-direction encoding)
    X = rng.normal(size=(1500, 6))
    X[::7, 2] = np.nan
    y = np.where(np.isnan(X[:, 2]), 1.5, X[:, 0]) + 0.3 * X[:, 1]
    p = {"objective": "regression", "verbosity": -1, "num_leaves": 31}
    b = lgb.train(p, lgb.Dataset(X, y), 10)
    check("regression+nan", b, X, b.predict(X))

    # 2. binary (sigmoid transform encoding)
    yb = (y > y.mean()).astype(float)
    b2 = lgb.train({**p, "objective": "binary"}, lgb.Dataset(X, yb), 10)
    check("binary", b2, X, b2.predict(X))

    # 3. multiclass (per-class trees interleave)
    ym = np.digitize(y, np.quantile(y, [0.33, 0.66]))
    b3 = lgb.train(
        {**p, "objective": "multiclass", "num_class": 3},
        lgb.Dataset(X, ym), 10,
    )
    check("multiclass", b3, X, b3.predict(X))

    # 4. integer categorical (cat_threshold bitset encoding)
    Xc = np.column_stack([
        rng.integers(0, 12, size=2000).astype(float),
        rng.normal(size=2000),
    ])
    yc = np.where(np.isin(Xc[:, 0], [2, 5, 7]), 2.0, 0.0) + 0.2 * Xc[:, 1]
    pc = {"objective": "regression", "verbosity": -1, "num_leaves": 15,
          "min_data_per_group": 1, "max_cat_to_onehot": 1}
    b4 = lgb.train(
        pc, lgb.Dataset(Xc, yc, categorical_feature=[0]), 10
    )
    check("categorical", b4, Xc, b4.predict(Xc))

    # 5. gain importances in the file must not break the reference loader
    b5 = lgb.train(
        {**p, "saved_feature_importance_type": 1}, lgb.Dataset(X, y), 5
    )
    check("gain-importances-file", b5, X, b5.predict(X))

    print(f"\n{5 - len(failures)}/5 cross-load cases match")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1]))
