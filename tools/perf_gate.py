"""Deterministic perf-contract gate.

Collects the DETERMINISTIC slice of the telemetry surface from three fixed
scenarios (serial train + streaming predict with executable accounting; an
8-virtual-device ``tree_learner=data`` dryrun with measured collectives) and
diffs it against the committed contract ``tools/perf_contract.json``:

* ``retrace/*``          jit trace counts by label        — HARD, tolerance 0
* ``collective/analytic_*`` modeled psum bytes            — HARD, tolerance 0
* ``collective/measured_*`` timed-wrapper psum bytes      — HARD, small rel tol
* ``cost/*``             executable FLOPs / bytes accessed — HARD, rel tol
* ``memory/*``           executable temp/output bytes      — HARD, rel tol
* ``wall/*``             scenario wall times               — SOFT, warn only

A failing hard metric means a real perf-shape regression (a retrace storm, a
collective that grew, an executable whose footprint jumped) — not noise: all
hard metrics are shape/trace-derived, so reruns on one machine agree exactly
(within the stated tolerance for XLA-version wobble on cost/memory).

Usage:
    python tools/perf_gate.py                      # collect + check
    python tools/perf_gate.py --update --justify "why each change is OK"
    python tools/perf_gate.py --out metrics.json   # also dump collected
    python tools/perf_gate.py --replay metrics.json  # check a prior dump
                                                     # (no jax needed)

``--update`` rewrites the contract; every metric whose value changed (or is
new) records the ``--justify`` line, so the contract file carries the audit
trail of accepted drifts.  Wired as a hard gate in tools/run_tests.sh.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
from typing import Any, Dict, Optional, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_CONTRACT = os.path.join(REPO_ROOT, "tools", "perf_contract.json")

# metric-kind policy: (hard, tol_rel, tol_abs) chosen by name prefix.  Order
# matters: first match wins.
_POLICIES: Tuple[Tuple[str, Tuple[bool, float, float]], ...] = (
    ("retrace/", (True, 0.0, 0.0)),
    ("collective/analytic_", (True, 0.0, 0.0)),
    # measured bytes are shape-exact per call; the small slack absorbs an
    # extra scalar psum if a trace-level refactor adds/removes one
    ("collective/measured_", (True, 0.05, 64.0)),
    ("cost/", (True, 0.10, 0.0)),
    ("memory/", (True, 0.25, 0.0)),
    ("wall/", (False, 0.5, 50.0)),
)


def policy_for(name: str) -> Tuple[bool, float, float]:
    for prefix, pol in _POLICIES:
        if name.startswith(prefix):
            return pol
    return (True, 0.0, 0.0)


# ---------------------------------------------------------------- scenarios
def _env_for_collect() -> None:
    """Pin the jax environment BEFORE the first import: CPU platform, an
    8-device virtual mesh (same flags as tests/conftest.py), persistent
    compile cache (compile caching never changes trace counts)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def collect() -> Dict[str, float]:
    """Run the fixed scenarios and return the metric map."""
    import time

    _env_for_collect()
    import numpy as np
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass

    if REPO_ROOT not in sys.path:  # `python tools/perf_gate.py` from anywhere
        sys.path.insert(0, REPO_ROOT)
    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs.jit import compile_counts_by_label
    from lightgbm_tpu.obs.registry import get_session

    metrics: Dict[str, float] = {}
    rng = np.random.RandomState(7)
    X = rng.rand(512, 10).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] + 0.1 * rng.rand(512)).astype(np.float32)

    base = {
        "objective": "regression",
        "num_leaves": 7,
        "max_bin": 63,
        "min_data_in_leaf": 5,
        "learning_rate": 0.2,
        "verbosity": -1,
        "telemetry": True,
        "deterministic": True,
        "seed": 11,
    }

    # -- scenario 1: serial train + streaming predict, device accounting on
    ses = get_session()
    ses.reset()
    labels_before = compile_counts_by_label()
    t0 = time.perf_counter()
    booster = lgb.train(
        {**base, "obs_device_accounting": True},
        lgb.Dataset(X, label=y, params=base),
        num_boost_round=3,
    )
    booster.predict(X)
    # tensor-forest engine on the same (eligible) model: pins the
    # predict/stream/tensor retrace labels + the matmul executables'
    # cost/memory accounting into the contract next to the walker's
    booster.predict(X, pred_engine="matmul")
    metrics["wall/serial_train_s"] = round(time.perf_counter() - t0, 3)
    labels_after = compile_counts_by_label()
    for label, count in sorted(labels_after.items()):
        delta = count - labels_before.get(label, 0)
        if delta:
            metrics[f"retrace/serial/{label}"] = float(delta)
    tel = booster.telemetry()
    for name, value in sorted(tel["gauges"].items()):
        # executable accounting: FLOPs + temp footprint per jit label (the
        # other cost/memory keys ride in telemetry but would double the
        # contract surface without adding signal)
        if name.startswith("cost/") and name.endswith("/flops"):
            metrics[name] = float(value)
        if name.startswith("memory/") and name.endswith("/temp_bytes"):
            metrics[name] = float(value)
        # GL013 donation wiring: per-entry HBM handed back to the allocator
        # (lowering-level args_info, exact on CPU too) — frozen so a lost
        # donate_argnums shows up as a hard contract diff
        if name.startswith("memory/") and name.endswith("/donated_bytes"):
            metrics[name] = float(value)

    # -- scenario 1b: streaming ingest — the SAME data/params built through
    # the chunked two-pass pipeline.  A one-shot train warms the jit cache
    # first, so retrace/ingest_total pins how many device programs the
    # streamed build adds over one-shot (the pipeline is host-side and the
    # packed planes are bit-identical, so the expected answer is zero and
    # any drift means the streamed path started tracing its own programs).
    # The chunk count and packed-plane footprint are analytic in (rows,
    # chunk_rows, layout), so they freeze as hard cost metrics.
    lgb.train(base, lgb.Dataset(X, label=y, params=base), num_boost_round=3)
    ing = {**base, "ingest_chunk_rows": 128}
    ses.reset()
    ses.configure(enabled=True)
    labels_before = compile_counts_by_label()
    t0 = time.perf_counter()
    dtrain = lgb.Dataset(X, label=y, params=ing).construct()
    lgb.train(ing, dtrain, num_boost_round=3)
    metrics["wall/ingest_train_s"] = round(time.perf_counter() - t0, 3)
    labels_after = compile_counts_by_label()
    metrics["retrace/ingest_total"] = float(
        sum(labels_after.values()) - sum(labels_before.values())
    )
    metrics["cost/ingest/chunks_total"] = float(
        ses.gauges.get("ingest/chunks_total", 0.0)
    )
    metrics["cost/ingest/bin_plane_bytes"] = float(
        np.asarray(dtrain.bins).nbytes
    )

    # -- scenario 2: 8-device data-parallel dryrun, measured collectives
    ndev = len(jax.devices("cpu"))
    if ndev >= 8:
        ses.reset()
        labels_before = compile_counts_by_label()
        t0 = time.perf_counter()
        lgb.train(
            {**base, "tree_learner": "data"},
            lgb.Dataset(X, label=y, params=base),
            num_boost_round=3,
        )
        metrics["wall/data_parallel_train_s"] = round(
            time.perf_counter() - t0, 3
        )
        labels_after = compile_counts_by_label()
        for label, count in sorted(labels_after.items()):
            delta = count - labels_before.get(label, 0)
            if delta:
                metrics[f"retrace/data_parallel/{label}"] = float(delta)
        iters = [
            e for e in ses.events if e.get("event") == "iteration"
        ]
        analytic = sum(
            float(e["collective"]["hist_bytes"])
            + float(e["collective"]["count_bytes"])
            for e in iters
            if "collective" in e
        )
        measured = sum(
            float(e["collective_measured"]["psum_bytes"])
            for e in iters
            if "collective_measured" in e
        )
        if analytic:
            metrics["collective/analytic_bytes"] = analytic
        if measured:
            metrics["collective/measured_psum_bytes"] = round(measured, 1)

        # -- scenario 3: quantized data-parallel train — pins the
        # quantized-training path (int grid + RenewIntGradTreeOutput) into
        # the retrace contract now that its leaf-stat psums route through
        # the timed wrappers (GL007's every-site-is-measured invariant)
        ses.reset()
        labels_before = compile_counts_by_label()
        t0 = time.perf_counter()
        lgb.train(
            {
                **base,
                "tree_learner": "data",
                "use_quantized_grad": True,
                "quant_train_renew_leaf": True,
            },
            lgb.Dataset(X, label=y, params=base),
            num_boost_round=3,
        )
        metrics["wall/quant_data_parallel_train_s"] = round(
            time.perf_counter() - t0, 3
        )
        labels_after = compile_counts_by_label()
        for label, count in sorted(labels_after.items()):
            delta = count - labels_before.get(label, 0)
            if delta:
                metrics[f"retrace/quant_data_parallel/{label}"] = float(delta)

        # -- scenario 4: hybrid (data×feature) 2-D mesh layout — the
        # named-mesh scale-out path (parallel/mesh.py).  10 features on 8
        # devices factorizes to a (4, 2) mesh: histogram/count psums over
        # 'data' on half-width feature slices, winner election over
        # 'feature'.  Pins the 2-D layout's retrace count and its
        # analytic-vs-measured collective bytes into the contract.
        ses.reset()
        labels_before = compile_counts_by_label()
        t0 = time.perf_counter()
        hyb = lgb.train(
            {**base, "tree_learner": "data", "mesh_layout": "hybrid"},
            lgb.Dataset(X, label=y, params=base),
            num_boost_round=3,
        )
        metrics["wall/hybrid_train_s"] = round(time.perf_counter() - t0, 3)
        spec = getattr(hyb, "_mesh_spec", None)
        assert spec is not None and spec.feature > 1, (
            "hybrid scenario did not form a 2-D mesh"
        )
        labels_after = compile_counts_by_label()
        for label, count in sorted(labels_after.items()):
            delta = count - labels_before.get(label, 0)
            if delta:
                metrics[f"retrace/hybrid/{label}"] = float(delta)
        iters = [
            e for e in ses.events if e.get("event") == "iteration"
        ]
        analytic = sum(
            float(e["collective"]["psum_bytes"])
            for e in iters
            if "collective" in e
        )
        measured = sum(
            float(e["collective_measured"]["psum_bytes"])
            for e in iters
            if "collective_measured" in e
        )
        # named to ride the existing policy prefixes: analytic exact,
        # measured with the scalar-psum slack
        if analytic:
            metrics["collective/analytic_hybrid_bytes"] = analytic
        if measured:
            metrics["collective/measured_hybrid_psum_bytes"] = round(
                measured, 1
            )

        # -- scenario 5: M=4 model fleet on the data mesh — ONE vmapped
        # grow executable serves the whole fleet, so retrace/fleet/* is
        # frozen at 1 compile per label, and the per-iteration psums
        # collapse into one stacked [M, K, F, B, 3] payload (the analytic
        # fleet model from parallel.mesh.fleet_psum_bytes_per_iteration,
        # surfaced through the fleet/psum_* gauges FleetTrainer sets)
        ses.reset()
        labels_before = compile_counts_by_label()
        t0 = time.perf_counter()
        lgb.train_fleet(
            [
                {**base, "tree_learner": "data", "seed": 11 + i}
                for i in range(4)
            ],
            lgb.Dataset(X, label=y, params=base),
            num_boost_round=3,
        )
        metrics["wall/fleet_train_s"] = round(time.perf_counter() - t0, 3)
        labels_after = compile_counts_by_label()
        for label, count in sorted(labels_after.items()):
            delta = count - labels_before.get(label, 0)
            if delta:
                metrics[f"retrace/fleet/{label}"] = float(delta)
        fleet_analytic = float(
            ses.gauges.get("fleet/psum_hist_bytes", 0.0)
        ) + float(ses.gauges.get("fleet/psum_count_bytes", 0.0))
        if fleet_analytic:
            metrics["collective/analytic_fleet_bytes"] = fleet_analytic

        # -- scenario 6: device-resident boosting on the data mesh — 6
        # iterations as 3 compiled launches (train_steps_per_launch=2).
        # The scan executable label grow/scan2 is frozen at EXACTLY 1
        # compile (a second trace would mean the warm launch re-specializes
        # per window — the regression this feature exists to prevent), and
        # the analytic per-launch collective bytes freeze the launch factor
        # in mesh_psum_bytes_per_iteration (each launch moves launch_steps×
        # the per-iteration psum payload; the scan body contains each psum
        # site once)
        ses.reset()
        ses.configure(enabled=True)
        labels_before = compile_counts_by_label()
        t0 = time.perf_counter()
        lgb.train(
            {**base, "tree_learner": "data", "train_steps_per_launch": 2},
            lgb.Dataset(X, label=y, params=base),
            num_boost_round=6,
        )
        metrics["wall/launch_train_s"] = round(time.perf_counter() - t0, 3)
        labels_after = compile_counts_by_label()
        for label, count in sorted(labels_after.items()):
            delta = count - labels_before.get(label, 0)
            if delta:
                metrics[f"retrace/launch/{label}"] = float(delta)
        launch_analytic = sum(
            float(e["collective"]["psum_bytes"])
            for e in ses.events
            if e.get("event") == "launch" and "collective" in e
        )
        if launch_analytic:
            metrics["collective/analytic_launch_bytes"] = launch_analytic
        metrics["cost/launch/steps_per_launch_effective"] = float(
            ses.gauges.get("train/steps_per_launch_effective", 0.0)
        )
    else:  # pragma: no cover - CI always has the virtual mesh
        print(
            f"perf_gate: only {ndev} cpu devices; skipping the "
            "data-parallel scenario",
            file=sys.stderr,
        )
    ses.reset()
    return metrics


# ------------------------------------------------------------ contract I/O
def load_contract(path: str) -> Optional[Dict[str, Any]]:
    if not os.path.exists(path):
        return None
    with open(path) as fp:
        return json.load(fp)


def build_contract(
    metrics: Dict[str, float],
    prior: Optional[Dict[str, Any]],
    justify: str,
) -> Dict[str, Any]:
    """New contract from collected metrics; changed/new metrics carry the
    justification line, unchanged ones keep their prior one."""
    out: Dict[str, Any] = {"version": 1, "metrics": {}}
    prior_metrics = (prior or {}).get("metrics", {})
    for name, value in sorted(metrics.items()):
        hard, tol_rel, tol_abs = policy_for(name)
        entry: Dict[str, Any] = {
            "value": value,
            "hard": hard,
            "tol_rel": tol_rel,
            "tol_abs": tol_abs,
        }
        old = prior_metrics.get(name)
        if old is not None and float(old.get("value", math.nan)) == value:
            if old.get("justification"):
                entry["justification"] = old["justification"]
        else:
            entry["justification"] = justify
        out["metrics"][name] = entry
    return out


def check(
    metrics: Dict[str, float], contract: Dict[str, Any]
) -> Tuple[int, int]:
    """Diff metrics against the contract; prints findings.  Returns
    (hard_failures, warnings)."""
    failures = warnings = 0
    cmetrics = contract.get("metrics", {})
    for name, entry in sorted(cmetrics.items()):
        expect = float(entry["value"])
        hard = bool(entry.get("hard", policy_for(name)[0]))
        tol_rel = float(entry.get("tol_rel", 0.0))
        tol_abs = float(entry.get("tol_abs", 0.0))
        got = metrics.get(name)
        if got is None:
            if name.startswith("wall/") or not hard:
                continue
            print(f"FAIL {name}: expected {expect}, metric missing")
            failures += 1
            continue
        tol = tol_abs + tol_rel * abs(expect)
        if abs(got - expect) <= tol:
            continue
        line = (
            f"{name}: expected {expect} ±{tol:g}, got {got} "
            f"(drift {got - expect:+g})"
        )
        if hard:
            print(f"FAIL {line}")
            failures += 1
        else:
            print(f"WARN {line}")
            warnings += 1
    for name in sorted(set(metrics) - set(cmetrics)):
        if policy_for(name)[0]:
            print(
                f"WARN {name}: not in contract (value {metrics[name]}); "
                "run --update to freeze it"
            )
            warnings += 1
    return failures, warnings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="deterministic perf-contract gate"
    )
    ap.add_argument("--contract", default=DEFAULT_CONTRACT)
    ap.add_argument(
        "--update",
        action="store_true",
        help="rewrite the contract from collected metrics",
    )
    ap.add_argument(
        "--justify",
        default="",
        help="justification recorded on every changed metric (--update)",
    )
    ap.add_argument(
        "--out", default="", help="also dump collected metrics to this path"
    )
    ap.add_argument(
        "--replay",
        default="",
        help="check a prior metrics dump instead of running the scenarios",
    )
    args = ap.parse_args(argv)

    if args.replay:
        with open(args.replay) as fp:
            metrics = {k: float(v) for k, v in json.load(fp).items()}
    else:
        metrics = collect()
    if args.out:
        with open(args.out, "w") as fp:
            json.dump(metrics, fp, indent=2, sort_keys=True)
            fp.write("\n")

    contract = load_contract(args.contract)
    if args.update:
        if contract is not None and not args.justify:
            changed = [
                n
                for n, e in contract.get("metrics", {}).items()
                if metrics.get(n) is not None
                and float(e["value"]) != metrics[n]
            ] + [n for n in metrics if n not in contract.get("metrics", {})]
            if changed:
                print(
                    "perf_gate: --update with changed metrics needs "
                    f"--justify (changed: {', '.join(sorted(changed)[:8])})",
                    file=sys.stderr,
                )
                return 2
        new = build_contract(
            metrics, contract, args.justify or "initial contract"
        )
        with open(args.contract, "w") as fp:
            json.dump(new, fp, indent=2, sort_keys=True)
            fp.write("\n")
        print(
            f"perf_gate: wrote {args.contract} "
            f"({len(new['metrics'])} metrics)"
        )
        return 0

    if contract is None:
        print(
            f"perf_gate: no contract at {args.contract}; run with --update "
            "to create it",
            file=sys.stderr,
        )
        return 2
    failures, warnings = check(metrics, contract)
    print(
        f"perf_gate: {len(contract.get('metrics', {}))} contract metrics, "
        f"{failures} hard failure(s), {warnings} warning(s)"
    )
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
