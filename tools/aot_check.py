"""Deviceless AOT Mosaic-compile check for the flagship Pallas kernels.

The axon tunnel can be down for whole rounds (rounds 3-4 shipped kernels
that Mosaic had never seen).  libtpu is installed locally, so
`jax.experimental.topologies.get_topology_desc` + ``.lower().compile()``
can drive the real Mosaic/XLA:TPU compiler WITHOUT hardware — a
layout/lowering rejection shows up here instead of at the first
tunnel-up moment.  Reference analog for what's at stake:
cuda_data_partition.cu:290-937, cuda_best_split_finder.cu:776.

Usage: JAX_PLATFORMS=cpu python tools/aot_check.py  (exit 0 = all compile)
"""

import os
import sys
import traceback

# Standalone runs stay off the (possibly dead) tunnel; under pytest the
# conftest owns platform selection — setting it here would run before the
# LGBM_TPU_NATIVE=1 native tier sees the real chip and silently skip it.
if "PYTEST_CURRENT_TEST" not in os.environ and __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import topologies
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from lightgbm_tpu.ops.pallas.seg import (  # noqa: E402
    pack_rows,  # noqa: F401  (layout doc)
    padded_rows,
    seg_hist_pallas,
    storage_lanes,
)
from lightgbm_tpu.ops.pallas.partition import seg_partition_pallas  # noqa: E402
from lightgbm_tpu.ops.pallas.histogram import histogram_pallas  # noqa: E402
from lightgbm_tpu.ops.pallas.histogram_int8 import histogram_pallas_int8  # noqa: E402


def _topo():
    return topologies.get_topology_desc(platform="tpu", topology_name="v5e:2x2")


def compile_on_topo(topo, fn, *args, **static):
    """AOT-compile fn(*args, **static) for one abstract TPU device."""
    mesh = Mesh(np.array(topo.devices[:1]), ("d",))
    sh = NamedSharding(mesh, P())

    def call(*a):
        return fn(*a, **static)

    lowered = jax.jit(call, in_shardings=[sh] * len(args)).lower(*args)
    return lowered.compile()


def s(shape, dtype):
    return jax.ShapeDtypeStruct(shape, dtype)


CHECKS = {}


def check(name):
    def deco(f):
        CHECKS[name] = f
        return f

    return deco


@check("histogram_pallas bf16 hi/lo (n=1000,f=28,b=256)")
def _c1(topo):
    return compile_on_topo(
        topo, histogram_pallas,
        s((1000, 28), jnp.int32), s((1000,), jnp.float32),
        s((1000,), jnp.float32), s((1000,), jnp.float32), num_bins=256,
    )


@check("seg_hist_pallas f=28 b=256")
def _c2(topo):
    n_pad = padded_rows(5000)
    return compile_on_topo(
        topo, seg_hist_pallas,
        s((storage_lanes(28), n_pad), jnp.int16), s((2,), jnp.int32),
        f=28, num_bins=256, n_pad=n_pad,
    )


@check("seg_hist_pallas int8 quantized f=28 b=256")
def _c3(topo):
    n_pad = padded_rows(5000)
    return compile_on_topo(
        topo, seg_hist_pallas,
        s((storage_lanes(28), n_pad), jnp.int16), s((2,), jnp.int32),
        s((2,), jnp.float32),
        f=28, num_bins=256, n_pad=n_pad, quantized=True,
    )


@check("seg_hist_pallas u16 wide f=4 b=1024")
def _c4(topo):
    n_pad = padded_rows(5000)
    return compile_on_topo(
        topo, seg_hist_pallas,
        s((storage_lanes(4, wide=True), n_pad), jnp.int16),
        s((2,), jnp.int32),
        f=4, num_bins=1024, n_pad=n_pad, wide=True,
    )


@check("histogram_pallas_int8 grid (n=1200,f=30,b=255)")
def _c5(topo):
    n = 1200

    def call(bins, g, h, m, gs, hs):
        return histogram_pallas_int8(bins, g, h, m, 255, gs, hs)

    return compile_on_topo(
        topo, call,
        s((n, 30), jnp.int32), s((n,), jnp.float32), s((n,), jnp.float32),
        s((n,), jnp.float32), s((), jnp.float32), s((), jnp.float32),
    )


@check("seg_partition_pallas column-read f=28")
def _c6(topo):
    n_pad = padded_rows(5000)
    return compile_on_topo(
        topo, seg_partition_pallas,
        s((storage_lanes(28), n_pad), jnp.int16), s((8,), jnp.int32),
        s((1, 256), jnp.float32),
        f=28, n_pad=n_pad, use_cat=True,
    )


@check("seg_partition_pallas bits-fed (gl_vec) f=28")
def _c7(topo):
    n_pad = padded_rows(5000)
    return compile_on_topo(
        topo, seg_partition_pallas,
        s((storage_lanes(28), n_pad), jnp.int16), s((8,), jnp.int32),
        s((1, 256), jnp.float32), s((n_pad,), jnp.float32),
        f=28, n_pad=n_pad, use_cat=False,
    )


@check("seg_partition_pallas u16 wide f=4")
def _c8(topo):
    n_pad = padded_rows(5000)
    return compile_on_topo(
        topo, seg_partition_pallas,
        s((storage_lanes(4, wide=True), n_pad), jnp.int16),
        s((8,), jnp.int32), s((1, 1024), jnp.float32),
        f=4, n_pad=n_pad, use_cat=True, wide=True,
    )


@check("split_scan fused best-split (F=28, B=256)")
def _c10(topo):
    from lightgbm_tpu.ops.pallas.split_scan import split_scan_pallas

    return compile_on_topo(
        topo, split_scan_pallas,
        s((28, 256, 3), jnp.float32), s((3,), jnp.float32),
        s((28,), jnp.int32), s((28,), jnp.int32), s((28,), jnp.float32),
        f=28, num_bins_pad=256, l1=0.1, l2=1.0, min_data=20, min_hess=1e-3,
    )


@check("forest_walk predictor (T=64 trees, F=28, cat)")
def _c9(topo):
    from lightgbm_tpu.ops.pallas.forest_walk import (
        _forest_walk_jit, n_planes, CAT_WORDS,
    )

    t, h, n_tiles = 64, 2, 4
    p = n_planes(28)
    return compile_on_topo(
        topo, _forest_walk_jit,
        s((n_tiles, p, 8, 128), jnp.int32),
        s((t, h, 128), jnp.int32),
        s((t, h, 128), jnp.int32),
        s((t, h, 128), jnp.float32),
        s((t, CAT_WORDS, h, 128), jnp.int32),
        n_trees=t, max_depth=8, k=1, m_nodes=h * 128, has_cat=True,
        interpret=False,
    )


def main(selected=None):
    topo = _topo()
    failures = []
    for name, fn in CHECKS.items():
        if selected and selected not in name:
            continue
        try:
            compiled = fn(topo)
            flops = None
            try:
                ca = compiled.cost_analysis()
                ca = ca[0] if isinstance(ca, (list, tuple)) else ca
                flops = ca.get("flops") if hasattr(ca, "get") else None
            except Exception:
                pass
            print(f"OK   {name}" + (f"  (flops={flops:.3g})" if flops else ""))
        except Exception as e:
            failures.append(name)
            print(f"FAIL {name}: {type(e).__name__}")
            traceback.print_exc(limit=8)
    print(f"\n{len(CHECKS) - len(failures)}/{len(CHECKS)} kernels compile on v5e topology")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else None))
