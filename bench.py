"""Benchmark: boosting iterations/sec on a Higgs-like workload, single chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: the reference CPU trains Higgs-10.5M x 28 at ~3.8 iters/sec
(500 iters in 130.094 s, 255 leaves, 16 threads — docs/Experiments.rst:108,
see BASELINE.md).  This benchmark runs the same shape of work (binary
objective, 255 leaves, max_bin 255, 28 features) on however many rows fit a
single chip comfortably, and reports iterations/sec; vs_baseline is the ratio
against 3.8 iters/s.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _probe_accelerator(timeout_s: int = 180) -> bool:
    """Check (in a subprocess, so a hung tunnel can't wedge the bench) that
    the default JAX backend actually comes up."""
    import subprocess
    import sys

    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s,
            capture_output=True,
        )
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _make_data(n_rows: int, n_features: int):
    rng = np.random.default_rng(42)
    X = rng.normal(size=(n_rows, n_features)).astype(np.float32)
    w = rng.normal(size=n_features)
    logits = X @ w * 0.5 + rng.normal(scale=1.0, size=n_rows)
    y = (logits > 0).astype(np.float64)
    return X, y


_PARAMS = {
    "objective": "binary",
    "num_leaves": 255,
    "max_bin": 255,
    "learning_rate": 0.1,
    "min_data_in_leaf": 100,
    "verbosity": -1,
    "metric": "none",
    # best-known training config at this shape: K=4 frontier batching was
    # the round-8 sweep peak (+8% over serial); the commit-rate clamp
    # (leaf_batch_adaptive, default on) protects the tail where batching
    # over-speculates, and grow_fused='auto' rides the fused grow step on
    # the seg fast path (identical XLA composition off TPU)
    "leaf_batch": 4,
}


def _train_bench(X, y, timed_iters: int, warmup_iters: int = 2, params=None):
    """(iters/sec, booster, compile stats) for the Higgs-shaped workload."""
    import jax

    import lightgbm_tpu as lgb

    params = params or _PARAMS
    dtrain = lgb.Dataset(X, y, params=params)
    booster = lgb.Booster(params, dtrain)
    c0 = lgb.compile_count()
    for _ in range(warmup_iters):
        booster.update()
    jax.block_until_ready(booster._score)
    c_warm = lgb.compile_count()
    t0 = time.perf_counter()
    for _ in range(timed_iters):
        booster.update()
    jax.block_until_ready(booster._score)
    ips = timed_iters / (time.perf_counter() - t0)
    stats = {
        "compiles_warmup": c_warm - c0,
        "recompiles_timed": lgb.compile_count() - c_warm,
    }
    return ips, booster, stats


def _time_op(fn, *args, reps: int = 3):
    """Seconds for one jitted call (min over reps, after a compile run)."""
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    return best


def _train_phases(X, y, iters_per_sec):
    """Per-tree training-phase breakdown from the telemetry event stream.

    A short instrumented re-fit with ``telemetry`` + ``obs_sync_timing``
    (each phase blocks on its device values, so phase walls measure device
    time rather than dispatch time) yields per-iteration phase timings; the
    headline run stays uninstrumented."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs.registry import get_session

    m = min(len(y), 1_000_000)  # bound the instrumented re-fit's cost
    ses = get_session()
    ses.reset()
    params = {**_PARAMS, "telemetry": True, "obs_sync_timing": True}
    dtrain = lgb.Dataset(X[:m], y[:m], params=params)
    booster = lgb.Booster(params, dtrain)
    try:
        for _ in range(5):
            booster.update()
        events = [
            e for e in booster.telemetry()["events"]
            if e.get("event") == "iteration"
        ]
    finally:
        ses.configure(enabled=False)
        ses.reset()
    # steady state only: iterations that retraced measure compile, not run
    steady = [e for e in events if e.get("compiles_delta", 0) == 0] or events
    n = max(1, len(steady))
    phases = {}
    for e in steady:
        for k, v in e["phases"].items():
            phases[k] = phases.get(k, 0.0) + v
    out = {f"{k}_ms": round(v / n, 1) for k, v in sorted(phases.items())}
    out["tree_ms"] = round(1000.0 / iters_per_sec, 1)
    out["wall_ms"] = round(sum(e["wall_ms"] for e in steady) / n, 1)
    trees = sum(e.get("trees_materialized", 0) for e in steady)
    out["splits_per_tree"] = round(
        sum(e.get("splits", 0) for e in steady) / max(1, trees), 1
    )
    out["recompiles_after_warmup"] = sum(
        e.get("compiles_delta", 0) for e in events[2:]
    )
    out["rows"] = m
    out["note"] = (
        "telemetry event stream, obs_sync_timing on (phase walls include "
        "device time); wall_ms is the instrumented re-fit, tree_ms the "
        "headline run"
    )
    try:
        out["grow_decomposition"] = _grow_decomposition(
            booster, len(y), m, out["tree_ms"]
        )
    except Exception as e:
        out["grow_decomposition"] = {"error": repr(e)}
    try:
        out["hist_engine_sweep"] = _hist_engine_sweep(booster, m)
    except Exception as e:
        out["hist_engine_sweep"] = {"error": repr(e)}
    return out


def _grow_decomposition(booster, n_rows: int, m: int, tree_ms: float):
    """Round-8-style primitive-throughput decomposition, emitted by the
    bench itself so bookkeeping_ms stays comparable round over round.

    partition / histogram cost per steady-state tree is measured as jitted
    per-ROW throughput of proxies for the path the bench ACTUALLY ran
    (ordered mode on CPU: windowed gather -> compare -> stable sort ->
    write-back for partition, gather + segment-sum ``leaf_histogram`` for
    the smaller child) — one call at the full-data window divided by rows,
    scaled by the trained trees' actual partitioned/histogrammed row
    totals.  Timing the seg-path primitives here instead would compare a
    different (and on CPU far costlier, full-array-sort) lowering against
    the ordered headline and drive the remainder negative.
    ``bookkeeping_ms`` is the remainder of the headline tree time
    (dispatch, fusion boundaries, state writes, score updates) — the fixed
    share that ``leaf_batch`` amortizes and the fused grow step collapses.
    Separately, the fused grow step is timed against the two-launch
    seg partition+histogram pair it replaces, at the average window
    (identical XLA composition off TPU; one kernel launch on it)."""
    import functools

    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.ops.grower import _candidate_for_leaf
    from lightgbm_tpu.ops.pallas.grow_step import fused_grow_step
    from lightgbm_tpu.ops.pallas.seg import pack_rows, padded_rows, seg_hist
    from lightgbm_tpu.ops.segpart import sort_partition

    trees = [t for t in booster.models_ if t.num_leaves > 1]
    if not trees:
        return {"error": "no grown trees"}
    s_calls = part_rows = hist_rows = 0
    for t in trees:
        ic = np.asarray(t.internal_count, dtype=np.int64)
        lc = np.asarray(t.leaf_count, dtype=np.int64)

        def _cnt(ch):
            return int(ic[ch]) if ch >= 0 else int(lc[-ch - 1])

        s_calls += len(ic)
        part_rows += int(ic.sum())
        hist_rows += sum(
            min(_cnt(int(t.left_child[i])), _cnt(int(t.right_child[i])))
            for i in range(len(ic))
        )
    s_per_tree = s_calls / len(trees)
    scale = n_rows / float(m)  # headline rows vs instrumented re-fit rows
    avg_part = max(1, part_rows // s_calls)
    avg_hist = max(1, hist_rows // s_calls)

    gp = booster._grower_params
    B = int(gp.max_bin)
    wide = B > 256
    bins = booster._bins
    f_used = int(bins.shape[1])
    g = jnp.full((m,), 0.5, jnp.float32)
    h = jnp.ones((m,), jnp.float32)
    msk = jnp.ones((m,), jnp.float32)
    n_pad = padded_rows(m)
    seg = pack_rows(bins, g, h, msk, n_pad, wide=wide)
    cmv = jnp.zeros((256,), jnp.float32)
    i32 = functools.partial(jnp.asarray, dtype=jnp.int32)

    part_fn = jax.jit(
        functools.partial(sort_partition, f=f_used, n_pad=n_pad, wide=wide)
    )
    hist_fn = jax.jit(
        functools.partial(
            seg_hist, f=f_used, num_bins=B, n_pad=n_pad, wide=wide
        )
    )
    fused_fn = jax.jit(
        functools.partial(
            fused_grow_step, f=f_used, num_bins=B, n_pad=n_pad, wide=wide
        )
    )
    hist_r = jax.random.uniform(jax.random.PRNGKey(0), (f_used, B, 3))
    fm = jnp.ones((f_used,), bool)

    def scan_fn(hh):
        return _candidate_for_leaf(
            hh, jnp.float32(1.0), jnp.float32(2.0), jnp.float32(m),
            booster._num_bins, booster._nan_bins, fm, gp,
        )

    # ---- benched-path proxies (ordered mode off-TPU): one full-window
    # call each, per-row scaled by the trees' measured row totals
    from lightgbm_tpu.ops.histogram import leaf_histogram

    bins_i32 = bins.astype(jnp.int32)
    bins_pad2 = jnp.concatenate(
        [bins_i32, jnp.zeros((1, f_used), jnp.int32)], axis=0
    )
    g_pad = jnp.concatenate([g, jnp.zeros((1,), jnp.float32)])
    h_pad = jnp.concatenate([h, jnp.zeros((1,), jnp.float32)])
    m_pad = jnp.concatenate([msk, jnp.zeros((1,), jnp.float32)])
    order0 = jnp.arange(m + 1, dtype=jnp.int32)
    featrow = bins_pad2[:, 0]

    @jax.jit
    def part_proxy(order, begin, cnt, featrow, tbin):
        idx = jax.lax.dynamic_slice(order, (begin,), (m,))
        valid = jnp.arange(m, dtype=jnp.int32) < cnt
        gl = (featrow[idx] <= tbin) & valid
        perm = jnp.argsort(jnp.where(gl, 0, 1).astype(jnp.int32), stable=True)
        order = jax.lax.dynamic_update_slice(order, idx[perm], (begin,))
        return order, jnp.sum(gl)

    @jax.jit
    def hist_proxy(order):
        idx = jax.lax.dynamic_slice(order, (0,), (m,))
        return leaf_histogram(
            bins_pad2[idx], g_pad[idx], h_pad[idx], m_pad[idx], B,
            method="auto", axis_name=None,
        )

    t_part_full = _time_op(part_proxy, order0, i32(0), i32(m), featrow,
                           i32(B // 2))
    t_hist_full = _time_op(hist_proxy, order0)
    t_scan = _time_op(jax.jit(scan_fn), hist_r)
    # seg-path per-call comparison at the average partition window: the
    # fused step vs the two launches it replaces (plus the election the
    # pair performs outside the kernels)
    t_part = _time_op(
        part_fn, seg, i32(0), i32(avg_part), i32(0), i32(B // 2), i32(1),
        i32(-1), i32(0), cmv,
    )
    t_hist = _time_op(hist_fn, seg, i32([0, avg_hist]))
    t_fused = _time_op(
        fused_fn, seg, i32([0]), i32([avg_part]), i32([0]), i32([B // 2]),
        i32([1]), i32([-1]), i32([0]), cmv[None],
    )

    n_trees = len(trees)
    partition_ms = (part_rows / n_trees) * (t_part_full / m) * scale * 1e3
    histogram_ms = (hist_rows / n_trees) * (t_hist_full / m) * scale * 1e3
    split_scan_ms = 2 * s_per_tree * t_scan * 1e3
    bookkeeping_ms = tree_ms - partition_ms - histogram_ms - split_scan_ms
    return {
        "partition_ms": round(partition_ms, 1),
        "histogram_ms": round(histogram_ms, 1),
        "split_scan_ms": round(split_scan_ms, 1),
        "bookkeeping_ms": round(bookkeeping_ms, 1),
        "bookkeeping_share": round(bookkeeping_ms / max(tree_ms, 1e-9), 3),
        "splits_per_tree": round(s_per_tree, 1),
        # per-call comparison at the average partition window: the fused
        # step vs the two launches it replaces
        "two_launch_call_ms": round((t_part + t_hist) * 1e3, 2),
        "fused_step_call_ms": round(t_fused * 1e3, 2),
        "grow_fused": bool(gp.grow_fused),
        "leaf_batch_effective": int(gp.leaf_batch),
    }


def _hist_engine_sweep(booster, m: int):
    """Histogram-engine v2 sweep: per-call seg-histogram cost per engine
    variant, scaled to a per-tree ``histogram_ms`` figure comparable to
    ``train_phases``.

    Variants: ``bf16_full_pass`` (the pre-v2 engine: one masked pass over
    the whole padded array — also what the bf16 kernel's launch pattern
    amortizes on TPU), ``default`` (the shipped engine: int8-by-default
    repacked kernel on TPU, capacity-bucketed windowed pass on CPU),
    ``int8`` (quantized accumulation explicitly on), and live-plane skip
    at ``feature_fraction`` 1.0 vs 0.5.  On CPU the reference ignores the
    ``live`` mask, so the 0.5 leg repacks only the live plane groups'
    features — cost is per-plane, so this is the honest stand-in for the
    kernel's zero-trip dead groups.  Asserts the v2 engine is >=2x the
    full pass (when windowing engages) and that ff=0.5 is measurably
    cheaper than ff=1.0."""
    import functools

    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.ops.pallas.seg import (
        _CPU_WINDOW_ROWS, hist_bpad, hist_group, hist_ngroups, pack_rows,
        padded_rows, seg_hist, seg_hist_ref,
    )
    from lightgbm_tpu.ops.quantize import hist_acc_scales

    trees = [t for t in booster.models_ if t.num_leaves > 1]
    if not trees:
        return {"error": "no grown trees"}
    s_calls = hist_rows = 0
    for t in trees:
        ic = np.asarray(t.internal_count, dtype=np.int64)
        lc = np.asarray(t.leaf_count, dtype=np.int64)

        def _cnt(ch):
            return int(ic[ch]) if ch >= 0 else int(lc[-ch - 1])

        s_calls += len(ic)
        hist_rows += sum(
            min(_cnt(int(t.left_child[i])), _cnt(int(t.right_child[i])))
            for i in range(len(ic))
        )
    s_per_tree = s_calls / len(trees)
    avg_hist = max(1, hist_rows // s_calls)

    gp = booster._grower_params
    B = int(gp.max_bin)
    wide = B > 256
    bins = booster._bins
    f_used = int(bins.shape[1])
    g = jnp.full((m,), 0.5, jnp.float32)
    h = jnp.ones((m,), jnp.float32)
    msk = jnp.ones((m,), jnp.float32)
    n_pad = padded_rows(m)
    seg = pack_rows(bins, g, h, msk, n_pad, wide=wide)
    scal = jnp.asarray([0, avg_hist], jnp.int32)
    qs = hist_acc_scales(g, h, msk)

    def mk(f=f_used, **kw):
        return jax.jit(functools.partial(
            seg_hist, f=f, num_bins=B, n_pad=n_pad, wide=wide, **kw
        ))

    full_fn = jax.jit(functools.partial(
        seg_hist_ref, f=f_used, num_bins=B, n_pad=n_pad, wide=wide
    ))
    bpad = hist_bpad(B)
    gb = hist_group(f_used, bpad)
    ng = hist_ngroups(f_used, bpad)
    live_groups = max(1, (ng + 1) // 2)  # ff=0.5 tree mask, group granular
    on_tpu = jax.default_backend() == "tpu"

    t_full = _time_op(full_fn, seg, scal)
    t_def = _time_op(mk(), seg, scal)
    t_int8 = _time_op(mk(quant_scales=qs), seg, scal)
    if on_tpu:
        t_ff10 = _time_op(
            mk(live=jnp.ones((ng,), jnp.int32)), seg, scal
        )
        live_half = (jnp.arange(ng) < live_groups).astype(jnp.int32)
        t_ff05 = _time_op(mk(live=live_half), seg, scal)
        ff_note = "live mask zero-trips dead plane groups in-kernel"
    else:
        f_half = min(f_used, live_groups * gb)
        seg_half = pack_rows(bins[:, :f_half], g, h, msk, n_pad, wide=wide)
        t_ff10 = t_def
        t_ff05 = _time_op(mk(f=f_half), seg_half, scal)
        ff_note = (
            "cpu proxy: repacked to the live plane groups' features only "
            "(kernel cost is per-plane; CPU reference ignores `live`)"
        )

    def h_ms(t):
        return round(s_per_tree * t * 1e3, 1)

    out = {
        "rows": m,
        "avg_hist_window": avg_hist,
        "plane_groups": ng,
        "live_groups_at_ff_0.5": live_groups,
        "per_call_ms": {
            "bf16_full_pass": round(t_full * 1e3, 3),
            "default": round(t_def * 1e3, 3),
            "int8": round(t_int8 * 1e3, 3),
            "ff_1.0": round(t_ff10 * 1e3, 3),
            "ff_0.5": round(t_ff05 * 1e3, 3),
        },
        "histogram_ms": {
            "bf16_full_pass": h_ms(t_full),
            "default": h_ms(t_def),
            "int8": h_ms(t_int8),
            "ff_1.0": h_ms(t_ff10),
            "ff_0.5": h_ms(t_ff05),
        },
        "speedup_vs_full_pass": round(t_full / t_def, 2),
        "ff_0.5_vs_1.0": round(t_ff05 / t_ff10, 3),
        "ff_note": ff_note,
    }
    # acceptance: the v2 engine cuts per-call histogram cost >=2x against
    # the pre-v2 full pass whenever its lever is engaged (windowing on
    # CPU above the threshold; int8+repack kernel on TPU), and ff=0.5
    # histogram cost lands measurably below ff=1.0
    if on_tpu or n_pad > _CPU_WINDOW_ROWS:
        assert t_full / t_def >= 2.0, (t_full, t_def)
    assert t_ff05 < t_ff10, (t_ff05, t_ff10)
    return out


def _leaf_batch_sweep(X, y, timed_iters: int):
    """iters/sec per leaf_batch K — the frontier-batched grower's headline:
    K splits per compiled step amortize the fixed per-split program cost."""
    ks = [
        int(k)
        for k in os.environ.get("BENCH_LEAF_BATCH_SWEEP", "1,2,4,8").split(",")
        if k.strip()
    ]
    out = {}
    for k in ks:
        ips, _b, _st = _train_bench(
            X, y, timed_iters, warmup_iters=1,
            params={**_PARAMS, "leaf_batch": k},
        )
        out[str(k)] = round(ips, 4)
    return out


def mesh_layout_sweep() -> dict:
    """Named-mesh layout sweep on the 8-virtual-CPU-device mesh.

    For each layout spec (data (8,1), feature, hybrid (4,2) — all through
    the single ``parallel/mesh.py`` grow path) train a fixed workload and
    record iters/sec plus the analytic-vs-measured collective byte totals;
    for the data layout additionally compare ``overlap_collectives`` on vs
    off (double-buffered histogram psums).  Runs standalone via
    ``python bench.py --mesh-sweep`` (the device-count flag must be set
    before the backend initializes, so this is its own process).
    """
    import jax

    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs.registry import get_session

    # layout COMPARISON shape, not the headline: small enough that five
    # cases (incl. 255-leaf compiles) fit a CPU-fallback bench budget
    n_rows = int(os.environ.get("BENCH_MESH_ROWS", 64_000))
    n_features = 28
    timed_iters = int(os.environ.get("BENCH_MESH_ITERS", 5))
    X, y = _make_data(n_rows, n_features)
    ses = get_session()

    cases = {
        "serial": {},
        # pin overlap off/on explicitly — "auto" engages at leaf_batch>1,
        # which would make the pair measure the same program
        "data": {"tree_learner": "data", "overlap_collectives": "off"},
        "data_overlap": {"tree_learner": "data", "overlap_collectives": "on"},
        "feature": {"tree_learner": "feature"},
        "hybrid": {"tree_learner": "data", "mesh_layout": "hybrid"},
    }
    out = {}
    for name, extra in cases.items():
        ses.configure(enabled=False)
        ses.reset()
        params = dict(
            _PARAMS,
            num_leaves=int(os.environ.get("BENCH_MESH_LEAVES", 63)),
            telemetry=True,
            **extra,
        )
        ips, booster, stats = _train_bench(
            X, y, timed_iters, params=params
        )
        rec = {
            "iters_per_sec": round(ips, 4),
            "recompiles_timed": stats["recompiles_timed"],
        }
        spec = getattr(booster, "_mesh_spec", None)
        if spec is not None:
            rec["mesh"] = {"data": spec.data, "feature": spec.feature}
            tel = booster.telemetry()
            iters = [
                e for e in tel["events"] if e["event"] == "iteration"
            ]
            analytic = sum(
                e["collective"]["psum_bytes"]
                for e in iters if "collective" in e
            )
            measured = sum(
                e["collective_measured"]["psum_bytes"]
                for e in iters if "collective_measured" in e
            )
            rec["analytic_psum_bytes"] = int(analytic)
            rec["measured_psum_bytes"] = int(measured)
            if measured and analytic:
                rec["measured_vs_analytic"] = round(measured / analytic, 4)
            rec["overlap"] = bool(
                booster._grower_params.overlap_collectives
            )
        ses.configure(enabled=False)
        ses.reset()
        out[name] = rec
    return out


def serve_sweep() -> dict:
    """Offered-load sweep of the serving plane (``lgb.serve``).

    For each offered load (requests/sec of fixed-size requests) a paced
    client drives the micro-batcher for a few seconds; we record achieved
    request p50/p99 latency (measured at the caller, enqueue->result),
    achieved rows/sec throughput, and the batcher's fill/flush/miss
    counters.  The trade this quantifies: at low load every request rides
    its own deadline flush (latency ~= deadline), at high load batches
    fill before the deadline and throughput approaches the bucket-ladder
    ceiling.  Runs standalone via ``python bench.py --serve-sweep``.
    """
    import threading

    import lightgbm_tpu as lgb

    n_rows = int(os.environ.get("BENCH_SERVE_ROWS", 50_000))
    n_features = 28
    n_trees = int(os.environ.get("BENCH_SERVE_TREES", 20))
    req_rows = int(os.environ.get("BENCH_SERVE_REQ_ROWS", 8))
    duration_s = float(os.environ.get("BENCH_SERVE_SECS", 3.0))
    loads = [
        int(v)
        for v in os.environ.get(
            "BENCH_SERVE_LOADS", "50,200,1000,4000"
        ).split(",")
        if v.strip()
    ]
    deadline_ms = float(os.environ.get("BENCH_SERVE_DEADLINE_MS", 5.0))
    max_batch = int(os.environ.get("BENCH_SERVE_MAX_BATCH", 4096))

    X, y = _make_data(n_rows, n_features)
    params = dict(_PARAMS, num_leaves=63)
    booster = lgb.train(params, lgb.Dataset(X, y, params=params), n_trees)
    rng = np.random.default_rng(7)
    Xq = rng.normal(size=(req_rows, n_features)).astype(np.float32)

    out = {
        "req_rows": req_rows,
        "duration_s": duration_s,
        "deadline_ms": deadline_ms,
        "max_batch": max_batch,
        "n_trees": len(booster.models_),
        "loads": {},
    }
    server = lgb.serve(
        booster, deadline_ms=deadline_ms, max_batch=max_batch, port=0
    )
    try:
        for load in loads:
            # paced open-loop client: one request every 1/load seconds,
            # latency measured enqueue->result at the caller
            lat_lock = threading.Lock()
            latencies: list = []
            pending: list = []
            interval = 1.0 / load
            t_end = time.perf_counter() + duration_s

            def reap(fut, t0):
                fut.result(timeout=60.0)
                with lat_lock:
                    latencies.append((time.perf_counter() - t0) * 1e3)

            t_next = time.perf_counter()
            n_sent = 0
            while time.perf_counter() < t_end:
                t0 = time.perf_counter()
                fut = server.predict_async(Xq)
                th = threading.Thread(target=reap, args=(fut, t0))
                th.start()
                pending.append(th)
                n_sent += 1
                t_next += interval
                sleep = t_next - time.perf_counter()
                if sleep > 0:
                    time.sleep(sleep)
            for th in pending:
                th.join(timeout=60.0)
            lat = sorted(latencies)

            def pct(q):
                return round(lat[min(len(lat) - 1, int(q * (len(lat) - 1)))], 3)

            stats = server.stats()
            out["loads"][str(load)] = {
                "offered_rps": load,
                "achieved_rps": round(n_sent / duration_s, 1),
                "rows_per_sec": round(n_sent * req_rows / duration_s, 1),
                "p50_ms": pct(0.50) if lat else None,
                "p99_ms": pct(0.99) if lat else None,
                "batch_fill": round(stats["batch_fill"], 4),
                "deadline_miss_rate": round(stats["deadline_miss_rate"], 4),
            }
    finally:
        server.stop()
    return out


def _tensor_flop_model(n_rows: int, n_trees: int, depth: int, f: int) -> dict:
    """Analytic MAC counts for the three tensor-forest contractions.

    The matmul engine trades the walker's D gather rounds per tree for
    dense int8/f32 contractions sized for a systolic MXU: per row it is
    deliberately FLOP-inflated (every node of every tree is evaluated),
    which is the right trade exactly when the hardware's matmul
    throughput dwarfs its gather throughput.  These counts feed the
    BENCH_NOTES roofline argument."""
    p_tree = (1 << depth) - 1
    lp = 1 << depth
    p = n_trees * p_tree
    sel_macs = 2 * n_rows * f * p        # hi/lo digit matmuls, int8 -> i32
    route_macs = n_rows * n_trees * p_tree * lp  # path-sign scoring, int8
    leaf_macs = n_rows * n_trees * lp    # one-hot . leaf values, f32
    return {
        "select_int8_macs": int(sel_macs),
        "route_int8_macs": int(route_macs),
        "leaf_f32_macs": int(leaf_macs),
        "total_macs": int(sel_macs + route_macs + leaf_macs),
        "macs_per_row": int((sel_macs + route_macs + leaf_macs) // n_rows),
        # the walker's per-row work for comparison: D node visits per tree,
        # each a handful of gathers + compares (no dense math)
        "walker_node_visits_per_row": int(n_trees * depth),
    }


def pred_engine_sweep() -> dict:
    """Walker vs matmul prediction-engine A/B (``--pred-engine-sweep``).

    Grid: rows x depth x trees (env-tunable, defaults 64k/1M rows,
    depth {4,6}, trees {50,200,500}).  One model per depth is trained
    small and its trees replicated to each target count (same trick as
    the headline predict bench), so every cell predicts through the
    exact streaming path a user would hit.  Each cell runs both engines
    on identical inputs: warmup predict (ladder compiles) then one timed
    predict, recording rows/sec, the phase breakdown (bin / device
    contract-or-walk / host), recompiles in the timed run, and byte
    parity between the two engines' outputs.  The analytic MXU FLOP
    model for each shape rides along for the BENCH_NOTES roofline
    analysis — on CPU fallback the matmul engine's FLOP inflation is
    expected to show as a slowdown; the model quantifies the MXU
    throughput at which the trade inverts."""
    import lightgbm_tpu as lgb

    row_grid = [
        int(v)
        for v in os.environ.get(
            "BENCH_PRED_ROWS", "64000,1000000"
        ).split(",")
        if v.strip()
    ]
    tree_grid = [
        int(v)
        for v in os.environ.get("BENCH_PRED_TREES", "50,200,500").split(",")
        if v.strip()
    ]
    depth_grid = [
        int(v)
        for v in os.environ.get("BENCH_PRED_DEPTHS", "4,6").split(",")
        if v.strip()
    ]
    train_rows = int(os.environ.get("BENCH_PRED_TRAIN_ROWS", 100_000))
    n_features = 28
    max_rows = max(row_grid)
    X, y = _make_data(max(max_rows, train_rows), n_features)

    out = {
        "train_rows": train_rows,
        "n_features": n_features,
        "cells": [],
    }
    for depth in depth_grid:
        params = dict(
            _PARAMS,
            num_leaves=1 << depth,
            max_depth=depth,
            max_bin=255,
        )
        base = lgb.train(
            params,
            lgb.Dataset(X[:train_rows], y[:train_rows], params=params),
            25,
        )
        orig_models = list(base.models_)
        orig_recs = list(base._bin_records)
        for n_trees in tree_grid:
            while len(base.models_) < n_trees:
                base.models_.extend(orig_models)
                base._bin_records.extend(orig_recs)
            del base.models_[n_trees:]
            del base._bin_records[n_trees:]
            base._bump_model_version()
            for n_rows in row_grid:
                Xp = X[:n_rows]
                cell = {
                    "depth": depth,
                    "trees": n_trees,
                    "rows": n_rows,
                    "flop_model": _tensor_flop_model(
                        n_rows, n_trees, depth, n_features
                    ),
                }
                preds = {}
                for eng in ("walk", "matmul"):
                    base.predict(Xp, pred_engine=eng)  # ladder warmup
                    c0 = lgb.compile_count()
                    t0 = time.perf_counter()
                    preds[eng] = np.asarray(
                        base.predict(Xp, pred_engine=eng)
                    )
                    dt = time.perf_counter() - t0
                    stats = dict(base.last_predict_stats)
                    cell[eng] = {
                        "rows_per_sec": round(n_rows / dt),
                        "wall_ms": round(dt * 1e3, 1),
                        "engine_resolved": stats.get("engine", "walk"),
                        "recompiles_timed": lgb.compile_count() - c0,
                        "phases_ms": {
                            "bin": round(float(stats.get("bin_ms", 0.0)), 1),
                            "device": round(
                                float(stats.get("walk_ms", 0.0)), 1
                            ),
                            "host": round(float(stats.get("host_ms", 0.0)), 1),
                            "transfer": round(
                                float(stats.get("transfer_ms", 0.0)), 1
                            ),
                        },
                    }
                cell["byte_identical"] = bool(
                    preds["walk"].tobytes() == preds["matmul"].tobytes()
                )
                cell["matmul_speedup"] = round(
                    cell["matmul"]["rows_per_sec"]
                    / max(1, cell["walk"]["rows_per_sec"]),
                    3,
                )
                out["cells"].append(cell)
    return out


_INGEST_CELL_SCRIPT = r"""
import json, os, resource, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
csv_path, chunk_rows = sys.argv[1], int(sys.argv[2])
import numpy as np
import lightgbm_tpu as lgb
from lightgbm_tpu.obs.registry import get_session

get_session().configure(enabled=True)
params = {
    "objective": "binary", "max_bin": 255, "verbosity": -1,
    "bin_construct_sample_cnt": 50000, "data_random_seed": 1,
    "ingest_chunk_rows": chunk_rows,
}
# settle the allocator baseline (interpreter + jax + a tiny construct)
# so the reported delta isolates THIS construct's footprint; ru_maxrss
# is process-lifetime-monotone, hence one fresh process per cell
rng = np.random.default_rng(0)
Xs = rng.normal(size=(256, 28))
ys = (Xs[:, 0] > 0).astype(np.float64)
lgb.Dataset(Xs, ys, params=params).construct()
base = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
t0 = time.perf_counter()
ds = lgb.Dataset(csv_path, params=params).construct()
wall = time.perf_counter() - t0
peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
n = int(ds.bins.shape[0])
print(json.dumps({
    "rows": n,
    "wall_s": round(wall, 2),
    "rows_per_sec": round(n / wall),
    "peak_rss_bytes": int(peak),
    "rss_delta_bytes": int(peak - base),
    # 0 for the one-shot path: only stream_pack sets this gauge
    "chunks_streamed": int(
        get_session().gauges.get("ingest/chunks_total", 0.0)
    ),
}))
"""


def ingest_sweep() -> dict:
    """Chunked-vs-one-shot ingest A/B (``--ingest-sweep``).

    Writes a Higgs-shaped label+28-feature CSV once (1M rows by default,
    generated chunk-wise so the bench itself stays lean), then builds a
    Dataset from that file in a FRESH subprocess per cell — ``ru_maxrss``
    is process-lifetime-monotone, so peak-RSS cells cannot share a
    process.  One cell runs the one-shot loader (``ingest_chunk_rows=0``:
    np.loadtxt materializes the full f64 matrix); the others stream the
    same file through the two-pass chunked ingest at chunk sizes
    {64k, 256k, 1M}.  Each cell reports wall, rows/s, lifetime peak RSS
    and the delta over a settled baseline; the headline ratios compare
    each chunked cell's RSS delta and wall against one-shot.  Byte parity
    between the two paths is asserted in-suite (tests/test_ingest.py),
    not here — the bench measures the memory/wall trade only."""
    import shutil
    import subprocess
    import tempfile

    n_rows = int(os.environ.get("BENCH_INGEST_ROWS", 1_000_000))
    n_features = 28
    chunk_grid = [
        int(v)
        for v in os.environ.get(
            "BENCH_INGEST_CHUNKS", "65536,262144,1000000"
        ).split(",")
        if v.strip()
    ]
    td = tempfile.mkdtemp(prefix="lgbtpu_ingest_bench_")
    csv_path = os.path.join(td, "higgs_like.csv")
    try:
        rng = np.random.default_rng(42)
        wvec = rng.normal(size=n_features)
        with open(csv_path, "w") as fh:
            done = 0
            while done < n_rows:
                m = min(100_000, n_rows - done)
                Xc = rng.normal(size=(m, n_features))
                yc = (
                    Xc @ wvec * 0.5 + rng.normal(size=m) > 0
                ).astype(np.float64)
                np.savetxt(
                    fh,
                    np.column_stack([yc, Xc]),
                    delimiter=",",
                    fmt="%.5f",
                )
                done += m
        csv_bytes = os.path.getsize(csv_path)

        def run_cell(chunk_rows: int) -> dict:
            r = subprocess.run(
                [
                    sys.executable,
                    "-c",
                    _INGEST_CELL_SCRIPT,
                    csv_path,
                    str(chunk_rows),
                ],
                capture_output=True,
                text=True,
                timeout=1800,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            if r.returncode != 0:
                raise RuntimeError(
                    f"ingest cell chunk_rows={chunk_rows} failed:\n"
                    + r.stderr[-4000:]
                )
            return json.loads(r.stdout.strip().splitlines()[-1])

        out = {
            "rows": n_rows,
            "n_features": n_features,
            "csv_bytes": int(csv_bytes),
            "raw_f64_bytes": int(n_rows * n_features * 8),
            "cells": [],
        }
        one_shot = run_cell(0)
        out["cells"].append(dict(one_shot, mode="one_shot", chunk_rows=0))
        for cr in chunk_grid:
            cell = run_cell(cr)
            cell.update(
                mode="chunked",
                chunk_rows=cr,
                rss_reduction_vs_one_shot=round(
                    one_shot["rss_delta_bytes"]
                    / max(1, cell["rss_delta_bytes"]),
                    2,
                ),
                wall_vs_one_shot=round(
                    cell["wall_s"] / one_shot["wall_s"], 3
                ),
            )
            out["cells"].append(cell)
        return out
    finally:
        shutil.rmtree(td, ignore_errors=True)


_LAUNCH_CELL_SCRIPT = r"""
import json, os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
n_rows, n_launch, rounds, leaves, mesh = (int(v) for v in sys.argv[1:6])
if mesh:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
import numpy as np
import lightgbm_tpu as lgb
from lightgbm_tpu.obs.jit import compile_counts_by_label

rng = np.random.default_rng(0)
X = rng.normal(size=(n_rows, 28))
y = X @ rng.normal(size=28) * 0.5 + rng.normal(size=n_rows) * 0.1
params = {
    "objective": "regression", "num_leaves": leaves, "verbosity": -1,
    "min_data_in_leaf": 20, "seed": 0,
    "train_steps_per_launch": n_launch,
}
if mesh:
    params.update({"tree_learner": "data", "num_machines": 8})
ds = lgb.Dataset(X, y, free_raw_data=False)

# warmup run in-process: compiles the grow/scan executable once so the
# timed run measures steady-state launches, not tracing
lgb.train(dict(params), ds, num_boost_round=2 * n_launch)
c0 = dict(compile_counts_by_label())

t0 = time.perf_counter()
booster = lgb.train(dict(params), ds, num_boost_round=rounds)
wall_s = time.perf_counter() - t0
c1 = compile_counts_by_label()

# exact whole-run totals (the _host_overhead_ms sample window is bounded)
host_total = float(booster._host_overhead_total_ms)
host_n = int(booster._host_overhead_n)
print(json.dumps({
    "steps_per_launch": n_launch,
    "rows": n_rows,
    "rounds": rounds,
    "mesh": "data8" if mesh else "serial",
    "wall_s": round(wall_s, 3),
    "iter_ms": round(wall_s / rounds * 1e3, 2),
    "iters_per_s": round(rounds / wall_s, 2),
    "dispatches": (rounds + n_launch - 1) // n_launch,
    # wall between device dispatches (callbacks, telemetry, Python loop),
    # amortized over the boosting iterations each dispatch covers
    "host_overhead_ms_per_iter": round(host_total / rounds, 4),
    "host_overhead_ms_per_dispatch": round(
        host_total / max(1, host_n), 4
    ),
    # retrace ledger for the timed run: the scan executable (and the
    # sharded grow beneath it) must show ZERO fresh compiles after warmup
    "timed_run_compiles": {
        k: int(c1.get(k, 0) - c0.get(k, 0))
        for k in sorted(set(c0) | set(c1))
        if (c1.get(k, 0) - c0.get(k, 0)) > 0
        and (k.startswith("grow/") or k.startswith("parallel/"))
    },
}))
"""


def launch_sweep() -> dict:
    """Device-resident boosting A/B (``--launch-sweep``).

    For N in {1, 2, 4, 8} train the same 20k x 28 regression model with
    ``train_steps_per_launch=N`` — serial and under the ``tree_learner=
    data`` 8-device mesh — and record per-iteration wall, the host
    overhead between device dispatches, and the steady-state retrace
    ledger.  Each cell is a fresh subprocess (cold jit caches + compile
    counters); a warmup train inside the cell absorbs tracing so the
    timed run measures launch steady state.  The model bytes are
    N-invariant (tests/test_launch_scan.py); this sweep measures only
    where the host round-trip time goes."""
    import subprocess

    n_rows = int(os.environ.get("BENCH_LAUNCH_ROWS", 20_000))
    rounds = int(os.environ.get("BENCH_LAUNCH_ROUNDS", 24))
    leaves = int(os.environ.get("BENCH_LAUNCH_LEAVES", 15))
    n_grid = [
        int(v)
        for v in os.environ.get("BENCH_LAUNCH_N", "1,2,4,8").split(",")
        if v.strip()
    ]
    out = {
        "rows": n_rows,
        "n_features": 28,
        "num_leaves": leaves,
        "rounds": rounds,
        "cells": [],
    }
    for mesh in (0, 1):
        for n in n_grid:
            r = subprocess.run(
                [
                    sys.executable,
                    "-c",
                    _LAUNCH_CELL_SCRIPT,
                    str(n_rows),
                    str(n),
                    str(rounds),
                    str(leaves),
                    str(mesh),
                ],
                capture_output=True,
                text=True,
                timeout=3600,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            if r.returncode != 0:
                raise RuntimeError(
                    f"launch cell n={n} mesh={mesh} failed:\n"
                    + r.stderr[-4000:]
                )
            out["cells"].append(json.loads(r.stdout.strip().splitlines()[-1]))
    return out


_FLEET_CELL_SCRIPT = r"""
import json, os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
n_rows, m, iters, leaves = (int(v) for v in sys.argv[1:5])
import numpy as np
import lightgbm_tpu as lgb
from lightgbm_tpu.boosting import create_booster
from lightgbm_tpu.boosting.fleet import FleetTrainer
from lightgbm_tpu.obs.jit import compile_counts_by_label

rng = np.random.default_rng(0)
X = rng.normal(size=(n_rows, 28))
y = X @ rng.normal(size=28) * 0.5 + rng.normal(size=n_rows) * 0.1
base = {
    "objective": "regression", "num_leaves": leaves, "verbosity": -1,
    "min_data_in_leaf": 20, "seed": 0,
}
param_sets = [
    dict(base, seed=i, learning_rate=0.05 + 0.01 * i) for i in range(m)
]
ds = lgb.Dataset(X, y, free_raw_data=False)

# solo reference: one member trained alone through the standard update
# path (what M sequential runs would each pay per iteration)
solo = create_booster(dict(param_sets[0]), ds)
t0 = time.perf_counter()
solo.update()
solo_compile_s = time.perf_counter() - t0
solo.update()  # settle
t0 = time.perf_counter()
for _ in range(iters):
    solo.update()
# the solo path pipelines its host fetch one iteration behind — drain it
# (models_ property) and block on the score so the timed window covers
# ALL the work an iteration dispatched
import jax
_ = solo.models_
jax.block_until_ready(solo._score)
solo_iter_ms = (time.perf_counter() - t0) / iters * 1e3
c0 = compile_counts_by_label()

boosters = [create_booster(dict(p), ds) for p in param_sets]
trainer = FleetTrainer(boosters)
t0 = time.perf_counter()
trainer.update()
fleet_compile_s = time.perf_counter() - t0
trainer.update()
t0 = time.perf_counter()
for _ in range(iters):
    trainer.update()
fleet_iter_ms = (time.perf_counter() - t0) / iters * 1e3
c1 = compile_counts_by_label()

print(json.dumps({
    "m": m,
    "rows": n_rows,
    "solo_iter_ms": round(solo_iter_ms, 1),
    "sequential_iter_ms": round(solo_iter_ms * m, 1),
    "fleet_iter_ms": round(fleet_iter_ms, 1),
    "fleet_iter_per_member_ms": round(fleet_iter_ms / m, 1),
    "speedup_vs_sequential": round(solo_iter_ms * m / fleet_iter_ms, 2),
    "solo_compile_s": round(solo_compile_s, 1),
    "fleet_compile_s": round(fleet_compile_s, 1),
    "fleet_grow_executables": int(
        c1.get("fleet/grow", 0) - c0.get("fleet/grow", 0)
    ),
    # dispatch ledger per boosting iteration: M sequential runs issue M
    # grow dispatches (each with its own per-leaf histogram launches);
    # the fleet's custom_vmap hist rule folds the member axis into the
    # segment ids, so ONE launch per leaf covers all M members
    "grow_dispatches_per_iter": {"sequential": m, "fleet": 1},
    "hist_launch_reduction": m,
}))
"""


def fleet_sweep() -> dict:
    """Vmapped model-fleet A/B (``--fleet-sweep``).

    For each fleet size M in {1, 4, 16, 32} train M same-shape regression
    members (seed + learning-rate sweep) at 64k x 28 two ways — M solo
    runs through the standard update path vs ONE FleetTrainer whose
    vmapped grow batches all members per launch — and record per-iteration
    wall, compile time, grow-executable counts and the dispatch ledger.
    Each cell runs in a fresh subprocess so compile caches and counters
    start cold.  The analytic fleet psum model (one stacked [M, ...]
    payload per collective step under ``tree_learner=data``) rides along
    from ``parallel.mesh.fleet_psum_bytes_per_iteration`` — the same
    formula the perf gate pins."""
    import subprocess

    from lightgbm_tpu.parallel.mesh import (
        MeshSpec,
        fleet_psum_bytes_per_iteration,
    )

    n_rows = int(os.environ.get("BENCH_FLEET_ROWS", 64_000))
    iters = int(os.environ.get("BENCH_FLEET_ITERS", 3))
    leaves = int(os.environ.get("BENCH_FLEET_LEAVES", 15))
    m_grid = [
        int(v)
        for v in os.environ.get("BENCH_FLEET_M", "1,4,16,32").split(",")
        if v.strip()
    ]
    out = {
        "rows": n_rows,
        "n_features": 28,
        "num_leaves": leaves,
        "timed_iters": iters,
        "cells": [],
    }
    for m in m_grid:
        r = subprocess.run(
            [
                sys.executable,
                "-c",
                _FLEET_CELL_SCRIPT,
                str(n_rows),
                str(m),
                str(iters),
                str(leaves),
            ],
            capture_output=True,
            text=True,
            timeout=3600,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        if r.returncode != 0:
            raise RuntimeError(
                f"fleet cell m={m} failed:\n" + r.stderr[-4000:]
            )
        cell = json.loads(r.stdout.strip().splitlines()[-1])
        cell["analytic_psum_bytes_data8"] = fleet_psum_bytes_per_iteration(
            n_splits=leaves - 1,
            n_features=28,
            num_bins=255,
            fleet=m,
            spec=MeshSpec("data", data=8, feature=1),
        )
        out["cells"].append(cell)
    return out


def main() -> None:
    if "--fleet-sweep" in sys.argv:
        # standalone, CPU-pinned: each M cell is its own subprocess so the
        # compile counters and jit caches start cold
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        print(json.dumps({"fleet_sweep": fleet_sweep()}))
        return
    if "--launch-sweep" in sys.argv:
        # standalone, CPU-pinned: each (N, mesh) cell is its own subprocess
        # so jit caches and compile counters start cold
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        print(json.dumps({"launch_sweep": launch_sweep()}))
        return
    if "--ingest-sweep" in sys.argv:
        # standalone, CPU-pinned: each cell is its own subprocess, so the
        # parent only orchestrates and writes the CSV fixture
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        print(json.dumps({"ingest_sweep": ingest_sweep()}))
        return
    if "--pred-engine-sweep" in sys.argv:
        # standalone, CPU-pinned like --serve-sweep: cross-engine parity
        # and phase shape, plus the analytic MXU model for the roofline
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        print(json.dumps({"pred_engine_sweep": pred_engine_sweep()}))
        return
    if "--serve-sweep" in sys.argv:
        # standalone, CPU-pinned like --mesh-sweep: the sweep measures the
        # batching/latency trade, not kernel speed
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        print(json.dumps({"serve_sweep": serve_sweep()}))
        return
    if "--mesh-sweep" in sys.argv:
        # standalone: 8 virtual CPU devices, CPU pinned before backend init
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        print(json.dumps({"mesh_layout_sweep": mesh_layout_sweep()}))
        return
    platform_note = None
    on_accel = _probe_accelerator()
    if not on_accel:
        # accelerator unreachable (e.g. TPU tunnel down): record an honest
        # CPU number rather than hanging the whole bench run
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        platform_note = "cpu-fallback (accelerator unreachable)"
    # the headline target is defined at Higgs scale (10.5M rows,
    # docs/Experiments.rst:108) — measure THAT on a real accelerator, plus
    # a secondary 1M point for round-over-round comparability; the CPU
    # fallback stays small so a tunnel outage doesn't stall the driver
    n_rows = int(
        os.environ.get("BENCH_ROWS", 10_500_000 if on_accel else 1_000_000)
    )
    n_features = 28
    timed_iters = int(os.environ.get("BENCH_ITERS", 10))

    X, y = _make_data(n_rows, n_features)
    iters_per_sec, booster, train_compiles = _train_bench(X, y, timed_iters)
    baseline = 3.8  # reference CPU iters/sec on Higgs (BASELINE.md)

    # phase breakdown BEFORE the predict section replicates models_
    try:
        train_phases = _train_phases(X, y, iters_per_sec)
    except Exception as e:  # diagnostics must not sink the headline number
        train_phases = {"error": repr(e)}
    sweep_iters = int(os.environ.get("BENCH_SWEEP_ITERS", min(timed_iters, 3)))
    try:
        leaf_batch_sweep = _leaf_batch_sweep(X, y, sweep_iters)
    except Exception as e:
        leaf_batch_sweep = {"error": repr(e)}

    secondary_rows = int(os.environ.get("BENCH_ROWS_SECONDARY", 1_000_000))
    iters_per_sec_secondary = None
    if on_accel and secondary_rows and secondary_rows < n_rows:
        Xs, ys = X[:secondary_rows], y[:secondary_rows]
        iters_per_sec_secondary, _, _ = _train_bench(Xs, ys, timed_iters)

    # batch-inference throughput. The fork's 84k preds/s (original.md) was
    # measured on a 376-tree model; replicate the trained trees to the same
    # count so the comparison is apples-to-apples.
    n_trees_target = 376
    orig_models = list(booster.models_)
    orig_recs = list(booster._bin_records)
    while len(booster.models_) < n_trees_target:
        booster.models_.extend(orig_models)
        booster._bin_records.extend(orig_recs)
    del booster.models_[n_trees_target:]
    del booster._bin_records[n_trees_target:]
    booster._bump_model_version()
    pred_rows = min(n_rows, 500_000)
    Xp = X[:pred_rows]
    t0 = time.perf_counter()
    booster.predict(Xp)  # warmup: bucket-ladder executables compile here
    pred_warmup_dt = time.perf_counter() - t0
    t0 = time.perf_counter()
    booster.predict(Xp)
    pred_dt = time.perf_counter() - t0
    preds_per_sec = pred_rows / pred_dt
    # phase-resolved breakdown of the timed run (streaming engine /
    # forest-walk stats): which pipeline stage regressed is visible
    # round-over-round instead of one opaque preds_per_sec scalar
    pred_stats = dict(booster.last_predict_stats)
    pred_phases = {
        k: round(float(pred_stats.get(k, 0.0)), 1)
        for k in ("bin_ms", "transfer_ms", "walk_ms", "host_ms")
    }
    pred_phases["path"] = pred_stats.get("path", "unknown")
    pred_phases["chunks"] = pred_stats.get("chunks", 1)
    pred_phases["compiles_in_timed_run"] = pred_stats.get("compiles", 0)

    import jax as _jax

    out = {
        "metric": f"higgs_like_{n_rows}_rows_boosting_iters_per_sec",
        "value": round(iters_per_sec, 4),
        "unit": "iters/sec",
        "vs_baseline": round(iters_per_sec / baseline, 4),
        "platform": platform_note or _jax.default_backend(),
        "rows": n_rows,
        "baseline_rows": 10_500_000,
        "note": "vs_baseline divides by the reference CPU's 3.8 iters/s on 10.5M rows (BASELINE.md); when 'rows' != baseline_rows the per-row throughput differs by rows/baseline_rows",
        "preds_per_sec": round(preds_per_sec),
        "pred_rows": pred_rows,
        "preds_vs_fork_84k": round(preds_per_sec / 84000.0, 2),
        "pred_warmup_s": round(pred_warmup_dt, 2),
        "pred_phases": pred_phases,
        "train_phases": train_phases,
        "train_compiles": train_compiles,
        "leaf_batch_sweep_iters_per_sec": leaf_batch_sweep,
    }
    if iters_per_sec_secondary is not None:
        out[f"iters_per_sec_{secondary_rows}_rows"] = round(
            iters_per_sec_secondary, 4
        )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
