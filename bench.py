"""Benchmark: boosting iterations/sec on a Higgs-like workload, single chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: the reference CPU trains Higgs-10.5M x 28 at ~3.8 iters/sec
(500 iters in 130.094 s, 255 leaves, 16 threads — docs/Experiments.rst:108,
see BASELINE.md).  This benchmark runs the same shape of work (binary
objective, 255 leaves, max_bin 255, 28 features) on however many rows fit a
single chip comfortably, and reports iterations/sec; vs_baseline is the ratio
against 3.8 iters/s.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _probe_accelerator(timeout_s: int = 180) -> bool:
    """Check (in a subprocess, so a hung tunnel can't wedge the bench) that
    the default JAX backend actually comes up."""
    import subprocess
    import sys

    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s,
            capture_output=True,
        )
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main() -> None:
    platform_note = None
    if not _probe_accelerator():
        # accelerator unreachable (e.g. TPU tunnel down): record an honest
        # CPU number rather than hanging the whole bench run
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        platform_note = "cpu-fallback (accelerator unreachable)"
    n_rows = int(os.environ.get("BENCH_ROWS", 1_000_000))
    n_features = 28
    num_leaves = 255
    warmup_iters = 2
    timed_iters = int(os.environ.get("BENCH_ITERS", 10))

    rng = np.random.default_rng(42)
    X = rng.normal(size=(n_rows, n_features)).astype(np.float32)
    w = rng.normal(size=n_features)
    logits = X @ w * 0.5 + rng.normal(scale=1.0, size=n_rows)
    y = (logits > 0).astype(np.float64)

    import lightgbm_tpu as lgb

    params = {
        "objective": "binary",
        "num_leaves": num_leaves,
        "max_bin": 255,
        "learning_rate": 0.1,
        "min_data_in_leaf": 100,
        "verbosity": -1,
        "metric": "none",
    }
    dtrain = lgb.Dataset(X, y, params=params)
    booster = lgb.Booster(params, dtrain)

    for _ in range(warmup_iters):
        booster.update()
    import jax

    jax.block_until_ready(booster._score)

    t0 = time.perf_counter()
    for _ in range(timed_iters):
        booster.update()
    jax.block_until_ready(booster._score)
    dt = time.perf_counter() - t0

    iters_per_sec = timed_iters / dt
    baseline = 3.8  # reference CPU iters/sec on Higgs (BASELINE.md)

    # batch-inference throughput. The fork's 84k preds/s (original.md) was
    # measured on a 376-tree model; replicate the trained trees to the same
    # count so the comparison is apples-to-apples.
    n_trees_target = 376
    orig_models = list(booster.models_)
    orig_recs = list(booster._bin_records)
    while len(booster.models_) < n_trees_target:
        booster.models_.extend(orig_models)
        booster._bin_records.extend(orig_recs)
    del booster.models_[n_trees_target:]
    del booster._bin_records[n_trees_target:]
    booster._bump_model_version()
    pred_rows = min(n_rows, 500_000)
    Xp = X[:pred_rows]
    booster.predict(Xp)  # warmup/compile
    t0 = time.perf_counter()
    booster.predict(Xp)
    pred_dt = time.perf_counter() - t0
    preds_per_sec = pred_rows / pred_dt

    import jax as _jax

    print(
        json.dumps(
            {
                "metric": "higgs_like_1m_boosting_iters_per_sec",
                "value": round(iters_per_sec, 4),
                "unit": "iters/sec",
                "vs_baseline": round(iters_per_sec / baseline, 4),
                "platform": platform_note or _jax.default_backend(),
                "rows": n_rows,
                "baseline_rows": 10_500_000,
                "note": "vs_baseline divides by the reference CPU's 3.8 iters/s on 10.5M rows (BASELINE.md); this run uses 'rows' rows, so per-row throughput differs by rows/baseline_rows",
                "preds_per_sec": round(preds_per_sec),
                "pred_rows": pred_rows,
                "preds_vs_fork_84k": round(preds_per_sec / 84000.0, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
