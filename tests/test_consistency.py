"""Golden parity vs the reference implementation on its own examples.

The goldens under tests/golden/ were generated ONCE by running the REFERENCE
CLI (built from /root/reference with cmake, CPU-only) on
examples/{regression,binary_classification,lambdarank,
multiclass_classification}/train.conf — see tests/golden/generate.py.  Each
golden records the reference's eval trajectory, its trained model file, and
that model's predictions on the example test set.

Tests here assert, WITHOUT needing the reference binary:
  * cross-loading: a reference-trained model file loads into our Booster and
    reproduces the reference's own predictions (tight tolerance — this is
    deterministic);
  * training parity: training on the same example data with the example's
    params lands within tolerance of the reference's final train metric
    (loose tolerance — bagging/feature_fraction RNG streams differ by
    design, reference Random vs jax.random).

The reverse cross-load (reference binary loading OUR model file) was
validated manually with the built CLI; it cannot run in CI without the
binary.  Pattern: reference tests/python_package_test/test_consistency.py:67.
"""

import json
from pathlib import Path

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import lightgbm_tpu as lgb  # noqa: E402

GOLDEN = Path(__file__).parent / "golden"
REF_EXAMPLES = Path("/root/reference/examples")

# per-example LOOSE band, used only when the example's own conf engages a
# cross-engine RNG stream (bagging / feature_fraction — reference Random
# vs jax.random draw different subsets by design).  Deterministic confs
# get the tight band below: same data, same binning, same greedy split
# rule must land within 1% (VERDICT item 6).
CASES = {
    "regression": ("regression", "l2", 0.05),
    "binary_classification": ("binary", "binary_logloss", 0.08),
    "lambdarank": ("rank", "ndcg@3", 0.05),
    "multiclass_classification": ("multiclass", "multi_logloss", 0.08),
}
DETERMINISTIC_RTOL = 0.01


def _conf_is_stochastic(conf: dict) -> bool:
    """True when the conf engages any cross-engine RNG stream."""
    ff = float(conf.get("feature_fraction", 1.0))
    bf = float(conf.get("bagging_fraction", 1.0))
    bfreq = int(conf.get("bagging_freq", 0))
    return (
        ff < 1.0
        or (bfreq > 0 and bf < 1.0)
        or conf.get("boosting", "gbdt") in ("dart", "goss", "rf")
        or float(conf.get("pos_bagging_fraction", 1.0)) < 1.0
        or float(conf.get("neg_bagging_fraction", 1.0)) < 1.0
    )


def _parse_conf(path: Path) -> dict:
    params = {}
    for line in path.read_text().splitlines():
        line = line.split("#", 1)[0].strip()
        if "=" in line:
            k, v = line.split("=", 1)
            params[k.strip()] = v.strip()
    return params


def _load_example(name: str, stem: str):
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import _load_text_file

    d = REF_EXAMPLES / name
    cfg = Config.from_params({})
    tr = _load_text_file(str(d / f"{stem}.train"), cfg)
    te = _load_text_file(str(d / f"{stem}.test"), cfg)

    def _dense(m, width):
        if hasattr(m, "toarray"):
            m = m.toarray()
            if m.shape[1] < width:
                m = np.pad(m, ((0, 0), (0, width - m.shape[1])))
        return np.asarray(m, dtype=np.float64)

    width = max(
        tr["data"].shape[1], te["data"].shape[1]
    )
    out = {
        "X": _dense(tr["data"], width),
        "y": np.asarray(tr["label"]),
        "Xt": _dense(te["data"], width),
        "yt": np.asarray(te["label"]),
    }
    q = d / f"{stem}.train.query"
    if q.exists():
        out["group"] = np.loadtxt(q, dtype=np.int64, ndmin=1)
    qt = d / f"{stem}.test.query"
    if qt.exists():
        out["group_t"] = np.loadtxt(qt, dtype=np.int64, ndmin=1)
    return out


@pytest.mark.skipif(not REF_EXAMPLES.exists(), reason="reference not mounted")
@pytest.mark.parametrize("name", list(CASES))
def test_reference_model_cross_loads(name):
    """Reference model file -> our Booster -> reference's own predictions."""
    stem, _, _ = CASES[name]
    model_file = GOLDEN / f"{name}.model.txt"
    preds_file = GOLDEN / f"{name}.preds.txt"
    if not model_file.exists():
        pytest.skip("goldens not generated")
    ex = _load_example(name, stem)
    booster = lgb.Booster(model_str=model_file.read_text())
    want = np.loadtxt(preds_file, dtype=np.float64, ndmin=1)
    got = booster.predict(ex["Xt"])
    if got.ndim == 2:  # multiclass: reference prints one row per sample
        want = want.reshape(got.shape)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


@pytest.mark.skipif(not REF_EXAMPLES.exists(), reason="reference not mounted")
@pytest.mark.parametrize("name", list(CASES))
def test_training_parity_on_example(name):
    """Our training on the example data reaches the reference's final train
    metric within tolerance."""
    stem, metric, rtol = CASES[name]
    evals_file = GOLDEN / f"{name}.evals.json"
    if not evals_file.exists():
        pytest.skip("goldens not generated")
    evals = json.loads(evals_file.read_text())
    ref_key = next(k for k in evals if k.endswith(metric))
    ref_final = evals[ref_key][-1][1]

    conf = _parse_conf(REF_EXAMPLES / name / "train.conf")
    if not _conf_is_stochastic(conf):
        # deterministic pipeline end to end -> tight band (VERDICT item 6)
        rtol = min(rtol, DETERMINISTIC_RTOL)
    ex = _load_example(name, stem)
    params = {
        k: v
        for k, v in conf.items()
        if k
        not in (
            "task",
            "data",
            "valid_data",
            "output_model",
            "is_training_metric",
            "metric_freq",
            "label_column",
        )
    }
    params["verbosity"] = -1
    num_rounds = int(params.pop("num_trees", 100))
    d = lgb.Dataset(ex["X"], ex["y"], group=ex.get("group"))
    ev = {}
    lgb.train(
        params,
        d,
        num_boost_round=num_rounds,
        valid_sets=[d],
        valid_names=["training"],
        callbacks=[lgb.record_evaluation(ev)],
    )
    metric_key = next(k for k in ev["training"] if k == metric or metric in k)
    ours_final = ev["training"][metric_key][-1]
    is_higher_better = metric.startswith("ndcg") or metric == "auc"
    if is_higher_better:
        assert ours_final >= ref_final * (1 - rtol), (ours_final, ref_final)
    else:
        assert ours_final <= ref_final * (1 + rtol), (ours_final, ref_final)


def test_forcedbins_golden_parity():
    """Forced bin bounds vs the reference CLI on identical data: the
    reference's model (trained with forcedbins_filename) cross-loads and
    reproduces its predictions, our forced-bins training splits at the
    same forced thresholds, and final train l2 matches within tolerance
    (fixtures from tests/golden/generate_forcedbins.py)."""
    model_file = GOLDEN / "forcedbins.model.txt"
    if not model_file.exists():
        pytest.skip("forced-bins goldens not generated")
    arr = np.loadtxt(GOLDEN / "forcedbins.train.csv", delimiter=",")
    y, X = arr[:, 0], arr[:, 1:]
    # cross-load: reference model + its own predictions
    ref = lgb.Booster(model_str=model_file.read_text())
    want = np.loadtxt(GOLDEN / "forcedbins.preds.txt", ndmin=1)
    np.testing.assert_allclose(ref.predict(X), want, rtol=1e-4, atol=1e-5)
    # the reference's feature-0 split thresholds honor the forced bounds:
    # every 1.25-adjacent threshold IS a forced bound
    params = {
        "objective": "regression", "learning_rate": 0.2, "num_leaves": 8,
        "max_bin": 16, "min_data_in_leaf": 20, "verbosity": -1,
        "forcedbins_filename": str(GOLDEN / "forcedbins.bounds.json"),
    }
    ds = lgb.Dataset(X, y, params=params)
    b = lgb.train(params, ds, 8)
    ub0 = ds.bin_mappers[0].bin_upper_bound
    for forced in (-3.0, 1.25, 2.5):
        assert forced in ub0
    # both engines must find the step at the forced 1.25 boundary: compare
    # the feature-0 thresholds used by the first tree
    def _f0_thresholds(booster):
        s = booster.model_to_string()
        tree0 = s.split("Tree=1")[0]
        feats, thrs = None, None
        for line in tree0.splitlines():
            if line.startswith("split_feature="):
                feats = [int(t) for t in line.split("=")[1].split()]
            if line.startswith("threshold="):
                thrs = [float(t) for t in line.split("=")[1].split()]
        return {t for f, t in zip(feats, thrs) if f == 0}
    ours, refs = _f0_thresholds(b), _f0_thresholds(ref)
    assert 1.25 in refs and 1.25 in ours
    # training quality parity on the same data/params
    mse_ref = float(np.mean((ref.predict(X) - y) ** 2))
    mse_ours = float(np.mean((b.predict(X) - y) ** 2))
    assert mse_ours <= mse_ref * 1.05, (mse_ours, mse_ref)


# scenario names only; the FULL per-scenario params travel WITH the
# fixtures (scen_<name>.params.json, written by generate_scenarios.py
# from its single SCENARIOS table) so regenerating goldens can never
# desync the test's training configuration
_SCENARIO_NAMES = [
    "cegb", "goss", "monotone_advanced", "monotone_basic", "quantized",
    "widebin", "obj_tweedie", "obj_poisson", "obj_quantile", "obj_huber",
    "obj_gamma", "obj_fair", "obj_mape", "obj_l1", "dart", "bagging",
    "obj_xentropy", "obj_xentlambda", "weighted", "interaction",
    "forcedsplits", "categorical", "linear", "bundle",
]


@pytest.mark.parametrize("name", _SCENARIO_NAMES)
def test_scenario_golden_parity(name):
    """Feature-scenario goldens (tests/golden/generate_scenarios.py): the
    reference's model cross-loads bit-consistently, and our training with
    the same feature engaged reaches the reference's final train metric
    (the scenario's own metric, from its params.json) within tolerance.
    Covers monotone (basic+advanced), CEGB, quantized gradients,
    max_bin=1024, GOSS, and the tweedie/poisson/quantile/huber objective
    families against the reference's own runs."""
    model_file = GOLDEN / f"scen_{name}.model.txt"
    if not model_file.exists():
        pytest.skip("scenario goldens not generated")
    arr = np.loadtxt(GOLDEN / f"scen_{name}.train.csv", delimiter=",")
    y, X = arr[:, 0], arr[:, 1:]
    ref = lgb.Booster(model_str=model_file.read_text())
    want = np.loadtxt(GOLDEN / f"scen_{name}.preds.txt", ndmin=1)
    np.testing.assert_allclose(ref.predict(X), want, rtol=1e-4, atol=1e-5)
    params = json.loads((GOLDEN / f"scen_{name}.params.json").read_text())
    params["verbosity"] = -1
    rounds = int(params.pop("num_trees", 10))
    # aux files travel as scen_<name>.<filename>; rewrite path params
    for k, v in list(params.items()):
        if k.endswith("_filename") and v:
            params[k] = str(GOLDEN / f"scen_{name}.{v}")
    metric = params.get("metric", "l2")
    evals = json.loads((GOLDEN / f"scen_{name}.evals.json").read_text())
    ref_key = next(k for k in evals if k.endswith(metric))
    ref_final = evals[ref_key][-1][1]
    wfile = GOLDEN / f"scen_{name}.train.csv.weight"
    weight = np.loadtxt(wfile, ndmin=1) if wfile.exists() else None
    ds = lgb.Dataset(X, y, weight=weight, params=params)
    ev = {}
    b = lgb.train(
        params, ds, rounds, valid_sets=[ds], valid_names=["training"],
        callbacks=[lgb.record_evaluation(ev)],
    )
    metric_key = next(k for k in ev["training"] if metric in k)
    ours_final = ev["training"][metric_key][-1]
    # stochastic modes (goss, quantized, dart drops, bagging draws) run
    # different RNG streams by design and get a wider band; deterministic
    # modes track much closer in practice.  additive-over-|ref| band: all
    # these metrics are lower-is-better but NLL-style ones
    # (poisson/tweedie/gamma) can go NEGATIVE, where a multiplicative
    # bound would invert into a stricter-than-parity test
    rtol = 0.15 if name in ("goss", "quantized", "dart", "bagging") else 0.05
    assert ours_final <= ref_final + rtol * abs(ref_final) + 1e-9, (
        ours_final, ref_final,
    )
    if name == "categorical":
        # both engines must actually have used categorical (bitset) splits
        for bst in (ref, b):
            assert "cat_threshold=" in bst.model_to_string()
    if name == "bundle":
        # EFB must actually have engaged on our side, and both models must
        # speak original-feature space (numeric one-hot thresholds, ids
        # within the raw column count)
        ds.construct()
        assert ds.bundle_layout is not None and ds.bundle_layout.has_bundles
        assert ds.num_planes < len(ds.used_features)
        for bst in (ref, b):
            txt = bst.model_to_string()
            assert "cat_threshold=" not in txt
            for line in txt.splitlines():
                if line.startswith("split_feature="):
                    ids = [int(t) for t in line.split("=")[1].split()]
                    assert all(0 <= i < X.shape[1] for i in ids)
    if name == "forcedsplits":
        # both engines must root at the forced feature 2 with the SAME
        # bin-snapped threshold (both snap the forced 0.5 to the nearest
        # bin upper bound; equal-count binning on identical data agrees)
        roots = []
        for bst in (ref, b):
            tree0 = bst.model_to_string().split("Tree=1")[0]
            feats = thrs = None
            for line in tree0.splitlines():
                if line.startswith("split_feature="):
                    feats = [int(t) for t in line.split("=")[1].split()]
                if line.startswith("threshold="):
                    thrs = [float(t) for t in line.split("=")[1].split()]
            roots.append((feats[0], thrs[0]))
        assert roots[0][0] == roots[1][0] == 2, roots
        assert abs(roots[0][1] - 0.5) < 0.05, roots  # snapped near 0.5
        assert abs(roots[0][1] - roots[1][1]) < 1e-6, roots
    if name.startswith("monotone"):
        # the produced model must actually satisfy the constraints
        rng2 = np.random.default_rng(0)
        base_pts = rng2.normal(size=(200, X.shape[1]))
        for fi, sign in ((0, 1), (1, -1)):
            lo, hi = base_pts.copy(), base_pts.copy()
            lo[:, fi] -= 1.0
            hi[:, fi] += 1.0
            d = b.predict(hi) - b.predict(lo)
            assert (sign * d >= -1e-9).all(), f"constraint violated on f{fi}"


@pytest.mark.parametrize("stem", ["forcedbins", "scen_monotone_basic"])
def test_shap_contrib_golden_parity(stem):
    """TreeSHAP contributions vs the reference CLI's predict_contrib=true
    on the SAME model file — deterministic, so the comparison is tight
    (fixtures from tests/golden/generate_contribs.py; reference analog
    src/treelearner/../tree.cpp TreeSHAP / pred_contrib)."""
    contribs_file = GOLDEN / f"{stem}.contribs.txt"
    if not contribs_file.exists():
        pytest.skip("contrib goldens not generated")
    arr = np.loadtxt(GOLDEN / f"{stem}.train.csv", delimiter=",")
    X = arr[:500, 1:]
    b = lgb.Booster(model_str=(GOLDEN / f"{stem}.model.txt").read_text())
    want = np.loadtxt(contribs_file, delimiter="\t", ndmin=2)
    got = b.predict(X, pred_contrib=True)
    assert got.shape == want.shape  # [n, F+1] incl. the expected-value col
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    # contributions must sum to the raw prediction (SHAP identity)
    raw = b.predict(X, raw_score=True)
    np.testing.assert_allclose(got.sum(axis=1), raw, rtol=1e-6, atol=1e-6)


def test_refit_golden_parity():
    """Booster.refit vs the reference CLI's task=refit on the same model
    and data (reference GBDT::RefitTree; deterministic, so leaf values
    compare tightly — fixtures from tests/golden/generate_refit.py)."""
    model_file = GOLDEN / "refit.model.txt"
    if not model_file.exists():
        pytest.skip("refit goldens not generated")
    arr = np.loadtxt(GOLDEN / "refit.refit.csv", delimiter=",")
    y2, X = arr[:, 0], arr[:, 1:]
    b = lgb.Booster(model_str=model_file.read_text())
    ours = b.refit(X, y2, decay_rate=0.9)
    ref = lgb.Booster(
        model_str=(GOLDEN / "refit.refit_model.txt").read_text()
    )

    def _leaf_values(booster):
        vals = []
        for line in booster.model_to_string().splitlines():
            if line.startswith("leaf_value="):
                vals.extend(float(t) for t in line.split("=")[1].split())
        return np.asarray(vals)

    lv_ours, lv_ref = _leaf_values(ours), _leaf_values(ref)
    assert lv_ours.shape == lv_ref.shape
    np.testing.assert_allclose(lv_ours, lv_ref, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        ours.predict(X), ref.predict(X), rtol=1e-5, atol=1e-6
    )


def test_position_debias_golden_parity():
    """Unbiased lambdarank vs the reference on the same data + .position
    sidecar (reference Metadata::LoadPositions + RankingObjective position
    bias factors): their model cross-loads, the .position sidecar loads
    through our text path, and our final train ndcg@3 lands within
    tolerance of the reference's trajectory."""
    model_file = GOLDEN / "position.model.txt"
    if not model_file.exists():
        pytest.skip("position goldens not generated")
    evals = json.loads((GOLDEN / "position.evals.json").read_text())
    ref_ndcg = evals["training:ndcg@3"][-1][1]
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import _load_text_file

    loaded = _load_text_file(str(GOLDEN / "position.train.csv"),
                             Config.from_params({}))
    X, y = np.asarray(loaded["data"]), np.asarray(loaded["label"])
    assert loaded.get("position") is not None  # sidecar picked up
    ref = lgb.Booster(model_str=model_file.read_text())
    assert np.isfinite(ref.predict(X)).all()
    params = {
        "objective": "lambdarank", "learning_rate": 0.15, "num_leaves": 31,
        "min_data_in_leaf": 10, "verbosity": -1, "metric": "ndcg",
        "eval_at": [3], "lambdarank_position_bias_regularization": 0.5,
    }
    ds = lgb.Dataset(str(GOLDEN / "position.train.csv"), params=params)
    # Train under a PRIVATE persistent-compilation-cache dir.  The 3/8
    # "flake" this test had was never model nondeterminism: with the cache
    # off, the trained model dump is bit-identical across PYTHONHASHSEED
    # values and device counts.  The machine-wide /tmp/lgbm_jax_cache the
    # suite shares (conftest.py) is also written by non-suite processes
    # (bench, smokes, debug shells) under other XLA topologies, and certain
    # cache states serve this test's lambdarank programs an executable
    # whose scores go NON-FINITE (observed: booster._score NaN, trees stop
    # growing, ndcg frozen ~0.63-0.84).  Which entry gets hit varies with
    # PYTHONHASHSEED via jaxpr-metadata ordering in the cache key — hence
    # the intermittent look.  A fresh private dir makes the quality bar
    # deterministic again (compile-from-scratch, ~3 s).
    import tempfile

    import jax
    from jax.experimental.compilation_cache import compilation_cache as _cc

    prev_dir = jax.config.jax_compilation_cache_dir
    with tempfile.TemporaryDirectory() as td:
        try:
            _cc.reset_cache()
            jax.config.update("jax_compilation_cache_dir", td)
            ev = {}
            lgb.train(
                params, ds, 10, valid_sets=[ds], valid_names=["training"],
                callbacks=[lgb.record_evaluation(ev)],
            )
        finally:
            jax.config.update("jax_compilation_cache_dir", prev_dir)
            _cc.reset_cache()
    key = next(k for k in ev["training"] if "ndcg" in k)
    ours = ev["training"][key][-1]
    assert ours >= ref_ndcg * 0.95, (ours, ref_ndcg)
