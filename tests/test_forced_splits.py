"""Forced splits via forcedsplits_filename (reference:
SerialTreeLearner::ForceSplits, serial_tree_learner.cpp:627 — BFS over the
JSON, thresholds quantized through the BinMapper, negative-gain forced splits
aborted)."""

import json

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import lightgbm_tpu as lgb  # noqa: E402


@pytest.fixture()
def xy():
    rng = np.random.default_rng(0)
    n = 800
    X = rng.normal(size=(n, 3))
    # feature 0 dominates; an unforced tree splits it first
    y = 3.0 * (X[:, 0] > 0) + 0.5 * (X[:, 1] > 0) + rng.normal(scale=0.1, size=n)
    return X, y


def _first_tree(X, y, fs_file):
    params = {
        "objective": "regression",
        "num_leaves": 8,
        "min_data_in_leaf": 5,
        "verbosity": -1,
        "forcedsplits_filename": fs_file,
    }
    return lgb.train(params, lgb.Dataset(X, y), 1).models_[0]


def test_root_split_is_forced(xy, tmp_path):
    X, y = xy
    fs = tmp_path / "forced.json"
    fs.write_text(json.dumps({"feature": 1, "threshold": 0.0}))
    tree = _first_tree(X, y, str(fs))
    assert tree.split_feature[0] == 1
    # sanity: without forcing, feature 0 wins
    tree_free = _first_tree(X, y, "")
    assert tree_free.split_feature[0] == 0


def test_nested_forced_splits_follow_bfs(xy, tmp_path):
    X, y = xy
    fs = tmp_path / "forced.json"
    fs.write_text(
        json.dumps(
            {
                "feature": 1,
                "threshold": 0.0,
                "left": {"feature": 2, "threshold": 0.5},
                "right": {"feature": 2, "threshold": -0.5},
            }
        )
    )
    tree = _first_tree(X, y, str(fs))
    # step 0: root on feature 1; steps 1/2: both children on feature 2
    assert tree.split_feature[0] == 1
    assert tree.split_feature[1] == 2
    assert tree.split_feature[2] == 2
    # node 0's children are the forced nodes (left keeps the leaf id ->
    # becomes node 1; right leaf 1 -> node 2)
    assert tree.left_child[0] == 1
    assert tree.right_child[0] == 2


def test_bad_forced_split_aborts_and_growth_continues(xy, tmp_path):
    X, y = xy
    Xc = X.copy()
    Xc[:, 2] = 1.0  # constant feature: zero-gain forced split
    fs = tmp_path / "forced.json"
    fs.write_text(
        json.dumps(
            {
                "feature": 2,
                "threshold": 0.5,
                "left": {"feature": 1, "threshold": 0.0},
            }
        )
    )
    tree = _first_tree(Xc, y, str(fs))
    # the forced split failed; normal growth picked the best feature instead
    assert tree.num_leaves > 1
    assert tree.split_feature[0] == 0


def test_forced_split_model_predicts_consistently(xy, tmp_path):
    X, y = xy
    fs = tmp_path / "forced.json"
    fs.write_text(json.dumps({"feature": 1, "threshold": 0.0}))
    params = {
        "objective": "regression",
        "num_leaves": 8,
        "min_data_in_leaf": 5,
        "verbosity": -1,
        "forcedsplits_filename": str(fs),
        "metric": "l2",
    }
    ev = {}
    b = lgb.train(
        params, lgb.Dataset(X, y), 8,
        valid_sets=[lgb.Dataset(X, y)], valid_names=["t"],
        callbacks=[lgb.record_evaluation(ev)],
    )
    pred = b.predict(X)
    assert float(np.mean((pred - y) ** 2)) == pytest.approx(
        ev["t"]["l2"][-1], rel=1e-3
    )
    for t in b.models_:
        assert t.split_feature[0] == 1  # every tree honors the forced root
