"""Frontier-batched growth (``leaf_batch=K``): the grower splits up to K
frontier leaves per compiled loop step, committing the longest prefix of the
gain-sorted batch whose members each beat every child created by earlier
members (strictly) — which is exactly when serial leaf-wise argmax would have
picked them next.  The committed split SEQUENCE is therefore identical to
serial growth; these tests assert structure equality (split features / bins /
topology / leaf counts) and leaf-value closeness across K for every supported
scenario.

Row ORDER inside a leaf window may differ from serial (uncommitted members
still physically partition their window before being rolled back as
value-preserving no-ops), so the tests compare tree structure, not
intermediate buffers.
"""

import dataclasses
import json

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.ops.grower import GrowerParams, grow_tree  # noqa: E402
from lightgbm_tpu.parallel import (  # noqa: E402
    DATA_AXIS,
    l2_gradients,
    make_data_parallel_train_step,
    replicate,
    shard_rows,
)

N, F, B = 600, 6, 16
KS = [2, 4, 8]


def _problem(seed=0, n=N, f=F, b=B):
    rs = np.random.RandomState(seed)
    bins = jnp.asarray(rs.randint(0, b, size=(n, f)), jnp.int32)
    grad = jnp.asarray(rs.randn(n), jnp.float32)
    hess = jnp.asarray(np.abs(rs.randn(n)) + 0.1, jnp.float32)
    mask = jnp.ones((n,), jnp.float32)
    num_bins = jnp.full((f,), b, jnp.int32)
    nan_bins = jnp.full((f,), -1, jnp.int32)
    fm = jnp.ones((f,), bool)
    return bins, grad, hess, mask, num_bins, nan_bins, fm


def _grow(problem, params, **kw):
    bins, grad, hess, mask, num_bins, nan_bins, fm = problem
    return grow_tree(bins, grad, hess, mask, num_bins, nan_bins, fm, params, **kw)


def _assert_same_tree(got, ref, *, check_leaf_id=True):
    ta, lid = got
    ta1, lid1 = ref
    assert int(ta.num_leaves) == int(ta1.num_leaves)
    np.testing.assert_array_equal(
        np.asarray(ta.split_feature), np.asarray(ta1.split_feature)
    )
    np.testing.assert_array_equal(np.asarray(ta.split_bin), np.asarray(ta1.split_bin))
    np.testing.assert_array_equal(
        np.asarray(ta.left_child), np.asarray(ta1.left_child)
    )
    np.testing.assert_array_equal(
        np.asarray(ta.right_child), np.asarray(ta1.right_child)
    )
    np.testing.assert_array_equal(
        np.asarray(ta.default_left), np.asarray(ta1.default_left)
    )
    np.testing.assert_allclose(
        np.asarray(ta.leaf_value), np.asarray(ta1.leaf_value), rtol=1e-5, atol=1e-6
    )
    if check_leaf_id:
        np.testing.assert_array_equal(np.asarray(lid), np.asarray(lid1))


@pytest.mark.parametrize("mode", ["seg", "ordered", "gather", "full"])
@pytest.mark.parametrize("K", KS)
def test_parity_hist_modes(mode, K):
    prob = _problem(0)
    p1 = GrowerParams(num_leaves=15, max_bin=B, hist_mode=mode, min_data_in_leaf=5)
    ref = _grow(prob, p1)
    got = _grow(prob, dataclasses.replace(p1, leaf_batch=K))
    _assert_same_tree(got, ref)


@pytest.mark.parametrize("K", KS)
def test_parity_categorical(K):
    prob = _problem(1)
    is_cat = jnp.asarray([True, False, True, False, False, True])
    p1 = GrowerParams(
        num_leaves=15, max_bin=B, hist_mode="ordered", min_data_in_leaf=5, use_cat=True
    )
    ref = _grow(prob, p1, is_cat=is_cat)
    got = _grow(prob, dataclasses.replace(p1, leaf_batch=K), is_cat=is_cat)
    _assert_same_tree(got, ref)


@pytest.mark.parametrize("K", KS)
def test_parity_monotone_basic(K):
    prob = _problem(2)
    mono = jnp.asarray([1, -1, 0, 0, 1, 0], jnp.int8)
    p1 = GrowerParams(
        num_leaves=15,
        max_bin=B,
        hist_mode="ordered",
        min_data_in_leaf=5,
        use_monotone=True,
        monotone_method="basic",
    )
    ref = _grow(prob, p1, monotone=mono)
    got = _grow(prob, dataclasses.replace(p1, leaf_batch=K), monotone=mono)
    _assert_same_tree(got, ref)


@pytest.mark.parametrize("K", [2, 4])
def test_parity_extra_trees(K):
    prob = _problem(3)
    p1 = GrowerParams(
        num_leaves=15, max_bin=B, hist_mode="gather", min_data_in_leaf=5,
        extra_trees=True,
    )
    rng = jax.random.PRNGKey(42)
    ref = _grow(prob, p1, rng=rng)
    got = _grow(prob, dataclasses.replace(p1, leaf_batch=K), rng=rng)
    _assert_same_tree(got, ref)


# ---- prefix-commit edge cases -------------------------------------------

@pytest.mark.parametrize("K", KS)
def test_prefix_commit_sequential_gains(K):
    """A single dominant feature makes every new child the next-best leaf:
    only the first batch member can commit each step (the rest lose to its
    children), so the batched loop degenerates to serial one-at-a-time — and
    must still match exactly."""
    rs = np.random.RandomState(4)
    n = 800
    bins = jnp.asarray(rs.randint(0, B, size=(n, F)), jnp.int32)
    # gradient is a steep function of feature 0 alone: refining feature 0
    # always produces the next-highest-gain leaf
    grad = jnp.asarray(-np.power(2.0, np.asarray(bins[:, 0]) / 2.0), jnp.float32)
    hess = jnp.ones((n,), jnp.float32)
    prob = (
        bins, grad, hess, jnp.ones((n,), jnp.float32),
        jnp.full((F,), B, jnp.int32), jnp.full((F,), -1, jnp.int32),
        jnp.ones((F,), bool),
    )
    p1 = GrowerParams(num_leaves=12, max_bin=B, hist_mode="ordered", min_data_in_leaf=2)
    ref = _grow(prob, p1)
    got = _grow(prob, dataclasses.replace(p1, leaf_batch=K))
    _assert_same_tree(got, ref)


@pytest.mark.parametrize("K", KS)
def test_prefix_commit_independent_gains(K):
    """Additively separable target over independent features: frontier leaves
    have unrelated gains, so most batch members commit every step (the
    all-committed edge)."""
    rs = np.random.RandomState(5)
    n = 1200
    bins = jnp.asarray(rs.randint(0, B, size=(n, F)), jnp.int32)
    b_np = np.asarray(bins)
    grad = jnp.asarray(
        -(
            4.0 * (b_np[:, 0] > 8)
            + 2.0 * (b_np[:, 1] > 8)
            + 1.0 * (b_np[:, 2] > 8)
            + 0.5 * (b_np[:, 3] > 8)
            + 0.25 * (b_np[:, 4] > 8)
        ),
        jnp.float32,
    )
    hess = jnp.ones((n,), jnp.float32)
    prob = (
        bins, grad, hess, jnp.ones((n,), jnp.float32),
        jnp.full((F,), B, jnp.int32), jnp.full((F,), -1, jnp.int32),
        jnp.ones((F,), bool),
    )
    p1 = GrowerParams(num_leaves=15, max_bin=B, hist_mode="seg", min_data_in_leaf=2)
    ref = _grow(prob, p1)
    got = _grow(prob, dataclasses.replace(p1, leaf_batch=K))
    _assert_same_tree(got, ref)


@pytest.mark.parametrize("K", KS)
def test_prefix_commit_tie_gains(K):
    """Duplicated feature columns give exact cross-feature gain ties; top_k
    and argmax both break ties toward the lowest index, so the batched
    frontier selection must agree with serial."""
    rs = np.random.RandomState(6)
    n = 600
    bins_np = rs.randint(0, B, size=(n, F))
    bins_np[:, 1] = bins_np[:, 0]  # exact duplicate -> identical gains
    bins_np[:, 3] = bins_np[:, 2]
    bins = jnp.asarray(bins_np, jnp.int32)
    grad = jnp.asarray(rs.randn(n), jnp.float32)
    hess = jnp.ones((n,), jnp.float32)
    prob = (
        bins, grad, hess, jnp.ones((n,), jnp.float32),
        jnp.full((F,), B, jnp.int32), jnp.full((F,), -1, jnp.int32),
        jnp.ones((F,), bool),
    )
    p1 = GrowerParams(num_leaves=15, max_bin=B, hist_mode="gather", min_data_in_leaf=5)
    ref = _grow(prob, p1)
    got = _grow(prob, dataclasses.replace(p1, leaf_batch=K))
    _assert_same_tree(got, ref)


def test_leaf_batch_clamped_to_frontier():
    """K larger than num_leaves-1 is clamped, not an error."""
    prob = _problem(7)
    p1 = GrowerParams(num_leaves=4, max_bin=B, hist_mode="ordered", min_data_in_leaf=5)
    ref = _grow(prob, p1)
    got = _grow(prob, dataclasses.replace(p1, leaf_batch=16))
    _assert_same_tree(got, ref)


# ---- e2e booster ---------------------------------------------------------

def _tree_dump(bst):
    return [
        (
            list(t.split_feature),
            list(t.left_child),
            list(t.right_child),
            [round(float(v), 5) for v in t.leaf_value],
        )
        for t in bst.models_
    ]


@pytest.mark.parametrize("K", KS)
def test_booster_e2e_structure_matches_serial(K):
    rng = np.random.default_rng(0)
    n = 800
    X = rng.normal(size=(n, 4))
    y = 3.0 * (X[:, 0] > 0) + 0.5 * (X[:, 1] > 0) + rng.normal(scale=0.1, size=n)
    base = {
        "objective": "regression",
        "num_leaves": 12,
        "min_data_in_leaf": 5,
        "verbosity": -1,
    }
    ref = lgb.train(base, lgb.Dataset(X, y), 3)
    got = lgb.train({**base, "leaf_batch": K}, lgb.Dataset(X, y), 3)
    assert _tree_dump(got) == _tree_dump(ref)


@pytest.mark.parametrize("K", [2, 4])
def test_booster_e2e_forced_splits(K, tmp_path):
    rng = np.random.default_rng(1)
    n = 800
    X = rng.normal(size=(n, 4))
    y = 3.0 * (X[:, 0] > 0) + 0.5 * (X[:, 1] > 0) + rng.normal(scale=0.1, size=n)
    fs = tmp_path / "forced.json"
    fs.write_text(
        json.dumps(
            {
                "feature": 1,
                "threshold": 0.0,
                "left": {"feature": 2, "threshold": 0.5},
                "right": {"feature": 2, "threshold": -0.5},
            }
        )
    )
    base = {
        "objective": "regression",
        "num_leaves": 12,
        "min_data_in_leaf": 5,
        "verbosity": -1,
        "forcedsplits_filename": str(fs),
    }
    ref = lgb.train(base, lgb.Dataset(X, y), 2)
    got = lgb.train({**base, "leaf_batch": K}, lgb.Dataset(X, y), 2)
    assert _tree_dump(got) == _tree_dump(ref)
    # the forced chain actually took effect
    assert ref.models_[0].split_feature[0] == 1


def test_unsupported_mode_falls_back_to_serial():
    """Interaction constraints aren't batched: the booster must warn, drop
    to leaf_batch=1, and train identically to serial."""
    rng = np.random.default_rng(2)
    n = 500
    X = rng.normal(size=(n, 4))
    y = X[:, 0] + X[:, 2] + rng.normal(scale=0.1, size=n)
    base = {
        "objective": "regression",
        "num_leaves": 8,
        "min_data_in_leaf": 5,
        "verbosity": -1,
        "interaction_constraints": [[0, 1], [2, 3]],
    }
    ref = lgb.train(base, lgb.Dataset(X, y), 2)
    got = lgb.train({**base, "leaf_batch": 4}, lgb.Dataset(X, y), 2)
    assert _tree_dump(got) == _tree_dump(ref)


def test_leaf_batch_validation():
    with pytest.raises(ValueError):
        lgb.Config.from_params({"leaf_batch": 0})


# ---- data-parallel -------------------------------------------------------

@pytest.mark.parametrize("K", [2, 4])
def test_parity_data_parallel(K, cpu_mesh_devices):
    """Sharded batched growth == sharded serial growth == single-device
    serial growth: the commit decisions derive from psummed quantities, so
    every shard takes the same trip count."""
    rng = np.random.default_rng(21)
    n = 512
    bins = rng.integers(0, B - 1, size=(n, F), dtype=np.int32)
    label = (bins[:, 0] * 0.3 - bins[:, 1] * 0.1 + rng.normal(size=n)).astype(
        np.float32
    )
    mesh = Mesh(np.array(cpu_mesh_devices[:8]), (DATA_AXIS,))

    def run(params):
        step = make_data_parallel_train_step(mesh, params, 0.1, l2_gradients)
        return step(
            shard_rows(bins, mesh),
            shard_rows(label, mesh),
            shard_rows(np.zeros(n, np.float32), mesh),
            replicate(np.full(F, B, np.int32), mesh),
            replicate(np.full(F, -1, np.int32), mesh),
            replicate(np.ones(F, bool), mesh),
        )

    p1 = GrowerParams(
        num_leaves=15, max_bin=B, min_data_in_leaf=5, axis_name=DATA_AXIS
    )
    _, tree_ref = run(p1)
    _, tree = run(dataclasses.replace(p1, leaf_batch=K))
    assert int(tree.num_leaves) == int(tree_ref.num_leaves)
    np.testing.assert_array_equal(
        np.asarray(tree.split_feature), np.asarray(tree_ref.split_feature)
    )
    np.testing.assert_array_equal(
        np.asarray(tree.split_bin), np.asarray(tree_ref.split_bin)
    )
    np.testing.assert_allclose(
        np.asarray(tree.leaf_value),
        np.asarray(tree_ref.leaf_value),
        rtol=1e-4,
        atol=1e-5,
    )


def test_psum_count_per_step_does_not_scale_with_k(cpu_mesh_devices):
    """The batched body issues ONE stacked counts-psum and ONE stacked
    histogram-psum per step regardless of K — so per-tree collective count
    drops by ~the committed batch factor.  Static proxy: the number of psum
    equations in the lowered jaxpr must not grow with K."""
    rng = np.random.default_rng(3)
    n = 512
    bins = rng.integers(0, B - 1, size=(n, F), dtype=np.int32)
    label = (bins[:, 0] * 0.3 + rng.normal(size=n)).astype(np.float32)
    mesh = Mesh(np.array(cpu_mesh_devices[:8]), (DATA_AXIS,))

    def count_psums(params):
        step = make_data_parallel_train_step(mesh, params, 0.1, l2_gradients)
        jx = jax.make_jaxpr(step)(
            shard_rows(bins, mesh),
            shard_rows(label, mesh),
            shard_rows(np.zeros(n, np.float32), mesh),
            replicate(np.full(F, B, np.int32), mesh),
            replicate(np.full(F, -1, np.int32), mesh),
            replicate(np.ones(F, bool), mesh),
        )
        return str(jx).count("psum")

    p1 = GrowerParams(
        num_leaves=15, max_bin=B, min_data_in_leaf=5, axis_name=DATA_AXIS
    )
    serial = count_psums(p1)
    batched = count_psums(dataclasses.replace(p1, leaf_batch=4))
    assert batched <= serial + 2, (batched, serial)
