"""Dask adapter: worker discovery -> per-worker _train_part -> model from
worker 0 (reference python-package/lightgbm/dask.py), driven end-to-end
with a MOCK client whose workers are real subprocesses joining one
jax.distributed CPU cluster (dask itself is not installed here)."""

import os
import pickle
import subprocess
import sys
import textwrap
from pathlib import Path

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.dask import (
    DaskLGBMClassifier,
    DaskLGBMRanker,
    DaskLGBMRegressor,
    _partition_data,
    _split_rows,
)

REPO_ROOT = str(Path(__file__).resolve().parents[1])

_RUNNER = textwrap.dedent(
    """
    import os, sys, pickle, importlib
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    mod, name, args = pickle.load(open(sys.argv[1], "rb"))
    fn = getattr(importlib.import_module(mod), name)
    res = fn(*args)
    pickle.dump(res, open(sys.argv[2], "wb"))
    """
).format(repo=REPO_ROOT)


class MockFuture:
    def __init__(self, proc, out_path):
        self._proc = proc
        self._out = out_path

    def result(self, timeout=300):
        rc = self._proc.wait(timeout=timeout)
        if rc != 0:
            out, err = self._proc.communicate()
            raise RuntimeError(f"worker failed rc={rc}:\n{out}\n{err}")
        with open(self._out, "rb") as f:
            return pickle.load(f)


class MockClient:
    """Duck-typed dask client: scheduler_info + submit; each submitted task
    runs in its own subprocess (a real separate jax process)."""

    def __init__(self, n_workers: int, tmpdir: Path):
        self._addrs = [
            f"tcp://127.0.0.1:{41000 + i}" for i in range(n_workers)
        ]
        self._tmp = tmpdir
        self._n = 0

    def scheduler_info(self):
        return {"workers": {a: {} for a in self._addrs}}

    def submit(self, fn, *args, workers=None, **kw):
        self._n += 1
        inp = self._tmp / f"in_{self._n}.pkl"
        out = self._tmp / f"out_{self._n}.pkl"
        with open(inp, "wb") as f:
            pickle.dump((fn.__module__, fn.__qualname__, args), f)
        proc = subprocess.Popen(
            [sys.executable, "-c", _RUNNER, str(inp), str(out)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            cwd=REPO_ROOT,
        )
        return MockFuture(proc, str(out))


def test_split_rows_group_aware():
    g = np.array([5, 5, 10, 5, 5], np.int64)
    boundaries = np.cumsum(g)
    X = np.arange(30)[:, None]
    parts = _split_rows(X, 2, boundaries)
    sizes = [p.shape[0] for p in parts]
    assert sum(sizes) == 30
    # cut lands exactly on a query boundary
    assert sizes[0] in (10, 15, 20)
    # partitioning requires an EQUAL split on a query boundary: 15/15 exists
    g2 = np.array([5, 10, 10, 5], np.int64)
    pd = _partition_data(X, np.arange(30), None, g2, 2)
    assert sum(int(p["group"].sum()) for p in pd) == 30
    for p in pd:
        assert int(p["group"].sum()) == p["data"].shape[0] == 15


def test_partition_data_even_split_no_group():
    X = np.arange(40).reshape(20, 2)
    parts = _partition_data(X, np.arange(20), np.ones(20), None, 3)
    assert [p["data"].shape[0] for p in parts] == [6, 7, 7]
    assert all(p["group"] is None for p in parts)
    np.testing.assert_array_equal(
        np.concatenate([p["data"] for p in parts]), X
    )


def test_no_workers_raises(tmp_path):
    client = MockClient(0, tmp_path)
    est = DaskLGBMRegressor(client=client, n_estimators=2)
    with pytest.raises(ValueError, match="no dask workers"):
        est.fit(np.zeros((10, 2)), np.zeros(10))


def test_dask_regressor_two_workers_matches_single_process(tmp_path):
    """2 mock workers train one jax.distributed cluster.  With
    integer-valued features (partition-invariant binning, same setup as the
    launcher pre_partition test) the tree STRUCTURE must match a
    single-process run exactly and leaf values to f32 reduction-order
    tolerance."""
    rng = np.random.default_rng(5)
    n = 3000
    X = rng.integers(0, 63, size=(n, 5)).astype(np.float64)
    y = X[:, 0] * 0.2 + np.sin(X[:, 1]) + rng.normal(scale=0.3, size=n)
    client = MockClient(2, tmp_path)
    est = DaskLGBMRegressor(
        client=client,
        n_estimators=8,
        num_leaves=15,
        max_bin=63,
        # pid-derived port: a previous killed run's orphaned workers must
        # not collide with this cluster's coordinator
        local_listen_port=20000 + (os.getpid() % 10000),
    )
    est.fit(X, y)
    # local single-process baseline with identical params
    base = lgb.train(
        {
            **{k: v for k, v in est._lgb_params().items()},
            "tree_learner": "data",
        },
        lgb.Dataset(X, y),
        num_boost_round=8,
    )

    def _structure_and_values(ms):
        struct, vals = [], []
        for line in ms.splitlines():
            if line.startswith(("split_feature=", "threshold=", "decision_type=")):
                struct.append(line)
            elif line.startswith("leaf_value="):
                vals.append([float(v) for v in line.split("=", 1)[1].split()])
        return struct, vals

    s_got, v_got = _structure_and_values(est._Booster.model_to_string())
    s_exp, v_exp = _structure_and_values(base.model_to_string())
    assert s_got == s_exp
    for a, b in zip(v_got, v_exp):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)
    p = est.predict(X)
    # y's std is ~3.6 (X0*0.2 spans 0..12.6); 8 rounds at lr 0.1 shrink it
    assert np.sqrt(np.mean((p - y) ** 2)) < 0.75 * np.std(y)
    # to_local keeps the booster
    local = est.to_local()
    assert np.array_equal(local.predict(X), p)


def test_dask_ranker_groups_not_split(tmp_path):
    rng = np.random.default_rng(7)
    n = 1200
    X = rng.normal(size=(n, 4))
    y = rng.integers(0, 4, n).astype(float)
    grp = np.full(60, 20)
    client = MockClient(2, tmp_path)
    est = DaskLGBMRanker(
        client=client,
        n_estimators=5,
        num_leaves=15,
        local_listen_port=31000 + (os.getpid() % 9000),
    )
    est.fit(X, y, group=grp)
    assert est._Booster.num_trees() == 5
    assert est.predict(X).shape == (n,)


def test_ranker_uneven_groups_rejected(tmp_path):
    g = np.array([7, 5, 9], np.int64)  # 21 rows, no boundary at 10/11
    client = MockClient(2, tmp_path)
    est = DaskLGBMRanker(client=client, n_estimators=2)
    with pytest.raises(ValueError, match="EQUALLY"):
        est.fit(np.zeros((21, 2)), np.zeros(21), group=g)


def test_fit_kwargs_rejected(tmp_path):
    client = MockClient(2, tmp_path)
    est = DaskLGBMRegressor(client=client, n_estimators=2)
    with pytest.raises(NotImplementedError, match="eval_set"):
        est.fit(np.zeros((10, 2)), np.zeros(10), eval_set=[(None, None)])


def test_dask_distributed_predict_matches_local(tmp_path):
    """predict(distributed=True) fans contiguous row partitions out to the
    workers; each worker loads the model string and streams its chunk, and
    the driver's concatenation is bit-identical to a single-host loaded
    booster predicting the same rows."""
    rng = np.random.default_rng(13)
    n = 2000
    X = rng.normal(size=(n, 5))
    y = X[:, 0] * 0.5 + rng.normal(scale=0.2, size=n)
    base = lgb.train(
        {"objective": "regression", "num_leaves": 15, "verbose": -1},
        lgb.Dataset(X, y),
        num_boost_round=5,
    )
    est = DaskLGBMRegressor(client=MockClient(2, tmp_path), n_estimators=5)
    est._Booster = base
    dist = est.predict(X, distributed=True)
    # workers predict from the model STRING (real-space walk) — compare
    # against the same loaded form, not the bin-space training booster
    loaded = lgb.Booster(model_str=base.model_to_string())
    np.testing.assert_array_equal(dist, loaded.predict(X))
    # local (non-distributed) predict is untouched by the fan-out path
    np.testing.assert_array_equal(est.predict(X), base.predict(X))


def test_dask_classifier_multiclass(tmp_path):
    """Labels are encoded and num_class shipped (mirrors LGBMClassifier.fit);
    3-class data must train a multiclass objective, not binary."""
    rng = np.random.default_rng(11)
    n = 1200
    X = rng.normal(size=(n, 4))
    y = np.digitize(X[:, 0], [-0.4, 0.4]) * 10.0  # classes {0, 10, 20}
    client = MockClient(2, tmp_path)
    est = DaskLGBMClassifier(
        client=client,
        n_estimators=5,
        num_leaves=15,
        local_listen_port=22000 + (os.getpid() % 9000),
    )
    est.fit(X, y)
    assert est.n_classes_ == 3
    proba = est.predict_proba(X)
    assert proba.shape == (n, 3)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-6)
    pred = est.predict(X)
    assert set(np.unique(pred)) <= {0.0, 10.0, 20.0}
    assert (pred == y).mean() > 0.8
