"""python-package API parity: Sequence ingestion, Dataset accessors,
Booster utility methods (reference: basic.py public surface)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import lightgbm_tpu as lgb  # noqa: E402


@pytest.fixture(scope="module")
def xy():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(500, 4))
    y = X[:, 0] + 0.5 * X[:, 1] + rng.normal(scale=0.1, size=500)
    return X, y


def test_sequence_ingestion(xy):
    X, y = xy

    class Seq(lgb.Sequence):
        batch_size = 128

        def __init__(self, arr):
            self.arr = arr

        def __getitem__(self, idx):
            return self.arr[idx]

        def __len__(self):
            return len(self.arr)

    d = lgb.Dataset(Seq(X), y)
    b = lgb.train({"objective": "regression", "verbosity": -1}, d, 5)
    d2 = lgb.Dataset(X, y)
    b2 = lgb.train({"objective": "regression", "verbosity": -1}, d2, 5)
    np.testing.assert_allclose(b.predict(X), b2.predict(X))
    # list-of-sequences concatenates
    d3 = lgb.Dataset([Seq(X[:250]), Seq(X[250:])], y)
    assert d3.num_data == 500


def test_dataset_accessors(xy):
    X, y = xy
    d = lgb.Dataset(X, y, free_raw_data=False)
    d.set_feature_name([f"f{i}" for i in range(4)])
    d.construct()
    assert d.get_feature_name() == ["f0", "f1", "f2", "f3"]
    assert d.get_data() is not None
    assert d.feature_num_bin(0) > 1
    assert d.feature_num_bin("f1") > 1
    v = lgb.Dataset(X[:100], y[:100], reference=d)
    assert d in v.get_ref_chain()
    with pytest.raises(ValueError):
        lgb.Dataset(X, y).construct().get_data()  # freed raw


def test_add_features_from(xy):
    X, y = xy
    d1 = lgb.Dataset(X[:, :2], y).construct()
    d2 = lgb.Dataset(X[:, 2:], y).construct()
    d1.add_features_from(d2)
    assert d1.num_total_features == 4
    assert d1.bins.shape[1] == len(d1.used_features)
    b = lgb.Booster({"objective": "regression", "verbosity": -1}, d1)
    b.update()
    assert b.num_trees() == 1


def test_booster_utilities(xy):
    X, y = xy
    b = lgb.train(
        {"objective": "regression", "verbosity": -1, "num_leaves": 7},
        lgb.Dataset(X, y),
        6,
    )
    hist, edges = b.get_split_value_histogram(0)
    assert hist.sum() > 0 and len(edges) == len(hist) + 1
    # model_from_string replaces in place
    other = lgb.train(
        {"objective": "regression", "verbosity": -1, "num_leaves": 3},
        lgb.Dataset(X, y),
        2,
    )
    b2 = lgb.Booster(model_str=other.model_to_string())
    b2.model_from_string(b.model_to_string())
    np.testing.assert_allclose(b2.predict(X), b.predict(X))
    # shuffle_models permutes but preserves the ensemble sum
    before = b.predict(X)
    b.shuffle_models()
    np.testing.assert_allclose(b.predict(X), before, rtol=1e-6)
    b.set_network(num_machines=1)  # no-op shim
    b.set_train_data_name("train")


def test_dask_estimators_constructible():
    # r4: the dask module is a real adapter now (see test_dask.py); the
    # estimators construct without a client and fail at fit time instead
    est = lgb.DaskLGBMRegressor(n_estimators=3)
    with pytest.raises(ValueError, match="client"):
        est.fit([[0.0]], [0.0])


def test_cli_save_binary_round_trip(tmp_path):
    """task=save_binary writes a binary dataset that Dataset(path) later
    auto-detects (reference: application.cpp TaskType::kSaveBinary +
    DatasetLoader binary-magic sniffing)."""
    rng = np.random.default_rng(0)
    X = rng.normal(size=(300, 4))
    y = X[:, 0]
    rows = [
        "\t".join([f"{yy:.6f}"] + [f"{v:.6f}" for v in r])
        for yy, r in zip(y, X)
    ]
    (tmp_path / "train.tsv").write_text("\n".join(rows))
    from lightgbm_tpu.cli import main

    main(
        [
            "task=save_binary",
            f"data={tmp_path/'train.tsv'}",
            f"output_model={tmp_path/'d.bin'}",
            "header=false",
            "label_column=0",
            "verbosity=-1",
        ]
    )
    d = lgb.Dataset(str(tmp_path / "d.bin"), params={"verbosity": -1})
    d.construct()
    assert d.num_data == 300 and d.num_total_features == 4
    b = lgb.train({"objective": "regression", "verbosity": -1}, d, 3)
    assert b.num_trees() == 3


def test_binary_dataset_guard_rails(tmp_path):
    """Binary datasets: explicit fields override the pickled metadata; a
    reference= binary load is rejected (its bins cannot be re-mapped)."""
    rng = np.random.default_rng(1)
    X = rng.normal(size=(200, 3))
    y = X[:, 0]
    d = lgb.Dataset(X, y)
    d.construct()
    f = str(tmp_path / "d.bin")
    d.save_binary(f)
    y2 = -y
    d2 = lgb.Dataset(f, label=y2)
    d2.construct()
    np.testing.assert_array_equal(d2.get_label(), y2)
    ref = lgb.Dataset(X, y)
    with pytest.raises(ValueError, match="bin mappers"):
        lgb.Dataset(f, reference=ref).construct()
