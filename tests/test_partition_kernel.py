"""Oracle tests for the Pallas streaming partition kernel
(ops/pallas/partition.py) against the stable-sort partition it replaces.

The kernel must be BIT-IDENTICAL to ops/segpart.sort_partition (both are
stable partitions of the same window), including untouched neighbors.
Reference semantics: DataPartition::Split (src/treelearner/data_partition.hpp:101).
"""

import numpy as np
import pytest
import jax.numpy as jnp

from lightgbm_tpu.ops.pallas.partition import seg_partition_pallas
from lightgbm_tpu.ops.pallas.seg import pack_rows, padded_rows
from lightgbm_tpu.ops.segpart import sort_partition_xla


@pytest.fixture(scope="module", params=[11, 28])
def packed(request):
    rng = np.random.default_rng(7)
    f, n = request.param, 5000
    n_pad = padded_rows(n)
    bins = rng.integers(0, 256, size=(n, f)).astype(np.int32)
    g = rng.normal(size=n).astype(np.float32)
    h = rng.random(n).astype(np.float32) + 0.5
    m = (rng.random(n) < 0.8).astype(np.float32)
    seg = pack_rows(
        jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h), jnp.asarray(m), n_pad
    )
    catmask = (rng.random(256) < 0.5).astype(np.float32)
    return dict(f=f, n=n, n_pad=n_pad, seg=seg, catmask=catmask)


@pytest.mark.parametrize(
    "sb,cnt,feat,tbin,dl,nanb,iscat",
    [
        (0, 5000, 3, 120, 0, -1, 0),  # root, multi-tile
        (17, 3000, 5, 80, 1, 200, 0),  # unaligned begin, NaN default-left
        (1000, 37, 2, 128, 0, -1, 0),  # tiny segment within one tile
        (513, 1029, 7, 30, 0, -1, 1),  # categorical
        (5, 600, 1, 255, 0, -1, 0),  # all-left
        (9, 600, 1, -1, 0, -1, 0),  # all-right
        (4000, 1000, 10, 100, 0, -1, 0),  # tail of the array
        (130, 255, 4, 100, 0, -1, 0),  # offset > 128 alignment fold
        (333, 0, 0, 10, 0, -1, 0),  # empty window (done step)
        (256, 512, 6, 100, 0, -1, 0),  # exactly tile-aligned window
    ],
)
def test_partition_kernel_matches_sort(packed, sb, cnt, feat, tbin, dl, nanb, iscat):
    p = packed
    if feat >= p["f"]:
        feat = feat % p["f"]
    catm = jnp.asarray(p["catmask"]).reshape(1, 256)
    scal = jnp.asarray([sb, cnt, feat, tbin, dl, nanb, iscat, 0], jnp.int32)
    got, nl_k = seg_partition_pallas(
        p["seg"], scal, catm, f=p["f"], n_pad=p["n_pad"],
        use_cat=True, interpret=True,
    )
    want, nl_s, _ = sort_partition_xla(
        p["seg"], jnp.int32(sb), jnp.int32(cnt), jnp.int32(feat),
        jnp.int32(tbin), jnp.int32(dl), jnp.int32(nanb), jnp.int32(iscat),
        jnp.asarray(p["catmask"]), f=p["f"], n_pad=p["n_pad"],
    )
    assert int(nl_k) == int(nl_s)
    assert np.array_equal(np.asarray(got), np.asarray(want))


def test_partition_kernel_sequential_tree_stress():
    """Drive the kernel through a leaf-wise tree's partition SEQUENCE
    (windows shrink and nest, state carries forward) and require bit-equal
    state vs the sort path after every step — errors would compound."""
    rng = np.random.default_rng(42)
    f, n = 14, 20000
    n_pad = padded_rows(n)
    bins = rng.integers(0, 256, size=(n, f)).astype(np.int32)
    g = rng.normal(size=n).astype(np.float32)
    h = np.ones(n, np.float32)
    m = np.ones(n, np.float32)
    seg_k = pack_rows(
        jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h), jnp.asarray(m), n_pad
    )
    seg_s = seg_k
    catm = jnp.asarray(np.zeros(256, np.float32)).reshape(1, 256)
    # maintain (begin, cnt) segments like the grower does
    segments = [(0, n)]
    for step in range(12):
        # split the largest segment on a pseudo-random feature/threshold
        segments.sort(key=lambda t: -t[1])
        sb, cnt = segments.pop(0)
        if cnt < 2:
            break
        feat = int(rng.integers(0, f))
        tbin = int(rng.integers(20, 236))
        scal = jnp.asarray([sb, cnt, feat, tbin, 0, -1, 0, 0], jnp.int32)
        seg_k, nl_k = seg_partition_pallas(
            seg_k, scal, catm, f=f, n_pad=n_pad, use_cat=False, interpret=True
        )
        seg_s, nl_s, _ = sort_partition_xla(
            seg_s, jnp.int32(sb), jnp.int32(cnt), jnp.int32(feat),
            jnp.int32(tbin), jnp.int32(0), jnp.int32(-1), jnp.int32(0),
            jnp.zeros((1,), jnp.float32), f=f, n_pad=n_pad,
        )
        assert int(nl_k) == int(nl_s), f"step {step}: nl {nl_k} != {nl_s}"
        assert np.array_equal(np.asarray(seg_k), np.asarray(seg_s)), (
            f"state diverged at step {step}"
        )
        nl = int(nl_k)
        segments += [(sb, nl), (sb + nl, cnt - nl)]


def test_partition_kernel_gl_vec_matches_sort():
    """Bits-fed kernel variant (feature-parallel seg): partitioning by a
    precomputed go-left vector must be bit-identical to the column-reading
    sort path given the same bits."""
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(11)
    f, n = 9, 40_000
    n_pad = padded_rows(n)
    bins = rng.integers(0, 256, size=(n, f)).astype(np.int32)
    g = rng.normal(size=n).astype(np.float32)
    h = np.ones(n, np.float32)
    m = np.ones(n, np.float32)
    seg = pack_rows(
        jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h), jnp.asarray(m),
        n_pad,
    )
    for sb, cnt, feat, tbin in ((0, n, 3, 120), (137, 7000, 5, 40)):
        colv = np.zeros(n_pad, np.int64)
        colv[:n] = bins[:, feat]
        glv = jnp.asarray((colv <= tbin).astype(np.float32))
        catm = jnp.zeros((1, 256), jnp.float32)
        scal = jnp.asarray([sb, cnt, feat, tbin, 0, -1, 0, 0], jnp.int32)
        got, nl_k = seg_partition_pallas(
            seg, scal, catm, glv, f=f, n_pad=n_pad, use_cat=False,
            interpret=True,
        )
        want, nl_s, _ = sort_partition_xla(
            seg, jnp.int32(sb), jnp.int32(cnt), jnp.int32(feat),
            jnp.int32(tbin), jnp.int32(0), jnp.int32(-1), jnp.int32(0),
            jnp.zeros((1,), jnp.float32), f=f, n_pad=n_pad,
        )
        assert int(nl_k) == int(nl_s)
        assert np.array_equal(np.asarray(got), np.asarray(want))


def test_partition_kernel_batch_matches_serial_loop():
    """K-program batched launch over DISJOINT windows == K serial kernel
    calls (bit-equal state), including zero-cnt no-op members."""
    from lightgbm_tpu.ops.pallas.partition import seg_partition_pallas_batch

    rng = np.random.default_rng(9)
    f, n = 11, 5000
    n_pad = padded_rows(n)
    bins = rng.integers(0, 256, size=(n, f)).astype(np.int32)
    g = rng.normal(size=n).astype(np.float32)
    h = np.ones(n, np.float32)
    m = np.ones(n, np.float32)
    seg = pack_rows(
        jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h), jnp.asarray(m), n_pad
    )
    catmask = (rng.random(256) < 0.5).astype(np.float32)
    # disjoint windows incl. a zero-cnt member and a categorical member
    rows = [
        (0, 1200, 3, 120, 0, -1, 0, 0),
        (1200, 800, 5, 80, 1, 200, 0, 0),
        (2000, 0, 0, 10, 0, -1, 0, 0),  # no-op
        (2500, 1500, 7, 30, 0, -1, 1, 0),  # categorical
    ]
    scal = jnp.asarray(rows, jnp.int32)
    catm = jnp.broadcast_to(jnp.asarray(catmask), (4, 256))
    got, nl_b = seg_partition_pallas_batch(
        seg, scal, catm, f=f, n_pad=n_pad, use_cat=True, interpret=True,
    )
    want = seg
    nls = []
    for r in rows:
        want, nl, _ = sort_partition_xla(
            want, *(jnp.int32(v) for v in r[:7]),
            jnp.asarray(catmask), f=f, n_pad=n_pad,
        )
        nls.append(int(nl))
    assert [int(v) for v in nl_b] == nls
    assert np.array_equal(np.asarray(got), np.asarray(want))
