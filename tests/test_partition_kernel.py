"""Oracle tests for the Pallas streaming partition kernel
(ops/pallas/partition.py) against the stable-sort partition it replaces.

The kernel must be BIT-IDENTICAL to ops/segpart.sort_partition (both are
stable partitions of the same window), including untouched neighbors.
Reference semantics: DataPartition::Split (src/treelearner/data_partition.hpp:101).
"""

import numpy as np
import pytest
import jax.numpy as jnp

from lightgbm_tpu.ops.pallas.partition import seg_partition_pallas
from lightgbm_tpu.ops.pallas.seg import pack_rows, padded_rows
from lightgbm_tpu.ops.segpart import sort_partition_xla


@pytest.fixture(scope="module", params=[11, 28])
def packed(request):
    rng = np.random.default_rng(7)
    f, n = request.param, 5000
    n_pad = padded_rows(n)
    bins = rng.integers(0, 256, size=(n, f)).astype(np.int32)
    g = rng.normal(size=n).astype(np.float32)
    h = rng.random(n).astype(np.float32) + 0.5
    m = (rng.random(n) < 0.8).astype(np.float32)
    seg = pack_rows(
        jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h), jnp.asarray(m), n_pad
    )
    catmask = (rng.random(256) < 0.5).astype(np.float32)
    return dict(f=f, n=n, n_pad=n_pad, seg=seg, catmask=catmask)


@pytest.mark.parametrize(
    "sb,cnt,feat,tbin,dl,nanb,iscat",
    [
        (0, 5000, 3, 120, 0, -1, 0),  # root, multi-tile
        (17, 3000, 5, 80, 1, 200, 0),  # unaligned begin, NaN default-left
        (1000, 37, 2, 128, 0, -1, 0),  # tiny segment within one tile
        (513, 1029, 7, 30, 0, -1, 1),  # categorical
        (5, 600, 1, 255, 0, -1, 0),  # all-left
        (9, 600, 1, -1, 0, -1, 0),  # all-right
        (4000, 1000, 10, 100, 0, -1, 0),  # tail of the array
        (130, 255, 4, 100, 0, -1, 0),  # offset > 128 alignment fold
        (333, 0, 0, 10, 0, -1, 0),  # empty window (done step)
        (256, 512, 6, 100, 0, -1, 0),  # exactly tile-aligned window
    ],
)
def test_partition_kernel_matches_sort(packed, sb, cnt, feat, tbin, dl, nanb, iscat):
    p = packed
    if feat >= p["f"]:
        feat = feat % p["f"]
    catm = jnp.asarray(p["catmask"]).reshape(1, 256)
    scal = jnp.asarray([sb, cnt, feat, tbin, dl, nanb, iscat, 0], jnp.int32)
    got, nl_k = seg_partition_pallas(
        p["seg"], scal, catm, f=p["f"], n_pad=p["n_pad"],
        use_cat=True, interpret=True,
    )
    want, nl_s, _ = sort_partition_xla(
        p["seg"], jnp.int32(sb), jnp.int32(cnt), jnp.int32(feat),
        jnp.int32(tbin), jnp.int32(dl), jnp.int32(nanb), jnp.int32(iscat),
        jnp.asarray(p["catmask"]), f=p["f"], n_pad=p["n_pad"],
    )
    assert int(nl_k) == int(nl_s)
    assert np.array_equal(np.asarray(got), np.asarray(want))


def test_partition_kernel_sequential_tree_stress():
    """Drive the kernel through a leaf-wise tree's partition SEQUENCE
    (windows shrink and nest, state carries forward) and require bit-equal
    state vs the sort path after every step — errors would compound."""
    rng = np.random.default_rng(42)
    f, n = 14, 20000
    n_pad = padded_rows(n)
    bins = rng.integers(0, 256, size=(n, f)).astype(np.int32)
    g = rng.normal(size=n).astype(np.float32)
    h = np.ones(n, np.float32)
    m = np.ones(n, np.float32)
    seg_k = pack_rows(
        jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h), jnp.asarray(m), n_pad
    )
    seg_s = seg_k
    catm = jnp.asarray(np.zeros(256, np.float32)).reshape(1, 256)
    # maintain (begin, cnt) segments like the grower does
    segments = [(0, n)]
    for step in range(12):
        # split the largest segment on a pseudo-random feature/threshold
        segments.sort(key=lambda t: -t[1])
        sb, cnt = segments.pop(0)
        if cnt < 2:
            break
        feat = int(rng.integers(0, f))
        tbin = int(rng.integers(20, 236))
        scal = jnp.asarray([sb, cnt, feat, tbin, 0, -1, 0, 0], jnp.int32)
        seg_k, nl_k = seg_partition_pallas(
            seg_k, scal, catm, f=f, n_pad=n_pad, use_cat=False, interpret=True
        )
        seg_s, nl_s, _ = sort_partition_xla(
            seg_s, jnp.int32(sb), jnp.int32(cnt), jnp.int32(feat),
            jnp.int32(tbin), jnp.int32(0), jnp.int32(-1), jnp.int32(0),
            jnp.zeros((1,), jnp.float32), f=f, n_pad=n_pad,
        )
        assert int(nl_k) == int(nl_s), f"step {step}: nl {nl_k} != {nl_s}"
        assert np.array_equal(np.asarray(seg_k), np.asarray(seg_s)), (
            f"state diverged at step {step}"
        )
        nl = int(nl_k)
        segments += [(sb, nl), (sb + nl, cnt - nl)]


def test_partition_kernel_gl_vec_matches_sort():
    """Bits-fed kernel variant (feature-parallel seg): partitioning by a
    precomputed go-left vector must be bit-identical to the column-reading
    sort path given the same bits."""
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(11)
    f, n = 9, 40_000
    n_pad = padded_rows(n)
    bins = rng.integers(0, 256, size=(n, f)).astype(np.int32)
    g = rng.normal(size=n).astype(np.float32)
    h = np.ones(n, np.float32)
    m = np.ones(n, np.float32)
    seg = pack_rows(
        jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h), jnp.asarray(m),
        n_pad,
    )
    for sb, cnt, feat, tbin in ((0, n, 3, 120), (137, 7000, 5, 40)):
        colv = np.zeros(n_pad, np.int64)
        colv[:n] = bins[:, feat]
        glv = jnp.asarray((colv <= tbin).astype(np.float32))
        catm = jnp.zeros((1, 256), jnp.float32)
        scal = jnp.asarray([sb, cnt, feat, tbin, 0, -1, 0, 0], jnp.int32)
        got, nl_k = seg_partition_pallas(
            seg, scal, catm, glv, f=f, n_pad=n_pad, use_cat=False,
            interpret=True,
        )
        want, nl_s, _ = sort_partition_xla(
            seg, jnp.int32(sb), jnp.int32(cnt), jnp.int32(feat),
            jnp.int32(tbin), jnp.int32(0), jnp.int32(-1), jnp.int32(0),
            jnp.zeros((1,), jnp.float32), f=f, n_pad=n_pad,
        )
        assert int(nl_k) == int(nl_s)
        assert np.array_equal(np.asarray(got), np.asarray(want))


def test_partition_kernel_batch_matches_serial_loop():
    """K-program batched launch over DISJOINT windows == K serial kernel
    calls (bit-equal state), including zero-cnt no-op members."""
    from lightgbm_tpu.ops.pallas.partition import seg_partition_pallas_batch

    rng = np.random.default_rng(9)
    f, n = 11, 5000
    n_pad = padded_rows(n)
    bins = rng.integers(0, 256, size=(n, f)).astype(np.int32)
    g = rng.normal(size=n).astype(np.float32)
    h = np.ones(n, np.float32)
    m = np.ones(n, np.float32)
    seg = pack_rows(
        jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h), jnp.asarray(m), n_pad
    )
    catmask = (rng.random(256) < 0.5).astype(np.float32)
    # disjoint windows incl. a zero-cnt member and a categorical member
    rows = [
        (0, 1200, 3, 120, 0, -1, 0, 0),
        (1200, 800, 5, 80, 1, 200, 0, 0),
        (2000, 0, 0, 10, 0, -1, 0, 0),  # no-op
        (2500, 1500, 7, 30, 0, -1, 1, 0),  # categorical
    ]
    scal = jnp.asarray(rows, jnp.int32)
    catm = jnp.broadcast_to(jnp.asarray(catmask), (4, 256))
    got, nl_b = seg_partition_pallas_batch(
        seg, scal, catm, f=f, n_pad=n_pad, use_cat=True, interpret=True,
    )
    want = seg
    nls = []
    for r in rows:
        want, nl, _ = sort_partition_xla(
            want, *(jnp.int32(v) for v in r[:7]),
            jnp.asarray(catmask), f=f, n_pad=n_pad,
        )
        nls.append(int(nl))
    assert [int(v) for v in nl_b] == nls
    assert np.array_equal(np.asarray(got), np.asarray(want))


def _aliasing_case():
    """Batched K=2 case where the windows are adjacent and the second
    window's aligned DMA base falls INSIDE the first window: program 1
    re-reads the shared COL_ALIGN boundary block that program 0's
    partition already rewrote."""
    from lightgbm_tpu.ops.pallas.seg import COL_ALIGN

    rng = np.random.default_rng(21)
    f, n = 9, 2000
    n_pad = padded_rows(n)
    bins = rng.integers(0, 256, size=(n, f)).astype(np.int32)
    g = rng.normal(size=n).astype(np.float32)
    seg = pack_rows(
        jnp.asarray(bins), jnp.asarray(g), jnp.ones((n,), jnp.float32),
        jnp.ones((n,), jnp.float32), n_pad,
    )
    # window 0 ends mid-block at 900 (900 % 128 != 0), window 1 begins
    # there: its aligned DMA base (896) re-reads the tail block window 0
    # rewrote
    assert 900 % COL_ALIGN != 0
    rows = [
        (0, 900, 3, 120, 0, -1, 0, 0),
        (900, 1100, 5, 80, 0, -1, 0, 0),
    ]
    return seg, rows, f, n_pad


def test_batch_aliased_boundary_reads_are_correct():
    """Adjacent windows sharing a COL_ALIGN block: the batched kernel must
    equal the sequential sort oracle (program 1 sees program 0's writes)."""
    seg, rows, f, n_pad = _aliasing_case()
    from lightgbm_tpu.ops.pallas.partition import seg_partition_pallas_batch

    scal = jnp.asarray(rows, jnp.int32)
    catm = jnp.zeros((2, 256), jnp.float32)
    got, nl_b = seg_partition_pallas_batch(
        seg, scal, catm, f=f, n_pad=n_pad, use_cat=False, interpret=True,
    )
    want = seg
    nls = []
    for r in rows:
        want, nl, _ = sort_partition_xla(
            want, *(jnp.int32(v) for v in r[:7]),
            jnp.zeros((1,), jnp.float32), f=f, n_pad=n_pad,
        )
        nls.append(int(nl))
    assert [int(v) for v in nl_b] == nls
    assert np.array_equal(np.asarray(got), np.asarray(want))


def test_read_via_input_recreates_aliasing_bug():
    """Regression guard for read_aliased_tile: reading boundary tiles
    through the INPUT ref of the input/output-aliased seg matrix (the
    PR-3 bug) makes interpret mode serve stale pre-partition data to the
    second program — this test FAILS (i.e. the outputs differ) if someone
    reverts the helper to input-ref reads.  If this test ever starts
    asserting equality, the read_via_input knob has stopped modelling the
    bug and both it and this test should be removed together."""
    seg, rows, f, n_pad = _aliasing_case()
    from lightgbm_tpu.ops.pallas.partition import seg_partition_pallas_batch

    scal = jnp.asarray(rows, jnp.int32)
    catm = jnp.zeros((2, 256), jnp.float32)
    good, _ = seg_partition_pallas_batch(
        seg, scal, catm, f=f, n_pad=n_pad, use_cat=False, interpret=True,
    )
    bad, _ = seg_partition_pallas_batch(
        seg, scal, catm, f=f, n_pad=n_pad, use_cat=False, interpret=True,
        read_via_input=True,
    )
    assert not np.array_equal(np.asarray(bad), np.asarray(good)), (
        "read_via_input=True no longer corrupts the shared boundary block; "
        "the aliasing regression knob is not exercising the bug path"
    )


def test_fused_step_aliased_boundary_reads_are_correct():
    """Same aliasing hazard through the FUSED grow-step kernel, which
    re-reads partitioned tiles in its own histogram phase on top of the
    program-to-program boundary: adjacent windows must still match the
    oracle partition state and split decisions bit-for-bit (histogram is
    bf16-vs-f32, compared at kernel tolerance)."""
    from lightgbm_tpu.ops.pallas.grow_step import fused_grow_step_pallas
    from lightgbm_tpu.ops.pallas.grow_step import fused_grow_step
    from lightgbm_tpu.ops.pallas.seg import hist_bpad, hist_ngroups

    seg, rows, f, n_pad = _aliasing_case()
    scal = jnp.asarray(rows, jnp.int32)
    catm = jnp.zeros((2, 256), jnp.float32)
    ones = jnp.ones((2,), jnp.float32)
    live = jnp.ones((hist_ngroups(f, hist_bpad(256)),), jnp.int32)
    seg_k, dec, hist = fused_grow_step_pallas(
        seg, scal, catm, ones, live, f=f, num_bins=256, n_pad=n_pad,
        use_cat=False, interpret=True,
    )
    args = tuple(
        jnp.asarray([rows[0][j], rows[1][j]], jnp.int32) for j in range(7)
    )
    want = fused_grow_step(
        seg, *args, jnp.zeros((2, 1), jnp.float32),
        f=f, num_bins=256, n_pad=n_pad,
    )
    assert np.array_equal(np.asarray(seg_k), np.asarray(want[0]))
    assert np.array_equal(np.asarray(dec[:, 0]), np.asarray(want[1]))  # nl
    np.testing.assert_allclose(
        np.asarray(hist), np.asarray(want[5]), rtol=1e-3, atol=1e-3
    )
    # the input-ref read corrupts this kernel the same way
    seg_bad, _, _ = fused_grow_step_pallas(
        seg, scal, catm, ones, live, f=f, num_bins=256, n_pad=n_pad,
        use_cat=False, interpret=True, read_via_input=True,
    )
    assert not np.array_equal(np.asarray(seg_bad), np.asarray(seg_k))
