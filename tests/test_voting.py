"""Voting-parallel (PV-Tree) tests over the 8-device CPU mesh.

Reference: src/treelearner/voting_parallel_tree_learner.cpp —
GlobalVoting (:152) elects top-2k features from per-machine top-k weighted
gains; only elected histogram slices are aggregated (:396 ReduceScatter).
Here the election is pmax over local top-k masks and the aggregation a psum
of the elected [2k, B, 3] slices (ops/grower._candidate_for_leaf).
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _make_wide(n, f, seed=0, informative=6):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    w = np.zeros(f)
    w[:informative] = rng.normal(size=informative) + 1.0
    y = X @ w + rng.normal(scale=0.3, size=n)
    return X, y


def test_voting_trains_and_learns_high_f():
    """F=64 >> 2*top_k: the election path is live and must still learn."""
    X, y = _make_wide(4000, 64, informative=5)
    params = {
        "objective": "regression",
        "num_leaves": 15,
        "verbosity": -1,
        "metric": "none",
        "tree_learner": "voting",
        "top_k": 4,
        "max_bin": 63,
    }
    b = lgb.train(params, lgb.Dataset(X, y, params=params), 10)
    mse = float(np.mean((b.predict(X) - y) ** 2))
    base = float(np.var(y))
    assert mse < 0.35 * base, (mse, base)
    # informative features dominate the elected splits
    imp = b.feature_importance()
    assert imp[:5].sum() >= 0.6 * imp.sum()


def test_voting_aliases_to_data_below_cutover():
    """F <= 2*top_k: voting must produce the EXACT data-parallel model
    (the documented cutover: dense psum is cheaper and exact there)."""
    X, y = _make_wide(3000, 10, informative=4, seed=1)
    models = {}
    for tl in ("data", "voting"):
        params = {
            "objective": "regression",
            "num_leaves": 15,
            "verbosity": -1,
            "metric": "none",
            "tree_learner": tl,
            "top_k": 20,  # 2k = 40 >= F=10
            "max_bin": 63,
        }
        b = lgb.train(params, lgb.Dataset(X, y, params=params), 5)
        # compare the trees, not the embedded parameters section (that one
        # records tree_learner itself)
        models[tl] = b.model_to_string().split("\nparameters:")[0]
    assert models["data"] == models["voting"]


def test_voting_quality_near_data_parallel():
    """Election is approximate but with informative features sparse it
    should land within a modest factor of the exact learner."""
    X, y = _make_wide(4000, 64, informative=5, seed=2)
    mses = {}
    for tl, k in (("data", 20), ("voting", 4)):
        params = {
            "objective": "regression",
            "num_leaves": 15,
            "verbosity": -1,
            "metric": "none",
            "tree_learner": tl,
            "top_k": k,
            "max_bin": 63,
        }
        b = lgb.train(params, lgb.Dataset(X, y, params=params), 10)
        mses[tl] = float(np.mean((b.predict(X) - y) ** 2))
    assert mses["voting"] <= mses["data"] * 1.25, mses


@pytest.mark.slow
def test_voting_f1024_smoke():
    """VERDICT r2 #7: the high-F regime voting exists for — F=1024 must
    compile and learn on the 8-shard mesh with [2k, B, 3] slice exchange."""
    X, y = _make_wide(2048, 1024, informative=4, seed=3)
    params = {
        "objective": "regression",
        "num_leaves": 7,
        "verbosity": -1,
        "metric": "none",
        "tree_learner": "voting",
        "top_k": 8,
        "max_bin": 15,
        "min_data_in_leaf": 5,
    }
    b = lgb.train(params, lgb.Dataset(X, y, params=params), 3)
    mse = float(np.mean((b.predict(X) - y) ** 2))
    assert mse < 0.9 * float(np.var(y))
