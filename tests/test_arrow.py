"""Arrow ingestion (reference: include/LightGBM/arrow.h +
LGBM_DatasetCreateFromArrow): pyarrow Tables/RecordBatches train and
predict, nulls become NaN, dictionary columns become categorical features.
"""

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")

import lightgbm_tpu as lgb


def _table(n=1200, seed=0):
    rng = np.random.default_rng(seed)
    a = rng.normal(size=n)
    b = rng.normal(size=n)
    c = rng.integers(0, 5, size=n)
    y = a * 2 + (c == 3) * 1.5 + rng.normal(scale=0.2, size=n)
    cat = pa.Array.from_pandas(
        __import__("pandas").Categorical.from_codes(c, list("pqrst"))
    )
    t = pa.table({
        "a": pa.array(a),
        "b": pa.array(b),
        "cat": cat,
    })
    return t, y, np.stack([a, b, c.astype(float)], axis=1)


def test_arrow_table_trains_and_predicts():
    t, y, Xnp = _table()
    params = {"objective": "regression", "verbosity": -1, "min_data_in_leaf": 5}
    d = lgb.Dataset(t, pa.array(y), params=params)
    b = lgb.train(params, d, 8)
    assert d.feature_names == ["a", "b", "cat"]
    # dictionary column auto-marked categorical
    assert b.train_set.bin_mappers[2].is_categorical
    p_arrow = b.predict(t)
    p_np = b.predict(Xnp)
    assert np.array_equal(p_arrow, p_np)
    mse = float(np.mean((p_arrow - y) ** 2))
    assert mse < 0.4 * float(np.var(y))


def test_arrow_nulls_are_nan_and_record_batch():
    rng = np.random.default_rng(1)
    a = rng.normal(size=500)
    mask = rng.random(500) < 0.2
    av = pa.array(np.where(mask, np.nan, a), from_pandas=True)  # nulls
    t = pa.table({"a": av, "b": pa.array(rng.normal(size=500))})
    y = np.where(mask, 3.0, a)
    params = {"objective": "regression", "verbosity": -1, "min_data_in_leaf": 5}
    b = lgb.train(params, lgb.Dataset(t, y, params=params), 8)
    batch = t.to_batches()[0]
    p = b.predict(batch)
    # the NaN rows are separable from the signal
    assert float(np.mean((p - y) ** 2)) < 0.3 * float(np.var(y))


def test_arrow_rejects_string_columns():
    t = pa.table({"s": pa.array(["x", "y", "z"])})
    with pytest.raises(ValueError, match="unsupported type"):
        lgb.Dataset(t, np.zeros(3)).construct()


def test_arrow_dictionary_order_stable_at_predict():
    """Codes must be remapped through the TRAIN dictionary: a predict table
    with the same logical values but a different dictionary order must
    predict identically (reference pandas_categorical remap)."""
    t, y, _ = _table()
    params = {"objective": "regression", "verbosity": -1, "min_data_in_leaf": 5}
    b = lgb.train(params, lgb.Dataset(t, y, params=params), 8)
    p_ref = b.predict(t)

    # re-encode the cat column with a reversed dictionary
    cat_vals = t.column("cat").combine_chunks()
    strings = cat_vals.cast(pa.string())
    rev = pa.DictionaryArray.from_arrays(
        pa.array(
            [list("tsrqp").index(s.as_py()) for s in strings], pa.int32()
        ),
        pa.array(list("tsrqp")),
    )
    t2 = pa.table({"a": t.column("a"), "b": t.column("b"), "cat": rev})
    assert np.array_equal(b.predict(t2), p_ref)


def test_arrow_single_column_table_label():
    t, y, _ = _table(400, seed=3)
    params = {"objective": "regression", "verbosity": -1, "min_data_in_leaf": 5}
    d = lgb.Dataset(t, pa.table({"y": pa.array(y)}), params=params)
    b = lgb.train(params, d, 3)
    assert np.isfinite(b.predict(t)).all()


def test_arrow_dictionary_remap_with_nulls():
    """Nulls in a reordered-dictionary predict table must stay missing, not
    crash the remap (ADVICE r3)."""
    t, y, _ = _table(600, seed=5)
    params = {"objective": "regression", "verbosity": -1, "min_data_in_leaf": 5}
    b = lgb.train(params, lgb.Dataset(t, y, params=params), 5)
    strings = t.column("cat").combine_chunks().cast(pa.string())
    idxs = [
        None if i % 7 == 0 else list("tsrqp").index(s.as_py())
        for i, s in enumerate(strings)
    ]
    rev = pa.DictionaryArray.from_arrays(
        pa.array(idxs, pa.int32()), pa.array(list("tsrqp"))
    )
    t2 = pa.table({"a": t.column("a"), "b": t.column("b"), "cat": rev})
    p = b.predict(t2)
    assert np.isfinite(p).all()
