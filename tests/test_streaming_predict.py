"""Streaming batch-prediction engine (predict.StreamingPredictor).

Contracts under test:
  * chunked/bucket-padded prediction is BIT-IDENTICAL to single-shot for
    bin-space and real-space walkers — including the f64 suspect re-walk
    rows, odd remainder chunks, and the 0-row edge;
  * varying batch sizes NEVER recompile once the bucket ladder is warm
    (streaming_compile_count is the jit cache-miss counter);
  * row-sharding a chunk over a local device mesh changes nothing about
    the output (virtual 8-device CPU mesh from conftest);
  * Booster.compile_predict AOT-builds the ladder so the first predict
    pays no compile.

The 500k-row A/B lives at the bottom and is tier-2 (`slow`); everything
else stays <=5k rows so the engine is exercised on every tier-1 run.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.predict import (
    LADDER_MIN,
    bucket_rows,
    ladder_buckets,
    streaming_compile_count,
)


def _make_binary(n=3000, f=12, seed=3, rounds=15):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    w = rng.normal(size=f)
    y = ((X @ w + rng.normal(scale=0.5, size=n)) > 0).astype(np.float64)
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 31, "verbose": -1},
        lgb.Dataset(X, label=y),
        num_boost_round=rounds,
    )
    return bst, X


def _make_multiclass(n=2500, f=10, seed=4, rounds=8):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = np.digitize(X[:, 0] + 0.3 * X[:, 1], [-0.5, 0.5]).astype(np.float64)
    bst = lgb.train(
        {
            "objective": "multiclass",
            "num_class": 3,
            "num_leaves": 15,
            "verbose": -1,
        },
        lgb.Dataset(X, label=y),
        num_boost_round=rounds,
    )
    return bst, X


@pytest.fixture(scope="module")
def binary_model():
    return _make_binary()


@pytest.fixture(scope="module")
def multiclass_model():
    return _make_multiclass()


def test_bucket_ladder_shapes():
    assert bucket_rows(1, 4096) == LADDER_MIN
    assert bucket_rows(LADDER_MIN, 4096) == LADDER_MIN
    assert bucket_rows(LADDER_MIN + 1, 4096) == 2 * LADDER_MIN
    assert bucket_rows(4096, 4096) == 4096
    assert bucket_rows(9999, 4096) == 4096  # full chunks cap at chunk
    # non-power-of-two chunk still tops the ladder with itself
    assert bucket_rows(5000, 5000) == 5000
    assert ladder_buckets(4096) == [256, 512, 1024, 2048, 4096]
    for n in (1, 100, 300, 1000, 5000):
        assert bucket_rows(n, 4096) >= min(n, 4096)
        assert bucket_rows(n, 4096) in ladder_buckets(4096)


def test_bin_space_chunked_bit_identical(binary_model):
    bst, X = binary_model
    single = bst.predict(X, pred_chunk_rows=1 << 20)
    assert bst.last_predict_stats["chunks"] == 1
    for chunk in (512, 1024, 2048):  # 3000 rows -> odd remainder chunks
        chunked = bst.predict(X, pred_chunk_rows=chunk)
        assert np.array_equal(single, chunked)
    assert bst.last_predict_stats["chunks"] > 1
    # raw scores and leaf indices stream through the same scheduler
    raw_s = bst.predict(X, raw_score=True, pred_chunk_rows=1 << 20)
    raw_c = bst.predict(X, raw_score=True, pred_chunk_rows=512)
    assert np.array_equal(raw_s, raw_c)
    leaf_s = bst.predict(X, pred_leaf=True, pred_chunk_rows=1 << 20)
    leaf_c = bst.predict(X, pred_leaf=True, pred_chunk_rows=512)
    assert leaf_c.dtype == np.int32
    assert np.array_equal(leaf_s, leaf_c)


def test_multiclass_chunked_bit_identical(multiclass_model):
    bst, X = multiclass_model
    single = bst.predict(X, pred_chunk_rows=1 << 20)
    chunked = bst.predict(X, pred_chunk_rows=512)
    assert single.shape == (X.shape[0], 3)
    assert np.array_equal(single, chunked)


def test_empty_input_all_kinds(binary_model, multiclass_model):
    bst, X = binary_model
    mc, Xm = multiclass_model
    assert bst.predict(X[:0]).shape == (0,)
    assert bst.predict(X[:0], raw_score=True).shape == (0,)
    leaves = bst.predict(X[:0], pred_leaf=True)
    assert leaves.shape == (0, bst.num_trees())
    assert leaves.dtype == np.int32
    assert mc.predict(Xm[:0]).shape == (0, 3)


def test_real_space_chunked_bit_identical_with_suspects(binary_model):
    """Loaded-from-text boosters walk in real-value space; rows sitting
    EXACTLY on split thresholds take the f64 suspect re-walk, which must be
    per-chunk identical to the single-shot patch."""
    bst, X = binary_model
    loaded = lgb.Booster(model_str=bst.model_to_string())
    # plant threshold-exact rows in several chunks
    X = np.array(X, copy=True)
    tree0 = loaded.models_[0]
    feat = int(tree0.split_feature[0])
    thr = float(tree0.threshold[0])
    X[5, feat] = thr
    X[701, feat] = thr
    X[2901, feat] = thr
    sus = loaded._real_walk_suspects(X, 0, len(loaded.models_))
    assert sus.size >= 3  # the planted rows ARE suspects
    single = loaded.predict(X, pred_chunk_rows=1 << 20)
    assert loaded.last_predict_stats["path"] == "stream_real"
    for chunk in (512, 700, 2048):
        chunked = loaded.predict(X, pred_chunk_rows=chunk)
        assert np.array_equal(single, chunked)
    # suspect rows match the host f64 reference walk exactly
    raw = loaded.predict(X, raw_score=True, pred_chunk_rows=512)
    host = np.sum(
        np.stack([t.predict(X[sus]) for t in loaded.models_], axis=1), axis=1
    )
    np.testing.assert_allclose(raw[sus], host, rtol=0, atol=0)


def test_zero_recompiles_across_batch_sizes(binary_model):
    bst, X = binary_model
    chunk = int(bst.config.pred_chunk_rows)
    # warm every ladder bucket once
    for b in ladder_buckets(chunk):
        bst.predict(X[: min(b, len(X))])
    before = streaming_compile_count()
    for n in (1, 3, 17, 100, 255, 256, 257, 999, 1024, 2047, 3000):
        out = bst.predict(X[:n])
        assert out.shape == (n,)
        assert bst.last_predict_stats["compiles"] == 0
    assert streaming_compile_count() == before


def test_stream_compiles_are_labeled_in_telemetry(binary_model):
    """The streaming executable cache jits through instrumented_jit with a
    per-variant label, so suspect re-walk ("real"-space) compiles are
    separable in compile_counts_by_label() — and repeat predicts at warm
    buckets add ZERO labeled retraces (exact retrace accounting)."""
    from lightgbm_tpu.obs.jit import compile_count, compile_counts_by_label

    bst, X = binary_model
    loaded = lgb.Booster(model_str=bst.model_to_string())
    X = np.array(X, copy=True)
    tree0 = loaded.models_[0]
    X[11, int(tree0.split_feature[0])] = float(tree0.threshold[0])
    out1 = loaded.predict(X, pred_chunk_rows=1024)
    assert loaded.last_predict_stats["path"] == "stream_real"
    assert compile_counts_by_label().get("predict/stream/real", 0) >= 1
    # warm repeat: bit-identical output, zero new retraces under ANY label
    before_labels = compile_counts_by_label()
    before_total = compile_count()
    out2 = loaded.predict(X, pred_chunk_rows=1024)
    assert np.array_equal(out1, out2)
    assert compile_counts_by_label() == before_labels
    assert compile_count() == before_total


def test_sklearn_route_zero_recompiles():
    """sklearn estimators ride the same bucket-padded path: once warm,
    predict/predict_proba across varying batch sizes never recompile."""
    from lightgbm_tpu.sklearn import LGBMClassifier

    rng = np.random.default_rng(8)
    X = rng.normal(size=(2000, 6))
    y = (X[:, 0] > 0).astype(int)
    est = LGBMClassifier(n_estimators=5, num_leaves=15, verbose=-1)
    est.fit(X, y)
    chunk = int(est.booster_.config.pred_chunk_rows)
    for b in ladder_buckets(chunk):
        est.predict_proba(X[: min(b, len(X))])
    before = streaming_compile_count()
    for n in (2, 33, 450, 1111, 2000):
        assert est.predict(X[:n]).shape == (n,)
        assert est.predict_proba(X[:n]).shape == (n, 2)
        assert est.booster_.last_predict_stats["compiles"] == 0
    assert streaming_compile_count() == before


def test_sklearn_sparse_predict_matches_dense():
    """scipy input stays sparse through the sklearn wrapper (binned once
    from CSC by the engine) and matches the dense prediction exactly."""
    import scipy.sparse as sp

    from lightgbm_tpu.sklearn import LGBMRegressor

    rng = np.random.default_rng(9)
    X = np.where(rng.random((1500, 8)) < 0.3, rng.normal(size=(1500, 8)), 0.0)
    y = X[:, 0] + 0.5 * X[:, 1]
    est = LGBMRegressor(n_estimators=5, num_leaves=15, verbose=-1)
    est.fit(X, y)
    np.testing.assert_array_equal(
        est.predict(sp.csr_matrix(X), pred_chunk_rows=512), est.predict(X, pred_chunk_rows=512)
    )


def test_aot_compile_then_first_predict_is_compile_free(binary_model):
    bst, X = binary_model
    fresh = lgb.Booster(model_str=bst.model_to_string())
    compiled = fresh.compile_predict()
    # a fresh real-space model may still share an executable shape with an
    # earlier test's model (the cache is process-global by design); what
    # matters is the ladder is FULLY warm now
    assert compiled >= 0
    assert fresh.compile_predict() == 0  # idempotent: everything cached
    for n in (7, 300, 2000):
        fresh.predict(X[:n])
        assert fresh.last_predict_stats["compiles"] == 0


def test_pred_aot_compile_param_warms_at_load(binary_model):
    bst, X = binary_model
    loaded = lgb.Booster(
        params={"pred_aot_compile": True}, model_str=bst.model_to_string()
    )
    loaded.predict(X[:123])
    assert loaded.last_predict_stats["compiles"] == 0


def test_sharded_matches_single_device(binary_model, multiclass_model):
    """Row-sharding chunks over the virtual CPU mesh (conftest forces 8
    host devices) is output-identical to the single-device walk."""
    import jax

    assert jax.local_device_count() >= 4  # conftest mesh
    bst, X = binary_model
    base = bst.predict(X, pred_chunk_rows=1024)
    for nd in (4, -1):  # -1 = all local devices
        sharded = bst.predict(X, pred_chunk_rows=1024, pred_shard_devices=nd)
        assert bst.last_predict_stats["shard_devices"] >= 4
        assert np.array_equal(base, sharded)
    mc, Xm = multiclass_model
    base_mc = mc.predict(Xm)
    sharded_mc = mc.predict(Xm, pred_shard_devices=4)
    assert np.array_equal(base_mc, sharded_mc)
    # loaded (real-space) models shard too
    loaded = lgb.Booster(model_str=bst.model_to_string())
    np.testing.assert_array_equal(
        loaded.predict(X, pred_chunk_rows=1024),
        loaded.predict(X, pred_chunk_rows=1024, pred_shard_devices=4),
    )


def test_num_buffers_depth_does_not_change_output(binary_model):
    bst, X = binary_model
    base = bst.predict(X, pred_chunk_rows=512, pred_num_buffers=1)
    for depth in (2, 4, 8):
        assert np.array_equal(
            base,
            bst.predict(X, pred_chunk_rows=512, pred_num_buffers=depth),
        )


def test_phase_breakdown_reported(binary_model):
    bst, X = binary_model
    bst.predict(X, pred_chunk_rows=512)
    stats = bst.last_predict_stats
    for key in ("bin_ms", "transfer_ms", "walk_ms", "host_ms"):
        assert key in stats and stats[key] >= 0.0
    assert stats["rows"] == X.shape[0]
    assert stats["chunks"] == -(-X.shape[0] // 512)
    assert set(stats["buckets"]) <= set(ladder_buckets(512))


def test_500k_prediction_ab_chunked_vs_singleshot():
    """Tier-2 (slow) A/B at bench scale: 500k rows through the streaming
    engine must match the one-chunk walk bit-for-bit and report a full
    phase breakdown."""
    bst, _ = _make_binary(n=20_000, f=28, rounds=10)
    rng = np.random.default_rng(99)
    Xp = rng.normal(size=(500_000, 28))
    single = bst.predict(Xp, pred_chunk_rows=1 << 20)
    chunked = bst.predict(Xp, pred_chunk_rows=4096)
    assert np.array_equal(single, chunked)
    stats = bst.last_predict_stats
    assert stats["chunks"] == -(-500_000 // 4096)
    bst.predict(Xp, pred_chunk_rows=4096)  # ladder warm: now compile-free
    assert bst.last_predict_stats["compiles"] == 0
