"""Oracle tests for the Pallas histogram kernel — the production TPU path.

The kernel (ops/pallas/histogram.py) must match leaf_histogram_segment within
f32 tolerance, including masked/bagged rows and padded (non-multiple-of-tile)
row counts.  Runs in interpret mode everywhere; natively when a TPU is
attached (the bf16 hi/lo MXU decomposition is only exercised natively).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from lightgbm_tpu.ops.histogram import leaf_histogram_segment  # noqa: E402
from lightgbm_tpu.ops.pallas.histogram import histogram_pallas  # noqa: E402

def _problem(n, f, b, seed=0, mask_frac=0.8, grad_scale=1.0):
    rng = np.random.default_rng(seed)
    bins = rng.integers(0, b, size=(n, f), dtype=np.int32)
    grad = (rng.normal(size=n) * grad_scale).astype(np.float32)
    hess = rng.uniform(0.1, 2.0, size=n).astype(np.float32)
    mask = (rng.uniform(size=n) < mask_frac).astype(np.float32)
    return bins, grad, hess, mask


CASES = [
    (512, 6, 16),  # single tile, tiny
    (1000, 28, 256),  # padded rows (1000 % tile != 0), full Higgs shape
    (5000, 28, 64),  # multiple tiles + padding
    (2048, 1, 4),  # degenerate single feature
    (300, 33, 255),  # odd feature count (not a multiple of any group), odd B
]


@pytest.mark.parametrize("n,f,b", CASES)
def test_pallas_interpret_matches_segment(n, f, b):
    bins, grad, hess, mask = _problem(n, f, b)
    ref = np.asarray(leaf_histogram_segment(jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess), jnp.asarray(mask), b))
    got = np.asarray(
        histogram_pallas(
            jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess), jnp.asarray(mask), b, interpret=True
        )
    )
    assert got.shape == (f, b, 3)
    # the interpreter evaluates the dot at bf16 precision (the hi/lo residual
    # is lost), so interpret-mode accuracy is ~2^-9 relative; the native MXU
    # path keeps f32 accumulation and is tested at 5e-5 below
    scale = np.abs(ref).max() + 1e-9
    np.testing.assert_allclose(got / scale, ref / scale, atol=4e-3)
    # counts are integral sums of 0/1 — must be exact
    np.testing.assert_allclose(got[..., 2], ref[..., 2], rtol=0, atol=1e-3)


@pytest.mark.native_tpu
@pytest.mark.parametrize("n,f,b", CASES)
def test_pallas_native_matches_segment(n, f, b):
    bins, grad, hess, mask = _problem(n, f, b, seed=7)
    ref = np.asarray(leaf_histogram_segment(jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess), jnp.asarray(mask), b))
    got = np.asarray(histogram_pallas(jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess), jnp.asarray(mask), b))
    # bf16 hi/lo split: each element carries ~2^-16 relative error; sums over
    # n rows stay within a few ulps of the f32 oracle
    scale = np.abs(ref).max() + 1e-9
    np.testing.assert_allclose(got / scale, ref / scale, atol=5e-5)
    np.testing.assert_allclose(got[..., 2], ref[..., 2], rtol=0, atol=0.01)


@pytest.mark.native_tpu
def test_pallas_native_all_masked_and_large_grads():
    n, f, b = 1024, 8, 32
    bins, grad, hess, _ = _problem(n, f, b, seed=3, grad_scale=1e3)
    zero = jnp.zeros(n, jnp.float32)
    got = np.asarray(
        histogram_pallas(jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess), zero, b)
    )
    assert np.all(got == 0.0)
    # large-magnitude grads exercise the hi/lo split
    ones = jnp.ones(n, jnp.float32)
    ref = np.asarray(leaf_histogram_segment(jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess), ones, b))
    got = np.asarray(histogram_pallas(jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess), ones, b))
    scale = np.abs(ref).max() + 1e-9
    np.testing.assert_allclose(got / scale, ref / scale, atol=5e-5)


def test_uint8_bins_accepted():
    n, f, b = 700, 5, 64
    bins, grad, hess, mask = _problem(n, f, b, seed=11)
    ref = np.asarray(leaf_histogram_segment(jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess), jnp.asarray(mask), b))
    got = np.asarray(
        histogram_pallas(
            jnp.asarray(bins.astype(np.uint8)), jnp.asarray(grad), jnp.asarray(hess), jnp.asarray(mask), b, interpret=True
        )
    )
    scale = np.abs(ref).max() + 1e-9
    np.testing.assert_allclose(got / scale, ref / scale, atol=4e-3)
    np.testing.assert_allclose(got[..., 2], ref[..., 2], rtol=0, atol=1e-3)
