"""Oracle tests for the Pallas forest-walk predictor (ops/pallas/forest_walk.py)
against the XLA level-sync walker — run in interpret mode so CPU CI covers
the kernel body (bit packing, NaN default-left, class interleave).

Reference semantics under test: the fork's PredictTreeBatchAVX512
(include/LightGBM/tree_avx512.hpp:41) batch walk.
"""

import numpy as np
import pytest
import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.ops.pallas.forest_walk import (
    KPAD,
    build_tables,
    forest_walk,
    pad_bins_for_walk,
    unpack_walk_scores,
    walk_eligible,
)
from lightgbm_tpu.predict import predict_bins_raw


def _train(X, y, params, rounds):
    return lgb.train({**params, "verbosity": -1}, lgb.Dataset(X, y), rounds)


def _walk_raw(booster, X, k):
    mat = booster._bin_input_host(X)
    recs = booster._bin_records
    nanb = np.asarray(booster._nan_bins)
    assert walk_eligible(recs, nanb, mat.shape[1], booster._max_bin_padded)
    tables = build_tables(recs, nanb)
    out = forest_walk(
        pad_bins_for_walk(mat),
        tables,
        n_trees=tables.n_trees,
        max_depth=tables.max_depth,
        k=k,
        interpret=True,
    )
    return unpack_walk_scores(np.asarray(out), X.shape[0], k)


def _xla_raw(booster, X, k):
    bins = jnp.asarray(booster._bin_input_host(X))
    batch = booster._stacked_bins(0, len(booster.models_))
    per_tree = np.asarray(predict_bins_raw(batch, bins, booster._nan_bins))
    return per_tree.reshape(X.shape[0], -1, k).sum(axis=1)


def test_forest_walk_matches_xla_walker_with_nans():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(3000, 7))
    X[::5, 2] = np.nan
    y = np.where(np.isnan(X[:, 2]), 1.0, X[:, 0]) + rng.normal(size=3000) * 0.1
    b = _train(X, y, {"objective": "regression", "num_leaves": 31}, 12)
    got = _walk_raw(b, X, 1)[:, 0]
    exp = _xla_raw(b, X, 1)[:, 0]
    assert np.allclose(got, exp, atol=1e-5)


def test_forest_walk_multiclass_interleave():
    rng = np.random.default_rng(1)
    X = rng.normal(size=(2000, 5))
    y = np.digitize(X[:, 1], [-0.4, 0.4]).astype(float)
    b = _train(
        X, y, {"objective": "multiclass", "num_class": 3, "num_leaves": 15}, 6
    )
    got = _walk_raw(b, X, 3)
    exp = _xla_raw(b, X, 3)
    assert np.allclose(got, exp, atol=1e-5)


def test_walk_eligibility_gates():
    rng = np.random.default_rng(2)
    X = rng.normal(size=(3000, 4))
    y = X[:, 0] + rng.normal(size=3000) * 0.1
    # bins must fit a byte for the packed layout: a model whose bin space
    # exceeds 256 must be rejected regardless of observed thresholds
    b = _train(X, y, {"objective": "regression"}, 3)
    assert not walk_eligible(
        b._bin_records, np.asarray(b._nan_bins), X.shape[1], 512
    )
    # categorical splits fall back
    Xc = X.copy()
    Xc[:, 3] = rng.integers(0, 6, size=3000)
    yc = (Xc[:, 3] >= 3).astype(float) + X[:, 0] * 0.1
    bc = _train(
        Xc, yc, {"objective": "regression", "categorical_feature": [3]}, 3
    )
    assert not walk_eligible(
        bc._bin_records, np.asarray(bc._nan_bins), Xc.shape[1],
        bc._max_bin_padded,
    )


def test_predict_fast_path_k_guard():
    # num_class > KPAD must not take the kernel path (classes would be lost)
    rng = np.random.default_rng(3)
    X = rng.normal(size=(1500, 4))
    y = rng.integers(0, KPAD + 2, size=1500).astype(float)
    b = _train(
        X, y,
        {"objective": "multiclass", "num_class": KPAD + 2, "num_leaves": 7},
        2,
    )
    p = b.predict(X)
    assert p.shape == (1500, KPAD + 2)
    assert np.allclose(p.sum(axis=1), 1.0, atol=1e-5)


def test_device_binning_matches_host():
    """bin_numeric_device (f32 compare-reduce ValueToBin) vs the f64 host
    path, including NaN and zero-as-missing features."""
    from lightgbm_tpu.binning import BinMapper
    from lightgbm_tpu.ops.pallas.forest_walk import (
        bin_numeric_device,
        build_devbin_tables,
    )

    rng = np.random.default_rng(5)
    vals = rng.normal(size=5000)
    vals[::7] = np.nan
    vals[::11] = 0.0
    m1 = BinMapper.from_sample(vals, 63)
    m2 = BinMapper.from_sample(np.abs(vals), 255, zero_as_missing=True)
    mappers = [m1, m2]
    X = np.stack(
        [rng.normal(size=2000), np.abs(rng.normal(size=2000))], axis=1
    )
    X[::5, 0] = np.nan
    X[::9, 1] = 0.0
    tabs = build_devbin_tables(mappers, [0, 1])
    dev = np.asarray(bin_numeric_device(jnp.asarray(X, jnp.float32), *tabs))
    host = np.stack(
        [m.values_to_bins(X[:, i]) for i, m in enumerate(mappers)], axis=1
    )
    assert np.array_equal(dev, host)

    # categorical features disqualify the device tables
    mc = BinMapper.from_sample(
        rng.integers(0, 5, 500).astype(float), 63, is_categorical=True
    )
    assert build_devbin_tables([m1, mc], [0, 1]) is None


def test_device_binned_walk_matches_slow_path():
    """The full dense fast-path hand-off (used-feature slice -> device
    binning -> device packing -> kernel) vs the host-binned XLA walker —
    interpret mode so CPU CI covers the integration, not just the pieces."""
    from lightgbm_tpu.ops.pallas.forest_walk import (
        ROW_TILE,
        _pack_bins_device,
        bin_numeric_device,
        build_devbin_tables,
        build_tables,
        forest_walk,
        unpack_walk_scores,
    )

    rng = np.random.default_rng(9)
    X = rng.normal(size=(3000, 6))
    X[::6, 1] = np.nan
    y = np.where(np.isnan(X[:, 1]), 1.0, X[:, 0]) + rng.normal(size=3000) * 0.1
    b = _train(X, y, {"objective": "regression", "num_leaves": 31}, 10)
    ds = b.train_set
    tabs = build_devbin_tables(ds.bin_mappers, ds.used_features)
    assert tabs is not None
    xs = np.ascontiguousarray(X[:, ds.used_features], dtype=np.float32)
    mat_dev = bin_numeric_device(jnp.asarray(xs), *tabs)
    n = X.shape[0]
    n_pad = (n + ROW_TILE - 1) // ROW_TILE * ROW_TILE
    packed = _pack_bins_device(mat_dev, n_pad)
    tables = build_tables(b._bin_records, np.asarray(b._nan_bins))
    out = forest_walk(
        packed, tables, n_trees=tables.n_trees,
        max_depth=tables.max_depth, k=1, interpret=True,
    )
    got = unpack_walk_scores(np.asarray(out), n, 1)[:, 0]
    exp = _xla_raw(b, X, 1)[:, 0]
    assert np.allclose(got, exp, atol=1e-5)
