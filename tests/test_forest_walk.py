"""Oracle tests for the Pallas forest-walk predictor (ops/pallas/forest_walk.py)
against the XLA level-sync walker — run in interpret mode so CPU CI covers
the kernel body (bit packing, NaN default-left, class interleave).

Reference semantics under test: the fork's PredictTreeBatchAVX512
(include/LightGBM/tree_avx512.hpp:41) batch walk.
"""

import numpy as np
import pytest
import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.ops.pallas.forest_walk import (
    KPAD,
    build_tables,
    forest_walk,
    pad_bins_for_walk,
    unpack_walk_scores,
    walk_eligible,
)
from lightgbm_tpu.predict import predict_bins_raw


def _train(X, y, params, rounds):
    return lgb.train({**params, "verbosity": -1}, lgb.Dataset(X, y), rounds)


def _walk_raw(booster, X, k):
    mat = booster._bin_input_host(X)
    recs = booster._bin_records
    nanb = np.asarray(booster._nan_bins)
    assert walk_eligible(recs, nanb, mat.shape[1], booster._max_bin_padded)
    tables = build_tables(recs, nanb)
    out = forest_walk(
        pad_bins_for_walk(mat),
        tables,
        n_trees=tables.n_trees,
        max_depth=tables.max_depth,
        k=k,
        interpret=True,
    )
    return unpack_walk_scores(np.asarray(out), X.shape[0], k)


def _xla_raw(booster, X, k):
    bins = jnp.asarray(booster._bin_input_host(X))
    batch = booster._stacked_bins(0, len(booster.models_))
    per_tree = np.asarray(predict_bins_raw(batch, bins, booster._nan_bins))
    return per_tree.reshape(X.shape[0], -1, k).sum(axis=1)


def test_forest_walk_matches_xla_walker_with_nans():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(3000, 7))
    X[::5, 2] = np.nan
    y = np.where(np.isnan(X[:, 2]), 1.0, X[:, 0]) + rng.normal(size=3000) * 0.1
    b = _train(X, y, {"objective": "regression", "num_leaves": 31}, 12)
    got = _walk_raw(b, X, 1)[:, 0]
    exp = _xla_raw(b, X, 1)[:, 0]
    assert np.allclose(got, exp, atol=1e-5)


def test_forest_walk_multiclass_interleave():
    rng = np.random.default_rng(1)
    X = rng.normal(size=(2000, 5))
    y = np.digitize(X[:, 1], [-0.4, 0.4]).astype(float)
    b = _train(
        X, y, {"objective": "multiclass", "num_class": 3, "num_leaves": 15}, 6
    )
    got = _walk_raw(b, X, 3)
    exp = _xla_raw(b, X, 3)
    assert np.allclose(got, exp, atol=1e-5)


def test_walk_eligibility_gates():
    rng = np.random.default_rng(2)
    X = rng.normal(size=(3000, 4))
    y = X[:, 0] + rng.normal(size=3000) * 0.1
    # bins must fit a byte for the packed layout: a model whose bin space
    # exceeds 256 must be rejected regardless of observed thresholds
    b = _train(X, y, {"objective": "regression"}, 3)
    assert not walk_eligible(
        b._bin_records, np.asarray(b._nan_bins), X.shape[1], 512
    )
    # > 512 features falls back (9-bit feature field / plane budget);
    # 200 features is now eligible via the deeper plane-select tree
    assert walk_eligible(
        b._bin_records, np.asarray(b._nan_bins), 200, b._max_bin_padded
    )
    assert not walk_eligible(
        b._bin_records, np.asarray(b._nan_bins), 600, b._max_bin_padded
    )


def test_forest_walk_categorical_matches_xla_walker():
    """Categorical splits walk through the in-kernel bitset test
    (tree_avx512.hpp:112-168 handles categorical inline; here it is the
    vectorized FindInBitset over per-node 256-bit masks)."""
    rng = np.random.default_rng(4)
    X = rng.normal(size=(2500, 5))
    X[:, 3] = rng.integers(0, 9, size=2500)
    X[:, 4] = rng.integers(0, 4, size=2500)
    y = (
        np.isin(X[:, 3], [1, 4, 7]).astype(float) * 2
        + (X[:, 4] == 2) * 1.5
        + X[:, 0] * 0.3
        + rng.normal(size=2500) * 0.05
    )
    b = _train(
        X, y,
        {"objective": "regression", "categorical_feature": [3, 4],
         "num_leaves": 31, "min_data_in_leaf": 5, "max_cat_to_onehot": 2},
        10,
    )
    recs = b._bin_records
    assert any(np.any(np.asarray(r.get("split_is_cat"))) for r in recs)
    assert walk_eligible(
        recs, np.asarray(b._nan_bins), X.shape[1], b._max_bin_padded
    )
    got = _walk_raw(b, X, 1)[:, 0]
    exp = _xla_raw(b, X, 1)[:, 0]
    assert np.allclose(got, exp, atol=1e-5)


def test_forest_walk_wide_tree_four_half_lookup():
    """Trees with > 256 nodes use the 4-half lane-gather (up to 512)."""
    rng = np.random.default_rng(5)
    X = rng.normal(size=(20000, 6))
    y = np.sin(2 * X[:, 0]) * np.cos(X[:, 1]) + 0.3 * X[:, 2] + rng.normal(
        size=20000
    ) * 0.05
    b = _train(
        X, y,
        {"objective": "regression", "num_leaves": 400, "min_data_in_leaf": 5},
        3,
    )
    n_nodes = max(len(r["split_feature"]) for r in b._bin_records)
    assert n_nodes > 256, n_nodes
    got = _walk_raw(b, X[:3000], 1)[:, 0]
    exp = _xla_raw(b, X[:3000], 1)[:, 0]
    assert np.allclose(got, exp, atol=1e-5)


def test_forest_walk_many_classes():
    # num_class > 8 pads the output class columns to a multiple of 8
    rng = np.random.default_rng(3)
    X = rng.normal(size=(1500, 4))
    k = KPAD + 2
    y = rng.integers(0, k, size=1500).astype(float)
    b = _train(
        X, y,
        {"objective": "multiclass", "num_class": k, "num_leaves": 7},
        2,
    )
    got = _walk_raw(b, X, k)
    exp = _xla_raw(b, X, k)
    assert np.allclose(got, exp, atol=1e-5)
    p = b.predict(X)
    assert p.shape == (1500, k)
    assert np.allclose(p.sum(axis=1), 1.0, atol=1e-5)


def test_device_binning_matches_host():
    """bin_numeric_device (f32 compare-reduce ValueToBin) vs the f64 host
    path, including NaN and zero-as-missing features."""
    from lightgbm_tpu.binning import BinMapper
    from lightgbm_tpu.ops.pallas.forest_walk import (
        bin_numeric_device,
        build_devbin_tables,
    )

    rng = np.random.default_rng(5)
    vals = rng.normal(size=5000)
    vals[::7] = np.nan
    vals[::11] = 0.0
    m1 = BinMapper.from_sample(vals, 63)
    m2 = BinMapper.from_sample(np.abs(vals), 255, zero_as_missing=True)
    mappers = [m1, m2]
    X = np.stack(
        [rng.normal(size=2000), np.abs(rng.normal(size=2000))], axis=1
    )
    X[::5, 0] = np.nan
    X[::9, 1] = 0.0
    tabs = build_devbin_tables(mappers, [0, 1])
    dev_b, suspect = bin_numeric_device(jnp.asarray(X, jnp.float32), *tabs)
    dev = np.asarray(dev_b)
    # random values are never near a boundary; exact-boundary rows must flag
    edge = X.copy()
    edge[0, 0] = float(np.asarray(tabs[0])[0, 3])  # exactly on a boundary
    _, sus2 = bin_numeric_device(jnp.asarray(edge, jnp.float32), *tabs)
    assert bool(np.asarray(sus2)[0])
    host = np.stack(
        [m.values_to_bins(X[:, i]) for i, m in enumerate(mappers)], axis=1
    )
    assert np.array_equal(dev, host)

    # categorical features disqualify the device tables
    mc = BinMapper.from_sample(
        rng.integers(0, 5, 500).astype(float), 63, is_categorical=True
    )
    assert build_devbin_tables([m1, mc], [0, 1]) is None


def test_device_binned_walk_matches_slow_path():
    """The full dense fast-path hand-off (used-feature slice -> device
    binning -> device packing -> kernel) vs the host-binned XLA walker —
    interpret mode so CPU CI covers the integration, not just the pieces."""
    from lightgbm_tpu.ops.pallas.forest_walk import (
        ROW_TILE,
        _pack_bins_device,
        bin_numeric_device,
        build_devbin_tables,
        build_tables,
        forest_walk,
        unpack_walk_scores,
    )

    rng = np.random.default_rng(9)
    X = rng.normal(size=(3000, 6))
    X[::6, 1] = np.nan
    y = np.where(np.isnan(X[:, 1]), 1.0, X[:, 0]) + rng.normal(size=3000) * 0.1
    b = _train(X, y, {"objective": "regression", "num_leaves": 31}, 10)
    ds = b.train_set
    tabs = build_devbin_tables(ds.bin_mappers, ds.used_features)
    assert tabs is not None
    xs = np.ascontiguousarray(X[:, ds.used_features], dtype=np.float32)
    mat_dev, _ = bin_numeric_device(jnp.asarray(xs), *tabs)
    n = X.shape[0]
    n_pad = (n + ROW_TILE - 1) // ROW_TILE * ROW_TILE
    packed = _pack_bins_device(mat_dev, n_pad)
    tables = build_tables(b._bin_records, np.asarray(b._nan_bins))
    out = forest_walk(
        packed, tables, n_trees=tables.n_trees,
        max_depth=tables.max_depth, k=1, interpret=True,
    )
    got = unpack_walk_scores(np.asarray(out), n, 1)[:, 0]
    exp = _xla_raw(b, X, 1)[:, 0]
    assert np.allclose(got, exp, atol=1e-5)


def test_bin_edge_rows_rebinned_exactly():
    """VERDICT r2 #9: rows at (or within f32-eps of) bin boundaries must
    predict identically to the host-binned path.  The device binning flags
    them suspect and the booster re-bins exactly those rows on host."""
    from lightgbm_tpu.ops.pallas.forest_walk import (
        bin_numeric_device,
        build_devbin_tables,
    )

    rng = np.random.default_rng(11)
    X = rng.normal(size=(4000, 5))
    y = X[:, 0] * 2 + X[:, 1] + rng.normal(size=4000) * 0.1
    b = _train(X, y, {"objective": "regression", "num_leaves": 31}, 8)
    ds = b.train_set
    tabs = build_devbin_tables(ds.bin_mappers, ds.used_features)
    ub0 = np.asarray(tabs[0], np.float64)  # f32 boundaries

    # craft rows sitting exactly on boundaries and one-ulp around them
    rows = X[:32].copy()
    f32 = np.float32
    for i in range(16):
        bidx = 1 + (i % 40)
        base = ub0[i % rows.shape[1], min(bidx, ub0.shape[1] - 2)]
        if not np.isfinite(base):
            base = ub0[i % rows.shape[1], 0]
        v = f32(base)
        rows[i, i % rows.shape[1]] = float(v)
        rows[16 + i // 2, i % rows.shape[1]] = float(
            np.nextafter(v, f32(np.inf))
        )
    xs = jnp.asarray(
        np.ascontiguousarray(rows[:, ds.used_features], np.float32)
    )
    bins_dev, suspect = bin_numeric_device(xs, *tabs)
    assert bool(np.asarray(suspect).any())
    # simulate the booster's patch step: suspect rows host-binned
    sidx = np.flatnonzero(np.asarray(suspect))
    patch = b._bin_input_host(rows[sidx])
    fixed = np.asarray(bins_dev.at[jnp.asarray(sidx)].set(
        jnp.asarray(patch.astype(np.int32))
    ))
    host = b._bin_input_host(rows)
    # EVERY row must now equal host binning: suspects were patched with the
    # exact path, and non-suspects are provably safe (their distance to any
    # boundary exceeds the f32/f64 rounding gap the tolerance covers)
    assert np.array_equal(fixed, host)


def test_forest_walk_256_features():
    """F > 128 rides the deeper plane-select tree (VERDICT r3 #8): a
    256-feature model must stay on the fast path and match the XLA walker."""
    rng = np.random.default_rng(9)
    n, f = 2000, 256
    X = rng.normal(size=(n, f))
    y = X[:, 0] + 0.5 * X[:, 200] - X[:, 129] + rng.normal(size=n) * 0.1
    b = _train(X, y, {"objective": "regression", "num_leaves": 31}, 8)
    got = _walk_raw(b, X, 1)[:, 0]
    exp = _xla_raw(b, X, 1)[:, 0]
    assert np.allclose(got, exp, atol=1e-5)


def test_walk_reject_reasons():
    from lightgbm_tpu.ops.pallas.forest_walk import walk_reject_reason

    assert "features > 512" in walk_reject_reason([], np.array([]), 600, 64)
    assert "max_bin" in walk_reject_reason([], np.array([]), 4, 1024)
    assert walk_reject_reason(
        [dict(split_feature=np.array([0]), split_bin=np.array([3]),
              default_left=np.array([0]), left_child=np.array([-1]),
              right_child=np.array([-2]), leaf_value=np.array([0.1, 0.2]))],
        np.array([-1]), 4, 64,
    ) is None
