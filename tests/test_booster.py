"""End-to-end Booster tests (the reference's test_engine.py style: train ->
eval -> predict assertions per objective family, model IO round-trip,
early stopping, continued training)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb

RNG = np.random.default_rng(0)
N, F = 600, 6
X = RNG.normal(size=(N, F))
Y_REG = X[:, 0] * 2 + np.sin(3 * X[:, 1]) + RNG.normal(scale=0.1, size=N)
Y_BIN = ((X[:, 0] - X[:, 1] + RNG.normal(scale=0.4, size=N)) > 0).astype(np.float64)

PARAMS = {"verbosity": -1, "num_leaves": 15, "learning_rate": 0.1, "min_data_in_leaf": 5}


def test_regression_improves_and_roundtrips():
    d = lgb.Dataset(X, Y_REG)
    b = lgb.train({**PARAMS, "objective": "regression"}, d, 30)
    p = b.predict(X)
    assert np.mean((p - Y_REG) ** 2) < 0.2 * np.var(Y_REG)
    s = b.model_to_string()
    b2 = lgb.Booster(model_str=s)
    np.testing.assert_array_equal(b2.predict(X), p)
    # loaded model predicts without any Dataset attached (real-space walker)
    assert b2.train_set is None


def test_binary_probabilities():
    d = lgb.Dataset(X, Y_BIN)
    b = lgb.train({**PARAMS, "objective": "binary"}, d, 30)
    p = b.predict(X)
    assert p.min() >= 0 and p.max() <= 1
    assert ((p > 0.5) == Y_BIN).mean() > 0.85
    raw = b.predict(X, raw_score=True)
    np.testing.assert_allclose(p, 1 / (1 + np.exp(-raw)), rtol=1e-5, atol=1e-6)


def test_multiclass_softmax_output():
    y3 = np.argmax(X[:, :3], axis=1).astype(np.float64)
    d = lgb.Dataset(X, y3)
    b = lgb.train({**PARAMS, "objective": "multiclass", "num_class": 3}, d, 20)
    p = b.predict(X)
    assert p.shape == (N, 3)
    np.testing.assert_allclose(p.sum(axis=1), 1.0, rtol=1e-4)
    assert (np.argmax(p, axis=1) == y3).mean() > 0.85


def test_early_stopping_and_best_iteration_predict():
    d = lgb.Dataset(X[:400], Y_REG[:400], free_raw_data=False)
    dv = d.create_valid(X[400:], Y_REG[400:])
    b = lgb.train(
        {**PARAMS, "objective": "regression"},
        d,
        200,
        valid_sets=[dv],
        callbacks=[lgb.early_stopping(5, verbose=False)],
    )
    assert 0 < b.best_iteration < 200
    # default predict uses best_iteration
    p_default = b.predict(X[400:])
    p_best = b.predict(X[400:], num_iteration=b.best_iteration)
    np.testing.assert_array_equal(p_default, p_best)
    p_all = b.predict(X[400:], num_iteration=-1)
    assert b.num_trees() == b.current_iteration()


def test_early_stopping_min_delta_param():
    """`early_stopping_min_delta` flows from params into the auto-created
    callback (reference config.h:405): a huge delta stops almost
    immediately, a zero delta trains longer on the same data."""
    d = lgb.Dataset(X[:400], Y_REG[:400], free_raw_data=False)
    dv = d.create_valid(X[400:], Y_REG[400:])
    base = {**PARAMS, "objective": "regression", "early_stopping_round": 5}
    b_zero = lgb.train(base, d, 120, valid_sets=[dv])
    b_huge = lgb.train(
        {**base, "early_stopping_min_delta": 1e6}, d, 120, valid_sets=[dv]
    )
    assert b_huge.best_iteration == 1  # nothing improves by 1e6
    assert b_zero.best_iteration > b_huge.best_iteration


def test_saved_feature_importance_type_param():
    """`saved_feature_importance_type=1` writes gain (float) importances to
    the model file instead of split counts (reference config.h:616)."""
    d = lgb.Dataset(X, Y_REG)
    b = lgb.train(
        {**PARAMS, "objective": "regression",
         "saved_feature_importance_type": 1}, d, 5
    )
    s = b.model_to_string()
    block = s.split("feature_importances:\n", 1)[1].split("\n\n", 1)[0]
    vals = [line.split("=")[1] for line in block.strip().splitlines() if "=" in line]
    assert vals and any("." in v for v in vals), block
    gains = b.feature_importance(importance_type="gain")
    assert abs(max(float(v) for v in vals) - gains.max()) < 1e-6 * max(1.0, gains.max())
    # default (0) keeps integer split counts
    s0 = lgb.train({**PARAMS, "objective": "regression"}, d, 5).model_to_string()
    block0 = s0.split("feature_importances:\n", 1)[1].split("\n\n", 1)[0]
    assert all(
        "." not in line.split("=")[1]
        for line in block0.strip().splitlines() if "=" in line
    )


def test_weights_change_model():
    w = np.where(X[:, 0] > 0, 5.0, 0.1)
    d1 = lgb.Dataset(X, Y_REG)
    d2 = lgb.Dataset(X, Y_REG, weight=w)
    b1 = lgb.train({**PARAMS, "objective": "regression"}, d1, 10)
    b2 = lgb.train({**PARAMS, "objective": "regression"}, d2, 10)
    assert not np.allclose(b1.predict(X), b2.predict(X))


def test_bagging_and_feature_fraction():
    d = lgb.Dataset(X, Y_REG)
    b = lgb.train(
        {
            **PARAMS,
            "objective": "regression",
            "bagging_fraction": 0.6,
            "bagging_freq": 1,
            "feature_fraction": 0.7,
        },
        d,
        15,
    )
    p = b.predict(X)
    assert np.mean((p - Y_REG) ** 2) < 0.5 * np.var(Y_REG)


def test_goss():
    d = lgb.Dataset(X, Y_REG)
    b = lgb.train(
        {**PARAMS, "objective": "regression", "boosting": "goss"}, d, 25
    )
    assert np.mean((b.predict(X) - Y_REG) ** 2) < 0.3 * np.var(Y_REG)


def test_dart():
    d = lgb.Dataset(X, Y_REG)
    b = lgb.train(
        {**PARAMS, "objective": "regression", "boosting": "dart", "drop_rate": 0.3},
        d,
        20,
    )
    assert np.mean((b.predict(X) - Y_REG) ** 2) < 0.6 * np.var(Y_REG)


def test_rf():
    d = lgb.Dataset(X, Y_REG)
    b = lgb.train(
        {
            **PARAMS,
            "objective": "regression",
            "boosting": "rf",
            "bagging_fraction": 0.7,
            "bagging_freq": 1,
        },
        d,
        15,
    )
    p = b.predict(X)
    # averaged forest output must be in the label range neighborhood
    assert np.mean((p - Y_REG) ** 2) < np.var(Y_REG)


def test_continued_training():
    d = lgb.Dataset(X, Y_REG, free_raw_data=False)
    b1 = lgb.train({**PARAMS, "objective": "regression"}, d, 10)
    l1 = np.mean((b1.predict(X) - Y_REG) ** 2)
    b2 = lgb.train({**PARAMS, "objective": "regression"}, d, 10, init_model=b1)
    l2 = np.mean((b2.predict(X) - Y_REG) ** 2)
    assert b2.num_trees() == 20
    assert l2 < l1


def test_pred_leaf_and_contrib():
    d = lgb.Dataset(X, Y_REG)
    b = lgb.train({**PARAMS, "objective": "regression"}, d, 8)
    leaves = b.predict(X[:20], pred_leaf=True)
    assert leaves.shape == (20, 8)
    assert leaves.max() < PARAMS["num_leaves"]
    contrib = b.predict(X[:10], pred_contrib=True)
    raw = b.predict(X[:10], raw_score=True)
    np.testing.assert_allclose(contrib.sum(axis=1), raw, rtol=1e-5, atol=1e-5)


def test_categorical_feature():
    rng = np.random.default_rng(9)
    Xc = X.copy()
    cats = rng.integers(0, 5, size=N).astype(np.float64)
    Xc[:, 3] = cats
    effect = np.array([2.0, -1.0, 0.5, 3.0, -2.0])
    yc = effect[cats.astype(int)] + 0.2 * Xc[:, 0] + rng.normal(scale=0.1, size=N)
    d = lgb.Dataset(Xc, yc, categorical_feature=[3])
    b = lgb.train({**PARAMS, "objective": "regression"}, d, 25)
    p = b.predict(Xc)
    assert np.mean((p - yc) ** 2) < 0.1 * np.var(yc)
    # model round-trip with categorical splits
    b2 = lgb.Booster(model_str=b.model_to_string())
    np.testing.assert_allclose(b2.predict(Xc), p, rtol=1e-5, atol=1e-5)


def test_cv_runs():
    d = lgb.Dataset(X, Y_REG, free_raw_data=False)
    res = lgb.cv({**PARAMS, "objective": "regression", "metric": "l2"}, d, 5, nfold=3)
    assert len(res["valid l2-mean"]) == 5
    assert res["valid l2-mean"][-1] < res["valid l2-mean"][0]


def test_sklearn_classifier():
    clf = lgb.LGBMClassifier(n_estimators=15, num_leaves=15, verbosity=-1)
    clf.fit(X, Y_BIN)
    acc = (clf.predict(X) == Y_BIN).mean()
    assert acc > 0.85
    proba = clf.predict_proba(X)
    assert proba.shape == (N, 2)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-6)


def test_feature_importance():
    d = lgb.Dataset(X, Y_REG)
    b = lgb.train({**PARAMS, "objective": "regression"}, d, 10)
    imp_split = b.feature_importance("split")
    imp_gain = b.feature_importance("gain")
    assert imp_split.sum() > 0
    # features 0 and 1 carry the signal
    assert imp_gain[0] + imp_gain[1] > imp_gain[2:].sum()


def test_constructed_dataset_rejects_conflicting_binning_params():
    """Dataset params freeze at construction (reference basic.py
    _update_params 'Cannot change ... after constructed'); a second booster
    with a conflicting binning param must error, including when the first
    booster's merge already wrote the key into the dataset (ADVICE r3)."""
    import pytest as _pytest

    rng = np.random.default_rng(0)
    X = rng.normal(size=(300, 4))
    y = X[:, 0] + rng.normal(size=300)
    d = lgb.Dataset(X, y)
    lgb.train({"objective": "regression", "verbosity": -1, "max_bin": 63}, d, 2)
    with _pytest.raises(ValueError, match="max_bin"):
        lgb.train(
            {"objective": "regression", "verbosity": -1, "max_bin": 127}, d, 2
        )
    # same params re-train is fine
    lgb.train({"objective": "regression", "verbosity": -1, "max_bin": 63}, d, 2)


def test_parameters_block_round_trips():
    """Loaded boosters keep the parameters block on re-save (reference
    GBDT::LoadModelFromString restores loaded_parameter_), including
    list-valued params; explicitly passed ctor params (alias-aware) win."""
    rng = np.random.default_rng(0)
    X = rng.uniform(-2, 2, size=(600, 3))
    y = X[:, 0] - X[:, 2]
    p = {
        "objective": "regression",
        "verbosity": -1,
        "monotone_constraints": [1, 0, -1],
        "metric": "none",
    }
    b = lgb.train(p, lgb.Dataset(X, y, params=p), 4)
    s1 = b.model_to_string()
    b2 = lgb.Booster(model_str=s1)
    assert b2.model_to_string() == s1
    assert np.array_equal(b.predict(X), b2.predict(X))
    b3 = lgb.Booster(params={"shrinkage_rate": 0.3}, model_str=s1)
    assert float(b3.config.learning_rate) == 0.3


def test_model_from_string_reload_swaps_params():
    """Reloading a different model replaces the previous FILE params (only
    user-passed ctor params shield against the new file's block)."""
    rng = np.random.default_rng(1)
    X = rng.normal(size=(400, 3))
    y = X[:, 0]
    s = {}
    for lr in (0.1, 0.5):
        b = lgb.train(
            {"objective": "regression", "learning_rate": lr, "verbosity": -1},
            lgb.Dataset(X, y),
            3,
        )
        s[lr] = b.model_to_string()
    b = lgb.Booster(model_str=s[0.1])
    assert float(b.config.learning_rate) == 0.1
    b.model_from_string(s[0.5])
    assert float(b.config.learning_rate) == 0.5
    assert b.model_to_string() == s[0.5]
