"""Prediction paths: early stopping, linear-tree coefficients, loaded-model
categorical device walker.

Reference analogs: prediction_early_stop.cpp (margin rules) +
gbdt_prediction.cpp:18 (per-iteration counter loop); CategoricalDecision
(tree.h:346) for the real-space bitset walker.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import lightgbm_tpu as lgb  # noqa: E402


def test_pred_early_stop_matches_sequential_reference():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(500, 6))
    y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(float)
    b = lgb.train(
        {"objective": "binary", "verbosity": -1, "num_leaves": 15},
        lgb.Dataset(X, y),
        40,
    )
    freq, margin = 5, 4.0
    raw_pt = np.asarray([t.predict(X) for t in b.models_]).T  # [N, T]
    want = np.zeros(len(X))
    for i in range(len(X)):
        acc, cnt = 0.0, 0
        for t in range(raw_pt.shape[1]):
            acc += raw_pt[i, t]
            cnt += 1
            if cnt == freq:
                if 2 * abs(acc) > margin:
                    break
                cnt = 0
        want[i] = acc
    got = b.predict(
        X,
        raw_score=True,
        pred_early_stop=True,
        pred_early_stop_freq=freq,
        pred_early_stop_margin=margin,
    )
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    # with an infinite margin the output is the full model exactly
    full = b.predict(X, raw_score=True)
    es_inf = b.predict(
        X, raw_score=True, pred_early_stop=True, pred_early_stop_margin=1e30
    )
    np.testing.assert_allclose(es_inf, full, rtol=1e-6)


def test_pred_early_stop_multiclass_margin():
    rng = np.random.default_rng(1)
    X = rng.normal(size=(400, 5))
    y = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0.3).astype(int)
    b = lgb.train(
        {
            "objective": "multiclass",
            "num_class": 3,
            "verbosity": -1,
            "num_leaves": 7,
        },
        lgb.Dataset(X, y),
        20,
    )
    p = b.predict(
        X, pred_early_stop=True, pred_early_stop_freq=3,
        pred_early_stop_margin=2.0,
    )
    assert p.shape == (400, 3)
    np.testing.assert_allclose(p.sum(axis=1), 1.0, atol=1e-5)
    # tight margin must diverge from the full model somewhere
    full = b.predict(X)
    loose = b.predict(
        X, pred_early_stop=True, pred_early_stop_margin=1e30
    )
    np.testing.assert_allclose(loose, full, rtol=1e-6)


def test_linear_tree_predict_uses_coefficients():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(500, 4))
    y = 2 * X[:, 0] + X[:, 1] + rng.normal(scale=0.05, size=500)
    b = lgb.train(
        {
            "objective": "regression",
            "linear_tree": True,
            "verbosity": -1,
            "num_leaves": 7,
        },
        lgb.Dataset(X, y),
        5,
    )
    p = b.predict(X)
    want = np.zeros(len(X))
    for t in b.models_:
        want += t.predict(X)
    np.testing.assert_allclose(p, want, rtol=1e-5, atol=1e-6)


def test_loaded_categorical_model_device_walker():
    """A model loaded from text (no train_set / bin mappers) with categorical
    splits predicts through the jitted real-space bitset walker — and agrees
    with both the training booster and the host per-row walk."""
    rng = np.random.default_rng(7)
    catv = rng.integers(0, 15, size=800).astype(float)
    y = np.where(catv % 3 == 0, 1.0, -1.0) + rng.normal(scale=0.05, size=800)
    X = catv.reshape(-1, 1)
    b = lgb.train(
        {
            "objective": "regression",
            "num_leaves": 8,
            "min_data_per_group": 1,
            "max_cat_to_onehot": 1,
            "verbosity": -1,
        },
        lgb.Dataset(X, y, categorical_feature=[0]),
        5,
    )
    p_train = b.predict(X)
    loaded = lgb.Booster(model_str=b.model_to_string())
    p_loaded = loaded.predict(X)
    np.testing.assert_allclose(p_loaded, p_train, rtol=1e-6, atol=1e-7)
    # unseen category and NaN go right (never crash, never go left wrongly)
    Xu = np.array([[99.0], [np.nan]])
    pu = loaded.predict(Xu)
    assert np.isfinite(pu).all()
    np.testing.assert_allclose(pu, b.predict(Xu), rtol=1e-6)


def test_chunked_walk_matches_single_chunk(monkeypatch):
    """The multi-chunk lookahead drain (chunk i dispatches while chunk i-1
    transfers) must produce exactly the single-chunk result; CHUNK shrinks
    so CI exercises the loop without 1M rows."""
    from lightgbm_tpu.boosting import gbdt as gbdt_mod

    rng = np.random.default_rng(11)
    X = rng.normal(size=(3000, 6))
    X[::13, 2] = np.nan
    y = X[:, 0] + np.sin(X[:, 1])
    b = lgb.train(
        {"objective": "regression", "verbosity": -1, "num_leaves": 31},
        lgb.Dataset(X, y),
        8,
    )
    p_one = b.predict(X)
    monkeypatch.setattr(gbdt_mod, "_PREDICT_CHUNK", 1024)
    monkeypatch.setattr(gbdt_mod, "_WALK_INTERPRET", True)
    walked = {}
    orig = b._forest_walk_raw

    def spy(*a, **kw):
        r = orig(*a, **kw)
        walked["hit"] = r is not None
        return r

    monkeypatch.setattr(b, "_forest_walk_raw", spy)
    p_chunked = b.predict(X)  # 3000 rows -> 3 chunks, last one ragged
    assert walked.get("hit"), "chunked walk path was not exercised"
    np.testing.assert_allclose(p_chunked, p_one, rtol=1e-6, atol=1e-7)
