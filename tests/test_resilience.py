"""Fault-tolerant training (resilience/ subsystem): checkpoint/resume,
fused-kernel graceful degradation, non-finite guard rails, fault injection.

Reference analog: the C++ tree has `continued training` via
``input_model`` (GBDT::MergeFrom) but no iteration-granular checkpointing;
the resilience/ subsystem is a superset required for preemptible TPU pods.
"""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.obs.registry import get_session  # noqa: E402
from lightgbm_tpu.resilience import (  # noqa: E402
    NumericsError,
    chaos,
    latest_checkpoint,
    list_checkpoints,
    restore_checkpoint,
    save_checkpoint,
)
from lightgbm_tpu.resilience.chaos import InjectedPallasFailure  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_state():
    chaos.reset()
    ses = get_session()
    ses.configure(enabled=False)
    ses.reset()
    yield
    chaos.reset()
    ses = get_session()
    ses.configure(enabled=False)
    ses.reset()


def _data(n=400, f=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = X[:, 0] * 2 + np.sin(X[:, 1]) + 0.1 * rng.normal(size=n)
    return X, y


def _params(**over):
    p = dict(
        objective="regression",
        num_leaves=15,
        learning_rate=0.1,
        min_data_in_leaf=20,
        verbosity=-1,
        deterministic=True,
        seed=7,
    )
    p.update(over)
    return p


# ======================================================== checkpoint / resume
# Byte-parity protocol: the params block is echoed into the model dump, so
# the baseline, interrupted, and resumed runs all use IDENTICAL params —
# including the same checkpoint_dir — and share one directory.  The resumed
# run picks up the interrupted run's latest checkpoint (written last).
CKPT_VARIANTS = {
    "plain": {},
    "bagging": dict(bagging_fraction=0.7, bagging_freq=2, bagging_seed=11),
    "goss": dict(boosting="goss", top_rate=0.3, other_rate=0.2),
    "leaf_batch": dict(leaf_batch=4),
}


@pytest.mark.parametrize("variant", sorted(CKPT_VARIANTS))
def test_checkpoint_resume_byte_parity(tmp_path, variant):
    X, y = _data()
    ckdir = str(tmp_path / "ck")
    p = _params(checkpoint_dir=ckdir, checkpoint_interval=5)
    p.update(CKPT_VARIANTS[variant])

    baseline = lgb.train(p, lgb.Dataset(X, y, params=p), num_boost_round=14)
    ref = baseline.model_to_string()

    # "interrupted" run: same params, dies (returns) after 10 iterations,
    # leaving checkpoints at iterations 5 and 10 in ckdir
    lgb.train(p, lgb.Dataset(X, y, params=p), num_boost_round=10)
    assert latest_checkpoint(ckdir) is not None

    # resume: num_boost_round is the TOTAL iteration count here
    resumed = lgb.train(
        p, lgb.Dataset(X, y, params=p), num_boost_round=14, resume_from=ckdir
    )
    assert resumed.current_iteration() == 14
    assert resumed.model_to_string() == ref


def test_checkpoint_resume_from_explicit_file(tmp_path):
    X, y = _data()
    ckdir = str(tmp_path / "ck")
    p = _params(checkpoint_dir=ckdir, checkpoint_interval=3, checkpoint_keep=0)
    baseline = lgb.train(p, lgb.Dataset(X, y, params=p), num_boost_round=9)
    ref = baseline.model_to_string()
    ckpts = list_checkpoints(ckdir)
    assert [it for it, _ in ckpts] == [3, 6, 9]
    # resume from the iteration-6 file specifically (not the latest)
    resumed = lgb.train(
        p, lgb.Dataset(X, y, params=p), num_boost_round=9,
        resume_from=ckpts[1][1],
    )
    assert resumed.model_to_string() == ref


def test_checkpoint_pruning_keeps_last_n(tmp_path):
    X, y = _data()
    ckdir = str(tmp_path / "ck")
    p = _params(checkpoint_dir=ckdir, checkpoint_interval=2, checkpoint_keep=2)
    lgb.train(p, lgb.Dataset(X, y, params=p), num_boost_round=10)
    assert [it for it, _ in list_checkpoints(ckdir)] == [8, 10]


def test_checkpoint_callback_writes_files(tmp_path):
    X, y = _data()
    ckdir = str(tmp_path / "ck")
    p = _params()
    lgb.train(
        p, lgb.Dataset(X, y, params=p), num_boost_round=6,
        callbacks=[lgb.checkpoint_callback(ckdir, period=3)],
    )
    assert [it for it, _ in list_checkpoints(ckdir)] == [3, 6]


def test_restore_rejects_mismatched_run(tmp_path):
    X, y = _data()
    ckdir = str(tmp_path / "ck")
    p = _params()
    booster = lgb.train(p, lgb.Dataset(X, y, params=p), num_boost_round=4)
    save_checkpoint(booster, ckdir)

    other = _params(seed=99)
    fresh = lgb.train(other, lgb.Dataset(X, y, params=other), num_boost_round=1)
    with pytest.raises(ValueError, match="seed"):
        restore_checkpoint(fresh, ckdir)


def test_config_checkpoint_validation():
    X, y = _data(n=50)
    with pytest.raises(Exception, match="checkpoint"):
        lgb.train(
            _params(checkpoint_interval=5),  # no checkpoint_dir
            lgb.Dataset(X, y), num_boost_round=1,
        )
    with pytest.raises(Exception, match="checkpoint"):
        lgb.train(
            _params(checkpoint_interval=-1),
            lgb.Dataset(X, y), num_boost_round=1,
        )


# ===================================================== atomic model writing
def test_save_model_atomic_under_interrupt(tmp_path, monkeypatch):
    X, y = _data()
    p = _params()
    booster = lgb.train(p, lgb.Dataset(X, y, params=p), num_boost_round=3)
    out = tmp_path / "model.txt"
    booster.save_model(str(out))
    good = out.read_bytes()

    # a crash between tmp-file write and rename must leave the target intact
    real_replace = os.replace

    def boom(src, dst):
        raise OSError("injected crash during rename")

    monkeypatch.setattr(os, "replace", boom)
    with pytest.raises(OSError, match="injected"):
        booster.save_model(str(out))
    assert out.read_bytes() == good
    assert not [f for f in os.listdir(tmp_path) if f != "model.txt"], (
        "tmp file leaked after interrupted save"
    )

    monkeypatch.setattr(os, "replace", real_replace)
    booster.save_model(str(out))
    reloaded = lgb.Booster(model_file=str(out))
    assert reloaded.num_trees() == booster.num_trees()


# ========================================== init_model continuation parity
@pytest.mark.parametrize(
    "extra",
    [
        pytest.param({}, id="plain"),
        pytest.param(
            dict(bagging_fraction=0.7, bagging_freq=2, bagging_seed=11),
            id="bagging",
        ),
        pytest.param(
            dict(boosting="goss", top_rate=0.3, other_rate=0.2), id="goss"
        ),
        pytest.param(
            dict(extra_trees=True, extra_seed=5, feature_fraction_bynode=0.8),
            id="extra_trees",
        ),
    ],
)
def test_init_model_continuation_byte_parity(extra):
    """20 continuous iterations == 10 + 10 via init_model, byte-identical.

    Exercises the RNG-stream re-fold in merge_from (bagging masks,
    extra-trees thresholds) and the f32-exact score replay."""
    X, y = _data(n=500, f=8, seed=3)
    p = _params(boost_from_average=False)
    p.update(extra)

    full = lgb.train(p, lgb.Dataset(X, y, params=p, free_raw_data=False), 20)
    b1 = lgb.train(p, lgb.Dataset(X, y, params=p, free_raw_data=False), 10)
    cont = lgb.train(
        p, lgb.Dataset(X, y, params=p, free_raw_data=False), 10, init_model=b1
    )
    assert cont.model_to_string() == full.model_to_string()


# ============================================ fused-kernel graceful fallback
def _fused_params(**over):
    # hist_mode must be explicit off-TPU; grow_fused="on" then lowers to the
    # two-launch XLA composition (the oracle) on CPU — byte-identical by
    # construction, which is what makes the parity assertion meaningful
    p = _params(hist_mode="seg", grow_fused="on", telemetry=True)
    p.update(over)
    return p


def test_fused_failure_falls_back_to_xla_oracle():
    X, y = _data(n=600, f=8, seed=1)
    p = _fused_params()

    clean = lgb.train(p, lgb.Dataset(X, y, params=p), num_boost_round=6)
    ref = clean.model_to_string()
    get_session().reset()

    chaos.force_pallas_raise(at_iteration=2)
    booster = lgb.train(p, lgb.Dataset(X, y, params=p), num_boost_round=6)
    chaos.reset()

    # the run completed on the XLA oracle with identical trees
    assert booster.model_to_string() == ref

    ses = get_session()
    degr = [e for e in ses.events if e.get("event") == "degradation"]
    assert len(degr) == 1, f"expected exactly one degradation event: {degr}"
    assert degr[0]["component"] == "fused_grow_step"
    assert degr[0]["action"] == "fallback_to_xla_oracle"
    assert degr[0]["iter"] == 2
    assert "InjectedPallasFailure" in degr[0]["error"]
    assert ses.counters.get("degradations") == 1

    # no retrace storm: the latch forces ONE rebuild of GrowerParams; after
    # that, further iterations reuse the fallback's compiled program
    from lightgbm_tpu.obs import compile_counts_by_label

    before = compile_counts_by_label()
    for _ in range(3):
        booster.update()
    assert compile_counts_by_label() == before, "fallback kept retracing"


def test_fused_fallback_latch_survives_checkpoint(tmp_path):
    X, y = _data(n=600, f=8, seed=1)
    ckdir = str(tmp_path / "ck")
    p = _fused_params(checkpoint_dir=ckdir, checkpoint_interval=4)

    baseline = lgb.train(p, lgb.Dataset(X, y, params=p), num_boost_round=8)
    ref = baseline.model_to_string()

    chaos.force_pallas_raise(at_iteration=1)
    lgb.train(p, lgb.Dataset(X, y, params=p), num_boost_round=4)
    chaos.reset()

    # resume from the DEGRADED run's checkpoint (iteration 4) explicitly —
    # the baseline above shares the directory and left a later one at 8
    ck4 = dict(list_checkpoints(ckdir))[4]
    resumed = lgb.train(
        p, lgb.Dataset(X, y, params=p), num_boost_round=8, resume_from=ck4
    )
    assert getattr(resumed, "_grow_fused_disabled", False), (
        "degradation latch lost across checkpoint/restore"
    )
    assert resumed.model_to_string() == ref


def test_chaos_pallas_raise_semantics():
    # default arming simulates a compile-time failure: trace-time consult
    # (iteration=None) fires
    chaos.force_pallas_raise()
    with pytest.raises(InjectedPallasFailure):
        chaos.maybe_raise_pallas("unit")
    # arming at a later iteration must NOT fire at trace time, only once
    # training reaches that iteration
    chaos.force_pallas_raise(at_iteration=2)
    chaos.maybe_raise_pallas("unit")  # trace-time: no raise
    chaos.maybe_raise_pallas("unit", iteration=1)  # earlier iter: no raise
    with pytest.raises(InjectedPallasFailure):
        chaos.maybe_raise_pallas("unit", iteration=2)
    chaos.reset()
    chaos.maybe_raise_pallas("unit")  # disarmed: no raise
    chaos.maybe_raise_pallas("unit", iteration=100)


# ================================================== non-finite guard rails
def test_check_numerics_flags_poisoned_gradients():
    X, y = _data()
    p = _params(check_numerics=True)
    chaos.poison_gradients_at(2)
    with pytest.raises(NumericsError, match=r"iteration 2.*Regression"):
        lgb.train(p, lgb.Dataset(X, y, params=p), num_boost_round=6)


def test_check_numerics_off_by_default_costs_nothing():
    # without the flag the poisoned run must NOT raise from the guard —
    # it silently degenerates (NaN gains kill every split and training
    # finishes early), which is exactly the failure mode the flag names
    X, y = _data()
    p = _params()
    chaos.poison_gradients_at(2)
    booster = lgb.train(p, lgb.Dataset(X, y, params=p), num_boost_round=4)
    assert booster.current_iteration() >= 2


def test_dataset_rejects_nonfinite_labels():
    X, y = _data(n=100)
    bad = y.copy()
    bad[7] = np.nan
    with pytest.raises(ValueError, match=r"non-finite.*row 7"):
        lgb.Dataset(X, bad).construct()

    ds = lgb.Dataset(X, y)
    ds.construct()
    inf_label = y.copy()
    inf_label[3] = np.inf
    with pytest.raises(ValueError, match=r"non-finite.*row 3"):
        ds.set_label(inf_label)


# ============================================== distributed init retry
def test_init_distributed_retries_then_succeeds(monkeypatch):
    from lightgbm_tpu import parallel as par

    calls = []

    def flaky(**kwargs):
        calls.append(kwargs)
        if len(calls) < 3:
            raise RuntimeError("coordination service bind race")

    monkeypatch.setattr(jax.distributed, "initialize", flaky)
    par.init_distributed(
        coordinator_address="localhost:1", num_processes=1, process_id=0,
        retries=3, backoff=0.0,
    )
    assert len(calls) == 3


def test_init_distributed_exhausts_retries(monkeypatch):
    from lightgbm_tpu import parallel as par

    def always_fails(**kwargs):
        raise RuntimeError("unreachable coordinator")

    monkeypatch.setattr(jax.distributed, "initialize", always_fails)
    with pytest.raises(RuntimeError, match="unreachable"):
        par.init_distributed(
            coordinator_address="localhost:1", num_processes=1,
            process_id=0, retries=2, backoff=0.0,
        )
