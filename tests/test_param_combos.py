"""Cross-feature interaction smoke tests: boosting modes x sampling x
categorical x constraints x quantization trained together must produce
finite, serializable, self-consistent models (the reference's config matrix
is exercised similarly by its R/python test grids)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import lightgbm_tpu as lgb  # noqa: E402

COMBOS = [
    {"boosting": "dart", "use_quantized_grad": True, "num_grad_quant_bins": 8},
    {"boosting": "dart", "bagging_fraction": 0.7, "bagging_freq": 1,
     "drop_rate": 0.3},
    {"boosting": "goss", "top_rate": 0.3, "other_rate": 0.2,
     "learning_rate": 0.3},
    {"boosting": "rf", "bagging_fraction": 0.6, "bagging_freq": 1},
    {"monotone_constraints": [1, -1, 0, 0], "lambda_l1": 0.5},
    {"use_quantized_grad": True, "quant_train_renew_leaf": True,
     "feature_fraction": 0.7},
    {"linear_tree": True, "lambda_l2": 1.0},
    {"min_gain_to_split": 0.5, "max_depth": 3,
     "interaction_constraints": [[0, 1], [2, 3]]},
    {"cegb_tradeoff": 1.0, "cegb_penalty_split": 0.01,
     "feature_fraction_bynode": 0.8},
    {"path_smooth": 2.0, "max_delta_step": 0.5, "extra_trees": True},
    # round-5 params riding existing machinery
    {"saved_feature_importance_type": 1, "early_stopping_round": 3,
     "early_stopping_min_delta": 0.001},
    {"monotone_constraints": [1, 0, -1, 0],
     "monotone_constraints_method": "advanced", "lambda_l2": 0.5},
]


@pytest.fixture(scope="module")
def xy():
    rng = np.random.default_rng(0)
    n = 1200
    X = rng.normal(size=(n, 4))
    X[:, 3] = rng.integers(0, 6, size=n)  # categorical column
    y = (
        X[:, 0]
        - 0.5 * X[:, 1]
        + np.where(X[:, 3] % 2 == 0, 0.7, -0.7)
        + rng.normal(scale=0.2, size=n)
    )
    return X, y


@pytest.mark.parametrize("extra", COMBOS)
def test_combo_trains_and_roundtrips(xy, extra):
    X, y = xy
    params = {
        "objective": "regression",
        "num_leaves": 15,
        "min_data_in_leaf": 5,
        "verbosity": -1,
        "seed": 7,
        **extra,
    }
    cat = [] if extra.get("linear_tree") else [3]
    b = lgb.train(params, lgb.Dataset(X, y, categorical_feature=cat), 8)
    p = b.predict(X)
    assert np.isfinite(p).all()
    assert p.std() > 0  # actually learned something
    b2 = lgb.Booster(model_str=b.model_to_string())
    np.testing.assert_allclose(b2.predict(X), p, rtol=1e-5, atol=1e-6)
    for t in b.models_:
        t.validate()


@pytest.mark.parametrize(
    "objective,extra",
    [
        ("binary", {"is_unbalance": True, "use_quantized_grad": True}),
        ("multiclass", {"num_class": 3, "bagging_fraction": 0.8,
                        "bagging_freq": 1}),
        ("regression_l1", {"boosting": "dart"}),
        ("huber", {"use_quantized_grad": True,
                   "quant_train_renew_leaf": True}),
        ("poisson", {"monotone_constraints": [1, 0, 0, 0]}),
    ],
)
def test_objective_combos(xy, objective, extra):
    X, y = xy
    if objective == "binary":
        y = (y > 0).astype(np.float64)
    elif objective == "multiclass":
        y = np.clip(np.digitize(y, [-0.5, 0.5]), 0, 2)
    elif objective == "poisson":
        y = np.abs(y)
    params = {
        "objective": objective,
        "num_leaves": 7,
        "min_data_in_leaf": 5,
        "verbosity": -1,
        **extra,
    }
    b = lgb.train(params, lgb.Dataset(X, y), 6)
    p = b.predict(X)
    assert np.isfinite(np.asarray(p)).all()
    b2 = lgb.Booster(model_str=b.model_to_string())
    np.testing.assert_allclose(np.asarray(b2.predict(X)), np.asarray(p),
                               rtol=1e-5, atol=1e-6)
