"""Int8 quantized histogram kernel vs the exact oracle (interpret mode —
numerics identical to the native TPU lowering since accumulation is integer).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from lightgbm_tpu.ops.histogram import leaf_histogram_segment  # noqa: E402
from lightgbm_tpu.ops.pallas.histogram_int8 import histogram_pallas_int8  # noqa: E402
from lightgbm_tpu.ops.quantize import quantize_gradients  # noqa: E402


def test_int8_training_path_matches_segment():
    """End-to-end: hist_method='pallas_int8_interpret' trains the identical
    model to the exact segment path on the same quantized gradients (integer
    accumulation is exact)."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(0)
    X = rng.normal(size=(1500, 6))
    y = X[:, 0] * 2 - X[:, 1] + rng.normal(scale=0.1, size=1500)
    base = {
        "objective": "regression",
        "verbosity": -1,
        "use_quantized_grad": True,
        "num_grad_quant_bins": 16,
        "quant_train_renew_leaf": True,
        "num_leaves": 15,
    }
    b_int8 = lgb.train(
        {**base, "hist_method": "pallas_int8_interpret"}, lgb.Dataset(X, y), 6
    )
    assert b_int8._grower_params.hist_method == "pallas_int8_interpret"
    b_seg = lgb.train({**base, "hist_method": "segment"}, lgb.Dataset(X, y), 6)
    np.testing.assert_allclose(
        b_int8.predict(X), b_seg.predict(X), rtol=1e-6, atol=1e-7
    )


def test_int8_method_requires_quantization():
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(0)
    X = rng.normal(size=(300, 3))
    y = X[:, 0]
    with pytest.raises(ValueError, match="quantized"):
        lgb.train(
            {"objective": "regression", "verbosity": -1,
             "hist_method": "pallas_int8_interpret"},
            lgb.Dataset(X, y),
            1,
        )


@pytest.mark.parametrize("n,f,b", [(500, 7, 16), (1200, 3, 64), (300, 30, 255)])
def test_int8_kernel_matches_oracle(n, f, b):
    rng = np.random.default_rng(n + f)
    bins = rng.integers(0, b, size=(n, f)).astype(np.int32)
    g = rng.normal(size=n).astype(np.float32)
    h = np.abs(rng.normal(size=n)).astype(np.float32) + 0.1
    mask = (rng.random(n) < 0.8).astype(np.float32)

    qg, qh, g_scale, h_scale = quantize_gradients(
        jnp.asarray(g), jnp.asarray(h), jax.random.PRNGKey(0),
        num_bins=8, stochastic=False,
    )

    got = histogram_pallas_int8(
        jnp.asarray(bins), qg, qh, jnp.asarray(mask), b,
        g_scale, h_scale, interpret=True,
    )
    want = leaf_histogram_segment(
        jnp.asarray(bins), qg, qh, jnp.asarray(mask), b
    )
    # integer accumulation is exact; only the final scale multiply rounds
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )
    # counts are exactly the masked row counts
    np.testing.assert_array_equal(
        np.asarray(got)[..., 2].sum(axis=1), np.full(f, mask.sum())
    )
