"""Monotone constraint tests: basic vs intermediate vs advanced
(reference: src/treelearner/monotone_constraints.hpp — BasicLeafConstraints
:465, IntermediateLeafConstraints :516, AdvancedLeafConstraints :858).

Property: predictions must be monotone along constrained features for ALL
methods.  Quality: intermediate's output-based bounds are tighter than
basic's midpoint bounds, and advanced's per-threshold slice bounds are less
restrictive than intermediate's whole-leaf scalars, so training loss must
not degrade along the ladder (the reference documents each step as an
accuracy upgrade)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _make_data(seed=3, n=4000):
    rng = np.random.default_rng(seed)
    X = rng.uniform(-3, 3, size=(n, 4))
    y = (
        2.0 * X[:, 0]
        + np.sin(2 * X[:, 1])
        - 1.5 * X[:, 2]
        + 0.7 * X[:, 3] ** 2
        + rng.normal(scale=0.2, size=n)
    )
    return X, y


def _check_monotone(booster, X, feat, direction, grid=21):
    """Sweep one feature over its range for a batch of rows; prediction must
    move with `direction` pointwise."""
    rows = X[:64].copy()
    vals = np.linspace(X[:, feat].min(), X[:, feat].max(), grid)
    preds = []
    for v in vals:
        r = rows.copy()
        r[:, feat] = v
        preds.append(booster.predict(r))
    P = np.stack(preds)  # [grid, rows]
    diffs = np.diff(P, axis=0) * direction
    assert (diffs >= -1e-9).all(), (
        f"feature {feat} violates monotonicity: worst {diffs.min()}"
    )


@pytest.mark.parametrize("method", ["basic", "intermediate", "advanced"])
def test_monotone_property(method):
    X, y = _make_data()
    params = {
        "objective": "regression",
        "num_leaves": 31,
        "verbosity": -1,
        "metric": "none",
        "monotone_constraints": [1, 0, -1, 0],
        "monotone_constraints_method": method,
    }
    b = lgb.train(params, lgb.Dataset(X, y, params=params), 25)
    _check_monotone(b, X, 0, +1)
    _check_monotone(b, X, 2, -1)


def test_intermediate_not_worse_than_basic():
    X, y = _make_data()
    out = {}
    for method in ("basic", "intermediate"):
        params = {
            "objective": "regression",
            "num_leaves": 63,
            "verbosity": -1,
            "metric": "none",
            "monotone_constraints": [1, 0, -1, 0],
            "monotone_constraints_method": method,
        }
        b = lgb.train(params, lgb.Dataset(X, y, params=params), 40)
        mse = float(np.mean((b.predict(X) - y) ** 2))
        out[method] = mse
    # tighter bounds must not lose accuracy (allow 2% noise margin)
    assert out["intermediate"] <= out["basic"] * 1.02, out


def test_advanced_not_worse_than_intermediate():
    """Advanced's per-threshold slice bounds usually relax the scan
    constraints vs intermediate's whole-leaf scalars, but not always:
    advanced also binds against DISTANT ordered leaves that intermediate's
    touch-propagation never reached.  The loss comparison is therefore a
    quality regression check on this data/seed, not a mathematical
    invariant."""
    X, y = _make_data()
    out = {}
    for method in ("intermediate", "advanced"):
        params = {
            "objective": "regression",
            "num_leaves": 63,
            "verbosity": -1,
            "metric": "none",
            "monotone_constraints": [1, 0, -1, 0],
            "monotone_constraints_method": method,
        }
        b = lgb.train(params, lgb.Dataset(X, y, params=params), 40)
        mse = float(np.mean((b.predict(X) - y) ** 2))
        out[method] = mse
    assert out["advanced"] <= out["intermediate"] * 1.02, out


def test_advanced_rejects_wide_bins():
    """advanced + max_bin > 256 would materialize tens-of-GB per-threshold
    bound planes; the config rejects the combination with a clear error
    instead of OOMing mid-train (r4 ADVICE)."""
    X, y = _make_data()
    params = {
        "objective": "regression",
        "verbosity": -1,
        "max_bin": 1024,
        "monotone_constraints": [1, 0, -1, 0],
        "monotone_constraints_method": "advanced",
    }
    with pytest.raises(ValueError, match="advanced"):
        lgb.train(params, lgb.Dataset(X, y, params=params), 2)
    # without constraints the method param is inert and wide bins are fine
    params.pop("monotone_constraints")
    lgb.train(params, lgb.Dataset(X, y, params=params), 2)


def test_advanced_monotone_with_path_smooth():
    """Smoothing is applied BEFORE the monotone clip at finalize; the
    advanced bound recompute must see smoothed outputs or cross-leaf
    ordering can break."""
    X, y = _make_data(seed=9, n=2500)
    params = {
        "objective": "regression",
        "num_leaves": 31,
        "verbosity": -1,
        "metric": "none",
        "monotone_constraints": [1, 0, -1, 0],
        "monotone_constraints_method": "advanced",
        "path_smooth": 5.0,
        "min_data_in_leaf": 5,
    }
    b = lgb.train(params, lgb.Dataset(X, y, params=params), 25)
    _check_monotone(b, X, 0, +1)
    _check_monotone(b, X, 2, -1)


def test_advanced_monotone_with_categoricals():
    """Advanced mode with a categorical feature in the mix: categorical
    splits keep the parent box, numeric monotonicity still holds."""
    rng = np.random.default_rng(11)
    n = 2500
    X = np.column_stack(
        [
            rng.uniform(-3, 3, size=n),
            rng.integers(0, 5, size=n).astype(float),
            rng.uniform(-3, 3, size=n),
        ]
    )
    y = 2.0 * X[:, 0] + (X[:, 1] == 2) * 1.5 - X[:, 2] + rng.normal(
        scale=0.2, size=n
    )
    params = {
        "objective": "regression",
        "num_leaves": 31,
        "verbosity": -1,
        "metric": "none",
        "monotone_constraints": [1, 0, -1],
        "monotone_constraints_method": "advanced",
        "categorical_feature": [1],
    }
    b = lgb.train(params, lgb.Dataset(X, y, params=params), 25)
    _check_monotone(b, X, 0, +1)
    _check_monotone(b, X, 2, -1)


# ---- monotone_penalty (reference monotone_constraints.hpp:357-366) -------

def _dup_feature_hist(seed=0, n=2000, b=32):
    """Two IDENTICAL feature columns -> exactly tied best gains, so any
    penalty on one feature must flip the argmax to the other."""
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    bins = rng.integers(0, b, size=n)
    g = rng.normal(size=n).astype(np.float32) - 0.3 * (bins > b // 2)
    h = np.ones(n, np.float32)
    hist = np.zeros((2, b, 3), np.float32)
    for j in range(2):
        np.add.at(hist[j, :, 0], bins, g)
        np.add.at(hist[j, :, 1], bins, h)
        np.add.at(hist[j, :, 2], bins, 1.0)
    parent = hist[0].sum(axis=0)
    return (
        jnp.asarray(hist),
        parent,
        jnp.full((2,), b, np.int32),
        jnp.full((2,), -1, np.int32),
        jnp.ones((2,), bool),
    )


_BS_HP = dict(
    lambda_l1=0.0,
    lambda_l2=0.01,
    min_data_in_leaf=5,
    min_sum_hessian_in_leaf=1e-3,
    min_gain_to_split=0.0,
)


def test_penalized_split_loses_to_unpenalized_at_matched_gain():
    """feature 0 is monotone-constrained, feature 1 is its exact copy but
    unconstrained: with monotone_penalty the tie must break to feature 1
    (serial argmax alone would pick feature 0)."""
    import jax.numpy as jnp

    from lightgbm_tpu.ops.split import best_split

    hist, parent, num_bins, nan_bins, mask = _dup_feature_hist()
    mono = jnp.asarray([1, 0], jnp.int8)
    base = best_split(
        hist, parent[0], parent[1], parent[2], num_bins, nan_bins, mask,
        monotone=mono, **_BS_HP,
    )
    assert int(base.feature) == 0  # tie -> lowest index without penalty
    pen = best_split(
        hist, parent[0], parent[1], parent[2], num_bins, nan_bins, mask,
        monotone=mono, monotone_penalty=1.0,
        leaf_depth=jnp.asarray(0, jnp.int32), **_BS_HP,
    )
    assert int(pen.feature) == 1
    # the winning (unpenalized) candidate keeps its full gain
    np.testing.assert_allclose(float(pen.gain), float(base.gain), rtol=1e-6)


def test_monotone_penalty_decays_with_depth():
    """The penalty factor is 1 - penalty/2^depth (penalty <= 1): deeper
    leaves are penalized less, converging to the unpenalized gain."""
    import jax.numpy as jnp

    from lightgbm_tpu.ops.split import best_split

    hist, parent, num_bins, nan_bins, mask = _dup_feature_hist(seed=1)
    mono = jnp.asarray([1, 1], jnp.int8)  # both constrained -> both penalized
    base = best_split(
        hist, parent[0], parent[1], parent[2], num_bins, nan_bins, mask,
        monotone=mono, **_BS_HP,
    )
    gains = []
    for depth in (0, 1, 4):
        c = best_split(
            hist, parent[0], parent[1], parent[2], num_bins, nan_bins, mask,
            monotone=mono, monotone_penalty=1.0,
            leaf_depth=jnp.asarray(depth, jnp.int32), **_BS_HP,
        )
        gains.append(float(c.gain))
    assert gains[0] < gains[1] < gains[2] <= float(base.gain) + 1e-6
    # depth 0 -> children at depth 1 -> factor 1 - 1/2 = 0.5
    np.testing.assert_allclose(gains[0], 0.5 * float(base.gain), rtol=1e-5)


def test_monotone_penalty_e2e_still_monotone():
    X, y = _make_data()
    params = {
        "objective": "regression",
        "num_leaves": 31,
        "verbosity": -1,
        "metric": "none",
        "monotone_constraints": [1, 0, -1, 0],
        "monotone_penalty": 1.5,
        "min_data_in_leaf": 5,
    }
    b = lgb.train(params, lgb.Dataset(X, y, params=params), 15)
    assert len(b.models_) == 15
    _check_monotone(b, X, 0, +1)
    _check_monotone(b, X, 2, -1)
