"""Monotone constraint tests: basic vs intermediate
(reference: src/treelearner/monotone_constraints.hpp — BasicLeafConstraints
:465, IntermediateLeafConstraints :516).

Property: predictions must be monotone along constrained features for BOTH
methods.  Quality: intermediate's output-based bounds are tighter than
basic's midpoint bounds, so training loss must not degrade (the reference
documents intermediate as the accuracy upgrade over basic).
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _make_data(seed=3, n=4000):
    rng = np.random.default_rng(seed)
    X = rng.uniform(-3, 3, size=(n, 4))
    y = (
        2.0 * X[:, 0]
        + np.sin(2 * X[:, 1])
        - 1.5 * X[:, 2]
        + 0.7 * X[:, 3] ** 2
        + rng.normal(scale=0.2, size=n)
    )
    return X, y


def _check_monotone(booster, X, feat, direction, grid=21):
    """Sweep one feature over its range for a batch of rows; prediction must
    move with `direction` pointwise."""
    rows = X[:64].copy()
    vals = np.linspace(X[:, feat].min(), X[:, feat].max(), grid)
    preds = []
    for v in vals:
        r = rows.copy()
        r[:, feat] = v
        preds.append(booster.predict(r))
    P = np.stack(preds)  # [grid, rows]
    diffs = np.diff(P, axis=0) * direction
    assert (diffs >= -1e-9).all(), (
        f"feature {feat} violates monotonicity: worst {diffs.min()}"
    )


@pytest.mark.parametrize("method", ["basic", "intermediate"])
def test_monotone_property(method):
    X, y = _make_data()
    params = {
        "objective": "regression",
        "num_leaves": 31,
        "verbosity": -1,
        "metric": "none",
        "monotone_constraints": [1, 0, -1, 0],
        "monotone_constraints_method": method,
    }
    b = lgb.train(params, lgb.Dataset(X, y, params=params), 25)
    _check_monotone(b, X, 0, +1)
    _check_monotone(b, X, 2, -1)


def test_intermediate_not_worse_than_basic():
    X, y = _make_data()
    out = {}
    for method in ("basic", "intermediate"):
        params = {
            "objective": "regression",
            "num_leaves": 63,
            "verbosity": -1,
            "metric": "none",
            "monotone_constraints": [1, 0, -1, 0],
            "monotone_constraints_method": method,
        }
        b = lgb.train(params, lgb.Dataset(X, y, params=params), 40)
        mse = float(np.mean((b.predict(X) - y) ** 2))
        out[method] = mse
    # tighter bounds must not lose accuracy (allow 2% noise margin)
    assert out["intermediate"] <= out["basic"] * 1.02, out


def test_advanced_falls_back_to_intermediate():
    X, y = _make_data(n=800)
    params = {
        "objective": "regression",
        "num_leaves": 15,
        "verbosity": -1,
        "metric": "none",
        "monotone_constraints": [1, 0, 0, 0],
        "monotone_constraints_method": "advanced",
    }
    b = lgb.train(params, lgb.Dataset(X, y, params=params), 10)
    _check_monotone(b, X, 0, +1)
