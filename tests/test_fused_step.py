"""Fused grow-step oracle parity (ops/pallas/grow_step.py).

Off-TPU, ``fused_grow_step`` lowers to the SAME XLA composition the
two-launch grower path runs (sequential stable-sort partitions + local
election + masked reference histogram), so CPU training with
``grow_fused`` on must be byte-identical to the oracle — the full model
dump is compared, not just structure.  The interpret-mode tests exercise
the actual Pallas kernel; its bf16 three-term histogram differs from the
f32 reference at ~1e-6, which can flip near-tie splits on hard data, so
those tests use well-separated data / few rounds and compare structure
plus predictions.

Engagement note: ``grow_fused='auto'`` resolves to the seg fast path,
which off-TPU must be requested explicitly (``hist_mode='seg'``) — the
booster's auto hist mode only picks seg on a TPU backend.

Trace-staleness note: ``grow_step._INTERPRET`` is read at TRACE time.
The interpret tests use distinctive shapes/params so no earlier test in
the process has already cached a non-interpret trace for the same
GrowerParams (which would silently run the oracle instead).
"""

import numpy as np
import pytest
import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.ops.pallas import grow_step
from lightgbm_tpu.ops.pallas.seg import pack_rows, padded_rows

BASE = dict(
    objective="binary", num_leaves=31, learning_rate=0.2, hist_mode="seg",
    min_data_in_leaf=5, verbosity=-1, deterministic=True, seed=7,
)

_STRUCT = (
    "split_feature=", "threshold=", "decision_type=", "left_child=",
    "right_child=", "num_leaves=",
)


def _trees(booster):
    """Model dump sliced to the trees section (the trailing parameters
    echo differs by construction when only grow_fused differs)."""
    s = booster.model_to_string()
    return s[s.index("Tree=0"):s.index("end of trees")]


def _structure(booster):
    return [l for l in _trees(booster).splitlines() if l.startswith(_STRUCT)]


@pytest.fixture(scope="module")
def xy():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(2000, 12)).astype(np.float32)
    y = (
        X[:, 0] + 0.5 * X[:, 1] ** 2 + 0.1 * rng.normal(size=2000) > 0.4
    ).astype(np.float32)
    return X, y


def _fit(X, y, rounds=8, dataset_kw=None, **over):
    p = {**BASE, **over}
    ds = lgb.Dataset(X, label=y, **(dataset_kw or {}))
    return lgb.train(p, ds, num_boost_round=rounds)


def test_fused_serial_parity(xy):
    X, y = xy
    ref = _fit(X, y, grow_fused="off")
    got = _fit(X, y, grow_fused="on")
    assert got._grower_params.grow_fused  # engagement, not a vacuous pass
    assert not ref._grower_params.grow_fused
    assert _trees(got) == _trees(ref)


@pytest.mark.parametrize("k", [2, 4])
def test_fused_batched_parity(xy, k):
    X, y = xy
    kw = dict(leaf_batch=k, leaf_batch_adaptive=False)
    ref = _fit(X, y, grow_fused="off", **kw)
    got = _fit(X, y, grow_fused="on", **kw)
    assert got._grower_params.leaf_batch == k
    assert _trees(got) == _trees(ref)


def test_fused_auto_resolves_on_seg(xy):
    X, y = xy
    auto = _fit(X, y, grow_fused="auto")
    assert auto._grower_params.grow_fused
    assert _trees(auto) == _trees(_fit(X, y, grow_fused="on"))


def test_fused_batched_matches_serial_structure(xy):
    """K-batched fused growth commits the same structure serial growth
    does (values can differ only if structure did — require both equal)."""
    X, y = xy
    serial = _fit(X, y, grow_fused="on")
    k4 = _fit(X, y, grow_fused="on", leaf_batch=4, leaf_batch_adaptive=False)
    assert _structure(k4) == _structure(serial)


def test_fused_inert_on_ordered_mode(xy):
    """grow_fused='on' without the seg fast path must not engage or
    perturb training — the ordered-mode dump stays byte-identical."""
    X, y = xy
    ref = _fit(X, y, hist_mode="ordered", grow_fused="off")
    got = _fit(X, y, hist_mode="ordered", grow_fused="on")
    assert _trees(got) == _trees(ref)


def test_fused_categorical_parity(xy):
    X, y = xy
    Xc = X.copy()
    rng = np.random.default_rng(3)
    Xc[:, 0] = rng.integers(0, 12, size=len(y)).astype(np.float32)
    kw = dict(dataset_kw=dict(categorical_feature=[0]))
    assert _trees(_fit(Xc, y, grow_fused="on", **kw)) == _trees(
        _fit(Xc, y, grow_fused="off", **kw)
    )


def test_fused_monotone_parity(xy):
    X, y = xy
    mc = [1, 0, -1] + [0] * (X.shape[1] - 3)
    assert _trees(_fit(X, y, grow_fused="on", monotone_constraints=mc)) == (
        _trees(_fit(X, y, grow_fused="off", monotone_constraints=mc))
    )


def test_fused_forced_splits_parity(xy, tmp_path):
    X, y = xy
    fs = tmp_path / "forced.json"
    fs.write_text('{"feature": 0, "threshold": 0.0, "left": '
                  '{"feature": 1, "threshold": 0.5}}')
    kw = dict(forcedsplits_filename=str(fs))
    assert _trees(_fit(X, y, grow_fused="on", **kw)) == _trees(
        _fit(X, y, grow_fused="off", **kw)
    )


def test_fused_quantized_parity(xy):
    X, y = xy
    kw = dict(use_quantized_grad=True)
    assert _trees(_fit(X, y, grow_fused="on", **kw)) == _trees(
        _fit(X, y, grow_fused="off", **kw)
    )


def test_fused_tree_learner_data_parity(xy):
    X, y = xy
    kw = dict(tree_learner="data", leaf_batch=2, leaf_batch_adaptive=False)
    assert _trees(_fit(X, y, grow_fused="on", **kw)) == _trees(
        _fit(X, y, grow_fused="off", **kw)
    )


def test_fused_no_recompile_after_warmup(xy):
    X, y = xy
    params = {**BASE, "grow_fused": "on", "leaf_batch": 2,
              "leaf_batch_adaptive": False}
    booster = lgb.Booster(params, lgb.Dataset(X, label=y))
    for _ in range(2):
        booster.update()
    warm = lgb.compile_count()
    warm_labels = dict(lgb.compile_counts_by_label())
    for _ in range(6):
        booster.update()
    assert lgb.compile_count() == warm, (
        f"retraced after warmup: {lgb.compile_counts_by_label()} "
        f"vs {warm_labels}"
    )


def test_fused_kernel_interpret_matches_oracle():
    """The actual Pallas kernel (interpret mode off-TPU) vs the XLA
    oracle, standalone: adjacent non-tile-aligned K=2 windows.  Partition
    state and split decisions must be bit-equal; the histogram is bf16
    three-term vs f32 reference, so values compare at kernel tolerance."""
    rng = np.random.default_rng(5)
    f, n = 11, 5000
    n_pad = padded_rows(n)
    bins = rng.integers(0, 256, size=(n, f)).astype(np.int32)
    g = rng.normal(size=n).astype(np.float32)
    h = rng.random(n).astype(np.float32) + 0.5
    m = np.ones(n, np.float32)
    seg = pack_rows(
        jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h), jnp.asarray(m),
        n_pad,
    )
    catm = jnp.zeros((2, 1), jnp.float32)
    kw = dict(f=f, num_bins=256, n_pad=n_pad)
    args = (
        jnp.asarray([37, 37 + 1900], jnp.int32),  # adjacent, unaligned
        jnp.asarray([1900, 2300], jnp.int32),
        jnp.asarray([3, 7], jnp.int32),
        jnp.asarray([120, 80], jnp.int32),
        jnp.asarray([0, 1], jnp.int32),
        jnp.asarray([-1, 200], jnp.int32),
        jnp.asarray([0, 0], jnp.int32),
        catm,
    )
    want = grow_step.fused_grow_step(seg, *args, **kw)
    assert not grow_step._INTERPRET
    grow_step._INTERPRET = True
    try:
        got = grow_step.fused_grow_step(seg, *args, **kw)
    finally:
        grow_step._INTERPRET = False
    for i, name in enumerate(("seg", "nl", "nr", "child_start", "child_cnt")):
        assert np.array_equal(np.asarray(got[i]), np.asarray(want[i])), name
    np.testing.assert_allclose(
        np.asarray(got[5]), np.asarray(want[5]), rtol=1e-3, atol=1e-3
    )


def test_fused_booster_interpret_structure():
    """End-to-end through the booster with the real kernel (interpret):
    distinctive shapes/params guarantee a fresh trace (see module note);
    well-separated data keeps near-tie gains out of bf16 flip range, so
    structure parity and prediction closeness must hold for serial and
    K=2."""
    rng = np.random.default_rng(0)
    X = rng.normal(size=(1200, 10)).astype(np.float32)
    y = (
        X[:, 0] + 0.6 * X[:, 1] + 0.1 * rng.normal(size=1200) > 0.2
    ).astype(np.float32)

    def run(**over):
        p = {**BASE, "num_leaves": 15, "min_data_in_leaf": 20}
        p.update(over)
        b = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=3)
        return _structure(b), b.predict(X[:200])

    s_ref, p_ref = run(grow_fused="off")
    s_ref2, _ = run(grow_fused="off", leaf_batch=2, leaf_batch_adaptive=False)
    assert not grow_step._INTERPRET
    grow_step._INTERPRET = True
    try:
        s1, p1 = run(grow_fused="on")
        s2, p2 = run(grow_fused="on", leaf_batch=2, leaf_batch_adaptive=False)
    finally:
        grow_step._INTERPRET = False
    assert s1 == s_ref
    assert s2 == s_ref2
    np.testing.assert_allclose(p1, p_ref, atol=1e-6)
    np.testing.assert_allclose(p2, p_ref, atol=1e-6)
