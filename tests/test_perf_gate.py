"""Perf-contract gate (tools/perf_gate.py): the deterministic-telemetry diff
that tools/run_tests.sh runs as a hard CI gate.

The gate's check logic is exercised via --replay-style metric dicts (no jax,
no scenario runs): an injected retrace/collective regression must FAIL the
gate, while wall-time drift only WARNS — the hard/soft split that keeps the
gate deterministic."""

import importlib.util
import json
import os

import pytest

_GATE_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools",
    "perf_gate.py",
)


@pytest.fixture(scope="module")
def gate():
    spec = importlib.util.spec_from_file_location("perf_gate", _GATE_PATH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


BASE_METRICS = {
    "retrace/serial/grow_tree": 1.0,
    "retrace/serial/predict/stream/packed": 1.0,
    "retrace/data_parallel/parallel/sharded_grow": 1.0,
    "collective/analytic_bytes": 161448.0,
    "collective/measured_psum_bytes": 161424.0,
    "cost/grow_tree/flops": 181986.0,
    "memory/grow_tree/temp_bytes": 76640.0,
    "wall/serial_train_s": 4.1,
}


def test_policy_hard_soft_split(gate):
    assert gate.policy_for("retrace/serial/grow_tree") == (True, 0.0, 0.0)
    assert gate.policy_for("collective/analytic_bytes") == (True, 0.0, 0.0)
    hard, tol_rel, _ = gate.policy_for("cost/grow_tree/flops")
    assert hard and tol_rel > 0
    assert gate.policy_for("wall/serial_train_s")[0] is False


def test_identical_metrics_pass(gate):
    contract = gate.build_contract(BASE_METRICS, None, "init")
    failures, warnings = gate.check(dict(BASE_METRICS), contract)
    assert failures == 0 and warnings == 0


def test_injected_retrace_regression_fails(gate, capsys):
    """A retrace storm (one extra trace of a hot label) must fail HARD."""
    contract = gate.build_contract(BASE_METRICS, None, "init")
    bad = dict(BASE_METRICS)
    bad["retrace/serial/grow_tree"] = 2.0  # regression: retraces per call
    failures, _ = gate.check(bad, contract)
    assert failures == 1
    assert "retrace/serial/grow_tree" in capsys.readouterr().out


def test_injected_collective_regression_fails(gate):
    """Analytic psum bytes growing (someone widened a collective) fails."""
    contract = gate.build_contract(BASE_METRICS, None, "init")
    bad = dict(BASE_METRICS)
    bad["collective/analytic_bytes"] *= 2
    failures, _ = gate.check(bad, contract)
    assert failures == 1


def test_cost_tolerance_band(gate):
    """cost/* metrics tolerate small XLA-version wobble but fail on jumps."""
    contract = gate.build_contract(BASE_METRICS, None, "init")
    drift = dict(BASE_METRICS)
    drift["cost/grow_tree/flops"] *= 1.05  # inside the 10% band
    assert gate.check(drift, contract)[0] == 0
    jump = dict(BASE_METRICS)
    jump["cost/grow_tree/flops"] *= 1.5
    assert gate.check(jump, contract)[0] == 1


def test_wall_time_drift_warns_only(gate, capsys):
    contract = gate.build_contract(BASE_METRICS, None, "init")
    slow = dict(BASE_METRICS)
    # far outside even the generous soft band (tol_abs 50 + 50% rel)
    slow["wall/serial_train_s"] *= 100
    failures, warnings = gate.check(slow, contract)
    assert failures == 0 and warnings == 1
    assert "WARN" in capsys.readouterr().out


def test_missing_hard_metric_fails_missing_soft_passes(gate):
    contract = gate.build_contract(BASE_METRICS, None, "init")
    partial = {
        k: v for k, v in BASE_METRICS.items() if k != "cost/grow_tree/flops"
    }
    assert gate.check(partial, contract)[0] == 1
    no_wall = {
        k: v for k, v in BASE_METRICS.items() if k != "wall/serial_train_s"
    }
    assert gate.check(no_wall, contract)[0] == 0


def test_new_metric_warns_until_frozen(gate):
    contract = gate.build_contract(BASE_METRICS, None, "init")
    extra = dict(BASE_METRICS)
    extra["retrace/serial/new_label"] = 1.0
    failures, warnings = gate.check(extra, contract)
    assert failures == 0 and warnings == 1


def test_main_replay_roundtrip(gate, tmp_path):
    """End-to-end CLI flow on a replay dump: --update creates the contract,
    a clean re-check passes, an injected regression exits non-zero."""
    metrics_path = str(tmp_path / "metrics.json")
    contract_path = str(tmp_path / "contract.json")
    with open(metrics_path, "w") as fp:
        json.dump(BASE_METRICS, fp)
    assert (
        gate.main(
            ["--replay", metrics_path, "--contract", contract_path, "--update"]
        )
        == 0
    )
    assert os.path.exists(contract_path)
    assert (
        gate.main(["--replay", metrics_path, "--contract", contract_path])
        == 0
    )
    bad = dict(BASE_METRICS)
    bad["collective/measured_psum_bytes"] *= 3
    bad_path = str(tmp_path / "bad.json")
    with open(bad_path, "w") as fp:
        json.dump(bad, fp)
    assert (
        gate.main(["--replay", bad_path, "--contract", contract_path]) == 1
    )


def test_update_requires_justification_on_change(gate, tmp_path):
    metrics_path = str(tmp_path / "metrics.json")
    contract_path = str(tmp_path / "contract.json")
    with open(metrics_path, "w") as fp:
        json.dump(BASE_METRICS, fp)
    gate.main(
        ["--replay", metrics_path, "--contract", contract_path, "--update"]
    )
    changed = dict(BASE_METRICS)
    changed["cost/grow_tree/flops"] *= 2
    changed_path = str(tmp_path / "changed.json")
    with open(changed_path, "w") as fp:
        json.dump(changed, fp)
    # changed metrics without --justify: refused (exit 2), contract intact
    assert (
        gate.main(
            ["--replay", changed_path, "--contract", contract_path, "--update"]
        )
        == 2
    )
    before = json.load(open(contract_path))
    assert (
        before["metrics"]["cost/grow_tree/flops"]["value"]
        == BASE_METRICS["cost/grow_tree/flops"]
    )
    # with --justify the accepted drift lands with its audit line
    assert (
        gate.main(
            [
                "--replay",
                changed_path,
                "--contract",
                contract_path,
                "--update",
                "--justify",
                "grower rewrite doubled fused FLOPs intentionally",
            ]
        )
        == 0
    )
    after = json.load(open(contract_path))
    entry = after["metrics"]["cost/grow_tree/flops"]
    assert entry["value"] == changed["cost/grow_tree/flops"]
    assert "intentionally" in entry["justification"]


def test_missing_contract_is_an_error(gate, tmp_path):
    metrics_path = str(tmp_path / "metrics.json")
    with open(metrics_path, "w") as fp:
        json.dump(BASE_METRICS, fp)
    rc = gate.main(
        [
            "--replay",
            metrics_path,
            "--contract",
            str(tmp_path / "nope.json"),
        ]
    )
    assert rc == 2


def test_committed_contract_exists_and_is_wellformed(gate):
    """tools/perf_contract.json is committed and every metric entry has the
    gate's schema (run_tests.sh depends on it)."""
    contract = gate.load_contract(gate.DEFAULT_CONTRACT)
    assert contract is not None, "tools/perf_contract.json missing"
    assert contract["version"] == 1
    metrics = contract["metrics"]
    assert metrics, "empty contract"
    for name, entry in metrics.items():
        assert {"value", "hard", "tol_rel", "tol_abs"} <= set(entry)
        assert entry["justification"]
    # the contract covers every hard family the gate collects
    prefixes = {n.split("/")[0] for n in metrics}
    assert {"retrace", "collective", "cost", "memory"} <= prefixes
