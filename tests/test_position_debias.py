"""Lambdarank position debias (reference: rank_objective.hpp:44-84 score
adjustment + :302 UpdatePositionBiasFactors Newton step)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import lightgbm_tpu as lgb  # noqa: E402


def _ranking_problem(seed=0, n_query=40, q=8):
    rng = np.random.default_rng(seed)
    n = n_query * q
    X = rng.normal(size=(n, 4))
    rel = (X[:, 0] > 0.3).astype(np.float64) + (X[:, 1] > 0.8)
    group = np.full(n_query, q)
    # position = display rank within each query (0..q-1); labels are
    # click-biased toward early positions
    position = np.tile(np.arange(q), n_query)
    click_prob = np.clip(rel / 2.0, 0, 1) * (1.0 / (1.0 + position))
    label = (rng.random(n) < click_prob).astype(np.float64)
    return X, label, group, position


def test_position_bias_factors_update_and_change_gradients():
    X, y, group, position = _ranking_problem()
    params = {
        "objective": "lambdarank",
        "verbosity": -1,
        "num_leaves": 7,
        "min_data_in_leaf": 2,
        "lambdarank_position_bias_regularization": 0.5,
    }
    d = lgb.Dataset(X, y, group=group, position=position)
    b = lgb.Booster(params, d)
    obj = b.objective
    assert obj._pos_inv is not None
    assert obj.num_position_ids == 8
    b0 = np.asarray(obj.pos_biases).copy()
    assert np.all(b0 == 0.0)
    b.update()
    b.update()
    b1 = np.asarray(obj.pos_biases)
    assert np.any(b1 != 0.0), "bias factors never updated"

    # gradients differ from the position-free run at the same score
    d2 = lgb.Dataset(X, y, group=group)
    b_nopos = lgb.Booster(params, d2)
    b_nopos.update()
    b_nopos.update()
    g_pos, _ = obj.get_gradients(b._score)
    g_nop, _ = b_nopos.objective.get_gradients(b._score)
    assert np.abs(np.asarray(g_pos) - np.asarray(g_nop)).max() > 0

    # training still reduces rank loss
    res = b.eval_train()
    assert np.isfinite([v for (_, _, v, _) in res]).all()


def test_position_none_unchanged():
    X, y, group, _ = _ranking_problem(seed=3)
    params = {"objective": "lambdarank", "verbosity": -1, "num_leaves": 7,
              "min_data_in_leaf": 2}
    b1 = lgb.train(params, lgb.Dataset(X, y, group=group), 5)
    b2 = lgb.train(params, lgb.Dataset(X, y, group=group), 5)
    np.testing.assert_allclose(b1.predict(X), b2.predict(X))


def test_position_survives_binary_roundtrip(tmp_path):
    """The .position sidecar loads through the text path and survives
    save_binary/load (silently dropping it would disable debias on the
    reference CLI's standard binary-dataset workflow)."""
    rng = np.random.default_rng(5)
    n, per = 300, 30
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] > 0).astype(float)
    data = tmp_path / "t.csv"
    np.savetxt(data, np.column_stack([y, X]), delimiter=",", fmt="%.6f")
    np.savetxt(str(data) + ".query", np.full(n // per, per), fmt="%d")
    pos = np.tile(np.arange(per), n // per)
    np.savetxt(str(data) + ".position", pos, fmt="%d")
    p = {"objective": "lambdarank", "verbosity": -1}
    ds = lgb.Dataset(str(data), params=p)
    ds.construct()
    np.testing.assert_array_equal(ds.get_position(), pos)
    f = str(tmp_path / "t.bin")
    ds.save_binary(f)
    d2 = lgb.Dataset(f, params=p)
    d2.construct()
    np.testing.assert_array_equal(d2.get_position(), pos)
    b = lgb.train(p, d2, 3)
    assert b.num_trees() >= 1
