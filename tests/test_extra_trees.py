"""extra_trees (extremely randomized trees — reference USE_RAND branch of
FindBestThresholdSequentially: one random threshold per feature per node)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import lightgbm_tpu as lgb  # noqa: E402


@pytest.fixture(scope="module")
def xy():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(1500, 5))
    y = X[:, 0] * 2 - X[:, 1] + rng.normal(scale=0.2, size=1500)
    return X, y


def test_extra_trees_randomizes_thresholds_but_learns(xy):
    X, y = xy
    base = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
            "seed": 3}
    b_norm = lgb.train(base, lgb.Dataset(X, y), 10)
    b_et = lgb.train({**base, "extra_trees": True}, lgb.Dataset(X, y), 10)
    t_n, t_e = b_norm.models_[0], b_et.models_[0]
    assert not np.array_equal(np.asarray(t_n.threshold), np.asarray(t_e.threshold))
    mse = float(np.mean((b_et.predict(X) - y) ** 2))
    assert mse < np.var(y) * 0.3  # randomized splits still learn


def test_extra_trees_deterministic_per_seed(xy):
    X, y = xy
    params = {"objective": "regression", "num_leaves": 7, "verbosity": -1,
              "seed": 11, "extra_trees": True}
    b1 = lgb.train(params, lgb.Dataset(X, y), 5)
    b2 = lgb.train(params, lgb.Dataset(X, y), 5)
    np.testing.assert_array_equal(b1.predict(X), b2.predict(X))
    b3 = lgb.train({**params, "seed": 12}, lgb.Dataset(X, y), 5)
    assert not np.array_equal(b1.predict(X), b3.predict(X))
