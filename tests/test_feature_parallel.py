"""Feature-parallel learner tests (reference:
src/treelearner/feature_parallel_tree_learner.cpp — every machine holds the
full data, features are partitioned for histogram/split-finding, the best
split is all-reduced, partitioning is local).

The TPU formulation (ops/grower.py feature_shard) slices features by mesh
axis_index and all-reduces the winner; results must equal serial training
EXACTLY (same histograms, same scan, deterministic tie-break by shard
order = feature order)."""

import numpy as np

import lightgbm_tpu as lgb


def _data(n=3000, f=16, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (
        X[:, 0] * 2 + np.sin(X[:, 5]) - X[:, min(11, f - 1)]
        + rng.normal(scale=0.3, size=n)
    )
    return X, y


def _trees(model_str):
    return model_str.split("\nparameters:")[0]


def test_feature_parallel_matches_serial():
    X, y = _data()
    out = {}
    for tl in ("serial", "feature"):
        params = {
            "objective": "regression",
            "num_leaves": 31,
            "verbosity": -1,
            "metric": "none",
            "tree_learner": tl,
            "max_bin": 63,
        }
        b = lgb.train(params, lgb.Dataset(X, y, params=params), 5)
        if tl == "feature":
            assert b._featpar > 1, "feature-parallel mesh did not engage"
        out[tl] = _trees(b.model_to_string())
    assert out["serial"] == out["feature"]


def test_feature_parallel_non_divisible_feature_count():
    # 13 features: the mesh shrinks to a divisor (13 devices unavailable ->
    # 1) and training falls back to serial without error
    X, y = _data(f=13, seed=1)
    params = {
        "objective": "regression",
        "num_leaves": 15,
        "verbosity": -1,
        "metric": "none",
        "tree_learner": "feature",
        "max_bin": 63,
    }
    b = lgb.train(params, lgb.Dataset(X, y, params=params), 10)
    p = b.predict(X)
    assert float(np.mean((p - y) ** 2)) < 0.6 * float(np.var(y))


def test_feature_parallel_multiclass_and_nan():
    X, y = _data(f=8, seed=2)
    X[::7, 3] = np.nan
    yc = np.digitize(y, np.quantile(y[np.isfinite(y)], [0.33, 0.66]))
    out = {}
    for tl in ("serial", "feature"):
        params = {
            "objective": "multiclass",
            "num_class": 3,
            "num_leaves": 15,
            "verbosity": -1,
            "metric": "none",
            "tree_learner": tl,
            "max_bin": 63,
        }
        b = lgb.train(params, lgb.Dataset(X, yc, params=params), 3)
        out[tl] = _trees(b.model_to_string())
    assert out["serial"] == out["feature"]


def test_feature_parallel_non_divisible_rows():
    """Rows are replicated (never padded): n not divisible by the shard
    count must work (ADVICE r3 — padding was computed but bins unpadded)."""
    X, y = _data(n=2999, f=16, seed=4)
    params = {
        "objective": "regression",
        "num_leaves": 15,
        "verbosity": -1,
        "metric": "none",
        "tree_learner": "feature",
        "max_bin": 63,
    }
    b = lgb.train(params, lgb.Dataset(X, y, params=params), 3)
    assert b._featpar > 1
    assert np.isfinite(b.predict(X)).all()


def test_feature_parallel_seg_matches_serial():
    """Feature-parallel on the seg fast path (VERDICT r3 missing #7): each
    shard packs only its feature slice; the winner's go-left bits arrive
    from the owning shard by psum.  Results must equal serial seg EXACTLY."""
    X, y = _data()
    X[::9, 2] = np.nan  # NaN routing must survive the bits broadcast
    out = {}
    for tl in ("serial", "feature"):
        params = {
            "objective": "regression",
            "num_leaves": 31,
            "verbosity": -1,
            "metric": "none",
            "tree_learner": tl,
            "max_bin": 63,
            "hist_mode": "seg",
        }
        b = lgb.train(params, lgb.Dataset(X, y, params=params), 5)
        if tl == "feature":
            assert b._featpar > 1, "feature-parallel mesh did not engage"
            assert b._grower_params.hist_mode == "seg"
        out[tl] = _trees(b.model_to_string())
    assert out["serial"] == out["feature"]


def test_feature_parallel_seg_categorical_matches_serial():
    rng = np.random.default_rng(5)
    n = 2500
    X = np.column_stack(
        [
            rng.normal(size=(n, 7)),
            rng.integers(0, 6, size=n).astype(float),
        ]
    )
    y = X[:, 0] + (X[:, 7] == 3) * 2.0 + rng.normal(scale=0.2, size=n)
    out = {}
    for tl in ("serial", "feature"):
        params = {
            "objective": "regression",
            "num_leaves": 15,
            "verbosity": -1,
            "metric": "none",
            "tree_learner": tl,
            "max_bin": 63,
            "hist_mode": "seg",
            "categorical_feature": [7],
        }
        b = lgb.train(params, lgb.Dataset(X, y, params=params), 5)
        out[tl] = _trees(b.model_to_string())
    assert out["serial"] == out["feature"]
