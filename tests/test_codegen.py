"""convert_model C++ codegen: the generated standalone predictor must match
booster.predict on the same rows (reference: SaveModelToIfElse,
src/boosting/gbdt_model_text.cpp:289)."""

import shutil
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.codegen import model_to_cpp

GXX = shutil.which("g++")


def _compile_and_predict(booster, X, tmp_path):
    src = tmp_path / "model.cpp"
    src.write_text(model_to_cpp(booster))
    exe = tmp_path / "model_bin"
    subprocess.run(
        [GXX, "-O1", "-DLGBM_CODEGEN_MAIN", "-o", str(exe), str(src)],
        check=True,
        capture_output=True,
        text=True,
    )
    rows = "\n".join(" ".join(repr(float(v)) for v in r) for r in X)
    r = subprocess.run(
        [str(exe)], input=rows, capture_output=True, text=True, check=True
    )
    return np.array(
        [[float(v) for v in line.split()] for line in r.stdout.splitlines()]
    )


needs_gxx = pytest.mark.skipif(GXX is None, reason="g++ not available")


@needs_gxx
def test_cpp_codegen_regression_with_nans(tmp_path):
    rng = np.random.default_rng(0)
    n = 1500
    X = rng.normal(size=(n, 6))
    X[::7, 2] = np.nan
    y = X[:, 0] + np.where(np.isnan(X[:, 2]), 1.5, X[:, 2]) + rng.normal(
        scale=0.1, size=n
    )
    b = lgb.train(
        {"objective": "regression", "num_leaves": 31, "verbosity": -1},
        lgb.Dataset(X, y),
        15,
    )
    got = _compile_and_predict(b, X[:200], tmp_path)[:, 0]
    exp = b.predict(X[:200])
    # the booster's device walker accumulates leaf values in f32; the
    # generated C++ sums in f64 — agreement is to f32 rounding
    np.testing.assert_allclose(got, exp, rtol=1e-5, atol=1e-7)


@needs_gxx
def test_cpp_codegen_binary_sigmoid(tmp_path):
    rng = np.random.default_rng(1)
    n = 1200
    X = rng.normal(size=(n, 4))
    y = (X[:, 0] + X[:, 1] > 0).astype(float)
    b = lgb.train(
        {"objective": "binary", "num_leaves": 15, "verbosity": -1},
        lgb.Dataset(X, y),
        10,
    )
    got = _compile_and_predict(b, X[:150], tmp_path)[:, 0]
    exp = b.predict(X[:150])
    np.testing.assert_allclose(got, exp, rtol=1e-5, atol=1e-7)


@needs_gxx
def test_cpp_codegen_multiclass_softmax(tmp_path):
    rng = np.random.default_rng(2)
    n = 1500
    X = rng.normal(size=(n, 5))
    y = np.digitize(X[:, 1], [-0.4, 0.4]).astype(float)
    b = lgb.train(
        {
            "objective": "multiclass",
            "num_class": 3,
            "num_leaves": 15,
            "verbosity": -1,
        },
        lgb.Dataset(X, y),
        6,
    )
    got = _compile_and_predict(b, X[:150], tmp_path)
    exp = b.predict(X[:150])
    assert got.shape == exp.shape
    np.testing.assert_allclose(got, exp, rtol=1e-6, atol=1e-9)


@needs_gxx
def test_cpp_codegen_categorical(tmp_path):
    rng = np.random.default_rng(3)
    n = 2000
    X = np.column_stack(
        [rng.normal(size=n), rng.integers(0, 8, n).astype(float)]
    )
    y = X[:, 0] + (np.isin(X[:, 1], [2, 5])) * 2.0 + rng.normal(
        scale=0.1, size=n
    )
    b = lgb.train(
        {
            "objective": "regression",
            "num_leaves": 15,
            "verbosity": -1,
            "categorical_feature": [1],
        },
        lgb.Dataset(X, y),
        10,
    )
    Xq = X[:200].copy()
    Xq[0, 1] = 11.0  # unseen category -> routes right, like predict
    got = _compile_and_predict(b, Xq, tmp_path)[:, 0]
    exp = b.predict(Xq)
    np.testing.assert_allclose(got, exp, rtol=1e-5, atol=1e-7)


@needs_gxx
def test_cli_convert_model_cpp(tmp_path):
    rng = np.random.default_rng(4)
    X = rng.normal(size=(500, 3))
    y = X[:, 0] + rng.normal(scale=0.1, size=500)
    b = lgb.train(
        {"objective": "regression", "verbosity": -1}, lgb.Dataset(X, y), 5
    )
    model = tmp_path / "m.txt"
    b.save_model(str(model))
    out = tmp_path / "m.cpp"
    from lightgbm_tpu.cli import main

    main(
        [
            "task=convert_model",
            f"input_model={model}",
            "convert_model_language=cpp",
            f"convert_model={out}",
        ]
    )
    text = out.read_text()
    assert "PredictTree0" in text and "void Predict(" in text


@needs_gxx
def test_cpp_codegen_xentlambda_softplus(tmp_path):
    rng = np.random.default_rng(5)
    n = 1000
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] > 0).astype(float)
    b = lgb.train(
        {"objective": "cross_entropy_lambda", "num_leaves": 15,
         "verbosity": -1},
        lgb.Dataset(X, y),
        8,
    )
    got = _compile_and_predict(b, X[:100], tmp_path)[:, 0]
    exp = b.predict(X[:100])
    np.testing.assert_allclose(got, exp, rtol=1e-5, atol=1e-7)
