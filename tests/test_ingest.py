"""Streaming ingest (lightgbm_tpu/ingest): byte-parity battery.

The acceptance oracle is BYTE parity: a chunk-streamed Dataset must produce
bit-identical packed bin planes, bundle layout and trained model dump
versus the one-shot path on the same data and seed — across source kinds
(text/CSV, ndarray, memory-mapped ``.npy``, chunk iterables with a ragged
last chunk, Sequences, Arrow, pandas), under ``np.memmap``-backed planes,
and through training with bagging/GOSS.  A subprocess peak-RSS drill
proves the raw float64 matrix never materializes, and a two-process
launcher drill proves sharded per-host ingest fits globally consistent
mappers.
"""

import os
import subprocess
import sys
import textwrap
from pathlib import Path

import numpy as np
import pytest

import lightgbm_tpu as lgb

REPO_ROOT = str(Path(__file__).resolve().parents[1])

PARAMS = {
    "objective": "binary",
    "num_leaves": 15,
    "verbose": -1,
    "bin_construct_sample_cnt": 800,
    "data_random_seed": 1,
    "min_data_in_leaf": 5,
}


def _mkdata(n=4000, f=10, seed=7):
    """Dense + sparse + integer columns, so EFB bundling and quantile
    binning both have something to do."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    X[:, 2] = (rng.random(n) < 0.04) * rng.normal(size=n)
    X[:, 3] = (rng.random(n) < 0.04) * rng.normal(size=n)
    ji = min(5, f - 1)
    X[:, ji] = rng.integers(0, 6, n)
    y = (
        X[:, 0] + 0.3 * X[:, ji] + rng.normal(scale=0.1, size=n) > 0.2
    ).astype(np.float64)
    return X, y


def _strip_ingest(dump: str) -> str:
    # the knob itself appears in the model's params section; everything
    # else (trees, mappers, feature infos) must match bit-for-bit
    return "\n".join(
        ln for ln in dump.splitlines() if not ln.startswith("[ingest_")
    )


def _assert_ds_parity(ds_ref, ds_stream):
    assert ds_ref.bins.dtype == ds_stream.bins.dtype
    assert np.array_equal(np.asarray(ds_ref.bins), np.asarray(ds_stream.bins))
    l1, l2 = ds_ref.bundle_layout, ds_stream.bundle_layout
    assert (l1 is None) == (l2 is None)
    if l1 is not None:
        assert [list(p) for p in l1.planes] == [list(p) for p in l2.planes]
        assert list(l1.plane_bins) == list(l2.plane_bins)
    assert ds_ref.used_features == ds_stream.used_features
    for m1, m2 in zip(ds_ref.bin_mappers, ds_stream.bin_mappers):
        assert m1.num_bins == m2.num_bins
        assert np.array_equal(
            np.asarray(m1.bin_upper_bound), np.asarray(m2.bin_upper_bound)
        )


@pytest.mark.parametrize("chunk_rows", [777, 4000, 64])
def test_ndarray_chunked_parity(chunk_rows):
    """Streamed ndarray binning == one-shot, including a ragged last chunk
    (777 ∤ 4000), a single whole-data chunk, and many tiny chunks."""
    X, y = _mkdata()
    ds1 = lgb.Dataset(X.copy(), y, params=PARAMS).construct()
    p2 = dict(PARAMS, ingest_chunk_rows=chunk_rows)
    ds2 = lgb.Dataset(X.copy(), y, params=p2).construct()
    _assert_ds_parity(ds1, ds2)


def test_model_dump_parity_and_sample_determinism():
    X, y = _mkdata()
    p2 = dict(PARAMS, ingest_chunk_rows=600)
    b1 = lgb.train(PARAMS, lgb.Dataset(X.copy(), y, params=PARAMS), 8)
    b2 = lgb.train(p2, lgb.Dataset(X.copy(), y, params=p2), 8)
    b3 = lgb.train(p2, lgb.Dataset(X.copy(), y, params=p2), 8)
    d1 = _strip_ingest(b1.model_to_string())
    d2 = _strip_ingest(b2.model_to_string())
    assert d1 == d2
    # the seeded pass-1 sample draw is deterministic: rebuilding from the
    # same chunks gives the identical model, not just close bins
    assert b2.model_to_string() == b3.model_to_string()


def test_text_csv_parity_with_weight_group_columns(tmp_path):
    """Chunked text ingest threads label + weight_column + group_column
    and the ``.init`` sidecar identically to the one-shot loader."""
    rng = np.random.default_rng(3)
    n = 2500
    X, y = _mkdata(n=n)
    w = rng.random(n) + 0.5
    qid = np.repeat(np.arange(n // 25), 25).astype(np.float64)
    raw = np.column_stack([y, X, w, qid])
    csv = tmp_path / "train.csv"
    np.savetxt(csv, raw, delimiter=",")
    (tmp_path / "train.csv.init").write_text(
        "\n".join(str(v) for v in rng.normal(size=n))
    )
    ncol = raw.shape[1]
    params = dict(
        PARAMS,
        weight_column=ncol - 2 - 1,  # data-column index (label not counted)
        group_column=ncol - 1 - 1,
    )
    ds1 = lgb.Dataset(str(csv), params=params).construct()
    ds2 = lgb.Dataset(
        str(csv), params=dict(params, ingest_chunk_rows=611)
    ).construct()
    _assert_ds_parity(ds1, ds2)
    assert np.array_equal(ds1.metadata.label, ds2.metadata.label)
    assert np.array_equal(ds1.metadata.weight, ds2.metadata.weight)
    assert np.array_equal(ds1.metadata.init_score, ds2.metadata.init_score)
    assert np.array_equal(
        ds1.metadata.query_boundaries, ds2.metadata.query_boundaries
    )


def test_text_blank_and_comment_lines(tmp_path):
    """np.loadtxt drops blank and '#' lines; the chunked line reader must
    count and parse the same surviving rows."""
    X, y = _mkdata(n=300, f=4)
    csv = tmp_path / "gaps.csv"
    rows = [
        ",".join(f"{v:.10g}" for v in np.concatenate([[y[i]], X[i]]))
        for i in range(300)
    ]
    rows.insert(100, "")
    rows.insert(200, "# a comment line")
    rows.append("")
    csv.write_text("\n".join(rows) + "\n")
    ds1 = lgb.Dataset(str(csv), params=PARAMS).construct()
    ds2 = lgb.Dataset(
        str(csv), params=dict(PARAMS, ingest_chunk_rows=97)
    ).construct()
    assert ds1.num_data == 300
    _assert_ds_parity(ds1, ds2)
    assert np.array_equal(ds1.metadata.label, ds2.metadata.label)


def test_chunk_iterable_list_and_callable():
    """Dataset(data=[chunk0, chunk1, ...]) and Dataset(data=callable)
    stream without the knob — the explicit out-of-core API."""
    X, y = _mkdata()
    ds1 = lgb.Dataset(X.copy(), y, params=PARAMS).construct()
    chunks = [X[:1100], X[1100:1100], X[1100:3999], X[3999:]]  # empty + ragged
    ds2 = lgb.Dataset([c.copy() for c in chunks], y, params=PARAMS).construct()
    _assert_ds_parity(ds1, ds2)

    def gen():
        for c in chunks:
            yield c.copy()

    ds3 = lgb.Dataset(gen, y, params=PARAMS).construct()
    _assert_ds_parity(ds1, ds3)


def test_chunk_callable_must_be_reiterable():
    X, y = _mkdata(n=500)
    g = iter([X[:300], X[300:]])
    with pytest.raises(ValueError, match="re-iterable|fresh iterator"):
        lgb.Dataset(lambda: g, y, params=PARAMS).construct()


def test_chunk_width_mismatch_rejected():
    X, y = _mkdata(n=500)
    with pytest.raises(ValueError, match="column counts disagree"):
        lgb.Dataset([X[:300], X[300:, :5]], y, params=PARAMS).construct()


def test_negative_chunk_rows_rejected():
    X, y = _mkdata(n=100)
    with pytest.raises(ValueError, match="ingest_chunk_rows"):
        lgb.Dataset(
            X, y, params=dict(PARAMS, ingest_chunk_rows=-1)
        ).construct()


def test_npy_mmap_source_parity(tmp_path):
    """.npy files stream through np.load(mmap_mode='r') — chunk slices read
    from disk; parity vs one-shot binning of the loaded array."""
    X, y = _mkdata()
    npy = tmp_path / "x.npy"
    np.save(npy, X)
    ds1 = lgb.Dataset(X.copy(), y, params=PARAMS).construct()
    ds2 = lgb.Dataset(
        str(npy), y, params=dict(PARAMS, ingest_chunk_rows=500)
    ).construct()
    _assert_ds_parity(ds1, ds2)


def test_sequence_source_parity():
    class Seq(lgb.Sequence):
        batch_size = 256

        def __init__(self, arr):
            self.arr = arr

        def __getitem__(self, idx):
            return self.arr[idx]

        def __len__(self):
            return len(self.arr)

    X, y = _mkdata()
    ds1 = lgb.Dataset(X.copy(), y, params=PARAMS).construct()
    ds2 = lgb.Dataset(
        Seq(X), y, params=dict(PARAMS, ingest_chunk_rows=1)
    ).construct()
    _assert_ds_parity(ds1, ds2)
    ds3 = lgb.Dataset(
        [Seq(X[:1500]), Seq(X[1500:])],
        y,
        params=dict(PARAMS, ingest_chunk_rows=1),
    ).construct()
    _assert_ds_parity(ds1, ds3)


def test_pandas_source_parity():
    pd = pytest.importorskip("pandas")
    X, y = _mkdata()
    df = pd.DataFrame(X, columns=[f"f{i}" for i in range(X.shape[1])])
    df["cat"] = pd.Categorical(
        np.random.default_rng(5).choice(["a", "b", "c"], len(df))
    )
    ds1 = lgb.Dataset(df.copy(), y, params=PARAMS).construct()
    ds2 = lgb.Dataset(
        df.copy(), y, params=dict(PARAMS, ingest_chunk_rows=700)
    ).construct()
    _assert_ds_parity(ds1, ds2)
    assert ds1.pandas_categorical == ds2.pandas_categorical
    assert ds1.feature_names == ds2.feature_names


def test_arrow_source_parity():
    pa = pytest.importorskip("pyarrow")
    X, y = _mkdata()
    cols = {f"f{i}": X[:, i] for i in range(X.shape[1])}
    cols["dict"] = pa.array(
        np.random.default_rng(6).choice(["u", "v", "w"], len(X))
    ).dictionary_encode()
    tbl = pa.table(cols)
    ds1 = lgb.Dataset(tbl, y, params=PARAMS).construct()
    ds2 = lgb.Dataset(
        tbl, y, params=dict(PARAMS, ingest_chunk_rows=700)
    ).construct()
    _assert_ds_parity(ds1, ds2)
    assert ds1.arrow_categories == ds2.arrow_categories


def test_memmap_backed_bins_parity(tmp_path):
    """ingest_mmap_dir puts the packed planes on disk (unlinked-after-map:
    nothing is left behind) with byte-identical contents."""
    X, y = _mkdata()
    ds1 = lgb.Dataset(X.copy(), y, params=PARAMS).construct()
    mdir = tmp_path / "spill"
    p2 = dict(PARAMS, ingest_chunk_rows=640, ingest_mmap_dir=str(mdir))
    ds2 = lgb.Dataset(X.copy(), y, params=p2).construct()
    assert isinstance(ds2.bins, np.memmap)
    _assert_ds_parity(ds1, ds2)
    assert list(mdir.iterdir()) == []  # spill file already unlinked
    # training from memmap-backed planes matches, end to end
    b1 = lgb.train(PARAMS, lgb.Dataset(X.copy(), y, params=PARAMS), 5)
    b2 = lgb.train(p2, lgb.Dataset(X.copy(), y, params=p2), 5)
    assert _strip_ingest(b1.model_to_string()) == _strip_ingest(
        b2.model_to_string()
    )


@pytest.mark.parametrize(
    "extra",
    [
        {"bagging_fraction": 0.7, "bagging_freq": 1, "bagging_seed": 9},
        {"boosting": "goss", "top_rate": 0.3, "other_rate": 0.2,
         "learning_rate": 0.3},
    ],
    ids=["bagging", "goss"],
)
def test_bagging_goss_streamed_parity(extra):
    """Row sampling consumes the binned planes and seeded device RNG only,
    so a chunk-streamed Dataset trains to the identical model under
    bagging and GOSS — no full raw row set ever exists host-side."""
    X, y = _mkdata()
    p1 = dict(PARAMS, **extra)
    p2 = dict(p1, ingest_chunk_rows=700)
    b1 = lgb.train(p1, lgb.Dataset(X.copy(), y, params=p1), 8)
    b2 = lgb.train(p2, lgb.Dataset(X.copy(), y, params=p2), 8)
    assert _strip_ingest(b1.model_to_string()) == _strip_ingest(
        b2.model_to_string()
    )


def test_mesh_spec_streamed_parity():
    """Under a tree_learner=data mesh spec (8 virtual devices, one
    process) the chunk-streamed Dataset trains to the identical model:
    the mesh consumes the packed planes after construction, and those
    are byte-identical to one-shot."""
    X, y = _mkdata()
    p1 = dict(PARAMS, tree_learner="data")
    p2 = dict(p1, ingest_chunk_rows=700)
    b1 = lgb.train(p1, lgb.Dataset(X.copy(), y, params=p1), 6)
    b2 = lgb.train(p2, lgb.Dataset(X.copy(), y, params=p2), 6)
    assert _strip_ingest(b1.model_to_string()) == _strip_ingest(
        b2.model_to_string()
    )


def test_valid_set_streams_against_reference():
    X, y = _mkdata()
    Xv, yv = _mkdata(n=1200, seed=11)
    p2 = dict(PARAMS, ingest_chunk_rows=500)
    train1 = lgb.Dataset(X.copy(), y, params=PARAMS).construct()
    valid1 = lgb.Dataset(Xv.copy(), yv, params=PARAMS, reference=train1)
    valid1.construct()
    train2 = lgb.Dataset(X.copy(), y, params=p2).construct()
    valid2 = lgb.Dataset(Xv.copy(), yv, params=p2, reference=train2)
    valid2.construct()
    assert np.array_equal(
        np.asarray(valid1.bins), np.asarray(valid2.bins)
    )


def test_linear_tree_falls_back_to_one_shot():
    """linear_tree needs the raw matrix; the knob falls back (with a
    warning) instead of breaking the mode."""
    X, y = _mkdata(n=800, f=5)
    p1 = dict(PARAMS, linear_tree=True)
    p2 = dict(p1, ingest_chunk_rows=300)
    ds2 = lgb.Dataset(X.copy(), y, params=p2).construct()
    assert ds2.raw is not None  # one-shot path kept the raw matrix
    b1 = lgb.train(p1, lgb.Dataset(X.copy(), y, params=p1), 5)
    b2 = lgb.train(p2, lgb.Dataset(X.copy(), y, params=p2), 5)
    assert _strip_ingest(b1.model_to_string()) == _strip_ingest(
        b2.model_to_string()
    )


def test_libsvm_falls_back_to_sparse_path(tmp_path):
    """LibSVM text probes as unstreamable and bins through the sparse
    path, knob or not."""
    rng = np.random.default_rng(4)
    lines = []
    for i in range(400):
        feats = sorted(rng.choice(8, size=3, replace=False))
        kv = " ".join(f"{j}:{rng.normal():.6f}" for j in feats)
        lines.append(f"{int(rng.random() < 0.5)} {kv}")
    path = tmp_path / "train.svm"
    path.write_text("\n".join(lines) + "\n")
    ds1 = lgb.Dataset(str(path), params=PARAMS).construct()
    ds2 = lgb.Dataset(
        str(path), params=dict(PARAMS, ingest_chunk_rows=100)
    ).construct()
    assert np.array_equal(np.asarray(ds1.bins), np.asarray(ds2.bins))


def test_ingest_telemetry_gauges():
    """Phase timers + ingest gauges land in the registry and export as
    lgbtpu_* prometheus lines."""
    from lightgbm_tpu.obs.export import prometheus_snapshot
    from lightgbm_tpu.obs.registry import get_session
    from lightgbm_tpu.utils.timer import global_timer

    sess = get_session()
    prev = sess.enabled
    sess.configure(enabled=True)
    try:
        sess.reset()
        X, y = _mkdata(n=1500)
        lgb.Dataset(
            X, y, params=dict(PARAMS, ingest_chunk_rows=400)
        ).construct()
        for g in (
            "ingest/chunks_total",
            "ingest/rows_per_sec",
            "ingest/peak_rss_bytes",
        ):
            assert g in sess.gauges, sorted(sess.gauges)
        assert sess.gauges["ingest/chunks_total"] == 4.0
        assert sess.gauges["ingest/peak_rss_bytes"] > 0
        text = prometheus_snapshot()
        assert "lgbtpu_ingest_chunks_total" in text
        assert "lgbtpu_ingest_peak_rss_bytes" in text
    finally:
        sess.reset()
        sess.configure(enabled=prev)
    for phase in (
        "dataset/ingest/sample",
        "dataset/ingest/bin_fit",
        "dataset/ingest/bundle",
        "dataset/ingest/pack",
    ):
        assert global_timer.counts.get(phase, 0) >= 1, phase


RSS_SCRIPT = textwrap.dedent(
    """
    import os, resource, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    import numpy as np
    import lightgbm_tpu as lgb

    N, F = 600_000, 50
    npy = sys.argv[1]
    mode = sys.argv[2]

    # settle the interpreter + jax + one tiny construct, THEN measure the
    # additional high-water the big build adds (ru_maxrss is monotone)
    Xs, ys = np.random.default_rng(0).normal(size=(500, F)), np.zeros(500)
    ys[:250] = 1.0
    lgb.Dataset(Xs, ys, params={{"verbose": -1}}).construct()
    base = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024

    y = np.zeros(N); y[: N // 2] = 1.0
    params = {{"verbose": -1, "bin_construct_sample_cnt": 50_000,
              "data_random_seed": 1}}
    if mode == "stream":
        params["ingest_chunk_rows"] = 65_536
    ds = lgb.Dataset(npy if mode == "stream" else np.load(npy), y,
                     params=params).construct()
    assert ds.bins.shape == (N, F), ds.bins.shape
    peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    print("DELTA", peak - base)
    """
)


@pytest.mark.slow
def test_streamed_peak_rss_never_materializes_raw(tmp_path):
    """The raw 600k x 50 float64 matrix is 240 MB; the streamed build's
    additional peak RSS must stay far under it (bins + sample + a bounded
    chunk window), while the one-shot build pays the full matrix."""
    npy = tmp_path / "big.npy"
    out = np.lib.format.open_memmap(
        npy, mode="w+", dtype=np.float64, shape=(600_000, 50)
    )
    rng = np.random.default_rng(12)
    for s in range(0, 600_000, 100_000):
        out[s : s + 100_000] = rng.normal(size=(100_000, 50))
    out.flush()
    del out

    def run(mode):
        script = tmp_path / f"rss_{mode}.py"
        script.write_text(RSS_SCRIPT.format(repo=REPO_ROOT))
        r = subprocess.run(
            [sys.executable, str(script), str(npy), mode],
            capture_output=True, text=True, timeout=600,
        )
        assert r.returncode == 0, r.stderr[-2000:]
        for ln in r.stdout.splitlines():
            if ln.startswith("DELTA"):
                return int(ln.split()[1])
        raise AssertionError(r.stdout)

    raw_bytes = 600_000 * 50 * 8
    stream_delta = run("stream")
    oneshot_delta = run("oneshot")
    assert stream_delta < raw_bytes // 2, (stream_delta, raw_bytes)
    # allocator page reuse can shave the one-shot delta slightly under the
    # nominal matrix size; 3/4 still clearly shows the full materialization
    assert oneshot_delta > raw_bytes * 3 // 4, (oneshot_delta, raw_bytes)
    assert stream_delta * 2 < oneshot_delta, (stream_delta, oneshot_delta)


def test_sharded_global_sample_simulated(monkeypatch):
    """exchange_global_sample with a faked 2-process collective (threads +
    barrier): every rank must end with the IDENTICAL global sample, equal
    to the one-shot seeded draw over the concatenated matrix.  This runs
    in the default tier; the real two-process launcher drill below needs
    cross-process CPU collectives."""
    import threading

    import jax

    from lightgbm_tpu import parallel as par
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.ingest.sharded import exchange_global_sample
    from lightgbm_tpu.ingest.sources import ArrayChunkSource

    X, _ = _mkdata(n=6000, f=8)
    shards = [X[:3500], X[3500:]]
    cfg = Config.from_params(
        {"bin_construct_sample_cnt": 1500, "data_random_seed": 21}
    )

    tl = threading.local()
    barrier = threading.Barrier(2)
    store = [None, None]
    lock = threading.Lock()

    def fake_varlen(arr, return_counts=False):
        store[tl.rank] = np.asarray(arr)
        barrier.wait()
        with lock:
            out = np.concatenate([store[0], store[1]], axis=0)
            counts = np.asarray([len(store[0]), len(store[1])], np.int32)
        barrier.wait()  # both ranks read before the next round overwrites
        return (out, counts) if return_counts else out

    monkeypatch.setattr(par, "allgather_host_varlen", fake_varlen)
    monkeypatch.setattr(jax, "process_index", lambda: tl.rank)

    results = [None, None]
    errors = []

    def worker(rank):
        tl.rank = rank
        try:
            src = ArrayChunkSource(shards[rank], 512)
            results[rank] = exchange_global_sample(src, cfg)
        except Exception as exc:  # pragma: no cover - surfaced below
            errors.append(exc)
            try:
                barrier.abort()
            except Exception:
                pass

    ts = [threading.Thread(target=worker, args=(r,)) for r in (0, 1)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert not errors, errors
    (gn0, off0, s0), (gn1, off1, s1) = results
    assert (gn0, gn1) == (6000, 6000)
    assert (off0, off1) == (0, 3500)
    assert np.array_equal(s0, s1)
    rows = np.sort(
        np.random.default_rng(21).choice(6000, size=1500, replace=False)
    )
    assert np.array_equal(s0, X[rows])


SHARDED_TMPL = textwrap.dedent(
    """
    import os, sys
    sys.path.insert(0, "__REPO__")
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    import hashlib
    import numpy as np
    from lightgbm_tpu.parallel import init_distributed

    init_distributed()
    rank = jax.process_index()
    rng = np.random.default_rng(99)
    X = rng.integers(0, 63, size=(8000, 6)).astype(np.float64)
    y = X[:, 0] * 0.2 + np.sin(X[:, 1]) + rng.normal(scale=0.3, size=8000)
    lo, hi = rank * 4000, (rank + 1) * 4000
    import lightgbm_tpu as lgb

    params = dict(
        objective="regression", num_leaves=31, min_data_in_leaf=20,
        tree_learner="data", pre_partition=True, verbosity=-1, metric="none",
        max_bin=63, ingest_chunk_rows=1024,
        bin_construct_sample_cnt=3000, data_random_seed=5,
    )
    d = lgb.Dataset(X[lo:hi], y[lo:hi], params=params)
    d.construct()
    # globally consistent mappers, fit from the allgathered GLOBAL sample
    h = hashlib.sha256()
    for m in d.bin_mappers:
        h.update(np.asarray(m.bin_upper_bound).tobytes())
        h.update(bytes([m.num_bins & 0xFF]))
    print(f"MAPPERHASH {h.hexdigest()}")
    b = lgb.train(params, d, 5)
    ms = b.model_to_string()
    print(f"MODELHASH {hashlib.sha256(ms.encode()).hexdigest()}")
    """
)


@pytest.mark.slow
def test_two_process_sharded_streamed_ingest(tmp_path):
    """Sharded per-host streamed ingest: each process streams only its row
    shard; the global-sample exchange must yield identical bin mappers
    (and identical trained models) on every process, equal to the mappers
    a single-process run fits from the SAME global sample."""
    script = tmp_path / "sharded_ingest_worker.py"
    script.write_text(SHARDED_TMPL.replace("__REPO__", REPO_ROOT))
    from lightgbm_tpu.parallel.launcher import launch_collect

    rc, outputs = launch_collect(
        2,
        [sys.executable, str(script)],
        extra_env={"XLA_FLAGS": "--xla_force_host_platform_device_count=4"},
    )
    assert rc == 0, outputs
    mapper_digests, model_digests = [], []
    for out in outputs:
        for line in out.splitlines():
            if line.startswith("MAPPERHASH"):
                mapper_digests.append(line.split()[1][:64])
            if line.startswith("MODELHASH"):
                model_digests.append(line.split()[1][:64])
    assert len(mapper_digests) == 2, outputs
    assert len(set(mapper_digests)) == 1, mapper_digests
    assert len(set(model_digests)) == 1, model_digests

    # single-process streamed run over the same GLOBAL data: the sharded
    # exchange must reproduce its seeded sample, hence its mappers
    import hashlib

    rng = np.random.default_rng(99)
    X = rng.integers(0, 63, size=(8000, 6)).astype(np.float64)
    y = X[:, 0] * 0.2 + np.sin(X[:, 1]) + rng.normal(scale=0.3, size=8000)
    params = dict(
        objective="regression", num_leaves=31, min_data_in_leaf=20,
        verbosity=-1, metric="none", max_bin=63, ingest_chunk_rows=1024,
        bin_construct_sample_cnt=3000, data_random_seed=5,
    )
    d = lgb.Dataset(X, y, params=params).construct()
    h = hashlib.sha256()
    for m in d.bin_mappers:
        h.update(np.asarray(m.bin_upper_bound).tobytes())
        h.update(bytes([m.num_bins & 0xFF]))
    assert h.hexdigest()[:64] == mapper_digests[0], (
        "sharded mappers diverge from the single-process global sample"
    )
