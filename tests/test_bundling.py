"""Exclusive Feature Bundling (EFB): greedy bundling, plane packing, the
50k-column one-hot path, and original-feature-space model output.

Reference analogs: DatasetLoader FindGroups / FastFeatureBundling
(src/io/dataset.cpp) and the EFB algorithm of Ke et al. (NeurIPS 2017).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
sp = pytest.importorskip("scipy.sparse")

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.bundling import (  # noqa: E402
    BundleLayout,
    build_layout,
    greedy_find_bundles,
)


def _onehot_problem(n=3000, nvar=10, ncat=25, seed=0, noise=0.1):
    """Block one-hot design: nvar categorical variables, one-hot encoded
    into nvar*ncat mutually-exclusive-within-block columns."""
    rng = np.random.default_rng(seed)
    codes = rng.integers(0, ncat, size=(n, nvar))
    rows = np.repeat(np.arange(n), nvar)
    cols = (np.arange(nvar) * ncat + codes).ravel()
    X = sp.csr_matrix(
        (np.ones(n * nvar), (rows, cols)), shape=(n, nvar * ncat)
    )
    w = rng.normal(size=nvar * ncat)
    y = np.asarray(X @ w).ravel() + noise * rng.normal(size=n)
    return X, y


# --------------------------------------------------------------- algorithm
def test_greedy_bundles_exclusive_features_share_a_group():
    # three features, pairwise disjoint nonzeros -> one bundle
    nz = [np.array([0, 1]), np.array([2, 3]), np.array([4, 5])]
    groups = greedy_find_bundles(nz, np.array([1, 1, 1]), 10, 0.0)
    assert groups == [[0, 1, 2]]


def test_greedy_bundles_conflicting_features_split():
    nz = [np.array([0, 1, 2]), np.array([2, 3, 4])]  # overlap at row 2
    groups = greedy_find_bundles(nz, np.array([1, 1]), 10, 0.0)
    assert sorted(map(sorted, groups)) == [[0], [1]]
    # a conflict budget of one row lets them merge
    groups2 = greedy_find_bundles(nz, np.array([1, 1]), 10, 0.1)
    assert groups2 == [[0, 1]]


def test_greedy_bundles_respect_bin_budget():
    # both features exclusive but each needs 200 bins: 1 + 200 + 200 > 256
    nz = [np.array([0]), np.array([1])]
    groups = greedy_find_bundles(nz, np.array([200, 200]), 10, 0.0)
    assert len(groups) == 2


def test_layout_decode_round_trip():
    layout = BundleLayout(
        planes=[[3, 7, 9], [5]],
        starts=[[1, 2, 4], [0]],
        widths=[[1, 2, 3], [10]],
        plane_bins=[7, 10],
    )
    assert layout.has_bundles
    assert layout.decode(0, 1) == (3, 0)
    assert layout.decode(0, 2) == (7, 0)
    assert layout.decode(0, 3) == (7, 1)
    assert layout.decode(0, 6) == (9, 2)
    assert layout.decode(1, 4) == (5, 4)  # singleton plane = identity
    assert layout.feature_position(9) == (0, 2)
    be = layout.bundle_end_array(8)
    np.testing.assert_array_equal(be[0], [-1, 1, 3, 3, 6, 6, 6, -1])
    np.testing.assert_array_equal(be[1], [-1] * 8)


def test_build_layout_identity_for_dense():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(500, 6))
    y = rng.normal(size=500)
    ds = lgb.Dataset(X, y)
    ds.construct()
    assert ds.bundle_layout is None  # dense columns never bundle
    assert ds.num_planes == len(ds.used_features)


# ----------------------------------------------------------- dataset layer
def test_bundled_plane_columns_decode_back_to_feature_bins():
    X, y = _onehot_problem(n=2000, nvar=8, ncat=20)
    ds = lgb.Dataset(X, y)
    ds.construct()
    L = ds.bundle_layout
    assert L is not None and L.has_bundles
    Xc = X.tocsc()
    n = X.shape[0]
    for j in ds.used_features:
        p, k = L.feature_position(j)
        m = ds.bin_mappers[j]
        col = np.zeros(n)
        sl = slice(Xc.indptr[j], Xc.indptr[j + 1])
        col[Xc.indices[sl]] = Xc.data[sl]
        want = m.values_to_bins(col)
        if L.is_bundle(p):
            s, w = L.starts[p][k], L.widths[p][k]
            pb = ds.bins[:, p].astype(int)
            got = np.where((pb >= s) & (pb < s + w), pb - s + 1, 0)
        else:
            got = ds.bins[:, p].astype(int)
        np.testing.assert_array_equal(want, got)


def test_one_plane_per_onehot_block():
    """Block one-hot discovers exactly one bundle per variable (the
    original-order first-fit: a filled block's bundle occupies every row,
    so the next block's first column immediately conflicts)."""
    X, y = _onehot_problem(n=2500, nvar=12, ncat=20)
    ds = lgb.Dataset(X, y)
    ds.construct()
    assert ds.num_planes == 12


def test_bundled_binary_dataset_round_trip(tmp_path):
    X, y = _onehot_problem(n=1500, nvar=6, ncat=15)
    ds = lgb.Dataset(X, y)
    ds.construct()
    fn = str(tmp_path / "d.bin")
    ds.save_binary(fn)
    ds2 = lgb.Dataset(fn)
    ds2.construct()
    assert ds2.bundle_layout is not None and ds2.bundle_layout.has_bundles
    np.testing.assert_array_equal(ds2.bins, ds.bins)


# ---------------------------------------------------------------- training
def test_bundled_training_matches_unbundled():
    """Bundled and unbundled training are the same algorithm over the same
    per-feature histograms (summation order aside): predictions agree to
    float tolerance and both models split on original feature ids."""
    X, y = _onehot_problem(n=4000, nvar=12, ncat=20, seed=3)
    params = {
        "objective": "regression", "num_leaves": 31, "min_data_in_leaf": 5,
        "verbosity": -1, "seed": 1,
    }
    b = lgb.train(params, lgb.Dataset(X, y, params=params), 20)
    p_off = {**params, "enable_bundle": False}
    b0 = lgb.train(p_off, lgb.Dataset(X, y, params=p_off), 20)
    pred, pred0 = b.predict(X), b0.predict(X)
    # near-tie split flips under different accumulation orders move a few
    # rows; overall fit must agree closely
    corr = np.corrcoef(pred, pred0)[0, 1]
    assert corr > 0.999, corr
    mse = np.mean((pred - y) ** 2)
    mse0 = np.mean((pred0 - y) ** 2)
    assert mse <= mse0 * 1.05, (mse, mse0)


def test_bundled_model_serializes_in_original_feature_space():
    """Round-trip through the Tree::ToString text format: bundled models
    carry original feature ids and real thresholds (never plane ids), and
    the reloaded model reproduces the trainer's predictions."""
    X, y = _onehot_problem(n=3000, nvar=10, ncat=20, seed=5)
    params = {
        "objective": "regression", "num_leaves": 15, "verbosity": -1,
        "min_data_in_leaf": 5,
    }
    ds = lgb.Dataset(X, y, params=params)
    b = lgb.train(params, ds, 10)
    ds.construct()
    assert ds.bundle_layout.has_bundles
    txt = b.model_to_string()
    assert "cat_threshold=" not in txt  # bundle splits decode as NUMERIC
    feats, thrs = [], []
    for line in txt.splitlines():
        if line.startswith("split_feature="):
            feats.extend(int(t) for t in line.split("=")[1].split())
        if line.startswith("threshold="):
            thrs.extend(float(t) for t in line.split("=")[1].split())
    assert feats, "no splits recorded"
    assert max(feats) < X.shape[1]
    # one-hot thresholds sit at the zero/one bin boundary
    assert all(0.0 < t < 1.0 for t in thrs), sorted(set(thrs))[:5]
    b2 = lgb.Booster(model_str=txt)
    np.testing.assert_allclose(
        b2.predict(X.toarray()), b.predict(X), rtol=1e-5, atol=1e-6
    )
    # feature importance is per ORIGINAL feature
    imp = b.feature_importance()
    assert len(imp) == X.shape[1]
    assert imp.sum() == len(feats)


def test_bundled_valid_set_eval_matches_predict():
    X, y = _onehot_problem(n=3000, nvar=8, ncat=15, seed=7)
    params = {
        "objective": "regression", "num_leaves": 15, "verbosity": -1,
        "metric": "l2",
    }
    ds = lgb.Dataset(X[:2000], y[:2000], params=params)
    dv = ds.create_valid(X[2000:], y[2000:])
    ev = {}
    b = lgb.train(
        params, ds, 10, valid_sets=[dv], valid_names=["valid"],
        callbacks=[lgb.record_evaluation(ev)],
    )
    manual = float(np.mean((b.predict(X[2000:]) - y[2000:]) ** 2))
    assert abs(manual - ev["valid"]["l2"][-1]) < 1e-5


def test_bundled_seg_mode_matches_ordered():
    X, y = _onehot_problem(n=2500, nvar=8, ncat=15, seed=11)
    base = {
        "objective": "regression", "num_leaves": 15, "verbosity": -1,
        "min_data_in_leaf": 5,
    }
    b_ord = lgb.train(
        {**base, "hist_mode": "ordered"},
        lgb.Dataset(X, y, params={**base, "hist_mode": "ordered"}), 8,
    )
    b_seg = lgb.train(
        {**base, "hist_mode": "seg"},
        lgb.Dataset(X, y, params={**base, "hist_mode": "seg"}), 8,
    )
    np.testing.assert_allclose(
        b_seg.predict(X), b_ord.predict(X), rtol=1e-5, atol=1e-6
    )


@pytest.mark.slow
def test_50k_onehot_trains_on_seg_fast_path(monkeypatch):
    """The acceptance scenario: 50k one-hot columns that raise the plane
    ceiling unbundled now bundle to ~nvar planes (>= 10x fewer than the
    column count), pack under the seg path's 242-plane budget, and train
    end-to-end with hist_mode='seg'."""
    monkeypatch.setenv("LGBM_TPU_MAX_BINNED_BYTES", str(64 << 20))
    X, y = _onehot_problem(n=3000, nvar=200, ncat=250, seed=0, noise=0.0)
    assert X.shape[1] == 50_000
    with pytest.raises(ValueError, match="enable_bundle|categorical"):
        lgb.Dataset(X, y, params={"enable_bundle": False}).construct()
    params = {
        "objective": "regression", "num_leaves": 15, "verbosity": -1,
        "hist_mode": "seg",
    }
    ds = lgb.Dataset(X, y, params=params)
    ds.construct()
    assert ds.num_planes * 10 <= ds.num_total_features
    assert ds.num_planes <= 242  # fits the seg packed-row lane budget
    b = lgb.train(params, ds, 3)
    assert b.num_trees() >= 1
    pred = b.predict(X[:200])
    assert np.isfinite(pred).all()


def test_wide_onehot_plane_reduction_and_ceiling(monkeypatch):
    """Default-tier twin of the 50k scenario (smaller for runtime): the
    unbundled construct raises the plane ceiling, the bundled one shrinks
    plane count >= 10x and trains on the seg path."""
    monkeypatch.setenv("LGBM_TPU_MAX_BINNED_BYTES", str(8 << 20))
    X, y = _onehot_problem(n=2500, nvar=40, ncat=100, seed=2, noise=0.0)
    assert X.shape[1] == 4000
    with pytest.raises(ValueError, match="enable_bundle|categorical"):
        lgb.Dataset(X, y, params={"enable_bundle": False}).construct()
    params = {
        "objective": "regression", "num_leaves": 15, "verbosity": -1,
        "hist_mode": "seg",
    }
    ds = lgb.Dataset(X, y, params=params)
    ds.construct()
    assert ds.num_planes * 10 <= ds.num_total_features
    b = lgb.train(params, ds, 3)
    pred = b.predict(X[:200])
    assert np.isfinite(pred).all()


def test_conflict_rate_budget_trains():
    rng = np.random.default_rng(1)
    n, f = 3000, 60
    X = sp.random(n, f, density=0.03, format="csr", random_state=rng)
    w = rng.normal(size=f)
    y = np.asarray(X @ w).ravel() + 0.05 * rng.normal(size=n)
    params = {
        "objective": "regression", "num_leaves": 15, "verbosity": -1,
        "max_conflict_rate": 0.1,
    }
    ds = lgb.Dataset(X, y, params=params)
    ds.construct()
    assert ds.bundle_layout is not None and ds.bundle_layout.has_bundles
    b = lgb.train(params, ds, 15)
    pred = b.predict(X)
    assert np.mean((pred - y) ** 2) < 0.8 * np.var(y)


def test_bundle_incompatible_modes_raise():
    X, y = _onehot_problem(n=1500, nvar=6, ncat=15)
    nf = X.shape[1]
    for bad in (
        {"monotone_constraints": [1] + [0] * (nf - 1)},
        {"interaction_constraints": "[0,1],[2,3]"},
        {"extra_trees": True},
        {"cegb_penalty_split": 1e-4},
    ):
        params = {"objective": "regression", "verbosity": -1, **bad}
        with pytest.raises(ValueError, match="enable_bundle"):
            lgb.train(params, lgb.Dataset(X, y, params=params), 2)
        # the documented escape hatch works
        params_off = {**params, "enable_bundle": False}
        b = lgb.train(params_off, lgb.Dataset(X, y, params=params_off), 2)
        assert b.num_trees() >= 0


def test_bundled_subset_shares_layout():
    X, y = _onehot_problem(n=2000, nvar=6, ncat=15)
    ds = lgb.Dataset(X, y)
    ds.construct()
    sub = ds.subset(np.arange(0, 2000, 2))
    assert sub.bundle_layout is ds.bundle_layout
    np.testing.assert_array_equal(sub.bins, ds.bins[::2])
