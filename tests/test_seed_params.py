"""``bagging_seed`` / ``extra_seed`` (reference: config.h — each consumer
derives its own deterministic stream).  Contract here: leaving the seeds
unset keeps the legacy derivation (byte-identical models, goldens untouched);
setting one folds it into the matching RNG stream, so changing it changes
exactly that draw and nothing else.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import lightgbm_tpu as lgb  # noqa: E402


@pytest.fixture(scope="module")
def xy():
    rng = np.random.default_rng(0)
    n = 1000
    X = rng.normal(size=(n, 6))
    y = X[:, 0] * 1.5 - X[:, 1] + 0.5 * X[:, 2] + rng.normal(scale=0.1, size=n)
    return X, y


def _preds(X, y, params, nt=4):
    bst = lgb.train(dict(params, verbosity=-1), lgb.Dataset(X, y), nt)
    return bst.predict(X)


BAG = {
    "objective": "regression",
    "num_leaves": 15,
    "min_data_in_leaf": 5,
    "bagging_freq": 1,
    "bagging_fraction": 0.6,
    "seed": 3,
}


def test_bagging_seed_changes_the_bag(xy):
    X, y = xy
    p0 = _preds(X, y, BAG)
    p_same = _preds(X, y, BAG)
    np.testing.assert_array_equal(p0, p_same)  # unset -> deterministic
    p99 = _preds(X, y, dict(BAG, bagging_seed=99))
    assert not np.allclose(p0, p99)
    p99b = _preds(X, y, dict(BAG, bagging_seed=99))
    np.testing.assert_array_equal(p99, p99b)  # seeded -> deterministic
    p7 = _preds(X, y, dict(BAG, bagging_seed=7))
    assert not np.allclose(p99, p7)


def test_bagging_seed_does_not_touch_unbagged_training(xy):
    """No bagging -> bagging_seed must be a no-op."""
    X, y = xy
    base = {k: v for k, v in BAG.items() if not k.startswith("bagging")}
    np.testing.assert_array_equal(
        _preds(X, y, base), _preds(X, y, dict(base, bagging_seed=99))
    )


def test_extra_seed_changes_the_threshold_draw(xy):
    X, y = xy
    base = {
        "objective": "regression",
        "num_leaves": 15,
        "min_data_in_leaf": 5,
        "extra_trees": True,
        "seed": 3,
    }
    p0 = _preds(X, y, base)
    np.testing.assert_array_equal(p0, _preds(X, y, base))
    p123 = _preds(X, y, dict(base, extra_seed=123))
    assert not np.allclose(p0, p123)
    np.testing.assert_array_equal(p123, _preds(X, y, dict(base, extra_seed=123)))


def test_extra_seed_noop_without_extra_trees(xy):
    X, y = xy
    base = {
        "objective": "regression",
        "num_leaves": 15,
        "min_data_in_leaf": 5,
        "seed": 3,
    }
    np.testing.assert_array_equal(
        _preds(X, y, base), _preds(X, y, dict(base, extra_seed=123))
    )


def test_seed_aliases_resolve():
    cfg = lgb.Config.from_params({"bagging_fraction_seed": 11})
    assert cfg.bagging_seed == 11
