"""weight_column / group_column / ignore_column extraction from text data
files (reference DatasetLoader::SetHeader, src/io/dataset_loader.cpp:111-160,
and Metadata::SetQueryId).

Semantics under test:
  * integer specs index DATA columns — they do not count the label column;
  * ``name:...`` specs require header=true and resolve against it;
  * the group column holds per-row query ids whose consecutive runs become
    query sizes;
  * extracted columns stay in the feature numbering but are ignored for
    training (trivial mappers — never in used_features, never split on);
  * an explicit group_column wins over a ``.query`` sidecar file.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.config import Config  # noqa: E402
from lightgbm_tpu.dataset import _load_text_file  # noqa: E402


def _write_csv(path, arr, header=None):
    with open(path, "w") as fh:
        if header:
            fh.write(",".join(header) + "\n")
        for row in arr:
            fh.write(",".join(f"{v:.8f}" for v in row) + "\n")


def _ranking_file(tmp_path, header=False):
    """label, f0, f1, qid, weight — 12 rows over 3 queries."""
    rng = np.random.default_rng(0)
    n = 12
    qid = np.repeat([0, 1, 2], [5, 4, 3]).astype(float)
    w = rng.uniform(0.5, 2.0, size=n)
    X = rng.normal(size=(n, 2))
    y = rng.integers(0, 3, size=n).astype(float)
    arr = np.column_stack([y, X, qid, w])
    path = tmp_path / "rank.csv"
    _write_csv(path, arr, ["label", "f0", "f1", "qid", "wt"] if header else None)
    return path, arr


def test_weight_column_by_index(tmp_path):
    path, arr = _ranking_file(tmp_path)
    # data-column 3 (not counting the label at raw col 0) = raw column 4
    cfg = Config.from_params({"weight_column": "3"})
    out = _load_text_file(str(path), cfg)
    np.testing.assert_allclose(out["weight"], arr[:, 4], rtol=1e-6)
    # the weight column is dropped from training features
    assert out["ignore"] == [3]


def test_group_column_by_index_run_length(tmp_path):
    path, _ = _ranking_file(tmp_path)
    cfg = Config.from_params({"group_column": "2"})
    out = _load_text_file(str(path), cfg)
    np.testing.assert_array_equal(out["group"], [5, 4, 3])
    assert out["ignore"] == [2]


def test_columns_by_name_require_header(tmp_path):
    path, arr = _ranking_file(tmp_path, header=True)
    cfg = Config.from_params(
        {"header": True, "weight_column": "name:wt",
         "group_column": "name:qid", "ignore_column": "name:f1"}
    )
    out = _load_text_file(str(path), cfg)
    np.testing.assert_allclose(out["weight"], arr[:, 4], rtol=1e-6)
    np.testing.assert_array_equal(out["group"], [5, 4, 3])
    # f1 (data col 1), qid (2), wt (3) all leave the feature set
    assert out["ignore"] == [1, 2, 3]
    # name: without a header is an error, not a silent ignore
    path2, _ = _ranking_file(tmp_path.joinpath("sub") if False else tmp_path)
    cfg2 = Config.from_params({"weight_column": "name:wt"})
    with pytest.raises(ValueError, match="header"):
        _load_text_file(str(path2), cfg2)
    # unknown names are an error too
    cfg3 = Config.from_params({"header": True, "weight_column": "name:nope"})
    with pytest.raises(ValueError, match="nope"):
        _load_text_file(str(path), cfg3)


def test_ignore_column_multiple_indices(tmp_path):
    path, _ = _ranking_file(tmp_path)
    cfg = Config.from_params({"ignore_column": "0,2"})
    out = _load_text_file(str(path), cfg)
    assert out["ignore"] == [0, 2]
    assert "weight" not in out and "group" not in out


def test_group_column_beats_query_sidecar(tmp_path):
    path, _ = _ranking_file(tmp_path)
    np.savetxt(str(path) + ".query", np.array([6, 6]), fmt="%d")
    cfg = Config.from_params({"group_column": "2"})
    out = _load_text_file(str(path), cfg)
    np.testing.assert_array_equal(out["group"], [5, 4, 3])
    # without the param the sidecar still applies
    out2 = _load_text_file(str(path), Config.from_params({}))
    np.testing.assert_array_equal(out2["group"], [6, 6])


def test_ignored_columns_never_train(tmp_path):
    """End-to-end: a file-fed Dataset with weight/group/ignore columns
    trains, ignored features never appear in used_features or splits, and
    the extracted weights change the fit exactly like in-memory weights."""
    rng = np.random.default_rng(7)
    n = 400
    X = rng.normal(size=(n, 3))
    y = 1.5 * X[:, 0] - 0.7 * X[:, 1] + 0.1 * rng.normal(size=n)
    w = np.where(rng.random(n) < 0.5, 3.0, 0.25)
    junk = rng.normal(size=n) * 100.0  # would split if not ignored
    arr = np.column_stack([y, X, junk + y, w])
    path = tmp_path / "train.csv"
    _write_csv(path, arr)
    params = {
        "objective": "regression", "verbosity": -1, "num_leaves": 7,
        "min_data_in_leaf": 10, "weight_column": "4", "ignore_column": "3",
    }
    ds = lgb.Dataset(str(path), params=params)
    b = lgb.train(params, ds, 10)
    ds.construct()
    assert 3 not in ds.used_features  # ignored leaky column
    assert 4 not in ds.used_features  # the weight column itself
    feats = set()
    for line in b.model_to_string().splitlines():
        if line.startswith("split_feature="):
            feats.update(int(t) for t in line.split("=")[1].split())
    assert 3 not in feats and 4 not in feats
    # parity with the in-memory weight path on the same features: the
    # file-fed model keeps all 5 columns in its numbering, the in-memory
    # one sees only the 3 real features — predictions must coincide
    params_mem = {k: v for k, v in params.items()
                  if k not in ("weight_column", "ignore_column")}
    ds_mem = lgb.Dataset(X, y, weight=w, params=params_mem)
    b_mem = lgb.train(params_mem, ds_mem, 10)
    X_full = np.column_stack([X, junk + y, w])
    np.testing.assert_allclose(
        b.predict(X_full), b_mem.predict(X), rtol=1e-6, atol=1e-7
    )


def test_group_column_trains_ranking(tmp_path):
    """lambdarank from a single CSV whose qid travels as group_column."""
    rng = np.random.default_rng(3)
    n, q = 240, 24
    qid = np.repeat(np.arange(q), n // q).astype(float)
    X = rng.normal(size=(n, 3))
    rel = (X[:, 0] + 0.5 * rng.normal(size=n) > 0.5).astype(float)
    arr = np.column_stack([rel, X, qid])
    path = tmp_path / "rank_train.csv"
    _write_csv(path, arr)
    params = {
        "objective": "lambdarank", "verbosity": -1, "num_leaves": 7,
        "min_data_in_leaf": 5, "group_column": "3", "metric": "ndcg",
        "eval_at": [3],
    }
    ds = lgb.Dataset(str(path), params=params)
    ev = {}
    lgb.train(params, ds, 5, valid_sets=[ds], valid_names=["training"],
              callbacks=[lgb.record_evaluation(ev)])
    key = next(k for k in ev["training"] if "ndcg" in k)
    assert np.isfinite(ev["training"][key][-1])
