"""Vmapped model-fleet training (engine.train_fleet / boosting/fleet.py).

The acceptance oracle is BYTE parity: every fleet member's model dump must
equal the dump a solo run of the same effective params produces — the fleet
is an execution strategy, never a semantic change.  The second oracle is the
compile counter: one fleet = one grow executable ("fleet/grow" compiles
exactly once), proving members with different finish times ride the same
warm program as zero-fed lanes.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.boosting import create_booster
from lightgbm_tpu.boosting.fleet import FleetTrainer
from lightgbm_tpu.obs.jit import compile_counts_by_label
from lightgbm_tpu.serving.registry import ModelRegistry

RNG = np.random.default_rng(0)
N, F = 500, 6
X = RNG.normal(size=(N, F))
Y = X[:, 0] * 2 + np.sin(3 * X[:, 1]) + RNG.normal(scale=0.1, size=N)

BASE = {
    "objective": "regression",
    "num_leaves": 8,
    "min_data_in_leaf": 5,
    "verbosity": -1,
}


def _solo_dumps(param_sets, rounds, masks=None):
    """Oracle: train each member alone (mask-based when masks given)."""
    dumps = []
    for i, p in enumerate(param_sets):
        ds = lgb.Dataset(X, Y, free_raw_data=False)
        b = create_booster(dict(p), ds)
        if masks is not None and masks[i] is not None:
            b.set_row_mask(masks[i])
        for _ in range(rounds):
            if b.update():
                break
        dumps.append(b.model_to_string())
    return dumps


def _fleet_dumps(param_sets, rounds, masks=None):
    ds = lgb.Dataset(X, Y, free_raw_data=False)
    boosters = lgb.train_fleet(
        param_sets, ds, num_boost_round=rounds, row_masks=masks
    )
    return [b.model_to_string() for b in boosters]


def _assert_parity(param_sets, rounds, masks=None):
    fleet = _fleet_dumps(param_sets, rounds, masks)
    solo = _solo_dumps(param_sets, rounds, masks)
    for i, (f, s) in enumerate(zip(fleet, solo)):
        assert f == s, f"member {i} diverged from its solo run"


# ----------------------------------------------------------------- parity


def test_fleet_parity_mixed_and_zero_retrace():
    """Plain seed/lr sweep + bagging + extra-trees in ONE fleet, with the
    compile counter proving a single grow executable served all of it."""
    before = dict(compile_counts_by_label())
    param_sets = [
        dict(BASE, seed=1, learning_rate=0.1),
        dict(BASE, seed=2, learning_rate=0.3),
        dict(BASE, seed=3, learning_rate=0.1, bagging_fraction=0.7,
             bagging_freq=1),
        dict(BASE, seed=4, learning_rate=0.2, bagging_fraction=0.5,
             bagging_freq=2),
    ]
    fleet = _fleet_dumps(param_sets, 5)
    after = dict(compile_counts_by_label())
    for label in ("fleet/grow", "fleet/pack_tree_arrays"):
        delta = after.get(label, 0) - before.get(label, 0)
        assert delta == 1, f"{label} compiled {delta} times for one fleet"
    solo = _solo_dumps(param_sets, 5)
    for i, (f, s) in enumerate(zip(fleet, solo)):
        assert f == s, f"member {i} diverged from its solo run"


def test_fleet_parity_extra_trees_seed_sweep():
    # extra_trees lives inside GrowerParams, so ALL members must enable it;
    # the sweep axis is extra_seed
    _assert_parity(
        [
            dict(BASE, seed=1, learning_rate=0.1, extra_trees=True,
                 extra_seed=11),
            dict(BASE, seed=1, learning_rate=0.1, extra_trees=True,
                 extra_seed=99),
        ],
        4,
    )


def test_fleet_parity_goss_sweep():
    # learning_rate 0.5 -> GOSS warmup of 2 iterations, so sampling is live
    _assert_parity(
        [
            dict(BASE, boosting="goss", seed=1, learning_rate=0.5,
                 top_rate=0.2, other_rate=0.1),
            dict(BASE, boosting="goss", seed=2, learning_rate=0.5,
                 top_rate=0.3, other_rate=0.2),
        ],
        5,
    )


def test_fleet_parity_cv_row_masks():
    m0 = np.zeros(N, np.float32)
    m0[: N // 2] = 1.0
    m1 = np.zeros(N, np.float32)
    m1[N // 2:] = 1.0
    _assert_parity(
        [dict(BASE, seed=1, learning_rate=0.1)] * 2, 4, masks=[m0, m1]
    )


def test_fleet_parity_data_parallel():
    # conftest forces 8 virtual CPU devices; the stacked [M, K, F, B, 3]
    # histogram psums one payload per step for the whole fleet
    _assert_parity(
        [
            dict(BASE, tree_learner="data", seed=1, learning_rate=0.1),
            dict(BASE, tree_learner="data", seed=2, learning_rate=0.2),
        ],
        4,
    )


def test_fleet_parity_m8():
    _assert_parity(
        [dict(BASE, seed=s, learning_rate=0.1) for s in range(8)], 3
    )


def test_num_fleet_dict_expansion():
    """One dict + num_fleet=M expands to M members with offset seeds, each
    byte-equal to a solo run of its effective params."""
    ds = lgb.Dataset(X, Y, free_raw_data=False)
    fleet = lgb.train_fleet(
        dict(BASE, seed=5, learning_rate=0.1, num_fleet=3),
        ds,
        num_boost_round=3,
    )
    assert len(fleet) == 3
    solo = _solo_dumps(
        [dict(BASE, seed=5 + i, learning_rate=0.1, num_fleet=3)
         for i in range(3)],
        3,
    )
    for i, b in enumerate(fleet):
        assert b.model_to_string() == solo[i], f"member {i} diverged"


# --------------------------------------------------------------------- cv


def test_cv_fleet_matches_sequential_mask_loop():
    """cv(fleet=True)'s oracle is the sequential mask-based loop over the
    SHARED binning (not legacy cv, which re-bins per fold — a documented
    fleet-mode difference)."""
    idx = np.arange(N)
    folds = [
        (idx[N // 3:], idx[: N // 3]),
        (np.concatenate([idx[: N // 3], idx[2 * N // 3:]]),
         idx[N // 3: 2 * N // 3]),
        (idx[: 2 * N // 3], idx[2 * N // 3:]),
    ]
    ds = lgb.Dataset(X, Y, free_raw_data=False)
    params = dict(BASE, seed=7, learning_rate=0.1, metric="l2")
    res = lgb.cv(
        params, ds, num_boost_round=4, folds=folds, fleet=True,
        return_cvbooster=True,
    )
    assert len(res["valid l2-mean"]) == 4
    assert len(res["valid l2-stdv"]) == 4
    fleet_dumps = [
        b.model_to_string() for b in res["cvbooster"].boosters
    ]

    # sequential oracle: per-fold mask-based training on the same binning
    masks = []
    for train_idx, _test_idx in folds:
        m = np.zeros(N, np.float32)
        m[np.asarray(train_idx)] = 1.0
        masks.append(m)
    solo = _solo_dumps([dict(params)] * len(folds), 4, masks=masks)
    for i, (f, s) in enumerate(zip(fleet_dumps, solo)):
        assert f == s, f"fold {i} diverged from its sequential mask run"

    # per-iteration mean really is the mean of the per-fold evals
    evals = [b.eval_valid() for b in res["cvbooster"].boosters]
    manual = float(np.mean([e[0][2] for e in evals]))
    assert res["valid l2-mean"][-1] == pytest.approx(manual)


def test_cv_fleet_falls_back_for_fobj():
    ds = lgb.Dataset(X, Y, free_raw_data=False)

    def fobj(preds, train_data):
        y = train_data.get_label()
        return preds - y, np.ones_like(preds)

    res = lgb.cv(
        dict(BASE, seed=1, learning_rate=0.1, metric="l2"),
        ds, num_boost_round=2, nfold=2, fleet=True, fobj=fobj,
    )
    assert any(k.endswith("-mean") for k in res)


# ------------------------------------------------------------ early stop


def test_fleet_per_member_early_stopping():
    """A member that early-stops freezes (best_iteration set, no further
    trees) while the rest of the fleet trains on in the same executable."""
    ds = lgb.Dataset(X, Y, free_raw_data=False)
    dv = lgb.Dataset(
        X[:100] + RNG.normal(scale=2.0, size=(100, F)), Y[:100],
        free_raw_data=False, reference=ds,
    )
    param_sets = [
        # huge lr on noisy valid -> stops almost immediately
        dict(BASE, seed=1, learning_rate=5.0, metric="l2",
             early_stopping_round=1, first_metric_only=True),
        dict(BASE, seed=2, learning_rate=0.1, metric="l2"),
    ]
    fleet = lgb.train_fleet(
        param_sets, ds, num_boost_round=8, valid_sets=[dv],
        valid_names=["v"],
    )
    assert fleet[0].best_iteration > 0
    assert fleet[0].current_iteration() < 8
    assert fleet[1].current_iteration() == 8
    # the survivor is still byte-equal to its solo run
    solo = _solo_dumps([param_sets[1]], 8)[0]
    assert fleet[1].model_to_string() == solo


# ------------------------------------------------------------- serving


def test_register_fleet_bulk():
    ds = lgb.Dataset(X, Y, free_raw_data=False)
    boosters = lgb.train_fleet(
        [dict(BASE, seed=1, learning_rate=0.1),
         dict(BASE, seed=2, learning_rate=0.3)],
        ds, num_boost_round=3,
    )
    reg = ModelRegistry(chunk=256)
    try:
        entries = reg.register_fleet(boosters, prefix="sweep")
        assert [e.model_id for e in entries] == ["sweep/0", "sweep/1"]
        ids = {m["model_id"] for m in reg.models()}
        assert ids == {"sweep/0", "sweep/1"}
        for i, b in enumerate(boosters):
            got = reg.booster(f"sweep/{i}").predict(X[:32])
            np.testing.assert_array_equal(got, b.predict(X[:32]))
        with pytest.raises(ValueError):
            reg.register_fleet(boosters, prefix="sweep")  # id clash
        with pytest.raises(ValueError):
            reg.register_fleet(boosters, model_ids=["only-one"])
    finally:
        reg.close()


# ----------------------------------------------------------- validation


def test_fleet_rejects_shape_mismatch():
    ds = lgb.Dataset(X, Y, free_raw_data=False)
    with pytest.raises(ValueError, match="GrowerParams"):
        lgb.train_fleet(
            [dict(BASE, seed=1), dict(BASE, seed=2, num_leaves=31)],
            ds, num_boost_round=2,
        )


def test_fleet_rejects_unsupported_features():
    ds = lgb.Dataset(X, Y, free_raw_data=False)
    with pytest.raises(ValueError, match="linear_tree"):
        lgb.train_fleet(
            [dict(BASE, seed=1, linear_tree=True)] * 2, ds,
            num_boost_round=2,
        )


def test_fleet_rejects_bad_row_mask():
    ds = lgb.Dataset(X, Y, free_raw_data=False)
    b = create_booster(dict(BASE, seed=1), ds)
    with pytest.raises(ValueError):
        b.set_row_mask(np.zeros(N, np.float32))  # no live rows
    with pytest.raises(ValueError):
        b.set_row_mask(np.ones(N + 1, np.float32))  # wrong length


def test_fleet_trainer_requires_shared_dataset():
    ds1 = lgb.Dataset(X, Y, free_raw_data=False)
    ds2 = lgb.Dataset(X, Y, free_raw_data=False)
    b1 = create_booster(dict(BASE, seed=1), ds1)
    b2 = create_booster(dict(BASE, seed=2), ds2)
    with pytest.raises(ValueError, match="Dataset"):
        FleetTrainer([b1, b2])
