"""Metric correctness vs direct NumPy oracles (reference: src/metric/*)."""

import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.metrics import create_metric

N = 200


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(3)
    label = rng.normal(size=N)
    score = label + rng.normal(scale=0.5, size=N)
    weight = rng.uniform(0.5, 2.0, size=N)
    return label, score, weight


def _eval(name, label, score, weight=None, qb=None, params=None):
    cfg = Config.from_params(params or {})
    m = create_metric(name, cfg)
    m.init(label, weight, qb)
    return dict((k, v) for k, v in m.eval(score[None], None)), m


def test_l2_rmse_l1(data):
    label, score, weight = data
    res, _ = _eval("l2", label, score)
    assert res["l2"] == pytest.approx(np.mean((score - label) ** 2))
    res, _ = _eval("rmse", label, score)
    assert res["rmse"] == pytest.approx(np.sqrt(np.mean((score - label) ** 2)))
    res, _ = _eval("l1", label, score, weight)
    assert res["l1"] == pytest.approx(
        np.sum(np.abs(score - label) * weight) / weight.sum()
    )


def test_auc_matches_rank_formula():
    rng = np.random.default_rng(5)
    y = (rng.random(300) > 0.6).astype(np.float64)
    s = rng.normal(size=300) + y
    res, _ = _eval("auc", y, s)
    # oracle: Mann-Whitney U with tie correction via average ranks
    from scipy.stats import rankdata  # type: ignore

    r = rankdata(s)
    n_pos, n_neg = y.sum(), (1 - y).sum()
    auc = (r[y == 1].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)
    assert res["auc"] == pytest.approx(auc, abs=1e-9)


def test_binary_logloss_error():
    rng = np.random.default_rng(6)
    y = (rng.random(100) > 0.5).astype(np.float64)
    raw = rng.normal(size=100)
    # metric converts raw -> prob only with an objective attached; pass probs
    # through a sigmoid objective by evaluating with objective=None on probs
    cfg = Config.from_params({})
    m = create_metric("binary_logloss", cfg)
    m.init(y, None)
    prob = 1 / (1 + np.exp(-raw))
    out = dict(m.eval(prob[None], None))
    expect = -np.mean(y * np.log(prob) + (1 - y) * np.log(1 - prob))
    assert out["binary_logloss"] == pytest.approx(expect, rel=1e-9)
    m2 = create_metric("binary_error", cfg)
    m2.init(y, None)
    out2 = dict(m2.eval(prob[None], None))
    assert out2["binary_error"] == pytest.approx(np.mean((prob > 0.5) != (y > 0)))


def test_multi_logloss_error():
    rng = np.random.default_rng(8)
    k, n = 4, 100
    y = rng.integers(0, k, size=n).astype(np.float64)
    raw = rng.normal(size=(k, n))
    cfg = Config.from_params({"num_class": k})
    m = create_metric("multi_error", cfg)
    m.init(y, None)
    out = dict(m.eval(raw, None))
    pred = raw.argmax(axis=0)
    assert out["multi_error"] == pytest.approx(np.mean(pred != y))


def test_ndcg_perfect_and_inverted():
    label = np.array([3, 2, 1, 0], dtype=np.float64)
    qb = np.array([0, 4])
    res, _ = _eval("ndcg", label, np.array([4.0, 3.0, 2.0, 1.0]), qb=qb, params={"eval_at": [4]})
    assert res["ndcg@4"] == pytest.approx(1.0)
    res2, _ = _eval("ndcg", label, np.array([1.0, 2.0, 3.0, 4.0]), qb=qb, params={"eval_at": [4]})
    assert res2["ndcg@4"] < 1.0


def test_map():
    label = np.array([1, 0, 1, 0], dtype=np.float64)
    score = np.array([4.0, 3.0, 2.0, 1.0])
    qb = np.array([0, 4])
    res, _ = _eval("map", label, score, qb=qb, params={"eval_at": [4]})
    # hits at ranks 1 and 3: AP = (1/1 + 2/3)/2
    assert res["map@4"] == pytest.approx((1.0 + 2.0 / 3.0) / 2.0)


def test_metric_aliases():
    cfg = Config.from_params({})
    assert create_metric("mse", cfg).name == "l2"
    assert create_metric("mae", cfg).name == "l1"
    assert create_metric("kldiv", cfg).name == "kullback_leibler"
