"""Model inspection/plotting surface (reference: python-package
Booster.trees_to_dataframe basic.py:4060, plotting.py)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import lightgbm_tpu as lgb  # noqa: E402


@pytest.fixture(scope="module")
def booster():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(400, 4))
    y = X[:, 0] + 0.3 * X[:, 1] + rng.normal(scale=0.2, size=400)
    return (
        lgb.train(
            {"objective": "regression", "verbosity": -1, "num_leaves": 7},
            lgb.Dataset(X, y),
            3,
        ),
        X,
        y,
    )


def test_trees_to_dataframe(booster):
    b, X, y = booster
    df = b.trees_to_dataframe()
    # 6 split nodes + 7 leaves per full tree
    assert (df.groupby("tree_index").size() == 13).all()
    assert set(
        ["tree_index", "node_index", "left_child", "right_child",
         "split_feature", "threshold", "value", "count"]
    ) <= set(df.columns)
    splits = df[df.split_feature.notna()]
    assert (splits.decision_type == "<=").all()
    # root counts cover the dataset
    roots = df[(df.node_depth == 1)]
    assert (roots["count"] == 400).all()


def test_leaf_output_and_bounds(booster):
    b, X, y = booster
    v = b.get_leaf_output(0, 0)
    assert np.isfinite(v)
    assert b.lower_bound() <= b.upper_bound()
    b2 = lgb.Booster(model_str=b.model_to_string())
    b2.set_leaf_output(0, 0, 99.0)
    assert b2.get_leaf_output(0, 0) == 99.0
    # predictions reflect the mutated leaf
    row = X[:1]
    leaves = b2.predict(row, pred_leaf=True)
    if leaves[0, 0] == 0:
        assert b2.predict(row)[0] != pytest.approx(b.predict(row)[0])


def test_plotting(booster):
    mpl = pytest.importorskip("matplotlib")
    mpl.use("Agg")
    b, X, y = booster
    ax = lgb.plot_importance(b)
    assert ax is not None
    ev = {"t": {"l2": [3.0, 2.0, 1.5]}}
    ax2 = lgb.plot_metric(ev)
    assert ax2 is not None


def test_tree_digraph(booster):
    pytest.importorskip("graphviz")
    b, _, _ = booster
    g = lgb.create_tree_digraph(b, 0)
    src = g.source
    assert "leaf" in src and "Column_0" in src
