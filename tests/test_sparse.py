"""Sparse ingestion: CSR/CSC binning without dense-float materialization and
the LibSVM parser (reference: SparseBin construction src/io/sparse_bin.hpp,
Dataset::CreateFromCSR c_api.cpp, LibSVMParser src/io/parser.hpp:136)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
sp = pytest.importorskip("scipy.sparse")

import lightgbm_tpu as lgb  # noqa: E402


def _sparse_problem(n=2000, f=40, density=0.05, seed=0):
    rng = np.random.default_rng(seed)
    X = sp.random(n, f, density=density, format="csr", random_state=rng)
    w = rng.normal(size=f)
    y = np.asarray(X @ w).ravel() + 0.05 * rng.normal(size=n)
    return X, y


def test_sparse_train_matches_dense():
    X, y = _sparse_problem()
    params = {
        "objective": "regression",
        "num_leaves": 15,
        "min_data_in_leaf": 5,
        "verbosity": -1,
        "seed": 1,
    }
    b_sparse = lgb.train(params, lgb.Dataset(X, y), 10)
    b_dense = lgb.train(params, lgb.Dataset(X.toarray(), y), 10)
    np.testing.assert_allclose(
        b_sparse.predict(X), b_dense.predict(X.toarray()), rtol=1e-5, atol=1e-6
    )
    # sparse predict == dense predict on the same model
    np.testing.assert_allclose(
        b_sparse.predict(X), b_sparse.predict(X.toarray()), rtol=1e-6
    )


def test_wide_sparse_constructs_without_dense_float():
    """A wide, very sparse matrix constructs directly from CSC columns; the
    bin matrix is narrow-int and the dense float matrix never exists."""
    n, f = 200_000, 2000
    rng = np.random.default_rng(3)
    X = sp.random(n, f, density=0.001, format="csr", random_state=rng)
    y = np.asarray(X.sum(axis=1)).ravel()
    d = lgb.Dataset(X, y, params={"verbosity": -1})
    d.construct()
    assert d.bins.dtype in (np.uint8, np.uint16)
    assert d.bins.shape[0] == n
    assert d.raw is None  # no dense float copy retained
    # zeros landed in each feature's zero bin
    j = d.used_features[0]
    zb = d.bin_mappers[j].values_to_bins(np.zeros(1))[0]
    col = d.bins[:, 0]
    assert (col == zb).mean() > 0.9


def test_libsvm_parser_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    n, f = 300, 12
    Xd = np.zeros((n, f))
    mask = rng.random((n, f)) < 0.3
    Xd[mask] = rng.normal(size=mask.sum())
    y = (Xd[:, 0] + Xd[:, 1] > 0).astype(float)
    lines = []
    for i in range(n):
        toks = [f"{y[i]:g}"]
        for j in np.nonzero(Xd[i])[0]:
            toks.append(f"{j}:{Xd[i, j]:.6g}")
        lines.append(" ".join(toks))
    path = tmp_path / "train.libsvm"
    path.write_text("\n".join(lines) + "\n")

    d = lgb.Dataset(str(path), params={"verbosity": -1})
    d.construct()
    assert d.num_data == n
    np.testing.assert_allclose(d.get_label(), y)
    b = lgb.train(
        {"objective": "binary", "verbosity": -1, "num_leaves": 7},
        lgb.Dataset(str(path), params={"verbosity": -1}),
        5,
    )
    pred = b.predict(Xd)
    assert ((pred > 0.5) == y).mean() > 0.8


def test_libsvm_qid_groups(tmp_path):
    lines = [
        "1 qid:1 0:0.5 1:1.0",
        "0 qid:1 0:0.1",
        "1 qid:2 1:0.7",
        "0 qid:2 0:0.2 1:0.1",
        "0 qid:2 1:0.9",
    ]
    path = tmp_path / "rank.libsvm"
    path.write_text("\n".join(lines) + "\n")
    from lightgbm_tpu.dataset import _load_text_file
    from lightgbm_tpu.config import Config

    out = _load_text_file(str(path), Config.from_params({}))
    np.testing.assert_array_equal(out["group"], [2, 3])
    assert out["data"].shape == (5, 2)


def test_sparse_wide_fails_actionably(monkeypatch):
    """With EFB OFF, a sparse-wide dataset (50k one-hot columns) over the
    dense-layout memory ceiling fails at construction with an error naming
    the fixes (enable_bundle / categorical re-encoding), not an OOM
    mid-allocation.  With EFB on (the default) the SAME data bundles into
    a handful of planes and constructs under the ceiling — the former
    error path is now the supported path."""
    sp = pytest.importorskip("scipy.sparse")
    # ~2.9k of the 50k columns survive trivial-feature pruning at this row
    # count; the ceiling sits below their ~8.3 MB footprint
    monkeypatch.setenv("LGBM_TPU_MAX_BINNED_BYTES", str(4 << 20))
    rng = np.random.default_rng(0)
    n, f = 3000, 50_000
    rows = np.arange(n)
    cols = rng.integers(0, f, size=n)
    X = sp.csc_matrix(
        (np.ones(n, np.float64), (rows, cols)), shape=(n, f)
    )
    y = rng.normal(size=n)
    ds = lgb.Dataset(X, y, params={"enable_bundle": False})
    with pytest.raises(ValueError, match="categorical"):
        ds.construct()
    # EFB (default) bundles the mutually-exclusive columns under the ceiling
    dsb = lgb.Dataset(X, y)
    dsb.construct()
    assert dsb.bundle_layout is not None and dsb.bundle_layout.has_bundles
    assert dsb.num_planes * 10 <= len(dsb.used_features)
    # a small slice of the same data is under the ceiling and trains
    Xs = X[:, :40].toarray()
    b = lgb.train(
        {"objective": "regression", "verbosity": -1},
        lgb.Dataset(Xs, y), 2,
    )
    assert b.num_trees() >= 1
