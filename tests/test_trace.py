"""End-to-end structured tracing: span recorder, Chrome export, wiring.

Covers the always-on span recorder (tree integrity, bounded-ring
eviction accounting, deterministic sampling), the Chrome trace-event
JSON export (Perfetto-loadable schema), the training instrumentation
(iteration spans, launch spans with synthetic per-iteration children
reconstructed from device counters, per-iteration ``from_launch`` JSONL
events), the serving decomposition (request/queue_wait/batch stages,
W3C traceparent round-trip over HTTP), dump-on-fault pairing with the
flight recorder, the iteration-denominated watchdog cadence at
``train_steps_per_launch`` N=1 vs N=8, and the zero-retrace contract.
"""

import json
import os
import urllib.request

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.obs.flight import get_flight  # noqa: E402
from lightgbm_tpu.obs.health import HealthWatchdog  # noqa: E402
from lightgbm_tpu.obs.jit import compile_counts_by_label  # noqa: E402
from lightgbm_tpu.obs.registry import get_session  # noqa: E402
from lightgbm_tpu.obs.trace import (  # noqa: E402
    MIN_CAPACITY,
    TRACE_SCHEMA,
    TraceRecorder,
    format_traceparent,
    get_tracer,
    parse_traceparent,
)


@pytest.fixture(autouse=True)
def _clean_obs():
    ses = get_session()
    ses.configure(enabled=False)
    ses.reset()
    flight = get_flight()
    flight.reset()
    flight.configure(fault_dir="", run_info={}, active=True)
    tracer = get_tracer()
    tracer.reset()
    tracer.configure(active=True, capacity=4096, default_rate=1.0, rates={})
    yield
    ses.configure(enabled=False)
    ses.reset()
    flight.reset()
    flight.configure(fault_dir="", run_info={}, active=True)
    tracer.reset()
    tracer.configure(active=True, capacity=4096, default_rate=1.0, rates={})


def _data(n=300, f=5, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = X[:, 0] * 2 + np.sin(X[:, 1]) + 0.1 * rng.normal(size=n)
    return X, y


_PARAMS = {
    "objective": "regression",
    "num_leaves": 7,
    "verbosity": -1,
    "deterministic": True,
    "seed": 7,
}


# ------------------------------------------------------------- recorder core
def test_span_tree_integrity():
    tr = TraceRecorder()
    with tr.span("root", "train") as root:
        assert root is not None
        with tr.span("child", "train") as child:
            tr.instant("leaf", "lifecycle")
    spans = tr.spans()
    by_name = {s["name"]: s for s in spans}
    assert by_name["child"]["parent_id"] == root.span_id
    assert by_name["child"]["trace_id"] == root.trace_id
    assert by_name["leaf"]["parent_id"] == child.span_id
    assert by_name["root"]["parent_id"] is None
    # ids are stable hex of the documented widths
    assert len(root.trace_id) == 32 and len(root.span_id) == 16
    int(root.trace_id, 16), int(root.span_id, 16)
    # ends arrive child-first, and every duration is non-negative
    assert [s["name"] for s in spans] == ["leaf", "child", "root"]
    assert all((s["dur"] or 0) >= 0 for s in spans)


def test_ring_eviction_accounting():
    tr = TraceRecorder()
    tr.configure(capacity=MIN_CAPACITY)
    for i in range(MIN_CAPACITY + 36):
        tr.end(tr.begin(f"s{i}", "train"))
    st = tr.stats()
    assert st["ring"] == MIN_CAPACITY
    assert st["spans_total"] == MIN_CAPACITY + 36
    assert st["dropped_total"] == 36
    # the ring keeps the newest spans
    assert tr.spans()[-1]["name"] == f"s{MIN_CAPACITY + 35}"


def test_sampling_deterministic_and_per_category():
    tr = TraceRecorder()
    tr.configure(default_rate=0.25, rates={"serve": 1.0, "phase": 0.0})
    kept = sum(tr.begin(f"t{i}", "train") is not None for i in range(100))
    assert kept == 25  # counter-based: exactly rate * n
    assert all(tr.begin(f"r{i}", "serve") is not None for i in range(10))
    assert all(tr.begin(f"p{i}", "phase") is None for i in range(10))
    tr.configure(active=False)
    assert tr.begin("off", "serve") is None


def test_traceparent_parse_and_format():
    tp = format_traceparent("ab" * 16, "cd" * 8)
    assert tp == "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
    assert parse_traceparent(tp) == ("ab" * 16, "cd" * 8)
    assert parse_traceparent("garbage") is None
    assert parse_traceparent("") is None
    assert parse_traceparent(None) is None
    # all-zero ids are invalid per W3C trace-context
    assert parse_traceparent("00-" + "0" * 32 + "-" + "cd" * 8 + "-01") is None
    assert parse_traceparent("00-" + "ab" * 16 + "-" + "0" * 16 + "-01") is None


def test_chrome_trace_schema(tmp_path):
    tr = TraceRecorder()
    with tr.span("outer", "train", args={"k": 1}):
        tr.instant("mark", "lifecycle")
    path = tr.dump(str(tmp_path / "trace.json"))
    doc = json.loads(open(path).read())
    assert isinstance(doc["traceEvents"], list)
    assert doc["displayTimeUnit"] == "ms"
    assert doc["metadata"]["schema"] == TRACE_SCHEMA
    events = doc["traceEvents"]
    metas = [e for e in events if e["ph"] == "M"]
    assert any(e["name"] == "process_name" for e in metas)
    assert any(e["name"] == "thread_name" for e in metas)
    xs = [e for e in events if e["ph"] == "X"]
    instants = [e for e in events if e["ph"] == "i"]
    assert len(xs) == 1 and len(instants) == 1
    for e in xs:
        assert isinstance(e["ts"], int) and isinstance(e["dur"], int)
        assert e["pid"] == os.getpid()
        assert {"trace_id", "span_id"} <= set(e["args"])
    assert instants[0]["s"] == "t"
    # non-meta events are sorted by timestamp
    ts = [e["ts"] for e in events if e["ph"] != "M"]
    assert ts == sorted(ts)
    assert tr.stats()["last_dump"] == path


# -------------------------------------------------------------- train spans
def test_train_iteration_spans_and_phase_children():
    X, y = _data()
    lgb.train(dict(_PARAMS, telemetry=True), lgb.Dataset(X, y), 3)
    spans = get_tracer().spans()
    runs = [s for s in spans if s["name"] == "train/run"]
    iters = [s for s in spans if s["name"] == "train/iteration"]
    phases = [s for s in spans if s["name"].startswith("phase/")]
    assert len(runs) == 1
    assert len(iters) == 3
    assert all(s["parent_id"] == runs[0]["span_id"] for s in iters)
    assert all(s["trace_id"] == runs[0]["trace_id"] for s in iters)
    iter_ids = {s["span_id"] for s in iters}
    assert phases and all(s["parent_id"] in iter_ids for s in phases)
    assert not any(s.get("synthetic") for s in spans)


def test_launch_synthetic_children_match_serial(tmp_path):
    X, y = _data()
    serial = lgb.train(
        dict(_PARAMS, telemetry=True), lgb.Dataset(X, y), 6
    )
    serial_events = [
        e for e in serial.telemetry()["events"]
        if e.get("event") == "iteration"
    ]
    assert len(serial_events) == 6
    # ground truth per-iteration splits from the serial model's own trees
    # (the serial JSONL's per-event split counts lag one iteration on the
    # pipelined path, so the trees are the alignment oracle)
    serial_splits = {
        i: tree["num_leaves"] - 1
        for i, tree in enumerate(serial.dump_model()["tree_info"])
    }

    tracer = get_tracer()
    tracer.reset()
    ses = get_session()
    ses.configure(enabled=False)
    ses.reset()
    launched = lgb.train(
        dict(_PARAMS, telemetry=True, train_steps_per_launch=3),
        lgb.Dataset(X, y), 6,
    )
    # byte-identical model (the params block legitimately differs by the
    # train_steps_per_launch line itself)
    drop = lambda txt: [  # noqa: E731
        ln for ln in txt.splitlines()
        if not ln.startswith("[train_steps_per_launch")
    ]
    assert drop(serial.model_to_string()) == drop(launched.model_to_string())
    spans = tracer.spans()
    launches = [s for s in spans if s["name"] == "train/launch"]
    synth = [s for s in spans if s.get("synthetic")]
    assert len(launches) == 2
    assert len(synth) == 6
    launch_ids = {s["span_id"] for s in launches}
    for s in synth:
        assert s["name"] == "train/iteration"
        assert s["parent_id"] in launch_ids
        assert s["args"]["from_launch"] is True
        # device counters on the synthetic span match the serial run
        assert s["args"]["splits"] == serial_splits[s["args"]["iter"]]
    # synthetic children tile their launch window in iteration order
    for launch in launches:
        kids = sorted(
            (s for s in synth if s["parent_id"] == launch["span_id"]),
            key=lambda s: s["args"]["iter"],
        )
        assert [s["ts"] for s in kids] == sorted(s["ts"] for s in kids)
        assert all(s["ts"] >= launch["ts"] for s in kids)

    # satellite: per-iteration JSONL events replayed with from_launch=true
    launched_events = [
        e for e in launched.telemetry()["events"]
        if e.get("event") == "iteration"
    ]
    assert len(launched_events) == 6
    assert all(e.get("from_launch") for e in launched_events)
    assert {e["iter"]: e["splits"] for e in launched_events} == serial_splits


def test_dump_trace_api(tmp_path):
    X, y = _data()
    b = lgb.train(dict(_PARAMS), lgb.Dataset(X, y), 2)
    out = str(tmp_path / "run_trace.json")
    assert b.dump_trace(out) == out
    doc = json.loads(open(out).read())
    names = {e["name"] for e in doc["traceEvents"]}
    assert "train/run" in names and "train/iteration" in names


def test_dump_on_fault_pairs_flight_and_trace(tmp_path):
    flight = get_flight()
    flight.configure(fault_dir=str(tmp_path), run_info={}, active=True)
    flight.note_event({"event": "iteration", "iter": 0, "wall_ms": 1.0})
    tr = get_tracer()
    tr.end(tr.begin("train/iteration", "train"))
    flight_path = flight.dump("unit_fault")
    trace_path = flight.last_trace_path
    assert os.path.exists(flight_path) and os.path.exists(trace_path)
    # the pair shares one <ts>_<pid>_<n> suffix for postmortem correlation
    fsuf = os.path.basename(flight_path)[len("flight_"):]
    tsuf = os.path.basename(trace_path)[len("trace_"):]
    assert fsuf == tsuf
    doc = json.loads(open(trace_path).read())
    assert any(
        e["name"] == "train/iteration" for e in doc["traceEvents"]
    )


def test_trace_disabled_by_config():
    X, y = _data()
    lgb.train(dict(_PARAMS, trace_spans=False), lgb.Dataset(X, y), 2)
    assert get_tracer().stats()["spans_total"] == 0


# ------------------------------------------------------------- serving spans
@pytest.mark.slow
def test_serving_traceparent_http_round_trip():
    X, y = _data()
    b = lgb.train(dict(_PARAMS), lgb.Dataset(X, y), 3)
    tracer = get_tracer()
    tracer.reset()
    srv = lgb.serve(b, params={"serve_port": -1, "serve_deadline_ms": 2.0})
    try:
        caller_trace, caller_span = "ab" * 16, "cd" * 8
        req = urllib.request.Request(
            srv.url + "/predict",
            data=json.dumps({"rows": X[:4].tolist()}).encode(),
            headers={"traceparent": format_traceparent(caller_trace, caller_span)},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            doc = json.loads(resp.read())
            echoed = resp.headers.get("traceparent")
        assert np.allclose(doc["predictions"], b.predict(X[:4]))
        # echoed header: caller's trace id, the request span's own id
        assert echoed == doc["traceparent"]
        parsed = parse_traceparent(echoed)
        assert parsed is not None and parsed[0] == caller_trace
        spans = {s["span_id"]: s for s in tracer.spans()}
        req_span = spans[parsed[1]]
        assert req_span["name"] == "serve/request"
        assert req_span["trace_id"] == caller_trace
        assert req_span["parent_id"] == caller_span
        by_name = {}
        for s in spans.values():
            by_name.setdefault(s["name"], []).append(s)
        # queue_wait decomposes the request span; the stage spans decompose
        # the flush's batch span
        qw = by_name["serve/queue_wait"]
        assert any(s["parent_id"] == req_span["span_id"] for s in qw)
        batch = by_name["serve/batch"][0]
        for stage in (
            "serve/batch_assembly",
            "serve/device_dispatch",
            "serve/unpad_respond",
        ):
            assert any(
                s["parent_id"] == batch["span_id"] for s in by_name[stage]
            )
        # GET /trace serves the same Chrome JSON document
        with urllib.request.urlopen(srv.url + "/trace", timeout=10) as resp:
            tdoc = json.loads(resp.read())
        assert {e["name"] for e in tdoc["traceEvents"]} >= {
            "serve/request", "serve/batch", "serve/queue_wait"
        }
        # /metrics: trace counters + queue/device attribution summaries
        with urllib.request.urlopen(srv.url + "/metrics", timeout=10) as resp:
            text = resp.read().decode()
        assert "lgbtpu_trace_spans_total" in text
        assert "lgbtpu_trace_dropped_total" in text
        assert 'lgbtpu_serve_queue_ms{quantile="0.99"}' in text
        assert 'lgbtpu_serve_device_ms{quantile="0.99"}' in text
    finally:
        srv.stop()


def test_predict_async_traceparent_echo():
    X, y = _data()
    b = lgb.train(dict(_PARAMS), lgb.Dataset(X, y), 2)
    srv = lgb.serve(b, params={"serve_port": 0, "serve_deadline_ms": 1.0})
    try:
        tp = format_traceparent("12" * 16, "34" * 8)
        resp = srv.predict_async(X[:2], traceparent=tp).result(timeout=30)
        parsed = parse_traceparent(resp.info["traceparent"])
        assert parsed is not None and parsed[0] == "12" * 16
        # without a header the info carries no trace context only when
        # the request span was sampled out; by default it is sampled in
        resp2 = srv.predict_async(X[:2]).result(timeout=30)
        assert parse_traceparent(resp2.info.get("traceparent")) is not None
    finally:
        srv.stop()


# --------------------------------------------------------- watchdog cadence
def _cadence_alerts(launch_steps: int, total: int = 80):
    """Feed the watchdog commit-rate-collapse telemetry as `total`
    iterations grouped into `launch_steps`-sized launch events; returns
    the iterations at which the rule fired."""
    ses = get_session()
    ses.configure(enabled=True)
    ses.set_gauge("grower.commit_rate", 0.05)
    ses.set_gauge("grower.leaf_batch_effective", 4.0)
    wd = HealthWatchdog(warmup_iters=7, cooldown_iters=16)
    fired = []
    for start in range(0, total, launch_steps):
        last = start + launch_steps - 1
        if launch_steps == 1:
            event = {"event": "iteration", "iter": last, "wall_ms": 10.0}
        else:
            event = {
                "event": "launch",
                "iter": last,
                "launch_begin": start,
                "steps": launch_steps,
                "wall_ms": 10.0,
            }
        for alert in wd.observe(event, ses):
            fired.append(alert["iter"])
    ses.configure(enabled=False)
    ses.reset()
    return fired


def test_watchdog_cadence_identical_serial_vs_launch():
    """Satellite: warmup/cooldown counted in iterations, not observe()
    calls — N=1 and N=8 launches see the identical alert cadence."""
    serial = _cadence_alerts(1)
    launched = _cadence_alerts(8)
    assert serial == [7, 23, 39, 55, 71]
    assert launched == serial


# ------------------------------------------------------------- perf contract
def test_tracing_adds_zero_retraces():
    X, y = _data()
    params = dict(_PARAMS, telemetry=True)
    lgb.train(params, lgb.Dataset(X, y), 3)
    before = compile_counts_by_label()
    # identical run with tracing exercised end-to-end (spans + dump) must
    # not introduce a single new compile at any jit site
    get_tracer().reset()
    b = lgb.train(params, lgb.Dataset(X, y), 3)
    assert get_tracer().stats()["spans_total"] > 0
    assert b.dump_trace  # API exists on every Booster
    after = compile_counts_by_label()
    assert after == before
