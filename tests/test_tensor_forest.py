"""Tensor-forest prediction engine (pred_engine=matmul, ops/tensor_forest.py).

Contracts under test:
  * matmul output is BYTE-IDENTICAL to the walker for every output kind
    (transformed values, raw scores, leaf indices), across remainder
    chunks, NaN default-direction routing, and multiclass grouping;
  * the eligibility matrix rejects exactly the forests the tensor layout
    cannot represent (categoricals, depth > 8, > 64 leaves, wide bins,
    too many trees/features) and every rejection falls back to the walker
    with identical output plus ONE telemetry event + gauge;
  * `auto` resolves through the compile-time parity probe;
  * warm ladders never recompile (compile_counts_by_label stays flat) —
    including through the serving plane (lgb.serve round-trip).
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs.jit import compile_counts_by_label
from lightgbm_tpu.obs.registry import get_session
from lightgbm_tpu.ops.tensor_forest import (
    TF_MAX_BIN,
    TF_MAX_DEPTH,
    TF_MAX_F,
    TF_MAX_LEAVES,
    TF_MAX_TREES,
    _host_tensor_values,
    _host_walk_values,
    build_tensor_forest,
    tensor_reject_reason,
)
from lightgbm_tpu.predict import streaming_compile_count


def _make_eligible(n=3000, f=12, seed=3, rounds=15, nan_frac=0.05, **extra):
    """Binary model inside the tensor sweet spot (depth <= 4), with NaNs
    planted so the default-direction term is exercised, not just <=."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    w = rng.normal(size=f)
    y = ((X @ w + rng.normal(scale=0.5, size=n)) > 0).astype(np.float64)
    if nan_frac:
        X[rng.random((n, f)) < nan_frac] = np.nan
    params = {
        "objective": "binary",
        "num_leaves": 16,
        "max_depth": 4,
        "min_data_in_leaf": 5,
        "verbose": -1,
        **extra,
    }
    bst = lgb.train(params, lgb.Dataset(X, label=y, params=params), rounds)
    return bst, X


@pytest.fixture(scope="module")
def eligible_model():
    return _make_eligible()


def _tiny_record(depth=1):
    """Synthetic bin-space chain record of the given depth."""
    d = max(1, depth)
    return {
        "split_feature": [0] * d,
        "split_bin": [1] * d,
        "default_left": [False] * d,
        "left_child": [i + 1 for i in range(d - 1)] + [~(d - 1) - 1],
        "right_child": [~i for i in range(d)],
        "leaf_value": [0.1 * i for i in range(d + 1)],
    }


def test_eligibility_rejection_matrix():
    nanb = np.full(4, -1, np.int64)
    ok = [_tiny_record(), _tiny_record(3)]
    assert tensor_reject_reason(ok, nanb, 4) is None
    # each axis of the envelope, one violation at a time
    cat = dict(_tiny_record(), split_is_cat=[True])
    assert "categorical" in tensor_reject_reason([cat], nanb, 4)
    deep = _tiny_record(TF_MAX_DEPTH + 1)
    assert f"> {TF_MAX_DEPTH}" in tensor_reject_reason([deep], nanb, 4)
    wide = dict(_tiny_record(), split_bin=[TF_MAX_BIN])
    assert f">= {TF_MAX_BIN}" in tensor_reject_reason([wide], nanb, 4)
    leafy = dict(_tiny_record(), leaf_value=[0.0] * (TF_MAX_LEAVES + 1))
    assert f"> {TF_MAX_LEAVES}" in tensor_reject_reason([leafy], nanb, 4)
    many = [_tiny_record()] * (TF_MAX_TREES + 1)
    assert f"> {TF_MAX_TREES}" in tensor_reject_reason(many, nanb, 4)
    assert f"> {TF_MAX_F}" in tensor_reject_reason(ok, nanb, TF_MAX_F + 1)
    assert "NaN bin" in tensor_reject_reason(
        ok, np.array([TF_MAX_BIN]), 4
    )
    assert "envelope" in tensor_reject_reason(ok, nanb, 4, max_bin=1 << 15)
    assert "no bin-space record" in tensor_reject_reason(
        [dict(_tiny_record(), no_bin_form=True)], nanb, 4
    )
    assert "no trees" in tensor_reject_reason([], nanb, 4)


def test_compiler_matches_reference_walk_on_random_bins():
    """build_tensor_forest + the contraction math reproduce a reference
    numpy walk bit-for-bit on random bins (skewed trees, NaN bins)."""
    rng = np.random.default_rng(11)
    records = [_tiny_record(d) for d in (1, 2, 5, 8)]
    nanb = np.array([3, -1, 0, 7], np.int64)
    for r in records:
        r["split_feature"] = list(
            rng.integers(0, 4, size=len(r["split_feature"]))
        )
        r["split_bin"] = list(rng.integers(0, 32, size=len(r["split_bin"])))
        r["default_left"] = list(rng.random(len(r["default_left"])) < 0.5)
    assert tensor_reject_reason(records, nanb, 4) is None
    forest = build_tensor_forest(records, nanb, 4)
    bins = rng.integers(0, 40, size=(256, 4)).astype(np.int64)
    ref_v, ref_l = _host_walk_values(records, nanb, bins)
    got_v, got_l = _host_tensor_values(forest, bins)
    assert ref_v.tobytes() == got_v.tobytes()
    assert np.array_equal(ref_l, got_l)


def test_matmul_byte_identical_all_kinds(eligible_model):
    bst, X = eligible_model
    walk = bst.predict(X)
    assert bst.last_predict_stats["engine"] == "walk"
    mm = bst.predict(X, pred_engine="matmul")
    assert bst.last_predict_stats["engine"] == "matmul"
    assert np.array_equal(walk, mm)
    assert np.array_equal(
        bst.predict(X, raw_score=True),
        bst.predict(X, raw_score=True, pred_engine="matmul"),
    )
    leaf_w = bst.predict(X, pred_leaf=True)
    leaf_m = bst.predict(X, pred_leaf=True, pred_engine="matmul")
    assert leaf_m.dtype == np.int32
    assert np.array_equal(leaf_w, leaf_m)
    # remainder chunks ride the same bucket ladder
    for chunk in (512, 700, 2048):  # 3000 rows -> odd remainders
        assert np.array_equal(
            walk, bst.predict(X, pred_engine="matmul", pred_chunk_rows=chunk)
        )
    # `auto` resolves to matmul via the parity probe
    assert np.array_equal(walk, bst.predict(X, pred_engine="auto"))
    assert bst.last_predict_stats["engine"] == "matmul"


def test_multiclass_grouping_byte_identical():
    rng = np.random.default_rng(7)
    X = rng.normal(size=(2500, 10))
    y = np.digitize(X[:, 0] + 0.3 * X[:, 1], [-0.5, 0.5]).astype(np.float64)
    params = {
        "objective": "multiclass",
        "num_class": 3,
        "num_leaves": 8,
        "max_depth": 3,
        "verbose": -1,
    }
    bst = lgb.train(params, lgb.Dataset(X, label=y, params=params), 8)
    walk = bst.predict(X)
    mm = bst.predict(X, pred_engine="matmul", pred_chunk_rows=512)
    assert walk.shape == (2500, 3)
    assert np.array_equal(walk, mm)


def test_real_space_falls_back_with_telemetry(eligible_model):
    """Loaded-from-text boosters have no bin mappers: a matmul request
    falls back to the real-space walker (suspect re-walk included) with
    identical output and a visible fallback event + gauges."""
    bst, X = eligible_model
    loaded = lgb.Booster(model_str=bst.model_to_string())
    ses = get_session()
    ses.configure(enabled=True)
    try:
        n_events = len(
            [e for e in ses.events if e.get("event") == "pred_engine_fallback"]
        )
        walk = loaded.predict(X, pred_chunk_rows=700)
        mm = loaded.predict(X, pred_engine="matmul", pred_chunk_rows=700)
        assert loaded.last_predict_stats["path"] == "stream_real"
        assert loaded.last_predict_stats["engine"] == "walk"
        assert np.array_equal(walk, mm)
        events = [
            e for e in ses.events if e.get("event") == "pred_engine_fallback"
        ]
        assert len(events) == n_events + 1  # deduped per model version
        assert "real-space" in events[-1]["reason"]
        assert ses.gauges.get("pred/engine_selected") == 0.0
        assert ses.gauges.get("pred/engine") == 0.0
        loaded.predict(X, pred_engine="matmul")  # repeat: still ONE event
        assert (
            len([
                e
                for e in ses.events
                if e.get("event") == "pred_engine_fallback"
            ])
            == n_events + 1
        )
    finally:
        ses.configure(enabled=False)


def test_ineligible_forest_falls_back_byte_identical():
    """Deep default-growth trees exceed the depth cap: matmul quietly
    (but observably) serves walker output."""
    rng = np.random.default_rng(13)
    X = rng.normal(size=(3000, 8))
    y = (X[:, 0] * X[:, 1] > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 63, "verbose": -1,
              "min_data_in_leaf": 2, "telemetry": True}
    bst = lgb.train(params, lgb.Dataset(X, label=y, params=params), 5)
    walk = bst.predict(X)
    mm = bst.predict(X, pred_engine="matmul")
    assert bst.last_predict_stats["engine"] == "walk"
    assert np.array_equal(walk, mm)
    ses = get_session()
    assert ses.counters.get("pred/engine_fallback_total", 0) >= 1


def test_matmul_gauge_selected(eligible_model):
    bst, X = eligible_model
    ses = get_session()
    ses.configure(enabled=True)
    try:
        bst.predict(X[:300], pred_engine="matmul")
        assert ses.gauges.get("pred/engine") == 1.0
        assert ses.gauges.get("pred/engine_selected") == 1.0
        bst.predict(X[:300])
        assert ses.gauges.get("pred/engine") == 0.0
    finally:
        ses.configure(enabled=False)


def test_zero_recompiles_after_warmup(eligible_model):
    bst, X = eligible_model
    fresh = lgb.train(
        {
            "objective": "binary",
            "num_leaves": 16,
            "max_depth": 4,
            "min_data_in_leaf": 5,
            "verbose": -1,
            "pred_engine": "matmul",
        },
        lgb.Dataset(X, label=(X[:, 0] > 0).astype(np.float64)),
        num_boost_round=10,
    )
    warmed = fresh.compile_predict(kinds=("value", "leaf"))
    assert warmed >= 0
    assert fresh.compile_predict(kinds=("value", "leaf")) == 0  # idempotent
    before = streaming_compile_count()
    labels_before = dict(compile_counts_by_label())
    for n in (1, 100, 256, 257, 1024, 3000):
        out = fresh.predict(X[:n])
        assert out.shape == (n,)
        assert fresh.last_predict_stats["engine"] == "matmul"
        assert fresh.last_predict_stats["compiles"] == 0
        assert fresh.predict(X[:n], pred_leaf=True).shape[0] == n
    assert streaming_compile_count() == before
    after = compile_counts_by_label()
    stream_labels = {
        k: v for k, v in after.items() if k.startswith("predict/stream")
    }
    for k, v in stream_labels.items():
        assert labels_before.get(k, 0) == v, f"label {k} retraced"
    assert any("tensor" in k for k in stream_labels)


def test_serving_roundtrip_matmul(eligible_model):
    """lgb.serve with pred_engine=matmul: warmed at load, byte-identical
    to direct predict, zero steady-state recompiles, engine visible in
    the registry description."""
    bst, X = eligible_model
    server = lgb.serve(bst, params={"pred_engine": "matmul"})
    try:
        desc = server.registry.models()[0]
        assert desc["pred_engine"] == "matmul"
        ref = bst.predict(X[:500], pred_engine="matmul")
        labels_before = dict(compile_counts_by_label())
        got = server.predict(X[:500])
        assert np.array_equal(ref, got)
        for n in (1, 64, 333, 500):
            assert np.array_equal(
                bst.predict(X[:n], pred_engine="matmul"),
                server.predict(X[:n]),
            )
        after = compile_counts_by_label()
        for k, v in after.items():
            if k.startswith("predict/stream"):
                assert labels_before.get(k, 0) == v, f"label {k} retraced"
    finally:
        server.stop()
