"""Logging redirect + per-phase timer (reference: utils/log.h:90 callback
redirect / python register_logger basic.py:160; global_timer common.h:979)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import lightgbm_tpu as lgb  # noqa: E402


class _Capture:
    def __init__(self):
        self.infos = []
        self.warnings = []

    def info(self, msg):
        self.infos.append(msg)

    def warning(self, msg):
        self.warnings.append(msg)


def test_register_logger_redirects_eval_lines():
    cap = _Capture()
    lgb.register_logger(cap)
    try:
        rng = np.random.default_rng(0)
        X = rng.normal(size=(300, 4))
        y = X[:, 0] + rng.normal(scale=0.1, size=300)
        lgb.train(
            {"objective": "regression", "verbosity": -1, "metric": "l2"},
            lgb.Dataset(X, y),
            3,
            valid_sets=[lgb.Dataset(X, y)],
            valid_names=["t"],
            callbacks=[lgb.log_evaluation(1)],
        )
        assert any("l2" in m for m in cap.infos)
    finally:
        lgb.unregister_logger()  # restore default stdout logging


def test_register_logger_validates():
    with pytest.raises(TypeError):
        lgb.register_logger(object())


def test_unregister_logger_restores_stdout(capsys):
    cap = _Capture()
    lgb.register_logger(cap)
    lgb.unregister_logger()
    from lightgbm_tpu.utils.log import log_info

    log_info("back to stdout")
    assert "back to stdout" in capsys.readouterr().out
    assert cap.infos == []


def test_global_timer_records_phases(capsys):
    lgb.global_timer.reset()
    rng = np.random.default_rng(0)
    X = rng.normal(size=(300, 4))
    y = X[:, 0] + rng.normal(scale=0.1, size=300)
    lgb.train(
        {"objective": "regression", "verbosity": 1, "metric": "l2"},
        lgb.Dataset(X, y),
        3,
    )
    t = lgb.global_timer
    assert t.totals.get("dataset/construct", 0) > 0
    assert t.totals.get("boosting/update", 0) > 0
    assert t.counts.get("tree/grow", 0) >= 3
    out = capsys.readouterr().out
    assert "LightGBM::timer" in out
