"""Logging redirect + per-phase timer (reference: utils/log.h:90 callback
redirect / python register_logger basic.py:160; global_timer common.h:979),
plus deep device observability: per-host aggregation (GlobalSyncUp analog,
network.h:169-240), straggler gauges, and the measured-vs-analytic
collective-byte cross-check on the 8-virtual-device mesh."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.obs.registry import get_session  # noqa: E402


class _Capture:
    def __init__(self):
        self.infos = []
        self.warnings = []

    def info(self, msg):
        self.infos.append(msg)

    def warning(self, msg):
        self.warnings.append(msg)


def test_register_logger_redirects_eval_lines():
    cap = _Capture()
    lgb.register_logger(cap)
    try:
        rng = np.random.default_rng(0)
        X = rng.normal(size=(300, 4))
        y = X[:, 0] + rng.normal(scale=0.1, size=300)
        lgb.train(
            {"objective": "regression", "verbosity": -1, "metric": "l2"},
            lgb.Dataset(X, y),
            3,
            valid_sets=[lgb.Dataset(X, y)],
            valid_names=["t"],
            callbacks=[lgb.log_evaluation(1)],
        )
        assert any("l2" in m for m in cap.infos)
    finally:
        lgb.unregister_logger()  # restore default stdout logging


def test_register_logger_validates():
    with pytest.raises(TypeError):
        lgb.register_logger(object())


def test_unregister_logger_restores_stdout(capsys):
    cap = _Capture()
    lgb.register_logger(cap)
    lgb.unregister_logger()
    from lightgbm_tpu.utils.log import log_info

    log_info("back to stdout")
    assert "back to stdout" in capsys.readouterr().out
    assert cap.infos == []


# ----------------------------------------------------- per-host aggregation
def test_merge_snapshots_counters_sum_gauges_minmaxmean():
    """The GlobalSyncUp-style merge is EXACT: counters sum, gauges
    min/max/mean, straggler gauges from per-host mean iteration walls."""
    from lightgbm_tpu.obs.aggregate import merge_snapshots

    snaps = [
        {
            "process": 0,
            "counters": {"iterations": 5, "splits": 30},
            "gauges": {"bagging_rows": 100.0},
            "iter_wall_ms": [10.0, 10.0],
        },
        {
            "process": 1,
            "counters": {"iterations": 5, "degradations": 1},
            "gauges": {"bagging_rows": 200.0},
            "iter_wall_ms": [30.0, 30.0],
        },
        {
            "process": 2,
            "counters": {"iterations": 5},
            "gauges": {"bagging_rows": 150.0},
            "iter_wall_ms": [20.0, 20.0],
        },
    ]
    merged = merge_snapshots(snaps)
    assert merged["hosts"] == 3
    # counters: exact SUM
    assert merged["counters"] == {
        "iterations": 15,
        "splits": 30,
        "degradations": 1,
    }
    # gauges: min / max / mean
    assert merged["gauges"]["agg/bagging_rows/min"] == 100.0
    assert merged["gauges"]["agg/bagging_rows/max"] == 200.0
    assert merged["gauges"]["agg/bagging_rows/mean"] == pytest.approx(150.0)
    # straggler: max / mean of per-host mean walls, skew = max/mean
    s = merged["straggler"]
    assert s["straggler/iter_wall_ms_max"] == 30.0
    assert s["straggler/iter_wall_ms_mean"] == pytest.approx(20.0)
    assert s["straggler/skew"] == pytest.approx(1.5)


def test_global_rollup_single_process_folds_gauges():
    ses = get_session().configure(enabled=True)
    ses.reset()
    try:
        ses.inc("iterations", 3)
        ses.set_gauge("bagging_rows", 123.0)
        for wall in (11.0, 12.0, 13.0):
            ses.record({"event": "iteration", "wall_ms": wall})
        from lightgbm_tpu.obs.aggregate import global_rollup

        merged = global_rollup(ses)
        assert merged is not None and merged["hosts"] == 1
        # single host: min == max == mean == the local value
        for stat in ("min", "max", "mean"):
            assert ses.gauges[f"agg/bagging_rows/{stat}"] == 123.0
        assert ses.gauges["straggler/iter_wall_ms_max"] == pytest.approx(12.0)
        assert ses.gauges["straggler/skew"] == pytest.approx(1.0)
        assert any(e["event"] == "host_rollup" for e in ses.events)
    finally:
        ses.configure(enabled=False)
        ses.reset()


def test_global_rollup_is_idempotent_across_repeated_fits():
    # the session is process-global: a long-lived process that trains
    # repeatedly (serving refresh loops, sweeps) rolls up many times.
    # Derived agg/* gauges must not be re-aggregated into agg/agg/* —
    # that blowup triples the gauge count per fit.
    from lightgbm_tpu.obs.aggregate import global_rollup

    ses = get_session().configure(enabled=True)
    ses.reset()
    try:
        ses.set_gauge("bagging_rows", 123.0)
        global_rollup(ses)
        n_after_first = len(ses.gauges)
        for _ in range(3):
            global_rollup(ses)
        assert len(ses.gauges) == n_after_first, sorted(ses.gauges)
        assert not any(name.startswith("agg/agg/") for name in ses.gauges)
        assert ses.gauges["agg/bagging_rows/mean"] == 123.0
    finally:
        ses.configure(enabled=False)
        ses.reset()


# --------------------------------------- measured collectives (8-device mesh)
def test_measured_psum_bytes_match_analytic_8dev(cpu_mesh_devices):
    """tree_learner=data dryrun on the 8-virtual-device mesh: the timed-psum
    wrappers' measured byte count lands within 10% of the analytic
    psum_bytes_per_iteration model (ISSUE 9 acceptance), and the per-host
    rollup + straggler gauges ride on the same run."""
    ses = get_session()
    ses.configure(enabled=False)
    ses.reset()
    rng = np.random.default_rng(3)
    X = rng.random((512, 10)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] + 0.1 * rng.random(512)).astype(np.float32)
    params = {
        "objective": "regression",
        "num_leaves": 7,
        "verbosity": -1,
        "tree_learner": "data",
        "telemetry": True,
    }
    try:
        booster = lgb.train(params, lgb.Dataset(X, y, params=params), 3)
        if booster._mesh is None:
            pytest.skip("data-parallel mesh not formed")
        tel = booster.telemetry()
        iters = [e for e in tel["events"] if e["event"] == "iteration"]
        assert all("collective_measured" in e for e in iters), (
            "measured-collective snapshots missing from iteration events"
        )
        analytic = sum(
            e["collective"]["hist_bytes"] + e["collective"]["count_bytes"]
            for e in iters
        )
        measured = sum(
            e["collective_measured"]["psum_bytes"] for e in iters
        )
        assert measured == pytest.approx(analytic, rel=0.10)
        # wall time is measured (soft signal, but must be present + sane)
        assert all(
            e["collective_measured"]["wall_ms"] >= 0 for e in iters
        )
        assert tel["gauges"]["collective_measured_psum_bytes"] > 0
        assert tel["counters"]["collective_measured_bytes_total"] > 0
        # per-host rollup ran at end-of-train: counters merged exactly
        # (single process: agg == local), straggler gauges present
        rollups = [e for e in tel["events"] if e["event"] == "host_rollup"]
        assert len(rollups) == 1 and rollups[0]["hosts"] == 1
        assert (
            rollups[0]["counters"]["iterations"]
            == tel["counters"]["iterations"]
        )
        assert tel["gauges"]["straggler/skew"] >= 1.0
        assert tel["gauges"]["straggler/iter_wall_ms_max"] > 0
    finally:
        ses.configure(enabled=False)
        ses.reset()


def test_obs_collectives_off_keeps_bare_psum(cpu_mesh_devices):
    """obs_collectives=false compiles the bare psum: no measured events."""
    ses = get_session()
    ses.configure(enabled=False)
    ses.reset()
    rng = np.random.default_rng(4)
    X = rng.random((512, 6)).astype(np.float32)
    y = X[:, 0].astype(np.float32)
    params = {
        "objective": "regression",
        "num_leaves": 7,
        "verbosity": -1,
        "tree_learner": "data",
        "telemetry": True,
        "obs_collectives": False,
    }
    try:
        booster = lgb.train(params, lgb.Dataset(X, y, params=params), 2)
        if booster._mesh is None:
            pytest.skip("data-parallel mesh not formed")
        iters = [
            e
            for e in booster.telemetry()["events"]
            if e["event"] == "iteration"
        ]
        assert iters and all("collective_measured" not in e for e in iters)
    finally:
        ses.configure(enabled=False)
        ses.reset()


def test_global_timer_records_phases(capsys):
    lgb.global_timer.reset()
    rng = np.random.default_rng(0)
    X = rng.normal(size=(300, 4))
    y = X[:, 0] + rng.normal(scale=0.1, size=300)
    lgb.train(
        {"objective": "regression", "verbosity": 1, "metric": "l2"},
        lgb.Dataset(X, y),
        3,
    )
    t = lgb.global_timer
    assert t.totals.get("dataset/construct", 0) > 0
    assert t.totals.get("boosting/update", 0) > 0
    assert t.counts.get("tree/grow", 0) >= 3
    out = capsys.readouterr().out
    assert "LightGBM::timer" in out
