"""Config-consumer guard: every ``Config`` field must be consumed somewhere
outside config.py, or sit in the documented not-applicable allowlist below.

VERDICT round-5 item 2: parameters the reference honors but this build
silently accepted-and-ignored (weight_column and friends) could only be
found by manual audit.  This test makes the audit structural — adding a
Config field without wiring a consumer (or documenting WHY it has none)
fails CI, so accept-and-ignore params cannot recur silently.

The scan is AST-based (not grep): a field counts as consumed when any
module under ``lightgbm_tpu/`` (except config.py) reads it as an attribute
(``cfg.field``) or via ``getattr(obj, "field", ...)``.  Mentions in
comments or docstrings do NOT count.
"""

import ast
import dataclasses
import pathlib

import pytest

from lightgbm_tpu.config import Config

PKG = pathlib.Path(__file__).resolve().parents[1] / "lightgbm_tpu"

# Fields with NO consumer outside config.py, each with the reason it is
# deliberately not applicable to the TPU build.  A field that GAINS a
# consumer must be removed from here (the test enforces staleness too);
# a field that loses its consumer must either be rewired or documented.
NOT_APPLICABLE = {
    # layout knobs: the dataset is ONE dense [N, P] device matrix, so
    # there is no row-wise/col-wise histogram layout choice to force
    # (num_threads is no longer listed: the streaming ingest thread pool
    # sizes itself from it, lightgbm_tpu/ingest/pipeline.py)
    "force_col_wise": "single dense bin matrix; no layout duel to force",
    "force_row_wise": "single dense bin matrix; no layout duel to force",
    "histogram_pool_size": "histograms live in HBM/VMEM per kernel launch; "
    "no host-side histogram LRU pool",
    "device_type": "accepted for interface parity; the backend is chosen "
    "by the installed jax platform, not per-param",
    "deterministic": "training is already run-to-run deterministic: one "
    "PRNGKey stream, no atomics, fixed reduction orders",
    # dataset-loading switches with no analog in the NumPy/scipy loaders
    "is_enable_sparse": "sparse input is type-driven (scipy matrix in -> "
    "CSC path); no heuristic sparse/dense switch to toggle",
    "feature_pre_filter": "trivial features are always pruned at binning; "
    "there is no pre-filter pass to disable",
    "two_round": "data loads through NumPy memory mapping, not the "
    "reference's two-pass disk scan",
    "precise_float_parser": "np.loadtxt parsing is already correctly "
    "rounded; no fast-vs-precise float parser pair",
    "predict_disable_shape_check": "predict validates shapes against the "
    "model's feature count; skipping it would only defer the XLA error",
    # socket-cluster networking replaced by jax.distributed (parallel/):
    # coordinator address + process count come from the launcher, not params
    "num_machines": "jax.distributed owns cluster membership",
    "local_listen_port": "consumed by dask.py's coordinator string only "
    "through _other_params; no socket server binds it",
    "time_out": "collectives ride XLA; no socket timeouts",
    "machine_list_filename": "jax.distributed owns cluster membership",
    "machines": "jax.distributed owns cluster membership",
}


def _consumed_names():
    names = set()
    for p in PKG.rglob("*.py"):
        if p.name == "config.py":
            continue
        tree = ast.parse(p.read_text())
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute):
                names.add(node.attr)
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "getattr"
                and len(node.args) >= 2
                and isinstance(node.args[1], ast.Constant)
            ):
                names.add(str(node.args[1].value))
    return names


def test_every_config_field_is_consumed_or_documented():
    consumed = _consumed_names()
    fields = [f.name for f in dataclasses.fields(Config) if f.name != "raw"]
    orphans = [
        f for f in fields if f not in consumed and f not in NOT_APPLICABLE
    ]
    assert not orphans, (
        "Config fields with no consumer outside config.py and no "
        f"documented not-applicable entry: {orphans} — wire a consumer or "
        "add an allowlist entry explaining why the TPU build ignores it"
    )


def test_allowlist_is_not_stale():
    consumed = _consumed_names()
    fields = {f.name for f in dataclasses.fields(Config)}
    stale = [f for f in NOT_APPLICABLE if f in consumed]
    assert not stale, (
        f"allowlisted Config fields now HAVE consumers: {stale} — remove "
        "them from NOT_APPLICABLE so the guard covers them again"
    )
    unknown = [f for f in NOT_APPLICABLE if f not in fields]
    assert not unknown, f"allowlist names unknown Config fields: {unknown}"


@pytest.mark.parametrize("field", ["weight_column", "group_column",
                                   "ignore_column"])
def test_verdict_item2_columns_are_wired(field):
    """The three params this PR wired (VERDICT item 2) must stay wired."""
    assert field in _consumed_names()
